"""NodeRuntime: host a sans-I/O consensus protocol on real sockets.

The runtime owns the event loop the :class:`~hbbft_tpu.traits.Step`
contract demands: it feeds received wire bytes into a
:class:`~hbbft_tpu.protocols.sender_queue.SenderQueue`-wrapped algorithm
(QHB/DHB/HB — anything ``SenderQueue`` can wrap), resolves each outgoing
``Target::All/AllExcept/Node`` against the transport's peer set, and
encodes every message exactly once per payload.

Catch-up (the ``EpochStarted`` path):

- every connection hello carries the sender's current (era, epoch);
- a hello *above* a peer's recorded key is fed to the SenderQueue as a
  normal ``EpochStarted`` (releasing held-back messages);
- a hello *below* it means the peer restarted: the runtime rewinds the
  SenderQueue via :meth:`SenderQueue.reinit_peer`, handing it the replay
  log of recently-sent (key, message) pairs it retains per peer.  The
  restarted peer then replays the protocol from its announced key, with
  the backlog flowing in epoch order as it announces progress — a node
  restarted from scratch at (0, 0) recovers every batch as long as the
  replay retention covers the history.

Client traffic (``TX``/``STATUS_REQ`` frames) is admitted through a
bounded dedup'd :class:`~hbbft_tpu.net.client.Mempool` — the backpressure
boundary — and committed batches are pushed back to every connected client
as ``TX_COMMIT`` digests, which is what the client's latency measurement
keys on.  A running SHA3 chain over committed batches (``ledger digest``)
makes cross-node batch-identity a one-line comparison.
"""

from __future__ import annotations

import hashlib
import json
import logging
import struct
import time
from typing import Any, Callable, Dict, Hashable, List, Optional, Tuple

from hbbft_tpu.net import framing
from hbbft_tpu.net.client import Mempool, tx_digest
from hbbft_tpu.net.transport import ClientConn, Transport
from hbbft_tpu.obs.flight import FlightObserver, FlightRecorder
from hbbft_tpu.obs.http import ObsServer
from hbbft_tpu.obs.metrics import MetricAttr, Registry, fault_counter
from hbbft_tpu.obs.spans import SpanTracer
from hbbft_tpu.protocols import wire
from hbbft_tpu.protocols.dynamic_honey_badger import DhbBatch
from hbbft_tpu.protocols.honey_badger import Batch as HbBatch
from hbbft_tpu.protocols.queueing_honey_badger import QhbBatch, TxInput
from hbbft_tpu.protocols.sender_queue import (
    AlgoMessage,
    EpochStarted,
    SenderQueue,
    _algo_key,
    _algo_window,
    message_key,
)
from hbbft_tpu.traits import Step

NodeId = Hashable
EpochKey = Tuple[int, int]
Addr = Tuple[str, int]

logger = logging.getLogger("hbbft_tpu.net")


class NodeRuntime:
    """One networked consensus node: SenderQueue-wrapped algorithm +
    :class:`Transport` + client admission."""

    def __init__(
        self,
        algo: Any,
        cluster_id: bytes,
        *,
        seed: int = 0,
        mempool: Optional[Mempool] = None,
        make_tx_input: Callable[[bytes], Any] = TxInput,
        replay_retain_epochs: int = 64,
        on_batch: Optional[Callable[[Any], None]] = None,
        trace=None,
        cost_model=None,
        registry: Optional[Registry] = None,
        digest_chain_retain: int = 4096,
        flight_dir: Optional[str] = None,
        flight_max_segment_bytes: int = 4 * 2**20,
        flight_max_segments: int = 16,
        **transport_kwargs,
    ):
        self.sq = algo if isinstance(algo, SenderQueue) else SenderQueue(algo)
        # one registry per node: every layer below (transport, mempool,
        # span tracer, fault tallies) registers onto it, and /metrics
        # exposes it live (see hbbft_tpu.obs)
        self.registry = registry or Registry()
        self.spans = SpanTracer(self.registry, node=self.sq.our_id())
        self._c_decode = self.registry.counter(
            "hbbft_node_decode_failures_total",
            "undecodable or protocol-rejected peer messages")
        self._c_send_fail = self.registry.counter(
            "hbbft_node_send_failures_total",
            "outbound frames dropped (frame cap)")
        self._c_replay_gaps = self.registry.counter(
            "hbbft_node_replay_gaps_total",
            "peer restarts whose gap exceeded replay retention "
            "(the peer cannot catch up from here)")
        self._c_committed = self.registry.counter(
            "hbbft_node_committed_txs_total", "transactions committed")
        self._c_faults = fault_counter(self.registry)
        self.registry.register_callback(self._refresh_gauges)
        self.mempool = mempool or Mempool()
        self.mempool.bind_registry(self.registry)
        # the oversized-frame drop in _dispatch is a last-resort guard,
        # not a config escape hatch: a proposal of batch_size max-size txs
        # must fit the wire blob cap with margin (TLV + TPKE overhead),
        # or an honest proposer could wedge its own epochs
        batch_size = getattr(self.sq.algo, "batch_size", None)
        if batch_size is not None:
            worst = batch_size * (self.mempool.max_tx_bytes + 16)
            if worst > wire.MAX_BLOB_BYTES // 2:
                raise ValueError(
                    f"batch_size {batch_size} × max_tx_bytes "
                    f"{self.mempool.max_tx_bytes} = {worst}B can exceed "
                    f"half the wire blob cap ({wire.MAX_BLOB_BYTES}B): "
                    f"lower one of them (Mempool(max_tx_bytes=…))"
                )
        self.make_tx_input = make_tx_input
        self.replay_retain_epochs = replay_retain_epochs
        self.on_batch = on_batch
        self.batches: List[Any] = []
        self.ledger_digest = b"\x00" * 32
        # the digest chain is CHECKPOINTED, not unbounded: only the last
        # `digest_chain_retain` entries stay in memory; `chain_len` (the
        # total) and `ledger_digest` (the head) never truncate, and the
        # flight journal keeps the full per-batch record on disk
        self.digest_chain_retain = max(1, digest_chain_retain)
        self._digest_chain: List[str] = []
        self._digest_chain_offset = 0
        # black-box flight recorder (obs.flight): journals every message,
        # commit, fault, span and lifecycle event for offline forensics
        self.flight: Optional[FlightObserver] = None
        if flight_dir:
            recorder = FlightRecorder(
                flight_dir, node=repr(self.sq.our_id()),
                flavor="runtime", clock=time.time,
                max_segment_bytes=flight_max_segment_bytes,
                max_segments=flight_max_segments,
                registry=self.registry,
            )
            self.flight = FlightObserver(recorder)
            self.spans.sink = self.flight.record_span
        # per-peer replay log of recently sent consensus messages, in send
        # order: the reinit_peer history (see module docstring).  The
        # companion set dedups by value so reinit re-sends don't duplicate
        # the log (protocol messages are frozen dataclasses — hashable)
        self._replay: Dict[NodeId, List[Tuple[EpochKey, Any]]] = {}
        self._replay_seen: Dict[NodeId, set] = {}
        self._clients: set = set()
        self.transport = Transport(
            our_id=self.sq.our_id(),
            cluster_id=cluster_id,
            seed=seed,
            hello_key=self.current_key,
            on_peer_message=self._on_peer_message,
            on_peer_hello=self._on_peer_hello,
            on_client_frame=self._on_client_frame,
            on_client_gone=self._on_client_gone,
            trace=trace,
            cost_model=cost_model,
            registry=self.registry,
            **transport_kwargs,
        )
        self._obs_server: Optional[ObsServer] = None
        self.obs_addr: Optional[Addr] = None

    # -- observability -------------------------------------------------------
    #
    # The pre-registry integer attributes survive as thin counter-backed
    # views (MetricAttr descriptors) so existing call sites — status_doc
    # consumers, tests — keep working; the registry is the single source
    # of truth.

    committed_txs = MetricAttr("_c_committed")
    decode_failures = MetricAttr("_c_decode")
    send_failures = MetricAttr("_c_send_fail")
    replay_gaps = MetricAttr("_c_replay_gaps")

    @property
    def digest_chain(self) -> List[str]:
        """The RETAINED tail of the ledger-digest chain (see
        :attr:`digest_chain_offset` for where it starts)."""
        return self._digest_chain

    @property
    def digest_chain_offset(self) -> int:
        return self._digest_chain_offset

    @property
    def chain_len(self) -> int:
        """Total batches folded into the digest chain (never truncates)."""
        return self._digest_chain_offset + len(self._digest_chain)

    @property
    def faults_observed(self) -> int:
        return int(self._c_faults.total())

    def _refresh_gauges(self) -> None:
        """Derived-state gauges, refreshed on every scrape: consensus
        position, ledger length, connection health, and the replay/catch-up
        surfaces PR 2 only logged — replay-log depth and each peer's
        last-acked (era, epoch) — now scrapeable instead of grep-able."""
        r = self.registry
        era, epoch = self.current_key()
        r.gauge("hbbft_node_era", "current consensus era").set(era)
        r.gauge("hbbft_node_epoch", "current epoch within the era").set(epoch)
        r.gauge("hbbft_node_batches", "batches committed so far").set(
            len(self.batches))
        r.gauge("hbbft_node_peers_connected",
                "peers with a live outbound connection").set(sum(
                    1 for p in self.transport.peer_ids()
                    if self.transport.connected(p)))
        g_replay = r.gauge(
            "hbbft_node_replay_log_entries",
            "retained replay-log messages per peer", labelnames=("peer",))
        for peer, entries in self._replay.items():
            g_replay.labels(peer=repr(peer)).set(len(entries))
        g_pera = r.gauge(
            "hbbft_node_peer_era",
            "last (era, epoch) each peer announced: era part",
            labelnames=("peer",))
        g_pep = r.gauge(
            "hbbft_node_peer_epoch",
            "last (era, epoch) each peer announced: epoch part",
            labelnames=("peer",))
        for peer, (p_era, p_epoch) in self.sq.peer_epochs.items():
            if peer == self.our_id():
                continue
            g_pera.labels(peer=repr(peer)).set(p_era)
            g_pep.labels(peer=repr(peer)).set(p_epoch)

    async def start_obs(self, host: str = "127.0.0.1",
                        port: int = 0) -> Addr:
        """Serve ``/metrics``, ``/status``, ``/spans`` (see obs.http)."""
        self._obs_server = ObsServer(
            self.registry,
            status_fn=self.status_doc,
            spans_fn=self.spans.export_jsonl,
            flight_fn=(self.flight.recorder.tail_jsonl
                       if self.flight is not None else None),
        )
        self.obs_addr = await self._obs_server.start(host, port)
        return self.obs_addr

    # -- lifecycle -----------------------------------------------------------

    def our_id(self) -> NodeId:
        return self.sq.our_id()

    def current_key(self) -> EpochKey:
        return _algo_key(self.sq.algo)

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> Addr:
        return await self.transport.listen(host, port)

    def connect(self, peer_addrs: Dict[NodeId, Addr]) -> None:
        """Add peers and announce our epoch (SenderQueue startup)."""
        for peer_id, addr in peer_addrs.items():
            if peer_id != self.our_id():
                self.transport.add_peer(peer_id, addr)
        self._absorb(self.sq.startup_step())

    async def stop(self) -> None:
        if self._obs_server is not None:
            await self._obs_server.stop()
        await self.transport.stop()
        if self.flight is not None:
            self.flight.close()

    def flight_crash(self, exc: BaseException) -> None:
        """Crash-dump flush: journal the fatal error and force the
        journal to disk before the process dies (the note/flush path is
        what makes a SIGKILL-adjacent crash auditable)."""
        if self.flight is not None:
            self.flight.on_note("crash", repr(exc))
            self.flight.recorder.flush()

    # -- consensus plumbing --------------------------------------------------

    def submit_tx(self, tx: bytes) -> int:
        """Local admission (same path as a client TX frame)."""
        status = self.mempool.add(tx)
        if status == Mempool.ACCEPTED:
            self._absorb(self.sq.handle_input(self.make_tx_input(tx)))
        return status

    def _on_peer_message(self, peer_id: NodeId, payload: bytes) -> None:
        try:
            msg = wire.decode_message(payload)
        except ValueError as exc:
            self.decode_failures += 1
            logger.warning("undecodable message from %r: %s", peer_id, exc)
            return
        if not isinstance(msg, (AlgoMessage, EpochStarted)):
            self.decode_failures += 1
            logger.warning("non-sender-queue message %s from %r",
                           type(msg).__name__, peer_id)
            return
        self.spans.on_message(peer_id, msg)
        if self.flight is not None:
            self.flight.on_message(peer_id, msg)
        try:
            step = self.sq.handle_message(peer_id, msg)
        except TypeError as exc:
            # decodable but protocol-unexpected (e.g. AlgoMessage wrapping
            # a bare ReadyMsg): Byzantine input at the network boundary —
            # count it, keep the connection and the loop alive
            self.decode_failures += 1
            logger.warning("protocol-rejected message from %r: %s",
                           peer_id, exc)
            return
        self._absorb(step)

    def _on_peer_hello(self, peer_id: NodeId, hello, direction: str) -> None:
        # A hello means a (re)connection: whatever we previously drained
        # into a socket for this peer may have died in TCP buffers, and a
        # below-record key means it restarted outright (possibly from
        # (0, 0)).  At-least-once, uniformly: (re)set its sender-queue
        # record to the announced key and replay the retained log from
        # there — entries below the key are obsolete at the peer, resent
        # duplicates above it are protocol no-ops.  On a clean first
        # connect the log is empty and this degrades to registering the
        # peer and exchanging EpochStarted.
        key = hello.key
        cur = self.sq.peer_epochs.get(peer_id)
        history = [
            e for e in self._replay.get(peer_id, []) if e[0] >= key
        ]
        if history or (cur is not None and key < cur):
            logger.info("peer %r reconnected at %r (recorded %r): "
                        "replaying %d retained messages through the "
                        "sender queue", peer_id, key, cur, len(history))
        # retention check: if the oldest retained entry is already beyond
        # the peer's delivery window, nothing we replay is deliverable and
        # the peer can never announce progress — it is wedged, not merely
        # catching up.  Surface that loudly instead of stalling silently
        # (remedy: restart the peer from a snapshot, or raise
        # replay_retain_epochs).
        window = _algo_window(self.sq.algo)
        if history and min(e[0] for e in history) > (key[0],
                                                     key[1] + window):
            self.replay_gaps += 1
            if self.flight is not None:
                self.flight.on_note(
                    "replay_gap",
                    f"peer={peer_id!r} announced={key!r} "
                    f"oldest_retained={min(e[0] for e in history)!r}")
            logger.error(
                "peer %r announced %r but the replay log only reaches "
                "back to %r (> window %d): retention does not cover its "
                "gap; it cannot catch up from here",
                peer_id, key, min(e[0] for e in history), window,
            )
        self._absorb(self.sq.reinit_peer(peer_id, key, history))

    def _absorb(self, step: Step) -> None:
        try:
            for fault in step.fault_log:
                self._c_faults.labels(kind=fault.kind.name).inc()
            self.spans.on_step(step)
            if self.flight is not None:
                self.flight.on_step(step)
            for out in step.output:
                if isinstance(out, (QhbBatch, DhbBatch, HbBatch)):
                    self._on_batch(out)
            self._dispatch(step)
        except Exception as exc:
            # fatal in the consensus path: flush the black box so the
            # journal's last records survive whatever happens next
            self.flight_crash(exc)
            raise

    def _dispatch(self, step: Step) -> None:
        our = self.our_id()
        peer_ids = self.transport.peer_ids()
        all_ids = peer_ids + [our]
        for tm in step.messages:
            payload = wire.encode_message(tm.message)
            key = (
                message_key(tm.message.msg)
                if isinstance(tm.message, AlgoMessage) else None
            )
            for dest in tm.target.resolve(all_ids, our):
                try:
                    self.transport.send(dest, payload)
                except framing.FrameError as exc:
                    # an oversized frame must not abort the rest of the
                    # Step's fan-out (the mempool's max_tx_bytes admission
                    # bound makes this unreachable for honest configs)
                    self.send_failures += 1
                    logger.error("dropping oversized frame for %r: %s",
                                 dest, exc)
                    break  # same payload, same cap: skip remaining dests
                if key is not None:
                    entry = (key, tm.message.msg)
                    seen = self._replay_seen.setdefault(dest, set())
                    if entry not in seen:
                        seen.add(entry)
                        self._replay.setdefault(dest, []).append(entry)
        self._prune_replay()

    def _prune_replay(self) -> None:
        era, epoch = self.current_key()
        if epoch >= self.replay_retain_epochs:
            floor = (era, epoch - self.replay_retain_epochs)
        else:
            # young era: a naive (era, epoch−retain) floor would discard
            # the ENTIRE previous era the instant a DKG rotation lands,
            # breaking replay for a peer whose outage spans the boundary.
            # Keep the previous era's tail (itself already pruned to its
            # last `retain` epochs while that era was current) until this
            # era is `retain` epochs old.
            floor = (era - 1, 0) if era > 0 else (0, 0)
        for dest, entries in self._replay.items():
            if entries and entries[0][0] < floor:
                kept = [e for e in entries if e[0] >= floor]
                self._replay[dest] = kept
                self._replay_seen[dest] = set(kept)

    # -- batches & clients ---------------------------------------------------

    def _on_batch(self, batch: Any) -> None:
        self.batches.append(batch)
        self.ledger_digest = hashlib.sha3_256(
            self.ledger_digest + wire.batch_bytes(batch)
        ).digest()
        self._digest_chain.append(self.ledger_digest.hex())
        if len(self._digest_chain) > self.digest_chain_retain:
            drop = len(self._digest_chain) - self.digest_chain_retain
            del self._digest_chain[:drop]
            self._digest_chain_offset += drop
        if isinstance(batch, QhbBatch):
            txs = batch.all_txs()
            self._c_committed.inc(len(txs))
            digests = self.mempool.mark_committed(txs)
            self._notify_commit(batch.era, batch.epoch, digests)
        if self.on_batch is not None:
            self.on_batch(batch)

    def _notify_commit(self, era: int, epoch: int,
                       digests: List[bytes]) -> None:
        if not self._clients or not digests:
            return
        payload = struct.pack(">QQI", era, epoch, len(digests)) + b"".join(
            digests
        )
        for conn in list(self._clients):
            conn.send(framing.TX_COMMIT, payload)
            if conn.closed:
                self._clients.discard(conn)

    def _on_client_frame(self, conn: ClientConn, kind: int,
                         payload: bytes) -> None:
        self._clients.add(conn)
        if kind == framing.TX:
            status = self.mempool.add(payload)
            conn.send(framing.TX_ACK, bytes([status]) + tx_digest(payload))
            if status == Mempool.ACCEPTED:
                self._absorb(self.sq.handle_input(self.make_tx_input(payload)))
        elif kind == framing.STATUS_REQ:
            conn.send(framing.STATUS, json.dumps(self.status_doc()).encode())
        else:
            logger.warning("unknown client frame kind %d", kind)

    def _on_client_gone(self, conn: ClientConn) -> None:
        self._clients.discard(conn)

    def status_doc(self, chain_tail: int = 256) -> dict:
        era, epoch = self.current_key()
        local = max(0, len(self._digest_chain) - chain_tail)
        return {
            "node": repr(self.our_id()),
            "era": era,
            "epoch": epoch,
            "batches": len(self.batches),
            "ledger": self.ledger_digest.hex(),
            # chain head + total length: what the forensic auditor
            # cross-checks against a live node without the full journal
            "chain_head": self.ledger_digest.hex(),
            "chain_len": self.chain_len,
            "digest_chain": self._digest_chain[local:],
            "digest_chain_offset": self._digest_chain_offset + local,
            "flight": (self.flight.recorder.stats_doc()
                       if self.flight is not None else None),
            "committed_txs": self.committed_txs,
            "mempool": len(self.mempool),
            "decode_failures": self.decode_failures,
            "send_failures": self.send_failures,
            "replay_gaps": self.replay_gaps,
            "faults_observed": self.faults_observed,
            "peers_connected": sum(
                1 for p in self.transport.peer_ids()
                if self.transport.connected(p)
            ),
            "epochs_traced": self.spans.epochs_finalized,
            "obs_addr": list(self.obs_addr) if self.obs_addr else None,
            "stats": self.transport.stats.as_dict(),
        }

"""Real async networking for the sans-I/O consensus stack.

Layering (bottom up):

- :mod:`~hbbft_tpu.net.framing` — length-prefixed, size-capped frames over
  the :mod:`hbbft_tpu.protocols.wire` codec, with a versioned hello;
- :mod:`~hbbft_tpu.net.transport` — asyncio TCP peer connections: per-peer
  persistent outbound queues, seeded deterministic exponential backoff,
  heartbeats and dead-peer detection;
- :mod:`~hbbft_tpu.net.runtime` — :class:`NodeRuntime` hosts any
  ``SenderQueue``-wrappable :class:`~hbbft_tpu.traits.ConsensusProtocol`
  behind sockets, resolving ``Target`` routing and driving the
  ``EpochStarted`` catch-up path for lagging/restarted peers;
- :mod:`~hbbft_tpu.net.client` — bounded dedup'd mempool (node side) and
  the :class:`ClusterClient` contribute frontend with backpressure and
  submit→commit latency tracking;
- :mod:`~hbbft_tpu.net.cluster` — cluster assembly: in-process
  :class:`LocalCluster` (tests/bench) and per-node subprocess entry
  (``python -m hbbft_tpu.net.cluster``).

The deterministic in-process simulators (``sim/virtual_net.py`` and the
batched ``parallel/`` drivers) remain the test harnesses; this package is
how the same protocol objects run as long-lived networked processes.
"""

from hbbft_tpu.net.client import ClusterClient, Mempool
from hbbft_tpu.net.cluster import ClusterConfig, LocalCluster
from hbbft_tpu.net.framing import (
    FrameDecoder,
    FrameError,
    Hello,
    PROTOCOL_VERSION,
)
from hbbft_tpu.net.runtime import NodeRuntime
from hbbft_tpu.net.transport import BackoffPolicy, Transport, TransportStats

__all__ = [
    "BackoffPolicy",
    "ClusterClient",
    "ClusterConfig",
    "FrameDecoder",
    "FrameError",
    "Hello",
    "LocalCluster",
    "Mempool",
    "NodeRuntime",
    "PROTOCOL_VERSION",
    "Transport",
    "TransportStats",
]

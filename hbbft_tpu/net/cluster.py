"""Cluster assembly: build QHB node runtimes, in-process or as processes.

One :class:`ClusterConfig` (n, seed, ports, batch size, encryption) fully
determines a cluster: every process derives the same BLS key material from
``NetworkInfo.generate_map(range(n), Random(seed))``, so nodes need no key
distribution — the config IS the deployment descriptor for localhost runs.

Two drivers share the builders:

- :class:`LocalCluster` — all runtimes on one asyncio loop with ephemeral
  ports (real sockets, one process): the fast harness for tests and for
  ``bench.py --net``'s latency measurements;
- :func:`spawn_node` / ``python -m hbbft_tpu.net.cluster --node-id I …`` —
  one OS process per node on ``base_port + i``: the deployment shape, used
  by ``examples/cluster.py`` and the slow kill/restart e2e test.

``VirtualNet`` remains the deterministic single-process test harness; this
module is the path that runs the same protocol objects over real TCP.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import random
import signal
import socket
import subprocess
import sys
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from hbbft_tpu.net.client import ClusterClient, Mempool
from hbbft_tpu.net.runtime import NodeRuntime
from hbbft_tpu.netinfo import NetworkInfo
from hbbft_tpu.protocols.dynamic_honey_badger import DynamicHoneyBadger
from hbbft_tpu.protocols.honey_badger import EncryptionSchedule
from hbbft_tpu.protocols.queueing_honey_badger import QueueingHoneyBadger
from hbbft_tpu.protocols.sender_queue import SenderQueue

Addr = Tuple[str, int]


@dataclass
class ClusterConfig:
    n: int = 4
    seed: int = 0
    host: str = "127.0.0.1"
    base_port: int = 0          # 0 → ephemeral ports (in-process only)
    batch_size: int = 8
    # per-tx admission ceiling (Mempool.max_tx_bytes); 0 keeps the
    # Mempool default (256 KiB).  batch_size × max_tx_bytes must fit in
    # half the wire blob cap, so MB-scale ingestion shapes (big batches
    # of small txs, or 64 KB txs) size this to the tx they carry
    max_tx_bytes: int = 0
    encrypt: bool = False       # TPKE-encrypt contributions
    # verifiable information dispersal (protocols/vid.py): propose
    # constant-size (root, cert) commitments and retrieve payloads
    # lazily post-commit, instead of reliable-broadcasting every full
    # contribution through the epoch — the WAN-asymmetry mode where one
    # bandwidth-starved node no longer drags every commit
    vid: bool = False
    heartbeat_s: float = 0.5
    dead_after_s: float = 3.0
    replay_retain_epochs: int = 64
    # bounded storage: per-peer replay-log byte ceiling (0 = epochs-only
    # retention) and flight-journal checkpoint retention in committed
    # batches (0 = segment-count cap only).  Truncations are counted
    # (`hbbft_node_replay_truncations_total`,
    # `hbbft_obs_flight_truncations_total`) and visible in /status.
    replay_retain_bytes: int = 0
    flight_retain_batches: int = 0
    # snapshot state-sync transfer chunk size (net/statesync.py)
    sync_chunk_bytes: int = 32 * 1024
    # obs endpoint (/metrics /status /spans /flight) base port: node i
    # serves on metrics_base_port + i; 0 → no fixed obs ports
    # (LocalCluster still opens ephemeral ones)
    metrics_base_port: int = 0
    # in-memory ledger-digest chain retention (the head + total length
    # never truncate; the flight journal keeps the full record on disk)
    digest_chain_retain: int = 4096
    # flight-recorder journal root: node i journals to
    # <flight_dir>/node-<i>; "" → recorder off
    flight_dir: str = ""
    flight_max_segment_bytes: int = 4 * 2**20
    flight_max_segments: int = 16
    # epochs kept in flight per node (net/scheduler.py): 1 = sequential
    # (today's behavior), N = epoch e+N-1's RBC/ABA may start while epoch
    # e still threshold-decrypts
    pipeline_depth: int = 1
    # outbound link shaping, "SRC>DST:SECONDS,…" (e.g. "3>0:0.02,3>1:0.02"
    # delays node 3's frames to nodes 0 and 1 by 20 ms); "" → no shaping
    link_delays: str = ""
    # named chaos preset (chaos.link.preset_shape: wan-100ms, lossy-1pct,
    # dup-reorder, partition-10s, bandwidth-64k) applied to every node's
    # egress through the shared LinkShaper hook; "" → no chaos shaping.
    # chaos_seed seeds the per-edge fault RNGs (-1 → the cluster seed):
    # same config, same seed, same faults — a campaign cell's scenario
    # is reproducible interactively (examples/cluster.py --chaos)
    chaos: str = ""
    chaos_seed: int = -1
    # slow-node shaping: node `slow_node` sleeps `slow_delay_s` before
    # every pump iteration (an overloaded validator) — the bench's
    # coin-exercise knob; -1 → nobody is slowed
    slow_node: int = -1
    slow_delay_s: float = 0.0
    # general form: per-node pump delays "NID:SECONDS,…" (e.g.
    # "0:0.04,3:0.02") — a heterogeneous cluster where every validator
    # runs at its own speed; entries here override slow_node/slow_delay_s
    step_delays: str = ""
    # ingress-budget overrides (overload defense, net/transport.py):
    # 0 keeps the IngressBudget defaults (sized far above honest
    # traffic); flood chaos cells tighten them so the guard engages
    # within a short run
    ingress_bytes_per_s: float = 0.0
    ingress_burst_bytes: float = 0.0
    ingress_max_inflight: int = 0
    ingress_decode_strikes: int = 0
    ingress_throttle_strikes: int = 0
    # per-peer ingress worker threads (net/ingress.py): framing + decode
    # run off the event loop, feeding the pump decoded batches.  Off by
    # default — it buys wall-clock only where spare cores exist (thread
    # switches cost more than they save on a saturated single core)
    ingress_workers: bool = False
    # transport authentication (net/transport.py security model):
    # node-role hellos are challenge–response proven with the per-era
    # keys; auth=False reverts to the identification-only legacy
    # handshake (trusted-fabric benchmarks, protocol archaeology).
    # auth_grace_s bounds the previous-era key window during DKG
    # rotations (counted hbbft_guard_auth_stale_era_total).
    auth: bool = True
    auth_grace_s: float = 30.0
    # guard-driven adaptive degradation (net/degrade.py): shrink the
    # proposed batch size / mempool admission under sustained guard
    # pressure instead of riding the buffers into their cliff-edge caps
    degrade: bool = True
    # the controller's raise arm (opt-in): under sustained benign slack
    # (perf-plane headroom + real demand) raise batch size / mempool
    # admission up to this many doubling boosts toward the 8x ceilings;
    # 0 keeps the ladder degrade-only (chaos verdicts unchanged)
    max_boost: int = 0
    # raise-arm tuning (only consulted when max_boost > 0): clean
    # windows per boost step and the headroom floor that counts as
    # slack — a loaded shared box may never see the 0.6 default
    raise_windows: int = 10
    raise_headroom: float = 0.6
    # class-selective shaping: the listed nodes ("0,1") hold their
    # outbound BINARY-AGREEMENT traffic (BVal/Aux/Conf/Coin/Term) for
    # `aba_out_delay_s` while RBC flows normally.  Decorrelating ABA
    # progress from RBC delivery is what genuinely splits Subset's
    # accept/give-up votes (plain per-link delay cannot: the RBC echo
    # relay re-equalizes deliveries) — the honest trigger for real
    # threshold-coin rounds.  "" → nobody shaped.
    aba_delay_nodes: str = ""
    aba_out_delay_s: float = 0.0
    # narrow the hold to specific phase classes (comma list of span
    # names, e.g. "aba_conf"); "" → every aba_* class
    aba_out_classes: str = ""

    def link_delays_for(self, nid: int) -> Dict[int, float]:
        """This node's outbound per-peer delays parsed from link_delays."""
        out: Dict[int, float] = {}
        if not self.link_delays:
            return out
        for entry in self.link_delays.split(","):
            entry = entry.strip()
            if not entry:
                continue
            path, _, secs = entry.partition(":")
            src, _, dst = path.partition(">")
            if not secs or not dst:
                raise ValueError(f"bad link_delays entry {entry!r} "
                                 "(want SRC>DST:SECONDS)")
            if int(src) == nid:
                out[int(dst)] = float(secs)
        return out

    def step_delay_for(self, nid: int) -> float:
        """This node's pump delay: step_delays map, else slow_node."""
        if self.step_delays:
            for entry in self.step_delays.split(","):
                entry = entry.strip()
                if not entry:
                    continue
                node, _, secs = entry.partition(":")
                if not secs:
                    raise ValueError(f"bad step_delays entry {entry!r} "
                                     "(want NID:SECONDS)")
                if int(node) == nid:
                    return float(secs)
        return self.slow_delay_s if nid == self.slow_node else 0.0

    def chaos_shaper_for(self, nid: int):
        """This node's LinkShaper under the configured chaos preset (one
        shaper per transport; the seed is shared so every node draws the
        same per-edge fault streams)."""
        if not self.chaos or self.chaos == "none":
            return None
        from hbbft_tpu.chaos.link import LinkShaper, preset_shape

        seed = self.seed if self.chaos_seed < 0 else self.chaos_seed
        return LinkShaper(preset_shape(self.chaos, self.n), seed=seed)

    def ingress_kwargs(self) -> Optional[Dict[str, float]]:
        """Non-default IngressBudget overrides, or None (defaults)."""
        out: Dict[str, float] = {}
        if self.ingress_bytes_per_s > 0:
            out["bytes_per_s"] = self.ingress_bytes_per_s
        if self.ingress_burst_bytes > 0:
            out["burst_bytes"] = self.ingress_burst_bytes
        if self.ingress_max_inflight > 0:
            out["max_inflight_frames"] = self.ingress_max_inflight
        if self.ingress_decode_strikes > 0:
            out["decode_strikes"] = self.ingress_decode_strikes
        if self.ingress_throttle_strikes > 0:
            out["throttle_strikes"] = self.ingress_throttle_strikes
        return out or None

    def aba_delay_for(self, nid: int) -> float:
        """This node's outbound ABA-class hold, from aba_delay_nodes."""
        if not self.aba_delay_nodes or self.aba_out_delay_s <= 0:
            return 0.0
        shaped = {int(x) for x in self.aba_delay_nodes.split(",") if x}
        return self.aba_out_delay_s if nid in shaped else 0.0

    @property
    def cluster_id(self) -> bytes:
        # VID and classic clusters must never cross-connect (their batch
        # flavors hash differently); non-VID ids stay byte-identical
        # with earlier releases
        return b"hbbft-net/%d/%d/%d" % (self.n, self.seed,
                                        1 if self.encrypt else 0) + (
            b"/vid" if self.vid else b"")

    def addr(self, nid: int) -> Addr:
        if self.base_port == 0:
            raise ValueError("base_port 0 has no fixed addresses")
        return (self.host, self.base_port + nid)

    def addr_map(self) -> Dict[int, Addr]:
        return {nid: self.addr(nid) for nid in range(self.n)}

    def metrics_addr(self, nid: int) -> Addr:
        if self.metrics_base_port == 0:
            raise ValueError("metrics_base_port 0 has no fixed addresses")
        return (self.host, self.metrics_base_port + nid)

    def node_flight_dir(self, nid: int) -> Optional[str]:
        if not self.flight_dir:
            return None
        return os.path.join(self.flight_dir, f"node-{nid}")


def generate_infos(cfg: ClusterConfig) -> Dict[int, NetworkInfo]:
    return NetworkInfo.generate_map(
        list(range(cfg.n)), random.Random(cfg.seed)
    )


def node_secret_key(cfg: ClusterConfig, nid: int,
                    infos: Optional[Dict[int, NetworkInfo]] = None):
    """Node ``nid``'s plain BLS secret key under this config.  Genesis
    members (``nid < cfg.n``) use their generated keypair; later joiners
    derive a fresh deterministic keypair from the cluster seed — the
    public half is what existing validators vote in."""
    from hbbft_tpu.crypto import tc

    if nid < cfg.n:
        if infos is None:
            infos = generate_infos(cfg)
        return infos[nid].secret_key()
    return tc.SecretKey.random(
        random.Random(cfg.seed * 100_000 + 9000 + nid))


def donor_key_fn(cfg: ClusterConfig):
    """Donor-authentication resolver for state-sync joins: donor node
    id -> config-derived plain public key (genesis members and derived
    joiners alike), ``None`` for anything else — an unknown id fails
    the statesync identity challenge instead of being trusted."""
    infos = generate_infos(cfg)

    def key(nid):
        if isinstance(nid, int) and 0 <= nid:
            return node_secret_key(cfg, nid, infos).public_key()
        return None

    return key


def peer_addr_book(cfg: ClusterConfig):
    """The deployment address book: membership says WHO may join
    (consensus state); this says WHERE a member listens (config-derived
    ports).  Only meaningful with fixed ports."""
    if cfg.base_port == 0:
        return None
    return lambda nid: ((cfg.host, cfg.base_port + nid)
                        if isinstance(nid, int) and nid >= 0 else None)


def build_algo(cfg: ClusterConfig, infos: Dict[int, NetworkInfo],
               nid: int) -> SenderQueue:
    """The standard node stack: SenderQueue(QHB(DHB)) with per-node seeded
    RNGs derived from the cluster seed (same-seed-same-trace)."""
    dhb = DynamicHoneyBadger(
        infos[nid],
        infos[nid].secret_key(),
        rng=random.Random(cfg.seed * 100_000 + 7000 + nid),
        encryption_schedule=(
            EncryptionSchedule.always() if cfg.encrypt
            else EncryptionSchedule.never()
        ),
    )
    if cfg.vid:
        from hbbft_tpu.protocols.vid import VidQueueingHoneyBadger

        qhb = VidQueueingHoneyBadger(
            dhb, batch_size=cfg.batch_size,
            rng=random.Random(cfg.seed * 100_000 + 8000 + nid),
        )
    else:
        qhb = QueueingHoneyBadger(
            dhb, batch_size=cfg.batch_size,
            rng=random.Random(cfg.seed * 100_000 + 8000 + nid),
        )
    return SenderQueue(qhb)


def _shared_runtime_kwargs(cfg: ClusterConfig, nid: int) -> dict:
    return dict(
        mempool=(Mempool(max_tx_bytes=cfg.max_tx_bytes)
                 if cfg.max_tx_bytes else None),
        seed=cfg.seed * 1000 + nid,
        heartbeat_s=cfg.heartbeat_s,
        dead_after_s=cfg.dead_after_s,
        replay_retain_epochs=cfg.replay_retain_epochs,
        replay_retain_bytes=cfg.replay_retain_bytes,
        flight_retain_batches=cfg.flight_retain_batches,
        sync_chunk_bytes=cfg.sync_chunk_bytes,
        peer_addr_book=peer_addr_book(cfg),
        digest_chain_retain=cfg.digest_chain_retain,
        flight_dir=cfg.node_flight_dir(nid),
        flight_max_segment_bytes=cfg.flight_max_segment_bytes,
        flight_max_segments=cfg.flight_max_segments,
        pipeline_depth=cfg.pipeline_depth,
        step_delay_s=cfg.step_delay_for(nid),
        aba_out_delay_s=cfg.aba_delay_for(nid),
        aba_out_classes=cfg.aba_out_classes,
        ingress_kwargs=cfg.ingress_kwargs(),
        ingress_workers=cfg.ingress_workers,
        auth=cfg.auth,
        auth_grace_s=cfg.auth_grace_s,
        degrade=cfg.degrade,
        degrade_kwargs=(dict(max_boost=cfg.max_boost,
                             raise_windows=cfg.raise_windows,
                             raise_headroom=cfg.raise_headroom)
                        if cfg.max_boost > 0 else None),
    )


def build_runtime(cfg: ClusterConfig, infos: Dict[int, NetworkInfo],
                  nid: int, **kwargs) -> NodeRuntime:
    kwargs.setdefault("shaper", cfg.chaos_shaper_for(nid))
    merged = _shared_runtime_kwargs(cfg, nid)
    merged["link_delays"] = cfg.link_delays_for(nid)
    merged.update(kwargs)
    return NodeRuntime(build_algo(cfg, infos, nid), cfg.cluster_id,
                       **merged)


def build_joiner_runtime(cfg: ClusterConfig, snap, nid: int,
                         **kwargs) -> NodeRuntime:
    """A runtime activated from a state-sync :class:`JoinSnapshot`
    instead of genesis config: the standard node stack built via
    ``snapshot.build_joiner`` (DKG-transcript share derivation included)
    with the ledger-digest chain seeded at the snapshot's era boundary.

    Works for brand-new validators (``nid ≥ cfg.n``) and for genesis
    members rejoining after an outage that outlived replay retention
    (their config netinfo backs share derivation across
    encryption-schedule rotations)."""
    from hbbft_tpu.snapshot import build_joiner

    infos = generate_infos(cfg)
    sq = build_joiner(
        snap, nid, node_secret_key(cfg, nid, infos),
        batch_size=cfg.batch_size,
        rng_seed=cfg.seed * 100_000 + 7000 + nid,
        config_netinfo=infos.get(nid),
    )
    # same egress shaping as a genesis member: a joiner in a
    # chaos-configured cluster is NOT exempt from the chaos
    kwargs.setdefault("shaper", cfg.chaos_shaper_for(nid))
    merged = _shared_runtime_kwargs(cfg, nid)
    merged["link_delays"] = cfg.link_delays_for(nid)
    merged["ledger_seed"] = (snap.chain_head, snap.chain_len)
    merged.update(kwargs)
    return NodeRuntime(sq, cfg.cluster_id, **merged)


# -- in-process cluster ------------------------------------------------------


class LocalCluster:
    """All n runtimes on this process's event loop, ephemeral ports."""

    def __init__(self, cfg: ClusterConfig, **runtime_kwargs):
        self.cfg = cfg
        self.runtime_kwargs = runtime_kwargs
        self.runtimes: List[NodeRuntime] = []
        self.addrs: Dict[int, Addr] = {}
        self.metrics_addrs: Dict[int, Addr] = {}
        self._clients: List[ClusterClient] = []
        self._infos: Dict[int, NetworkInfo] = {}

    async def start(self) -> None:
        self._infos = generate_infos(self.cfg)
        self.runtimes = [
            build_runtime(self.cfg, self._infos, nid,
                          **self.runtime_kwargs)
            for nid in range(self.cfg.n)
        ]
        for nid, rt in enumerate(self.runtimes):
            # base_port set → fixed addresses (restart_node can rebind);
            # 0 → ephemeral as before
            self.addrs[nid] = await rt.start(
                self.cfg.host,
                self.cfg.base_port + nid if self.cfg.base_port else 0,
            )
            self.metrics_addrs[nid] = await rt.start_obs(
                self.cfg.host,
                (self.cfg.metrics_base_port + nid
                 if self.cfg.metrics_base_port else 0),
            )
        for rt in self.runtimes:
            rt.connect(self.addrs)

    async def stop(self) -> None:
        for client in self._clients:
            await client.close()
        for rt in self.runtimes:
            await rt.stop()

    async def restart_node(self, nid: int) -> None:
        """Kill/restart churn primitive: stop runtime ``nid`` and rebuild
        it from scratch at (0, 0) on its old address (requires fixed
        ports, i.e. ``cfg.base_port``).  Peers' senders keep dialing the
        address and the fresh hello triggers the SenderQueue replay
        catch-up; with a flight dir the journal's incarnation bumps, so
        the restart is visible to the auditor."""
        if not self.cfg.base_port:
            raise ValueError("restart_node needs fixed ports "
                             "(ClusterConfig.base_port)")
        await self.runtimes[nid].stop()
        rt = build_runtime(self.cfg, self._infos, nid,
                           **self.runtime_kwargs)
        self.runtimes[nid] = rt
        await rt.start(self.cfg.host, self.cfg.base_port + nid)
        self.metrics_addrs[nid] = await rt.start_obs(
            self.cfg.host,
            (self.cfg.metrics_base_port + nid
             if self.cfg.metrics_base_port else 0),
        )
        rt.connect(self.addrs)

    def vote_change(self, change) -> None:
        """Queue the same signed membership vote on every live runtime
        (votes commit through contributions; a majority rotates the
        era)."""
        from hbbft_tpu.protocols.dynamic_honey_badger import ChangeInput

        for rt in self.runtimes:
            rt.pump.enqueue("input", ChangeInput(change))

    def vote_to_add(self, nid: int) -> None:
        """Every validator votes to add ``nid`` (its config-derived
        public key) to the validator set."""
        from hbbft_tpu.protocols.dynamic_honey_badger import Change

        pk = node_secret_key(self.cfg, nid, self._infos).public_key()
        keys = dict(
            self.runtimes[0].sq.algo.dhb.netinfo.public_key_map())
        keys[nid] = pk
        self.vote_change(Change.node_change(keys))

    def vote_to_readd(self) -> None:
        """Vote a node-change to the CURRENT key map: a no-op membership
        change that still runs a full DKG and rotates the era — the
        checkpoint rotation that re-arms snapshot joins with a fresh
        transcript (how a restarted-beyond-retention validator gets a
        boundary to recover through)."""
        from hbbft_tpu.protocols.dynamic_honey_badger import Change

        keys = dict(
            self.runtimes[0].sq.algo.dhb.netinfo.public_key_map())
        self.vote_change(Change.node_change(keys))

    async def wait_snapshot(self, min_era: int,
                            timeout_s: float = 60.0) -> None:
        """Until every live runtime serves a join snapshot of era ≥
        ``min_era`` (i.e. the voted rotation completed everywhere)."""

        async def _wait():
            while any(
                rt.sync_store.manifest is None
                or rt.sync_store.manifest.era < min_era
                for rt in self.runtimes
            ):
                await asyncio.sleep(0.02)

        await asyncio.wait_for(_wait(), timeout_s)

    async def join_node(self, nid: int, *, timeout_s: float = 90.0,
                        donors: Optional[List[int]] = None
                        ) -> NodeRuntime:
        """The full membership-lifecycle join: vote ``nid`` in, wait for
        the DKG rotation, state-sync the boundary snapshot from donors,
        activate the joiner (share-complete, zero history replay), and
        wire it into the cluster.  Requires fixed ports
        (``cfg.base_port``)."""
        if not self.cfg.base_port:
            raise ValueError("join_node needs fixed ports "
                             "(ClusterConfig.base_port)")
        self.vote_to_add(nid)
        min_era = max(rt.current_key()[0] for rt in self.runtimes) + 1
        await self.wait_snapshot(min_era, timeout_s)
        return await self.activate_from_snapshot(
            nid, donors=donors, min_manifest_confirm=2)

    async def activate_from_snapshot(
        self, nid: int, *, donors: Optional[List[int]] = None,
        min_manifest_confirm: int = 1,
    ) -> NodeRuntime:
        """State-sync ``nid`` from live donors and start it — the shared
        tail of a brand-new join and a restarted-beyond-retention
        recovery."""
        from hbbft_tpu.net.statesync import StateSyncClient

        from hbbft_tpu.obs.metrics import Registry

        donor_addrs = [self.addrs[d] for d in (donors or
                       [d for d in self.addrs if d != nid])]
        # the bootstrap transfer's counters live on the SAME registry the
        # runtime will serve on /metrics — the join story stays scrapeable
        registry = self.runtime_kwargs.get("registry") or Registry()
        snap = await StateSyncClient(
            donor_addrs, self.cfg.cluster_id,
            client_id=f"statesync-{nid}", seed=self.cfg.seed,
            min_manifest_confirm=min_manifest_confirm,
            registry=registry,
            donor_key=donor_key_fn(self.cfg) if self.cfg.auth else None,
        ).fetch()
        kwargs = dict(self.runtime_kwargs)
        kwargs["registry"] = registry
        # DKG-transcript replay (BLS row decryption + commitment checks)
        # is CPU-heavy sync work — off the event loop, or the donors
        # sharing this loop would miss heartbeats mid-join
        rt = await asyncio.to_thread(
            build_joiner_runtime, self.cfg, snap, nid, **kwargs)
        addr = (self.cfg.host, self.cfg.base_port + nid)
        if nid < len(self.runtimes):
            self.runtimes[nid] = rt
        else:
            self.runtimes.append(rt)
        self.addrs[nid] = addr
        await rt.start(*addr)
        self.metrics_addrs[nid] = await rt.start_obs(
            self.cfg.host,
            (self.cfg.metrics_base_port + nid
             if self.cfg.metrics_base_port else 0),
        )
        # the joiner dials every existing member; members accept its
        # hello through the membership-resolved dynamic-peer path and
        # dial back (transport.peer_resolver)
        rt.connect(dict(self.addrs))
        return rt

    async def client(self, nid: int,
                     client_id: str = "client",
                     trace_dir: Optional[str] = None) -> ClusterClient:
        client = ClusterClient(
            self.addrs[nid], self.cfg.cluster_id, client_id=client_id,
            trace_dir=trace_dir,
        )
        await client.connect()
        self._clients.append(client)
        return client

    async def wait_epochs(self, min_batches: int,
                          timeout_s: float = 60.0) -> None:
        """Until every runtime has committed ≥ ``min_batches`` batches."""

        async def _wait():
            while any(
                len(rt.batches) < min_batches for rt in self.runtimes
            ):
                await asyncio.sleep(0.02)

        await asyncio.wait_for(_wait(), timeout_s)

    def common_digest_prefix(self) -> List[str]:
        """The agreed ledger-digest chain across all runtimes wherever
        their RETAINED chains overlap (chains are checkpointed — see
        ``NodeRuntime.digest_chain_retain``); raises if any node's chain
        *conflicts* (same index, different digest)."""
        tails = [(rt.digest_chain_offset, rt.digest_chain)
                 for rt in self.runtimes]
        lo = max(off for off, _c in tails)
        hi = min(off + len(c) for off, c in tails)
        prefix: List[str] = []
        for i in range(lo, hi):
            vals = {c[i - off] for off, c in tails}
            if len(vals) != 1:
                raise AssertionError(
                    f"ledger fork at batch {i}: {sorted(vals)}"
                )
            prefix.append(tails[0][1][i - tails[0][0]])
        return prefix


def assert_status_chains_consistent(docs) -> int:
    """Every pair of node STATUS documents must agree wherever their
    ledger digest chains overlap; returns how many indices were checked.
    The cross-process sibling of :meth:`LocalCluster.common_digest_prefix`.
    """
    checked = 0
    tails = [(d["digest_chain_offset"], d["digest_chain"]) for d in docs]
    lo = max(off for off, _c in tails)
    hi = min(off + len(c) for off, c in tails)
    for i in range(lo, hi):
        vals = {c[i - off] for off, c in tails}
        if len(vals) != 1:
            raise AssertionError(f"ledger fork at batch {i}: {sorted(vals)}")
        checked += 1
    return checked


# -- multi-process cluster ---------------------------------------------------


def find_free_base_port(n: int, lo: int = 23000, hi: int = 52000) -> int:
    """A base port with n consecutive free TCP ports on localhost."""
    for base in range(lo, hi, max(n, 1)):
        socks = []
        try:
            for i in range(n):
                s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
                s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                s.bind(("127.0.0.1", base + i))
                socks.append(s)
            return base
        # hblint: disable=fault-swallowed-drop (port-availability probe:
        # a busy port is the expected negative result, not dropped input)
        except OSError:
            continue
        finally:
            for s in socks:
                s.close()
    raise RuntimeError("no free port range found")


def node_command(cfg: ClusterConfig, nid: int) -> List[str]:
    cmd = [
        sys.executable, "-m", "hbbft_tpu.net.cluster",
        "--nodes", str(cfg.n),
        "--node-id", str(nid),
        "--seed", str(cfg.seed),
        "--base-port", str(cfg.base_port),
        "--batch-size", str(cfg.batch_size),
    ]
    if cfg.max_tx_bytes:
        cmd += ["--max-tx-bytes", str(cfg.max_tx_bytes)]
    if cfg.metrics_base_port:
        cmd += ["--metrics-port", str(cfg.metrics_base_port + nid)]
    if cfg.flight_dir:
        cmd += ["--flight-dir", cfg.flight_dir]
    if cfg.encrypt:
        cmd.append("--encrypt")
    if cfg.vid:
        cmd.append("--vid")
    if cfg.pipeline_depth != 1:
        cmd += ["--pipeline-depth", str(cfg.pipeline_depth)]
    if cfg.link_delays:
        cmd += ["--link-delays", cfg.link_delays]
    if cfg.chaos:
        cmd += ["--chaos", cfg.chaos]
        if cfg.chaos_seed >= 0:
            cmd += ["--chaos-seed", str(cfg.chaos_seed)]
    if cfg.ingress_workers:
        cmd.append("--ingress-workers")
    if not cfg.auth:
        cmd.append("--no-auth")
    if cfg.auth_grace_s != 30.0:
        cmd += ["--auth-grace-s", str(cfg.auth_grace_s)]
    if not cfg.degrade:
        cmd.append("--no-degrade")
    if cfg.max_boost > 0:
        cmd += ["--max-boost", str(cfg.max_boost)]
        if cfg.raise_windows != 10:
            cmd += ["--raise-windows", str(cfg.raise_windows)]
        if cfg.raise_headroom != 0.6:
            cmd += ["--raise-headroom", str(cfg.raise_headroom)]
    if cfg.step_delay_for(nid) > 0:
        cmd += ["--step-delay", str(cfg.step_delay_for(nid))]
    if cfg.aba_delay_for(nid) > 0:
        cmd += ["--aba-out-delay", str(cfg.aba_out_delay_s)]
        if cfg.aba_out_classes:
            cmd += ["--aba-out-classes", cfg.aba_out_classes]
    return cmd


def spawn_node(cfg: ClusterConfig, nid: int, *, join: bool = False,
               **popen_kwargs) -> subprocess.Popen:
    """One node as a child process (forces the CPU jax backend so node
    processes never grab an accelerator).  ``join=True`` spawns the
    state-sync joiner flow (``--join``) instead of a genesis member."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.setdefault("HBBFT_PLAIN_LADDER", "1")
    cwd = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    )))
    cmd = join_command(cfg, nid) if join else node_command(cfg, nid)
    return subprocess.Popen(cmd, env=env, cwd=cwd, **popen_kwargs)


async def connect_when_up(cfg: ClusterConfig, nid: int, *,
                          client_id: Optional[str] = None,
                          timeout_s: float = 120.0,
                          trace_dir: Optional[str] = None) -> ClusterClient:
    """A connected :class:`ClusterClient` for node ``nid``, retrying while
    the node process boots.  ``trace_dir`` journals the client's side of
    the per-tx causal trace (obs.trace) for ``obs.critpath``."""
    deadline = time.monotonic() + timeout_s
    while True:
        client = ClusterClient(cfg.addr(nid), cfg.cluster_id,
                               client_id=client_id or f"client-{nid}",
                               trace_dir=trace_dir)
        try:
            await client.connect()
            return client
        except (OSError, asyncio.TimeoutError):
            await client.close()
            if time.monotonic() > deadline:
                raise TimeoutError(f"node {nid} never came up")
            await asyncio.sleep(0.3)


def shutdown_procs(procs, timeout_s: float = 15.0) -> None:
    """SIGTERM every live node process, escalating to SIGKILL."""
    for p in procs:
        if p.poll() is None:
            p.send_signal(signal.SIGTERM)
    for p in procs:
        try:
            p.wait(timeout=timeout_s)
        # hblint: disable=fault-swallowed-drop (escalation, not a drop:
        # a node ignoring SIGTERM for timeout_s is SIGKILLed)
        except subprocess.TimeoutExpired:
            p.kill()


async def _serve_runtime(rt: NodeRuntime) -> None:
    """Serve a started runtime until SIGTERM/SIGINT (shared tail of
    ``run_node`` and ``run_join_node``): a dead step pump is a dead
    node, so its exception is surfaced instead of serving sockets for a
    consensus engine that no longer runs."""
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGTERM, signal.SIGINT):
        loop.add_signal_handler(sig, stop.set)
    stop_task = asyncio.ensure_future(stop.wait())
    done, _pending = await asyncio.wait(
        {stop_task, rt.pump.task}, return_when=asyncio.FIRST_COMPLETED
    )
    if rt.pump.task in done:
        stop_task.cancel()
        exc = rt.pump.task.exception()
        if exc is not None:
            raise exc


async def run_join_node(cfg: ClusterConfig, nid: int,
                        metrics_port: int = 0,
                        donors: Optional[List[int]] = None,
                        min_manifest_confirm: int = 2) -> None:
    """Join a LIVE cluster as a fresh OS process — the multi-process
    face of the PR-8 membership lifecycle (``LocalCluster.join_node``
    drives the same path in-process):

    1. the existing validators must already have voted ``nid`` in (its
       config-derived public key) and completed the DKG rotation, so
       every donor serves an era-boundary join snapshot;
    2. this process state-syncs the snapshot from the donors (chunked,
       CRC'd, multi-donor-confirmed — ``net/statesync.py``), derives
       its secret key share from the committed DKG transcript, and
    3. activates at the era boundary with zero history replay, dialing
       every existing member; members accept its hello through the
       membership-resolved dynamic-peer path and dial back.

    ``python -m hbbft_tpu.net.cluster --join --node-id I …`` lands here.
    """
    from hbbft_tpu.net.statesync import StateSyncClient

    donor_ids = [d for d in (donors if donors is not None
                             else range(cfg.n)) if d != nid]
    if not donor_ids:
        raise ValueError("--join needs at least one donor node")
    snap = await StateSyncClient(
        [cfg.addr(d) for d in donor_ids], cfg.cluster_id,
        client_id=f"statesync-{nid}", seed=cfg.seed,
        min_manifest_confirm=min(min_manifest_confirm, len(donor_ids)),
        donor_key=donor_key_fn(cfg) if cfg.auth else None,
    ).fetch()
    print(f"node {nid} state-synced era {snap.era} snapshot "
          f"(chain len {snap.chain_len})", flush=True)
    rt = build_joiner_runtime(cfg, snap, nid)
    try:
        host, port = cfg.addr(nid)
        await rt.start(host, port)
        if metrics_port:
            m_host, m_port = await rt.start_obs(host, metrics_port)
            print(f"node {nid} obs endpoint on http://{m_host}:{m_port}"
                  f"/metrics", flush=True)
        rt.connect({d: cfg.addr(d) for d in donor_ids})
        print(f"node {nid} joined, listening on {host}:{port}",
              flush=True)
        await _serve_runtime(rt)
    except BaseException as exc:
        rt.flight_crash(exc)
        raise
    await rt.stop()


def join_command(cfg: ClusterConfig, nid: int) -> List[str]:
    """The ``--join`` subprocess invocation for ``nid`` under ``cfg``."""
    cmd = node_command(cfg, nid)
    # --node-id validation differs under --join (a joiner's id may be
    # outside 0..n-1), so the flag must precede nothing in particular —
    # append is fine
    cmd.append("--join")
    return cmd


async def run_node(cfg: ClusterConfig, nid: int,
                   metrics_port: int = 0) -> None:
    """Run one node forever (the subprocess entry body).

    ``HBBFT_NODE_PROFILE=<dir>`` cProfiles the whole node process and
    dumps pstats to ``<dir>/node-<id>.pstats`` on clean shutdown — the
    only way to see where a REAL (multi-process, socket-driven) node
    spends CPU, since in-process profiles skew the event-loop/syscall
    mix.
    """
    # The consensus hot path allocates heavily (Steps, frozen message
    # dataclasses, frames) but makes almost no reference cycles; the
    # default gen-0 threshold (700) makes the collector scan thousands of
    # times per second for nothing.  Raise the thresholds rather than
    # disable: asyncio does create cycles (Task exception contexts), so
    # collection must still happen, just orders of magnitude less often.
    import gc
    gc.set_threshold(50_000, 25, 25)
    profile_dir = os.environ.get("HBBFT_NODE_PROFILE", "")
    profiler = None
    if profile_dir:
        import cProfile
        # CPU-time timer: with several node processes sharing cores, the
        # default wall timer books preemption gaps onto whatever call was
        # live, swamping the real hot spots
        profiler = cProfile.Profile(time.process_time)
        profiler.enable()
    infos = generate_infos(cfg)
    rt = build_runtime(cfg, infos, nid)
    try:
        host, port = cfg.addr(nid)
        await rt.start(host, port)
        if metrics_port:
            m_host, m_port = await rt.start_obs(host, metrics_port)
            print(f"node {nid} obs endpoint on http://{m_host}:{m_port}"
                  f"/metrics", flush=True)
        rt.connect(cfg.addr_map())
        print(f"node {nid} listening on {host}:{port}", flush=True)
        await _serve_runtime(rt)
    except BaseException as exc:
        # crash-dump flush: make the black box land on disk before the
        # process dies, whatever killed it
        rt.flight_crash(exc)
        raise
    finally:
        if profiler is not None:
            profiler.disable()
            os.makedirs(profile_dir, exist_ok=True)
            profiler.dump_stats(
                os.path.join(profile_dir, f"node-{nid}.pstats"))
        timing_dir = os.environ.get("HBBFT_PUMP_TIMING", "")
        if timing_dir and rt._pump_timing:
            os.makedirs(timing_dir, exist_ok=True)
            # hblint: disable=async-blocking-call (one-shot perf-diagnosis
            # dump on the shutdown path; nothing is being served anymore)
            with open(os.path.join(timing_dir, f"node-{nid}.json"),
                      "w") as fh:
                json.dump({"timing": rt._pump_timing,
                           "batches": len(rt.batches),
                           "iterations": rt.pump.iterations}, fh)
    await rt.stop()


def main(argv: Optional[List[str]] = None) -> None:
    ap = argparse.ArgumentParser(
        description="run ONE hbbft-tpu cluster node (see examples/cluster.py "
                    "for the multi-process launcher)"
    )
    ap.add_argument("--nodes", type=int, required=True)
    ap.add_argument("--node-id", type=int, required=True)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--base-port", type=int, required=True)
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--max-tx-bytes", type=int, default=0,
                    help="per-tx admission ceiling in bytes "
                         "(0 = Mempool default, 256 KiB)")
    ap.add_argument("--encrypt", action="store_true")
    ap.add_argument("--vid", action="store_true",
                    help="verifiable information dispersal: order "
                         "constant-size (root, cert) commitments and "
                         "retrieve payloads lazily post-commit")
    ap.add_argument("--metrics-port", type=int, default=0,
                    help="serve /metrics /status /spans /flight on this "
                         "port (0 = off)")
    ap.add_argument("--flight-dir", default="",
                    help="flight-recorder journal ROOT (this node "
                         "journals to <dir>/node-<id>; empty = off)")
    ap.add_argument("--pipeline-depth", type=int, default=1,
                    help="epochs kept in flight at once (1 = sequential)")
    ap.add_argument("--link-delays", default="",
                    help="outbound link shaping, SRC>DST:SECONDS[,…] "
                         "(only entries whose SRC is this node apply)")
    ap.add_argument("--chaos", default="",
                    help="named chaos link-shaping preset (wan-100ms, "
                         "lossy-1pct, dup-reorder, partition-10s, "
                         "bandwidth-64k); empty = off")
    ap.add_argument("--chaos-seed", type=int, default=-1,
                    help="seed for the chaos fault RNGs "
                         "(-1 = the cluster seed)")
    ap.add_argument("--step-delay", type=float, default=0.0,
                    help="sleep SECONDS before every pump iteration "
                         "(slow-node chaos shaping)")
    ap.add_argument("--aba-out-delay", type=float, default=0.0,
                    help="hold THIS node's outbound binary-agreement "
                         "traffic for SECONDS (class-selective shaping)")
    ap.add_argument("--aba-out-classes", default="",
                    help="narrow --aba-out-delay to these phase classes "
                         "(comma list, e.g. aba_conf); empty = all aba_*")
    ap.add_argument("--ingress-workers", action="store_true",
                    help="decode inbound peer frames on per-peer worker "
                         "threads instead of the event loop "
                         "(net/ingress.py)")
    ap.add_argument("--no-auth", action="store_true",
                    help="disable the authenticated node handshake "
                         "(identification-only hellos — trusted "
                         "fabrics only)")
    ap.add_argument("--auth-grace-s", type=float, default=30.0,
                    help="previous-era key grace window during DKG "
                         "rotations, seconds")
    ap.add_argument("--no-degrade", action="store_true",
                    help="disable guard-driven adaptive degradation "
                         "(batch-size/mempool shrink under sustained "
                         "overload)")
    ap.add_argument("--max-boost", type=int, default=0,
                    help="arm the controller's raise side: up to this "
                         "many batch-size/mempool doublings under "
                         "sustained measured headroom (0 = degrade-"
                         "only ladder)")
    ap.add_argument("--raise-windows", type=int, default=10,
                    help="clean windows of slack+demand per boost step "
                         "(with --max-boost)")
    ap.add_argument("--raise-headroom", type=float, default=0.6,
                    help="measured headroom floor that counts as slack "
                         "(with --max-boost)")
    ap.add_argument("--join", action="store_true",
                    help="join a LIVE cluster via snapshot state-sync "
                         "instead of starting from genesis: the "
                         "existing validators must already have voted "
                         "this node id in (DKG rotation complete); "
                         "--node-id may exceed --nodes-1 for a brand-"
                         "new validator")
    args = ap.parse_args(argv)
    if args.join:
        if args.node_id < 0:
            ap.error(f"--node-id {args.node_id} must be >= 0")
    elif not 0 <= args.node_id < args.nodes:
        ap.error(f"--node-id {args.node_id} not in 0..{args.nodes - 1}")
    cfg = ClusterConfig(
        n=args.nodes, seed=args.seed, base_port=args.base_port,
        batch_size=args.batch_size, max_tx_bytes=args.max_tx_bytes,
        encrypt=args.encrypt, vid=args.vid,
        flight_dir=args.flight_dir, pipeline_depth=args.pipeline_depth,
        link_delays=args.link_delays,
        chaos=args.chaos, chaos_seed=args.chaos_seed,
        slow_node=(args.node_id if args.step_delay > 0 else -1),
        slow_delay_s=args.step_delay,
        aba_delay_nodes=(str(args.node_id) if args.aba_out_delay > 0
                         else ""),
        aba_out_delay_s=args.aba_out_delay,
        aba_out_classes=args.aba_out_classes,
        ingress_workers=args.ingress_workers,
        auth=not args.no_auth,
        auth_grace_s=args.auth_grace_s,
        degrade=not args.no_degrade,
        max_boost=args.max_boost,
        raise_windows=args.raise_windows,
        raise_headroom=args.raise_headroom,
    )
    if args.join:
        asyncio.run(run_join_node(cfg, args.node_id,
                                  metrics_port=args.metrics_port))
    else:
        asyncio.run(run_node(cfg, args.node_id,
                             metrics_port=args.metrics_port))


if __name__ == "__main__":
    main()

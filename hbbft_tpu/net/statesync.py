"""Chunked, resumable snapshot state-sync over the framed transport.

The production join path (ROADMAP item 5): a node with **zero history** —
a brand-new validator, or a restarted one whose outage exceeded its
peers' replay retention — fetches a
:class:`~hbbft_tpu.snapshot.JoinSnapshot` from the live cluster instead
of replaying epochs.  The protocol is deliberately dumb-donor /
smart-joiner:

- every node keeps the latest era-boundary snapshot image published by
  its runtime (:class:`SnapshotStore`) and answers two request types on
  ordinary client-role connections: *manifest* (era, image digest,
  ledger-chain position, chunk geometry) and *chunk n of image X*;
- the joiner (:class:`StateSyncClient`) first collects manifests from
  every reachable donor and requires ``min_manifest_confirm`` of them to
  agree on ``(era, image digest, chain head, chain length)`` before
  fetching a single byte — a lone lying donor cannot pick the image;
- chunks are **content-addressed** by the image digest, so the transfer
  resumes on any other donor serving the same image: a donor that
  stalls, dies mid-chunk, or answers garbage costs one retry and a
  failover, never a restart from byte zero.  Full donor cycles back off
  exponentially (seeded — deterministic schedules in tests);
- every chunk carries a CRC32 and the assembled image must hash to the
  manifest's digest; the decoded snapshot must agree with the manifest's
  chain head/length — only then is it handed to activation
  (:func:`hbbft_tpu.snapshot.build_joiner` replays the DKG transcript
  and verifies the regenerated public key set).

Wire records (``SyncManifestReq``/``SyncManifest``/``SyncChunkReq``/
``SyncChunk``/``SyncNack``) are registered with the canonical codec at
tags 0x90-0x94 and travel in :data:`hbbft_tpu.net.framing.SYNC` frames.

Concurrency: the client is a plain sequential request/response loop —
no shared state, no locks, nothing held across awaits.  Abandoning a
transfer is always counted (``hbbft_sync_transfers_abandoned_total``).
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
import random
import zlib
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

from hbbft_tpu.net import framing
from hbbft_tpu.obs.metrics import Registry
from hbbft_tpu.snapshot import (
    JoinSnapshot,
    decode_join_snapshot,
    encode_join_snapshot,
)

Addr = Tuple[str, int]

logger = logging.getLogger("hbbft_tpu.net")

#: default transfer chunk size — small enough that a stalled donor costs
#: little progress, large enough that a realistic image is a few chunks
DEFAULT_CHUNK_BYTES = 32 * 1024


class StateSyncError(RuntimeError):
    """The transfer could not complete (no donors / no quorum / all
    donor cycles exhausted / image verification failed)."""


class _ImageRotated(Exception):
    """Every donor now NACKs the image being fetched ("unknown image"):
    the cluster rotated to a newer snapshot mid-transfer — refresh the
    manifests and restart on the new image."""


# ===========================================================================
# Wire records (registered at 0x90-0x94 in protocols.wire)
# ===========================================================================


@dataclass(frozen=True)
class SyncManifestReq:
    """Joiner → donor: describe your latest join snapshot."""


@dataclass(frozen=True)
class SyncManifest:
    """Donor → joiner: snapshot advertisement.

    ``image_sha3`` content-addresses the image: chunk requests quote it,
    and any donor advertising the same digest is interchangeable."""

    era: int
    chain_len: int
    chain_head: bytes        # 32-byte ledger digest at the era boundary
    image_sha3: bytes        # 32-byte digest of the full image
    image_len: int
    chunk_bytes: int
    n_chunks: int


@dataclass(frozen=True)
class SyncChunkReq:
    """Joiner → donor: chunk ``index`` of image ``image_sha3``."""

    image_sha3: bytes
    index: int


@dataclass(frozen=True)
class SyncChunk:
    """Donor → joiner: one CRC'd transfer chunk."""

    image_sha3: bytes
    index: int
    crc: int                 # zlib.crc32(data)
    data: bytes


@dataclass(frozen=True)
class SyncNack:
    """Donor → joiner: the request cannot be served (no snapshot yet,
    unknown image, out-of-range chunk)."""

    reason: str


def manifest_key(m: SyncManifest) -> Tuple:
    """What donors must agree on before the joiner trusts an image."""
    return (m.era, m.image_sha3, m.chain_head, m.chain_len,
            m.image_len, m.chunk_bytes, m.n_chunks)


# ===========================================================================
# Donor side
# ===========================================================================


class SnapshotStore:
    """The latest published era-boundary snapshot of ONE node, plus the
    request handler the runtime routes ``SYNC`` client frames into.

    ``publish`` runs on the pump's worker thread, ``handle`` on the
    event loop: the (manifest, image) pair is swapped as ONE reference
    so a chunk is always sliced from the image its manifest describes.
    """

    def __init__(self, registry: Optional[Registry] = None,
                 chunk_bytes: int = DEFAULT_CHUNK_BYTES):
        self.chunk_bytes = max(1024, int(chunk_bytes))
        self._published: Optional[Tuple[SyncManifest, bytes]] = None
        r = registry if registry is not None else Registry()
        self._c_published = r.counter(
            "hbbft_sync_snapshots_published_total",
            "era-boundary join snapshots captured and made fetchable")
        self._c_manifests = r.counter(
            "hbbft_sync_manifests_served_total",
            "snapshot manifests served to joiners")
        self._c_chunks = r.counter(
            "hbbft_sync_chunks_served_total",
            "snapshot transfer chunks served to joiners")
        self._c_nacks = r.counter(
            "hbbft_sync_nacks_total",
            "sync requests refused (no snapshot, unknown image, bad "
            "index, undecodable request)")
        self._c_capture_misses = r.counter(
            "hbbft_sync_capture_misses_total",
            "era boundaries that passed before a join snapshot could "
            "be captured (joiners must wait for the next rotation)")

    @property
    def manifest(self) -> Optional[SyncManifest]:
        pub = self._published
        return pub[0] if pub is not None else None

    @property
    def image(self) -> Optional[bytes]:
        pub = self._published
        return pub[1] if pub is not None else None

    def publish(self, snap: JoinSnapshot) -> None:
        """Make ``snap`` the served snapshot (replacing any older era's;
        in-flight transfers of the old image get ``unknown image`` NACKs
        and the joiner restarts on the new manifest)."""
        image = encode_join_snapshot(snap)
        n_chunks = max(1, -(-len(image) // self.chunk_bytes))
        manifest = SyncManifest(
            era=snap.era,
            chain_len=snap.chain_len,
            chain_head=snap.chain_head,
            image_sha3=hashlib.sha3_256(image).digest(),
            image_len=len(image),
            chunk_bytes=self.chunk_bytes,
            n_chunks=n_chunks,
        )
        self._published = (manifest, image)
        self._c_published.inc()

    def handle(self, msg: Any) -> Any:
        """One request → one reply record."""
        pub = self._published
        if isinstance(msg, SyncManifestReq):
            if pub is None:
                self._c_nacks.inc()
                return SyncNack("no snapshot published yet")
            self._c_manifests.inc()
            return pub[0]
        if isinstance(msg, SyncChunkReq):
            if pub is None or msg.image_sha3 != pub[0].image_sha3:
                self._c_nacks.inc()
                return SyncNack("unknown image")
            m, image = pub
            if not 0 <= msg.index < m.n_chunks:
                self._c_nacks.inc()
                return SyncNack(f"chunk index {msg.index} out of range")
            lo = msg.index * m.chunk_bytes
            data = image[lo: lo + m.chunk_bytes]
            self._c_chunks.inc()
            return SyncChunk(m.image_sha3, msg.index, zlib.crc32(data),
                             data)
        self._c_nacks.inc()
        return SyncNack(f"unexpected sync record {type(msg).__name__}")


# ===========================================================================
# Joiner side
# ===========================================================================


class _DonorConn:
    """One client-role connection to a donor, used sequentially."""

    def __init__(self, addr: Addr, cluster_id: bytes, client_id: str,
                 max_frame: int, verify_node=None, challenge_rng=None):
        self.addr = addr
        self.cluster_id = cluster_id
        self.client_id = client_id
        self.max_frame = max_frame
        self.verify_node = verify_node
        self.challenge_rng = challenge_rng
        self.reader: Optional[asyncio.StreamReader] = None
        self.writer: Optional[asyncio.StreamWriter] = None

    async def connect(self, timeout_s: float) -> None:
        # with verify_node set, the handshake CHALLENGEs the donor to
        # sign with its era key — a snapshot source must prove it IS the
        # validator its address claims (framing.client_hello_handshake);
        # refusal surfaces as FrameError -> counted retry/failover
        self.reader, self.writer, _hello = \
            await framing.client_hello_handshake(
                self.addr, self.cluster_id, self.client_id,
                timeout_s=timeout_s, max_frame=self.max_frame,
                verify_node=self.verify_node,
                challenge_rng=self.challenge_rng)

    async def request(self, msg: Any, timeout_s: float) -> Any:
        """Send one sync record, await the next SYNC reply (skipping
        unrelated node→client pushes like TX_COMMIT)."""
        from hbbft_tpu.protocols import wire

        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout_s
        self.writer.write(framing.encode_frame(
            framing.SYNC, wire.encode_message(msg), self.max_frame))
        await self.writer.drain()
        while True:
            remaining = deadline - loop.time()
            if remaining <= 0:
                raise asyncio.TimeoutError("sync request timed out")
            kind, payload = await asyncio.wait_for(
                framing.read_one_frame(self.reader, self.max_frame),
                remaining)
            if kind == framing.SYNC:
                return wire.decode_message(payload)

    def close(self) -> None:
        if self.writer is not None:
            self.writer.close()
            self.reader = self.writer = None


class StateSyncClient:
    """Fetch a verified :class:`~hbbft_tpu.snapshot.JoinSnapshot` from a
    set of donor nodes, with donor failover and resumable chunking."""

    def __init__(
        self,
        donors: List[Addr],
        cluster_id: bytes,
        *,
        client_id: str = "statesync",
        request_timeout_s: float = 4.0,
        connect_timeout_s: float = 3.0,
        min_manifest_confirm: int = 1,
        max_donor_cycles: int = 3,
        max_image_refreshes: int = 2,
        backoff_base_s: float = 0.2,
        seed: int = 0,
        max_frame: int = framing.DEFAULT_MAX_FRAME,
        registry: Optional[Registry] = None,
        donor_key: Optional[Callable[[Any], Any]] = None,
    ):
        if not donors:
            raise ValueError("statesync needs at least one donor address")
        self.donors = list(donors)
        # donor authentication: node_id -> plain public key (None =
        # unknown donor).  With the callable set, every donor connection
        # is challenge–response verified before any snapshot byte is
        # trusted; without it the legacy identification-only handshake
        # applies (the snapshot is still multi-donor cross-checked).
        self.donor_key = donor_key
        self.cluster_id = bytes(cluster_id)
        self.client_id = client_id
        self.request_timeout_s = request_timeout_s
        self.connect_timeout_s = connect_timeout_s
        self.min_manifest_confirm = max(1, min_manifest_confirm)
        self.max_donor_cycles = max(1, max_donor_cycles)
        self.max_image_refreshes = max(0, max_image_refreshes)
        self.backoff_base_s = backoff_base_s
        self.rng = random.Random(seed)
        self.max_frame = max_frame
        r = registry if registry is not None else Registry()
        self._c_manifests = r.counter(
            "hbbft_sync_manifests_fetched_total",
            "donor manifests fetched during joins")
        self._c_chunks = r.counter(
            "hbbft_sync_chunks_fetched_total",
            "verified transfer chunks received")
        self._c_bytes = r.counter(
            "hbbft_sync_bytes_fetched_total",
            "verified snapshot image bytes received")
        self._c_retries = r.counter(
            "hbbft_sync_chunk_retries_total",
            "chunk requests that failed (timeout, CRC mismatch, nack, "
            "dead donor) and were retried elsewhere")
        self._c_failovers = r.counter(
            "hbbft_sync_donor_failovers_total",
            "switches to another donor mid-transfer")
        self._c_abandoned = r.counter(
            "hbbft_sync_transfers_abandoned_total",
            "transfers abandoned after exhausting every donor cycle")
        self._c_auth_fail = r.counter(
            "hbbft_sync_donor_auth_failures_total",
            "donor connections refused because the donor failed the "
            "identity challenge (unknown id or bad era-key signature)")

    def _verify_donor(self, node_id, era, sig_bytes, transcript) -> bool:
        """client_hello_handshake verify_node hook: judge a donor's
        challenge answer against the configured key map; every refusal
        is counted before it surfaces as a connect failure."""
        from hbbft_tpu.crypto import tc

        ok = False
        key = self.donor_key(node_id) if self.donor_key else None
        if key is not None:
            try:
                ok = bool(key.verify(
                    tc.Signature.from_bytes(bytes(sig_bytes)),
                    transcript))
            # hblint: disable=fault-swallowed-drop (accounted just
            # below: every refusal path funnels into the shared
            # hbbft_sync_donor_auth_failures_total increment)
            except ValueError:
                ok = False
        if not ok:
            self._c_auth_fail.inc()
            logger.warning("statesync: donor claiming %r failed the "
                           "identity challenge", node_id)
        return ok

    def _donor_conn(self, addr: Addr) -> _DonorConn:
        return _DonorConn(
            addr, self.cluster_id, self.client_id, self.max_frame,
            verify_node=(self._verify_donor if self.donor_key else None),
            challenge_rng=self.rng)

    # -- manifests -----------------------------------------------------------

    async def collect_manifests(self) -> List[Tuple[Addr, SyncManifest]]:
        """Best-effort manifest from every donor, queried CONCURRENTLY
        (dead donors cost one shared timeout, not a serialized one each;
        result order follows the donor list).  Unreachable donors and
        NACKs are skipped; each skip is a counted retry."""

        async def one(addr: Addr) -> Optional[SyncManifest]:
            conn = self._donor_conn(addr)
            try:
                await conn.connect(self.connect_timeout_s)
                reply = await conn.request(SyncManifestReq(),
                                           self.request_timeout_s)
                if isinstance(reply, SyncManifest):
                    self._c_manifests.inc()
                    return reply
                self._c_retries.inc()
                logger.info("statesync: donor %r answered %s",
                            addr, type(reply).__name__)
            except (OSError, asyncio.TimeoutError, ValueError,
                    asyncio.IncompleteReadError) as exc:
                self._c_retries.inc()
                logger.info("statesync: donor %r manifest failed: %r",
                            addr, exc)
            finally:
                conn.close()
            return None

        replies = await asyncio.gather(*(one(a) for a in self.donors))
        return [(addr, m) for addr, m in zip(self.donors, replies)
                if m is not None]

    def _choose_image(
        self, manifests: List[Tuple[Addr, SyncManifest]]
    ) -> Tuple[SyncManifest, List[Addr]]:
        """The manifest enough donors agree on (largest agreeing donor
        set; highest era breaks ties)."""
        groups: Dict[Tuple, List[Addr]] = {}
        by_key: Dict[Tuple, SyncManifest] = {}
        for addr, m in manifests:
            key = manifest_key(m)
            groups.setdefault(key, []).append(addr)
            by_key[key] = m
        if not groups:
            raise StateSyncError("no donor served a snapshot manifest")
        best = max(groups.items(),
                   key=lambda kv: (len(kv[1]), kv[0][0]))
        key, addrs = best
        if len(addrs) < self.min_manifest_confirm:
            raise StateSyncError(
                f"only {len(addrs)} donor(s) agree on a snapshot "
                f"(need {self.min_manifest_confirm}); manifests: "
                f"{sorted(groups, key=repr)!r}")
        return by_key[key], addrs

    # -- the transfer --------------------------------------------------------

    async def fetch(self) -> JoinSnapshot:
        """Collect manifests, fetch + verify every chunk with failover,
        decode and cross-check the image.  A cluster that rotates to a
        NEWER snapshot mid-transfer (every donor starts NACKing the old
        image) triggers a manifest refresh and a restart on the new
        image, up to ``max_image_refreshes`` times.  Raises
        :class:`StateSyncError` after exhausting every donor cycle."""
        for _refresh in range(self.max_image_refreshes + 1):
            manifests = await self.collect_manifests()
            try:
                manifest, addrs = self._choose_image(manifests)
            except StateSyncError:
                # giving up before the first chunk is still an abandoned
                # transfer — the joiner must never fail silently
                self._c_abandoned.inc()
                raise
            try:
                return await self._transfer(manifest, addrs)
            except _ImageRotated:
                self._c_retries.inc()
                logger.info("statesync: donors rotated to a newer "
                            "snapshot mid-transfer; refreshing "
                            "manifests and restarting")
            except StateSyncError:
                # the single abandon accounting point for a transfer
                # that ran out of road (donor cycles, bad image)
                self._c_abandoned.inc()
                raise
        self._c_abandoned.inc()
        raise StateSyncError(
            f"snapshot rotated out from under the transfer "
            f"{self.max_image_refreshes + 1} times; abandoned")

    async def _transfer(self, manifest: SyncManifest,
                        addrs: List[Addr]) -> JoinSnapshot:
        chunks: List[bytes] = []
        conn: Optional[_DonorConn] = None
        donor_i = 0
        failures_this_cycle = 0
        cycles = 0
        # donors that answered "unknown image": once every donor has (or
        # the cycles run dry with any such evidence), the cluster rotated
        # to a newer snapshot — restart on fresh manifests, don't abandon
        unknown_image: set = set()
        while len(chunks) < manifest.n_chunks:
            if conn is None:
                addr = addrs[donor_i % len(addrs)]
                conn = self._donor_conn(addr)
                try:
                    await conn.connect(self.connect_timeout_s)
                except (OSError, asyncio.TimeoutError,
                        ValueError) as exc:
                    logger.info("statesync: donor %r connect failed: %r",
                                conn.addr, exc)
                    conn = None
                    donor_i, failures_this_cycle, cycles = (
                        await self._failover(addrs, donor_i,
                                             failures_this_cycle, cycles))
                    continue
            index = len(chunks)
            try:
                reply = await conn.request(
                    SyncChunkReq(manifest.image_sha3, index),
                    self.request_timeout_s)
                if (isinstance(reply, SyncNack)
                        and reply.reason.startswith("unknown image")):
                    unknown_image.add(conn.addr)
                    raise StateSyncError(
                        "donor no longer serves this image")
                if not isinstance(reply, SyncChunk):
                    raise StateSyncError(
                        f"donor answered {type(reply).__name__}")
                if (reply.image_sha3 != manifest.image_sha3
                        or reply.index != index
                        or zlib.crc32(reply.data) != reply.crc
                        or not reply.data):
                    raise StateSyncError("corrupt chunk")
            except (OSError, asyncio.TimeoutError, ValueError,
                    asyncio.IncompleteReadError, StateSyncError) as exc:
                self._c_retries.inc()
                logger.info("statesync: chunk %d from %r failed: %r",
                            index, conn.addr, exc)
                conn.close()
                conn = None
                if len(unknown_image) >= len(addrs):
                    raise _ImageRotated()
                try:
                    donor_i, failures_this_cycle, cycles = (
                        await self._failover(addrs, donor_i,
                                             failures_this_cycle,
                                             cycles))
                except StateSyncError:
                    if unknown_image:
                        # dead donors + rotated donors: the image is
                        # gone either way — refresh, don't abandon yet
                        raise _ImageRotated() from None
                    raise
                continue
            failures_this_cycle = 0
            self._c_chunks.inc()
            self._c_bytes.inc(len(reply.data))
            chunks.append(reply.data)
        if conn is not None:
            conn.close()
        image = b"".join(chunks)
        if (len(image) != manifest.image_len
                or hashlib.sha3_256(image).digest()
                != manifest.image_sha3):
            raise StateSyncError(
                "assembled image fails digest verification")
        snap = decode_join_snapshot(image)
        if (snap.chain_head != manifest.chain_head
                or snap.chain_len != manifest.chain_len
                or snap.era != manifest.era):
            raise StateSyncError(
                "decoded snapshot disagrees with the confirmed manifest")
        return snap

    async def _failover(self, addrs: List[Addr], donor_i: int,
                        failures_this_cycle: int, cycles: int
                        ) -> Tuple[int, int, int]:
        """Advance to the next donor; after a full cycle of failures,
        back off (seeded exponential + jitter) and start another cycle —
        up to ``max_donor_cycles``, then raise (``fetch`` counts the
        abandon)."""
        self._c_failovers.inc()
        donor_i += 1
        failures_this_cycle += 1
        if failures_this_cycle >= len(addrs):
            cycles += 1
            if cycles >= self.max_donor_cycles:
                raise StateSyncError(
                    f"every donor failed {cycles} full cycle(s); "
                    f"transfer abandoned")
            delay = (self.backoff_base_s * (2 ** (cycles - 1))
                     * (0.5 + 0.5 * self.rng.random()))
            await asyncio.sleep(delay)
            failures_this_cycle = 0
        return donor_i, failures_this_cycle, cycles


async def fetch_join_snapshot(donors: List[Addr], cluster_id: bytes,
                              **kwargs) -> JoinSnapshot:
    """One-call joiner bootstrap (see :class:`StateSyncClient`)."""
    return await StateSyncClient(donors, cluster_id, **kwargs).fetch()

"""Length-prefixed frames over the wire codec, plus the versioned hello.

The sans-I/O stack speaks :mod:`hbbft_tpu.protocols.wire` message bytes;
this module wraps those bytes (and the small set of runtime control
payloads) into self-delimiting TCP frames:

    u32 length | u8 kind | payload            (length = 1 + len(payload))

Every decode path is capped: a frame claiming more than ``max_frame`` bytes
is a loud :class:`FrameError` before any allocation happens, and a cut
stream simply stays pending — :class:`FrameDecoder` never yields a partial
frame.  The first frame on every connection must be a :data:`HELLO` whose
payload carries magic, protocol version, the sender's role and id, its
current (era, epoch), and the cluster id; any mismatch kills the
connection before a single protocol message is parsed.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Hashable, List, Tuple

from hbbft_tpu.protocols import wire

MAGIC = b"HBTN"
# v2: MSG_BATCH coalesced consensus frames (epoch-pipelined runtime).
# v3: authenticated node-role handshake (CHALLENGE/AUTH) — a node hello
# is now *proven* with a per-era key signature, not merely claimed.
# The hello's version check turns a mixed-version cluster into a clean
# handshake error instead of mid-stream frame-kind surprises.
PROTOCOL_VERSION = 3

# Frame cap: one frame carries at most one wire message (itself capped at
# wire.MAX_MESSAGE_BYTES) plus the kind byte; the hello/control frames are
# tiny.  Kept as a parameter everywhere so tests can shrink it.
DEFAULT_MAX_FRAME = wire.MAX_MESSAGE_BYTES + 1

# -- frame kinds -------------------------------------------------------------

HELLO = 0x01       # versioned handshake; first frame both ways
MSG = 0x02         # consensus payload: wire.encode_message bytes
PING = 0x03        # heartbeat, u64 nonce
PONG = 0x04        # heartbeat echo
TX = 0x05          # client → node: raw transaction bytes
TX_ACK = 0x06      # node → client: u8 status + 32-byte tx digest
TX_COMMIT = 0x07   # node → client: era/epoch + committed tx digests
STATUS_REQ = 0x08  # client → node: empty
STATUS = 0x09      # node → client: JSON status document
MSG_BATCH = 0x0A   # several MSG payloads coalesced into one frame
SYNC = 0x0B        # snapshot state-sync record (net/statesync.py), both
                   # directions on a client-role connection; payload is
                   # wire.encode_message bytes of a Sync* record
CHALLENGE = 0x0C   # verifier → prover: random nonce + session id the
                   # prover must sign (node-role handshake; also sent by
                   # a statesync joiner to authenticate its donor)
AUTH = 0x0D        # prover → verifier: u64 era + blob(signature) over
                   # auth_transcript(...) by the prover's per-era key

KIND_NAMES = {
    HELLO: "HELLO", MSG: "MSG", PING: "PING", PONG: "PONG", TX: "TX",
    TX_ACK: "TX_ACK", TX_COMMIT: "TX_COMMIT", STATUS_REQ: "STATUS_REQ",
    STATUS: "STATUS", MSG_BATCH: "MSG_BATCH", SYNC: "SYNC",
    CHALLENGE: "CHALLENGE", AUTH: "AUTH",
}

# TX_ACK status bytes
ACK_ACCEPTED = 0
ACK_DUPLICATE = 1
ACK_FULL = 2       # backpressure: retry later
ACK_REJECTED = 3   # oversized: never retry
ACK_SHED = 4       # push notification: a previously-ACCEPTED tx was
                   # shed under fair-admission pressure and will not
                   # commit — re-submit if still wanted

ROLE_NODE = 0x01
ROLE_CLIENT = 0x02

# -- authenticated handshake (v3) --------------------------------------------
#
# The node-role hello is identification; the CHALLENGE/AUTH exchange is
# authentication.  The verifier issues a random nonce + session id; the
# prover signs auth_transcript(...) — which binds the cluster id, the
# nonce, the session, and the hello header material (claimed id, role,
# era) — with its per-era secret key.  The session id is then bound into
# every subsequent heartbeat PING on the connection, so a hijacked TCP
# stream cannot ride an already-authenticated session.  All handshake
# frames fit under MAX_HANDSHAKE_FRAME: the half-open byte budget — a
# dialer cannot make the verifier buffer a large frame before it proves
# anything.

#: byte budget for any single pre-auth handshake frame (hello /
#: challenge / auth); generous for every legitimate encoding, tiny
#: against the transport's MiB-scale steady-state frame cap
MAX_HANDSHAKE_FRAME = 4096

NONCE_LEN = 32     # server-issued random challenge nonce
SESSION_LEN = 8    # per-connection session id, echoed in heartbeats


class FrameError(ValueError):
    """Malformed, oversized, or protocol-violating frame data."""


def encode_frame(kind: int, payload: bytes,
                 max_frame: int = DEFAULT_MAX_FRAME) -> bytes:
    body_len = 1 + len(payload)
    if body_len > max_frame:
        raise FrameError(
            f"frame of {body_len} bytes exceeds cap {max_frame}"
        )
    return struct.pack(">IB", body_len, kind) + payload


class FrameDecoder:
    """Incremental frame parser: ``feed`` bytes, get complete frames.

    Holds at most one partial frame; enforces the size cap on the *claimed*
    length, so a hostile 4 GiB prefix is rejected before buffering."""

    def __init__(self, max_frame: int = DEFAULT_MAX_FRAME):
        self.max_frame = max_frame
        self._buf = bytearray()

    def feed(self, data: bytes) -> List[Tuple[int, bytes]]:
        self._buf.extend(data)
        frames: List[Tuple[int, bytes]] = []
        while True:
            if len(self._buf) < 4:
                return frames
            (body_len,) = struct.unpack_from(">I", self._buf, 0)
            if body_len < 1:
                raise FrameError("zero-length frame body")
            if body_len > self.max_frame:
                raise FrameError(
                    f"frame of {body_len} bytes exceeds cap {self.max_frame}"
                )
            if len(self._buf) < 4 + body_len:
                return frames
            kind = self._buf[4]
            payload = bytes(self._buf[5 : 4 + body_len])
            del self._buf[: 4 + body_len]
            frames.append((kind, payload))

    def pending(self) -> int:
        """Bytes buffered awaiting a complete frame."""
        return len(self._buf)


def pack_msgs(payloads: List[bytes],
              max_frame: int = DEFAULT_MAX_FRAME) -> List[bytes]:
    """Coalesce consensus message payloads into as few frames as the cap
    allows: one plain :data:`MSG` frame for a lone payload, otherwise
    :data:`MSG_BATCH` frames whose body is ``(u32 len | payload)*``.

    This is the per-(pump-iteration, destination) write path of the
    epoch-pipelined runtime — it turns dozens of per-message socket
    writes into one or two — and it is order-preserving.  A payload that
    cannot fit even alone raises :class:`FrameError` (callers pre-check
    against the cap and drop loudly)."""
    frames: List[bytes] = []
    group: List[bytes] = []
    size = 1  # kind byte

    def flush() -> None:
        if not group:
            return
        if len(group) == 1:
            frames.append(encode_frame(MSG, group[0], max_frame))
        else:
            body = b"".join(
                struct.pack(">I", len(p)) + p for p in group
            )
            frames.append(encode_frame(MSG_BATCH, body, max_frame))
        group.clear()

    for p in payloads:
        if 1 + len(p) > max_frame:
            raise FrameError(
                f"message of {len(p)} bytes exceeds frame cap {max_frame}"
            )
        if group and size + 4 + len(p) > max_frame:
            flush()
            size = 1
        group.append(p)
        size += 4 + len(p)
    flush()
    return frames


def split_msgs(payload: bytes) -> List[bytes]:
    """Inverse of the :data:`MSG_BATCH` body encoding; truncation or
    trailing garbage is a loud :class:`FrameError` (the sender is
    malformed, not merely slow)."""
    out: List[bytes] = []
    off = 0
    n = len(payload)
    while off < n:
        if off + 4 > n:
            raise FrameError("truncated MSG_BATCH length prefix")
        (length,) = struct.unpack_from(">I", payload, off)
        off += 4
        if off + length > n:
            raise FrameError("truncated MSG_BATCH entry")
        out.append(payload[off : off + length])
        off += length
    if not out:
        raise FrameError("empty MSG_BATCH frame")
    return out


async def read_one_frame(reader, max_frame: int = DEFAULT_MAX_FRAME
                         ) -> Tuple[int, bytes]:
    """Read exactly one frame from an ``asyncio.StreamReader`` — the
    handshake-time sibling of :class:`FrameDecoder` (used before a
    connection's steady-state decode loop starts)."""
    header = await reader.readexactly(4)
    (body_len,) = struct.unpack(">I", header)
    if body_len < 1 or body_len > max_frame:
        raise FrameError(
            f"frame of {body_len} bytes outside (0, {max_frame}]"
        )
    body = await reader.readexactly(body_len)
    return body[0], body[1:]


def auth_transcript(cluster_id: bytes, nonce: bytes, session: bytes,
                    node_id, role: int, era: int) -> bytes:
    """The exact bytes an authenticating peer signs: domain tag, cluster
    id, the verifier's random nonce + session id, and the hello header
    material (claimed node id, role, the era whose key signs).  Both
    sides derive it independently — nothing signature-relevant ever
    travels only one way."""
    if len(nonce) != NONCE_LEN or len(session) != SESSION_LEN:
        raise FrameError("bad challenge nonce/session length")
    return (
        b"hbbft-auth/3"
        + wire.blob(cluster_id)
        + nonce
        + session
        + wire.node_id(node_id)
        + bytes([role])
        + wire.u64(era)
    )


def encode_challenge(nonce: bytes, session: bytes) -> bytes:
    if len(nonce) != NONCE_LEN or len(session) != SESSION_LEN:
        raise FrameError("bad challenge nonce/session length")
    return nonce + session


def decode_challenge(payload: bytes) -> Tuple[bytes, bytes]:
    if len(payload) != NONCE_LEN + SESSION_LEN:
        raise FrameError(
            f"challenge payload of {len(payload)} bytes "
            f"(want {NONCE_LEN + SESSION_LEN})"
        )
    return payload[:NONCE_LEN], payload[NONCE_LEN:]


def encode_auth(era: int, sig: bytes) -> bytes:
    return wire.u64(era) + wire.blob(sig)


def decode_auth(payload: bytes) -> Tuple[int, bytes]:
    r = wire.Reader(payload)
    try:
        era = r.u64()
        sig = r.blob()
        if not r.done():
            raise FrameError("trailing bytes after auth record")
    except ValueError as exc:
        if isinstance(exc, FrameError):
            raise
        raise FrameError(f"malformed auth record: {exc}") from exc
    return era, sig


async def client_hello_handshake(
    addr, cluster_id: bytes, client_id, *,
    timeout_s: float, max_frame: int = DEFAULT_MAX_FRAME,
    verify_node=None, challenge_rng=None,
):
    """Dial ``addr``, perform the client-role HELLO exchange, and return
    ``(reader, writer, node_hello)`` — the one handshake shared by every
    client-side connection (``ClusterClient``, the state-sync joiner).
    Raises :class:`FrameError` on a non-HELLO reply or cluster-id
    mismatch; timeouts/connection errors propagate.

    ``verify_node`` authenticates the NODE to the client (the statesync
    joiner's donor check): a callable ``(node_id, era, sig, transcript)
    -> bool``.  When given, the client issues a CHALLENGE after the hello
    exchange and the node must answer a valid AUTH signed by its per-era
    key — an impersonated donor fails loudly here, before a single sync
    byte is trusted.  ``challenge_rng`` (a ``random.Random``) seeds the
    nonce for deterministic tests; default is OS entropy."""
    import asyncio
    import os

    reader, writer = await asyncio.wait_for(
        asyncio.open_connection(*addr), timeout_s
    )
    try:
        hello = Hello(node_id=client_id, role=ROLE_CLIENT,
                      cluster_id=cluster_id, era=0, epoch=0)
        writer.write(encode_frame(HELLO, encode_hello(hello), max_frame))
        await writer.drain()
        kind, payload = await asyncio.wait_for(
            read_one_frame(reader, max_frame), timeout_s
        )
        if kind != HELLO:
            raise FrameError("node did not answer with HELLO")
        node_hello = decode_hello(payload)
        if node_hello.cluster_id != cluster_id:
            raise FrameError("cluster id mismatch")
        if verify_node is not None:
            if challenge_rng is not None:
                blob = challenge_rng.randbytes(NONCE_LEN + SESSION_LEN)
            else:
                blob = os.urandom(NONCE_LEN + SESSION_LEN)
            nonce, session = blob[:NONCE_LEN], blob[NONCE_LEN:]
            writer.write(encode_frame(
                CHALLENGE, encode_challenge(nonce, session), max_frame))
            await writer.drain()
            kind, payload = await asyncio.wait_for(
                read_one_frame(reader, MAX_HANDSHAKE_FRAME), timeout_s
            )
            if kind != AUTH:
                raise FrameError("node did not answer the challenge")
            era, sig = decode_auth(payload)
            transcript = auth_transcript(
                cluster_id, nonce, session,
                node_hello.node_id, ROLE_NODE, era)
            if not verify_node(node_hello.node_id, era, sig, transcript):
                raise FrameError(
                    f"node {node_hello.node_id!r} failed the donor "
                    f"authentication challenge"
                )
    except BaseException:
        writer.close()
        raise
    return reader, writer, node_hello


# -- hello -------------------------------------------------------------------


@dataclass(frozen=True)
class Hello:
    node_id: Hashable           # node id, or a client token string
    role: int                   # ROLE_NODE | ROLE_CLIENT
    cluster_id: bytes           # must match on both ends
    era: int                    # sender's current (era, epoch) — the
    epoch: int                  # SenderQueue resume key

    @property
    def key(self) -> Tuple[int, int]:
        return (self.era, self.epoch)


def encode_hello(h: Hello) -> bytes:
    if h.role not in (ROLE_NODE, ROLE_CLIENT):
        raise FrameError(f"bad hello role {h.role}")
    return (
        MAGIC
        + wire.u32(PROTOCOL_VERSION)
        + bytes([h.role])
        + wire.node_id(h.node_id)
        + wire.u64(h.era)
        + wire.u64(h.epoch)
        + wire.blob(h.cluster_id)
    )


def decode_hello(payload: bytes) -> Hello:
    r = wire.Reader(payload)
    try:
        if r.take(4) != MAGIC:
            raise FrameError("bad hello magic")
        version = r.u32()
        if version != PROTOCOL_VERSION:
            raise FrameError(
                f"hello version mismatch: peer speaks {version}, "
                f"we speak {PROTOCOL_VERSION}"
            )
        role = r.take(1)[0]
        if role not in (ROLE_NODE, ROLE_CLIENT):
            raise FrameError(f"bad hello role {role}")
        node_id = wire.read_node_id(r)
        era = r.u64()
        epoch = r.u64()
        cluster_id = r.blob()
        if not r.done():
            raise FrameError("trailing bytes after hello")
    except ValueError as exc:  # wire truncation/caps → FrameError
        if isinstance(exc, FrameError):
            raise
        raise FrameError(f"malformed hello: {exc}") from exc
    return Hello(node_id=node_id, role=role, cluster_id=cluster_id,
                 era=era, epoch=epoch)

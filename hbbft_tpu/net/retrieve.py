"""Lazy retrieval for dispersed payloads: fetch k shards, reconstruct.

The second half of :mod:`hbbft_tpu.protocols.vid`: once an epoch orders a
``(root, cert)`` commitment, the node runtime asks the holders of the
shards it is missing — targeted, one :class:`~hbbft_tpu.protocols.vid.VidRetrieve`
per missing index, escalating to broadcast only on late retry rounds —
collects proof-valid :class:`~hbbft_tpu.protocols.vid.VidShard` replies, and
reconstructs the payload through the RS coder's LRU'd Gauss–Jordan
pattern caches the moment ``k = n − 2f`` distinct shards are in hand.
The reconstruction is re-encoded and re-rooted against the committed
commitment before anything is surfaced — a Byzantine proposer's
non-codeword dispersal fails this check for EVERY shard subset, so all
correct retrievers agree the contribution is empty and fault the
proposer.

Everything here is clock-free (``now`` is an explicit parameter —
hblint's determinism scope covers this module): the runtime supplies its
clock and drives :meth:`RetrieveService.tick` for retries/timeouts.

Serving is budgeted per peer: a token bucket of shard bytes per second
(the retrieve-side sibling of the transport's ``IngressBudget``) bounds
how hard one peer can milk the shard store; over-budget requests are
dropped, counted, and reported through ``on_note`` so the guard/audit
pipeline sees the incident.  Retrieves for roots this node never stored
are *refused loudly* the same way — counted plus a ``vid_refusal`` note
— instead of faulting the requester, because a faster peer legitimately
retrieves an epoch the local node has not finished receiving dispersals
for (the requester simply retries).
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Hashable, List, Optional, Tuple

from hbbft_tpu.fault_log import FaultKind
from hbbft_tpu.ops import rs
from hbbft_tpu.ops.merkle import MerkleTree, Proof
from hbbft_tpu.protocols.broadcast import _unframe_value
from hbbft_tpu.protocols.vid import VidRetrieve, VidShard
from hbbft_tpu.traits import Step

NodeId = Hashable

#: default shard-store byte budget — a few epochs of MB-scale dispersals
DEFAULT_STORE_BYTES = 64 * 2**20

#: per-stored-root bookkeeping overhead charged on top of the shard bytes
#: (root key, proof path digests, dict slots) so a flood of tiny shards
#: cannot grow the store unbounded under a pure payload-byte cap
_ROOT_OVERHEAD = 128


class ShardStore:
    """Bounded LRU of (root → our shard + proof), byte-capped.

    One entry per root: a node holds exactly its OWN shard of each
    dispersal (the proposer included).  ``put`` refreshes recency and
    evicts the oldest roots once the byte budget is exceeded; eviction is
    whole-root, counted.  Memoryview proof values (the proposer's
    zero-copy slices of the full shard buffer) are materialized on entry
    — retaining the view would pin the entire n-shard allocation."""

    def __init__(self, max_bytes: int = DEFAULT_STORE_BYTES):
        self.max_bytes = int(max_bytes)
        self._roots: "OrderedDict[bytes, Tuple[int, Proof]]" = OrderedDict()
        self.bytes = 0
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._roots)

    @staticmethod
    def _cost(proof: Proof) -> int:
        return len(proof.value) + 33 * len(proof.path) + _ROOT_OVERHEAD

    def put(self, root: bytes, total_len: int, proof: Proof) -> None:
        if root in self._roots:
            self._roots.move_to_end(root)
            return
        if isinstance(proof.value, memoryview):
            proof = Proof(value=bytes(proof.value), index=proof.index,
                          root_hash=proof.root_hash, path=proof.path)
        self._roots[root] = (total_len, proof)
        self.bytes += self._cost(proof)
        while self.bytes > self.max_bytes and len(self._roots) > 1:
            _, (_, old) = self._roots.popitem(last=False)
            self.bytes -= self._cost(old)
            self.evictions += 1

    def proof_for(self, root: bytes) -> Optional[Tuple[int, Proof]]:
        """(total_len, proof) for ``root``, refreshing recency."""
        entry = self._roots.get(root)
        if entry is not None:
            self._roots.move_to_end(root)
        return entry

    def known(self, root: bytes) -> bool:
        return root in self._roots


@dataclass(frozen=True)
class RetrievedPayload:
    """Step output of a finished retrieval.  ``payload is None`` means the
    retrieval failed — reconstruction mismatched the committed root
    (proposer fault, already logged) or every round timed out."""

    root: bytes
    proposer: Any
    payload: Optional[bytes]
    total_len: int
    shards_bad: int
    rounds: int
    t_ordered: float


@dataclass
class _Retrieval:
    n: int
    f: int
    total_len: int
    proposer: Any
    t_ordered: float
    deadline: float
    shards: Dict[int, bytes] = field(default_factory=dict)
    shard_len: int = -1
    bad: int = 0
    rounds: int = 0
    #: validator ids in shard-index order (holders[i] stores shard i);
    #: empty = unknown mapping, fall back to broadcast retrieves
    holders: Tuple[Any, ...] = ()
    cursor: int = 0
    #: False while queued behind the in-flight cap: no requests sent, no
    #: retry rounds burned — promoted FIFO as active retrievals finish
    active: bool = False


class RetrieveService:
    """Fetch/reconstruct driver state for one node.

    Methods return :class:`~hbbft_tpu.traits.Step`\\ s (messages to peers,
    fault evidence, :class:`RetrievedPayload` outputs) that the runtime
    absorbs exactly like protocol steps.  All counters are plain ints,
    snapshotted into the ``hbbft_vid_*`` metric family by the runtime.
    """

    def __init__(self, our_id: NodeId, store: ShardStore, *,
                 serve_bytes_per_s: float = 8 * 2**20,
                 serve_burst_bytes: float = 4 * 2**20,
                 retry_s: float = 0.5,
                 max_rounds: int = 8,
                 max_inflight: int = 2,
                 on_note: Optional[Callable[[str, str], None]] = None):
        self.our_id = our_id
        self.store = store
        self.serve_bytes_per_s = float(serve_bytes_per_s)
        self.serve_burst_bytes = float(serve_burst_bytes)
        self.retry_s = float(retry_s)
        self.max_rounds = int(max_rounds)
        # Retrieval is deliberately BACKGROUND work: payloads are fetched
        # with whatever capacity ordering leaves over.  Only this many
        # retrievals request shards concurrently; the rest queue FIFO.
        # Unbounded retrieval (0 = no cap) is exactly how a
        # bandwidth-starved node buries its own consensus traffic — every
        # committed root pulls k shards of bulk through the same links
        # that carry the tiny ordering frames.
        self.max_inflight = int(max_inflight)
        self.on_note = on_note
        self._pending: Dict[bytes, _Retrieval] = {}
        self._quota: Dict[NodeId, Tuple[float, float]] = {}
        # deterministic counters
        self.retrieves = 0          # retrievals started
        self.retrieved = 0          # payloads reconstructed + verified
        self.served = 0             # shards served to peers
        self.refusals = 0           # retrieves for roots we never stored
        self.quota_drops = 0        # retrieves dropped by the serve budget
        self.shards_bad = 0         # donor shards failing their proof
        self.mismatches = 0         # reconstructions not matching the root
        self.retries = 0            # retry rounds sent
        self.failures = 0           # retrievals exhausted without payload
        self.stray_shards = 0       # shards for nothing pending

    def _note(self, kind: str, detail: str) -> None:
        if self.on_note is not None:
            self.on_note(kind, detail)

    def pending_count(self) -> int:
        return len(self._pending)

    def next_deadline(self) -> Optional[float]:
        due = [p.deadline for p in self._pending.values() if p.active]
        return min(due) if due else None

    # -- requester side ------------------------------------------------------

    def start(self, root: bytes, total_len: int, n: int, f: int,
              proposer: Any, now: float, t_ordered: float,
              holders: Tuple[Any, ...] = ()) -> Step:
        """Open a retrieval for a committed commitment: seed it with our
        own stored shard and fetch the rest.

        With ``holders`` (validator ids in shard-index order — node ``i``
        stores shard ``i``) the request is TARGETED: only the
        ``k − already_held`` missing shards are asked for, one specific
        holder each, starting at a root-derived offset so the donor load
        spreads across the cluster.  A broadcast retrieve would make every
        peer ship its shard — ``n − 1`` responses where ``k − 1`` suffice
        — which is exactly the redundant bulk that buries a
        bandwidth-starved node's links (the ``bandwidth-asym`` shape).
        Un-answered rounds walk to the next holder via :meth:`tick`, and
        round ``≥ 2`` escalates to broadcast, so liveness never depends
        on the targeting.  Without ``holders`` every round broadcasts."""
        if root in self._pending:
            return Step()
        ret = _Retrieval(n=n, f=f, total_len=total_len, proposer=proposer,
                         t_ordered=t_ordered, deadline=float("inf"),
                         holders=tuple(holders),
                         cursor=root[0] if root else 0)
        self._pending[root] = ret
        self.retrieves += 1
        own = self.store.proof_for(root)
        if own is not None:
            _len, proof = own
            ret.shards[proof.index] = bytes(proof.value)
            ret.shard_len = len(proof.value)
        done = self._try_reconstruct(root, ret)
        if done is not None:
            done.extend(self._activate(now))
            return done
        return self._activate(now)

    def _activate(self, now: float) -> Step:
        """Promote queued retrievals into the in-flight window (FIFO,
        insertion order = commit order) and send their first request
        round.  With ``max_inflight <= 0`` everything activates."""
        step = Step()
        cap = self.max_inflight
        active = sum(1 for p in self._pending.values() if p.active)
        for root, ret in self._pending.items():
            if cap > 0 and active >= cap:
                break
            if ret.active:
                continue
            ret.active = True
            ret.deadline = now + self.retry_s
            active += 1
            step.extend(self._request_step(root, ret))
        return step

    def _request_step(self, root: bytes, ret: _Retrieval) -> Step:
        """One round of shard requests: targeted while the holder map is
        known and the round is young, broadcast otherwise."""
        step = Step()
        if ret.holders and ret.rounds < 2:
            k = rs.for_n_f(ret.n, ret.f).data_shards
            need = k - len(ret.shards)
            targets = self._pick_targets(ret, need)
            if len(targets) >= need:
                for h in targets:
                    step.send_to(h, VidRetrieve(root))
                return step
        return step.send_all(VidRetrieve(root))

    def _pick_targets(self, ret: _Retrieval, need: int) -> List[Any]:
        """The next ``need`` holders of shards we don't have, walking the
        index ring from the retrieval's cursor (deterministic — hblint's
        determinism scope covers this module)."""
        out: List[Any] = []
        if need <= 0 or not ret.holders:
            return out
        n = len(ret.holders)
        for _ in range(n):
            i = ret.cursor % n
            ret.cursor += 1
            if i in ret.shards or ret.holders[i] == self.our_id:
                continue
            out.append(ret.holders[i])
            if len(out) >= need:
                break
        return out

    def handle_shard(self, peer: NodeId, msg: VidShard, now: float) -> Step:
        ret = self._pending.get(msg.root)
        if ret is None:
            self.stray_shards += 1
            return Step()
        p = msg.proof
        if p.index in ret.shards:
            return Step()  # duplicate donor — benign
        ok = (
            0 <= p.index < ret.n
            and p.root_hash == msg.root
            and (ret.shard_len < 0 or len(p.value) == ret.shard_len)
            and p.validate(ret.n)
        )
        if not ok:
            ret.bad += 1
            self.shards_bad += 1
            self._note("vid_bad_shard",
                       f"peer={peer!r} root={msg.root.hex()[:24]}")
            return Step.from_fault(peer, FaultKind.VidShardProofInvalid)
        ret.shards[p.index] = bytes(p.value)
        if ret.shard_len < 0:
            ret.shard_len = len(p.value)
        done = self._try_reconstruct(msg.root, ret)
        if done is None:
            return Step()
        return done.extend(self._activate(now))

    def _try_reconstruct(self, root: bytes, ret: _Retrieval
                         ) -> Optional[Step]:
        coder = rs.for_n_f(ret.n, ret.f)
        k = coder.data_shards
        if len(ret.shards) < k:
            return None
        del self._pending[root]
        lst: List[Optional[bytes]] = [None] * coder.total_shards
        for idx, shard in ret.shards.items():
            lst[idx] = shard
        step = Step()
        payload: Optional[bytes] = None
        try:
            full = coder.reconstruct_np(lst)
        # hblint: disable=fault-swallowed-drop (accounted below: a None
        # reconstruction lands in the mismatches counter + the proposer's
        # VidReconstructMismatch fault, never silently)
        except ValueError:
            full = None
        if full is not None and MerkleTree.from_vec(
                full).root_hash() == root:
            payload = _unframe_value(b"".join(full[:k]))
            if payload is not None and len(payload) != ret.total_len:
                payload = None
        if payload is None:
            # every k-subset of proof-valid shards fails this identically:
            # the committed leaves were not an RS codeword — proposer fault
            self.mismatches += 1
            self._note("vid_mismatch",
                       f"proposer={ret.proposer!r} root={root.hex()[:24]}")
            step.fault(ret.proposer, FaultKind.VidReconstructMismatch)
        else:
            self.retrieved += 1
        step.output.append(RetrievedPayload(
            root=root, proposer=ret.proposer, payload=payload,
            total_len=ret.total_len, shards_bad=ret.bad,
            rounds=ret.rounds, t_ordered=ret.t_ordered))
        return step

    def tick(self, now: float) -> Step:
        """Retry overdue ACTIVE retrievals; exhaust after ``max_rounds``.
        Queued retrievals burn no rounds — they promote via
        :meth:`_activate` as slots free up."""
        step = Step()
        for root in [r for r, p in self._pending.items()
                     if p.active and p.deadline <= now]:
            ret = self._pending[root]
            ret.rounds += 1
            if ret.rounds >= self.max_rounds:
                del self._pending[root]
                self.failures += 1
                self._note("vid_exhausted",
                           f"root={root.hex()[:24]} "
                           f"shards={len(ret.shards)} bad={ret.bad}")
                step.output.append(RetrievedPayload(
                    root=root, proposer=ret.proposer, payload=None,
                    total_len=ret.total_len, shards_bad=ret.bad,
                    rounds=ret.rounds, t_ordered=ret.t_ordered))
                continue
            self.retries += 1
            ret.deadline = now + self.retry_s * (ret.rounds + 1)
            step.extend(self._request_step(root, ret))
        return step.extend(self._activate(now))

    # -- donor side ----------------------------------------------------------

    def handle_retrieve(self, peer: NodeId, msg: VidRetrieve, now: float
                        ) -> Step:
        entry = self.store.proof_for(msg.root)
        if entry is None:
            # never dispersed to us (or long evicted): refuse LOUDLY —
            # counted + noted, never a fault (a fast peer's early retrieve
            # is honest; it retries once our dispersal lands)
            self.refusals += 1
            self._note("vid_refusal",
                       f"peer={peer!r} root={msg.root.hex()[:24]}")
            return Step()
        total_len, proof = entry
        if not self._quota_ok(peer, len(proof.value), now):
            self.quota_drops += 1
            self._note("vid_quota",
                       f"peer={peer!r} root={msg.root.hex()[:24]} "
                       f"bytes={len(proof.value)}")
            return Step()
        self.served += 1
        return Step().send_to(
            peer, VidShard(msg.root, total_len, proof))

    def _quota_ok(self, peer: NodeId, nbytes: int, now: float) -> bool:
        if self.serve_bytes_per_s <= 0:
            return True
        tokens, last = self._quota.get(
            peer, (self.serve_burst_bytes, now))
        tokens = min(self.serve_burst_bytes,
                     tokens + (now - last) * self.serve_bytes_per_s)
        if nbytes > tokens:
            self._quota[peer] = (tokens, now)
            return False
        self._quota[peer] = (tokens - nbytes, now)
        return True

"""Per-peer ingress worker threads: framing + decode off the event loop.

The transport's steady-state receive path (:class:`_NodeRecvProtocol` in
:mod:`hbbft_tpu.net.transport`) normally decodes frames inline on the
event loop.  With ``ingress_workers`` enabled, each authenticated node
connection instead hands its raw socket chunks to a dedicated
:class:`PeerIngressWorker` thread which runs the CPU-bearing slice —
frame parsing (:class:`~hbbft_tpu.net.framing.FrameDecoder`), MSG_BATCH
splitting, and the ``wire.decode_message`` memo — and delivers whole
decoded batches back to the loop as ``(payload, msg_or_None)`` pairs via
``call_soon_threadsafe``.

Serialization contract: ONE worker thread per peer, feeding the loop
through ``call_soon_threadsafe`` (FIFO from a single thread), so a
peer's batches arrive at the pump strictly in socket order — ledgers
stay byte-identical with the inline path.  IngressBudget semantics are
intact: byte-rate charging and flow control stay on the event loop (the
protocol still charges per chunk and pauses reading); the worker calls
the lock-protected ``frame_admitted`` itself before delivery, and
decode failures are delivered as ``(payload, None)`` so the runtime
re-decodes and attributes the strike to THIS peer, exactly as inline.

Bounded queue: the hand-off deque is bounded in BYTES — once the
backlog passes :data:`WORKER_BACKLOG_BYTES`, the protocol pauses the
socket (real TCP backpressure) until the worker drains, so a slow
worker can never buffer unboundedly.

Faults: a framing error, an unknown frame kind, or a bad heartbeat
session id discovered on the worker thread is marshalled back to the
loop and fails the connection through the protocol's ``_fail`` — the
same counted drop path the inline decoder takes.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Deque, Dict, Hashable, List, Optional, Tuple

from hbbft_tpu.net import framing
from hbbft_tpu.net.framing import FrameDecoder, FrameError
from hbbft_tpu.protocols import wire

NodeId = Hashable

#: pause the socket once the worker's undecoded backlog passes this many
#: bytes (resume is polled by the protocol's throttle timer)
WORKER_BACKLOG_BYTES = 1 << 20

#: decode-memo bound, mirroring NodeRuntime._decode_cache: identical
#: payloads (echoed broadcasts) decode once; cleared wholesale at cap
DECODE_MEMO_CAP = 4096


class PeerIngressWorker:
    """One ingress worker thread for one authenticated peer connection.

    Lifecycle: constructed by the transport when the connection upgrades
    to the protocol path, ``bind()``-ed to the protocol (for the failure
    back-channel), started lazily on the first ``feed``, and ``stop``-ed
    from ``connection_lost``.  The thread drains any queued chunks after
    stop is signalled, then exits (daemon — a hung delivery cannot block
    interpreter shutdown).
    """

    def __init__(self, t: Any, peer_id: NodeId, writer: Any,
                 session: Optional[bytes]):
        self.t = t
        self.peer_id = peer_id
        self.writer = writer
        self.session = session
        self.loop = None  # set by bind() (the protocol's loop)
        self.proto = None
        self.decoder = FrameDecoder(t.max_frame)
        self._memo: Dict[bytes, Any] = {}
        self._chunks: Deque[bytes] = deque()
        self._lock = threading.Lock()
        self._queued_bytes = 0
        self._wake = threading.Event()
        self._stopped = False
        self._failed = False
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"hbbft-ingress-{peer_id!r}")
        self._started = False

    # -- event-loop surface --------------------------------------------------

    def bind(self, proto: Any) -> None:
        self.proto = proto
        self.loop = proto.loop

    def feed(self, data: bytes) -> None:
        """Queue one raw socket chunk (event-loop side; the caller
        checks :meth:`backlog_over` and pauses the socket — that check
        is what bounds this queue)."""
        with self._lock:
            self._chunks.append(data)
            self._queued_bytes += len(data)
        if not self._started:
            self._started = True
            self._thread.start()
        self._wake.set()

    def backlog_over(self) -> bool:
        with self._lock:
            return self._queued_bytes > WORKER_BACKLOG_BYTES

    def stop(self) -> None:
        self._stopped = True
        self._wake.set()

    # -- worker thread -------------------------------------------------------

    def _run(self) -> None:
        while True:
            self._wake.wait()
            self._wake.clear()
            while True:
                with self._lock:
                    if not self._chunks:
                        break
                    data = self._chunks.popleft()
                    self._queued_bytes -= len(data)
                if self._failed:
                    continue  # drain and discard after a failure
                try:
                    self._process(data)
                # nothing swallowed: the failure is marshalled to the
                # loop, where proto._fail kills the connection through
                # the same counted drop path the inline decoder takes
                # (chunks queued behind the poison frame die with the
                # socket exactly as on the inline path)
                # hblint: disable=fault-swallowed-drop
                except (FrameError, ValueError) as exc:
                    self._failed = True
                    self.loop.call_soon_threadsafe(self.proto._fail, exc)
            if self._stopped:
                return

    def _decode(self, payload: bytes) -> Tuple[bytes, Any]:
        memo = self._memo
        msg = memo.get(payload)
        if msg is None:
            try:
                msg = wire.decode_message(payload)
            # nothing dropped here: the raw payload is handed through as
            # (payload, None) and the runtime re-decodes, fails
            # identically, and charges the strike to this peer —
            # attribution preserved
            # hblint: disable=fault-swallowed-drop
            except ValueError:
                return (payload, None)
            if len(memo) >= DECODE_MEMO_CAP:
                memo.clear()
            memo[payload] = msg
        return (payload, msg)

    def _process(self, data: bytes) -> None:
        t = self.t
        batch: List[Tuple[bytes, Any]] = []
        nbytes = 0
        frames = self.decoder.feed(data)
        for kind, payload in frames:
            nbytes += len(payload) + 5
            if kind == framing.MSG:
                batch.append(self._decode(payload))
            elif kind == framing.MSG_BATCH:
                for sub in framing.split_msgs(payload):
                    batch.append(self._decode(sub))
            elif kind == framing.PING:
                if self.session is not None and (
                        len(payload) != framing.SESSION_LEN + 8
                        or payload[:framing.SESSION_LEN] != self.session):
                    raise FrameError(
                        f"heartbeat with wrong session id on "
                        f"authenticated stream from {self.peer_id!r}"
                    )
                self.loop.call_soon_threadsafe(self._pong, payload)
            else:
                raise FrameError(
                    f"unexpected frame kind {kind} from node "
                    f"{self.peer_id!r}"
                )
        if batch:
            # admitted BEFORE delivery so the in-flight window the
            # event loop polls already covers these frames
            t.ingress.frame_admitted(self.peer_id, len(batch))
        if frames:
            self.loop.call_soon_threadsafe(
                self._deliver, batch, len(frames), nbytes)

    # -- loop-side delivery callbacks ----------------------------------------

    def _pong(self, payload: bytes) -> None:
        if self.writer.is_closing():
            return
        pong = framing.encode_frame(framing.PONG, payload,
                                    self.t.max_frame)
        self.writer.write(pong)
        self.t._record_send(self.peer_id, pong)

    def _deliver(self, batch: List[Tuple[bytes, Any]], nframes: int,
                 nbytes: int) -> None:
        """Runs on the event loop, in feed order (single scheduling
        thread): stats stay single-threaded and batches reach the pump
        strictly serialized per peer."""
        t = self.t
        if t.trace is not None or t.cost_model is not None:
            # per-frame granularity is lost off-loop; charge the chunk
            # as one aggregate recv event for the cost model
            t.stats.frame_recv_batch(nframes, nbytes)
            if t.cost_model is not None:
                t.stats.virtual_cost_s += t.cost_model.charge(nbytes)
        else:
            t.stats.frame_recv_batch(nframes, nbytes)
        if batch and t.on_peer_batch is not None:
            t.on_peer_batch(self.peer_id, batch)

"""Client-facing pieces: the node-side mempool and the contribute frontend.

:class:`Mempool` is the node's admission gate — bounded and dedup'd, so a
client flood turns into ``ACK_FULL`` backpressure instead of unbounded
QueueingHoneyBadger queues, and a replayed transaction (pending *or*
recently committed) is acknowledged without being re-proposed.

:class:`ClusterClient` is the load-generator side: it dials a node, submits
raw transaction bytes, honours backpressure (FULL acks retry with capped
exponential delay), and records submit→commit latency per transaction — the
end-to-end number "The Latency Price of Threshold Cryptosystems" says is
the one that matters.
"""

from __future__ import annotations

import asyncio
import contextlib
import hashlib
import json
import struct
import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Tuple

from hbbft_tpu.net import framing, transport
from hbbft_tpu.net.framing import (
    DEFAULT_MAX_FRAME,
    FrameDecoder,
    Hello,
)


class TxShedError(Exception):
    """A previously-ACCEPTED transaction was shed by the node's
    fair-admission guard and will never commit.  Raised promptly from
    ``wait_committed`` (instead of a blind timeout) when the node
    pushes the ``ACK_SHED`` notification; re-submission is the
    caller's policy (the dedup window makes it cheap)."""

    def __init__(self, digest: bytes):
        super().__init__(f"tx {digest.hex()[:16]} shed by the mempool "
                         f"fair-admission guard; re-submit if wanted")
        self.digest = digest


def tx_digest(tx: bytes) -> bytes:
    return hashlib.sha3_256(tx).digest()


def percentile(vals: List[float], p: float) -> float:
    """Nearest-rank percentile of a pre-sorted sequence — the one
    definition every latency summary in the repo shares (client
    submit→commit, bench phase breakdowns)."""
    return vals[min(len(vals) - 1, int(p * (len(vals) - 1) + 0.5))]


def latency_percentiles(latencies) -> Dict[str, float]:
    """p50/p90/p99/max summary of a sequence of latency seconds."""
    vals = sorted(latencies)
    if not vals:
        return {}
    return {
        "p50_s": percentile(vals, 0.50), "p90_s": percentile(vals, 0.90),
        "p99_s": percentile(vals, 0.99),
        "max_s": vals[-1], "count": len(vals),
    }


class Mempool:
    """Bounded, dedup'd FIFO of not-yet-committed transactions.

    ``max_tx_bytes`` bounds a single transaction at admission: a proposed
    contribution is roughly ``batch_size · max_tx_bytes`` and must stay
    well under ``wire.MAX_BLOB_BYTES`` (8 MiB) or its RBC shard messages
    would be undeliverable — reject at the door, not mid-broadcast.  The
    256 KiB default leaves a 4× margin at the default batch size of 8.

    **Fair admission under FULL pressure** (overload defense): admission
    is tracked per client id.  When the pool is full and the submitting
    client holds LESS than its fair share (``capacity // active
    clients``), the pool *sheds* the oldest pending transaction of the
    most-over-share client — counted per shed client
    (``hbbft_guard_mempool_sheds_total``) — and admits the newcomer,
    instead of letting whichever client filled the pool first starve
    everyone else.  At most ONE victim is shed per admission, and only
    when that single shed actually makes the newcomer fit.  A shed
    transaction was already acked ``ACCEPTED``; the runtime's
    ``on_shed`` hook pulls it back out of the protocol queue and
    pushes ``ACK_SHED`` to the clients, so a pending
    ``wait_committed`` fails fast with :class:`TxShedError` instead of
    riding out its timeout — re-submission is the caller's policy (the
    dedup window makes it cheap).  Clients that stay under their share
    are never shed, and the share divisor is clamped
    (``fair_clients_max``) so a swarm of self-declared sybil client
    ids cannot grind an honest bulk client's allocation toward zero.
    """

    ACCEPTED = framing.ACK_ACCEPTED
    DUPLICATE = framing.ACK_DUPLICATE
    FULL = framing.ACK_FULL
    REJECTED = framing.ACK_REJECTED

    _ACK_NAMES = {
        framing.ACK_ACCEPTED: "accepted",
        framing.ACK_DUPLICATE: "duplicate",
        framing.ACK_FULL: "full",
        framing.ACK_REJECTED: "rejected",
    }

    def __init__(self, capacity: int = 10_000, seen_cap: int = 100_000,
                 max_tx_bytes: int = 256 * 1024,
                 max_pending_bytes: int = 64 * 2**20,
                 registry=None):
        self.capacity = capacity
        self.seen_cap = seen_cap
        self.max_tx_bytes = max_tx_bytes
        # byte budget alongside the entry count: 10k max-size txs would
        # otherwise admit ~2.5 GiB before FULL fires
        self.max_pending_bytes = max_pending_bytes
        self.pending_bytes = 0
        self._pending: "OrderedDict[bytes, bytes]" = OrderedDict()  # digest→tx
        self._seen: "OrderedDict[bytes, None]" = OrderedDict()  # recent commits
        # fair-admission bookkeeping: who owns each pending digest, how
        # many each client holds, and each client's digests in FIFO
        # order (the shed victim is the hog's OLDEST pending tx)
        self._owners: Dict[bytes, str] = {}
        self._client_counts: Dict[str, int] = {}
        self._client_bytes: Dict[str, int] = {}
        self._client_fifo: Dict[str, List[bytes]] = {}
        self._fifo_stale: Dict[str, int] = {}
        # per-victim shed tallies, key-capped like the metric registry
        # (attacker-minted client ids must not grow this dict or the
        # /status payload without bound)
        self.sheds: Dict[str, int] = {}
        self._sheds_key_cap = 32
        # fair-share floor against sybil client ids: client identities
        # are self-declared, so the share divisor is clamped — a swarm
        # of minted ids can displace an honest bulk client down to
        # capacity/fair_clients_max pending txs, never to zero
        self.fair_clients_max = 32
        # a shed tx was already handed to the consensus layer at
        # admission; the owner (NodeRuntime) hooks this to pull it back
        # out of the protocol queue so shedding really sheds —
        # otherwise every shed would grow the protocol queue past the
        # mempool's ceiling
        self.on_shed: Optional[Callable[[bytes], None]] = None
        # admission (event loop) and commit pruning (the runtime's pump
        # worker) run on different threads since the pipelined scheduler;
        # the compound size/byte-budget invariants need this lock
        self._lock = threading.Lock()
        self._acks = None
        self._sheds = None
        if registry is not None:
            self.bind_registry(registry)

    def bind_registry(self, registry) -> None:
        """Attach admission metrics to a node's registry (the runtime does
        this, so a caller-supplied mempool is counted too); gauges for
        depth and byte budget are registered via the collect callback."""
        self._acks = registry.counter(
            "hbbft_node_mempool_acks_total",
            "client/local tx admissions by outcome",
            labelnames=("status",), max_label_sets=len(self._ACK_NAMES) + 1,
        )
        for name in self._ACK_NAMES.values():
            self._acks.labels(status=name)
        self._sheds = registry.counter(
            "hbbft_guard_mempool_sheds_total",
            "pending transactions shed under FULL pressure to admit an "
            "under-share client's tx, labeled by the SHED client",
            labelnames=("client",), max_label_sets=33)
        g_pending = registry.gauge(
            "hbbft_node_mempool_pending", "not-yet-committed transactions")
        g_bytes = registry.gauge(
            "hbbft_node_mempool_pending_bytes",
            "bytes held by pending transactions")
        g_clients = registry.gauge(
            "hbbft_guard_mempool_clients",
            "distinct clients with pending transactions")
        registry.register_callback(lambda: (
            g_pending.set(len(self._pending)),
            g_bytes.set(self.pending_bytes),
            g_clients.set(len(self._client_counts)),
        ))

    def _count(self, status: int) -> int:
        if self._acks is not None:
            self._acks.labels(status=self._ACK_NAMES[status]).inc()
        return status

    def add(self, tx: bytes, client_id: str = "_anon") -> int:
        if len(tx) > self.max_tx_bytes:
            return self._count(self.REJECTED)
        digest = tx_digest(tx)
        shed_tx: Optional[bytes] = None
        try:
            with self._lock:
                if digest in self._pending or digest in self._seen:
                    return self._count(self.DUPLICATE)
                if (len(self._pending) >= self.capacity
                        or self.pending_bytes + len(tx)
                        > self.max_pending_bytes):
                    # at most ONE victim per admission, and only when
                    # that single shed actually makes the newcomer fit
                    # — never destroy acked state for a FULL anyway
                    shed_tx = self._shed_for(client_id, len(tx))
                    if shed_tx is None:
                        return self._count(self.FULL)
                self._admit(digest, tx, client_id)
            return self._count(self.ACCEPTED)
        finally:
            # outside the lock: the hook re-enters the runtime (pump
            # enqueue)
            if shed_tx is not None and self.on_shed is not None:
                self.on_shed(shed_tx)

    def _admit(self, digest: bytes, tx: bytes, client_id: str) -> None:
        self._pending[digest] = tx
        self.pending_bytes += len(tx)
        self._owners[digest] = client_id
        self._client_counts[client_id] = (
            self._client_counts.get(client_id, 0) + 1
        )
        self._client_bytes[client_id] = (
            self._client_bytes.get(client_id, 0) + len(tx)
        )
        self._client_fifo.setdefault(client_id, []).append(digest)

    def _shed_for(self, client_id: str,
                  need_bytes: int) -> Optional[bytes]:
        """Shed ONE pending tx to make room for ``client_id`` — only if
        the submitter is UNDER its fair share, some other client is
        over it, and removing that single victim actually admits a
        ``need_bytes`` newcomer (feasibility first: acked state is
        never destroyed for a FULL anyway).  Returns the shed tx bytes
        (for the ``on_shed`` hook) or None.  Caller holds the lock."""
        # the submitter counts as active even before its first
        # admission — that is exactly the starvation case.  The divisor
        # is clamped (`fair_clients_max`): client ids are self-declared,
        # and an unclamped share would let a sybil swarm grind an
        # honest bulk client's allocation toward zero.  Pressure is the
        # worse of the COUNT share and the BYTE share — a client that
        # filled max_pending_bytes with a few huge txs is just as much
        # over its share as one that filled the entry count.
        active = len(self._client_counts) + (
            0 if client_id in self._client_counts else 1)
        denom = max(1, min(active, self.fair_clients_max))
        count_share = max(1, self.capacity // denom)
        byte_share = max(1, self.max_pending_bytes // denom)

        def pressure(c: str) -> float:
            return max(
                self._client_counts.get(c, 0) / count_share,
                self._client_bytes.get(c, 0) / byte_share,
            )

        if pressure(client_id) >= 1.0:
            return None
        victim = max(self._client_counts,
                     key=lambda c: (pressure(c), c), default=None)
        if (victim is None or victim == client_id
                or pressure(victim) <= 1.0):
            return None
        fifo = self._client_fifo.get(victim, [])
        while fifo:
            digest = fifo[0]
            dropped = self._pending.get(digest)
            if dropped is None:
                fifo.pop(0)
                continue  # already committed; stale fifo entry
            if (len(self._pending) - 1 >= self.capacity
                    or self.pending_bytes - len(dropped) + need_bytes
                    > self.max_pending_bytes):
                return None  # one shed would not admit the newcomer
            fifo.pop(0)
            del self._pending[digest]
            self.pending_bytes -= len(dropped)
            self._forget_owner(digest, len(dropped))
            key = victim
            if (key not in self.sheds
                    and len(self.sheds) >= self._sheds_key_cap):
                key = "_overflow_"        # bounded like the registry
            self.sheds[key] = self.sheds.get(key, 0) + 1
            if self._sheds is not None:
                self._sheds.labels(client=victim).inc()
            return dropped
        return None

    def _forget_owner(self, digest: bytes, nbytes: int) -> None:
        owner = self._owners.pop(digest, None)
        if owner is None:
            return
        left = self._client_counts.get(owner, 0) - 1
        if left > 0:
            self._client_counts[owner] = left
            self._client_bytes[owner] = max(
                0, self._client_bytes.get(owner, 0) - nbytes)
            # committed digests go stale in the owner's FIFO (removing
            # them eagerly would be O(n) per commit); compact once the
            # stale fraction dominates so the list itself stays bounded
            stale = self._fifo_stale.get(owner, 0) + 1
            fifo = self._client_fifo.get(owner)
            if fifo is not None and stale * 2 > len(fifo):
                fifo[:] = [d for d in fifo if d in self._pending]
                stale = 0
            self._fifo_stale[owner] = stale
        else:
            self._client_counts.pop(owner, None)
            self._client_bytes.pop(owner, None)
            self._client_fifo.pop(owner, None)
            self._fifo_stale.pop(owner, None)

    def mark_committed_digests(self, digests) -> int:
        """Complete pending txs by DIGEST — a relay tier (the gateway)
        forwarding a node's ``TX_COMMIT`` only sees digests, never the
        tx bytes.  Drops matching pending entries, records every digest
        in the dedup window (a re-submission of a committed tx answers
        DUPLICATE, same as :meth:`mark_committed`), and returns how many
        pending entries were actually dropped."""
        n = 0
        with self._lock:
            for digest in digests:
                dropped = self._pending.pop(digest, None)
                if dropped is not None:
                    self.pending_bytes -= len(dropped)
                    self._forget_owner(digest, len(dropped))
                    n += 1
                self._seen[digest] = None
            while len(self._seen) > self.seen_cap:
                self._seen.popitem(last=False)
        return n

    def has_pending(self, digest: bytes) -> bool:
        """Is this digest still awaiting commit here?  (Relay tiers use
        this to skip forwarding entries that were shed or completed
        between enqueue and flush.)"""
        with self._lock:
            return digest in self._pending

    def mark_committed(self, txs) -> List[bytes]:
        """Drop committed txs from pending; returns their digests."""
        digests = []
        with self._lock:
            for tx in txs:
                digest = tx_digest(tx)
                digests.append(digest)
                dropped = self._pending.pop(digest, None)
                if dropped is not None:
                    self.pending_bytes -= len(dropped)
                    self._forget_owner(digest, len(dropped))
                self._seen[digest] = None
            while len(self._seen) > self.seen_cap:
                self._seen.popitem(last=False)
        return digests

    def __len__(self) -> int:
        return len(self._pending)


class ClusterClient:
    """Asyncio frontend for submitting transactions to one node."""

    def __init__(self, addr: Tuple[str, int], cluster_id: bytes,
                 client_id: str = "client",
                 max_frame: int = DEFAULT_MAX_FRAME,
                 connect_timeout_s: float = 5.0,
                 keepalive_s: float = 10.0,
                 trace_dir: Optional[str] = None):
        self.addr = addr
        self.cluster_id = bytes(cluster_id)
        self.client_id = client_id
        self.max_frame = max_frame
        self.connect_timeout_s = connect_timeout_s
        # periodic PINGs keep an idle client (e.g. one parked in
        # wait_committed) from tripping the node's inbound read deadline
        self.keepalive_s = keepalive_s
        self.node_hello: Optional[Hello] = None
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        # concurrent submit()/status() coroutines must not await
        # writer.drain() simultaneously (asyncio's _drain_helper assert)
        self._wlock = asyncio.Lock()
        self._reader_task: Optional[asyncio.Task] = None
        self._keepalive_task: Optional[asyncio.Task] = None
        # per-digest FIFO waiter lists, like _commits: a duplicate digest
        # in one batch (or a submit racing submit_many) must not clobber
        # an earlier future — each TX frame written earns one ack, and
        # acks resolve waiters in submission order
        self._acks: Dict[bytes, List[asyncio.Future]] = {}
        # one future PER WAITER (asyncio.wait_for cancels the future it
        # wraps, so sharing one would let a timed-out waiter break the
        # others and leave a dead future pinned under the digest)
        self._commits: Dict[bytes, List[asyncio.Future]] = {}
        self._status_waiters: List[asyncio.Future] = []
        self._submit_times: Dict[bytes, float] = {}
        # commits already seen for OUR txs (bounded), so a wait_committed
        # issued after the TX_COMMIT frame still resolves; foreign digests
        # (other clients' txs, which nodes broadcast to everyone) are not
        # retained at all
        self._committed: "OrderedDict[bytes, float]" = OrderedDict()
        self._committed_cap = 65_536
        self._dead: Optional[Exception] = None
        # (digest_hex, submit→commit seconds), in commit order
        self.latencies: List[Tuple[str, float]] = []
        # per-tx causal tracing (obs.trace / obs.critpath): journal the
        # client-side stages — submit (TX frame written), ack (the
        # node's admission reply) and commit_seen (TX_COMMIT arrived) —
        # with wall-clock timestamps; obs.critpath pairs them with the
        # node journals to bound the client↔node clock offset
        self._trace_rec = None
        if trace_dir:
            from hbbft_tpu.obs.flight import FlightRecorder

            self._trace_rec = FlightRecorder(
                trace_dir, node=client_id, flavor="client",
                clock=time.time)

    # -- lifecycle -----------------------------------------------------------

    async def connect(self) -> Hello:
        reader, writer, node_hello = await framing.client_hello_handshake(
            self.addr, self.cluster_id, self.client_id,
            timeout_s=self.connect_timeout_s, max_frame=self.max_frame,
        )
        transport.set_nodelay(writer)
        self._reader, self._writer = reader, writer
        self.node_hello = node_hello
        loop = asyncio.get_running_loop()
        self._reader_task = loop.create_task(
            self._recv_loop(), name=f"client-{self.client_id}"
        )
        self._keepalive_task = loop.create_task(
            self._keepalive_loop(), name=f"client-ka-{self.client_id}"
        )
        return self.node_hello

    async def close(self) -> None:
        for task in (self._reader_task, self._keepalive_task):
            if task is not None:
                task.cancel()
                # suppress: awaiting our own cancelled tasks; a late recv
                # error already failed all waiters via _fail_waiters
                with contextlib.suppress(asyncio.CancelledError, Exception):
                    await task
        if self._writer is not None:
            self._writer.close()
        if self._trace_rec is not None:
            self._trace_rec.close()

    def _trace(self, stage: str, tids: bytes, era: int = 0,
               epoch: int = (1 << 64) - 1) -> None:
        """Journal one client-side trace stage (no-op without
        ``trace_dir``); default (era, epoch) is the unknown-epoch
        sentinel — the client learns the committing epoch only from
        the TX_COMMIT frame."""
        if self._trace_rec is not None and tids:
            self._trace_rec.record_trace(stage, era, epoch, tids,
                                         detail=self.client_id)

    # -- submitting ----------------------------------------------------------

    async def submit(self, tx: bytes, *, retry_full: bool = True,
                     max_retries: int = 50,
                     ack_timeout_s: float = 10.0) -> int:
        """Submit ``tx``; waits for the node's ack.  ``ACK_FULL`` retries
        with capped exponential delay (backpressure) unless ``retry_full``
        is off.  Returns the final ack status."""
        digest = tx_digest(tx)
        delay = 0.02
        status = framing.ACK_FULL
        try:
            for _attempt in range(max_retries):
                self._check_alive()
                fut = asyncio.get_running_loop().create_future()
                self._acks.setdefault(digest, []).append(fut)
                self._submit_times.setdefault(digest, time.monotonic())
                self._trace("submit", digest[:16])
                async with self._wlock:
                    self._writer.write(framing.encode_frame(
                        framing.TX, tx, self.max_frame
                    ))
                    await self._writer.drain()
                try:
                    status = await asyncio.wait_for(fut, ack_timeout_s)
                finally:
                    self._drop_ack_waiter(digest, fut)
                if status != framing.ACK_FULL or not retry_full:
                    return status
                await asyncio.sleep(delay)
                delay = min(delay * 2, 1.0)
            return status
        finally:
            # a tx that will never commit must not pin a submit-time entry
            # forever: rejected/full outcomes are final, and a duplicate of
            # an already-seen commit resolves from the bounded record
            if status in (framing.ACK_REJECTED, framing.ACK_FULL) or (
                status == framing.ACK_DUPLICATE and digest in self._committed
            ):
                self._submit_times.pop(digest, None)

    async def submit_many(self, txs, *, ack_timeout_s: float = 30.0) -> list:
        """Submit a batch of transactions with ONE socket write and one
        shared ack wait — the load-generator fast path (a per-tx
        ``submit()`` loop costs a lock round + drain + timer per
        transaction, which on a small host is a measurable share of the
        cluster's CPU).  No FULL-retry logic: callers that batch are
        expected to size waves under the mempool bound.  Returns the ack
        status list, index-aligned with ``txs``."""
        self._check_alive()
        loop = asyncio.get_running_loop()
        futs = []
        buf = bytearray()
        for tx in txs:
            digest = tx_digest(tx)
            fut = loop.create_future()
            self._acks.setdefault(digest, []).append(fut)
            futs.append((digest, fut))
            self._submit_times.setdefault(digest, time.monotonic())
            buf += framing.encode_frame(framing.TX, tx, self.max_frame)
        # one packed trace record for the whole wave (one record per
        # batch, not per tx — same shape as the node's commit records)
        self._trace("submit", b"".join(d[:16] for d, _f in futs))
        async with self._wlock:
            self._writer.write(bytes(buf))
            await self._writer.drain()
        try:
            return list(await asyncio.wait_for(
                asyncio.gather(*(f for _d, f in futs)), ack_timeout_s
            ))
        finally:
            for digest, fut in futs:
                self._drop_ack_waiter(digest, fut)

    async def wait_committed(self, tx: bytes, timeout_s: float = 60.0) -> float:
        """Block until the node reports ``tx`` committed; returns the
        submit→commit latency in seconds."""
        digest = tx_digest(tx)
        done = self._committed.get(digest)
        if done is not None:
            return done
        self._check_alive()
        fut = asyncio.get_running_loop().create_future()
        waiters = self._commits.setdefault(digest, [])
        waiters.append(fut)
        try:
            return await asyncio.wait_for(fut, timeout_s)
        finally:
            # a timed-out waiter must not pin its (cancelled) future
            if fut in waiters:
                waiters.remove(fut)
            if not waiters:
                self._commits.pop(digest, None)

    async def wait_committed_many(self, txs, timeout_s: float = 60.0) -> list:
        """Latencies for a batch of transactions with one shared timeout
        (a ``wait_committed`` per tx costs a timer handle + future wrap
        each).  Returns latency seconds, index-aligned with ``txs``."""
        loop = asyncio.get_running_loop()
        futs = []
        waiter_refs = []
        for tx in txs:
            digest = tx_digest(tx)
            done = self._committed.get(digest)
            if done is not None:
                fut = loop.create_future()
                fut.set_result(done)
                futs.append(fut)
                continue
            self._check_alive()
            fut = loop.create_future()
            waiters = self._commits.setdefault(digest, [])
            waiters.append(fut)
            waiter_refs.append((digest, waiters, fut))
            futs.append(fut)
        try:
            return list(await asyncio.wait_for(
                asyncio.gather(*futs), timeout_s
            ))
        finally:
            for digest, waiters, fut in waiter_refs:
                if fut in waiters:
                    waiters.remove(fut)
                if not waiters:
                    self._commits.pop(digest, None)

    async def status(self, timeout_s: float = 10.0,
                     chain_tail: Optional[int] = None) -> dict:
        """Fetch the node's status document.  ``chain_tail`` limits the
        digest-chain tail in the reply (0 = head/length only — the cheap
        form for poll loops; None = the node's full default)."""
        self._check_alive()
        fut = asyncio.get_running_loop().create_future()
        self._status_waiters.append(fut)
        payload = b"" if chain_tail is None else struct.pack(
            ">I", chain_tail)
        async with self._wlock:
            self._writer.write(framing.encode_frame(
                framing.STATUS_REQ, payload, self.max_frame
            ))
            await self._writer.drain()
        return await asyncio.wait_for(fut, timeout_s)

    # -- stats ---------------------------------------------------------------

    def latency_percentiles(self) -> Dict[str, float]:
        return latency_percentiles(lat for _d, lat in self.latencies)

    # -- internals -----------------------------------------------------------

    async def _keepalive_loop(self) -> None:
        nonce = 0
        while self._dead is None:
            await asyncio.sleep(self.keepalive_s)
            nonce += 1
            try:
                async with self._wlock:
                    self._writer.write(framing.encode_frame(
                        framing.PING, struct.pack(">Q", nonce),
                        self.max_frame,
                    ))
                    await self._writer.drain()
            # hblint: disable=fault-swallowed-drop (nothing to account
            # client-side: the recv loop fails every pending waiter with
            # the connection error; this loop just stops pinging)
            except (ConnectionError, OSError):
                return

    async def _recv_loop(self) -> None:
        decoder = FrameDecoder(self.max_frame)
        try:
            while True:
                data = await self._reader.read(65536)
                if not data:
                    raise ConnectionError("node closed connection")
                for kind, payload in decoder.feed(data):
                    self._on_frame(kind, payload)
        except asyncio.CancelledError:
            raise
        except Exception as exc:
            # a dead reader must surface NOW on every pending future —
            # not as N× full submit/commit timeouts later
            self._fail_waiters(
                exc if isinstance(exc, ConnectionError)
                else ConnectionError(f"client receive loop died: {exc!r}")
            )

    def _drop_ack_waiter(self, digest: bytes, fut: asyncio.Future) -> None:
        waiters = self._acks.get(digest)
        if waiters is not None:
            with contextlib.suppress(ValueError):
                waiters.remove(fut)
            if not waiters:
                del self._acks[digest]

    def _on_frame(self, kind: int, payload: bytes) -> None:
        if kind == framing.TX_ACK:
            status, digest = payload[0], payload[1:33]
            if status == framing.ACK_SHED:
                # push notification, not a reply to a written TX frame:
                # fail the commit waiters NOW instead of letting them
                # ride out the full timeout on a tx that can never land
                self._submit_times.pop(digest, None)
                for fut in self._commits.pop(digest, ()) or ():
                    if not fut.done():
                        fut.set_exception(TxShedError(digest))
                return
            waiters = self._acks.get(digest)
            if waiters:
                fut = waiters.pop(0)  # one ack per written TX frame: FIFO
                if not waiters:
                    del self._acks[digest]
                if not fut.done():
                    fut.set_result(status)
                if status == framing.ACK_ACCEPTED:
                    self._trace("ack", digest[:16])
        elif kind == framing.TX_COMMIT:
            # u64 era + u64 epoch + u32 count + count × 32-byte digests;
            # nodes broadcast every committed digest to every client, so
            # only digests we submitted or are awaiting are retained
            era, epoch, count = struct.unpack_from(">QQI", payload, 0)
            now = time.monotonic()
            seen_tids = []
            for i in range(count):
                digest = payload[20 + 32 * i : 52 + 32 * i]
                t0 = self._submit_times.pop(digest, None)
                waiters = self._commits.pop(digest, None)
                if t0 is None and waiters is None:
                    continue  # someone else's transaction
                seen_tids.append(digest[:16])
                lat = now - t0 if t0 is not None else 0.0
                if t0 is not None:
                    # hblint: disable=bounded-ingress (one entry per tx
                    # THIS client submitted — caller-controlled load-
                    # generator measurement data, not peer-driven growth)
                    self.latencies.append((digest.hex(), lat))
                self._committed[digest] = lat
                while len(self._committed) > self._committed_cap:
                    self._committed.popitem(last=False)
                for fut in waiters or ():
                    if not fut.done():
                        fut.set_result(lat)
            self._trace("commit_seen", b"".join(seen_tids), era, epoch)
        elif kind == framing.STATUS:
            doc = json.loads(payload.decode())
            waiters, self._status_waiters = self._status_waiters, []
            for fut in waiters:
                if not fut.done():
                    fut.set_result(doc)

    def _check_alive(self) -> None:
        if self._dead is not None:
            raise ConnectionError(
                f"connection to {self.addr} is dead: {self._dead}"
            )

    def _fail_waiters(self, exc: Exception) -> None:
        self._dead = exc
        commit_futs = [
            fut for waiters in self._commits.values() for fut in waiters
        ]
        ack_futs = [
            fut for waiters in self._acks.values() for fut in waiters
        ]
        for fut in (ack_futs + commit_futs + self._status_waiters):
            if not fut.done():
                fut.set_exception(exc)

"""Client-facing pieces: the node-side mempool and the contribute frontend.

:class:`Mempool` is the node's admission gate — bounded and dedup'd, so a
client flood turns into ``ACK_FULL`` backpressure instead of unbounded
QueueingHoneyBadger queues, and a replayed transaction (pending *or*
recently committed) is acknowledged without being re-proposed.

:class:`ClusterClient` is the load-generator side: it dials a node, submits
raw transaction bytes, honours backpressure (FULL acks retry with capped
exponential delay), and records submit→commit latency per transaction — the
end-to-end number "The Latency Price of Threshold Cryptosystems" says is
the one that matters.
"""

from __future__ import annotations

import asyncio
import contextlib
import hashlib
import json
import struct
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from hbbft_tpu.net import framing, transport
from hbbft_tpu.net.framing import (
    DEFAULT_MAX_FRAME,
    FrameDecoder,
    Hello,
)


def tx_digest(tx: bytes) -> bytes:
    return hashlib.sha3_256(tx).digest()


def percentile(vals: List[float], p: float) -> float:
    """Nearest-rank percentile of a pre-sorted sequence — the one
    definition every latency summary in the repo shares (client
    submit→commit, bench phase breakdowns)."""
    return vals[min(len(vals) - 1, int(p * (len(vals) - 1) + 0.5))]


def latency_percentiles(latencies) -> Dict[str, float]:
    """p50/p90/p99/max summary of a sequence of latency seconds."""
    vals = sorted(latencies)
    if not vals:
        return {}
    return {
        "p50_s": percentile(vals, 0.50), "p90_s": percentile(vals, 0.90),
        "p99_s": percentile(vals, 0.99),
        "max_s": vals[-1], "count": len(vals),
    }


class Mempool:
    """Bounded, dedup'd FIFO of not-yet-committed transactions.

    ``max_tx_bytes`` bounds a single transaction at admission: a proposed
    contribution is roughly ``batch_size · max_tx_bytes`` and must stay
    well under ``wire.MAX_BLOB_BYTES`` (8 MiB) or its RBC shard messages
    would be undeliverable — reject at the door, not mid-broadcast.  The
    256 KiB default leaves a 4× margin at the default batch size of 8.
    """

    ACCEPTED = framing.ACK_ACCEPTED
    DUPLICATE = framing.ACK_DUPLICATE
    FULL = framing.ACK_FULL
    REJECTED = framing.ACK_REJECTED

    _ACK_NAMES = {
        framing.ACK_ACCEPTED: "accepted",
        framing.ACK_DUPLICATE: "duplicate",
        framing.ACK_FULL: "full",
        framing.ACK_REJECTED: "rejected",
    }

    def __init__(self, capacity: int = 10_000, seen_cap: int = 100_000,
                 max_tx_bytes: int = 256 * 1024,
                 max_pending_bytes: int = 64 * 2**20,
                 registry=None):
        self.capacity = capacity
        self.seen_cap = seen_cap
        self.max_tx_bytes = max_tx_bytes
        # byte budget alongside the entry count: 10k max-size txs would
        # otherwise admit ~2.5 GiB before FULL fires
        self.max_pending_bytes = max_pending_bytes
        self.pending_bytes = 0
        self._pending: "OrderedDict[bytes, bytes]" = OrderedDict()  # digest→tx
        self._seen: "OrderedDict[bytes, None]" = OrderedDict()  # recent commits
        # admission (event loop) and commit pruning (the runtime's pump
        # worker) run on different threads since the pipelined scheduler;
        # the compound size/byte-budget invariants need this lock
        self._lock = threading.Lock()
        self._acks = None
        if registry is not None:
            self.bind_registry(registry)

    def bind_registry(self, registry) -> None:
        """Attach admission metrics to a node's registry (the runtime does
        this, so a caller-supplied mempool is counted too); gauges for
        depth and byte budget are registered via the collect callback."""
        self._acks = registry.counter(
            "hbbft_node_mempool_acks_total",
            "client/local tx admissions by outcome",
            labelnames=("status",), max_label_sets=len(self._ACK_NAMES) + 1,
        )
        for name in self._ACK_NAMES.values():
            self._acks.labels(status=name)
        g_pending = registry.gauge(
            "hbbft_node_mempool_pending", "not-yet-committed transactions")
        g_bytes = registry.gauge(
            "hbbft_node_mempool_pending_bytes",
            "bytes held by pending transactions")
        registry.register_callback(lambda: (
            g_pending.set(len(self._pending)),
            g_bytes.set(self.pending_bytes),
        ))

    def _count(self, status: int) -> int:
        if self._acks is not None:
            self._acks.labels(status=self._ACK_NAMES[status]).inc()
        return status

    def add(self, tx: bytes) -> int:
        if len(tx) > self.max_tx_bytes:
            return self._count(self.REJECTED)
        digest = tx_digest(tx)
        with self._lock:
            if digest in self._pending or digest in self._seen:
                return self._count(self.DUPLICATE)
            if (len(self._pending) >= self.capacity
                    or self.pending_bytes + len(tx) > self.max_pending_bytes):
                return self._count(self.FULL)
            self._pending[digest] = tx
            self.pending_bytes += len(tx)
        return self._count(self.ACCEPTED)

    def mark_committed(self, txs) -> List[bytes]:
        """Drop committed txs from pending; returns their digests."""
        digests = []
        with self._lock:
            for tx in txs:
                digest = tx_digest(tx)
                digests.append(digest)
                dropped = self._pending.pop(digest, None)
                if dropped is not None:
                    self.pending_bytes -= len(dropped)
                self._seen[digest] = None
            while len(self._seen) > self.seen_cap:
                self._seen.popitem(last=False)
        return digests

    def __len__(self) -> int:
        return len(self._pending)


class ClusterClient:
    """Asyncio frontend for submitting transactions to one node."""

    def __init__(self, addr: Tuple[str, int], cluster_id: bytes,
                 client_id: str = "client",
                 max_frame: int = DEFAULT_MAX_FRAME,
                 connect_timeout_s: float = 5.0,
                 keepalive_s: float = 10.0):
        self.addr = addr
        self.cluster_id = bytes(cluster_id)
        self.client_id = client_id
        self.max_frame = max_frame
        self.connect_timeout_s = connect_timeout_s
        # periodic PINGs keep an idle client (e.g. one parked in
        # wait_committed) from tripping the node's inbound read deadline
        self.keepalive_s = keepalive_s
        self.node_hello: Optional[Hello] = None
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        # concurrent submit()/status() coroutines must not await
        # writer.drain() simultaneously (asyncio's _drain_helper assert)
        self._wlock = asyncio.Lock()
        self._reader_task: Optional[asyncio.Task] = None
        self._keepalive_task: Optional[asyncio.Task] = None
        # per-digest FIFO waiter lists, like _commits: a duplicate digest
        # in one batch (or a submit racing submit_many) must not clobber
        # an earlier future — each TX frame written earns one ack, and
        # acks resolve waiters in submission order
        self._acks: Dict[bytes, List[asyncio.Future]] = {}
        # one future PER WAITER (asyncio.wait_for cancels the future it
        # wraps, so sharing one would let a timed-out waiter break the
        # others and leave a dead future pinned under the digest)
        self._commits: Dict[bytes, List[asyncio.Future]] = {}
        self._status_waiters: List[asyncio.Future] = []
        self._submit_times: Dict[bytes, float] = {}
        # commits already seen for OUR txs (bounded), so a wait_committed
        # issued after the TX_COMMIT frame still resolves; foreign digests
        # (other clients' txs, which nodes broadcast to everyone) are not
        # retained at all
        self._committed: "OrderedDict[bytes, float]" = OrderedDict()
        self._committed_cap = 65_536
        self._dead: Optional[Exception] = None
        # (digest_hex, submit→commit seconds), in commit order
        self.latencies: List[Tuple[str, float]] = []

    # -- lifecycle -----------------------------------------------------------

    async def connect(self) -> Hello:
        reader, writer, node_hello = await framing.client_hello_handshake(
            self.addr, self.cluster_id, self.client_id,
            timeout_s=self.connect_timeout_s, max_frame=self.max_frame,
        )
        transport.set_nodelay(writer)
        self._reader, self._writer = reader, writer
        self.node_hello = node_hello
        loop = asyncio.get_running_loop()
        self._reader_task = loop.create_task(
            self._recv_loop(), name=f"client-{self.client_id}"
        )
        self._keepalive_task = loop.create_task(
            self._keepalive_loop(), name=f"client-ka-{self.client_id}"
        )
        return self.node_hello

    async def close(self) -> None:
        for task in (self._reader_task, self._keepalive_task):
            if task is not None:
                task.cancel()
                # suppress: awaiting our own cancelled tasks; a late recv
                # error already failed all waiters via _fail_waiters
                with contextlib.suppress(asyncio.CancelledError, Exception):
                    await task
        if self._writer is not None:
            self._writer.close()

    # -- submitting ----------------------------------------------------------

    async def submit(self, tx: bytes, *, retry_full: bool = True,
                     max_retries: int = 50,
                     ack_timeout_s: float = 10.0) -> int:
        """Submit ``tx``; waits for the node's ack.  ``ACK_FULL`` retries
        with capped exponential delay (backpressure) unless ``retry_full``
        is off.  Returns the final ack status."""
        digest = tx_digest(tx)
        delay = 0.02
        status = framing.ACK_FULL
        try:
            for _attempt in range(max_retries):
                self._check_alive()
                fut = asyncio.get_running_loop().create_future()
                self._acks.setdefault(digest, []).append(fut)
                self._submit_times.setdefault(digest, time.monotonic())
                async with self._wlock:
                    self._writer.write(framing.encode_frame(
                        framing.TX, tx, self.max_frame
                    ))
                    await self._writer.drain()
                try:
                    status = await asyncio.wait_for(fut, ack_timeout_s)
                finally:
                    self._drop_ack_waiter(digest, fut)
                if status != framing.ACK_FULL or not retry_full:
                    return status
                await asyncio.sleep(delay)
                delay = min(delay * 2, 1.0)
            return status
        finally:
            # a tx that will never commit must not pin a submit-time entry
            # forever: rejected/full outcomes are final, and a duplicate of
            # an already-seen commit resolves from the bounded record
            if status in (framing.ACK_REJECTED, framing.ACK_FULL) or (
                status == framing.ACK_DUPLICATE and digest in self._committed
            ):
                self._submit_times.pop(digest, None)

    async def submit_many(self, txs, *, ack_timeout_s: float = 30.0) -> list:
        """Submit a batch of transactions with ONE socket write and one
        shared ack wait — the load-generator fast path (a per-tx
        ``submit()`` loop costs a lock round + drain + timer per
        transaction, which on a small host is a measurable share of the
        cluster's CPU).  No FULL-retry logic: callers that batch are
        expected to size waves under the mempool bound.  Returns the ack
        status list, index-aligned with ``txs``."""
        self._check_alive()
        loop = asyncio.get_running_loop()
        futs = []
        buf = bytearray()
        for tx in txs:
            digest = tx_digest(tx)
            fut = loop.create_future()
            self._acks.setdefault(digest, []).append(fut)
            futs.append((digest, fut))
            self._submit_times.setdefault(digest, time.monotonic())
            buf += framing.encode_frame(framing.TX, tx, self.max_frame)
        async with self._wlock:
            self._writer.write(bytes(buf))
            await self._writer.drain()
        try:
            return list(await asyncio.wait_for(
                asyncio.gather(*(f for _d, f in futs)), ack_timeout_s
            ))
        finally:
            for digest, fut in futs:
                self._drop_ack_waiter(digest, fut)

    async def wait_committed(self, tx: bytes, timeout_s: float = 60.0) -> float:
        """Block until the node reports ``tx`` committed; returns the
        submit→commit latency in seconds."""
        digest = tx_digest(tx)
        done = self._committed.get(digest)
        if done is not None:
            return done
        self._check_alive()
        fut = asyncio.get_running_loop().create_future()
        waiters = self._commits.setdefault(digest, [])
        waiters.append(fut)
        try:
            return await asyncio.wait_for(fut, timeout_s)
        finally:
            # a timed-out waiter must not pin its (cancelled) future
            if fut in waiters:
                waiters.remove(fut)
            if not waiters:
                self._commits.pop(digest, None)

    async def wait_committed_many(self, txs, timeout_s: float = 60.0) -> list:
        """Latencies for a batch of transactions with one shared timeout
        (a ``wait_committed`` per tx costs a timer handle + future wrap
        each).  Returns latency seconds, index-aligned with ``txs``."""
        loop = asyncio.get_running_loop()
        futs = []
        waiter_refs = []
        for tx in txs:
            digest = tx_digest(tx)
            done = self._committed.get(digest)
            if done is not None:
                fut = loop.create_future()
                fut.set_result(done)
                futs.append(fut)
                continue
            self._check_alive()
            fut = loop.create_future()
            waiters = self._commits.setdefault(digest, [])
            waiters.append(fut)
            waiter_refs.append((digest, waiters, fut))
            futs.append(fut)
        try:
            return list(await asyncio.wait_for(
                asyncio.gather(*futs), timeout_s
            ))
        finally:
            for digest, waiters, fut in waiter_refs:
                if fut in waiters:
                    waiters.remove(fut)
                if not waiters:
                    self._commits.pop(digest, None)

    async def status(self, timeout_s: float = 10.0,
                     chain_tail: Optional[int] = None) -> dict:
        """Fetch the node's status document.  ``chain_tail`` limits the
        digest-chain tail in the reply (0 = head/length only — the cheap
        form for poll loops; None = the node's full default)."""
        self._check_alive()
        fut = asyncio.get_running_loop().create_future()
        self._status_waiters.append(fut)
        payload = b"" if chain_tail is None else struct.pack(
            ">I", chain_tail)
        async with self._wlock:
            self._writer.write(framing.encode_frame(
                framing.STATUS_REQ, payload, self.max_frame
            ))
            await self._writer.drain()
        return await asyncio.wait_for(fut, timeout_s)

    # -- stats ---------------------------------------------------------------

    def latency_percentiles(self) -> Dict[str, float]:
        return latency_percentiles(lat for _d, lat in self.latencies)

    # -- internals -----------------------------------------------------------

    async def _keepalive_loop(self) -> None:
        nonce = 0
        while self._dead is None:
            await asyncio.sleep(self.keepalive_s)
            nonce += 1
            try:
                async with self._wlock:
                    self._writer.write(framing.encode_frame(
                        framing.PING, struct.pack(">Q", nonce),
                        self.max_frame,
                    ))
                    await self._writer.drain()
            # hblint: disable=fault-swallowed-drop (nothing to account
            # client-side: the recv loop fails every pending waiter with
            # the connection error; this loop just stops pinging)
            except (ConnectionError, OSError):
                return

    async def _recv_loop(self) -> None:
        decoder = FrameDecoder(self.max_frame)
        try:
            while True:
                data = await self._reader.read(65536)
                if not data:
                    raise ConnectionError("node closed connection")
                for kind, payload in decoder.feed(data):
                    self._on_frame(kind, payload)
        except asyncio.CancelledError:
            raise
        except Exception as exc:
            # a dead reader must surface NOW on every pending future —
            # not as N× full submit/commit timeouts later
            self._fail_waiters(
                exc if isinstance(exc, ConnectionError)
                else ConnectionError(f"client receive loop died: {exc!r}")
            )

    def _drop_ack_waiter(self, digest: bytes, fut: asyncio.Future) -> None:
        waiters = self._acks.get(digest)
        if waiters is not None:
            with contextlib.suppress(ValueError):
                waiters.remove(fut)
            if not waiters:
                del self._acks[digest]

    def _on_frame(self, kind: int, payload: bytes) -> None:
        if kind == framing.TX_ACK:
            status, digest = payload[0], payload[1:33]
            waiters = self._acks.get(digest)
            if waiters:
                fut = waiters.pop(0)  # one ack per written TX frame: FIFO
                if not waiters:
                    del self._acks[digest]
                if not fut.done():
                    fut.set_result(status)
        elif kind == framing.TX_COMMIT:
            # u64 era + u64 epoch + u32 count + count × 32-byte digests;
            # nodes broadcast every committed digest to every client, so
            # only digests we submitted or are awaiting are retained
            era, epoch, count = struct.unpack_from(">QQI", payload, 0)
            now = time.monotonic()
            for i in range(count):
                digest = payload[20 + 32 * i : 52 + 32 * i]
                t0 = self._submit_times.pop(digest, None)
                waiters = self._commits.pop(digest, None)
                if t0 is None and waiters is None:
                    continue  # someone else's transaction
                lat = now - t0 if t0 is not None else 0.0
                if t0 is not None:
                    self.latencies.append((digest.hex(), lat))
                self._committed[digest] = lat
                while len(self._committed) > self._committed_cap:
                    self._committed.popitem(last=False)
                for fut in waiters or ():
                    if not fut.done():
                        fut.set_result(lat)
        elif kind == framing.STATUS:
            doc = json.loads(payload.decode())
            waiters, self._status_waiters = self._status_waiters, []
            for fut in waiters:
                if not fut.done():
                    fut.set_result(doc)

    def _check_alive(self) -> None:
        if self._dead is not None:
            raise ConnectionError(
                f"connection to {self.addr} is dead: {self._dead}"
            )

    def _fail_waiters(self, exc: Exception) -> None:
        self._dead = exc
        commit_futs = [
            fut for waiters in self._commits.values() for fut in waiters
        ]
        ack_futs = [
            fut for waiters in self._acks.values() for fut in waiters
        ]
        for fut in (ack_futs + commit_futs + self._status_waiters):
            if not fut.done():
                fut.set_exception(exc)

"""Asyncio TCP transport: peer connections, backoff, heartbeats.

Connection topology: every node keeps ONE outbound connection per peer used
exclusively for sending (consensus ``MSG`` frames + ``PING`` heartbeats;
the acceptor answers ``PONG`` on the same socket), and accepts inbound
connections for receiving.  Send/receive asymmetry means there is no
connection-dedup race: a (dialer, acceptor) pair owns each socket.

Reliability model:

- per-peer outbound queues are *persistent across reconnects*: frames
  enqueued while a peer is down are delivered, in order, once it is back
  (at-least-once — a frame written into a socket that dies mid-flight is
  re-sent, and the consensus protocols treat duplicates as no-ops/logged
  faults);
- reconnects use seeded exponential backoff with jitter: with a fixed
  ``seed`` the drawn delay sequence is identical run to run (the
  same-seed-same-trace property the simulator guarantees extends to the
  transport's schedule), and every drawn delay is recorded in
  ``stats.backoff_delays`` so tests can assert it;
- a dialer that misses heartbeat ``PONG``\\ s for ``dead_after_s`` declares
  the peer dead, tears the socket down, and re-enters backoff.

Inbound connections announce themselves with the versioned hello
(:mod:`hbbft_tpu.net.framing`); node-role hellos from ids outside the
configured peer set, cluster-id mismatches, and version mismatches are
rejected before any payload frame is parsed.  Client-role connections are
handed to the runtime via ``on_client_frame``.

SECURITY MODEL — node-role hellos are AUTHENTICATED (protocol v3): a
node hello is identification only until the acceptor's challenge is
answered.  The acceptor issues a random nonce + session id; the dialer
must sign the transcript (cluster id, nonce, session, claimed id, role,
era — :func:`hbbft_tpu.net.framing.auth_transcript`) with the node's
per-era secret key, and the acceptor verifies against the era key map
(:class:`EraKeyRing`; the same ``NetworkInfo`` map the dynamic-peer
resolver consults for WHERE, used here for WHO).  Until that signature
verifies, the connection allocates NO per-peer guard state, every
handshake frame is capped at ``framing.MAX_HANDSHAKE_FRAME`` bytes and
timed out (the half-open handshake has its own byte/time budget plus a
concurrent-connection cap, so the auth step cannot become the flood
target), and refusals are counted (``hbbft_guard_auth_failures_total``)
and journaled attributed to the attacker's SOCKET ENDPOINT — never to
the impersonated validator.  The session id is bound into every
subsequent heartbeat PING, so a hijacked TCP stream cannot ride an
authenticated session.  A transport built without ``auth_verify`` (raw
tests, sim harnesses) keeps the legacy identification-only behavior;
``NodeRuntime`` always wires authentication when its protocol stack
carries an era key map.  Residual gaps: client-role and obs ports stay
identification-only — bind them to localhost or a private fabric — and
transport auth is a floor under the per-node Ed/BLS signatures INSIDE
the protocol (DHB votes, key-gen messages, threshold shares), which
remain verified regardless.

All callbacks run on the event loop; they may call :meth:`Transport.send`
re-entrantly (it only enqueues).
"""

from __future__ import annotations

import asyncio
import contextlib
import hashlib
import logging
import random
import socket
import struct
import threading
import time
from collections import deque
from typing import Any, Callable, Deque, Dict, Hashable, List, Optional, Tuple

from hbbft_tpu.net import framing
from hbbft_tpu.obs.metrics import MetricAttr
from hbbft_tpu.net.framing import (
    DEFAULT_MAX_FRAME,
    FrameDecoder,
    FrameError,
    Hello,
    ROLE_CLIENT,
    ROLE_NODE,
)

NodeId = Hashable
Addr = Tuple[str, int]

logger = logging.getLogger("hbbft_tpu.net")


def set_nodelay(writer: asyncio.StreamWriter) -> None:
    """Disable Nagle on a stream's socket.  Consensus frames are tiny
    (~70 B) and latency-critical; Nagle + delayed-ACK otherwise holds
    them back up to 40 ms waiting to coalesce with traffic that never
    comes."""
    sock = writer.get_extra_info("socket")
    if sock is not None:
        with contextlib.suppress(OSError):  # non-TCP / already-closed socket
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)


class BackoffPolicy:
    """Seeded exponential backoff with jitter — deterministic per seed.

    ``delays(peer_key)`` yields ``min(cap, base·factor^i) · u`` where ``u``
    is drawn uniformly from ``[1−jitter, 1)`` by a per-(seed, peer) RNG.
    The RNG stream is owned by the caller via :meth:`rng_for` so that
    successive outages continue one deterministic sequence.
    """

    def __init__(self, seed: int = 0, base: float = 0.05,
                 factor: float = 2.0, cap: float = 2.0,
                 jitter: float = 0.5):
        if not 0.0 <= jitter < 1.0:
            raise ValueError("jitter must be in [0, 1)")
        self.seed = seed
        self.base = base
        self.factor = factor
        self.cap = cap
        self.jitter = jitter

    def rng_for(self, peer_key: str) -> random.Random:
        digest = hashlib.sha3_256(
            b"hbbft-net-backoff:%d:%s" % (self.seed, peer_key.encode())
        ).digest()
        return random.Random(int.from_bytes(digest[:8], "big"))

    def delay(self, attempt: int, rng: random.Random) -> float:
        raw = min(self.cap, self.base * self.factor ** attempt)
        return raw * (1.0 - self.jitter + self.jitter * rng.random())

    def preview(self, peer_key: str, n: int) -> List[float]:
        """First ``n`` delays of a fresh stream (for tests/debugging)."""
        rng = self.rng_for(peer_key)
        return [self.delay(i, rng) for i in range(n)]


class _PeerBudget:
    """Per-peer ingress bookkeeping (see :class:`IngressBudget`)."""

    __slots__ = ("tokens", "t_last", "inflight", "strikes", "decode_fails",
                 "disconnects", "backoff_until", "kill")

    def __init__(self, burst: float, now: float):
        self.tokens = burst
        self.t_last = now
        self.inflight = 0
        self.strikes = 0
        self.decode_fails = 0
        self.disconnects = 0
        self.backoff_until = 0.0
        self.kill = False


class IngressBudget:
    """Per-peer ingress budgets: the transport's overload defense.

    Every node-role connection is metered three ways, each violation
    counted (``hbbft_guard_*``), never silent:

    - a **bytes/sec token bucket** (``bytes_per_s`` sustained,
      ``burst_bytes`` burst): a peer over budget is *throttled* — the
      recv loop stops reading its socket for the shortfall, so the
      kernel's TCP window closes and the flood backs up at the sender;
    - a **max in-flight frames** cap: frames admitted to the pump but
      not yet processed, per peer (enabled once a consumer calls
      :meth:`frame_done`; a raw transport with a synchronous callback
      has no in-flight window to track);
    - a **strike ladder**: sustained throttling (or a run of
      decode-invalid frames, reported by the runtime via
      :meth:`decode_strike`) escalates to a counted
      *disconnect-with-backoff* — the connection is torn down and the
      peer's node-role hellos are rejected until the (exponentially
      growing, capped) backoff expires.

    Budgets attribute to the VERIFIED peer identity: with transport
    authentication on (see the module security model) no per-peer state
    is allocated — and none of the meters above are chargeable — until
    the dialer proves the claimed identity with its era key, so a spoofer
    cannot spend validator X's budget or burn X's strike ladder.  Failed
    proofs are counted per refusal *reason* (``auth_failures`` below) and
    attributed to the attacker's socket endpoint.  On a transport built
    without ``auth_verify`` the ledger reverts to claimed identities;
    run that mode only on a trusted fabric.

    Defaults are sized far above honest consensus traffic (a 4-node
    pipelined cluster peaks well under 1 MiB/s per peer) so the guard
    only ever engages on floods.
    """

    #: every way a handshake can be refused — each refusal is counted
    #: under exactly one of these (pre-initialized so a zero shows up in
    #: scrapes before the first attack): signature did not verify
    #: (``bad_sig``), claimed id absent from every admissible era map
    #: (``unknown_key``), a non-AUTH frame where the proof was due
    #: (``no_auth``), an unparsable handshake frame (``malformed``), the
    #: proof never arrived in time (``timeout``), a heartbeat carrying
    #: the wrong session id on an authenticated stream (``session``),
    #: or the half-open connection cap was hit (``half_open``).
    AUTH_FAIL_REASONS = ("bad_sig", "unknown_key", "no_auth", "malformed",
                         "timeout", "session", "half_open")

    def __init__(self, registry=None, *,
                 bytes_per_s: float = 16 * 2**20,
                 burst_bytes: float = 4 * 2**20,
                 max_inflight_frames: int = 16384,
                 throttle_strikes: int = 64,
                 decode_strikes: int = 256,
                 backoff_s: float = 2.0,
                 backoff_cap_s: float = 30.0,
                 max_throttle_sleep_s: float = 0.25,
                 clock: Callable[[], float] = time.monotonic):
        from hbbft_tpu.obs.metrics import Registry

        self.bytes_per_s = float(bytes_per_s)
        self.burst_bytes = float(burst_bytes)
        self.max_inflight_frames = int(max_inflight_frames)
        self.throttle_strikes = int(throttle_strikes)
        self.decode_strikes = int(decode_strikes)
        self.backoff_s = float(backoff_s)
        self.backoff_cap_s = float(backoff_cap_s)
        self.max_throttle_sleep_s = float(max_throttle_sleep_s)
        self.clock = clock
        # inflight counts cross threads (event loop admits, the pump's
        # worker retires); one lock covers the whole peer table
        self._lock = threading.Lock()
        self._peers: Dict[NodeId, _PeerBudget] = {}
        # in-flight tracking is opt-in: only a consumer that retires
        # frames (NodeRuntime) can keep the window honest
        self.track_inflight = False
        # a guard event sink (the runtime journals disconnects/rejects
        # to the flight recorder through its pump, so the forensic
        # auditor can attribute an overload incident to the peer)
        self.on_event: Optional[Callable[[str, NodeId, str], None]] = None
        r = registry if registry is not None else Registry()
        self._c_throttles = r.counter(
            "hbbft_guard_ingress_throttles_total",
            "per-peer ingress budget violations that paused the recv "
            "loop (token-bucket shortfall or in-flight frame overflow)",
            labelnames=("peer",), max_label_sets=33)
        self._c_throttle_s = r.counter(
            "hbbft_guard_ingress_throttle_seconds_total",
            "seconds the recv loops spent paused on over-budget peers")
        self._c_disconnects = r.counter(
            "hbbft_guard_ingress_disconnects_total",
            "peers disconnected with backoff after sustained budget "
            "violations or decode-invalid streams",
            labelnames=("peer",), max_label_sets=33)
        self._c_hello_rejects = r.counter(
            "hbbft_guard_hello_rejects_total",
            "node-role hellos rejected while the peer's guard backoff "
            "window was still open")
        self._c_decode_strikes = r.counter(
            "hbbft_guard_decode_strikes_total",
            "decode-invalid frames charged against a peer's guard "
            "budget by the runtime", labelnames=("peer",),
            max_label_sets=33)
        self._g_inflight = r.gauge(
            "hbbft_guard_inflight_frames",
            "frames admitted from a peer but not yet processed by the "
            "pump", labelnames=("peer",), max_label_sets=33)
        self._c_auth_ok = r.counter(
            "hbbft_guard_auth_ok_total",
            "node-role handshakes that proved the claimed identity with "
            "a valid era-key signature")
        self._c_auth_stale = r.counter(
            "hbbft_guard_auth_stale_era_total",
            "handshakes accepted against the PREVIOUS era's key map "
            "within the rotation grace window (counted, not refused)")
        # reason cardinality is fixed by AUTH_FAIL_REASONS; the attacker
        # endpoint is deliberately NOT a label (unbounded cardinality) —
        # it travels through the guard-event journal instead
        self._c_auth_fail = r.counter(
            "hbbft_guard_auth_failures_total",
            "node-role handshakes refused before allocating any "
            "per-peer state, by refusal reason",
            labelnames=("reason",),
            max_label_sets=len(self.AUTH_FAIL_REASONS) + 1)
        for reason in self.AUTH_FAIL_REASONS:
            self._c_auth_fail.labels(reason=reason)
        r.register_callback(self._refresh_gauges)

    def _refresh_gauges(self) -> None:
        with self._lock:
            snap = [(p, b.inflight) for p, b in self._peers.items()]
        for peer, inflight in snap:
            self._g_inflight.labels(peer=repr(peer)).set(inflight)

    def _budget(self, peer: NodeId) -> _PeerBudget:
        b = self._peers.get(peer)
        if b is None:
            b = self._peers[peer] = _PeerBudget(
                self.burst_bytes, self.clock())
        return b

    def _emit(self, kind: str, peer: NodeId, detail: str) -> None:
        if self.on_event is not None:
            self.on_event(kind, peer, detail)

    def _trip(self, b: _PeerBudget, peer: NodeId, why: str) -> None:
        """Escalate to a counted disconnect-with-backoff."""
        b.kill = True
        b.strikes = 0
        if self.clock() < b.backoff_until:
            # aftershock: the pump is still draining frames admitted
            # before the disconnect (decode strikes keep arriving with
            # no live recv loop).  The window is already armed — do not
            # re-count the incident or double the backoff for it.
            return
        b.disconnects += 1
        backoff = min(self.backoff_cap_s,
                      self.backoff_s * 2 ** (b.disconnects - 1))
        b.backoff_until = self.clock() + backoff
        self._c_disconnects.labels(peer=repr(peer)).inc()
        self._emit("disconnect", peer,
                   f"why={why} backoff_s={backoff:.3f}")
        logger.warning("guard: disconnecting peer %r (%s), backoff "
                       "%.1fs", peer, why, backoff)

    def connection_accepted(self, peer: NodeId) -> None:
        """A fresh node-role connection for ``peer`` passed the backoff
        gate: clear any stale kill mark left by backlog drained after
        the OLD connection died, so the legitimate successor is not
        torn down on its first chunk for the predecessor's sins."""
        with self._lock:
            b = self._peers.get(peer)
            if b is not None:
                b.kill = False
                b.strikes = 0

    # -- recv-loop surface (event loop) --------------------------------------

    def charge(self, peer: NodeId, nbytes: int) -> float:
        """Account one received chunk; returns seconds the recv loop
        must pause before reading again (0.0 when within budget).  A
        peer that keeps earning pauses trips the strike ladder and is
        marked for disconnect (see :meth:`kill_pending`)."""
        now = self.clock()
        with self._lock:
            b = self._budget(peer)
            b.tokens = min(self.burst_bytes,
                           b.tokens + (now - b.t_last) * self.bytes_per_s)
            b.t_last = now
            b.tokens -= nbytes
            over_tokens = b.tokens < 0
            over_inflight = (self.track_inflight
                             and b.inflight > self.max_inflight_frames)
            if not over_tokens and not over_inflight:
                if b.strikes:
                    b.strikes -= 1  # calm traffic pays strikes down
                return 0.0
            b.strikes += 1
            if b.strikes > self.throttle_strikes:
                why = ("inflight" if over_inflight else "bytes_per_s")
                self._trip(b, peer, why)
                return 0.0
            if over_tokens:
                delay = min(self.max_throttle_sleep_s,
                            -b.tokens / self.bytes_per_s)
            else:
                delay = min(self.max_throttle_sleep_s, 0.05)
        self._c_throttles.labels(peer=repr(peer)).inc()
        self._c_throttle_s.inc(delay)
        return delay

    #: worst-case frames a single 64 KiB recv chunk can admit: a
    #: MSG_BATCH sub-message costs 4 bytes minimum (u32 length prefix,
    #: empty payload), so one chunk can carry up to 64 Ki/4 of them.
    #: The in-flight cap is enforced at chunk granularity, so the
    #: resident count is bounded by ``max_inflight_frames +
    #: CHUNK_FRAMES_MAX``, never by the cap alone mid-chunk
    CHUNK_FRAMES_MAX = 65536 // 4

    @property
    def inflight_hard_bound(self) -> int:
        """The enforced ceiling on any peer's in-flight frames: the cap
        plus one recv chunk's worst-case admissions (the chunk is the
        enforcement granularity — the loop stops READING once over the
        cap, but a chunk already read is admitted whole)."""
        return self.max_inflight_frames + self.CHUNK_FRAMES_MAX

    def inflight_over(self, peer: NodeId) -> bool:
        """Is the peer currently over its in-flight frame cap?  The
        recv loop polls this and stops READING until the pump drains
        the window — the cap is enforced, not just sampled (overshoot
        is bounded by one chunk's worth of frames)."""
        if not self.track_inflight:
            return False
        with self._lock:
            b = self._peers.get(peer)
            return (b is not None
                    and b.inflight > self.max_inflight_frames)

    def kill_pending(self, peer: NodeId) -> bool:
        """True once for a peer marked for disconnect (clears the mark;
        the backoff window stays armed)."""
        with self._lock:
            b = self._peers.get(peer)
            if b is None or not b.kill:
                return False
            b.kill = False
            return True

    def in_backoff(self, peer: NodeId) -> bool:
        with self._lock:
            b = self._peers.get(peer)
            backed_off = (b is not None
                          and self.clock() < b.backoff_until)
        if backed_off:
            self._c_hello_rejects.inc()
            self._emit("hello_reject", peer, "backoff window open")
        return backed_off

    def frame_admitted(self, peer: NodeId, n: int = 1) -> None:
        if not self.track_inflight:
            return
        with self._lock:
            self._budget(peer).inflight += n

    # -- handshake authentication surface (event loop) -----------------------

    def auth_ok(self) -> None:
        self._c_auth_ok.inc()

    def auth_stale(self, peer: NodeId) -> None:
        """A handshake that verified against the PREVIOUS era's key
        inside the rotation grace window: admitted, but counted — a
        burst of these outside a rotation is worth an operator's look."""
        self._c_auth_stale.inc()
        logger.info("guard: peer %r authenticated with previous-era key "
                    "(rotation grace window)", peer)

    def auth_fail(self, endpoint: str, claimed: Any, reason: str) -> None:
        """A refused handshake: counted by ``reason`` and journaled
        attributed to the attacker's socket ENDPOINT — never to the
        impersonated ``claimed`` identity, whose budgets and strike
        ladder stay untouched (no per-peer state exists yet)."""
        if reason not in self.AUTH_FAIL_REASONS:
            reason = "malformed"
        self._c_auth_fail.labels(reason=reason).inc()
        self._emit("auth_fail", endpoint,
                   f"claimed={claimed!r} reason={reason}")
        logger.warning("guard: refused handshake from %s claiming %r "
                       "(%s)", endpoint, claimed, reason)

    # -- consumer surface (pump worker thread) -------------------------------

    def frame_done(self, peer: NodeId, n: int = 1) -> None:
        with self._lock:
            b = self._peers.get(peer)
            if b is not None:
                b.inflight = max(0, b.inflight - n)

    def decode_strike(self, peer: NodeId) -> None:
        """A framing-valid but decode-invalid (or protocol-rejected)
        frame: charged by the runtime.  A sustained garbage stream —
        ``decode_strikes`` of them — trips the disconnect ladder; the
        recv loop notices via :meth:`kill_pending` on its next chunk."""
        self._c_decode_strikes.labels(peer=repr(peer)).inc()
        with self._lock:
            b = self._budget(peer)
            b.decode_fails += 1
            if b.decode_fails % self.decode_strikes == 0:
                self._trip(b, peer, "decode_garbage")

    # -- introspection -------------------------------------------------------

    def peer_doc(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {
                repr(p): {
                    "inflight": b.inflight,
                    "strikes": b.strikes,
                    "decode_fails": b.decode_fails,
                    "disconnects": b.disconnects,
                }
                for p, b in self._peers.items()
            }

    def as_dict(self) -> Dict[str, Any]:
        return {
            "throttles": int(self._c_throttles.total()),
            "throttle_seconds": round(float(self._c_throttle_s.total()),
                                      6),
            "disconnects": int(self._c_disconnects.total()),
            "hello_rejects": int(self._c_hello_rejects.total()),
            "decode_strikes": int(self._c_decode_strikes.total()),
            "auth_ok": int(self._c_auth_ok.total()),
            "auth_stale_era": int(self._c_auth_stale.total()),
            "auth_failures": {
                reason: int(self._c_auth_fail.value(reason=reason))
                for reason in self.AUTH_FAIL_REASONS
            },
            "peers": self.peer_doc(),
        }


class EraKeyRing:
    """Per-era public-key lookup for handshake verification, with a
    bounded previous-era grace window.

    ``provider()`` returns ``(era, {node_id: public_key})`` — the
    CURRENT era's key map (``NodeRuntime`` reads it off the live
    protocol's ``NetworkInfo``).  The ring polls the provider on every
    lookup; when the era advances it stashes the outgoing map so that a
    peer still dialing with the *previous* era's key during an in-flight
    DKG rotation verifies within ``grace_s`` seconds (counted
    ``hbbft_guard_auth_stale_era_total`` by the caller) instead of being
    refused into a strike-laddered retry storm.  Exactly one previous
    era is retained and it expires on the clock, so the admissible key
    set stays bounded.  The converse race — a dialer already rotated
    ahead of an acceptor that has not observed the new era yet — needs
    no stash: the plain keypairs rarely change across eras (re-adds keep
    keys), and a genuinely new key is refused ``unknown_key`` until the
    acceptor's own rotation lands, bounded by the dialer's backoff.
    """

    def __init__(self, provider: Callable[[], Tuple[int, Dict[NodeId, Any]]],
                 *, grace_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic):
        self.provider = provider
        self.grace_s = float(grace_s)
        self.clock = clock
        self._era: Optional[int] = None
        self._keys: Dict[NodeId, Any] = {}
        self._prev_era: Optional[int] = None
        self._prev_keys: Dict[NodeId, Any] = {}
        self._prev_at = 0.0

    def _refresh(self) -> None:
        era, keys = self.provider()
        if self._era is not None and era != self._era:
            self._prev_era = self._era
            self._prev_keys = self._keys
            self._prev_at = self.clock()
        self._era = era
        self._keys = dict(keys)

    def lookup(self, node_id: NodeId) -> List[Tuple[int, Any, bool]]:
        """Admissible ``(era, public_key, stale)`` candidates for a
        claimed id, current era first.  Empty when the id is unknown to
        every admissible era."""
        self._refresh()
        out: List[Tuple[int, Any, bool]] = []
        key = self._keys.get(node_id)
        if key is not None:
            out.append((self._era, key, False))
        if (self._prev_era is not None
                and self.clock() - self._prev_at <= self.grace_s):
            prev = self._prev_keys.get(node_id)
            if prev is not None:
                out.append((self._prev_era, prev, True))
        return out


class _LabeledCounterView:
    """Dict-shaped view over one labeled counter, keyed by the original
    (hashable) id — the shim that lets ``stats.reconnects[peer] += 1``-style
    call sites keep working while the registry carries the series.

    The view keeps its own per-key values and applies *deltas* to the
    counter: past the metric's label-cardinality cap several keys share
    the ``_overflow_`` series, and a plain assignment there would clobber
    every other overflowed peer's aggregate — a delta only ever adds this
    key's change."""

    def __init__(self, counter):
        self._counter = counter
        self._values: Dict[NodeId, float] = {}

    def get(self, key: NodeId, default: int = 0) -> int:
        return int(self._values.get(key, default))

    def __getitem__(self, key: NodeId) -> int:
        return int(self._values[key])

    def __setitem__(self, key: NodeId, value: int) -> None:
        self._counter.labels(repr(key)).inc(
            value - self._values.get(key, 0)
        )
        self._values[key] = value

    def __contains__(self, key: NodeId) -> bool:
        return key in self._values

    def __len__(self) -> int:
        return len(self._values)

    def items(self):
        return [(k, int(v)) for k, v in self._values.items()]

    def keys(self):
        return list(self._values.keys())

    def __iter__(self):
        return iter(self.keys())


class TransportStats:
    """Socket-layer counters, backed by an :mod:`hbbft_tpu.obs.metrics`
    registry (``hbbft_net_*``); the original dataclass attribute API is
    preserved as thin property views so no call site or test breaks.
    ``backoff_delays`` keeps the exact per-peer delay *lists* (the seeded
    determinism tests assert on the sequences, which a histogram cannot
    represent); :meth:`record_backoff` also feeds the registry histogram."""

    def __init__(self, registry=None):
        from hbbft_tpu.obs.metrics import Registry

        self.registry = registry or Registry()
        r = self.registry
        self._frames_sent = r.counter(
            "hbbft_net_frames_sent_total",
            "frames written to peer/client sockets")
        self._bytes_sent = r.counter(
            "hbbft_net_bytes_sent_total",
            "framed bytes written, length prefix included")
        self._frames_recv = r.counter(
            "hbbft_net_frames_recv_total", "frames received")
        self._bytes_recv = r.counter(
            "hbbft_net_bytes_recv_total", "framed bytes received")
        self._reconnects = r.counter(
            "hbbft_net_reconnects_total",
            "outbound connection losses per peer", labelnames=("peer",))
        self._send_queue_peak = r.gauge(
            "hbbft_net_send_queue_peak",
            "high-water mark of any per-peer outbox")
        self._dead_peer_events = r.counter(
            "hbbft_net_dead_peer_events_total",
            "peers declared dead after missed heartbeats")
        # drop accounting (hblint fault-swallowed-drop): connection-level
        # losses must be scrapeable, not just debug-logged
        self._inbound_drops = r.counter(
            "hbbft_net_inbound_drops_total",
            "inbound connections dropped on error/timeout before or "
            "during frame processing")
        self._client_conn_drops = r.counter(
            "hbbft_net_client_conn_drops_total",
            "client connections dropped mid-send (write-buffer overflow "
            "or dead socket)")
        self._dynamic_peers = r.counter(
            "hbbft_net_dynamic_peers_total",
            "peers added live from a membership-resolved inbound hello "
            "(a validator voted in by a DHB rotation dialing us)")
        # virtual cost of received traffic under the attached CostModel —
        # the simulator's synthetic clock applied to real frames, so sim
        # and net runs report comparable virtual time
        self._virtual_cost = r.counter(
            "hbbft_net_virtual_cost_seconds_total",
            "CostModel virtual seconds charged to received frames")
        self._backoff_hist = r.histogram(
            "hbbft_net_backoff_delay_seconds",
            "reconnect backoff delays drawn",
            buckets=(0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.0, 4.0))
        # egress fairness (guard family: bounded-resource enforcement):
        # a drain round that hit its byte quantum with backlog remaining
        # — the sender yielded the event loop instead of writing on
        self._egress_stalls = r.counter(
            "hbbft_guard_egress_stalls_total",
            "per-peer egress drain rounds truncated at the byte quantum "
            "with frames still queued (round-robin yield points)",
            labelnames=("peer",), max_label_sets=33)
        self.reconnects = _LabeledCounterView(self._reconnects)
        self.backoff_delays: Dict[NodeId, List[float]] = {}
        # hot-path handles: _record_send/_record_recv run per frame, and
        # the MetricAttr `+= 1` shim costs a registry read + a set each —
        # these direct child references make the per-frame accounting two
        # plain ``inc`` calls (part of the r01→r02 obs-overhead fix)
        self._c_frames_sent = self._frames_sent._default()
        self._c_bytes_sent = self._bytes_sent._default()
        self._c_frames_recv = self._frames_recv._default()
        self._c_bytes_recv = self._bytes_recv._default()

    def frame_sent(self, nbytes: int) -> None:
        self._c_frames_sent.inc()
        self._c_bytes_sent.inc(nbytes)

    def frame_recv(self, nbytes: int) -> None:
        self._c_frames_recv.inc()
        self._c_bytes_recv.inc(nbytes)

    def frame_recv_batch(self, nframes: int, nbytes: int) -> None:
        """Batched receive accounting: one pair of counter bumps for a
        whole decoded chunk instead of two per frame (the batch-handle
        hot path; only the totals are observable either way)."""
        self._c_frames_recv.inc(nframes)
        self._c_bytes_recv.inc(nbytes)

    def egress_stall(self, peer_id: NodeId) -> None:
        self._egress_stalls.labels(peer=repr(peer_id)).inc()

    # -- attribute views (the pre-registry dataclass API) -------------------

    frames_sent = MetricAttr("_frames_sent")
    bytes_sent = MetricAttr("_bytes_sent")
    frames_recv = MetricAttr("_frames_recv")
    bytes_recv = MetricAttr("_bytes_recv")
    send_queue_peak = MetricAttr("_send_queue_peak")
    dead_peer_events = MetricAttr("_dead_peer_events")
    inbound_drops = MetricAttr("_inbound_drops")
    client_conn_drops = MetricAttr("_client_conn_drops")
    dynamic_peers = MetricAttr("_dynamic_peers")
    virtual_cost_s = MetricAttr("_virtual_cost", cast=float)

    def record_backoff(self, peer_id: NodeId, delay: float) -> None:
        delays = self.backoff_delays.setdefault(peer_id, [])
        delays.append(delay)
        if len(delays) > 512:
            # bounded-ingress: a peer that stays down draws a delay
            # every couple of seconds forever; the determinism tests
            # assert on short prefixes, so front-chopping the exact
            # list at depth keeps both properties
            del delays[: len(delays) - 512]
        self._backoff_hist.observe(delay)

    def as_dict(self) -> Dict[str, Any]:
        return {
            "frames_sent": self.frames_sent,
            "bytes_sent": self.bytes_sent,
            "frames_recv": self.frames_recv,
            "bytes_recv": self.bytes_recv,
            "reconnects": {repr(k): v for k, v in self.reconnects.items()},
            "send_queue_peak": self.send_queue_peak,
            "dead_peer_events": self.dead_peer_events,
            "inbound_drops": self.inbound_drops,
            "client_conn_drops": self.client_conn_drops,
            "dynamic_peers": self.dynamic_peers,
            "virtual_cost_s": round(self.virtual_cost_s, 6),
        }


class ClientConn:
    """One inbound client-role connection.

    Writes are fire-and-forget but bounded: a client that stops reading
    its socket would otherwise make the node buffer commit notifications
    without limit, so once the transport's write buffer exceeds
    ``MAX_WRITE_BUFFER`` the connection is declared dead and dropped (the
    client can reconnect; commit state is queryable via STATUS_REQ)."""

    MAX_WRITE_BUFFER = 1 << 20

    _next = 0

    def __init__(self, hello: Hello, writer: asyncio.StreamWriter,
                 max_frame: int, record_send=None,
                 stats: Optional["TransportStats"] = None):
        ClientConn._next += 1
        self.conn_id = ClientConn._next
        self.hello = hello
        self.client_id = hello.node_id
        self._writer = writer
        self._max_frame = max_frame
        self._record_send = record_send
        self._stats = stats
        self.closed = False
        # chunk-scoped write coalescing: between begin_batch/flush_batch
        # frames accumulate and go out as ONE writer.write — a 16-tx
        # submit wave answers with one ack syscall, not 16 (socket send
        # is a measurable share of a small host's consensus budget)
        self._batching = False
        self._pending: List[bytes] = []

    def _drop(self) -> None:
        self.closed = True
        if self._stats is not None:
            self._stats.client_conn_drops += 1

    def begin_batch(self) -> None:
        self._batching = True

    def flush_batch(self) -> None:
        self._batching = False
        if not self._pending or self.closed:
            self._pending.clear()
            return
        buf = b"".join(self._pending)
        self._pending.clear()
        try:
            if (self._writer.transport.get_write_buffer_size()
                    > self.MAX_WRITE_BUFFER):
                self._drop()
                self._writer.close()
                return
            self._writer.write(buf)
        except (ConnectionError, RuntimeError):
            self._drop()

    def send(self, kind: int, payload: bytes) -> None:
        if self.closed:
            return
        try:
            frame = framing.encode_frame(kind, payload, self._max_frame)
            if self._record_send is not None:
                self._record_send(self.client_id, frame)
            if self._batching:
                self._pending.append(frame)
                return
            if (self._writer.transport.get_write_buffer_size()
                    > self.MAX_WRITE_BUFFER):
                self._drop()
                self._writer.close()
                return
            self._writer.write(frame)
        except (ConnectionError, RuntimeError):
            self._drop()


class _PeerSender:
    """Outbound half for one peer: queue + dial/backoff/heartbeat loop."""

    def __init__(self, transport: "Transport", peer_id: NodeId, addr: Addr):
        self.t = transport
        self.peer_id = peer_id
        self.addr = addr
        self.outbox: Deque[bytes] = deque()
        self.wake = asyncio.Event()
        self.connected = asyncio.Event()
        self.stopped = False
        self.rng = transport.backoff.rng_for(
            f"{transport.our_id!r}->{peer_id!r}"
        )
        self.task: Optional[asyncio.Task] = None
        # session id issued by the acceptor's CHALLENGE (None on a
        # legacy unauthenticated handshake); bound into every heartbeat
        # PING so a hijacked stream can't ride the authenticated session
        self.session: Optional[bytes] = None

    def start(self) -> None:
        self.task = asyncio.get_running_loop().create_task(
            self._run(), name=f"peer-sender-{self.peer_id!r}"
        )

    def send(self, frame: bytes) -> None:
        # the transport side of the shared shaping hook
        # (chaos.link.LinkShaper): per-edge latency/jitter/loss/dup/
        # bandwidth/partition decisions, seeded and accounted.  Shaped
        # copies are scheduled onto the event loop; a dropped frame was
        # already counted by the shaper (hbbft_chaos_frames_dropped_total)
        shaper = self.t.shaper
        if shaper is not None:
            delays = shaper.shape_frame(
                self.t.our_id, self.peer_id, self.t.chaos_now(),
                nbytes=len(frame))
            if delays is not None:
                loop = asyncio.get_running_loop()
                for d in delays:
                    if d > 0:
                        loop.call_later(d, self._enqueue, frame)
                    else:
                        self._enqueue(frame)
                return
        self._enqueue(frame)

    def _enqueue(self, frame: bytes) -> None:
        if self.stopped:
            return  # a shaped frame landing after shutdown
        self.outbox.append(frame)
        peak = len(self.outbox)
        if peak > self.t.stats.send_queue_peak:
            self.t.stats.send_queue_peak = peak
        self.wake.set()

    async def _run(self) -> None:
        attempt = 0
        while not self.stopped:
            try:
                reader, writer = await asyncio.wait_for(
                    asyncio.open_connection(*self.addr),
                    self.t.connect_timeout_s,
                )
            except (OSError, asyncio.TimeoutError):
                attempt = await self._backoff(attempt)
                continue
            set_nodelay(writer)
            try:
                hello = await self._handshake(reader, writer)
            except (OSError, asyncio.TimeoutError, FrameError,
                    asyncio.IncompleteReadError) as exc:
                logger.debug("handshake with %r failed: %r",
                             self.peer_id, exc)
                writer.close()
                attempt = await self._backoff(attempt)
                continue
            self.connected.set()
            self.t._notify_hello(self.peer_id, hello, direction="dial")
            t_conn = time.monotonic()
            try:
                await self._serve(reader, writer)
            finally:
                self.connected.clear()
                writer.close()
                if not self.stopped:
                    self.t.stats.reconnects[self.peer_id] = (
                        self.t.stats.reconnects.get(self.peer_id, 0) + 1
                    )
            # a connection that survived a while earns an immediate redial
            # with reset growth; one that died right after the handshake
            # keeps climbing the backoff ladder — otherwise a peer that
            # kills every fresh connection induces a zero-delay dial spin
            if time.monotonic() - t_conn >= self.t.dead_after_s:
                attempt = 0
            else:
                attempt = await self._backoff(attempt)

    async def _backoff(self, attempt: int) -> int:
        delay = self.t.backoff.delay(attempt, self.rng)
        self.t.stats.record_backoff(self.peer_id, delay)
        await asyncio.sleep(delay)
        return attempt + 1

    async def _handshake(self, reader: asyncio.StreamReader,
                         writer: asyncio.StreamWriter) -> Hello:
        frame = framing.encode_frame(
            framing.HELLO, framing.encode_hello(self.t.local_hello()),
            self.t.max_frame,
        )
        writer.write(frame)
        await writer.drain()
        self.t._record_send(self.peer_id, frame)
        kind, payload = await asyncio.wait_for(
            framing.read_one_frame(reader, self.t.max_frame),
            self.t.dead_after_s,
        )
        self.session = None
        if kind == framing.CHALLENGE:
            # authenticated acceptor: prove our identity by signing the
            # challenge transcript with our current era key, then the
            # hello reply follows on success
            if self.t.auth_sign is None:
                raise FrameError(
                    "peer demands an authenticated handshake but this "
                    "transport has no signer (auth disabled?)"
                )
            nonce, session = framing.decode_challenge(payload)
            era, sig = self.t.auth_sign(self.t.cluster_id, nonce, session)
            auth = framing.encode_frame(
                framing.AUTH, framing.encode_auth(era, sig),
                self.t.max_frame,
            )
            writer.write(auth)
            await writer.drain()
            self.t._record_send(self.peer_id, auth)
            kind, payload = await asyncio.wait_for(
                framing.read_one_frame(reader, self.t.max_frame),
                self.t.dead_after_s,
            )
            self.session = session
        if kind != framing.HELLO:
            raise FrameError(f"expected HELLO reply, got kind {kind}")
        hello = framing.decode_hello(payload)
        if hello.cluster_id != self.t.cluster_id:
            raise FrameError("cluster id mismatch")
        if hello.role != ROLE_NODE or hello.node_id != self.peer_id:
            raise FrameError(
                f"dialed {self.peer_id!r}, got hello from "
                f"{hello.node_id!r}"
            )
        return hello

    async def _serve(self, reader: asyncio.StreamReader,
                     writer: asyncio.StreamWriter) -> None:
        """Drain the outbox + heartbeat until the connection dies."""
        last_pong = time.monotonic()
        ping_nonce = 0
        # drainer and heartbeat share the StreamWriter; two tasks awaiting
        # writer.drain() concurrently trip asyncio's _drain_helper assert
        # under write backpressure, so every write+drain takes this lock
        wlock = asyncio.Lock()

        async def pong_reader():
            nonlocal last_pong
            decoder = FrameDecoder(self.t.max_frame)
            while True:
                data = await reader.read(65536)
                if not data:
                    return
                for kind, _payload in decoder.feed(data):
                    if kind == framing.PONG:
                        last_pong = time.monotonic()
                    else:
                        raise FrameError(
                            f"unexpected frame kind {kind} on send socket"
                        )

        async def drainer():
            quantum = self.t.egress_quantum_bytes
            while True:
                await self.wake.wait()
                self.wake.clear()
                while self.outbox:
                    # write queued frames up to the byte QUANTUM, then ONE
                    # drain for the lot — per-frame drains cost a writer
                    # round trip each and dominated the sequential-path
                    # profile, while an unbounded batch lets one peer's
                    # MB-scale shard backlog monopolize the event loop
                    # (every other peer's drainer and the recv loops wait
                    # behind the memcpy).  (Link shaping happens BEFORE
                    # the outbox — see send(): a queued frame is already
                    # due.)
                    batch = []
                    nbytes = 0
                    for f in self.outbox:
                        batch.append(f)
                        nbytes += len(f)
                        if nbytes >= quantum:
                            break
                    async with wlock:
                        for f in batch:
                            writer.write(f)
                        await writer.drain()
                    # popped only after a successful drain: frames in
                    # flight when the socket dies are re-sent
                    # (at-least-once)
                    for f in batch:
                        self.outbox.popleft()
                        self.t._record_send(self.peer_id, f)
                    if self.outbox:
                        # counted yield point: round-robin fairness across
                        # peers is observable, not assumed
                        self.t.stats.egress_stall(self.peer_id)
                        await asyncio.sleep(0)

        async def ping_once():
            # on an authenticated session the PING carries the session
            # id issued at the handshake — the acceptor refuses the
            # stream if it ever mismatches (hijack defense)
            prefix = self.session if self.session is not None else b""
            frame = framing.encode_frame(
                framing.PING, prefix + struct.pack(">Q", ping_nonce),
                self.t.max_frame,
            )
            async with wlock:
                writer.write(frame)
                await writer.drain()
            self.t._record_send(self.peer_id, frame)

        async def heartbeat():
            nonlocal ping_nonce
            while True:
                await asyncio.sleep(self.t.heartbeat_s)
                # deadline check runs UNLOCKED every cycle: when the peer
                # stops reading, the drainer wedges inside writer.drain()
                # holding wlock — the ping below must not be allowed to
                # park this task behind it, or dead-peer detection would
                # never fire and the connection would hang forever
                if time.monotonic() - last_pong > self.t.dead_after_s:
                    self.t.stats.dead_peer_events += 1
                    raise ConnectionError(
                        f"peer {self.peer_id!r} missed heartbeats for "
                        f"{self.t.dead_after_s}s"
                    )
                ping_nonce += 1
                try:
                    await asyncio.wait_for(ping_once(), self.t.heartbeat_s)
                # hblint: disable=fault-swallowed-drop (nothing is
                # dropped: a congested writer just skips this ping and
                # the pong deadline above decides peer death)
                except asyncio.TimeoutError:
                    pass

        self.wake.set()  # flush anything queued while disconnected
        tasks = [
            asyncio.get_running_loop().create_task(c())
            for c in (pong_reader, drainer, heartbeat)
        ]
        try:
            done, _pending = await asyncio.wait(
                tasks, return_when=asyncio.FIRST_COMPLETED
            )
            for d in done:
                exc = d.exception()
                if exc is not None:
                    logger.debug("connection to %r dropped: %r",
                                 self.peer_id, exc)
        finally:
            # re-cancel until done: ping_once sits under a wait_for, and a
            # cancel landing as it completes is swallowed on CPython 3.10
            # (bpo-42130) — see Transport.stop
            live = {t for t in tasks if not t.done()}
            while live:
                for task in live:
                    task.cancel()
                _done, live = await asyncio.wait(live, timeout=1.0)

    async def stop(self) -> None:
        self.stopped = True
        if self.task is not None:
            self.task.cancel()
            # suppress: awaiting our own cancelled task; any late error
            # was already logged by _serve and the sender is going away
            with contextlib.suppress(asyncio.CancelledError, Exception):
                await self.task


class _NodeRecvProtocol(asyncio.Protocol):
    """Post-handshake node receive path as a raw asyncio protocol.

    Swapped onto the socket with ``set_protocol`` once the stream-based
    handshake completes: every chunk is then one synchronous
    ``data_received`` callback that decodes, admits, and delivers the
    whole chunk's consensus payloads as a single batch (or feeds a
    per-peer ingress worker thread when the transport runs with
    ``ingress_workers``).  The IngressBudget verdicts map onto transport
    flow control: a throttle delay or an in-flight-cap breach pauses
    reading (closing the TCP window — real backpressure) and a timer
    re-polls until the pump drains the window or the strike ladder
    trips.  ``done`` resolves when the connection ends, carrying the
    same exception shapes the old StreamReader loop raised so the
    caller's drop accounting is untouched.
    """

    __slots__ = ("t", "peer_id", "writer", "decoder", "state", "session",
                 "worker", "loop", "done", "transport", "timing",
                 "seg_recv", "_paused", "_resume_handle")

    def __init__(self, t: "Transport", peer_id: NodeId,
                 writer: asyncio.StreamWriter, decoder: FrameDecoder,
                 state: list, session: Optional[bytes],
                 worker: Optional[Any] = None):
        self.t = t
        self.peer_id = peer_id
        self.writer = writer
        self.decoder = decoder
        self.state = state  # shared with _idle_watchdog
        self.session = session
        self.worker = worker
        self.loop = asyncio.get_running_loop()
        self.done: asyncio.Future = self.loop.create_future()
        self.transport: Optional[asyncio.BaseTransport] = None
        # cached per-connection: the runtime wires these before serving
        self.timing = getattr(t, "timing", None)
        self.seg_recv = getattr(t, "seg_recv", None)
        self._paused = False
        self._resume_handle: Optional[asyncio.TimerHandle] = None

    def connection_made(self, transport: asyncio.BaseTransport) -> None:
        self.transport = transport

    def data_received(self, data: bytes) -> None:
        if self.done.done():
            return
        self.state[0] = time.monotonic()
        t = self.t
        if self.worker is not None:
            # decode happens off-loop; only byte-rate accounting and
            # flow control stay here
            self.worker.feed(data)
            if self.worker.backlog_over():
                # bounded hand-off queue: a slow worker closes the TCP
                # window instead of buffering unboundedly
                self._pause(0.01)
        else:
            try:
                if self.timing is None and self.seg_recv is None:
                    t._recv_chunk(self.peer_id, self.writer,
                                  self.decoder, data,
                                  session=self.session)
                else:
                    w0 = time.perf_counter()
                    t0 = (time.thread_time()
                          if self.timing is not None else 0.0)
                    t._recv_chunk(self.peer_id, self.writer,
                                  self.decoder, data,
                                  session=self.session)
                    if self.timing is not None:
                        self.timing["recv"] = (
                            self.timing.get("recv", 0.0)
                            + (time.thread_time() - t0))
                        self.timing["n_recv"] = (
                            self.timing.get("n_recv", 0) + 1)
                    if self.seg_recv is not None:
                        self.seg_recv(time.perf_counter() - w0)
            except (FrameError, ValueError) as exc:
                # same exception set the stream loop let propagate to
                # the acceptor's drop accounting
                self._fail(exc)
                return
        guard = t.ingress
        delay = guard.charge(self.peer_id, len(data))
        if guard.kill_pending(self.peer_id):
            self._fail(FrameError(
                f"ingress budget exceeded by peer {self.peer_id!r}"
            ))
            return
        if delay > 0 or guard.inflight_over(self.peer_id):
            self._pause(delay if delay > 0 else 0.05)

    def _pause(self, delay: float) -> None:
        if self._paused or self.transport is None:
            return
        self._paused = True
        self.transport.pause_reading()
        self._resume_handle = self.loop.call_later(
            delay, self._maybe_resume)

    def _maybe_resume(self) -> None:
        """Timer path of the in-flight cap: re-poll the guard until the
        pump retires this peer's admitted frames.  Each wait cycle is a
        counted strike (``charge(peer, 0)``), so a wedged consumer or a
        flood the pump cannot keep up with escalates to the disconnect
        ladder instead of pausing forever — same ladder the old polling
        loop walked."""
        self._resume_handle = None
        if self.done.done() or self.transport is None:
            return
        self.state[0] = time.monotonic()  # a throttle is not idleness
        if self.worker is not None and self.worker.backlog_over():
            # our own worker is behind, not the peer misbehaving: wait
            # without charging the peer's strike ladder
            self._resume_handle = self.loop.call_later(
                0.01, self._maybe_resume)
            return
        guard = self.t.ingress
        if guard.inflight_over(self.peer_id):
            delay = guard.charge(self.peer_id, 0)
            if guard.kill_pending(self.peer_id):
                self._fail(FrameError(
                    f"in-flight frame cap exceeded by peer "
                    f"{self.peer_id!r}"
                ))
                return
            self._resume_handle = self.loop.call_later(
                delay if delay > 0 else 0.05, self._maybe_resume)
            return
        self._paused = False
        self.transport.resume_reading()

    def _fail(self, exc: BaseException) -> None:
        """Terminate the connection with ``exc`` as the recv outcome
        (thread-safe callers schedule this via call_soon_threadsafe)."""
        if not self.done.done():
            self.done.set_exception(exc)
        if self.transport is not None:
            self.transport.close()

    def eof_received(self) -> bool:
        return False  # close on EOF, like reader.read() returning b""

    def connection_lost(self, exc: Optional[Exception]) -> None:
        if self._resume_handle is not None:
            self._resume_handle.cancel()
            self._resume_handle = None
        if self.worker is not None:
            self.worker.stop()
        if self.done.done():
            return
        if self.state[1]:
            # the idle watchdog closed us: surface the same timeout the
            # stream loop raised so drop accounting is unchanged
            self.done.set_exception(asyncio.TimeoutError(
                f"peer {self.peer_id!r} recv idle timeout"))
        elif exc is not None:
            self.done.set_exception(exc)
        else:
            self.done.set_result(None)


class Transport:
    """The node's socket layer: one listener + one sender per peer."""

    def __init__(
        self,
        our_id: NodeId,
        cluster_id: bytes,
        *,
        seed: int = 0,
        hello_key: Callable[[], Tuple[int, int]] = lambda: (0, 0),
        on_peer_message: Optional[Callable[[NodeId, bytes], None]] = None,
        on_peer_batch: Optional[
            Callable[[NodeId, List[Any]], None]
        ] = None,
        ingress_workers: bool = False,
        on_peer_hello: Optional[
            Callable[[NodeId, Hello, str], None]
        ] = None,
        on_client_frame: Optional[
            Callable[[ClientConn, int, bytes], None]
        ] = None,
        on_client_gone: Optional[Callable[[ClientConn], None]] = None,
        heartbeat_s: float = 0.5,
        dead_after_s: float = 3.0,
        connect_timeout_s: float = 2.0,
        client_idle_timeout_s: float = 60.0,
        max_frame: int = DEFAULT_MAX_FRAME,
        egress_quantum_bytes: int = 256 * 1024,
        backoff: Optional[BackoffPolicy] = None,
        trace=None,
        cost_model=None,
        registry=None,
        link_delays: Optional[Dict[NodeId, float]] = None,
        shaper=None,
        peer_resolver: Optional[
            Callable[[NodeId], Optional[Addr]]
        ] = None,
        ingress: Optional[IngressBudget] = None,
        ingress_kwargs: Optional[Dict[str, Any]] = None,
        auth_sign: Optional[
            Callable[[bytes, bytes, bytes], Tuple[int, bytes]]
        ] = None,
        auth_verify: Optional[
            Callable[[NodeId, int, int, bytes, bytes, bytes], str]
        ] = None,
        max_half_open: int = 64,
    ):
        self.our_id = our_id
        self.cluster_id = bytes(cluster_id)
        self.hello_key = hello_key
        self.on_peer_message = on_peer_message
        # batch-handle fast path: when set, each network chunk delivers its
        # whole decoded MSG/MSG_BATCH content as ONE callback (a list of
        # payloads, or (payload, pre_decoded) pairs from ingress workers)
        # instead of N per-message callbacks — one pump enqueue per chunk
        self.on_peer_batch = on_peer_batch
        # move framing/CRC/decode work off the event loop onto per-peer
        # worker threads (net/ingress.py); requires on_peer_batch
        self.ingress_workers = bool(ingress_workers)
        self.on_peer_hello = on_peer_hello
        self.on_client_frame = on_client_frame
        self.on_client_gone = on_client_gone
        self.heartbeat_s = heartbeat_s
        self.dead_after_s = dead_after_s
        self.connect_timeout_s = connect_timeout_s
        self.client_idle_timeout_s = client_idle_timeout_s
        self.max_frame = max_frame
        # egress fairness: a drainer round writes at most this many bytes
        # before draining and yielding — bounds any single peer's hold on
        # the event loop (counted: hbbft_guard_egress_stalls_total)
        self.egress_quantum_bytes = int(egress_quantum_bytes)
        self.backoff = backoff or BackoffPolicy(seed=seed)
        self.trace = trace
        self.cost_model = cost_model
        # dynamic membership: an inbound node-role hello from an id
        # OUTSIDE the configured peer set is normally rejected; with a
        # resolver, the embedder (NodeRuntime) is asked whether the id is
        # a legitimate cluster member now (e.g. a validator voted in by a
        # DHB rotation) and at what address — if it answers, the peer is
        # added live and the connection proceeds
        self.peer_resolver = peer_resolver
        self.stats = TransportStats(registry)
        # per-peer ingress budgets (overload defense): every inbound
        # node connection is metered; violators are throttled, then
        # disconnected with backoff — counted, never silent growth
        self.ingress = ingress if ingress is not None else IngressBudget(
            self.stats.registry, **(ingress_kwargs or {}))
        # outbound link shaping — the real-socket side of the shared
        # chaos.link hook: per-directed-edge latency/jitter/loss/dup/
        # bandwidth/partition policies applied to this node's egress
        # queue (see _PeerSender.send).  The legacy per-peer constant
        # `link_delays` knob is now sugar for a constant-delay shaper.
        # A shaper instance belongs to ONE transport (bind_registry
        # re-homes its counters onto this node's registry).
        self.link_delays: Dict[NodeId, float] = dict(link_delays or {})
        if self.link_delays:
            if shaper is not None:
                # refusing beats silently dropping one of them: before
                # the shared hook, link_delays ALWAYS applied
                raise ValueError(
                    "link_delays and a chaos shaper are mutually "
                    "exclusive — express the constant delays as "
                    "ShapedLink edges in the shaper's NetShape instead")
            from hbbft_tpu.chaos.link import (
                LinkShaper, NetShape, ShapedLink,
            )

            shaper = LinkShaper(NetShape(edges={
                (our_id, peer): ShapedLink(delay_s=delay)
                for peer, delay in self.link_delays.items()
            }), seed=seed)
        self.shaper = shaper
        if shaper is not None:
            shaper.bind_registry(self.stats.registry)
        # the shaping clock: seconds since this transport was built —
        # preset partition windows are relative to node start
        self._chaos_t0 = time.monotonic()
        self._senders: Dict[NodeId, _PeerSender] = {}
        self._peer_ids_cache: Optional[List[NodeId]] = None
        self._server: Optional[asyncio.base_events.Server] = None
        self._inbound_tasks: set = set()
        self._stopping = False
        self.addr: Optional[Addr] = None
        # handshake authentication (module security model).  auth_sign
        # answers an acceptor's CHALLENGE with (era, signature) over the
        # transcript; auth_verify judges an inbound proof -> verdict in
        # {"ok", "stale", "bad_sig", "unknown_key"}.  Both are embedder
        # callbacks so the transport stays crypto-free; None keeps the
        # legacy identification-only handshake on that side.
        self.auth_sign = auth_sign
        self.auth_verify = auth_verify
        # half-open budget: connections past accept() but not yet past
        # the handshake.  The cap (with the per-frame MAX_HANDSHAKE_FRAME
        # byte cap and dead_after_s time cap) bounds what a SYN-and-stall
        # flood can pin, so the auth step can't become the flood target.
        self.max_half_open = int(max_half_open)
        self._half_open = 0
        # challenge nonces/session ids: seeded for deterministic tests
        self._auth_rng = random.Random(
            int.from_bytes(hashlib.sha3_256(
                b"hbbft-net-auth:%d:%s" % (seed, repr(our_id).encode())
            ).digest()[:8], "big"))

    def chaos_now(self) -> float:
        """The link-shaping clock (seconds since transport creation)."""
        return time.monotonic() - self._chaos_t0

    # -- lifecycle -----------------------------------------------------------

    async def listen(self, host: str = "127.0.0.1", port: int = 0) -> Addr:
        self._server = await asyncio.start_server(
            self._accept, host=host, port=port
        )
        sock = self._server.sockets[0]
        self.addr = sock.getsockname()[:2]
        return self.addr

    def add_peer(self, peer_id: NodeId, addr: Addr) -> None:
        if peer_id in self._senders:
            raise ValueError(f"peer {peer_id!r} already added")
        sender = _PeerSender(self, peer_id, addr)
        self._senders[peer_id] = sender
        self._peer_ids_cache = None
        sender.start()

    async def stop(self) -> None:
        self._stopping = True
        for sender in self._senders.values():
            await sender.stop()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        # cancel inbound handlers and wait RE-CANCELLING: on CPython 3.10
        # a cancel that lands exactly as a wait_for's inner read completes
        # is swallowed (bpo-42130) and the recv loop keeps running — one
        # plain gather here then hangs forever (observed ~1-in-3 at
        # in-process cluster shutdown).  The loops also check _stopping so
        # a swallowed cancel exits at its next iteration either way.
        pending = {t for t in self._inbound_tasks if not t.done()}
        while pending:
            for task in pending:
                task.cancel()
            _done, pending = await asyncio.wait(pending, timeout=1.0)

    # -- sending -------------------------------------------------------------

    def peer_ids(self) -> List[NodeId]:
        # called once per dispatched Step — cache the sorted list (peers
        # are only ever added via add_peer, which invalidates)
        if self._peer_ids_cache is None:
            self._peer_ids_cache = sorted(self._senders.keys(), key=repr)
        return self._peer_ids_cache

    def connected(self, peer_id: NodeId) -> bool:
        sender = self._senders.get(peer_id)
        return sender is not None and sender.connected.is_set()

    def queued(self, peer_id: NodeId) -> int:
        sender = self._senders.get(peer_id)
        return 0 if sender is None else len(sender.outbox)

    def send_backlog_s(self, peer_id: NodeId) -> float:
        """Seconds of bulk already committed to the shaped link toward
        ``peer_id``.  Shaped frames are delayed *before* they reach the
        outbox (``_PeerSender.send`` defers them via ``call_later``), so
        ``queued()`` never sees that backlog — the shaper's bandwidth
        clock is the only honest congestion signal.  Returns 0.0 when no
        shaper is attached (real deployments would read the socket send
        buffer instead)."""
        if self.shaper is None:
            return 0.0
        return self.shaper.backlog_s(self.our_id, peer_id, self.chaos_now())

    def send(self, peer_id: NodeId, payload: bytes) -> None:
        """Queue one consensus MSG frame for ``peer_id``."""
        self.send_frame(peer_id, framing.MSG, payload)

    def send_frame(self, peer_id: NodeId, kind: int, payload: bytes) -> None:
        sender = self._senders.get(peer_id)
        if sender is None:
            raise KeyError(f"unknown peer {peer_id!r}")
        sender.send(framing.encode_frame(kind, payload, self.max_frame))

    def send_payloads(self, peer_id: NodeId, payloads) -> None:
        """Queue many consensus payloads for ``peer_id``, coalesced into
        as few MSG/MSG_BATCH frames as the cap allows — the pump's
        per-iteration write path (:func:`framing.pack_msgs`)."""
        sender = self._senders.get(peer_id)
        if sender is None:
            raise KeyError(f"unknown peer {peer_id!r}")
        for frame in framing.pack_msgs(payloads, self.max_frame):
            sender.send(frame)

    def local_hello(self) -> Hello:
        era, epoch = self.hello_key()
        return Hello(node_id=self.our_id, role=ROLE_NODE,
                     cluster_id=self.cluster_id, era=era, epoch=epoch)

    # -- receiving -----------------------------------------------------------

    async def _accept(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        task = asyncio.current_task()
        self._inbound_tasks.add(task)
        set_nodelay(writer)
        try:
            await self._serve_inbound(reader, writer)
        except (
            OSError, FrameError, ValueError,
            asyncio.IncompleteReadError, asyncio.TimeoutError,
        ) as exc:
            # an inbound peer/client dying here silently disappeared from
            # the metrics before (hblint fault-swallowed-drop): count it
            self.stats.inbound_drops += 1
            logger.debug("inbound connection dropped: %r", exc)
        finally:
            self._inbound_tasks.discard(task)
            writer.close()

    @staticmethod
    def _endpoint(writer: asyncio.StreamWriter) -> str:
        """The remote socket address as ``host:port`` — the attribution
        handle for refused handshakes (a spoofer's CLAIMED id must never
        be the ledger key)."""
        peer = writer.get_extra_info("peername")
        try:
            return f"{peer[0]}:{peer[1]}"
        # hblint: disable=fault-swallowed-drop (address formatting
        # fallback, no input dropped — the refusal this string labels
        # is itself counted at every call site)
        except (TypeError, IndexError):
            return "<unknown>"

    def _rand_bytes(self, n: int) -> bytes:
        return self._auth_rng.getrandbits(8 * n).to_bytes(n, "big")

    async def _serve_inbound(self, reader: asyncio.StreamReader,
                             writer: asyncio.StreamWriter) -> None:
        # half-open budget: the handshake phase is the only window where
        # an unproven endpoint holds a task/fd, so it is capped (count +
        # refuse past the cap), byte-capped (MAX_HANDSHAKE_FRAME per
        # frame), and time-capped (dead_after_s per read)
        self._half_open += 1
        try:
            if self._half_open > self.max_half_open:
                self.ingress.auth_fail(self._endpoint(writer), None,
                                       "half_open")
                raise FrameError("half-open handshake budget exhausted")
            hello, session = await self._inbound_handshake(reader, writer)
        finally:
            self._half_open -= 1
        if hello.role == ROLE_NODE:
            self._notify_hello(hello.node_id, hello, direction="accept")
            await self._node_recv_loop(hello.node_id, reader, writer,
                                       session)
        else:
            await self._client_recv_loop(hello, reader, writer)

    async def _inbound_handshake(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter,
    ) -> Tuple[Hello, Optional[bytes]]:
        """Read + judge one inbound hello; returns the hello and the
        issued session id (None on the legacy unauthenticated path).
        ORDER MATTERS: a node-role claim is challenged and VERIFIED
        before ``in_backoff``/``connection_accepted``/peer resolution
        run — a spoofer must not clear the impersonated victim's strike
        ladder, consume its backoff gate, or allocate any per-peer state."""
        hs_frame = min(self.max_frame, framing.MAX_HANDSHAKE_FRAME)
        kind, payload = await asyncio.wait_for(
            framing.read_one_frame(reader, hs_frame), self.dead_after_s
        )
        if kind != framing.HELLO:
            raise FrameError(f"first frame must be HELLO, got kind {kind}")
        hello = framing.decode_hello(payload)
        if hello.cluster_id != self.cluster_id:
            raise FrameError("cluster id mismatch")
        session: Optional[bytes] = None
        if hello.role == ROLE_NODE:
            if self.auth_verify is not None:
                session = await self._challenge(reader, writer, hello)
            if self.ingress.in_backoff(hello.node_id):
                # the counted disconnect's backoff window: a flooding
                # peer redialing immediately is refused until it expires
                raise FrameError(
                    f"guard backoff open for peer {hello.node_id!r}"
                )
            self.ingress.connection_accepted(hello.node_id)
        if hello.role == ROLE_NODE and hello.node_id not in self._senders:
            addr = (self.peer_resolver(hello.node_id)
                    if self.peer_resolver is not None else None)
            if addr is None:
                raise FrameError(
                    f"node hello from unknown peer {hello.node_id!r}"
                )
            self.stats.dynamic_peers += 1
            logger.info("accepting new cluster member %r at %r "
                        "(membership-resolved)", hello.node_id, addr)
            self.add_peer(hello.node_id, addr)
        reply = framing.encode_frame(
            framing.HELLO, framing.encode_hello(self.local_hello()),
            self.max_frame,
        )
        writer.write(reply)
        await writer.drain()
        self._record_send(hello.node_id, reply)
        return hello, session

    async def _challenge(self, reader: asyncio.StreamReader,
                         writer: asyncio.StreamWriter,
                         hello: Hello) -> bytes:
        """Issue CHALLENGE, await AUTH, verify — every refusal path is
        counted under exactly one ``hbbft_guard_auth_failures_total``
        reason and attributed to the socket endpoint."""
        endpoint = self._endpoint(writer)
        claimed = hello.node_id
        nonce = self._rand_bytes(framing.NONCE_LEN)
        session = self._rand_bytes(framing.SESSION_LEN)
        challenge = framing.encode_frame(
            framing.CHALLENGE, framing.encode_challenge(nonce, session),
            self.max_frame,
        )
        writer.write(challenge)
        await writer.drain()
        self._record_send(claimed, challenge)
        try:
            kind, payload = await asyncio.wait_for(
                framing.read_one_frame(reader, framing.MAX_HANDSHAKE_FRAME),
                self.dead_after_s,
            )
        except asyncio.TimeoutError:
            self.ingress.auth_fail(endpoint, claimed, "timeout")
            raise
        except (FrameError, asyncio.IncompleteReadError):
            self.ingress.auth_fail(endpoint, claimed, "malformed")
            raise
        if kind != framing.AUTH:
            self.ingress.auth_fail(endpoint, claimed, "no_auth")
            raise FrameError(
                f"expected AUTH from {endpoint} claiming {claimed!r}, "
                f"got kind {kind}"
            )
        self._record_recv(claimed, kind, payload)
        try:
            era, sig = framing.decode_auth(payload)
        except FrameError:
            self.ingress.auth_fail(endpoint, claimed, "malformed")
            raise
        verdict = self.auth_verify(claimed, hello.role, era, sig,
                                   nonce, session)
        if verdict == "ok":
            self.ingress.auth_ok()
        elif verdict == "stale":
            self.ingress.auth_stale(claimed)
        else:
            reason = (verdict if verdict in ("bad_sig", "unknown_key")
                      else "bad_sig")
            self.ingress.auth_fail(endpoint, claimed, reason)
            raise FrameError(
                f"handshake auth failed for {endpoint} claiming "
                f"{claimed!r}: {verdict}"
            )
        return session

    async def _idle_watchdog(self, writer: asyncio.StreamWriter,
                             state: list, idle_timeout: float) -> None:
        """Close ``writer`` once ``state[0]`` (last-data time) goes stale.

        One long-lived task per connection instead of an
        ``asyncio.wait_for`` per read: wait_for creates and cancels a
        Task + timer handle around EVERY chunk, which was a measurable
        slice of the per-epoch event-loop CPU.  Closing the transport
        unblocks the pending read (EOF/reset), and ``state[1]`` tells
        the recv loop the EOF was an idle kill so the drop accounting
        is unchanged."""
        while True:
            deadline = state[0] + idle_timeout
            now = time.monotonic()
            if now >= deadline:
                state[1] = True
                writer.close()
                return
            await asyncio.sleep(deadline - now + 0.05)

    async def _node_recv_loop(self, peer_id: NodeId,
                              reader: asyncio.StreamReader,
                              writer: asyncio.StreamWriter,
                              session: Optional[bytes] = None) -> None:
        decoder = FrameDecoder(self.max_frame)
        # a live dialer pings every heartbeat_s, so silence beyond the
        # dead-peer window means a half-open socket (peer power-loss,
        # partition): time the read out or this task and its fd would
        # leak forever — the dialer side re-dials with a fresh connection
        idle_timeout = self.dead_after_s * 2 + 1.0
        state = [time.monotonic(), False]
        watchdog = asyncio.get_running_loop().create_task(
            self._idle_watchdog(writer, state, idle_timeout)
        )
        try:
            tr = writer.transport
            if hasattr(tr, "set_protocol"):
                await self._node_recv_proto(peer_id, reader, writer, tr,
                                            decoder, state, session)
            else:
                # non-socket transports (test doubles) keep the
                # stream-reader loop
                await self._node_recv_inner(peer_id, reader, writer,
                                            decoder, state, session)
        finally:
            watchdog.cancel()

    async def _node_recv_proto(self, peer_id: NodeId,
                               reader: asyncio.StreamReader,
                               writer: asyncio.StreamWriter,
                               tr: asyncio.BaseTransport,
                               decoder: FrameDecoder, state: list,
                               session: Optional[bytes]) -> None:
        """Steady-state node receive via a raw asyncio protocol.

        After the (stream-based, cold-path) handshake the connection is
        upgraded in place with ``set_protocol``: chunks then arrive as
        direct ``data_received`` callbacks — no StreamReader buffer
        append + task wakeup + 64 KiB ``read()`` future round-trip per
        chunk, which was a measurable slice of per-epoch loop CPU.
        Bytes the StreamReader already buffered are drained into the
        protocol first (no await between the buffer grab and the
        protocol swap, so no chunk can interleave)."""
        worker = None
        if self.ingress_workers and self.on_peer_batch is not None:
            from hbbft_tpu.net.ingress import PeerIngressWorker

            worker = PeerIngressWorker(self, peer_id, writer, session)
        proto = _NodeRecvProtocol(self, peer_id, writer, decoder,
                                  state, session, worker)
        if worker is not None:
            worker.bind(proto)
        leftover = bytes(reader._buffer)
        del reader._buffer[:]
        tr.set_protocol(proto)
        proto.connection_made(tr)
        if hasattr(tr, "is_reading") and not tr.is_reading():
            # the StreamReader's flow control may have paused the socket
            # with its buffer full; the new protocol owns pausing now
            tr.resume_reading()
        if leftover:
            proto.data_received(leftover)
        try:
            await proto.done
        finally:
            if worker is not None:
                worker.stop()

    async def _node_recv_inner(self, peer_id: NodeId,
                               reader: asyncio.StreamReader,
                               writer: asyncio.StreamWriter,
                               decoder: FrameDecoder, state: list,
                               session: Optional[bytes] = None) -> None:
        timing = getattr(self, "timing", None)
        # always-on recv segment observer (the runtime wires the
        # hbbft_pump_segment_seconds "recv" child here); one observe per
        # socket chunk, a perf_counter pair of overhead
        seg_recv = getattr(self, "seg_recv", None)
        guard = self.ingress
        while not self._stopping:
            data = await reader.read(65536)
            if not data:
                if state[1]:
                    raise asyncio.TimeoutError(
                        f"peer {peer_id!r} recv idle timeout")
                return
            state[0] = time.monotonic()
            if timing is None and seg_recv is None:
                self._recv_chunk(peer_id, writer, decoder, data,
                                 session=session)
            else:
                w0 = time.perf_counter()
                t0 = time.thread_time() if timing is not None else 0.0
                self._recv_chunk(peer_id, writer, decoder, data,
                                 session=session)
                if timing is not None:
                    timing["recv"] = (
                        timing.get("recv", 0.0)
                        + (time.thread_time() - t0))
                    timing["n_recv"] = timing.get("n_recv", 0) + 1
                if seg_recv is not None:
                    seg_recv(time.perf_counter() - w0)
            # ingress budget: over-budget peers pause the read (the TCP
            # window closes → real backpressure); sustained violation or
            # a runtime-reported garbage stream tears the connection
            # down with a counted backoff
            delay = guard.charge(peer_id, len(data))
            if guard.kill_pending(peer_id):
                raise FrameError(
                    f"ingress budget exceeded by peer {peer_id!r}"
                )
            if delay > 0:
                await asyncio.sleep(delay)
                state[0] = time.monotonic()  # a throttle is not idleness
            # in-flight cap ENFORCEMENT: stop reading until the pump
            # retires this peer's admitted frames — each wait cycle is
            # a counted strike, so a wedged consumer (or a flood the
            # pump cannot keep up with) escalates to the disconnect
            # ladder instead of waiting forever
            while guard.inflight_over(peer_id):
                delay = guard.charge(peer_id, 0)
                if guard.kill_pending(peer_id):
                    raise FrameError(
                        f"in-flight frame cap exceeded by peer "
                        f"{peer_id!r}"
                    )
                await asyncio.sleep(delay if delay > 0 else 0.05)
                state[0] = time.monotonic()

    def _recv_chunk(self, peer_id: NodeId, writer: asyncio.StreamWriter,
                    decoder: FrameDecoder, data: bytes, *,
                    session: Optional[bytes] = None) -> None:
        """One chunk of the node recv path — synchronous on purpose: the
        PONG reply is written without an awaited drain (a 15-byte reply
        to a rare heartbeat; asyncio flushes it on the next loop pass),
        which keeps the whole per-chunk path free of coroutine hops.

        With ``on_peer_batch`` set, every consensus payload decoded from
        this chunk is admitted and delivered as ONE list (one ingress
        lock round, one runtime callback, one pump enqueue) — the
        batch-handle fast path.  Without it, the legacy per-message
        ``on_peer_message`` callback fires per payload (raw-transport
        tests and embedders rely on that shape)."""
        frames = decoder.feed(data)
        # per-frame recv accounting only when a trace or cost model is
        # attached (they need kind + per-frame granularity); the plain
        # path batches the two counter bumps for the whole chunk
        heavy = self.trace is not None or self.cost_model is not None
        batch: Optional[List[Any]] = (
            [] if self.on_peer_batch is not None else None)
        nbytes = 0
        for kind, payload in frames:
            if heavy:
                self._record_recv(peer_id, kind, payload)
            else:
                nbytes += len(payload) + 5
            if kind == framing.PING:
                if session is not None and (
                        len(payload) != framing.SESSION_LEN + 8
                        or payload[:framing.SESSION_LEN] != session):
                    # an authenticated stream's heartbeat must carry the
                    # session id issued at the handshake: a mismatch is
                    # a hijacked/confused stream — refuse it loudly
                    self.ingress.auth_fail(self._endpoint(writer),
                                           peer_id, "session")
                    raise FrameError(
                        f"heartbeat with wrong session id on "
                        f"authenticated stream from {peer_id!r}"
                    )
                pong = framing.encode_frame(
                    framing.PONG, payload, self.max_frame
                )
                writer.write(pong)
                self._record_send(peer_id, pong)
            elif kind == framing.MSG:
                if batch is not None:
                    batch.append(payload)
                elif self.on_peer_message is not None:
                    self.ingress.frame_admitted(peer_id)
                    self.on_peer_message(peer_id, payload)
            elif kind == framing.MSG_BATCH:
                if batch is not None:
                    batch.extend(framing.split_msgs(payload))
                elif self.on_peer_message is not None:
                    for sub in framing.split_msgs(payload):
                        self.ingress.frame_admitted(peer_id)
                        self.on_peer_message(peer_id, sub)
            else:
                raise FrameError(
                    f"unexpected frame kind {kind} from node "
                    f"{peer_id!r}"
                )
        if not heavy and frames:
            self.stats.frame_recv_batch(len(frames), nbytes)
        if batch:
            self.ingress.frame_admitted(peer_id, len(batch))
            self.on_peer_batch(peer_id, batch)

    async def _client_recv_loop(self, hello: Hello,
                                reader: asyncio.StreamReader,
                                writer: asyncio.StreamWriter) -> None:
        conn = ClientConn(hello, writer, self.max_frame,
                          record_send=self._record_send, stats=self.stats)
        decoder = FrameDecoder(self.max_frame)
        # clients keep-alive every ~10 s (ClusterClient); longer silence
        # is a half-open socket — reclaim the task/fd (idle watchdog, not
        # a per-read wait_for: see _idle_watchdog)
        state = [time.monotonic(), False]
        watchdog = asyncio.get_running_loop().create_task(
            self._idle_watchdog(writer, state, self.client_idle_timeout_s)
        )
        try:
            while not self._stopping:
                data = await reader.read(65536)
                if not data:
                    if state[1]:
                        raise asyncio.TimeoutError(
                            f"client {hello.node_id!r} recv idle timeout")
                    return
                state[0] = time.monotonic()
                frames = decoder.feed(data)
                if len(frames) > 1:
                    # one reply syscall per CHUNK: a submit wave's acks
                    # coalesce instead of hitting the socket per tx
                    conn.begin_batch()
                for kind, payload in frames:
                    self._record_recv(hello.node_id, kind, payload)
                    if kind == framing.PING:
                        conn.send(framing.PONG, payload)
                    elif kind == framing.CHALLENGE:
                        # a state-sync fetcher verifying this DONOR: sign
                        # its challenge with our current era key (clients
                        # stay identification-only; this authenticates
                        # the NODE side of the client connection)
                        if self.auth_sign is None:
                            raise FrameError(
                                "client challenged this node but it has "
                                "no signer (auth disabled?)"
                            )
                        nonce, csession = framing.decode_challenge(payload)
                        era, sig = self.auth_sign(self.cluster_id,
                                                  nonce, csession)
                        conn.send(framing.AUTH,
                                  framing.encode_auth(era, sig))
                    elif self.on_client_frame is not None:
                        self.on_client_frame(conn, kind, payload)
                conn.flush_batch()
        finally:
            watchdog.cancel()
            conn.closed = True
            if self.on_client_gone is not None:
                self.on_client_gone(conn)

    # -- accounting ----------------------------------------------------------

    def _notify_hello(self, peer_id: NodeId, hello: Hello,
                      direction: str) -> None:
        if self.on_peer_hello is not None:
            self.on_peer_hello(peer_id, hello, direction)

    def _record_send(self, peer_id: NodeId, frame: bytes) -> None:
        self.stats.frame_sent(len(frame))
        if self.trace is not None:
            from hbbft_tpu.sim.trace import NetEvent

            self.trace.record_net(NetEvent(
                direction="send", peer=peer_id,
                kind=framing.KIND_NAMES.get(frame[4], str(frame[4])),
                wire_bytes=len(frame), t_mono=time.monotonic(),
            ))

    def _record_recv(self, peer_id: NodeId, kind: int,
                     payload: bytes) -> None:
        nbytes = len(payload) + 5
        self.stats.frame_recv(nbytes)
        if self.cost_model is not None:
            self.stats.virtual_cost_s += self.cost_model.charge(nbytes)
        if self.trace is not None:
            from hbbft_tpu.sim.trace import NetEvent

            self.trace.record_net(NetEvent(
                direction="recv", peer=peer_id,
                kind=framing.KIND_NAMES.get(kind, str(kind)),
                wire_bytes=nbytes, t_mono=time.monotonic(),
            ))

"""Client gateway tier: terminate client connections off the consensus path.

Thetacrypt-style service split (arxiv 2502.03247): the per-client
connection work — socket churn, dedup, admission fairness, ack/commit
fan-out — is lifted OUT of the consensus node's event loop into a
dedicated gateway process, so the node spends its single precious core
on consensus and talks to a handful of gateways instead of thousands of
clients.

Wire protocol: the gateway speaks the node's exact client protocol on
BOTH sides —

- **south (clients)**: it serves ``HELLO``/``TX``/``TX_ACK``/
  ``TX_COMMIT``/``STATUS_REQ``/``PING`` exactly like a node, so an
  unmodified :class:`~hbbft_tpu.net.client.ClusterClient` connects to a
  gateway address with no code change;
- **north (nodes)**: it multiplexes accepted transactions into node
  mempools over a few long-lived **authenticated node links** — plain
  client-role connections upgraded with the statesync donor challenge
  (:func:`~hbbft_tpu.net.framing.client_hello_handshake` with
  ``verify_node``), so a gateway never trusts an impersonated node with
  client traffic.

Dedup + aggregation + fairness: submissions land in a standard
:class:`~hbbft_tpu.net.client.Mempool` — the SAME admission engine the
node runs, so the dedup window, the FULL backpressure, the fair
per-client shares under pressure, and the single-victim shed policy
(pushed to clients as ``ACK_SHED``, matching the node's semantics
exactly) need no reimplementation.  Accepted txs are forwarded
at-least-once: each link tracks its in-flight window; a link that dies
re-queues its window and fails over to the next node (round-robin
redial), and node-side ``DUPLICATE`` acks make redelivery harmless.
``FULL`` from a node parks the tx in the gateway pool for the next
flush — the gateway is the elastic buffer between client bursts and
node admission.

Commit relay: each node pushes every committed digest to its clients;
the gateway dedups the per-epoch pushes across its links (they connect
to different nodes) and relays ONE encoded ``TX_COMMIT`` frame to all
clients, write-buffer bounded per client (slow consumers are dropped,
not buffered unboundedly — same :class:`ClientConn` policy as the
node).

Trust model: clients are identification-only, exactly as at the node —
the gateway adds no client authentication, it just moves the same
boundary out one tier.  Node links are authenticated northbound (the
gateway verifies the NODE); the node sees the gateway as an ordinary
client.  A malicious gateway can therefore drop or delay its clients'
traffic — clients that need the stronger guarantee connect to a node
directly; Byzantine safety of the ledger itself is untouched either
way.

Metrics: the ``hbbft_gw_*`` family (see README) plus the standard
mempool families from the embedded pool.
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import logging
import struct
import time
from collections import OrderedDict, deque
from typing import Any, Callable, Deque, Dict, Hashable, List, Optional, Tuple

from hbbft_tpu.net import framing
from hbbft_tpu.net.client import Mempool, tx_digest
from hbbft_tpu.net.framing import (
    DEFAULT_MAX_FRAME,
    FrameDecoder,
    FrameError,
    Hello,
    ROLE_CLIENT,
    ROLE_NODE,
    client_hello_handshake,
)
from hbbft_tpu.net.transport import ClientConn, set_nodelay

NodeId = Hashable
Addr = Tuple[str, int]

logger = logging.getLogger("hbbft_tpu.net")

#: per-link in-flight window: TX frames written but not yet acked by the
#: node; the flush loop stops feeding a link at this depth (the node's
#: own mempool FULL responses are the deeper backpressure)
LINK_INFLIGHT_MAX = 1024

#: (era, epoch) commit pushes already relayed — bounded dedup across the
#: redundant node links
COMMIT_SEEN_CAP = 4096

HANDSHAKE_TIMEOUT_S = 10.0


def node_verifier(key_fn) -> Callable[..., bool]:
    """Wrap a ``node_id -> public key | None`` resolver (e.g.
    :func:`~hbbft_tpu.net.cluster.donor_key_fn`) into the
    ``client_hello_handshake`` ``verify_node`` signature used for
    authenticating gateway node links."""
    from hbbft_tpu.crypto import tc

    def verify(node_id, era, sig_bytes, transcript) -> bool:
        key = key_fn(node_id)
        if key is None:
            return False
        try:
            return bool(key.verify(
                tc.Signature.from_bytes(bytes(sig_bytes)), transcript))
        # hblint: disable=fault-swallowed-drop (a malformed signature IS
        # the refusal: verify() returning False surfaces as a counted
        # link failover at the call site)
        except ValueError:
            return False

    return verify


class _NodeLink:
    """One authenticated north-side connection to a consensus node."""

    def __init__(self, gw: "Gateway", link_id: int):
        self.gw = gw
        self.link_id = link_id
        self.addr: Optional[Addr] = None
        self.node_id: Optional[NodeId] = None
        self.writer: Optional[asyncio.StreamWriter] = None
        self.connected = asyncio.Event()
        # digest -> tx written on THIS link, awaiting the node's ack;
        # bounded by LINK_INFLIGHT_MAX (the flush loop checks), re-queued
        # wholesale if the link dies (at-least-once; DUPLICATE is a no-op)
        self.inflight: Dict[bytes, bytes] = {}
        self.task: Optional[asyncio.Task] = None

    def start(self) -> None:
        self.task = asyncio.get_running_loop().create_task(self._serve())

    async def stop(self) -> None:
        if self.task is not None:
            self.task.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await self.task
        if self.writer is not None:
            self.writer.close()

    async def _serve(self) -> None:
        gw = self.gw
        attempt = self.link_id  # stagger links across the node set
        while not gw._stopping:
            addr = gw.node_addrs[attempt % len(gw.node_addrs)]
            attempt += 1
            try:
                reader, writer, node_hello = await client_hello_handshake(
                    addr, gw.cluster_id,
                    f"{gw.gateway_id}-link{self.link_id}",
                    timeout_s=gw.connect_timeout_s,
                    max_frame=gw.max_frame,
                    verify_node=gw.verify_node,
                )
            except (OSError, FrameError, asyncio.TimeoutError) as exc:
                gw._c_link_failovers.inc()
                logger.info("gateway %s link %d: dial %r failed (%s), "
                            "rotating", gw.gateway_id, self.link_id,
                            addr, exc)
                await asyncio.sleep(gw.redial_backoff_s)
                continue
            set_nodelay(writer)
            self.addr = addr
            self.node_id = node_hello.node_id
            self.writer = writer
            self.connected.set()
            gw._g_links.set(gw._live_links())
            logger.info("gateway %s link %d: connected to node %r at %r",
                        gw.gateway_id, self.link_id,
                        node_hello.node_id, addr)
            try:
                await self._recv(reader)
            except (ConnectionError, OSError, FrameError,
                    asyncio.IncompleteReadError,
                    asyncio.TimeoutError) as exc:
                gw._c_link_failovers.inc()
                logger.warning("gateway %s link %d to node %r died: %s",
                               gw.gateway_id, self.link_id,
                               self.node_id, exc)
            finally:
                self.connected.clear()
                self.writer = None
                writer.close()
                gw._g_links.set(gw._live_links())
                # at-least-once: everything this link had in flight goes
                # back to the forward queue for the successor link/node
                requeue, self.inflight = self.inflight, {}
                for digest, tx in requeue.items():
                    gw._forward_q.append((digest, tx))
                gw._flush_wake.set()
            await asyncio.sleep(gw.redial_backoff_s)

    async def _recv(self, reader: asyncio.StreamReader) -> None:
        gw = self.gw
        decoder = FrameDecoder(gw.max_frame)
        ping_nonce = 0
        last_ping = time.monotonic()
        while True:
            try:
                data = await asyncio.wait_for(reader.read(65536),
                                              gw.keepalive_s)
            except asyncio.TimeoutError:
                # idle: keep the node's client-idle watchdog fed
                ping_nonce += 1
                self.writer.write(framing.encode_frame(
                    framing.PING, struct.pack(">Q", ping_nonce),
                    gw.max_frame))
                continue
            if not data:
                raise ConnectionError("node closed the link")
            now = time.monotonic()
            if now - last_ping > gw.keepalive_s:
                last_ping = now
                ping_nonce += 1
                self.writer.write(framing.encode_frame(
                    framing.PING, struct.pack(">Q", ping_nonce),
                    gw.max_frame))
            for kind, payload in decoder.feed(data):
                if kind == framing.TX_ACK:
                    gw._on_node_ack(self, payload)
                elif kind == framing.TX_COMMIT:
                    gw._on_node_commit(payload)
                elif kind in (framing.PONG, framing.STATUS):
                    pass  # keepalive echo / unsolicited status
                else:
                    raise FrameError(
                        f"unexpected frame kind {kind} from node "
                        f"{self.node_id!r}"
                    )


class Gateway:
    """Client-terminating gateway in front of a consensus cluster.

    ``node_addrs`` is the dial list; ``node_links`` connections are held
    live at once, each to a different node (round-robin with failover).
    ``verify_node`` is the northbound authentication callable
    ``(node_id, era, sig, transcript) -> bool`` — None only on trusted
    fabrics (mirrors the transport's legacy mode).
    """

    def __init__(self, node_addrs: List[Addr], cluster_id: bytes, *,
                 gateway_id: str = "gw0",
                 node_links: int = 2,
                 verify_node: Optional[Callable[..., bool]] = None,
                 mempool: Optional[Mempool] = None,
                 registry=None,
                 max_frame: int = DEFAULT_MAX_FRAME,
                 connect_timeout_s: float = 5.0,
                 redial_backoff_s: float = 0.2,
                 keepalive_s: float = 5.0,
                 client_idle_timeout_s: float = 60.0):
        from hbbft_tpu.obs.metrics import Registry

        if not node_addrs:
            raise ValueError("gateway needs at least one node address")
        self.node_addrs = list(node_addrs)
        self.cluster_id = bytes(cluster_id)
        self.gateway_id = gateway_id
        self.verify_node = verify_node
        self.max_frame = max_frame
        self.connect_timeout_s = connect_timeout_s
        self.redial_backoff_s = redial_backoff_s
        self.keepalive_s = keepalive_s
        self.client_idle_timeout_s = client_idle_timeout_s
        self.registry = registry or Registry()
        # the node's admission engine, reused verbatim: dedup window,
        # FULL backpressure, fair per-client shares, single-victim shed
        # (identity check, not truthiness: an EMPTY caller-supplied pool
        # is len()==0 and must not be silently replaced)
        self.mempool = mempool if mempool is not None else Mempool()
        self.mempool.bind_registry(self.registry)
        self.mempool.on_shed = self._on_pool_shed
        self._clients: "set[ClientConn]" = set()
        self._client_tasks: "set[asyncio.Task]" = set()
        self._links = [_NodeLink(self, i)
                       for i in range(max(1, node_links))]
        self._next_link = 0
        self._forward_q: Deque[Tuple[bytes, bytes]] = deque()
        self._flush_wake = asyncio.Event()
        self._flush_task: Optional[asyncio.Task] = None
        self._commit_seen: "OrderedDict[Tuple[int, int], None]" = (
            OrderedDict())
        self._server: Optional[asyncio.base_events.Server] = None
        self._obs_server: Optional[Any] = None
        self.obs_addr: Optional[Addr] = None
        self._stopping = False
        self.addr: Optional[Addr] = None
        r = self.registry
        self._c_submissions = r.counter(
            "hbbft_gw_submissions_total",
            "client tx submissions at the gateway by admission outcome",
            labelnames=("status",), max_label_sets=5)
        self._c_forwarded = r.counter(
            "hbbft_gw_forwarded_total",
            "tx frames forwarded over node links (re-sends after "
            "failover/FULL included)")
        self._c_node_acks = r.counter(
            "hbbft_gw_node_acks_total",
            "node responses to forwarded txs by status",
            labelnames=("status",), max_label_sets=6)
        self._c_sheds = r.counter(
            "hbbft_gw_sheds_total",
            "ACK_SHED pushes to clients (gateway-pool fair-share sheds "
            "+ relayed node sheds)")
        self._c_commits = r.counter(
            "hbbft_gw_commits_relayed_total",
            "committed tx digests relayed to clients")
        self._c_link_failovers = r.counter(
            "hbbft_gw_link_failovers_total",
            "node-link dial failures and mid-session deaths (each "
            "rotates to the next node)")
        self._c_client_drops = r.counter(
            "hbbft_gw_client_drops_total",
            "client connections dropped mid-session (disconnect, idle "
            "timeout, torn/garbage frames)")
        self._g_clients = r.gauge(
            "hbbft_gw_clients", "connected client sockets")
        self._g_links = r.gauge(
            "hbbft_gw_node_links", "live authenticated node links")
        self._g_forward_q = r.gauge(
            "hbbft_gw_forward_queue", "txs waiting for a node link slot")
        r.register_callback(lambda: (
            self._g_clients.set(len(self._clients)),
            self._g_forward_q.set(len(self._forward_q)),
        ))

    # -- lifecycle -----------------------------------------------------------

    async def start(self, host: str = "127.0.0.1",
                    port: int = 0) -> Addr:
        self._server = await asyncio.start_server(
            self._serve_client, host, port)
        self.addr = self._server.sockets[0].getsockname()[:2]
        for link in self._links:
            link.start()
        self._flush_task = asyncio.get_running_loop().create_task(
            self._flush_loop())
        return self.addr

    async def start_obs(self, host: str = "127.0.0.1",
                        port: int = 0) -> Addr:
        """Serve ``/metrics`` + ``/status`` for this gateway (obs.http),
        so ``obs.top --gateways`` and scrapers see the tier like any
        node."""
        from hbbft_tpu.obs.http import ObsServer

        self._obs_server = ObsServer(self.registry,
                                     status_fn=self.status_doc)
        self.obs_addr = await self._obs_server.start(host, port)
        return self.obs_addr

    async def stop(self) -> None:
        self._stopping = True
        if self._obs_server is not None:
            await self._obs_server.stop()
            self._obs_server = None
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        if self._flush_task is not None:
            self._flush_task.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await self._flush_task
        for link in self._links:
            await link.stop()
        for task in list(self._client_tasks):
            task.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await task

    def _live_links(self) -> int:
        return sum(1 for li in self._links if li.connected.is_set())

    async def wait_links(self, n: int = 1,
                         timeout_s: float = 30.0) -> None:
        """Until ≥ ``n`` node links are live (test/CLI startup gate)."""

        async def _wait():
            while self._live_links() < n:
                await asyncio.sleep(0.02)

        await asyncio.wait_for(_wait(), timeout_s)

    # -- south side: client serving ------------------------------------------

    async def _serve_client(self, reader: asyncio.StreamReader,
                            writer: asyncio.StreamWriter) -> None:
        task = asyncio.current_task()
        self._client_tasks.add(task)
        conn: Optional[ClientConn] = None
        try:
            kind, payload = await asyncio.wait_for(
                framing.read_one_frame(reader,
                                       framing.MAX_HANDSHAKE_FRAME),
                HANDSHAKE_TIMEOUT_S)
            if kind != framing.HELLO:
                raise FrameError("client did not open with HELLO")
            hello = framing.decode_hello(payload)
            if hello.role != ROLE_CLIENT:
                raise FrameError("gateway accepts client-role "
                                 "connections only")
            if hello.cluster_id != self.cluster_id:
                raise FrameError("cluster id mismatch")
            set_nodelay(writer)
            reply = Hello(node_id=self.gateway_id, role=ROLE_NODE,
                          cluster_id=self.cluster_id, era=0, epoch=0)
            writer.write(framing.encode_frame(
                framing.HELLO, framing.encode_hello(reply),
                self.max_frame))
            conn = ClientConn(hello, writer, self.max_frame)
            self._clients.add(conn)
            decoder = FrameDecoder(self.max_frame)
            while True:
                data = await asyncio.wait_for(
                    reader.read(65536), self.client_idle_timeout_s)
                if not data:
                    return
                frames = decoder.feed(data)
                if len(frames) > 1:
                    # one ack syscall per chunk (same coalescing as the
                    # node's client loop)
                    conn.begin_batch()
                for kind, payload in frames:
                    self._on_client_frame(conn, kind, payload)
                conn.flush_batch()
        except (OSError, FrameError, ValueError, ConnectionError,
                asyncio.TimeoutError, asyncio.IncompleteReadError):
            # client-side disconnects/garbage are routine churn — counted,
            # never fatal to the tier (the client's pending txs stay in
            # the pool and its commits resume on reconnect)
            self._c_client_drops.inc()
            return
        finally:
            self._client_tasks.discard(task)
            if conn is not None:
                self._clients.discard(conn)
            writer.close()

    def _on_client_frame(self, conn: ClientConn, kind: int,
                         payload: bytes) -> None:
        if kind == framing.TX:
            status = self.mempool.add(payload,
                                      client_id=str(conn.client_id))
            self._c_submissions.labels(
                status=Mempool._ACK_NAMES[status]).inc()
            conn.send(framing.TX_ACK,
                      bytes([status]) + tx_digest(payload))
            if status == Mempool.ACCEPTED:
                self._forward_q.append((tx_digest(payload), payload))
                self._flush_wake.set()
        elif kind == framing.PING:
            conn.send(framing.PONG, payload)
        elif kind == framing.STATUS_REQ:
            conn.send(framing.STATUS,
                      json.dumps(self.status_doc()).encode())
        else:
            logger.warning("gateway %s: unknown client frame kind %d",
                           self.gateway_id, kind)

    def _broadcast(self, kind: int, payload: bytes) -> None:
        """One encode, every client; dead/overflowing conns drop."""
        if not self._clients:
            return
        for conn in list(self._clients):
            conn.send(kind, payload)
            if conn.closed:
                self._clients.discard(conn)

    def _on_pool_shed(self, tx: bytes) -> None:
        """Gateway-pool fair-share shed: same client-visible semantics
        as the node's — an ACK_SHED push so pending commit waits fail
        fast (re-submission is the client's policy)."""
        self._c_sheds.inc()
        self._broadcast(framing.TX_ACK,
                        bytes([framing.ACK_SHED]) + tx_digest(tx))

    # -- north side: forwarding + relaying -----------------------------------

    async def _flush_loop(self) -> None:
        """Drain the forward queue into link in-flight windows.  One
        writer.write per flush round per link (TX frames coalesced into
        a single buffer — the aggregation step), round-robin across
        live links."""
        while True:
            await self._flush_wake.wait()
            self._flush_wake.clear()
            while self._forward_q:
                link = self._pick_link()
                if link is None:
                    # no live link with window room: wait for a
                    # (re)connect or an ack to open one up
                    await asyncio.sleep(0.05)
                    continue
                room = LINK_INFLIGHT_MAX - len(link.inflight)
                chunk: List[bytes] = []
                while self._forward_q and room > 0:
                    digest, tx = self._forward_q.popleft()
                    if (digest in link.inflight
                            or not self.mempool.has_pending(digest)):
                        continue  # committed/shed meanwhile, or dup
                    link.inflight[digest] = tx
                    chunk.append(framing.encode_frame(
                        framing.TX, tx, self.max_frame))
                    room -= 1
                if chunk:
                    link.writer.write(b"".join(chunk))
                    self._c_forwarded.inc(len(chunk))
                await asyncio.sleep(0)  # yield between rounds

    def _pick_link(self) -> Optional[_NodeLink]:
        n = len(self._links)
        for i in range(n):
            link = self._links[(self._next_link + i) % n]
            if (link.connected.is_set() and link.writer is not None
                    and len(link.inflight) < LINK_INFLIGHT_MAX):
                self._next_link = (self._next_link + i + 1) % n
                return link
        return None

    def _on_node_ack(self, link: _NodeLink, payload: bytes) -> None:
        status, digest = payload[0], payload[1:33]
        name = Mempool._ACK_NAMES.get(status, "shed")
        self._c_node_acks.labels(status=name).inc()
        tx = link.inflight.pop(digest, None)
        if status in (framing.ACK_ACCEPTED, framing.ACK_DUPLICATE):
            # the node owns it now; commit relay closes the loop.
            # Recorded in the dedup window so gateway-level re-submits
            # keep answering DUPLICATE
            self.mempool.mark_committed_digests([digest])
        elif status == framing.ACK_FULL:
            # node backpressure: park it for a later flush (possibly on
            # another link) — the gateway is the elastic buffer
            if tx is not None and self.mempool.has_pending(digest):
                self._forward_q.append((digest, tx))
                self._flush_wake.set()
        elif status == framing.ACK_REJECTED:
            self.mempool.mark_committed_digests([digest])
            self._broadcast(framing.TX_ACK, payload)
        elif status == framing.ACK_SHED:
            # push notification: a tx the node accepted earlier was shed
            # there — relay so client commit waits fail fast
            self._c_sheds.inc()
            self._broadcast(framing.TX_ACK, payload)

    def _on_node_commit(self, payload: bytes) -> None:
        era, epoch, count = struct.unpack_from(">QQI", payload, 0)
        if (era, epoch) in self._commit_seen:
            return  # the other links' nodes push the same epoch
        self._commit_seen[(era, epoch)] = None
        while len(self._commit_seen) > COMMIT_SEEN_CAP:
            self._commit_seen.popitem(last=False)
        digests = [payload[20 + 32 * i: 52 + 32 * i]
                   for i in range(count)]
        self.mempool.mark_committed_digests(digests)
        self._c_commits.inc(count)
        self._broadcast(framing.TX_COMMIT, payload)

    # -- introspection -------------------------------------------------------

    def status_doc(self) -> dict:
        return {
            "gateway": self.gateway_id,
            "clients": len(self._clients),
            "pending": len(self.mempool),
            "forward_queue": len(self._forward_q),
            "links": [
                {
                    "link": li.link_id,
                    "node": repr(li.node_id),
                    "connected": li.connected.is_set(),
                    "inflight": len(li.inflight),
                }
                for li in self._links
            ],
            "submissions": {
                name: int(self._c_submissions.value(status=name))
                for name in Mempool._ACK_NAMES.values()
            },
            "forwarded": int(self._c_forwarded.total()),
            "commits_relayed": int(self._c_commits.total()),
            "sheds": int(self._c_sheds.total()),
            "link_failovers": int(self._c_link_failovers.total()),
        }


def main(argv=None) -> None:
    """Standalone gateway process: ``python -m hbbft_tpu.net.gateway
    --nodes N --seed S --base-port P [--port GW_PORT]`` — derives the
    cluster id and node addresses the same way the cluster CLI does and
    authenticates node links with the config-derived keys."""
    import argparse

    from hbbft_tpu.net.cluster import (
        ClusterConfig,
        donor_key_fn,
    )

    ap = argparse.ArgumentParser(description=main.__doc__)
    ap.add_argument("--nodes", type=int, required=True)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--base-port", type=int, required=True)
    ap.add_argument("--encrypt", action="store_true")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0,
                    help="gateway listen port (0 = ephemeral)")
    ap.add_argument("--gateway-id", default="gw0")
    ap.add_argument("--node-links", type=int, default=2)
    ap.add_argument("--no-auth", action="store_true",
                    help="skip node-link authentication (trusted "
                         "fabrics only)")
    ap.add_argument("--metrics-port", type=int, default=0,
                    help="serve /metrics + /status on this port "
                         "(0 = off); obs.top --gateways polls it")
    args = ap.parse_args(argv)
    cfg = ClusterConfig(n=args.nodes, seed=args.seed, host=args.host,
                        base_port=args.base_port, encrypt=args.encrypt)
    verify = (None if args.no_auth
              else node_verifier(donor_key_fn(cfg)))

    async def serve():
        gw = Gateway(
            [(cfg.host, cfg.base_port + i) for i in range(cfg.n)],
            cfg.cluster_id, gateway_id=args.gateway_id,
            node_links=args.node_links,
            verify_node=verify,
        )
        addr = await gw.start(args.host, args.port)
        doc = {"gateway": args.gateway_id, "addr": list(addr)}
        if args.metrics_port:
            obs = await gw.start_obs(args.host, args.metrics_port)
            doc["obs"] = list(obs)
        print(json.dumps(doc), flush=True)
        try:
            while True:
                await asyncio.sleep(3600)
        finally:
            await gw.stop()

    asyncio.run(serve())


if __name__ == "__main__":
    main()

"""Epoch-pipelined Step pump: the node runtime's scheduler.

PR 2's runtime processed every socket event *synchronously inside the
transport callback*: one message → decode → protocol state machine (BLS
pairings included) → per-message frame writes, all on the event loop.
That shape caps the sequential path (every protocol round pays a full
asyncio wakeup + per-frame drain) and stalls heartbeats/clients whenever
threshold crypto runs.  This module replaces it with a pump:

- **Inbox**: transport callbacks only *enqueue* events (peer messages,
  hellos, client/local inputs) — nothing protocol-touching runs on the
  event loop anymore.
- **Adaptive executor offload**: each pump iteration drains a batch of
  events and runs the whole protocol step through ``pump_process``.
  Iterations whose recent cost exceeds ``OFFLOAD_THRESHOLD_S`` (the
  threshold-crypto regime: pairings and MSM folds are multi-ms) run on a
  single worker thread via ``loop.run_in_executor`` so the event loop
  stays responsive (heartbeats, obs scrapes, client acks) while crypto
  grinds; cheap unencrypted iterations (~100 µs of pure Python) run
  inline, because a thread hop per protocol round costs more wall clock
  than it frees (measured: ~25 ms of p50 client latency at N=4).
  Either way the iterations are strictly serialized by this one pump
  task, so protocol state never sees concurrent access and no
  protocol-level locking exists or is needed.
- **Epoch pipelining**: after the batch, the pump feeds the protocol a
  :class:`~hbbft_tpu.protocols.queueing_honey_badger.PipelineInput` so up
  to ``pipeline_depth`` epochs stay proposed-into at once — epoch e+1's
  RBC/ABA runs while epoch e threshold-decrypts (the ``max_future_epochs``
  window and the SenderQueue's epoch gating are the protocol seam).
  ``pipeline_depth=1`` never emits the input: today's sequential behavior.
- **Cross-epoch batched crypto**: the protocols park threshold-decrypt
  share-set verifications (``HoneyBadger.defer_decrypt``); the pump drains
  them once per iteration via ``resolve_deferred`` — ONE merged
  pairing-product / MSM call for all (epoch, proposer) instances in
  flight (``crypto.batch.verify_dec_share_sets``).
- **Coalesced egress**: a whole iteration's outbound messages are grouped
  per destination and written as MSG_BATCH frames
  (:func:`hbbft_tpu.net.framing.pack_msgs`) — one writer drain per peer
  per iteration instead of one per message.

This module deliberately contains NO direct cryptography: share
generation/verification lives behind the protocols' deferred-resolution
surface and :mod:`hbbft_tpu.crypto.batch` (the hblint
``pump-inline-crypto`` rule enforces it).
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from time import perf_counter
from typing import Any, Deque, Optional, Tuple

logger = logging.getLogger("hbbft_tpu.net")

#: events drained per executor hop — large enough to amortize the thread
#: hop, small enough to keep egress latency bounded under floods
DEFAULT_MAX_BATCH = 512

#: iterations whose exponentially-weighted recent cost exceeds this run
#: on the executor (loop kept responsive through crypto); below it they
#: run inline (the thread hop would dominate).  ~2 ms sits between the
#: unencrypted per-round cost (~0.1–0.5 ms) and a single pairing check
#: (~10+ ms host) with a wide margin either side.
OFFLOAD_THRESHOLD_S = 0.002


class StepPump:
    """The runtime's event pump (see module docstring).

    ``runtime`` must provide ``pump_process(events, depth)`` (worker
    thread: run the batch through the protocol, return an outcome) and
    ``pump_flush(outcome)`` (event loop: write frames / notify clients).
    """

    def __init__(self, runtime: Any, *, pipeline_depth: int = 1,
                 max_batch: int = DEFAULT_MAX_BATCH,
                 tick_s: Optional[float] = None):
        if pipeline_depth < 1:
            raise ValueError("pipeline_depth must be >= 1")
        if tick_s is not None and tick_s <= 0:
            raise ValueError("tick_s must be > 0 (or None)")
        self.runtime = runtime
        self.pipeline_depth = pipeline_depth
        self.max_batch = max_batch
        # periodic wake: with tick_s set, the pump also wakes every
        # tick_s while IDLE and calls runtime.pump_tick() after every
        # cycle — the adaptive-degradation controller's heartbeat
        # (recovery must proceed on a quiet node, which an event-driven
        # pump would never revisit)
        self.tick_s = tick_s
        self._inbox: Deque[Tuple[str, tuple, float]] = deque()
        self._wake: Optional[asyncio.Event] = None
        self._task: Optional[asyncio.Task] = None
        self._executor = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="hbbft-pump"
        )
        self._stopped = False
        #: terminal pump failure, if any (run_node watches the task)
        self.error: Optional[BaseException] = None
        self.iterations = 0
        self.offloaded = 0
        #: cumulative pump CPU seconds (thread time summed across
        #: iterations, inline or offloaded) — the perf plane's
        #: pump-layer CPU source, sampled by counter snapshot
        self.cpu_seconds = 0.0
        # EWMA of recent iteration cost drives the inline-vs-executor
        # decision; it starts cheap (inline) and a single expensive
        # iteration (first pairing burst) flips it within a few rounds
        self._cost_ewma = 0.0

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        loop = asyncio.get_running_loop()
        self._wake = asyncio.Event()
        if self._inbox:
            # events enqueued before start (e.g. a connect() racing the
            # runtime's start) must drive the first iteration themselves
            self._wake.set()
        self._task = loop.create_task(self._run(), name="hbbft-step-pump")

    @property
    def task(self) -> Optional[asyncio.Task]:
        return self._task

    async def stop(self) -> None:
        self._stopped = True
        if self._task is not None:
            self._task.cancel()
            # suppress: awaiting our own cancelled task; a real pump
            # failure was already recorded in self.error and journaled
            with contextlib.suppress(asyncio.CancelledError, Exception):
                await self._task
        # wait=True: cancelling the run_in_executor await does NOT
        # interrupt an in-flight pump_process on the worker thread — it
        # must finish BEFORE the runtime closes the transport and flight
        # recorder, or its tail writes land on closed handles (a torn
        # journal exactly where the black box matters most).  The block
        # is bounded by one iteration (~ms; worst case one pairing burst).
        self._executor.shutdown(wait=True)

    # -- ingress (event-loop side) -------------------------------------------

    def enqueue(self, kind: str, *args) -> None:
        """Queue one event; processing order is strict FIFO.

        Each event carries its enqueue time (``perf_counter``) so the
        pump can account queue-wait — the latency the event spent parked
        in the inbox before its iteration started — in the
        ``hbbft_pump_segment_seconds`` histogram and the per-tx critical
        path."""
        self._inbox.append((kind, args, perf_counter()))
        if self._wake is not None:
            self._wake.set()

    def pending(self) -> int:
        return len(self._inbox)

    # -- the pump ------------------------------------------------------------

    async def _run(self) -> None:
        loop = asyncio.get_running_loop()
        tick = getattr(self.runtime, "pump_tick", None)
        try:
            while not self._stopped:
                if self.tick_s is None or self._wake.is_set():
                    # busy path: no timer — a wait_for here would mint a
                    # Task + TimerHandle per iteration, and that garbage
                    # churn alone measurably fattens p99 under load
                    await self._wake.wait()
                else:
                    handle = loop.call_later(self.tick_s, self._wake.set)
                    await self._wake.wait()
                    handle.cancel()
                self._wake.clear()
                while self._inbox and not self._stopped:
                    n = min(len(self._inbox), self.max_batch)
                    batch = [self._inbox.popleft() for _ in range(n)]
                    if self._cost_ewma > OFFLOAD_THRESHOLD_S:
                        self.offloaded += 1
                        outcome = await loop.run_in_executor(
                            self._executor, self.runtime.pump_process,
                            batch, self.pipeline_depth,
                        )
                    else:
                        outcome = self.runtime.pump_process(
                            batch, self.pipeline_depth
                        )
                    # outcome.cpu_s is the iteration's THREAD time: on a
                    # contended host, wall time would read preemption as
                    # "expensive work" and flip everything to the
                    # executor, where the extra thread churn makes the
                    # contention worse
                    self._cost_ewma = (
                        0.7 * self._cost_ewma + 0.3 * outcome.cpu_s
                    )
                    self.cpu_seconds += outcome.cpu_s
                    self.iterations += 1
                    self.runtime.pump_flush(outcome)
                if tick is not None:
                    # after the drain (or an idle timeout): the
                    # controller tick stays serialized with pump_process
                    # iterations, so its lever mutations (batch size,
                    # mempool ceilings) never race the proposer
                    tick()
        except asyncio.CancelledError:
            raise
        except BaseException as exc:
            # fatal in the consensus path: the runtime already journaled
            # (flight_crash in _absorb); record and re-raise so the node
            # process dies loudly instead of wedging silently
            self.error = exc
            logger.error("step pump died: %r", exc)
            raise

"""The deterministic in-process message-pump simulator.

Reference: ``tests/net/mod.rs :: VirtualNet / NetBuilder`` — the event loop
that owns message delivery for the sans-I/O protocol objects.  ``crank()``
delivers exactly one message (chosen by the adversary), feeds it to the
destination node, fans out the resulting ``Step.messages`` (resolving
``Target::All`` etc. against the membership), and records outputs and faults.

Faulty nodes here are *crash/byzantine-by-adversary*: their outgoing messages
pass through ``Adversary.tamper`` (which may rewrite or drop them), and they
can be driven by custom algorithms supplied via ``NetBuilder.faulty``.
"""

from __future__ import annotations

import heapq
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Hashable, List, Optional, Sequence, Tuple

from hbbft_tpu.fault_log import Fault, FaultLog
from hbbft_tpu.sim.adversary import Adversary, NullAdversary
from hbbft_tpu.traits import Step, TargetedMessage

NodeId = Hashable


class CrankError(Exception):
    """Limit exceeded (reference: ``tests/net/err.rs :: CrankError``)."""


@dataclass
class NetworkMessage:
    sender: NodeId
    to: NodeId
    payload: Any
    #: earliest virtual delivery time (set by link shaping; 0 = now).
    #: The cost model floors the receiver's clock here, so shaped
    #: latency shows up in per-cell virtual latency numbers.
    at: float = 0.0


@dataclass
class Node:
    node_id: NodeId
    algorithm: Any  # a ConsensusProtocol
    is_faulty: bool = False
    outputs: List[Any] = field(default_factory=list)
    faults_observed: FaultLog = field(default_factory=FaultLog)


class VirtualNet:
    def __init__(
        self,
        nodes: Dict[NodeId, Node],
        adversary: Optional[Adversary] = None,
        message_limit: Optional[int] = None,
        crank_limit: Optional[int] = None,
        trace: Optional["EventLog"] = None,
        cost_model: Optional["CostModel"] = None,
        observers: Optional[Dict[NodeId, Any]] = None,
        shaper: Optional[Any] = None,
    ):
        self.nodes = nodes
        self.queue: List[NetworkMessage] = []
        self.adversary = adversary or NullAdversary()
        self.message_limit = message_limit
        self.crank_limit = crank_limit
        self.messages_delivered = 0
        self.cranks = 0
        self.trace = trace
        self.cost_model = cost_model
        # the shared link-shaping hook (chaos.link.LinkShaper): shaped
        # messages wait in _held until the virtual clock reaches their
        # delivery time; [] from the shaper means the frame was dropped
        self.shaper = shaper
        self._held: List[Tuple[float, int, NetworkMessage]] = []
        self._held_seq = 0
        # messages removed by the adversary's network-level gate
        # (filter_message returning None) — censorship/eclipse/crash
        self.adversary_filtered = 0
        # per-node traits.StepObserver hooks (e.g. obs.spans.SpanTracer):
        # each delivery/input to node i is reported to observers[i]
        self.observers: Dict[NodeId, Any] = observers or {}
        # per-node clocks: nodes work in parallel, so simulated wall time is
        # the max over nodes, not the sum (mirrors the reference example's
        # per-node timing model)
        self.node_times: Dict[NodeId, float] = {}
        self.virtual_time = 0.0

    # -- topology -----------------------------------------------------------

    def node_ids(self) -> List[NodeId]:
        return sorted(self.nodes.keys(), key=repr)

    def correct_ids(self) -> List[NodeId]:
        return [n for n in self.node_ids() if not self.nodes[n].is_faulty]

    # -- driving ------------------------------------------------------------

    def send_input(self, node_id: NodeId, input: Any) -> None:
        """Feed an input to a node and fan out its step."""
        node = self.nodes[node_id]
        obs = self.observers.get(node_id)
        # with a cost model the virtual clock is meaningful: stamp the
        # ingress (and the step) with it so per-tx traces and spans
        # share the journal's timebase; without one, None → each
        # observer falls back to its own (logical) clock
        t = self.virtual_time if self.cost_model is not None else None
        if obs is not None:
            on_input = getattr(obs, "on_input", None)
            if on_input is not None:
                on_input(node_id, input, t)
        step = node.algorithm.handle_input(input)
        if obs is not None:
            obs.on_step(step, t)
        self._process_step(node, step)

    def crank(self) -> Optional[NetworkMessage]:
        """Deliver exactly one message; None if nothing is deliverable
        (both the live queue and the shaper's held set are empty)."""
        self.adversary.pre_crank(self)
        self._release_due()
        if not self.queue:
            if not self._held:
                return None
            # every in-flight message is future-dated (a shaped lull):
            # event-driven clock jump to the earliest delivery time
            self.virtual_time = self._held[0][0]
            self._release_due()
        self.cranks += 1
        if self.crank_limit is not None and self.cranks > self.crank_limit:
            raise CrankError(f"crank limit {self.crank_limit} exceeded")
        idx = self.adversary.pick_message(self)
        msg = self.queue.pop(idx)
        dest = self.nodes.get(msg.to)
        if dest is None:
            return msg
        nbytes = 0
        t_deliver: Optional[float] = None
        if self.trace is not None or self.cost_model is not None:
            from hbbft_tpu.sim.trace import wire_size

            nbytes = wire_size(msg.payload)
            if self.cost_model is not None:
                # the virtual delivery time is charged BEFORE the handler
                # runs, so observers (spans, per-tx traces) stamp events
                # with the time they happened on the virtual clock — the
                # deterministic-timestamp half of obs.critpath
                t_deliver = max(self.node_times.get(msg.to, 0.0), msg.at) \
                    + self.cost_model.charge(nbytes)
        obs = self.observers.get(msg.to)
        if obs is not None:
            obs.on_message(msg.sender, msg.payload, t_deliver)
        step = dest.algorithm.handle_message(msg.sender, msg.payload)
        if obs is not None:
            obs.on_step(step, t_deliver)
        self._process_step(dest, step)
        self.messages_delivered += 1
        if t_deliver is not None:
            self.node_times[msg.to] = t_deliver
            self.virtual_time = max(self.virtual_time, t_deliver)
        if self.trace is not None:
            from hbbft_tpu.sim.trace import CrankEvent, msg_type_path

            self.trace.record(CrankEvent(
                crank=self.cranks,
                sender=msg.sender,
                dest=msg.to,
                msg_type=msg_type_path(msg.payload),
                wire_bytes=nbytes,
                outputs=len(step.output),
                faults=len(step.fault_log),
                virtual_time=self.virtual_time,
            ))
        if (
            self.message_limit is not None
            and self.messages_delivered > self.message_limit
        ):
            raise CrankError(f"message limit {self.message_limit} exceeded")
        return msg

    def crank_until(
        self, pred: Callable[["VirtualNet"], bool], max_cranks: int = 1_000_000
    ) -> None:
        n = 0
        while not pred(self):
            if self.crank() is None:
                raise CrankError("queue drained before predicate held")
            n += 1
            if n > max_cranks:
                raise CrankError(f"predicate not reached in {max_cranks} cranks")

    @property
    def quiescent(self) -> bool:
        """Nothing left to deliver: the live queue AND the shaper's
        held set are both empty (time-triggered adversaries check this,
        not ``queue`` alone — shaped traffic in flight is not silence)."""
        return not self.queue and not self._held

    def run_to_quiescence(self) -> None:
        while self.crank() is not None:
            pass

    def close_observers(self) -> None:
        """Close any per-node observers that hold resources (the flight
        recorder flushes + finalizes its journal here)."""
        for obs in self.observers.values():
            close = getattr(obs, "close", None)
            if close is not None:
                close()

    # -- internals ----------------------------------------------------------

    def _release_due(self) -> None:
        """Move shaped messages whose delivery time has arrived from the
        held set into the live queue, in (ready, enqueue-seq) order."""
        held = self._held
        while held and held[0][0] <= self.virtual_time:
            _ready, _seq, msg = heapq.heappop(held)
            self.queue.append(msg)

    def _process_step(self, node: Node, step: Step) -> None:
        node.outputs.extend(step.output)
        node.faults_observed.extend(step.fault_log)
        all_ids = self.node_ids()
        for tm in step.messages:
            for dest in tm.target.resolve(all_ids, node.node_id):
                msg = NetworkMessage(node.node_id, dest, tm.message)
                if node.is_faulty:
                    tampered = self.adversary.tamper(self, msg)
                    if tampered is None:
                        continue
                    msg = tampered
                # network-level adversary gate: censorship, eclipse and
                # crash-stop apply to EVERY message, not just faulty
                # senders' (the async model's network IS the adversary)
                filtered = self.adversary.filter_message(self, msg)
                if filtered is None:
                    self.adversary_filtered += 1
                    continue
                self._enqueue(filtered)

    def _enqueue(self, msg: NetworkMessage) -> None:
        """The simulator side of the shared shaping hook: consult the
        LinkShaper (if any) per directed edge; future-dated copies wait
        in the held set until the virtual clock reaches them."""
        if self.shaper is not None:
            from hbbft_tpu.sim.trace import wire_size

            delays = self.shaper.shape_frame(
                msg.sender, msg.to, self.virtual_time,
                size_fn=lambda: wire_size(msg.payload))
            if delays is not None:
                for d in delays:
                    if d <= 0:
                        self.queue.append(msg)
                    else:
                        ready = self.virtual_time + d
                        self._held_seq += 1
                        heapq.heappush(
                            self._held,
                            (ready, self._held_seq,
                             NetworkMessage(msg.sender, msg.to,
                                            msg.payload, at=ready)))
                return
        self.queue.append(msg)


class NetBuilder:
    """Reference: ``tests/net/mod.rs :: NetBuilder``.

    ``using_step`` receives (node_id, netinfo_like) and returns the
    algorithm instance for that node.
    """

    def __init__(self, ids: Sequence[NodeId]):
        self.ids = list(ids)
        self._faulty: set = set()
        self._adversary: Optional[Adversary] = None
        self._message_limit: Optional[int] = None
        self._crank_limit: Optional[int] = None
        self._trace = None
        self._cost_model = None
        self._observer_factory: Optional[Callable[[NodeId], Any]] = None
        self._shaper = None

    def faulty(self, ids: Sequence[NodeId]) -> "NetBuilder":
        self._faulty = set(ids)
        return self

    def num_faulty(self, f: int) -> "NetBuilder":
        """Mark the first f ids faulty."""
        self._faulty = set(sorted(self.ids, key=repr)[:f])
        return self

    def adversary(self, adv: Adversary) -> "NetBuilder":
        self._adversary = adv
        return self

    def message_limit(self, n: int) -> "NetBuilder":
        self._message_limit = n
        return self

    def crank_limit(self, n: int) -> "NetBuilder":
        self._crank_limit = n
        return self

    def trace(self, log) -> "NetBuilder":
        """Attach an :class:`hbbft_tpu.sim.trace.EventLog`."""
        self._trace = log
        return self

    def cost_model(self, model) -> "NetBuilder":
        """Attach an :class:`hbbft_tpu.sim.trace.CostModel` (virtual clock)."""
        self._cost_model = model
        return self

    def shape(self, shape, seed: int = 0) -> "NetBuilder":
        """Attach link shaping — the simulator side of the shared hook
        (:mod:`hbbft_tpu.chaos.link`).  ``shape`` is a ``NetShape`` (or a
        prebuilt ``LinkShaper``); times are in VIRTUAL seconds, so pair
        this with :meth:`cost_model` so the virtual clock advances (the
        net still progresses without one — an all-held queue jumps the
        clock to the next delivery — but latency numbers mean nothing)."""
        from hbbft_tpu.chaos.link import LinkShaper

        self._shaper = (shape if isinstance(shape, LinkShaper)
                        else LinkShaper(shape, seed=seed))
        return self

    def observe(self, factory: Callable[[NodeId], Any]) -> "NetBuilder":
        """Attach one :class:`hbbft_tpu.traits.StepObserver` per node —
        ``factory(node_id)`` builds it (e.g. an ``obs.spans.SpanTracer``);
        the built observers are reachable as ``net.observers[node_id]``."""
        self._observer_factory = factory
        return self

    def flight(self, journal_root: str, **recorder_kwargs) -> "NetBuilder":
        """Attach a flight recorder per node: node ``i`` journals to
        ``<journal_root>/node-<i>`` with a **logical clock** (record
        sequence numbers), so the same deterministic schedule produces
        byte-identical journals — the tier-1 way to audit a full run
        offline (``python -m hbbft_tpu.obs.audit <journal_root>``).
        Call :meth:`VirtualNet.close_observers` when the run ends."""
        import os as _os

        from hbbft_tpu.obs.flight import FlightObserver, FlightRecorder
        from hbbft_tpu.obs.spans import SpanTracer

        def logical_clock():
            # per-node call counter: span timestamps must be as
            # deterministic as the journal's record clock
            state = [0.0]

            def clock() -> float:
                state[0] += 1.0
                return state[0]

            return clock

        def factory(nid: NodeId):
            rec = FlightRecorder(
                _os.path.join(journal_root, f"node-{nid}"),
                node=repr(nid), flavor="virtualnet", clock=None,
                **recorder_kwargs,
            )
            return FlightObserver(
                rec, spans=SpanTracer(node=nid, clock=logical_clock()))

        return self.observe(factory)

    def using_step(self, make_algo: Callable[[NodeId], Any]) -> VirtualNet:
        nodes = {
            nid: Node(
                node_id=nid,
                algorithm=make_algo(nid),
                is_faulty=nid in self._faulty,
            )
            for nid in self.ids
        }
        return VirtualNet(
            nodes,
            adversary=self._adversary,
            message_limit=self._message_limit,
            crank_limit=self._crank_limit,
            trace=self._trace,
            cost_model=self._cost_model,
            observers=(
                {nid: self._observer_factory(nid) for nid in self.ids}
                if self._observer_factory is not None else None
            ),
            shaper=self._shaper,
        )

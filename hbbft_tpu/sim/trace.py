"""Structured observability for the simulators (SURVEY §5).

The reference's only tracing is ``log`` crate debug lines plus the
simulated-hardware timing table of ``examples/simulation.rs``.  This module
provides both, structured:

- :class:`EventLog` — one record per crank (sender, destination, message
  type, wire size, outputs and faults produced), queryable and summable;
- :class:`CostModel` — the reference example's synthetic hardware knobs
  (per-message CPU lag + size/bandwidth charge) driving a virtual clock, so
  throughput numbers mean something without real networking.

``VirtualNet`` takes both as optional constructor arguments; the batched
simulator reports its per-epoch dense counters through the detail dict it
already returns.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Any, Dict, Hashable, List, Optional, Tuple

logger = logging.getLogger("hbbft_tpu.sim")


@dataclass
class CrankEvent:
    crank: int
    sender: Hashable
    dest: Hashable
    msg_type: str
    wire_bytes: int
    outputs: int
    faults: int
    virtual_time: float


@dataclass
class NetEvent:
    """One frame crossing the real transport (net/transport.py) — the
    socket-layer sibling of :class:`CrankEvent`.  ``direction`` is ``"send"``
    or ``"recv"`` from the recording node's perspective; ``kind`` is the
    frame-kind name (MSG/PING/TX/…); ``wire_bytes`` counts the framed size
    including the length prefix."""

    direction: str
    peer: Hashable
    kind: str
    wire_bytes: int
    t_mono: float


@dataclass
class EventLog:
    """Append-only per-crank event records with summary accessors.

    Net-frame counters live on an :mod:`hbbft_tpu.obs.metrics` registry
    (``hbbft_sim_net_*``, labeled kind × direction) — the by-kind accessor
    methods are thin views over those counters, and attaching a node's
    registry (``registry=``) makes the log's tallies scrapeable alongside
    that node's other metrics.  The raw event lists are retained for
    detailed queries."""

    events: List[CrankEvent] = field(default_factory=list)
    net_events: List[NetEvent] = field(default_factory=list)
    registry: Optional[Any] = None

    def __post_init__(self):
        if self.registry is None:
            from hbbft_tpu.obs.metrics import Registry

            self.registry = Registry()
        self._c_net_frames = self.registry.counter(
            "hbbft_sim_net_frames_total",
            "real-transport frames recorded by the event log",
            labelnames=("kind", "direction"),
        )
        self._c_net_bytes = self.registry.counter(
            "hbbft_sim_net_bytes_total",
            "framed bytes recorded by the event log",
            labelnames=("kind", "direction"),
        )

    def record(self, ev: CrankEvent) -> None:
        self.events.append(ev)
        logger.debug(
            "crank %d: %s→%s %s (%dB) outputs=%d faults=%d t=%.6f",
            ev.crank, ev.sender, ev.dest, ev.msg_type, ev.wire_bytes,
            ev.outputs, ev.faults, ev.virtual_time,
        )

    def record_net(self, ev: NetEvent) -> None:
        self.net_events.append(ev)
        self._c_net_frames.labels(kind=ev.kind,
                                  direction=ev.direction).inc()
        self._c_net_bytes.labels(kind=ev.kind,
                                 direction=ev.direction).inc(ev.wire_bytes)
        logger.debug(
            "net %s %s %s (%dB)", ev.direction, ev.peer, ev.kind,
            ev.wire_bytes,
        )

    def _sum_series(self, counter, direction: Optional[str] = None
                    ) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for labels, child in counter.series():
            if direction is not None and labels["direction"] != direction:
                continue
            k = labels["kind"]
            out[k] = out.get(k, 0) + int(child.get())
        return out

    def net_frames_by_kind(self) -> Dict[str, int]:
        return self._sum_series(self._c_net_frames)

    def net_bytes_by_kind(self) -> Dict[str, int]:
        return self._sum_series(self._c_net_bytes)

    def net_total_bytes(self, direction: Optional[str] = None) -> int:
        return sum(self._sum_series(self._c_net_bytes, direction).values())

    def messages_by_type(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for ev in self.events:
            out[ev.msg_type] = out.get(ev.msg_type, 0) + 1
        return out

    def bytes_by_type(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for ev in self.events:
            out[ev.msg_type] = out.get(ev.msg_type, 0) + ev.wire_bytes
        return out

    def total_bytes(self) -> int:
        return sum(ev.wire_bytes for ev in self.events)

    def __len__(self) -> int:
        return len(self.events)


@dataclass
class CostModel:
    """Reference ``examples/simulation.rs`` hardware model: delivering one
    message costs ``cpu_lag_s`` plus ``wire_bytes / bandwidth_bps``."""

    bandwidth_bps: float = 1e9
    cpu_lag_s: float = 1e-5
    # per-digest CPU charge for the proof-verification term of
    # batched_epoch_estimate (≈ one short SHA3-256 on a single core)
    hash_lag_s: float = 5e-7

    def charge(self, wire_bytes: int) -> float:
        return self.cpu_lag_s + 8.0 * wire_bytes / self.bandwidth_bps

    def batched_epoch_estimate(
        self, n: int, f: int, payload_bytes: int, aba_epochs: int
    ) -> float:
        """Virtual seconds for ONE bulk-synchronous HoneyBadger epoch.

        The batched simulator executes a whole communication round at once,
        so instead of per-crank charges it accrues the analytic PER-RECEIVER
        load (nodes receive in parallel; the epoch's virtual duration is one
        node's sequential receive work under this hardware model).  Counts
        per receiver, with N RBC instances and shard size B ≈ payload/k:

        - Value: N shards+proofs (one per instance addressed to us);
        - Echo: N instances × N sources, shard+proof each;
        - Ready: N × N digests;
        - per ABA epoch: N instances × N sources × 3 votes (BVal+Aux+Conf),
          charged at 8 framed bytes per vote (1 payload byte + wire/header
          overhead), and on coin epochs N×N 96-byte G2 shares — the coin
          term charges at least one coin epoch even when aba_epochs < 3,
          covering the schedule's mandatory first threshold-coin flip;
        - Merkle proof VERIFICATION compute: (depth+1) digests for each of
          the N×N received echo proofs (plus N Values).  The large-N
          full-delivery simulator path replaces per-receiver proof checks
          with a god-view commitment comparison (parallel/rbc.py::
          _run_large — the verify itself is the check a real receiver
          performs, SURVEY §3.2 HOT), so the work a deployment would do is
          charged HERE rather than silently dropped.
        """
        k = max(n - 2 * f, 1)
        shard = max(2, -(-(4 + payload_bytes) // k))
        depth = max(1, (n - 1).bit_length())
        proof = 32 * (depth + 1) + 16
        value_b = n * (shard + proof)
        echo_b = n * n * (shard + proof)
        ready_b = n * n * 40
        votes_b = aba_epochs * n * n * 3 * 8
        coin_b = max(aba_epochs // 3, 1) * n * n * 96
        msgs = (
            n + 2 * n * n + aba_epochs * n * n * 3
            + max(aba_epochs // 3, 1) * n * n
        )
        total_b = value_b + echo_b + ready_b + votes_b + coin_b
        verify_digests = (n * n + n) * (depth + 1)
        return (
            msgs * self.cpu_lag_s
            + 8.0 * total_b / self.bandwidth_bps
            + verify_digests * self.hash_lag_s
        )


_wire_size_failed_types: set = set()


def wire_size(payload: Any) -> int:
    """Canonical wire size of a protocol message.

    An encode failure still returns 0 (the crank loop must not die on an
    unencodable adversarial payload), but it is no longer silent: every
    failure increments ``hbbft_sim_wire_size_failures_total`` (labeled by
    the nested type path, on the process-wide default registry) and the
    offending type path is logged once — so EventLog byte totals can't
    under-report without leaving a trace."""
    import struct

    from hbbft_tpu.protocols import wire

    try:
        return len(wire.encode_message(payload))
    except (TypeError, ValueError, struct.error) as exc:
        from hbbft_tpu.obs.metrics import DEFAULT

        path = msg_type_path(payload)
        DEFAULT.counter(
            "hbbft_sim_wire_size_failures_total",
            "messages whose wire size could not be computed "
            "(byte totals under-report by these)",
            labelnames=("type",),
        ).labels(type=path).inc()
        if path not in _wire_size_failed_types:
            _wire_size_failed_types.add(path)
            logger.warning(
                "wire_size: cannot encode %s (%s) — counting as 0 bytes; "
                "EventLog byte totals under-report this type", path, exc,
            )
        return 0


def msg_type_path(payload: Any) -> str:
    """Type path through nested wrappers, e.g.
    ``HbWrap/SubsetWrap/BroadcastWrap/EchoMsg`` — the outermost name alone
    would put every DHB message in one uninformative bucket."""
    parts = []
    seen = 0
    while payload is not None and seen < 8:
        parts.append(type(payload).__name__)
        payload = getattr(payload, "msg", None)
        seen += 1
    return "/".join(parts)

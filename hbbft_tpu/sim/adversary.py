"""Delivery-schedule adversaries (reference: ``tests/net/adversary.rs``).

An adversary controls the order in which queued messages are delivered and
may tamper with or inject messages.  The BFT protocols must stay correct
under *any* schedule, so tests run each suite under several of these.
"""

from __future__ import annotations

import random
from typing import TYPE_CHECKING, List, Optional

if TYPE_CHECKING:
    from hbbft_tpu.sim.virtual_net import NetworkMessage, VirtualNet


class Adversary:
    """Base: FIFO delivery, no tampering.

    ``pick_message`` returns the index into the queue to deliver next;
    ``tamper`` may rewrite a message addressed from/to a faulty node.
    Reference: ``trait Adversary { pre_crank, tamper }``.
    """

    def pick_message(self, net: "VirtualNet") -> int:
        return 0

    def tamper(self, net: "VirtualNet", msg: "NetworkMessage") -> Optional["NetworkMessage"]:
        """Return a replacement for a message sent BY a faulty node (or None
        to drop it).  Only called for messages from faulty senders."""
        return msg


class NullAdversary(Adversary):
    """Honest FIFO scheduler."""


class NodeOrderAdversary(Adversary):
    """Delivers messages grouped by destination node id (lowest first).

    Reference: ``NodeOrderAdversary`` — exposes ordering assumptions.
    """

    def pick_message(self, net: "VirtualNet") -> int:
        order = {nid: i for i, nid in enumerate(sorted(net.node_ids(), key=repr))}
        best, best_key = 0, None
        for i, m in enumerate(net.queue):
            k = order.get(m.to, len(order))
            if best_key is None or k < best_key:
                best, best_key = i, k
        return best


class ReorderingAdversary(Adversary):
    """Deterministically swaps pairs of queued messages before delivery."""

    def __init__(self, seed: int = 0):
        self.rng = random.Random(seed)

    def pick_message(self, net: "VirtualNet") -> int:
        if len(net.queue) >= 2 and self.rng.random() < 0.5:
            return 1
        return 0


class RandomAdversary(Adversary):
    """Random delivery order with occasional duplication of messages.

    Reference: ``RandomAdversary`` — random schedule plus message replays;
    protocols must be idempotent against duplicates.
    """

    def __init__(self, seed: int = 0, dup_prob: float = 0.05):
        self.rng = random.Random(seed)
        self.dup_prob = dup_prob

    def pick_message(self, net: "VirtualNet") -> int:
        i = self.rng.randrange(len(net.queue))
        if self.rng.random() < self.dup_prob:
            # duplicate: re-enqueue a copy before delivery
            net.queue.append(net.queue[i])
        return i

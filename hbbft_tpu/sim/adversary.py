"""Delivery-schedule adversaries (reference: ``tests/net/adversary.rs``).

An adversary controls the order in which queued messages are delivered and
may tamper with or inject messages.  The BFT protocols must stay correct
under *any* schedule, so tests run each suite under several of these.
"""

from __future__ import annotations

import random
from typing import TYPE_CHECKING, List, Optional

if TYPE_CHECKING:
    from hbbft_tpu.sim.virtual_net import NetworkMessage, VirtualNet


class Adversary:
    """Base: FIFO delivery, no tampering.

    ``pick_message`` returns the index into the queue to deliver next;
    ``tamper`` may rewrite a message addressed from/to a faulty node.
    Reference: ``trait Adversary { pre_crank, tamper }``.

    Two network-level hooks beyond the reference trait:

    - ``filter_message`` is consulted for EVERY enqueued message (not
      just faulty senders') — returning None removes it from the network.
      Censorship, eclipse and crash-stop adversaries live here: in the
      asynchronous model the network itself is adversarial;
    - ``pre_crank`` runs at the start of every crank (before delivery),
      so time-triggered behavior (heals, releases) can fire even when
      the live queue has momentarily drained.
    """

    def pick_message(self, net: "VirtualNet") -> int:
        return 0

    def pre_crank(self, net: "VirtualNet") -> None:
        """Called at the start of every crank, before delivery."""

    def tamper(self, net: "VirtualNet", msg: "NetworkMessage") -> Optional["NetworkMessage"]:
        """Return a replacement for a message sent BY a faulty node (or None
        to drop it).  Only called for messages from faulty senders."""
        return msg

    def filter_message(self, net: "VirtualNet",
                       msg: "NetworkMessage") -> Optional["NetworkMessage"]:
        """Network-level gate over every enqueued message; None removes
        it (counted in ``net.adversary_filtered``)."""
        return msg


class NullAdversary(Adversary):
    """Honest FIFO scheduler."""


class NodeOrderAdversary(Adversary):
    """Delivers messages grouped by destination node id (lowest first).

    Reference: ``NodeOrderAdversary`` — exposes ordering assumptions.
    """

    def pick_message(self, net: "VirtualNet") -> int:
        order = {nid: i for i, nid in enumerate(sorted(net.node_ids(), key=repr))}
        best, best_key = 0, None
        for i, m in enumerate(net.queue):
            k = order.get(m.to, len(order))
            if best_key is None or k < best_key:
                best, best_key = i, k
        return best


class ReorderingAdversary(Adversary):
    """Deterministically swaps pairs of queued messages before delivery."""

    def __init__(self, seed: int = 0):
        self.rng = random.Random(seed)

    def pick_message(self, net: "VirtualNet") -> int:
        if len(net.queue) >= 2 and self.rng.random() < 0.5:
            return 1
        return 0


class MitmDelayAdversary(Adversary):
    """Man-in-the-middle delay schedule against binary agreement.

    Reference: ``tests/binary_agreement_mitm.rs`` — the Moumen-style attack:
    hold back every message to/from a targeted node for as long as the
    budget allows so its estimate keeps lagging the coin.  With a threshold
    (unpredictable) coin the protocol must still terminate; a predictable
    coin could be stalled forever.

    ``max_delay`` is the hold budget (consecutive cranks the target is
    starved).  The no-arg default stays the historical fixed 200; passing
    ``max_delay=None`` draws the budget from the seeded RNG instead
    (uniform in [50, 500]) so campaign cells sweep it with their scenario
    seed rather than all probing one magic number.
    """

    def __init__(self, target, max_delay: Optional[int] = 200,
                 seed: int = 0):
        self.target = target
        self.rng = random.Random(seed)
        if max_delay is None:
            max_delay = 50 + self.rng.randrange(0, 451)
        self.max_delay = max_delay
        self._held = 0

    def pick_message(self, net: "VirtualNet") -> int:
        others = [
            i for i, m in enumerate(net.queue)
            if m.to != self.target and m.sender != self.target
        ]
        if others and self._held < self.max_delay:
            self._held += 1
            return self.rng.choice(others)
        self._held = 0
        return self.rng.randrange(len(net.queue))


class EquivocatingAdversary(Adversary):
    """A faulty node equivocates: the Merkle root carried by its
    root-bearing broadcast messages (Ready/EchoHash/CanDecode, and the
    proof roots of Value/Echo) is rewritten for HALF its peers, so odd-
    and even-indexed destinations observe conflicting values for the same
    RBC slot.  Delivery order stays FIFO — the point is not scheduling
    pressure but producing the receiver-side evidence the forensic
    auditor (``hbbft_tpu.obs.audit``) must reconstruct: two journals
    holding different roots from one sender for one slot, keyed to the
    ``Multiple*`` FaultKind family.

    Deterministic (no RNG): the same run yields the same tampered bytes,
    which the audit byte-identity tests rely on.
    """

    def tamper(self, net: "VirtualNet", msg: "NetworkMessage"):
        from hbbft_tpu.sim.virtual_net import NetworkMessage

        order = sorted(net.node_ids(), key=repr)
        if order.index(msg.to) % 2 == 0:
            return msg  # even destinations see the honest value
        flipped = _flip_roots(msg.payload)
        if flipped is None:
            return msg
        return NetworkMessage(msg.sender, msg.to, flipped)


def _flip_roots(msg):
    """A copy of ``msg`` with every embedded 32-byte broadcast root's
    last bit flipped (walking the wrapper chain); None if the message
    carries no root."""
    import dataclasses

    from hbbft_tpu.protocols.broadcast import (
        CanDecodeMsg, EchoHashMsg, EchoMsg, ReadyMsg, ValueMsg,
    )

    def flip(root: bytes) -> bytes:
        return root[:-1] + bytes([root[-1] ^ 1])

    if isinstance(msg, (ReadyMsg, EchoHashMsg, CanDecodeMsg)):
        return type(msg)(flip(msg.root))
    if isinstance(msg, (ValueMsg, EchoMsg)):
        proof = dataclasses.replace(msg.proof,
                                    root_hash=flip(msg.proof.root_hash))
        return type(msg)(proof)
    if dataclasses.is_dataclass(msg) and hasattr(msg, "msg"):
        inner = _flip_roots(msg.msg)
        if inner is None:
            return None
        return dataclasses.replace(msg, msg=inner)
    return None


class RandomAdversary(Adversary):
    """Random delivery order with duplication, INJECTION, and TAMPERING.

    Reference: ``RandomAdversary`` — random schedule plus replays, randomly
    mutated copies of in-flight messages re-sent under faulty identities,
    and field-level tampering of faulty nodes' outgoing messages.  Correct
    nodes must treat all of it as noise: at worst the culprits land in
    fault logs; agreement/termination must be unaffected.
    """

    def __init__(self, seed: int = 0, dup_prob: float = 0.05,
                 inject_prob: float = 0.05, tamper_prob: float = 0.3):
        self.rng = random.Random(seed)
        self.dup_prob = dup_prob
        self.inject_prob = inject_prob
        self.tamper_prob = tamper_prob

    def pick_message(self, net: "VirtualNet") -> int:
        from hbbft_tpu.sim.virtual_net import NetworkMessage

        i = self.rng.randrange(len(net.queue))
        if self.rng.random() < self.dup_prob:
            # duplicate: re-enqueue a copy before delivery
            net.queue.append(net.queue[i])
        faulty = [n for n in net.node_ids() if net.nodes[n].is_faulty]
        if faulty and self.rng.random() < self.inject_prob:
            # inject: a mutated copy of a random in-flight message, re-sent
            # under a faulty identity to a random destination
            src = self.rng.choice(faulty)
            template = self.rng.choice(net.queue)
            payload = self._mutate(template.payload)
            dst = self.rng.choice(net.node_ids())
            net.queue.append(NetworkMessage(src, dst, payload))
        return i

    def tamper(self, net: "VirtualNet", msg: "NetworkMessage"):
        """Faulty senders' messages: drop some, corrupt some fields."""
        from hbbft_tpu.sim.virtual_net import NetworkMessage

        roll = self.rng.random()
        if roll < self.tamper_prob / 3:
            return None  # drop
        if roll < self.tamper_prob:
            return NetworkMessage(
                msg.sender, msg.to, self._mutate(msg.payload)
            )
        return msg

    def _mutate(self, msg):
        """Type-aware field corruption of protocol messages (falls back to
        the original object for unknown/deeply-nested types)."""
        import dataclasses

        from hbbft_tpu.protocols.binary_agreement import (
            AuxMsg, BValMsg, ConfMsg, TermMsg,
        )
        from hbbft_tpu.protocols.broadcast import (
            CanDecodeMsg, EchoHashMsg, EchoMsg, ReadyMsg, ValueMsg,
        )

        r = self.rng
        if isinstance(msg, (BValMsg, AuxMsg)):
            if r.random() < 0.5:
                return dataclasses.replace(msg, value=not msg.value)
            return dataclasses.replace(msg, epoch=msg.epoch + r.randrange(1, 3))
        if isinstance(msg, TermMsg):
            return dataclasses.replace(msg, value=not msg.value)
        if isinstance(msg, ConfMsg):
            return dataclasses.replace(
                msg, values=frozenset([r.random() < 0.5])
            )
        if isinstance(msg, (ReadyMsg, EchoHashMsg, CanDecodeMsg)):
            root = bytearray(msg.root)
            root[r.randrange(len(root))] ^= 1 << r.randrange(8)
            return type(msg)(bytes(root))
        if isinstance(msg, (ValueMsg, EchoMsg)):
            proof = msg.proof
            value = bytearray(proof.value)
            if value:
                value[r.randrange(len(value))] ^= 1 << r.randrange(8)
            bad = dataclasses.replace(proof, value=bytes(value))
            return type(msg)(bad)
        if dataclasses.is_dataclass(msg) and hasattr(msg, "msg"):
            try:
                return dataclasses.replace(msg, msg=self._mutate(msg.msg))
            except Exception:
                return msg
        return msg


class TargetedDelayAdversary(Adversary):
    """Targeted message-delay against a SET of victims.

    The zoo generalization of :class:`MitmDelayAdversary`: while a seeded
    hold budget lasts, any message to or from a victim is starved (other
    traffic is delivered first); when the budget runs out the backlog
    floods through at once, and the cycle repeats.  Exposes ordering /
    staleness assumptions without dropping anything.
    """

    def __init__(self, targets, max_hold: Optional[int] = None,
                 seed: int = 0):
        self.targets = set(targets)
        self.rng = random.Random(seed)
        if max_hold is None:
            max_hold = 40 + self.rng.randrange(0, 261)
        self.max_hold = max_hold
        self._held = 0

    def pick_message(self, net: "VirtualNet") -> int:
        others = [
            i for i, m in enumerate(net.queue)
            if m.to not in self.targets and m.sender not in self.targets
        ]
        if others and self._held < self.max_hold:
            self._held += 1
            return self.rng.choice(others)
        self._held = 0
        return self.rng.randrange(len(net.queue))


class CensorshipAdversary(Adversary):
    """Selective censorship by message type and/or peer.

    Messages matching EVERY given criterion (type name anywhere in the
    wrapper chain; sender in ``senders``; destination in ``dests``; a
    ``None`` criterion matches anything) are removed from the network —
    up to a seeded budget, so liveness pressure is real but bounded and
    the protocol's recovery after the censor exhausts itself is part of
    the scenario.  Censored drops are counted both here (``censored``)
    and on the net (``adversary_filtered``).
    """

    def __init__(self, msg_types=(), senders=None, dests=None,
                 budget: Optional[int] = None, seed: int = 0):
        self.msg_types = frozenset(msg_types)
        self.senders = None if senders is None else set(senders)
        self.dests = None if dests is None else set(dests)
        self.rng = random.Random(seed)
        if budget is None:
            budget = 50 + self.rng.randrange(0, 451)
        self.budget = budget
        self.censored = 0

    def filter_message(self, net: "VirtualNet", msg: "NetworkMessage"):
        if self.censored >= self.budget:
            return msg
        if self.senders is not None and msg.sender not in self.senders:
            return msg
        if self.dests is not None and msg.to not in self.dests:
            return msg
        if self.msg_types:
            from hbbft_tpu.sim.trace import msg_type_path

            parts = set(msg_type_path(msg.payload).split("/"))
            if not (parts & self.msg_types):
                return msg
        self.censored += 1
        return None


class EclipseAdversary(Adversary):
    """Eclipse one CORRECT node: every message to or from the victim is
    HELD (not dropped) until the heal, then the backlog is re-injected —
    the victim is cut off while the rest of the cluster makes progress,
    and must catch up from the flood afterwards.

    The heal fires at ``heal_crank`` — or earlier, the moment the rest of
    the network goes QUIESCENT (``net.quiescent``: nothing left in the
    live queue or the shaper's held set — link-shaped traffic in flight
    is not silence), so an eclipse can never deadlock a run whose only
    remaining traffic is the held backlog.  Deterministic: no RNG at all.
    """

    def __init__(self, victim, heal_crank: int):
        self.victim = victim
        self.heal_crank = heal_crank
        self.healed = False
        self._held: List["NetworkMessage"] = []

    def pending(self) -> int:
        return len(self._held)

    def filter_message(self, net: "VirtualNet", msg: "NetworkMessage"):
        if not self.healed and (msg.to == self.victim
                                or msg.sender == self.victim):
            self._held.append(msg)
            return None
        return msg

    def pre_crank(self, net: "VirtualNet") -> None:
        if not self.healed and (net.cranks >= self.heal_crank
                                or net.quiescent):
            self.healed = True
            net.queue.extend(self._held)
            self._held.clear()


class VoteStormAdversary(Adversary):
    """Membership-vote storms: drives DynamicHoneyBadger era rotations
    (and vote chaos) WHILE the link layer is doing its worst.

    On a seeded schedule — a crank threshold, or the moment the network
    goes quiescent, whichever comes first — every correct validator is
    fed a :class:`~hbbft_tpu.protocols.dynamic_honey_badger.ChangeInput`:

    - **coordinated waves** alternate removing and re-adding a victim
      validator, each winning vote starting a REAL SyncKeyGen DKG and
      rotating the era — composed with ``partition-10s`` link shaping
      this is a DKG rotation riding out a partition, ROADMAP item 4's
      named next step;
    - **split waves** (seeded coin) hand half the validators a remove
      vote and half a keep vote: no majority, no rotation, just vote
      traffic piggy-backing on every contribution until a later
      coordinated wave supersedes it (``VoteCounter``'s later-vote-wins
      pressure).

    Deterministic per seed: the schedule depends only on crank counts,
    quiescence, and the seeded RNG.  Injection counts are exposed
    (``waves``, ``injected``) and rotations are visible to the auditor
    as era changes in the committed batches — a clean cell must commit
    across every boundary with all chains agreeing.
    """

    def __init__(self, seed: int = 0, first_crank: int = 300,
                 min_gap: int = 600, max_waves: int = 4, victim=None):
        self.rng = random.Random(seed)
        self.min_gap = min_gap
        self.max_waves = max_waves
        self.victim = victim
        self.waves = 0
        self.injected = 0
        self._next_at = first_crank
        self._removed = False
        self._victim_pk = None

    def pre_crank(self, net: "VirtualNet") -> None:
        if self.waves >= self.max_waves:
            return
        if net.cranks < self._next_at and not (net.quiescent
                                               and net.cranks > 0):
            return
        from hbbft_tpu.protocols.dynamic_honey_badger import (
            Change, ChangeInput,
        )

        correct = net.correct_ids()
        probe = net.nodes[correct[0]].algorithm
        dhb = getattr(probe, "dhb", probe)
        if dhb.change_state.state != "none":
            # a DKG is already in flight — let it rotate before storming
            # again (retry shortly; quiescence keeps the run alive)
            self._next_at = net.cranks + 200
            return
        keys = dict(dhb.netinfo.public_key_map())
        victim = self.victim if self.victim is not None else correct[-1]
        self.waves += 1
        self._next_at = net.cranks + self.min_gap
        split = self.rng.random() < 0.34
        if not self._removed:
            if victim not in keys:
                return  # victim vanished from the key map: nothing to do
            self._victim_pk = keys[victim]
            target = {k: v for k, v in keys.items() if k != victim}
        else:
            target = dict(keys)
            target[victim] = self._victim_pk
        change = Change.node_change(target)
        if split:
            keep = Change.node_change(keys)
            for i, nid in enumerate(correct):
                net.send_input(
                    nid, ChangeInput(change if i % 2 == 0 else keep))
                self.injected += 1
            return
        self._removed = not self._removed
        for nid in correct:
            net.send_input(nid, ChangeInput(change))
            self.injected += 1


class FloodAdversary(Adversary):
    """Max-rate valid-frame spam from one peer (overload defense drill).

    Every message the flooder emits is amplified ``copies``-fold, and
    each crank the flooder re-injects duplicates of its own in-flight
    traffic — all of it VALID protocol messages, the flood shape a
    budget guard cannot reject on content.  Correct nodes must keep
    committing with every per-peer buffer pinned under its cap: the
    protocols treat duplicates as no-ops, the queues absorb the burst,
    and nothing grows without bound.  The injection budget is seeded so
    the run terminates and replays byte-identically.
    """

    def __init__(self, flooder, seed: int = 0, copies: int = 3,
                 budget: Optional[int] = None):
        self.flooder = flooder
        self.rng = random.Random(seed)
        self.copies = copies
        if budget is None:
            budget = 2_000 + self.rng.randrange(0, 2_001)
        self.budget = budget
        self.injected = 0

    def filter_message(self, net: "VirtualNet", msg: "NetworkMessage"):
        if msg.sender == self.flooder and self.injected < self.budget:
            for _ in range(self.copies):
                if self.injected >= self.budget:
                    break
                net.queue.append(msg)
                self.injected += 1
        return msg

    def pre_crank(self, net: "VirtualNet") -> None:
        if self.injected >= self.budget:
            return
        mine = [m for m in net.queue if m.sender == self.flooder]
        if mine:
            net.queue.append(self.rng.choice(mine))
            self.injected += 1


class SpoofReplayAdversary(FloodAdversary):
    """Replay-as-spoof: the in-sim analog of identity spoofing.

    Under the authenticated transport an attacker cannot FORGE a
    validator's messages — the strongest impersonation left is
    replaying byte-identical copies of messages the victim genuinely
    sent.  Every crank this adversary re-injects seeded duplicates of
    the victim's in-flight traffic (and amplifies fresh emissions
    ``copies``-fold), exactly :class:`FloodAdversary`'s mechanics but
    with an HONEST victim: the protocols treat duplicates as no-ops,
    every node keeps committing, and the cell verdict must stay CLEAN —
    the replayed victim did nothing wrong and must never be blamed for
    traffic it sent once (``spec.faulty`` excludes it)."""

    def __init__(self, victim, seed: int = 0, copies: int = 2,
                 budget: Optional[int] = None):
        super().__init__(victim, seed=seed, copies=copies, budget=budget)
        self.victim = victim


class FutureEpochSpamAdversary(Adversary):
    """Window-edge protocol spam: the spammer injects binary-agreement
    messages addressed to epochs at ``hb.epoch + max_future_epochs`` —
    the farthest epoch a correct node must still accept — with ABA
    epochs fanned across the ABA future window, forcing the receivers'
    future-epoch buffers toward their caps.

    Correct nodes must keep committing, every BA ``future`` buffer must
    stay ≤ ``future_cap_per_sender`` (overflow front-evicts the
    spammer's own entries, counted), and HoneyBadger's per-sender
    future-epoch budget must absorb the rest.  Deterministic per seed.
    """

    def __init__(self, spammer, seed: int = 0, per_wave: int = 40,
                 budget: Optional[int] = None):
        self.spammer = spammer
        self.rng = random.Random(seed)
        self.per_wave = per_wave
        if budget is None:
            # sized so EACH victim's share of the stream exceeds the
            # HoneyBadger per-sender future-epoch budget (the drill must
            # actually make the defense engage, not just approach it)
            budget = 6_000 + self.rng.randrange(0, 3_001)
        self.budget = budget
        self.injected = 0

    def pre_crank(self, net: "VirtualNet") -> None:
        if self.injected >= self.budget or not net.queue:
            return
        from hbbft_tpu.protocols.binary_agreement import (
            AuxMsg, BValMsg,
        )
        from hbbft_tpu.protocols.dynamic_honey_badger import HbWrap
        from hbbft_tpu.protocols.honey_badger import SubsetWrap
        from hbbft_tpu.protocols.sender_queue import AlgoMessage
        from hbbft_tpu.protocols.subset import AgreementWrap
        from hbbft_tpu.sim.virtual_net import NetworkMessage

        correct = net.correct_ids()
        probe = net.nodes[correct[0]].algorithm
        algo = getattr(probe, "algo", probe)          # unwrap SenderQueue
        sender_queued = algo is not probe
        dhb = getattr(algo, "dhb", algo)
        hb = getattr(dhb, "hb", dhb)
        era = getattr(dhb, "era", 0)
        edge = hb.epoch + hb.max_future_epochs        # window edge
        proposers = sorted(net.node_ids(), key=repr)
        for _ in range(self.per_wave):
            if self.injected >= self.budget:
                return
            proposer = proposers[self.rng.randrange(len(proposers))]
            aba_epoch = self.rng.randrange(1, 17)     # BA future window
            cls = BValMsg if self.rng.random() < 0.5 else AuxMsg
            inner = cls(aba_epoch, bool(self.rng.randrange(2)))
            payload = HbWrap(era, SubsetWrap(
                edge, AgreementWrap(proposer, inner)))
            if sender_queued:
                payload = AlgoMessage(payload)
            # the same spam hits EVERY correct node, so each receiver's
            # per-sender budget sees the full stream
            for victim in correct:
                if self.injected >= self.budget:
                    return
                net.queue.append(
                    NetworkMessage(self.spammer, victim, payload))
                self.injected += 1


class GarbageStreamAdversary:
    """Framing-valid, decode-invalid byte streams against a REAL node.

    The socket-kind sibling of :class:`FloodAdversary`: it dials a live
    node's port, completes a node-role hello under a CLAIMED validator
    identity (the transport's documented trust boundary — identification,
    not authentication), then streams MSG frames whose payloads are
    seeded random bytes — every frame passes the length-prefix framing
    layer, every payload fails ``wire.decode_message``.  The victim must
    count each one (``decode_failures`` + guard decode strikes), keep
    committing, and eventually disconnect the stream with a counted
    backoff (``hbbft_guard_ingress_disconnects_total``), which this
    driver observes as connection resets.

    With ``valid_frames=True`` the payloads are instead well-formed
    ``EpochStarted`` announcements — max-rate VALID-frame spam, the
    socket realization of :class:`FloodAdversary`: the byte budget and
    in-flight frame caps are then the only defense that can engage.
    """

    def __init__(self, seed: int = 0, budget_frames: int = 20_000,
                 frame_bytes: int = 256, valid_frames: bool = False,
                 secret_key=None):
        self.rng = random.Random(seed)
        self.budget_frames = budget_frames
        self.frame_bytes = frame_bytes
        self.valid_frames = valid_frames
        # the claimed identity's plain BLS secret key: with it, the
        # drill models a COMPROMISED validator — the handshake
        # challenge is answered correctly and the flood proceeds past
        # an authenticating victim; without it, an auth-enabled victim
        # refuses the hello outright (that refusal is
        # IdentitySpoofAdversary's drill, not this one's)
        self.secret_key = secret_key
        self.frames_sent = 0
        self.bytes_sent = 0
        # connection teardowns observed, INCLUDING hellos refused
        # during the victim's guard backoff window (a refused hello
        # surfaces as the socket closing before any reply — the
        # victim-side hbbft_guard_hello_rejects_total counter is the
        # authoritative per-cause ledger)
        self.disconnects = 0

    def _frame(self) -> bytes:
        from hbbft_tpu.net import framing

        if self.valid_frames:
            if not hasattr(self, "_valid_frame"):
                from hbbft_tpu.protocols import wire
                from hbbft_tpu.protocols.sender_queue import EpochStarted

                # one MSG_BATCH frame carrying hundreds of well-formed
                # EpochStarted announcements: a single socket write
                # floods the victim with valid frames faster than the
                # write path alone ever could
                enc = wire.encode_message(EpochStarted((0, 0)))
                self._valid_frame = framing.pack_msgs(
                    [enc] * 512, framing.DEFAULT_MAX_FRAME)[0]
            return self._valid_frame
        return framing.encode_frame(
            framing.MSG,
            bytes(self.rng.randrange(256)
                  for _ in range(self.frame_bytes)),
            framing.DEFAULT_MAX_FRAME)

    async def run(self, addr, cluster_id: bytes, identity,
                  duration_s: float = 10.0, era: int = 0) -> None:
        """Flood ``addr`` claiming ``identity`` until the frame budget
        or ``duration_s`` runs out, reconnecting through disconnects."""
        import asyncio
        import time as _time

        from hbbft_tpu.net import framing

        deadline = _time.monotonic() + duration_s
        while (self.frames_sent < self.budget_frames
               and _time.monotonic() < deadline):
            try:
                reader, writer = await asyncio.wait_for(
                    asyncio.open_connection(*addr), 2.0)
            except (OSError, asyncio.TimeoutError):
                await asyncio.sleep(0.05)
                continue
            try:
                hello = framing.Hello(
                    node_id=identity, role=framing.ROLE_NODE,
                    cluster_id=bytes(cluster_id), era=era, epoch=0)
                writer.write(framing.encode_frame(
                    framing.HELLO, framing.encode_hello(hello),
                    framing.DEFAULT_MAX_FRAME))
                await writer.drain()
                kind, payload = await asyncio.wait_for(
                    framing.read_one_frame(
                        reader, framing.DEFAULT_MAX_FRAME), 2.0)
                if kind == framing.CHALLENGE:
                    # authenticated victim: with the compromised key the
                    # challenge is answered properly (the flood drill
                    # continues past the handshake); without it this
                    # connection is already lost — surface the refusal
                    if self.secret_key is None:
                        raise ConnectionError(
                            "victim demands auth and no key was given")
                    nonce, session = framing.decode_challenge(payload)
                    transcript = framing.auth_transcript(
                        bytes(cluster_id), nonce, session, identity,
                        framing.ROLE_NODE, era)
                    sig = self.secret_key.sign(transcript).to_bytes()
                    writer.write(framing.encode_frame(
                        framing.AUTH, framing.encode_auth(era, sig),
                        framing.DEFAULT_MAX_FRAME))
                    await writer.drain()
                    kind, payload = await asyncio.wait_for(
                        framing.read_one_frame(
                            reader, framing.DEFAULT_MAX_FRAME), 2.0)
                if kind != framing.HELLO:
                    raise ConnectionError(
                        f"unexpected reply kind {kind}")
                while (self.frames_sent < self.budget_frames
                       and _time.monotonic() < deadline):
                    if writer.is_closing():
                        raise ConnectionError("stream torn down")
                    frame = self._frame()
                    writer.write(frame)
                    self.frames_sent += 1
                    self.bytes_sent += len(frame)
                    if self.frames_sent % 16 == 0:
                        await asyncio.wait_for(writer.drain(), 5.0)
            except (OSError, asyncio.TimeoutError,
                    asyncio.IncompleteReadError, ConnectionError):
                # the guard tore the stream down (or refused the hello
                # during its backoff window): the defense engaging IS
                # the observable — count it and press on
                self.disconnects += 1
                await asyncio.sleep(0.1)
            finally:
                writer.close()


class IdentitySpoofAdversary:
    """Raw-socket identity theft against an AUTHENTICATED node.

    Dials a live node's port claiming a CORRECT validator identity in
    the hello, then fails the challenge–response in one of four ways:

    - ``nokey``: answers the CHALLENGE with seeded random bytes where
      the era-key signature belongs (an attacker holding no key
      material at all);
    - ``wrongkey``: signs the exact transcript with a DIFFERENT secret
      key (compromised non-validator key trying to impersonate);
    - ``hijack``: skips AUTH entirely and streams a protocol MSG frame
      in its place (inject-before-the-challenge-completes, the
      session-hijack shape);
    - ``downgrade``: signs with the wrong key while claiming an
      ancient era (an era-downgrade probe at the rotation grace
      window).

    The victim must refuse every attempt BEFORE allocating any
    per-peer state: zero spoofed frames reach the protocol, the
    impersonated validator's budgets/strikes stay untouched, and every
    refusal is counted (``hbbft_guard_auth_failures_total``) and
    journaled with the ATTACKER's endpoint — never the victim's.  From
    outside, a refusal is the stream closing without a hello reply;
    ``hellos_accepted`` staying 0 is the spoof-proof acceptance
    criterion this driver can observe directly.
    """

    MODES = ("nokey", "wrongkey", "hijack", "downgrade")

    def __init__(self, seed: int = 0, mode: str = "nokey",
                 secret_key=None, claim_era: int = 0,
                 budget_attempts: int = 40):
        if mode not in self.MODES:
            raise ValueError(f"unknown spoof mode {mode!r}")
        if mode in ("wrongkey", "downgrade") and secret_key is None:
            raise ValueError(f"mode {mode!r} needs a (wrong) secret_key")
        self.rng = random.Random(seed)
        self.mode = mode
        self.secret_key = secret_key
        self.claim_era = claim_era
        self.budget_attempts = budget_attempts
        self.attempts = 0
        #: refusals observed (stream closed / no hello reply) — the
        #: defense engaging, seen from the attacker's side
        self.refusals = 0
        #: spoofed hellos the victim ACCEPTED — must stay 0
        self.hellos_accepted = 0

    def _auth_payload(self, cluster_id: bytes, nonce: bytes,
                      session: bytes, identity) -> bytes:
        from hbbft_tpu.net import framing

        era = self.claim_era
        if self.secret_key is not None:
            transcript = framing.auth_transcript(
                bytes(cluster_id), nonce, session, identity,
                framing.ROLE_NODE, era)
            sig = self.secret_key.sign(transcript).to_bytes()
        else:
            sig = bytes(self.rng.randrange(256) for _ in range(96))
        return framing.encode_auth(era, sig)

    async def run(self, addr, cluster_id: bytes, identity,
                  duration_s: float = 5.0) -> None:
        """Spoof ``identity`` at ``addr`` until the attempt budget or
        ``duration_s`` runs out; every refusal feeds the next try."""
        import asyncio
        import time as _time

        from hbbft_tpu.net import framing

        deadline = _time.monotonic() + duration_s
        while (self.attempts < self.budget_attempts
               and _time.monotonic() < deadline):
            try:
                reader, writer = await asyncio.wait_for(
                    asyncio.open_connection(*addr), 2.0)
            except (OSError, asyncio.TimeoutError):
                await asyncio.sleep(0.05)
                continue
            self.attempts += 1
            try:
                hello = framing.Hello(
                    node_id=identity, role=framing.ROLE_NODE,
                    cluster_id=bytes(cluster_id), era=self.claim_era,
                    epoch=0)
                writer.write(framing.encode_frame(
                    framing.HELLO, framing.encode_hello(hello),
                    framing.DEFAULT_MAX_FRAME))
                await writer.drain()
                kind, payload = await asyncio.wait_for(
                    framing.read_one_frame(
                        reader, framing.DEFAULT_MAX_FRAME), 2.0)
                if kind == framing.HELLO:
                    # unauthenticated victim took the spoof at face
                    # value — the exact hole this drill exists to catch
                    self.hellos_accepted += 1
                    continue
                if kind != framing.CHALLENGE:
                    raise ConnectionError(
                        f"unexpected reply kind {kind}")
                nonce, session = framing.decode_challenge(payload)
                if self.mode == "hijack":
                    # stream a protocol frame where AUTH belongs: the
                    # victim must refuse it unparsed (no_auth), not
                    # feed it to the protocol
                    writer.write(framing.encode_frame(
                        framing.MSG,
                        bytes(self.rng.randrange(256)
                              for _ in range(64)),
                        framing.DEFAULT_MAX_FRAME))
                else:
                    writer.write(framing.encode_frame(
                        framing.AUTH,
                        self._auth_payload(cluster_id, nonce, session,
                                           identity),
                        framing.DEFAULT_MAX_FRAME))
                await writer.drain()
                kind, _ = await asyncio.wait_for(
                    framing.read_one_frame(
                        reader, framing.DEFAULT_MAX_FRAME), 2.0)
                if kind == framing.HELLO:
                    self.hellos_accepted += 1
            except (OSError, asyncio.TimeoutError,
                    asyncio.IncompleteReadError, ConnectionError,
                    framing.FrameError):
                # refused before the hello reply: the defense held
                self.refusals += 1
                await asyncio.sleep(0.02)
            finally:
                writer.close()


class CrashAtEpochAdversary(Adversary):
    """Crash-stop at epoch: once the victim node has produced
    ``after_batches`` outputs (committed batches for a QHB stack), ALL
    its subsequent traffic — both directions — is removed forever.  The
    fail-stop shape consensus must tolerate for up to f nodes: the
    remaining n−1 keep committing, the victim's ledger freezes at its
    crash point (its journal simply ends — no fork, no fault).

    Deterministic: the trigger is the victim's own output count.
    Messages already in flight at the crash instant still deliver (the
    usual fuzzy crash boundary).
    """

    def __init__(self, victim, after_batches: int = 1):
        self.victim = victim
        self.after_batches = after_batches
        self.crashed = False
        self.dropped = 0

    def filter_message(self, net: "VirtualNet", msg: "NetworkMessage"):
        if not self.crashed:
            node = net.nodes.get(self.victim)
            if node is not None and len(node.outputs) >= self.after_batches:
                self.crashed = True
        if self.crashed and (msg.sender == self.victim
                             or msg.to == self.victim):
            self.dropped += 1
            return None
        return msg

"""Delivery-schedule adversaries (reference: ``tests/net/adversary.rs``).

An adversary controls the order in which queued messages are delivered and
may tamper with or inject messages.  The BFT protocols must stay correct
under *any* schedule, so tests run each suite under several of these.
"""

from __future__ import annotations

import random
from typing import TYPE_CHECKING, List, Optional

if TYPE_CHECKING:
    from hbbft_tpu.sim.virtual_net import NetworkMessage, VirtualNet


class Adversary:
    """Base: FIFO delivery, no tampering.

    ``pick_message`` returns the index into the queue to deliver next;
    ``tamper`` may rewrite a message addressed from/to a faulty node.
    Reference: ``trait Adversary { pre_crank, tamper }``.
    """

    def pick_message(self, net: "VirtualNet") -> int:
        return 0

    def tamper(self, net: "VirtualNet", msg: "NetworkMessage") -> Optional["NetworkMessage"]:
        """Return a replacement for a message sent BY a faulty node (or None
        to drop it).  Only called for messages from faulty senders."""
        return msg


class NullAdversary(Adversary):
    """Honest FIFO scheduler."""


class NodeOrderAdversary(Adversary):
    """Delivers messages grouped by destination node id (lowest first).

    Reference: ``NodeOrderAdversary`` — exposes ordering assumptions.
    """

    def pick_message(self, net: "VirtualNet") -> int:
        order = {nid: i for i, nid in enumerate(sorted(net.node_ids(), key=repr))}
        best, best_key = 0, None
        for i, m in enumerate(net.queue):
            k = order.get(m.to, len(order))
            if best_key is None or k < best_key:
                best, best_key = i, k
        return best


class ReorderingAdversary(Adversary):
    """Deterministically swaps pairs of queued messages before delivery."""

    def __init__(self, seed: int = 0):
        self.rng = random.Random(seed)

    def pick_message(self, net: "VirtualNet") -> int:
        if len(net.queue) >= 2 and self.rng.random() < 0.5:
            return 1
        return 0


class MitmDelayAdversary(Adversary):
    """Man-in-the-middle delay schedule against binary agreement.

    Reference: ``tests/binary_agreement_mitm.rs`` — the Moumen-style attack:
    hold back every message to/from a targeted node for as long as the
    budget allows so its estimate keeps lagging the coin.  With a threshold
    (unpredictable) coin the protocol must still terminate; a predictable
    coin could be stalled forever.
    """

    def __init__(self, target, max_delay: int = 200, seed: int = 0):
        self.target = target
        self.max_delay = max_delay
        self.rng = random.Random(seed)
        self._held = 0

    def pick_message(self, net: "VirtualNet") -> int:
        others = [
            i for i, m in enumerate(net.queue)
            if m.to != self.target and m.sender != self.target
        ]
        if others and self._held < self.max_delay:
            self._held += 1
            return self.rng.choice(others)
        self._held = 0
        return self.rng.randrange(len(net.queue))


class EquivocatingAdversary(Adversary):
    """A faulty node equivocates: the Merkle root carried by its
    root-bearing broadcast messages (Ready/EchoHash/CanDecode, and the
    proof roots of Value/Echo) is rewritten for HALF its peers, so odd-
    and even-indexed destinations observe conflicting values for the same
    RBC slot.  Delivery order stays FIFO — the point is not scheduling
    pressure but producing the receiver-side evidence the forensic
    auditor (``hbbft_tpu.obs.audit``) must reconstruct: two journals
    holding different roots from one sender for one slot, keyed to the
    ``Multiple*`` FaultKind family.

    Deterministic (no RNG): the same run yields the same tampered bytes,
    which the audit byte-identity tests rely on.
    """

    def tamper(self, net: "VirtualNet", msg: "NetworkMessage"):
        from hbbft_tpu.sim.virtual_net import NetworkMessage

        order = sorted(net.node_ids(), key=repr)
        if order.index(msg.to) % 2 == 0:
            return msg  # even destinations see the honest value
        flipped = _flip_roots(msg.payload)
        if flipped is None:
            return msg
        return NetworkMessage(msg.sender, msg.to, flipped)


def _flip_roots(msg):
    """A copy of ``msg`` with every embedded 32-byte broadcast root's
    last bit flipped (walking the wrapper chain); None if the message
    carries no root."""
    import dataclasses

    from hbbft_tpu.protocols.broadcast import (
        CanDecodeMsg, EchoHashMsg, EchoMsg, ReadyMsg, ValueMsg,
    )

    def flip(root: bytes) -> bytes:
        return root[:-1] + bytes([root[-1] ^ 1])

    if isinstance(msg, (ReadyMsg, EchoHashMsg, CanDecodeMsg)):
        return type(msg)(flip(msg.root))
    if isinstance(msg, (ValueMsg, EchoMsg)):
        proof = dataclasses.replace(msg.proof,
                                    root_hash=flip(msg.proof.root_hash))
        return type(msg)(proof)
    if dataclasses.is_dataclass(msg) and hasattr(msg, "msg"):
        inner = _flip_roots(msg.msg)
        if inner is None:
            return None
        return dataclasses.replace(msg, msg=inner)
    return None


class RandomAdversary(Adversary):
    """Random delivery order with duplication, INJECTION, and TAMPERING.

    Reference: ``RandomAdversary`` — random schedule plus replays, randomly
    mutated copies of in-flight messages re-sent under faulty identities,
    and field-level tampering of faulty nodes' outgoing messages.  Correct
    nodes must treat all of it as noise: at worst the culprits land in
    fault logs; agreement/termination must be unaffected.
    """

    def __init__(self, seed: int = 0, dup_prob: float = 0.05,
                 inject_prob: float = 0.05, tamper_prob: float = 0.3):
        self.rng = random.Random(seed)
        self.dup_prob = dup_prob
        self.inject_prob = inject_prob
        self.tamper_prob = tamper_prob

    def pick_message(self, net: "VirtualNet") -> int:
        from hbbft_tpu.sim.virtual_net import NetworkMessage

        i = self.rng.randrange(len(net.queue))
        if self.rng.random() < self.dup_prob:
            # duplicate: re-enqueue a copy before delivery
            net.queue.append(net.queue[i])
        faulty = [n for n in net.node_ids() if net.nodes[n].is_faulty]
        if faulty and self.rng.random() < self.inject_prob:
            # inject: a mutated copy of a random in-flight message, re-sent
            # under a faulty identity to a random destination
            src = self.rng.choice(faulty)
            template = self.rng.choice(net.queue)
            payload = self._mutate(template.payload)
            dst = self.rng.choice(net.node_ids())
            net.queue.append(NetworkMessage(src, dst, payload))
        return i

    def tamper(self, net: "VirtualNet", msg: "NetworkMessage"):
        """Faulty senders' messages: drop some, corrupt some fields."""
        from hbbft_tpu.sim.virtual_net import NetworkMessage

        roll = self.rng.random()
        if roll < self.tamper_prob / 3:
            return None  # drop
        if roll < self.tamper_prob:
            return NetworkMessage(
                msg.sender, msg.to, self._mutate(msg.payload)
            )
        return msg

    def _mutate(self, msg):
        """Type-aware field corruption of protocol messages (falls back to
        the original object for unknown/deeply-nested types)."""
        import dataclasses

        from hbbft_tpu.protocols.binary_agreement import (
            AuxMsg, BValMsg, ConfMsg, TermMsg,
        )
        from hbbft_tpu.protocols.broadcast import (
            CanDecodeMsg, EchoHashMsg, EchoMsg, ReadyMsg, ValueMsg,
        )

        r = self.rng
        if isinstance(msg, (BValMsg, AuxMsg)):
            if r.random() < 0.5:
                return dataclasses.replace(msg, value=not msg.value)
            return dataclasses.replace(msg, epoch=msg.epoch + r.randrange(1, 3))
        if isinstance(msg, TermMsg):
            return dataclasses.replace(msg, value=not msg.value)
        if isinstance(msg, ConfMsg):
            return dataclasses.replace(
                msg, values=frozenset([r.random() < 0.5])
            )
        if isinstance(msg, (ReadyMsg, EchoHashMsg, CanDecodeMsg)):
            root = bytearray(msg.root)
            root[r.randrange(len(root))] ^= 1 << r.randrange(8)
            return type(msg)(bytes(root))
        if isinstance(msg, (ValueMsg, EchoMsg)):
            proof = msg.proof
            value = bytearray(proof.value)
            if value:
                value[r.randrange(len(value))] ^= 1 << r.randrange(8)
            bad = dataclasses.replace(proof, value=bytes(value))
            return type(msg)(bad)
        if dataclasses.is_dataclass(msg) and hasattr(msg, "msg"):
            try:
                return dataclasses.replace(msg, msg=self._mutate(msg.msg))
            except Exception:
                return msg
        return msg

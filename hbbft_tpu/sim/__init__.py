"""Object-mode deterministic network simulation (reference: ``tests/net/``).

``VirtualNet`` is the message-pump event loop the sans-I/O protocols need:
a queue of in-flight messages, an :class:`~hbbft_tpu.sim.adversary.Adversary`
that chooses/tampers delivery, and ``crank()`` delivering exactly one message
at a time.  Fully deterministic from a seed.  The TPU execution path
(``hbbft_tpu.parallel``) replaces this loop with one device step per
communication round; this harness is the semantic ground truth it is
cross-checked against.
"""

from hbbft_tpu.sim.adversary import (
    Adversary,
    CensorshipAdversary,
    CrashAtEpochAdversary,
    EclipseAdversary,
    EquivocatingAdversary,
    MitmDelayAdversary,
    NodeOrderAdversary,
    NullAdversary,
    RandomAdversary,
    ReorderingAdversary,
    TargetedDelayAdversary,
)
from hbbft_tpu.sim.trace import CostModel, CrankEvent, EventLog, NetEvent
from hbbft_tpu.sim.virtual_net import CrankError, NetBuilder, VirtualNet

"""Small shared helpers (reference: ``src/util.rs``)."""

from __future__ import annotations

import contextlib
import random
from typing import Optional


class SubRng:
    """Fork child RNGs from a parent deterministically.

    Reference: ``src/util.rs :: SubRng`` — protocols that need randomness
    (e.g. ``TransactionQueue::choose``) get a forked RNG so runs stay
    reproducible from one seed.
    """

    @staticmethod
    def sub_rng(parent: random.Random) -> random.Random:
        return random.Random(parent.getrandbits(64))


def fmt_hex(data: bytes, max_len: int = 8) -> str:
    """Short hex rendering for logs (reference: ``hex_fmt`` crate usage)."""
    h = data[:max_len].hex()
    return h + ("…" if len(data) > max_len else "")


def enable_compilation_cache(path: Optional[str] = None) -> None:
    """Persist XLA executables to disk across processes.

    The big fori_loop ladder graphs (ops/gcurve, parallel/acs) cost
    100–250 s to compile on this backend; the persistent cache turns that
    into a one-time cost per (shape, code) rather than per process.  Safe to
    call more than once; a failure (unsupported backend) is non-fatal.
    """
    import os

    import jax

    if path is None:  # anchor to the repo, not the launch cwd
        path = os.path.join(os.path.dirname(os.path.dirname(__file__)),
                            ".jax_cache")
    # suppress: older jax / unsupported backend is non-fatal by contract
    with contextlib.suppress(Exception):  # pragma: no cover
        jax.config.update("jax_compilation_cache_dir", path)
        # 1 s threshold: the suite re-pays hundreds of 1–5 s compiles per
        # process otherwise; the cache entries are small relative to the
        # ladder executables that dominate the directory
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)


def shard_map_compat():
    """``jax.shard_map`` across jax versions.

    The top-level ``jax.shard_map`` API (with its ``check_vma`` kwarg)
    graduated out of ``jax.experimental.shard_map`` (where the same knob
    is spelled ``check_rep``) after the 0.4.x line; this repo's sharded
    phases are written against the top-level spelling.  Returns the real
    function when it exists, else a wrapper over the experimental one
    that translates the kwarg — call sites import this instead of
    ``from jax import shard_map`` so both jax generations work."""
    try:
        from jax import shard_map

        return shard_map
    except ImportError:
        import functools

        from jax.experimental.shard_map import shard_map as _sm

        @functools.wraps(_sm)
        def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True,
                      **kw):
            return _sm(f, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs, check_rep=check_vma, **kw)

        return shard_map

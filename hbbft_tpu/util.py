"""Small shared helpers (reference: ``src/util.rs``)."""

from __future__ import annotations

import random
from typing import Optional


class SubRng:
    """Fork child RNGs from a parent deterministically.

    Reference: ``src/util.rs :: SubRng`` — protocols that need randomness
    (e.g. ``TransactionQueue::choose``) get a forked RNG so runs stay
    reproducible from one seed.
    """

    @staticmethod
    def sub_rng(parent: random.Random) -> random.Random:
        return random.Random(parent.getrandbits(64))


def fmt_hex(data: bytes, max_len: int = 8) -> str:
    """Short hex rendering for logs (reference: ``hex_fmt`` crate usage)."""
    h = data[:max_len].hex()
    return h + ("…" if len(data) > max_len else "")

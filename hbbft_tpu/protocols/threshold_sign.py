"""Threshold signing — the common-coin primitive.

Reference: ``src/threshold_sign.rs :: ThresholdSign<N>`` — every validator
BLS-signs a session-unique document; t+1 = f+1 valid shares interpolate to a
unique group signature (independent of *which* shares), whose hash is the
unpredictable common coin for binary agreement.

Optimisation over the reference: *optimistic combination*.  The reference
pairing-verifies every incoming share (the protocol's hottest loop, O(N²)
pairings per coin network-wide).  We combine any t+1 unverified shares and
verify the combined signature once; only if that fails do we fall back to
per-share verification to identify and fault the culprits.  With honest
shares this is 1 pairing-check per node instead of f+1.  The batched TPU
verifier uses the same trick in array form.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Hashable, Optional

from hbbft_tpu.crypto import tc
from hbbft_tpu.fault_log import FaultKind
from hbbft_tpu.netinfo import NetworkInfo
from hbbft_tpu.traits import ConsensusProtocol, Step

NodeId = Hashable


@dataclass(frozen=True)
class ThresholdSignMessage:
    share: tc.SignatureShare


class ThresholdSign(ConsensusProtocol):
    """Reference: ``src/threshold_sign.rs``."""

    def __init__(self, netinfo: NetworkInfo, optimistic: bool = True):
        self.netinfo = netinfo
        self.document: Optional[bytes] = None
        self.shares: Dict[NodeId, tc.SignatureShare] = {}
        self.verified: Dict[NodeId, bool] = {}
        self.pending: Dict[NodeId, tc.SignatureShare] = {}
        self.signature: Optional[tc.Signature] = None
        self.had_input = False
        self.optimistic = optimistic

    def our_id(self) -> NodeId:
        return self.netinfo.our_id()

    def terminated(self) -> bool:
        return self.signature is not None

    # -- API ----------------------------------------------------------------

    def set_document(self, document: bytes) -> Step:
        """Define what is being signed; processes any queued shares."""
        if self.document is not None:
            return Step()
        self.document = bytes(document)
        step = Step()
        pending, self.pending = self.pending, {}
        for sender, share in pending.items():
            step.extend(self._handle_share(sender, share))
        return step

    def sign(self) -> Step:
        """Sign the document and broadcast our share (reference ``sign``)."""
        if self.had_input:
            return Step()
        if self.document is None:
            raise ValueError("set_document before sign")
        self.had_input = True
        if not self.netinfo.is_validator():
            return Step()
        share = self.netinfo.secret_key_share().sign(self.document)
        step = Step()
        step.send_all(ThresholdSignMessage(share))
        step.extend(self._handle_share(self.our_id(), share))
        return step

    def handle_input(self, input: bytes) -> Step:
        """Input = the document; sets and signs in one go."""
        step = self.set_document(input)
        return step.extend(self.sign())

    def handle_message(self, sender_id: NodeId, message) -> Step:
        if not self.netinfo.is_node_validator(sender_id):
            return Step.from_fault(sender_id, FaultKind.UnknownSender)
        if not isinstance(message, ThresholdSignMessage):
            raise TypeError(f"unknown threshold_sign message {message!r}")
        if self.document is None:
            # buffer until the document is known (can arrive first under
            # adversarial schedules)
            if sender_id in self.pending:
                if self.pending[sender_id] == message.share:
                    return Step()  # network replay — idempotent
                return Step.from_fault(
                    sender_id, FaultKind.MultipleSignatureShares
                )
            self.pending[sender_id] = message.share
            return Step()
        return self._handle_share(sender_id, message.share)

    # -- internals ----------------------------------------------------------

    def _handle_share(self, sender_id: NodeId, share: tc.SignatureShare) -> Step:
        if self.signature is not None:
            return Step()
        if sender_id in self.shares:
            if self.shares[sender_id] == share:
                return Step()  # network replay — idempotent
            return Step.from_fault(sender_id, FaultKind.MultipleSignatureShares)
        pks = self.netinfo.public_key_set()
        if not self.optimistic:
            idx = self.netinfo.node_index(sender_id)
            if not pks.verify_signature_share(idx, share, self.document):
                return Step.from_fault(
                    sender_id, FaultKind.InvalidSignatureShare
                )
            self.verified[sender_id] = True
        self.shares[sender_id] = share
        return self._try_output()

    def _try_output(self) -> Step:
        pks = self.netinfo.public_key_set()
        t = pks.threshold()
        if len(self.shares) < t + 1:
            return Step()
        indexed = {
            self.netinfo.node_index(nid): s for nid, s in self.shares.items()
        }
        sig = pks.combine_signatures(indexed)
        if pks.verify_signature(sig, self.document):
            self.signature = sig
            return Step.from_output(sig)
        # Pessimistic fallback: someone sent garbage — verify individually,
        # evict + fault the liars, wait for more shares.
        step = Step()
        for nid in list(self.shares.keys()):
            if self.verified.get(nid):
                continue
            idx = self.netinfo.node_index(nid)
            if pks.verify_signature_share(idx, self.shares[nid], self.document):
                self.verified[nid] = True
            else:
                del self.shares[nid]
                step.fault(nid, FaultKind.InvalidSignatureShare)
        return step.extend(self._try_output() if len(self.shares) >= t + 1 else Step())

"""HoneyBadger atomic broadcast: the epoch loop.

Reference: ``src/honey_badger/`` — ``honey_badger.rs`` (epoch window +
message routing), ``epoch_state.rs`` (one ``Subset`` + per-proposer
``ThresholdDecrypt``), ``batch.rs``, ``builder.rs``, ``message.rs``.

Per epoch: each node TPKE-encrypts its serialized contribution under the
network's threshold public key (per the ``EncryptionSchedule``), proposes the
ciphertext into that epoch's ``Subset``; when the ACS delivers the agreed
ciphertext set, every validator publishes a decryption share per accepted
ciphertext; t+1 shares decrypt each one, and the epoch closes with a
``Batch`` of (proposer → contribution bytes), identical on every correct
node and in epoch order.

Contribution payloads here are opaque bytes; ``DynamicHoneyBadger``/
``QueueingHoneyBadger`` own (de)serialization (the reference uses bincode at
this boundary and faults ``BatchDeserializationFailed``; our equivalent
fault is raised there).
"""

from __future__ import annotations

import random
import struct
from dataclasses import dataclass, field
from typing import Any, Dict, Hashable, List, Optional, Tuple

from hbbft_tpu.crypto import tc
from hbbft_tpu.fault_log import FaultKind
from hbbft_tpu.netinfo import NetworkInfo
from hbbft_tpu.protocols import subset as subset_mod
from hbbft_tpu.protocols.subset import Subset, SubsetHandlingStrategy
from hbbft_tpu.protocols.threshold_decrypt import (
    DecryptionMessage,
    ThresholdDecrypt,
)
from hbbft_tpu.traits import ConsensusProtocol, Step

NodeId = Hashable


# -- encryption schedule (reference: EncryptionSchedule) ---------------------


class EncryptionSchedule:
    """When to TPKE-encrypt contributions.

    Reference variants: ``Always``, ``Never``, ``EveryNthEpoch(n)``,
    ``TickTock(on, off)``.
    """

    def __init__(self, kind: str, a: int = 0, b: int = 0):
        self.kind = kind
        self.a = a
        self.b = b

    @classmethod
    def always(cls):
        return cls("always")

    @classmethod
    def never(cls):
        return cls("never")

    @classmethod
    def every_nth_epoch(cls, n: int):
        return cls("nth", n)

    @classmethod
    def tick_tock(cls, on: int, off: int):
        return cls("ticktock", on, off)

    def encrypt_on_epoch(self, epoch: int) -> bool:
        if self.kind == "always":
            return True
        if self.kind == "never":
            return False
        if self.kind == "nth":
            return epoch % self.a == 0
        period = self.a + self.b
        return (epoch % period) < self.a


# -- messages ---------------------------------------------------------------


@dataclass(frozen=True)
class SubsetWrap:
    epoch: int
    msg: object


@dataclass(frozen=True)
class DecryptionShareWrap:
    epoch: int
    proposer_id: NodeId
    msg: DecryptionMessage


# -- batch ------------------------------------------------------------------


@dataclass(frozen=True)
class Batch:
    """Reference: ``src/honey_badger/batch.rs :: Batch<C, N>``."""

    epoch: int
    contributions: Tuple[Tuple[NodeId, bytes], ...]

    def contributions_map(self) -> Dict[NodeId, bytes]:
        return dict(self.contributions)

    def is_empty(self) -> bool:
        return not self.contributions


# -- epoch state ------------------------------------------------------------

_PLAIN = 0x00
_ENCRYPTED = 0x01


class _EpochState:
    """Reference: ``src/honey_badger/epoch_state.rs :: EpochState``."""

    def __init__(self, netinfo: NetworkInfo, session_id: bytes, epoch: int,
                 subset_handling_strategy=None):
        self.netinfo = netinfo
        self.epoch = epoch
        self.subset = Subset(
            netinfo, session_id + b"/hb-epoch/" + struct.pack(">Q", epoch),
            handling_strategy=(
                subset_handling_strategy or SubsetHandlingStrategy.Incremental
            ),
        )
        self.decrypts: Dict[NodeId, ThresholdDecrypt] = {}
        self.plain: Dict[NodeId, bytes] = {}
        self.excluded: set = set()
        self.subset_done = False
        self.accepted: set = set()

    def decrypted_all(self) -> bool:
        return self.subset_done and all(
            pid in self.plain or pid in self.excluded for pid in self.accepted
        )

    def batch(self) -> Batch:
        return Batch(
            epoch=self.epoch,
            contributions=tuple(
                sorted(self.plain.items(), key=lambda kv: repr(kv[0]))
            ),
        )


class HoneyBadgerBuilder:
    """Reference: ``src/honey_badger/builder.rs``."""

    def __init__(self, netinfo: NetworkInfo):
        self.netinfo = netinfo
        self._session_id = b"hb"
        self._max_future_epochs = 3
        self._encryption_schedule = EncryptionSchedule.always()
        self._subset_handling_strategy = None
        self._rng: Optional[random.Random] = None

    def session_id(self, sid: bytes) -> "HoneyBadgerBuilder":
        self._session_id = bytes(sid)
        return self

    def max_future_epochs(self, n: int) -> "HoneyBadgerBuilder":
        self._max_future_epochs = n
        return self

    def encryption_schedule(self, es: EncryptionSchedule) -> "HoneyBadgerBuilder":
        self._encryption_schedule = es
        return self

    def rng(self, rng: random.Random) -> "HoneyBadgerBuilder":
        self._rng = rng
        return self

    def subset_handling_strategy(self, s) -> "HoneyBadgerBuilder":
        """Reference: ``HoneyBadgerBuilder::subset_handling_strategy``."""
        self._subset_handling_strategy = s
        return self

    def build(self) -> "HoneyBadger":
        return HoneyBadger(
            self.netinfo,
            session_id=self._session_id,
            max_future_epochs=self._max_future_epochs,
            encryption_schedule=self._encryption_schedule,
            rng=self._rng or random.Random(0),
            subset_handling_strategy=self._subset_handling_strategy,
        )


class HoneyBadger(ConsensusProtocol):
    """Reference: ``src/honey_badger/honey_badger.rs :: HoneyBadger<C, N>``."""

    def __init__(
        self,
        netinfo: NetworkInfo,
        session_id: bytes = b"hb",
        max_future_epochs: int = 3,
        encryption_schedule: Optional[EncryptionSchedule] = None,
        rng: Optional[random.Random] = None,
        subset_handling_strategy=None,
    ):
        self.netinfo = netinfo
        self.session_id = bytes(session_id)
        self.epoch = 0
        self.max_future_epochs = max_future_epochs
        self.encryption_schedule = encryption_schedule or EncryptionSchedule.always()
        self.rng = rng or random.Random(0)
        self.subset_handling_strategy = subset_handling_strategy
        self.epochs: Dict[int, _EpochState] = {}
        self.has_input: Dict[int, bool] = {}
        self.completed: Dict[int, Batch] = {}
        # Deferred threshold-decrypt verification (the epoch-pipelined
        # runtime's cross-epoch crypto seam): when True, every
        # ThresholdDecrypt this instance creates parks its t+1 share-set
        # verification here instead of pairing inline; the pump drains
        # them via resolve_deferred() as ONE merged device/pairing call
        # per iteration.  False (default) keeps the simulator-exact path.
        self.defer_decrypt = False
        self._deferred_decrypts: List[Tuple[int, NodeId, Any]] = []
        # Per-sender future-epoch admission budget (overload defense):
        # a Byzantine validator spamming protocol messages at the
        # `epoch + max_future_epochs` window edge forces future epoch
        # states open and churns their sub-protocols.  Honest pipelined
        # traffic between two epoch advances is well under ~100 messages
        # per sender per future epoch at any tested topology; beyond the
        # budget the sender's messages for epochs ahead of the current
        # one are dropped with a counted FutureEpochFlood fault.  Counts
        # reset every time the current epoch advances (the window slid).
        self.future_msg_budget = 256 * (max_future_epochs + 1)
        self._future_counts: Dict[NodeId, int] = {}
        self.future_drops: Dict[NodeId, int] = {}
        # guard statistics folded from CLOSED epochs (their Subset/BA
        # instances are deleted with the epoch state — without this a
        # run-long "peak stayed ≤ cap" witness would silently lose
        # every epoch that completed before it was read)
        self.closed_guard: Dict[str, int] = {
            "aba_future_peak": 0,
            "aba_future_evictions": 0,
            "subset_flood_drops": 0,
        }

    @classmethod
    def builder(cls, netinfo: NetworkInfo) -> HoneyBadgerBuilder:
        return HoneyBadgerBuilder(netinfo)

    # -- ConsensusProtocol ---------------------------------------------------

    def our_id(self) -> NodeId:
        return self.netinfo.our_id()

    def terminated(self) -> bool:
        return False  # atomic broadcast runs forever

    def next_epoch(self) -> int:
        return self.epoch

    def handle_input(self, input: bytes) -> Step:
        return self.propose(input)

    def propose(self, contribution: bytes) -> Step:
        """Encrypt (per schedule) and propose into the current epoch's ACS.

        Reference: ``HoneyBadger::propose`` (HOT: TPKE encrypt —
        G1/G2 scalar muls).
        """
        return self.propose_into(self.epoch, contribution)

    def propose_into(self, epoch: int, contribution: bytes) -> Step:
        """Propose into ``epoch`` — the current one (``propose``) or a
        future one within the ``max_future_epochs`` window.

        This is the epoch-pipelining seam: the protocol already accepts
        peers' messages up to ``max_future_epochs`` ahead, so a proposer
        may open epoch e+k's Subset while epoch e is still threshold-
        decrypting.  Out-of-window or already-proposed epochs are no-ops.
        """
        if epoch < self.epoch or epoch > self.epoch + self.max_future_epochs:
            return Step()
        if self.has_input.get(epoch):
            return Step()
        self.has_input[epoch] = True
        if self.encryption_schedule.encrypt_on_epoch(epoch):
            ct = (
                self.netinfo.public_key_set()
                .public_key()
                .encrypt(bytes(contribution), self.rng)
            )
            payload = bytes([_ENCRYPTED]) + ct.to_bytes()
        else:
            payload = bytes([_PLAIN]) + bytes(contribution)
        state = self._epoch_state(epoch)
        inner = state.subset.handle_input(payload)
        return self._process_subset_step(epoch, inner)

    def handle_message(self, sender_id: NodeId, message) -> Step:
        if not self.netinfo.is_node_validator(sender_id):
            return Step.from_fault(sender_id, FaultKind.UnknownSender)
        epoch = message.epoch
        if epoch < self.epoch:
            return Step()  # obsolete epoch
        if epoch > self.epoch + self.max_future_epochs:
            return Step.from_fault(sender_id, FaultKind.UnexpectedHbMessage)
        if epoch > self.epoch:
            count = self._future_counts.get(sender_id, 0) + 1
            if count > self.future_msg_budget:
                self.future_drops[sender_id] = (
                    self.future_drops.get(sender_id, 0) + 1
                )
                return Step.from_fault(sender_id,
                                       FaultKind.FutureEpochFlood)
            self._future_counts[sender_id] = count
        if isinstance(message, SubsetWrap):
            state = self._epoch_state(epoch)
            inner = state.subset.handle_message(sender_id, message.msg)
            return self._process_subset_step(epoch, inner)
        if isinstance(message, DecryptionShareWrap):
            if not self.netinfo.is_node_validator(message.proposer_id):
                # unknown proposer: reject before creating any state
                return Step.from_fault(
                    sender_id, FaultKind.UnexpectedDecryptionShare
                )
            state = self._epoch_state(epoch)
            td = self._decrypt_for(state, message.proposer_id)
            inner = td.handle_message(sender_id, message.msg)
            return self._process_decrypt_step(epoch, message.proposer_id, inner)
        raise TypeError(f"unknown honey_badger message {message!r}")

    # -- internals -----------------------------------------------------------

    def _epoch_state(self, epoch: int) -> _EpochState:
        if epoch not in self.epochs:
            self.epochs[epoch] = _EpochState(
                self.netinfo, self.session_id, epoch,
                subset_handling_strategy=self.subset_handling_strategy,
            )
        return self.epochs[epoch]

    def _decrypt_for(self, state: _EpochState, proposer_id: NodeId) -> ThresholdDecrypt:
        if proposer_id not in state.decrypts:
            td = ThresholdDecrypt(self.netinfo)
            if self.defer_decrypt:
                epoch = state.epoch
                td.defer_verify = (
                    lambda inst, e=epoch, p=proposer_id:
                    self._deferred_decrypts.append((e, p, inst))
                )
            state.decrypts[proposer_id] = td
        return state.decrypts[proposer_id]

    # -- deferred threshold-decrypt verification (pipelined pump seam) -------

    def has_deferred(self) -> bool:
        return bool(self._deferred_decrypts)

    def resolve_deferred(self) -> Step:
        """Verify every parked t+1 share set in ONE merged call and resume
        the instances (see ``crypto.batch.verify_dec_share_sets``).  The
        pump calls this at the end of each iteration, so the shares of all
        epochs in flight verify together — cross-epoch batched threshold
        crypto — instead of one pairing check per (epoch, proposer)."""
        if not self._deferred_decrypts:
            return Step()
        from hbbft_tpu.crypto.batch import verify_dec_share_sets

        jobs, self._deferred_decrypts = self._deferred_decrypts, []
        pks = self.netinfo.public_key_set()
        live = []
        for epoch, proposer, td in jobs:
            # an era rotation or epoch close can orphan a parked job —
            # nothing to resume then
            if epoch not in self.epochs or td.deferred_job() is None:
                continue
            live.append((epoch, proposer, td))
        if not live:
            return Step()
        oks = verify_dec_share_sets([
            (pks,) + td.deferred_job() for _e, _p, td in live
        ])
        step = Step()
        for (epoch, proposer, td), ok in zip(live, oks):
            inner = td.finish_deferred(ok)
            step.extend(
                self._process_decrypt_step(epoch, proposer, inner)
            )
        return step

    def _fold_guard(self, state: "_EpochState") -> None:
        """Preserve a closing epoch's overload-guard statistics."""
        g = self.closed_guard
        g["subset_flood_drops"] += sum(
            state.subset.flood_drops.values())
        for prop in state.subset.proposals.values():
            ba = prop.agreement
            if ba.future_peak > g["aba_future_peak"]:
                g["aba_future_peak"] = ba.future_peak
            g["aba_future_evictions"] += sum(
                ba.future_evictions.values())

    def _process_subset_step(self, epoch: int, inner: Step) -> Step:
        step = inner.map(lambda m: SubsetWrap(epoch, m))
        state = self.epochs.get(epoch)
        if state is None:  # epoch already closed mid-step
            step.output = []
            return step
        outputs = step.output
        if not outputs:
            # nothing accepted and Done unchanged → completion state
            # cannot have moved: skip the per-message _try_complete scan
            # (decryption progress runs its own completion check via
            # _process_decrypt_step)
            return step
        step.output = []
        accepted = [
            o for o in outputs if isinstance(o, subset_mod.Contribution)
        ]
        pre = self._precheck_accepted(accepted) if len(accepted) > 1 else {}
        for out in outputs:
            if isinstance(out, subset_mod.Contribution):
                step.extend(
                    self._on_accepted(epoch, out.proposer_id, out.value,
                                      pre.get(out.proposer_id))
                )
            elif isinstance(out, subset_mod.Done):
                state.subset_done = True
        return step.extend(self._try_complete(epoch))

    def _precheck_accepted(self, accepted) -> Dict[NodeId, tuple]:
        """Batch the crypto of several simultaneously ACS-accepted
        ciphertexts: ONE merged CCA pairing check for all of them and ONE
        batched call generating our decryption shares, instead of a
        pairing + a scalar-mul per proposer.  Returns
        ``{proposer: (ct, ok, share)}`` consumed by ``_on_accepted`` —
        verdicts and shares are value-identical to the per-item path, so
        behavior (and the simulator's byte-determinism) is unchanged."""
        from hbbft_tpu.crypto.batch import (
            batch_decrypt_share_gen,
            verify_ciphertext_batch,
        )

        entries = []  # (proposer, ct)
        for out in accepted:
            payload = out.value
            if not payload or payload[0] != _ENCRYPTED:
                continue
            try:
                ct = tc.Ciphertext.from_bytes(payload[1:])
            except (ValueError, IndexError):
                continue
            entries.append((out.proposer_id, ct))
        if not entries:
            return {}
        oks = verify_ciphertext_batch([ct for _p, ct in entries])
        shares: List[Any] = [None] * len(entries)
        if self.netinfo.is_validator():
            valid = [i for i, ok in enumerate(oks) if ok]
            gen = batch_decrypt_share_gen(
                self.netinfo.secret_key_share().scalar,
                [entries[i][1] for i in valid],
            )
            for i, share in zip(valid, gen):
                shares[i] = share
        return {
            p: (ct, ok, share)
            for (p, ct), ok, share in zip(entries, oks, shares)
        }

    def _on_accepted(self, epoch: int, proposer_id: NodeId, payload: bytes,
                     pre: Optional[tuple] = None) -> Step:
        """An ACS-accepted contribution: plaintext or ciphertext to decrypt.

        ``pre`` optionally carries this proposer's pre-batched
        ``(ct, verify_ok, our_share)`` from :meth:`_precheck_accepted`.
        """
        state = self.epochs[epoch]
        state.accepted.add(proposer_id)
        step = Step()
        if not payload:
            state.excluded.add(proposer_id)
            return step.fault(proposer_id, FaultKind.InvalidCiphertext)
        tag, body = payload[0], payload[1:]
        if tag == _PLAIN:
            state.plain[proposer_id] = body
            return step
        if tag != _ENCRYPTED:
            state.excluded.add(proposer_id)
            return step.fault(proposer_id, FaultKind.InvalidCiphertext)
        share = None
        if pre is not None:
            ct, ok, share = pre
        else:
            try:
                ct = tc.Ciphertext.from_bytes(body)
                ok = ct.verify()
            except (ValueError, IndexError):
                ok = False
        if not ok:
            # all correct nodes agree (same RBC bytes) → consistent exclusion
            state.excluded.add(proposer_id)
            return step.fault(proposer_id, FaultKind.InvalidCiphertext)
        td = self._decrypt_for(state, proposer_id)
        inner = td.set_ciphertext(ct, share=share)
        return step.extend(self._process_decrypt_step(epoch, proposer_id, inner))

    def _process_decrypt_step(
        self, epoch: int, proposer_id: NodeId, inner: Step
    ) -> Step:
        step = inner.map(
            lambda m: DecryptionShareWrap(epoch, proposer_id, m)
        )
        state = self.epochs.get(epoch)
        if state is None:  # epoch already closed mid-step
            step.output = []
            return step
        outputs = step.output
        step.output = []
        for plaintext in outputs:
            state.plain[proposer_id] = plaintext
        return step.extend(self._try_complete(epoch))

    def _try_complete(self, epoch: int) -> Step:
        """Close completed epochs in order (reference ``update_epoch``)."""
        state = self.epochs.get(epoch)
        if state is None:
            return Step()
        if epoch not in self.completed and state.decrypted_all():
            self.completed[epoch] = state.batch()
        step = Step()
        advanced = False
        while self.epoch in self.completed:
            batch = self.completed.pop(self.epoch)
            step.output.append(batch)
            self._fold_guard(self.epochs[self.epoch])
            del self.epochs[self.epoch]
            self.has_input.pop(self.epoch, None)  # bound per-epoch state
            self.epoch += 1
            advanced = True
        if advanced and self._future_counts:
            # the future window slid: every sender's admission budget
            # renews (state stays bounded by the validator set)
            self._future_counts.clear()
        return step

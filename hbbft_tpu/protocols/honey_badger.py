"""HoneyBadger atomic broadcast: the epoch loop.

Reference: ``src/honey_badger/`` — ``honey_badger.rs`` (epoch window +
message routing), ``epoch_state.rs`` (one ``Subset`` + per-proposer
``ThresholdDecrypt``), ``batch.rs``, ``builder.rs``, ``message.rs``.

Per epoch: each node TPKE-encrypts its serialized contribution under the
network's threshold public key (per the ``EncryptionSchedule``), proposes the
ciphertext into that epoch's ``Subset``; when the ACS delivers the agreed
ciphertext set, every validator publishes a decryption share per accepted
ciphertext; t+1 shares decrypt each one, and the epoch closes with a
``Batch`` of (proposer → contribution bytes), identical on every correct
node and in epoch order.

Contribution payloads here are opaque bytes; ``DynamicHoneyBadger``/
``QueueingHoneyBadger`` own (de)serialization (the reference uses bincode at
this boundary and faults ``BatchDeserializationFailed``; our equivalent
fault is raised there).
"""

from __future__ import annotations

import random
import struct
from dataclasses import dataclass, field
from typing import Any, Dict, Hashable, List, Optional, Tuple

from hbbft_tpu.crypto import tc
from hbbft_tpu.fault_log import FaultKind
from hbbft_tpu.netinfo import NetworkInfo
from hbbft_tpu.protocols import subset as subset_mod
from hbbft_tpu.protocols.subset import Subset, SubsetHandlingStrategy
from hbbft_tpu.protocols.threshold_decrypt import (
    DecryptionMessage,
    ThresholdDecrypt,
)
from hbbft_tpu.traits import ConsensusProtocol, Step

NodeId = Hashable


# -- encryption schedule (reference: EncryptionSchedule) ---------------------


class EncryptionSchedule:
    """When to TPKE-encrypt contributions.

    Reference variants: ``Always``, ``Never``, ``EveryNthEpoch(n)``,
    ``TickTock(on, off)``.
    """

    def __init__(self, kind: str, a: int = 0, b: int = 0):
        self.kind = kind
        self.a = a
        self.b = b

    @classmethod
    def always(cls):
        return cls("always")

    @classmethod
    def never(cls):
        return cls("never")

    @classmethod
    def every_nth_epoch(cls, n: int):
        return cls("nth", n)

    @classmethod
    def tick_tock(cls, on: int, off: int):
        return cls("ticktock", on, off)

    def encrypt_on_epoch(self, epoch: int) -> bool:
        if self.kind == "always":
            return True
        if self.kind == "never":
            return False
        if self.kind == "nth":
            return epoch % self.a == 0
        period = self.a + self.b
        return (epoch % period) < self.a


# -- messages ---------------------------------------------------------------


@dataclass(frozen=True)
class SubsetWrap:
    epoch: int
    msg: object


@dataclass(frozen=True)
class DecryptionShareWrap:
    epoch: int
    proposer_id: NodeId
    msg: DecryptionMessage


# -- batch ------------------------------------------------------------------


@dataclass(frozen=True)
class Batch:
    """Reference: ``src/honey_badger/batch.rs :: Batch<C, N>``."""

    epoch: int
    contributions: Tuple[Tuple[NodeId, bytes], ...]

    def contributions_map(self) -> Dict[NodeId, bytes]:
        return dict(self.contributions)

    def is_empty(self) -> bool:
        return not self.contributions


# -- epoch state ------------------------------------------------------------

_PLAIN = 0x00
_ENCRYPTED = 0x01


class _EpochState:
    """Reference: ``src/honey_badger/epoch_state.rs :: EpochState``."""

    def __init__(self, netinfo: NetworkInfo, session_id: bytes, epoch: int,
                 subset_handling_strategy=None):
        self.netinfo = netinfo
        self.epoch = epoch
        self.subset = Subset(
            netinfo, session_id + b"/hb-epoch/" + struct.pack(">Q", epoch),
            handling_strategy=(
                subset_handling_strategy or SubsetHandlingStrategy.Incremental
            ),
        )
        self.decrypts: Dict[NodeId, ThresholdDecrypt] = {}
        self.plain: Dict[NodeId, bytes] = {}
        self.excluded: set = set()
        self.subset_done = False
        self.accepted: set = set()

    def decrypted_all(self) -> bool:
        return self.subset_done and all(
            pid in self.plain or pid in self.excluded for pid in self.accepted
        )

    def batch(self) -> Batch:
        return Batch(
            epoch=self.epoch,
            contributions=tuple(
                sorted(self.plain.items(), key=lambda kv: repr(kv[0]))
            ),
        )


class HoneyBadgerBuilder:
    """Reference: ``src/honey_badger/builder.rs``."""

    def __init__(self, netinfo: NetworkInfo):
        self.netinfo = netinfo
        self._session_id = b"hb"
        self._max_future_epochs = 3
        self._encryption_schedule = EncryptionSchedule.always()
        self._subset_handling_strategy = None
        self._rng: Optional[random.Random] = None

    def session_id(self, sid: bytes) -> "HoneyBadgerBuilder":
        self._session_id = bytes(sid)
        return self

    def max_future_epochs(self, n: int) -> "HoneyBadgerBuilder":
        self._max_future_epochs = n
        return self

    def encryption_schedule(self, es: EncryptionSchedule) -> "HoneyBadgerBuilder":
        self._encryption_schedule = es
        return self

    def rng(self, rng: random.Random) -> "HoneyBadgerBuilder":
        self._rng = rng
        return self

    def subset_handling_strategy(self, s) -> "HoneyBadgerBuilder":
        """Reference: ``HoneyBadgerBuilder::subset_handling_strategy``."""
        self._subset_handling_strategy = s
        return self

    def build(self) -> "HoneyBadger":
        return HoneyBadger(
            self.netinfo,
            session_id=self._session_id,
            max_future_epochs=self._max_future_epochs,
            encryption_schedule=self._encryption_schedule,
            rng=self._rng or random.Random(0),
            subset_handling_strategy=self._subset_handling_strategy,
        )


class HoneyBadger(ConsensusProtocol):
    """Reference: ``src/honey_badger/honey_badger.rs :: HoneyBadger<C, N>``."""

    def __init__(
        self,
        netinfo: NetworkInfo,
        session_id: bytes = b"hb",
        max_future_epochs: int = 3,
        encryption_schedule: Optional[EncryptionSchedule] = None,
        rng: Optional[random.Random] = None,
        subset_handling_strategy=None,
    ):
        self.netinfo = netinfo
        self.session_id = bytes(session_id)
        self.epoch = 0
        self.max_future_epochs = max_future_epochs
        self.encryption_schedule = encryption_schedule or EncryptionSchedule.always()
        self.rng = rng or random.Random(0)
        self.subset_handling_strategy = subset_handling_strategy
        self.epochs: Dict[int, _EpochState] = {}
        self.has_input: Dict[int, bool] = {}
        self.completed: Dict[int, Batch] = {}

    @classmethod
    def builder(cls, netinfo: NetworkInfo) -> HoneyBadgerBuilder:
        return HoneyBadgerBuilder(netinfo)

    # -- ConsensusProtocol ---------------------------------------------------

    def our_id(self) -> NodeId:
        return self.netinfo.our_id()

    def terminated(self) -> bool:
        return False  # atomic broadcast runs forever

    def next_epoch(self) -> int:
        return self.epoch

    def handle_input(self, input: bytes) -> Step:
        return self.propose(input)

    def propose(self, contribution: bytes) -> Step:
        """Encrypt (per schedule) and propose into the current epoch's ACS.

        Reference: ``HoneyBadger::propose`` (HOT: TPKE encrypt —
        G1/G2 scalar muls).
        """
        if self.has_input.get(self.epoch):
            return Step()
        self.has_input[self.epoch] = True
        if self.encryption_schedule.encrypt_on_epoch(self.epoch):
            ct = (
                self.netinfo.public_key_set()
                .public_key()
                .encrypt(bytes(contribution), self.rng)
            )
            payload = bytes([_ENCRYPTED]) + ct.to_bytes()
        else:
            payload = bytes([_PLAIN]) + bytes(contribution)
        state = self._epoch_state(self.epoch)
        inner = state.subset.handle_input(payload)
        return self._process_subset_step(self.epoch, inner)

    def handle_message(self, sender_id: NodeId, message) -> Step:
        if not self.netinfo.is_node_validator(sender_id):
            return Step.from_fault(sender_id, FaultKind.UnknownSender)
        epoch = message.epoch
        if epoch < self.epoch:
            return Step()  # obsolete epoch
        if epoch > self.epoch + self.max_future_epochs:
            return Step.from_fault(sender_id, FaultKind.UnexpectedHbMessage)
        if isinstance(message, SubsetWrap):
            state = self._epoch_state(epoch)
            inner = state.subset.handle_message(sender_id, message.msg)
            return self._process_subset_step(epoch, inner)
        if isinstance(message, DecryptionShareWrap):
            if not self.netinfo.is_node_validator(message.proposer_id):
                # unknown proposer: reject before creating any state
                return Step.from_fault(
                    sender_id, FaultKind.UnexpectedDecryptionShare
                )
            state = self._epoch_state(epoch)
            td = self._decrypt_for(state, message.proposer_id)
            inner = td.handle_message(sender_id, message.msg)
            return self._process_decrypt_step(epoch, message.proposer_id, inner)
        raise TypeError(f"unknown honey_badger message {message!r}")

    # -- internals -----------------------------------------------------------

    def _epoch_state(self, epoch: int) -> _EpochState:
        if epoch not in self.epochs:
            self.epochs[epoch] = _EpochState(
                self.netinfo, self.session_id, epoch,
                subset_handling_strategy=self.subset_handling_strategy,
            )
        return self.epochs[epoch]

    def _decrypt_for(self, state: _EpochState, proposer_id: NodeId) -> ThresholdDecrypt:
        if proposer_id not in state.decrypts:
            state.decrypts[proposer_id] = ThresholdDecrypt(self.netinfo)
        return state.decrypts[proposer_id]

    def _process_subset_step(self, epoch: int, inner: Step) -> Step:
        step = inner.map(lambda m: SubsetWrap(epoch, m))
        state = self.epochs.get(epoch)
        if state is None:  # epoch already closed mid-step
            step.output = []
            return step
        outputs = step.output
        step.output = []
        for out in outputs:
            if isinstance(out, subset_mod.Contribution):
                step.extend(
                    self._on_accepted(epoch, out.proposer_id, out.value)
                )
            elif isinstance(out, subset_mod.Done):
                state.subset_done = True
        return step.extend(self._try_complete(epoch))

    def _on_accepted(self, epoch: int, proposer_id: NodeId, payload: bytes) -> Step:
        """An ACS-accepted contribution: plaintext or ciphertext to decrypt."""
        state = self.epochs[epoch]
        state.accepted.add(proposer_id)
        step = Step()
        if not payload:
            state.excluded.add(proposer_id)
            return step.fault(proposer_id, FaultKind.InvalidCiphertext)
        tag, body = payload[0], payload[1:]
        if tag == _PLAIN:
            state.plain[proposer_id] = body
            return step
        if tag != _ENCRYPTED:
            state.excluded.add(proposer_id)
            return step.fault(proposer_id, FaultKind.InvalidCiphertext)
        try:
            ct = tc.Ciphertext.from_bytes(body)
            ok = ct.verify()
        except (ValueError, IndexError):
            ok = False
        if not ok:
            # all correct nodes agree (same RBC bytes) → consistent exclusion
            state.excluded.add(proposer_id)
            return step.fault(proposer_id, FaultKind.InvalidCiphertext)
        td = self._decrypt_for(state, proposer_id)
        inner = td.set_ciphertext(ct)
        return step.extend(self._process_decrypt_step(epoch, proposer_id, inner))

    def _process_decrypt_step(
        self, epoch: int, proposer_id: NodeId, inner: Step
    ) -> Step:
        step = inner.map(
            lambda m: DecryptionShareWrap(epoch, proposer_id, m)
        )
        state = self.epochs.get(epoch)
        if state is None:  # epoch already closed mid-step
            step.output = []
            return step
        outputs = step.output
        step.output = []
        for plaintext in outputs:
            state.plain[proposer_id] = plaintext
        return step.extend(self._try_complete(epoch))

    def _try_complete(self, epoch: int) -> Step:
        """Close completed epochs in order (reference ``update_epoch``)."""
        state = self.epochs.get(epoch)
        if state is None:
            return Step()
        if epoch not in self.completed and state.decrypted_all():
            self.completed[epoch] = state.batch()
        step = Step()
        while self.epoch in self.completed:
            batch = self.completed.pop(self.epoch)
            step.output.append(batch)
            del self.epochs[self.epoch]
            self.has_input.pop(self.epoch, None)  # bound per-epoch state
            self.epoch += 1
        return step

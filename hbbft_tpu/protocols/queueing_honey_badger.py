"""Queueing HoneyBadger: the user-facing transaction buffer.

Reference: ``src/queueing_honey_badger/`` + ``src/transaction_queue.rs`` —
wraps ``DynamicHoneyBadger`` with a transaction queue: user transactions are
buffered; each epoch the node proposes a *random sample* of ``batch_size``
transactions (random so that distinct nodes' proposals overlap little —
the HoneyBadger paper's throughput trick); committed transactions are removed
everywhere; leftovers are re-proposed.

Divergence from the reference worth knowing: a node with an empty queue also
proposes an empty contribution once it sees consensus activity for the
current epoch, so epochs complete without requiring ≥ N−f non-empty queues.
"""

from __future__ import annotations

import random
from dataclasses import dataclass
from typing import Dict, Hashable, List, Optional, Sequence, Tuple

from hbbft_tpu.fault_log import FaultKind
from hbbft_tpu.netinfo import NetworkInfo
from hbbft_tpu.protocols import wire
from hbbft_tpu.protocols.dynamic_honey_badger import (
    Change,
    ChangeInput,
    ChangeState,
    DhbBatch,
    DynamicHoneyBadger,
    HbWrap,
    UserInput,
)
from hbbft_tpu.traits import ConsensusProtocol, Step

NodeId = Hashable


class TransactionQueue:
    """Reference: ``src/transaction_queue.rs :: trait TransactionQueue``.

    Random sampling (``choose``) keeps different nodes' batch proposals
    mostly disjoint, which is what makes N proposals per epoch add up to
    N× throughput instead of N× duplication.
    """

    def __init__(self):
        self._txs: List[bytes] = []
        self._set: Dict[bytes, int] = {}

    def extend(self, txs: Sequence[bytes]) -> None:
        for tx in txs:
            tx = bytes(tx)
            if tx not in self._set:
                self._set[tx] = 1
                self._txs.append(tx)

    def remove_multiple(self, txs) -> int:
        """Drop ``txs`` from the queue; returns how many were present
        (the overload guard's shed path needs to know whether a tx was
        actually still queued)."""
        # accept a pre-built set: the QHB commit prunes N queues with the
        # same epoch batch, and rebuilding the drop set per queue is O(N²)
        # across the network (16.7M hashes per epoch at N=4096)
        drop = txs if isinstance(txs, (set, frozenset)) else {
            bytes(t) for t in txs
        }
        if not drop:
            return 0
        before = len(self._txs)
        self._txs = [t for t in self._txs if t not in drop]
        # iterate the smaller side: a node's queue is usually far smaller
        # than the network-wide epoch batch
        if len(self._set) < len(drop):
            for t in [t for t in self._set if t in drop]:
                del self._set[t]
        else:
            for t in drop:
                self._set.pop(t, None)
        return before - len(self._txs)

    def choose(self, rng: random.Random, amount: int,
               exclude: Optional[set] = None) -> List[bytes]:
        """Sample ``amount`` txs; with ``exclude`` (the pipelined
        proposer's in-flight set), sample only txs not already riding an
        open epoch — a duplicate commit wastes a slot in BOTH epochs and
        holds the client's latency to the later one."""
        if exclude:
            fresh = [t for t in self._txs if t not in exclude]
            if amount >= len(fresh):
                return fresh
            return rng.sample(fresh, amount)
        if amount >= len(self._txs):
            return list(self._txs)
        return rng.sample(self._txs, amount)

    def __len__(self) -> int:
        return len(self._txs)


def _ser_txs(txs: Sequence[bytes]) -> bytes:
    out = wire.u32(len(txs))
    for tx in txs:
        out += wire.blob(tx)
    return out


def _de_txs(data: bytes) -> Tuple[bytes, ...]:
    r = wire.Reader(data)
    n = r.u32()
    if n > 1_000_000:
        raise ValueError("absurd tx count")
    return tuple(r.blob() for _ in range(n))


@dataclass(frozen=True)
class QhbBatch:
    """A committed batch of transactions (decoded DHB batch)."""

    era: int
    epoch: int
    contributions: Tuple[Tuple[NodeId, Tuple[bytes, ...]], ...]
    change: ChangeState

    def all_txs(self) -> List[bytes]:
        out = []
        seen = set()
        for _, txs in self.contributions:
            for tx in txs:
                if tx not in seen:
                    seen.add(tx)
                    out.append(tx)
        return out


@dataclass(frozen=True)
class TxInput:
    tx: bytes


@dataclass(frozen=True)
class PipelineInput:
    """Driver input: keep up to ``depth`` epochs proposed-into at once.

    The epoch-pipelined node runtime feeds one per pump iteration; a
    simulator can inject them between cranks to exercise the same
    concurrency deterministically.  ``depth=1`` is a no-op (the normal
    one-epoch-at-a-time proposal flow)."""

    depth: int


class QueueingHoneyBadgerBuilder:
    """Reference: ``queueing_honey_badger.rs :: QueueingHoneyBadgerBuilder``
    (batch_size + rng + queue knobs over a DynamicHoneyBadger)."""

    def __init__(self, dhb):
        self._dhb = dhb
        self._batch_size = 100
        self._rng = None
        self._queue = None

    def batch_size(self, n: int) -> "QueueingHoneyBadgerBuilder":
        self._batch_size = n
        return self

    def rng(self, rng) -> "QueueingHoneyBadgerBuilder":
        self._rng = rng
        return self

    def queue(self, q) -> "QueueingHoneyBadgerBuilder":
        self._queue = q
        return self

    def build(self) -> "QueueingHoneyBadger":
        return QueueingHoneyBadger(
            self._dhb,
            batch_size=self._batch_size,
            rng=self._rng,
            queue=self._queue,
        )


class QueueingHoneyBadger(ConsensusProtocol):
    """Reference: ``queueing_honey_badger.rs :: QueueingHoneyBadger<T,N,Q>``."""

    def __init__(
        self,
        dhb: DynamicHoneyBadger,
        batch_size: int = 100,
        rng: Optional[random.Random] = None,
        queue: Optional[TransactionQueue] = None,
    ):
        self.dhb = dhb
        self.batch_size = batch_size
        self.rng = rng or random.Random(0)
        self.queue = queue or TransactionQueue()
        self.dhb.empty_contribution = _ser_txs([])
        # pipelined proposals only: txs we proposed into epochs that have
        # not committed yet, keyed by (era, epoch) — propose_ahead samples
        # around them so concurrent epochs carry disjoint fresh txs
        # instead of duplicating in-flight ones (bounded by depth entries;
        # commits and era rotations prune)
        self._proposed: Dict[Tuple[int, int], Tuple[bytes, ...]] = {}
        # DHB's DKG keep-alive proposes REAL transactions, not empties
        self._install_provider()

    def _install_provider(self) -> None:
        self.dhb.contribution_provider = lambda: _ser_txs(
            self.queue.choose(self.rng, self.batch_size)
        )

    def __setstate__(self, state):
        # snapshot/restore: DHB drops the (unpicklable) provider closure
        self.__dict__.update(state)
        self.__dict__.setdefault("_proposed", {})
        self._install_provider()

    @classmethod
    def builder(cls, dhb) -> "QueueingHoneyBadgerBuilder":
        return QueueingHoneyBadgerBuilder(dhb)

    # -- ConsensusProtocol ---------------------------------------------------

    def our_id(self) -> NodeId:
        return self.dhb.our_id()

    def terminated(self) -> bool:
        return False

    def handle_input(self, input) -> Step:
        if isinstance(input, TxInput):
            return self.push_transaction(input.tx)
        if isinstance(input, ChangeInput):
            step = self.dhb.vote_for(input.change)
            return step.extend(self._maybe_propose(force=True))
        if isinstance(input, PipelineInput):
            return self.propose_ahead(input.depth)
        raise TypeError(f"unknown QHB input {input!r}")

    def push_transaction(self, tx: bytes) -> Step:
        """Buffer a transaction and propose if we haven't this epoch."""
        self.queue.extend([tx])
        return self._maybe_propose(force=True)

    def handle_message(self, sender_id: NodeId, message) -> Step:
        step = self._process(self.dhb.handle_message(sender_id, message))
        # if consensus activity exists for the current epoch and we haven't
        # proposed, contribute (possibly an empty sample) to keep it live
        # (the has_input pre-check keeps the common already-proposed case
        # allocation-free — _maybe_propose re-checks it authoritatively)
        if (
            isinstance(message, HbWrap)
            and message.era == self.dhb.era
            and not self.dhb.hb.has_input.get(self.dhb.hb.epoch)
            and self.dhb.hb.epoch in self.dhb.hb.epochs
        ):
            step.extend(self._maybe_propose(force=True))
        return step

    def propose_ahead(self, depth: int) -> Step:
        """Epoch pipelining: sample and propose into every epoch in
        ``[hb.epoch, hb.epoch + depth)`` that lacks our contribution, so
        epoch e+1's RBC/ABA starts while epoch e threshold-decrypts.

        Gated three ways: only with queued transactions (an idle cluster
        must not spin empty epochs), only while no membership change is in
        progress (a DKG rotation would orphan the future epochs' work),
        and never past the protocol's ``max_future_epochs`` window.  A
        transaction can be sampled into several in-flight epochs and then
        commit more than once; duplicate commits are idempotent at every
        consumer (queue pruning, mempool, client notification) — the
        standard cost of pipelined HoneyBadger, paid for ~depth× epoch
        concurrency."""
        if depth <= 1 or not self.dhb.is_validator():
            return Step()
        if self.dhb.change_state.state != "none":
            return Step()
        step = Step()
        for _ in range(depth):
            hb = self.dhb.hb  # re-read: _process can advance/rotate it
            base = hb.epoch
            off = next(
                (
                    k for k in range(min(depth, hb.max_future_epochs + 1))
                    if not hb.has_input.get(base + k)
                ),
                None,
            )
            if off is None or len(self.queue) == 0:
                break
            in_flight = (
                {t for txs in self._proposed.values() for t in txs}
                if self._proposed else None
            )
            sample = self.queue.choose(self.rng, self.batch_size,
                                       exclude=in_flight)
            if not sample:
                # every queued tx already rides an open epoch: an empty
                # filler proposal would spin cheap epochs that commit
                # nothing — let the pipeline refill from fresh traffic
                break
            self._proposed[(self.dhb.era, base + off)] = tuple(sample)
            step.extend(
                self._process(self.dhb.propose_ahead(_ser_txs(sample), off))
            )
        return step

    def has_deferred(self) -> bool:
        return self.dhb.has_deferred()

    def resolve_deferred(self) -> Step:
        return self._process(self.dhb.resolve_deferred())

    # -- internals -----------------------------------------------------------

    def in_flight_txs(self) -> set:
        """Txs riding a not-yet-committed proposal (sequential AND
        pipelined — both record into ``_proposed``): a shed of one of
        these cannot stop it committing, so the overload guard must not
        tell the client otherwise."""
        return {t for txs in self._proposed.values() for t in txs}

    def _maybe_propose(self, force: bool = False) -> Step:
        if not self.dhb.is_validator():
            return Step()
        if self.dhb.hb.has_input.get(self.dhb.hb.epoch):
            return Step()
        if not force and len(self.queue) == 0:
            return Step()
        sample = self.queue.choose(self.rng, self.batch_size)
        if sample:
            # recorded for propose_ahead's exclusion only — the sequential
            # path's own sampling is untouched (depth-1 determinism)
            self._proposed[(self.dhb.era, self.dhb.hb.epoch)] = tuple(sample)
        return self._process(self.dhb.propose(_ser_txs(sample)))

    def _process(self, inner: Step) -> Step:
        """Decode DHB batches into tx batches and update the queue."""
        if not inner.output:
            # nothing to decode and nothing dropped: the common
            # (mid-epoch) per-message case — pass the step through
            # without re-allocating it
            return inner
        step = Step(
            fault_log=inner.fault_log, messages=inner.messages
        )
        for out in inner.output:
            if not isinstance(out, DhbBatch):
                continue
            contribs: List[Tuple[NodeId, Tuple[bytes, ...]]] = []
            committed: List[bytes] = []
            for proposer, payload in out.contributions:
                try:
                    txs = _de_txs(payload)
                except ValueError:
                    step.fault(
                        proposer, FaultKind.BatchDeserializationFailed
                    )
                    continue
                contribs.append((proposer, txs))
                committed.extend(txs)
            self.queue.remove_multiple(committed)
            # this epoch's proposal landed (and any stale older-era /
            # older-epoch records with it): stop excluding its txs
            for k in [k for k in self._proposed
                      if k <= (out.era, out.epoch)]:
                del self._proposed[k]
            step.output.append(
                QhbBatch(
                    era=out.era,
                    epoch=out.epoch,
                    contributions=tuple(contribs),
                    change=out.change,
                )
            )
        # a batch completed → next epoch began: re-propose leftovers
        if step.output:
            step.extend(self._maybe_propose())
        return step

"""Asynchronous common subset (ACS).

Reference: ``src/subset/{subset.rs, proposal_state.rs}`` — runs one
``Broadcast`` and one ``BinaryAgreement`` per proposer.  BA_j gets input
``true`` as soon as RBC_j delivers; once N−f BAs have decided ``true``,
``false`` is input to every undecided BA.  The output is the set of
contributions whose BA decided ``true`` (each emitted incrementally as
``SubsetOutput.Contribution``), followed by ``SubsetOutput.Done`` when all
BAs have decided and all accepted values are in.

All correct nodes output the same ≥ N−f proposal set.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Dict, Hashable, Optional

from hbbft_tpu.fault_log import FaultKind
from hbbft_tpu.netinfo import NetworkInfo
from hbbft_tpu.protocols.binary_agreement import BinaryAgreement
from hbbft_tpu.protocols.broadcast import Broadcast
from hbbft_tpu.traits import ConsensusProtocol, Step

NodeId = Hashable


# -- messages (reference: Message::{Broadcast, Agreement}) -------------------


@dataclass(frozen=True)
class BroadcastWrap:
    proposer_id: NodeId
    msg: object


@dataclass(frozen=True)
class AgreementWrap:
    proposer_id: NodeId
    msg: object


class SubsetHandlingStrategy(enum.Enum):
    """When accepted contributions are released to the caller.

    Reference: ``src/subset/ :: SubsetHandlingStrategy`` (builder knob,
    [MED]).  ``Incremental`` emits each ``Contribution`` as soon as its BA
    decides true and the value is in hand (lower latency for callers that
    can start work per-contribution, e.g. spawning threshold-decrypts);
    ``AllAtEnd`` withholds them and emits the entire accepted set
    immediately before ``Done`` (single completion event).
    The decided *set* is identical either way.
    """

    Incremental = "incremental"
    AllAtEnd = "all_at_end"


# -- outputs (reference: SubsetOutput) ---------------------------------------


@dataclass(frozen=True)
class Contribution:
    proposer_id: NodeId
    value: bytes


@dataclass(frozen=True)
class Done:
    pass


class _ProposalState:
    """Reference: ``src/subset/proposal_state.rs :: ProposalState``."""

    def __init__(self, broadcast: Broadcast, agreement: BinaryAgreement):
        self.broadcast = broadcast
        self.agreement = agreement
        self.value: Optional[bytes] = None
        self.decision: Optional[bool] = None
        self.emitted = False


class Subset(ConsensusProtocol):
    """Reference: ``src/subset/subset.rs :: Subset<N, S>``."""

    def __init__(
        self,
        netinfo: NetworkInfo,
        session_id: bytes,
        handling_strategy: SubsetHandlingStrategy = (
            SubsetHandlingStrategy.Incremental
        ),
    ):
        self.netinfo = netinfo
        self.session_id = bytes(session_id)
        self.handling_strategy = handling_strategy
        self.proposals: Dict[NodeId, _ProposalState] = {}
        for pid in netinfo.all_ids():
            ba_session = self.session_id + b"/ba/" + repr(pid).encode()
            self.proposals[pid] = _ProposalState(
                Broadcast(netinfo, pid),
                BinaryAgreement(netinfo, ba_session, pid),
            )
        self.done = False
        self.false_inputs_sent = False
        # Per-sender message budget for this ONE ACS instance (overload
        # defense): honest traffic per sender is a few messages per
        # proposer for RBC plus ~6 per ABA round — even a long
        # coin-fought ABA stays well under this.  Past the budget a
        # sender's messages are dropped with a counted fault; the
        # count state is bounded by the validator set.
        self.msg_budget_per_sender = 4096 * max(1, netinfo.num_nodes())
        self._msg_counts: Dict[NodeId, int] = {}
        self.flood_drops: Dict[NodeId, int] = {}

    # -- ConsensusProtocol ---------------------------------------------------

    def our_id(self) -> NodeId:
        return self.netinfo.our_id()

    def terminated(self) -> bool:
        return self.done

    def handle_input(self, input: bytes) -> Step:
        """Propose our contribution via our own broadcast instance."""
        prop = self.proposals[self.our_id()]
        inner = prop.broadcast.handle_input(input)
        return self._process_broadcast_step(self.our_id(), inner)

    def handle_message(self, sender_id: NodeId, message) -> Step:
        if not self.netinfo.is_node_validator(sender_id):
            return Step.from_fault(sender_id, FaultKind.UnknownSender)
        count = self._msg_counts.get(sender_id, 0) + 1
        if count > self.msg_budget_per_sender:
            self.flood_drops[sender_id] = (
                self.flood_drops.get(sender_id, 0) + 1
            )
            return Step.from_fault(sender_id, FaultKind.SubsetMessageFlood)
        self._msg_counts[sender_id] = count
        if isinstance(message, BroadcastWrap):
            prop = self.proposals.get(message.proposer_id)
            if prop is None:
                return Step.from_fault(sender_id, FaultKind.InvalidSubsetMessage)
            inner = prop.broadcast.handle_message(sender_id, message.msg)
            return self._process_broadcast_step(message.proposer_id, inner)
        if isinstance(message, AgreementWrap):
            prop = self.proposals.get(message.proposer_id)
            if prop is None:
                return Step.from_fault(sender_id, FaultKind.InvalidSubsetMessage)
            inner = prop.agreement.handle_message(sender_id, message.msg)
            return self._process_agreement_step(message.proposer_id, inner)
        raise TypeError(f"unknown subset message {message!r}")

    # -- internals -----------------------------------------------------------

    def _process_broadcast_step(self, proposer_id: NodeId, inner: Step) -> Step:
        prop = self.proposals[proposer_id]
        step = inner.map(lambda m: BroadcastWrap(proposer_id, m))
        values = step.output
        step.output = []
        changed = False
        for value in values:
            if prop.value is None:
                prop.value = value
                changed = True
                # RBC delivered → vote to accept this proposal
                if prop.decision is None and prop.agreement.estimate is None:
                    ba_step = prop.agreement.handle_input(True)
                    step.extend(
                        self._process_agreement_step(proposer_id, ba_step)
                    )
        if not changed:
            # no new delivery → emission/threshold/Done state cannot have
            # moved: skip the all-proposals _try_progress scan (it runs
            # once per consensus message otherwise)
            return step
        return step.extend(self._try_progress())

    def _process_agreement_step(self, proposer_id: NodeId, inner: Step) -> Step:
        prop = self.proposals[proposer_id]
        step = inner.map(lambda m: AgreementWrap(proposer_id, m))
        decisions = step.output
        step.output = []
        changed = False
        for d in decisions:
            if prop.decision is None:
                prop.decision = bool(d)
                changed = True
        if not changed:
            return step
        return step.extend(self._try_progress())

    def _count_true(self) -> int:
        return sum(1 for p in self.proposals.values() if p.decision is True)

    def _try_progress(self) -> Step:
        if self.done:
            return Step()
        step = Step()
        n, f = self.netinfo.num_nodes(), self.netinfo.num_faulty()
        # emit newly-available accepted contributions (AllAtEnd withholds
        # them until the Done edge below)
        if self.handling_strategy is SubsetHandlingStrategy.Incremental:
            for pid in self.netinfo.all_ids():
                prop = self.proposals[pid]
                if (
                    prop.decision is True
                    and prop.value is not None
                    and not prop.emitted
                ):
                    prop.emitted = True
                    step.output.append(Contribution(pid, prop.value))
        # N−f accepted → vote false on the rest
        if self._count_true() >= n - f and not self.false_inputs_sent:
            self.false_inputs_sent = True
            for pid in self.netinfo.all_ids():
                prop = self.proposals[pid]
                if prop.decision is None and prop.agreement.estimate is None:
                    ba_step = prop.agreement.handle_input(False)
                    step.extend(
                        self._process_agreement_step(pid, ba_step)
                    )
        # all decided and all accepted values in hand → Done
        # (re-check self.done: a nested _try_progress via the false-input
        # loop may already have emitted it)
        all_decided = all(
            p.decision is not None for p in self.proposals.values()
        )
        if self.handling_strategy is SubsetHandlingStrategy.Incremental:
            complete = all(
                p.emitted or p.decision is False
                for p in self.proposals.values()
            )
        else:  # AllAtEnd: accepted values present, none emitted yet
            complete = all(
                p.decision is False or p.value is not None
                for p in self.proposals.values()
            )
        if not self.done and all_decided and complete:
            self.done = True
            if self.handling_strategy is SubsetHandlingStrategy.AllAtEnd:
                for pid in self.netinfo.all_ids():
                    prop = self.proposals[pid]
                    if prop.decision is True and not prop.emitted:
                        prop.emitted = True
                        step.output.append(Contribution(pid, prop.value))
            step.output.append(Done())
        return step

"""Dynamic HoneyBadger: validator-set changes via consensus-committed DKG.

Reference: ``src/dynamic_honey_badger/`` — ``dynamic_honey_badger.rs``,
``votes.rs`` (``VoteCounter``/``SignedVote``), ``change.rs`` (``Change``,
``ChangeState``), ``batch.rs``, plus the ``KeyGenMessage::{Part, Ack}``
plumbing, and ``JoinPlan`` for nodes joining at an era boundary.

Mechanism: every epoch, each validator's contribution is an
``InternalContrib`` — the user payload piggy-backed with its pending signed
votes and any signed key-gen messages it has observed.  Because these ride
through HoneyBadger, **all correct nodes process the same votes and DKG
messages in the same order** — exactly the external agreement ``SyncKeyGen``
requires.  When a ``Change`` gains a majority of validator votes it becomes
``ChangeState.InProgress``; the new validator set runs the DKG (candidates
send their ``Part``/``Ack`` as signed direct messages, validators commit
them); when the DKG is ready the era rotates: fresh ``NetworkInfo`` with the
new ``PublicKeySet`` and shares, a fresh inner ``HoneyBadger``, and the batch
reports ``ChangeState.Complete``.

Era boundaries are the join points: ``join_plan()`` packages everything a
new node needs to start at the next era.
"""

from __future__ import annotations

import random
from dataclasses import dataclass
from typing import Any, Dict, Hashable, List, Optional, Set, Tuple

from hbbft_tpu.crypto import tc
from hbbft_tpu.fault_log import FaultKind
from hbbft_tpu.netinfo import NetworkInfo
from hbbft_tpu.protocols import wire
from hbbft_tpu.protocols.honey_badger import (
    Batch as HbBatch,
    EncryptionSchedule,
    HoneyBadger,
)
from hbbft_tpu.protocols.sync_key_gen import Ack, Part, SyncKeyGen
from hbbft_tpu.traits import ConsensusProtocol, Step

NodeId = Hashable


# -- Change / ChangeState (reference: change.rs) -----------------------------


@dataclass(frozen=True)
class Change:
    """``Change::NodeChange(new validator key map)`` or
    ``Change::EncryptionSchedule(schedule)``."""

    kind: str  # "nodes" | "encryption_schedule"
    new_keys: Tuple[Tuple[NodeId, bytes], ...] = ()  # sorted (id, pk bytes)
    schedule: Tuple = ()

    @classmethod
    def node_change(cls, pub_keys: Dict[NodeId, tc.PublicKey]) -> "Change":
        return cls(
            "nodes",
            tuple(
                sorted(
                    ((nid, pk.to_bytes()) for nid, pk in pub_keys.items()),
                    key=lambda kv: repr(kv[0]),
                )
            ),
        )

    @classmethod
    def encryption_schedule(cls, es: EncryptionSchedule) -> "Change":
        return cls("encryption_schedule", schedule=(es.kind, es.a, es.b))

    def key_map(self) -> Dict[NodeId, tc.PublicKey]:
        return {nid: tc.PublicKey.from_bytes(pk) for nid, pk in self.new_keys}

    def to_bytes(self) -> bytes:
        if self.kind == "nodes":
            out = b"\x01" + wire.u32(len(self.new_keys))
            for nid, pk in self.new_keys:
                out += wire.node_id(nid) + wire.blob(pk)
            return out
        k, a, b = self.schedule
        return b"\x02" + wire.blob(k.encode()) + wire.u32(a) + wire.u32(b)

    @classmethod
    def read(cls, r: wire.Reader) -> "Change":
        tag = r.take(1)
        if tag == b"\x01":
            n = r.u32()
            if n > 100_000:
                raise ValueError("absurd validator count")
            keys = tuple((wire.read_node_id(r), r.blob()) for _ in range(n))
            return cls("nodes", keys)
        if tag == b"\x02":
            k = r.blob().decode()
            return cls("encryption_schedule", schedule=(k, r.u32(), r.u32()))
        raise ValueError("bad change tag")


@dataclass(frozen=True)
class ChangeState:
    """None / InProgress(change) / Complete(change)."""

    state: str  # "none" | "in_progress" | "complete"
    change: Optional[Change] = None

    @classmethod
    def none(cls):
        return cls("none")

    @classmethod
    def in_progress(cls, change: Change):
        return cls("in_progress", change)

    @classmethod
    def complete(cls, change: Change):
        return cls("complete", change)


# -- votes (reference: votes.rs) --------------------------------------------


@dataclass(frozen=True)
class SignedVote:
    voter: NodeId
    era: int
    num: int  # per-voter sequence number; later votes supersede earlier
    change: Change
    sig: tc.Signature

    def signed_payload(self) -> bytes:
        return _vote_payload(self.voter, self.era, self.num, self.change)

    def to_bytes(self) -> bytes:
        return (
            wire.node_id(self.voter)
            + wire.u64(self.era)
            + wire.u64(self.num)
            + wire.blob(self.change.to_bytes())
            + wire.signature(self.sig)
        )

    @classmethod
    def read(cls, r: wire.Reader) -> "SignedVote":
        voter = wire.read_node_id(r)
        era = r.u64()
        num = r.u64()
        change = Change.read(wire.Reader(r.blob()))
        sig = wire.read_signature(r)
        return cls(voter, era, num, change, sig)


def _vote_payload(voter: NodeId, era: int, num: int, change: Change) -> bytes:
    return (
        b"HBBFT-DHB-VOTE"
        + wire.node_id(voter)
        + wire.u64(era)
        + wire.u64(num)
        + change.to_bytes()
    )


class VoteCounter:
    """Reference: ``votes.rs :: VoteCounter`` — committed votes decide."""

    def __init__(self, era: int):
        self.era = era
        self.pending: Dict[NodeId, SignedVote] = {}
        self.committed: Dict[NodeId, SignedVote] = {}

    def add_pending(self, vote: SignedVote) -> None:
        cur = self.pending.get(vote.voter)
        if cur is None or cur.num < vote.num:
            self.pending[vote.voter] = vote

    def add_committed(self, vote: SignedVote) -> None:
        cur = self.committed.get(vote.voter)
        if cur is None or cur.num < vote.num:
            self.committed[vote.voter] = vote
        self.pending.pop(vote.voter, None)

    def pending_votes(self) -> List[SignedVote]:
        return sorted(self.pending.values(), key=lambda v: repr(v.voter))

    def compute_winner(self, validators: List[NodeId]) -> Optional[Change]:
        """The change voted for by a strict majority of current validators."""
        tally: Dict[Change, int] = {}
        for nid in validators:
            v = self.committed.get(nid)
            if v is not None:
                tally[v.change] = tally.get(v.change, 0) + 1
        for change, count in sorted(
            tally.items(), key=lambda kv: repr(kv[0])
        ):
            if count * 2 > len(validators):
                return change
        return None


# -- key-gen messages --------------------------------------------------------


def _keygen_payload(era: int, sender: NodeId, kind: str, payload: bytes) -> bytes:
    """Signing preimage for key-gen messages.  Every field is length-framed
    so the kind/payload boundary is not malleable under a valid signature."""
    return (
        b"HBBFT-DHB-KEYGEN"
        + wire.u64(era)
        + wire.node_id(sender)
        + wire.blob(kind.encode())
        + wire.blob(payload)
    )


@dataclass(frozen=True)
# hblint: disable=wire-unregistered (never travels bare: always inside
# the registered KeyGenWrap envelope, whose codec — enc_skg/dec_skg in
# wire._lazy_register — covers this class field-for-field)
class SignedKeyGenMsg:
    era: int
    sender: NodeId
    kind: str  # "part" | "ack"
    payload: bytes  # serialized Part or Ack
    sig: tc.Signature

    def signed_payload(self) -> bytes:
        return _keygen_payload(self.era, self.sender, self.kind, self.payload)

    def to_bytes(self) -> bytes:
        return (
            wire.u64(self.era)
            + wire.node_id(self.sender)
            + wire.blob(self.kind.encode())
            + wire.blob(self.payload)
            + wire.signature(self.sig)
        )

    @classmethod
    def read(cls, r: wire.Reader) -> "SignedKeyGenMsg":
        era = r.u64()
        sender = wire.read_node_id(r)
        kind = r.blob().decode()
        payload = r.blob()
        sig = wire.read_signature(r)
        return cls(era, sender, kind, payload, sig)


def ser_part(part: Part) -> bytes:
    out = wire.commitment_bivar(part.commitment)
    out += wire.u32(len(part.rows))
    for ct in part.rows:
        out += wire.ciphertext(ct)
    return out


def de_part(data: bytes) -> Part:
    r = wire.Reader(data)
    com = wire.read_commitment_bivar(r)
    n = r.u32()
    if n > 100_000:
        raise ValueError("absurd row count")
    rows = tuple(wire.read_ciphertext(r) for _ in range(n))
    return Part(com, rows)


def ser_ack(ack: Ack) -> bytes:
    out = wire.u32(ack.proposer_index) + wire.u32(len(ack.values))
    for ct in ack.values:
        out += wire.ciphertext(ct)
    return out


def de_ack(data: bytes) -> Ack:
    r = wire.Reader(data)
    proposer = r.u32()
    n = r.u32()
    if n > 100_000:
        raise ValueError("absurd value count")
    values = tuple(wire.read_ciphertext(r) for _ in range(n))
    return Ack(proposer, values)


# -- internal contribution ---------------------------------------------------


@dataclass
class InternalContrib:
    """What actually rides through HoneyBadger each epoch.

    Reference: ``dynamic_honey_badger.rs :: InternalContrib`` — user payload
    + pending votes + observed signed key-gen messages.
    """

    contribution: bytes
    votes: List[SignedVote]
    key_gen_msgs: List[SignedKeyGenMsg]

    def to_bytes(self) -> bytes:
        out = wire.blob(self.contribution)
        out += wire.u32(len(self.votes))
        for v in self.votes:
            out += wire.blob(v.to_bytes())
        out += wire.u32(len(self.key_gen_msgs))
        for m in self.key_gen_msgs:
            out += wire.blob(m.to_bytes())
        return out

    @classmethod
    def from_bytes(cls, data: bytes) -> "InternalContrib":
        r = wire.Reader(data)
        contribution = r.blob()
        nv = r.u32()
        if nv > 100_000:
            raise ValueError("absurd vote count")
        votes = [SignedVote.read(wire.Reader(r.blob())) for _ in range(nv)]
        nk = r.u32()
        if nk > 100_000:
            raise ValueError("absurd keygen count")
        kgs = [SignedKeyGenMsg.read(wire.Reader(r.blob())) for _ in range(nk)]
        return cls(contribution, votes, kgs)


# -- inputs / outputs --------------------------------------------------------


@dataclass(frozen=True)
class UserInput:
    contribution: bytes


@dataclass(frozen=True)
class ChangeInput:
    change: Change


@dataclass(frozen=True)
class DhbBatch:
    """Reference: ``dynamic_honey_badger/batch.rs``."""

    era: int
    epoch: int
    contributions: Tuple[Tuple[NodeId, bytes], ...]
    change: ChangeState

    def contributions_map(self) -> Dict[NodeId, bytes]:
        return dict(self.contributions)


@dataclass(frozen=True)
class JoinPlan:
    """Everything a node needs to join at the start of ``era``.

    Reference: ``dynamic_honey_badger.rs :: JoinPlan``.
    """

    era: int
    pub_key_set_bytes: bytes
    pub_keys: Tuple[Tuple[NodeId, bytes], ...]
    encryption_schedule: Tuple

    def public_key_set(self) -> tc.PublicKeySet:
        from hbbft_tpu.crypto import bls12_381 as bls

        data = self.pub_key_set_bytes
        pts = [
            bls.g1_from_bytes(data[i : i + 97])
            for i in range(0, len(data), 97)
        ]
        return tc.PublicKeySet(tc.Commitment(pts))

    def key_map(self) -> Dict[NodeId, tc.PublicKey]:
        return {nid: tc.PublicKey.from_bytes(pk) for nid, pk in self.pub_keys}


# -- messages ---------------------------------------------------------------


@dataclass(frozen=True)
class HbWrap:
    era: int
    msg: object


@dataclass(frozen=True)
class KeyGenWrap:
    era: int
    msg: SignedKeyGenMsg


class DynamicHoneyBadgerBuilder:
    """Reference: ``dynamic_honey_badger/builder.rs`` — the same typed knobs
    (era, rng, encryption schedule, epoch window)."""

    def __init__(self, netinfo: NetworkInfo, secret_key: tc.SecretKey):
        self._netinfo = netinfo
        self._secret_key = secret_key
        self._era = 0
        self._rng: Optional[random.Random] = None
        self._schedule: Optional[EncryptionSchedule] = None
        self._max_future_epochs = 3

    def era(self, era: int) -> "DynamicHoneyBadgerBuilder":
        self._era = era
        return self

    def rng(self, rng: random.Random) -> "DynamicHoneyBadgerBuilder":
        self._rng = rng
        return self

    def encryption_schedule(self, s: EncryptionSchedule) -> "DynamicHoneyBadgerBuilder":
        self._schedule = s
        return self

    def max_future_epochs(self, n: int) -> "DynamicHoneyBadgerBuilder":
        self._max_future_epochs = n
        return self

    def build(self) -> "DynamicHoneyBadger":
        return DynamicHoneyBadger(
            self._netinfo,
            self._secret_key,
            era=self._era,
            rng=self._rng,
            encryption_schedule=self._schedule,
            max_future_epochs=self._max_future_epochs,
        )


class DynamicHoneyBadger(ConsensusProtocol):
    """Reference: ``dynamic_honey_badger.rs :: DynamicHoneyBadger<C, N>``."""

    def __init__(
        self,
        netinfo: NetworkInfo,
        secret_key: tc.SecretKey,
        era: int = 0,
        rng: Optional[random.Random] = None,
        encryption_schedule: Optional[EncryptionSchedule] = None,
        max_future_epochs: int = 3,
    ):
        self.netinfo = netinfo
        self.secret_key = secret_key
        self.era = era
        self.rng = rng or random.Random(0)
        self.encryption_schedule = encryption_schedule or EncryptionSchedule.always()
        self.max_future_epochs = max_future_epochs
        self.vote_counter = VoteCounter(era)
        self.change_state: ChangeState = ChangeState.none()
        self.key_gen: Optional[SyncKeyGen] = None
        self.key_gen_change: Optional[Change] = None
        self.pending_kg: List[SignedKeyGenMsg] = []
        self.kg_seen: Set[bytes] = set()
        # the consensus-committed DKG transcript of the in-progress change
        # (every signature-valid key-gen message, in committed order) and,
        # after a node-change rotation, the completed era's transcript —
        # what a snapshot-joining node replays through its own SyncKeyGen
        # to decrypt its rows and derive its secret key share with zero
        # epoch replay (see hbbft_tpu.snapshot)
        self.kg_transcript: List[SignedKeyGenMsg] = []
        self.last_join_transcript: Tuple[SignedKeyGenMsg, ...] = ()
        self.vote_num = 0
        # next-era message buffer — budgeted PER SENDER (overload
        # defense): the old shared 100k cap let one Byzantine peer fill
        # the whole buffer (uncounted) and starve honest next-era
        # traffic.  Now each sender owns a slice; overflow drops ONLY
        # that sender's messages, counted in ``future_era_drops``.
        self.future_era: List[Tuple[NodeId, object]] = []
        self.future_era_cap_per_sender = 4096
        self._future_era_counts: Dict[NodeId, int] = {}
        self.future_era_drops: Dict[NodeId, int] = {}
        # what to propose when only the DKG needs the epoch to advance: a
        # wrapper (QueueingHoneyBadger) installs a provider that returns a
        # REAL contribution so throughput doesn't stall during a DKG
        self.contribution_provider: Optional[Any] = None
        self.empty_contribution: bytes = b""
        self.era_has_batches = False
        # epoch-pipelined runtimes set this (see HoneyBadger.defer_decrypt);
        # it must survive era rotation, so it lives here and _make_hb
        # stamps every inner HoneyBadger with it
        self.defer_decrypt_verify = False
        self.hb = self._make_hb()

    @classmethod
    def builder(cls, netinfo: NetworkInfo, secret_key: tc.SecretKey) -> "DynamicHoneyBadgerBuilder":
        return DynamicHoneyBadgerBuilder(netinfo, secret_key)

    @classmethod
    def from_join_plan(
        cls,
        our_id: NodeId,
        secret_key: tc.SecretKey,
        plan: JoinPlan,
        rng: Optional[random.Random] = None,
        secret_key_share: Optional[tc.SecretKeyShare] = None,
    ) -> "DynamicHoneyBadger":
        """Construct a node starting at an era boundary.

        Without ``secret_key_share`` the node is an observer (the
        reference's JoinPlan semantics); with one — derived by replaying
        the era's committed DKG transcript through ``SyncKeyGen`` (see
        :func:`hbbft_tpu.snapshot.derive_secret_share`) — it is a full
        validator from epoch 0 of the plan's era."""
        netinfo = NetworkInfo(
            our_id=our_id,
            public_keys=plan.key_map(),
            public_key_set=plan.public_key_set(),
            secret_key_share=secret_key_share,
            secret_key=secret_key,
        )
        k, a, b = plan.encryption_schedule
        return cls(
            netinfo,
            secret_key,
            era=plan.era,
            rng=rng,
            encryption_schedule=EncryptionSchedule(k, a, b),
        )

    def _make_hb(self) -> HoneyBadger:
        hb = HoneyBadger(
            self.netinfo,
            session_id=b"dhb-era-" + wire.u64(self.era),
            max_future_epochs=self.max_future_epochs,
            encryption_schedule=self.encryption_schedule,
            rng=random.Random(self.rng.getrandbits(64)),
        )
        hb.defer_decrypt = self.defer_decrypt_verify
        return hb

    # -- pickling (snapshot/restore support) ---------------------------------

    def __getstate__(self):
        # contribution_provider is a closure installed by wrappers
        # (QueueingHoneyBadger) — drop it; the wrapper reinstalls on restore
        d = self.__dict__.copy()
        d["contribution_provider"] = None
        return d

    # -- ConsensusProtocol ---------------------------------------------------

    def our_id(self) -> NodeId:
        return self.netinfo.our_id()

    def terminated(self) -> bool:
        return False

    def is_validator(self) -> bool:
        return self.netinfo.is_validator()

    def handle_input(self, input) -> Step:
        if isinstance(input, UserInput):
            return self.propose(input.contribution)
        if isinstance(input, ChangeInput):
            return self.vote_for(input.change)
        raise TypeError(f"unknown DHB input {input!r}")

    def propose(self, contribution: bytes) -> Step:
        """Wrap the user payload with pending votes + key-gen messages and
        propose it into the inner HoneyBadger."""
        if not self.is_validator():
            return Step()
        contrib = InternalContrib(
            contribution=bytes(contribution),
            votes=self.vote_counter.pending_votes(),
            key_gen_msgs=list(self.pending_kg),
        )
        inner = self.hb.propose(contrib.to_bytes())
        return self._process_hb_step(inner)

    def propose_ahead(self, contribution: bytes, offset: int) -> Step:
        """Propose into epoch ``hb.epoch + offset`` of the CURRENT era —
        the epoch-pipelining entry (``offset=0`` is plain ``propose``).

        The wrapped payload carries this node's pending votes/key-gen
        messages exactly like a current-epoch proposal; if the era rotates
        before the future epoch completes, its in-flight state dies with
        the old inner HoneyBadger and the transactions simply get
        re-proposed in the new era (they leave the queue only on commit).
        """
        if not self.is_validator():
            return Step()
        contrib = InternalContrib(
            contribution=bytes(contribution),
            votes=self.vote_counter.pending_votes(),
            key_gen_msgs=list(self.pending_kg),
        )
        inner = self.hb.propose_into(
            self.hb.epoch + offset, contrib.to_bytes()
        )
        return self._process_hb_step(inner)

    def has_deferred(self) -> bool:
        return self.hb.has_deferred()

    def resolve_deferred(self) -> Step:
        """Drain the inner HoneyBadger's parked decrypt verifications
        (see ``HoneyBadger.resolve_deferred``), with batch/era processing
        applied to whatever completes."""
        return self._process_hb_step(self.hb.resolve_deferred())

    def vote_for(self, change: Change) -> Step:
        """Sign and queue a vote (committed via a later contribution).

        Reference: ``DynamicHoneyBadger::vote_for``.
        """
        if not self.is_validator():
            return Step()
        self.vote_num += 1
        payload = _vote_payload(self.our_id(), self.era, self.vote_num, change)
        vote = SignedVote(
            self.our_id(),
            self.era,
            self.vote_num,
            change,
            self.secret_key.sign(payload),
        )
        self.vote_counter.add_pending(vote)
        return Step()

    def vote_to_add(self, node_id: NodeId, pub_key: tc.PublicKey) -> Step:
        keys = dict(self.netinfo.public_key_map())
        keys[node_id] = pub_key
        return self.vote_for(Change.node_change(keys))

    def vote_to_remove(self, node_id: NodeId) -> Step:
        keys = dict(self.netinfo.public_key_map())
        keys.pop(node_id, None)
        return self.vote_for(Change.node_change(keys))

    def handle_message(self, sender_id: NodeId, message) -> Step:
        if isinstance(message, HbWrap):
            if message.era < self.era:
                return Step()
            if message.era > self.era:
                if message.era > self.era + 1:
                    return Step.from_fault(
                        sender_id, FaultKind.UnexpectedHbMessage
                    )
                count = self._future_era_counts.get(sender_id, 0)
                if count >= self.future_era_cap_per_sender:
                    # counted drop of the SPAMMER's overflow only —
                    # other senders' next-era slices are untouched
                    self.future_era_drops[sender_id] = (
                        self.future_era_drops.get(sender_id, 0) + 1
                    )
                    return Step.from_fault(
                        sender_id, FaultKind.FutureEpochFlood
                    )
                self._future_era_counts[sender_id] = count + 1
                self.future_era.append((sender_id, message))
                return Step()
            inner = self.hb.handle_message(sender_id, message.msg)
            return self._process_hb_step(inner)
        if isinstance(message, KeyGenWrap):
            if message.era != self.era:
                return Step()
            return self._observe_key_gen_msg(sender_id, message.msg)
        raise TypeError(f"unknown DHB message {message!r}")

    # -- key-gen message flow ------------------------------------------------

    def _kg_key_map(self) -> Dict[NodeId, tc.PublicKey]:
        """Who may sign key-gen messages: current validators + candidates."""
        keys = dict(self.netinfo.public_key_map())
        if self.key_gen_change is not None:
            keys.update(self.key_gen_change.key_map())
        return keys

    def _observe_key_gen_msg(self, sender_id: NodeId, skg: SignedKeyGenMsg) -> Step:
        """A validator observed a signed Part/Ack: queue it for inclusion in
        our next contribution (after signature screening)."""
        key = skg.to_bytes()
        if key in self.kg_seen:
            return Step()
        if skg.era != self.era or skg.sender != sender_id:
            return Step.from_fault(sender_id, FaultKind.InvalidKeyGenMessage)
        pk = self._kg_key_map().get(skg.sender)
        if pk is None or not pk.verify(skg.sig, skg.signed_payload()):
            return Step.from_fault(sender_id, FaultKind.InvalidKeyGenMessage)
        self.kg_seen.add(key)
        self.pending_kg.append(skg)
        return Step()

    def _send_key_gen_msg(self, kind: str, payload: bytes) -> Step:
        skg = SignedKeyGenMsg(
            era=self.era,
            sender=self.our_id(),
            kind=kind,
            payload=payload,
            sig=self.secret_key.sign(
                _keygen_payload(self.era, self.our_id(), kind, payload)
            ),
        )
        self.kg_seen.add(skg.to_bytes())
        self.pending_kg.append(skg)
        step = Step()
        step.send_all(KeyGenWrap(self.era, skg))
        return step

    # -- batch processing ----------------------------------------------------

    def _process_hb_step(self, inner: Step) -> Step:
        step = inner.map(lambda m: HbWrap(self.era, m))
        batches = step.output
        step.output = []
        for hb_batch in batches:
            step.extend(self._process_batch(hb_batch))
        return step

    def _process_batch(self, hb_batch: HbBatch) -> Step:
        step = Step()
        contributions: List[Tuple[NodeId, bytes]] = []
        all_kg: List[Tuple[NodeId, SignedKeyGenMsg]] = []
        for proposer, payload in hb_batch.contributions:
            try:
                contrib = InternalContrib.from_bytes(payload)
            except (ValueError, TypeError, UnicodeDecodeError):
                step.fault(proposer, FaultKind.BatchDeserializationFailed)
                continue
            contributions.append((proposer, contrib.contribution))
            for vote in contrib.votes:
                step.extend(self._commit_vote(proposer, vote))
            for skg in contrib.key_gen_msgs:
                all_kg.append((proposer, skg))
        # winner check happens before applying this batch's keygen messages:
        # a fresh InProgress change means the DKG starts with this batch
        if self.change_state.state == "none":
            winner = self.vote_counter.compute_winner(self.netinfo.all_ids())
            if winner is not None:
                step.extend(self._start_change(winner))
        # committed key-gen messages, in deterministic batch order
        for proposer, skg in all_kg:
            step.extend(self._apply_committed_kg(proposer, skg))
        # this era now has a completed epoch (set BEFORE rotation: _rotate
        # resets it for the new era, and replayed new-era batches re-set it)
        era_of_batch = self.era
        epoch_of_batch = hb_batch.epoch
        self.era_has_batches = True
        # era rotation check: if this batch completed the change, the batch
        # itself reports Complete (reference batch semantics)
        rot_step, completed = self._try_rotate_era()
        batch_change = (
            ChangeState.complete(completed)
            if completed is not None
            else self.change_state
        )
        batch = DhbBatch(
            era=era_of_batch,
            epoch=epoch_of_batch,
            contributions=tuple(contributions),
            change=batch_change,
        )
        step.output.append(batch)
        step.extend(rot_step)
        # keep the pipeline moving while a DKG is pending
        if (
            self.key_gen is not None
            and self.is_validator()
            and not self.hb.has_input.get(self.hb.epoch)
        ):
            contrib = (
                self.contribution_provider()
                if self.contribution_provider is not None
                else self.empty_contribution
            )
            step.extend(self.propose(contrib))
        return step

    def _commit_vote(self, proposer: NodeId, vote: SignedVote) -> Step:
        if vote.era != self.era:
            return Step()
        if not self.netinfo.is_node_validator(vote.voter):
            return Step.from_fault(proposer, FaultKind.InvalidVoteSignature)
        pk = self.netinfo.public_key(vote.voter)
        if pk is None or not pk.verify(vote.sig, vote.signed_payload()):
            return Step.from_fault(proposer, FaultKind.InvalidVoteSignature)
        self.vote_counter.add_committed(vote)
        return Step()

    def _start_change(self, change: Change) -> Step:
        self.change_state = ChangeState.in_progress(change)
        step = Step()
        if change.kind == "encryption_schedule":
            # no DKG needed: rotate immediately at the next batch boundary
            return step
        # start the DKG among the new validator set
        self.key_gen_change = change
        self.kg_transcript = []
        new_keys = change.key_map()
        threshold = (len(new_keys) - 1) // 3
        self.key_gen = SyncKeyGen(
            self.our_id(),
            self.secret_key,
            new_keys,
            threshold,
            random.Random(self.rng.getrandbits(64)),
        )
        if self.our_id() in new_keys:
            part = self.key_gen.generate_part()
            step.extend(self._send_key_gen_msg("part", ser_part(part)))
        return step

    def _apply_committed_kg(self, proposer: NodeId, skg: SignedKeyGenMsg) -> Step:
        if self.key_gen is None or skg.era != self.era:
            return Step()
        # committed: no need to re-propose it ourselves anymore
        key = skg.to_bytes()
        self.kg_seen.add(key)
        self.pending_kg = [m for m in self.pending_kg if m.to_bytes() != key]
        pk = self._kg_key_map().get(skg.sender)
        if pk is None or not pk.verify(skg.sig, skg.signed_payload()):
            return Step.from_fault(proposer, FaultKind.InvalidKeyGenMessage)
        # transcript entry: every signature-valid committed message, in
        # committed order — a snapshot joiner replaying these through its
        # own SyncKeyGen reaches the identical complete-dealer set (the
        # messages below that SyncKeyGen rejects, it rejects identically)
        self.kg_transcript.append(skg)
        step = Step()
        try:
            if skg.kind == "part":
                outcome = self.key_gen.handle_part(skg.sender, de_part(skg.payload))
                if outcome.fault is not None:
                    return step.fault(skg.sender, outcome.fault)
                if outcome.ack is not None:
                    step.extend(
                        self._send_key_gen_msg("ack", ser_ack(outcome.ack))
                    )
            elif skg.kind == "ack":
                outcome = self.key_gen.handle_ack(skg.sender, de_ack(skg.payload))
                if outcome.fault is not None:
                    return step.fault(skg.sender, outcome.fault)
            else:
                # the signature covers the framed kind, so a bad kind is the
                # SIGNER's doing — but a malformed frame could only have come
                # from the proposer; blame whoever actually authored it
                return step.fault(skg.sender, FaultKind.InvalidKeyGenMessage)
        except ValueError:
            return step.fault(skg.sender, FaultKind.InvalidKeyGenMessage)
        return step

    # -- era rotation --------------------------------------------------------

    def _try_rotate_era(self) -> Tuple[Step, Optional[Change]]:
        """Returns (step, completed_change) — the change is not None iff the
        era rotated now."""
        if self.change_state.state != "in_progress":
            return Step(), None
        change = self.change_state.change
        if change.kind == "encryption_schedule":
            k, a, b = change.schedule
            self.encryption_schedule = EncryptionSchedule(k, a, b)
            return self._rotate(change, self.netinfo), change
        assert self.key_gen is not None
        if not self.key_gen.is_ready():
            return Step(), None
        pub_key_set, sk_share = self.key_gen.generate()
        new_keys = change.key_map()
        netinfo = NetworkInfo(
            our_id=self.our_id(),
            public_keys=new_keys,
            public_key_set=pub_key_set,
            secret_key_share=sk_share,
            secret_key=self.secret_key,
        )
        return self._rotate(change, netinfo), change

    def _rotate(self, change: Change, netinfo: NetworkInfo) -> Step:
        self.netinfo = netinfo
        self.era += 1
        self.era_has_batches = False
        self.change_state = ChangeState.none()
        self.vote_counter = VoteCounter(self.era)
        # a node-change era carries its DKG transcript to the boundary:
        # join_plan() + last_join_transcript is the complete snapshot a
        # joiner needs (an encryption-schedule rotation keeps the old key
        # material, so its transcript is empty and joiners fall back to
        # config-derived shares — see snapshot.derive_secret_share)
        self.last_join_transcript = (
            tuple(self.kg_transcript) if change.kind == "nodes" else ()
        )
        self.kg_transcript = []
        self.key_gen = None
        self.key_gen_change = None
        self.pending_kg = []
        self.kg_seen = set()
        self.vote_num = 0
        self.hb = self._make_hb()
        step = Step()
        # replay buffered next-era messages
        future, self.future_era = self.future_era, []
        self._future_era_counts.clear()
        for sender, msg in future:
            if msg.era == self.era:
                step.extend(self.handle_message(sender, msg))
        return step

    # -- join plan -----------------------------------------------------------

    def join_plan(self) -> JoinPlan:
        """Information for a node joining at the CURRENT era boundary.

        Only valid while no epoch of this era has completed: a joiner cannot
        replay epochs whose messages it never received, so it must observe
        the era from its very start (the reference produces JoinPlans only
        at era rotation for the same reason).  Raises mid-era.
        """
        if self.era_has_batches:
            raise ValueError(
                "join_plan() is only valid at an era boundary (epochs of "
                "this era already completed; rotate the era first)"
            )
        from hbbft_tpu.crypto import bls12_381 as bls

        pks = self.netinfo.public_key_set()
        return JoinPlan(
            era=self.era,
            pub_key_set_bytes=b"".join(
                bls.g1_to_bytes(p) for p in pks.commitment.points
            ),
            pub_keys=tuple(
                sorted(
                    (
                        (nid, pk.to_bytes())
                        for nid, pk in self.netinfo.public_key_map().items()
                    ),
                    key=lambda kv: repr(kv[0]),
                )
            ),
            encryption_schedule=(
                self.encryption_schedule.kind,
                self.encryption_schedule.a,
                self.encryption_schedule.b,
            ),
        )

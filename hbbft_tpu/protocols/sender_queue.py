"""SenderQueue: buffer messages for peers in earlier epochs.

Reference: ``src/sender_queue/`` — wraps HoneyBadger/DHB/QHB so that
messages addressed to a peer that has not yet reached the message's epoch
are held back until the peer announces (via ``EpochStarted``) that it can
process them, bounding "future epoch" drops/faults on real networks where
nodes progress at different speeds.

Epoch keys are (era, epoch) tuples ordered lexicographically; plain
HoneyBadger uses era 0.  A message is deliverable to a peer once
``msg_key ≤ (peer_era, peer_epoch + max_future_epochs)``.
"""

from __future__ import annotations

import bisect
from dataclasses import dataclass
from typing import Any, Callable, Dict, Hashable, Iterable, List, Optional, Tuple

from hbbft_tpu.protocols.dynamic_honey_badger import (
    DhbBatch,
    DynamicHoneyBadger,
    HbWrap,
    KeyGenWrap,
)
from hbbft_tpu.protocols.honey_badger import (
    Batch as HbBatch,
    DecryptionShareWrap,
    HoneyBadger,
    SubsetWrap,
)
from hbbft_tpu.protocols.queueing_honey_badger import QhbBatch, QueueingHoneyBadger
from hbbft_tpu.protocols.vid import VidDisperse, VidVote
from hbbft_tpu.traits import ConsensusProtocol, Step, Target, TargetedMessage

NodeId = Hashable
EpochKey = Tuple[int, int]


@dataclass(frozen=True)
class EpochStarted:
    key: EpochKey


@dataclass(frozen=True)
class AlgoMessage:
    msg: Any


def message_key(msg: Any) -> EpochKey:
    """The (era, epoch) a message belongs to.

    Every message type the wrapped algorithms emit is enumerated; an unknown
    type is a bug in the wrapper, not an always-deliverable message, so it
    raises instead of silently bypassing the buffering discipline."""
    if isinstance(msg, (SubsetWrap, DecryptionShareWrap)):
        return (0, msg.epoch)
    if isinstance(msg, HbWrap):
        inner = msg.msg
        if isinstance(inner, (SubsetWrap, DecryptionShareWrap)):
            return (msg.era, inner.epoch)
        raise TypeError(
            f"SenderQueue: unknown HbWrap inner message {type(inner).__name__}"
        )
    if isinstance(msg, KeyGenWrap):
        return (msg.era, 0)
    if isinstance(msg, (VidDisperse, VidVote)):
        # dispersal runs ahead of the epoch it will be proposed into:
        # deliverable to any peer inside the message's era
        return (msg.era, 0)
    raise TypeError(
        f"SenderQueue: no epoch key rule for {type(msg).__name__}"
    )


def _algo_key(algo: Any) -> EpochKey:
    if isinstance(algo, QueueingHoneyBadger):
        return (algo.dhb.era, algo.dhb.hb.epoch)
    if isinstance(algo, DynamicHoneyBadger):
        return (algo.era, algo.hb.epoch)
    if isinstance(algo, HoneyBadger):
        return (0, algo.epoch)
    raise TypeError(f"SenderQueue cannot wrap {type(algo)!r}")


def _algo_window(algo: Any) -> int:
    if isinstance(algo, QueueingHoneyBadger):
        return algo.dhb.max_future_epochs
    if isinstance(algo, DynamicHoneyBadger):
        return algo.max_future_epochs
    return algo.max_future_epochs


#: default per-peer backlog ceiling — several full epochs of traffic at
#: any tested topology, far above what an honest laggard accumulates
#: inside its delivery window
DEFAULT_BUFFERED_CAP = 2048


class SenderQueue(ConsensusProtocol):
    """Reference: ``src/sender_queue/mod.rs :: SenderQueue<D>``."""

    def __init__(self, algo: Any, *,
                 buffered_cap: int = DEFAULT_BUFFERED_CAP,
                 on_evict: Optional[Callable[[NodeId, int], None]] = None):
        self.algo = algo
        self.peer_epochs: Dict[NodeId, EpochKey] = {}
        # per-peer buffered (key, message) — HARD-CAPPED per peer: a
        # voted-in joiner that never connects (or a peer wedged far
        # behind its window) must not grow this without bound.  At the
        # cap the backlog front-chops its OLDEST (lowest-epoch) entries,
        # counted per peer: a peer that far behind recovers via snapshot
        # state-sync, which lands it at the current era boundary where
        # the RETAINED (newest) entries are exactly the deliverable ones.
        self.buffered: Dict[NodeId, List[Tuple[EpochKey, Any]]] = {}
        self.buffered_cap = int(buffered_cap)
        self.evictions: Dict[NodeId, int] = {}
        self.on_evict = on_evict
        # run-long high-water mark of any peer's backlog, recorded
        # BEFORE the cap chops (so a broken chop shows up as a growing
        # peak — a post-chop reading would hold ≤ cap by construction
        # and could never fail).  A working cap keeps this ≤ cap + 1
        # (the one just-inserted entry).  Plain int: samplers on other
        # threads read it without racing the list mutations.
        self.buffered_peak = 0
        self.last_announced: Optional[EpochKey] = None
        # _known_peers runs once per posted Step (hot path): cache the
        # sorted peer list, keyed on what can change it — a new peer in
        # peer_epochs or a fresh NetworkInfo after an era rotation
        self._peers_cache: Optional[List[NodeId]] = None
        self._peers_not_us: List[NodeId] = []
        self._peers_cache_key: Tuple[Any, int] = (None, -1)

    def startup_step(self) -> Step:
        """Announce our epoch so peers learn we exist.

        An observer/candidate is not in the validators' ``netinfo``, so their
        SenderQueues would never address it; its ``EpochStarted`` broadcast
        registers it with every peer (reference: the sender queue's peer
        transitions).  Call once when joining a network.
        """
        cur = _algo_key(self.algo)
        self.last_announced = cur
        return Step().send(Target.all(), EpochStarted(cur))

    # -- ConsensusProtocol ---------------------------------------------------

    def our_id(self) -> NodeId:
        return self.algo.our_id()

    def terminated(self) -> bool:
        return self.algo.terminated()

    def handle_input(self, input) -> Step:
        return self._post(self.algo.handle_input(input))

    def handle_message(self, sender_id: NodeId, message) -> Step:
        if isinstance(message, EpochStarted):
            return self._peer_advanced(sender_id, message.key)
        if isinstance(message, AlgoMessage):
            return self._post(self.algo.handle_message(sender_id, message.msg))
        raise TypeError(f"unknown sender_queue message {message!r}")

    def handle_message_batch(self, sender_id: NodeId, messages, *,
                             pre=None, on_error=None) -> Step:
        """Handle a whole received batch from one peer, merging the
        per-message Steps into ONE (the runtime's batch-handle fast
        path: one absorb/dispatch per network chunk instead of one per
        message).  Semantically identical to calling
        :meth:`handle_message` per message and joining the Steps —
        output/fault/message order is the concatenation in batch order.

        ``pre(message)`` runs before each message (span/flight hooks);
        ``on_error(message, exc)`` absorbs a per-message ``TypeError``
        (protocol-rejected message — Byzantine attribution) so one bad
        message cannot void the rest of the batch; without it the error
        propagates as before.
        """
        step = Step()
        for message in messages:
            if pre is not None:
                pre(message)
            try:
                step.extend(self.handle_message(sender_id, message))
            except TypeError as exc:
                if on_error is None:
                    raise
                on_error(message, exc)
        return step

    # -- pipelined-runtime passthroughs --------------------------------------

    def has_deferred(self) -> bool:
        """Whether the wrapped algorithm parked deferred crypto work."""
        probe = getattr(self.algo, "has_deferred", None)
        return bool(probe()) if probe is not None else False

    def resolve_deferred(self) -> Step:
        """Drain the wrapped algorithm's deferred crypto (batched share
        verification), with the usual epoch-gated buffering applied to
        whatever messages the resolution emits."""
        resolver = getattr(self.algo, "resolve_deferred", None)
        if resolver is None:
            return Step()
        return self._post(resolver())

    # -- internals -----------------------------------------------------------

    def _cap_backlog(self, peer: NodeId) -> None:
        """Enforce the per-peer backlog ceiling: front-chop the lowest
        (era, epoch) entries beyond ``buffered_cap``, counted.  Epoch
        priority on purpose — the retained NEWEST entries are the ones a
        state-sync'd joiner (activated at the current era boundary) can
        actually use; entries that old were only reachable through a
        full replay the peer has already lost.  Backlogs are kept
        key-sorted at insertion (bisect in ``_post``; ``reinit_peer``
        merges pre-sorted), so the chop is O(drop), not a re-sort per
        buffered message once a peer pins at the cap."""
        entries = self.buffered.get(peer)
        if entries is None:
            return
        if len(entries) > self.buffered_peak:
            self.buffered_peak = len(entries)    # pre-chop, on purpose
        if len(entries) > self.buffered_cap:
            drop = len(entries) - self.buffered_cap
            del entries[:drop]
            self.evictions[peer] = self.evictions.get(peer, 0) + drop
            if self.on_evict is not None:
                self.on_evict(peer, drop)

    def buffered_len(self, peer: NodeId) -> int:
        return len(self.buffered.get(peer, ()))

    def _deliverable(self, key: Optional[EpochKey], peer: NodeId) -> bool:
        if key is None:
            return True
        era, epoch = self.peer_epochs.get(peer, (0, 0))
        window = _algo_window(self.algo)
        return key <= (era, epoch + window)

    def reinit_peer(
        self,
        peer: NodeId,
        key: EpochKey,
        history: Iterable[Tuple[EpochKey, Any]] = (),
    ) -> Step:
        """A peer restarted at ``key``, below its recorded epoch: rewind its
        record and re-feed it the epoch-ordered backlog.

        ``history`` is the caller's replay log of messages that were already
        handed to the network for this peer (the net runtime retains the
        recent (key, message) pairs it sent; ``_peer_advanced`` alone cannot
        help a restarted peer because those messages left the buffer when
        they were first deliverable).  The backlog — history merged with
        anything still buffered here — is re-run through the buffering
        discipline: messages within the peer's new window are re-sent now,
        the rest are held back and flow in order as the peer announces
        ``EpochStarted`` progress while it replays the protocol.

        Duplicates at the peer are safe: the protocols treat a repeated
        well-typed message as a no-op or a logged fault, never corruption.
        The merged backlog is value-deduped so a flapping peer (one
        reinit per reconnect, and reconnects come in pairs — dial and
        accept hellos) cannot accumulate copies of the same held-back
        entries across calls.
        """
        merged = sorted(
            list(history) + self.buffered.pop(peer, []),
            key=lambda kv: kv[0],
        )
        seen: set = set()
        backlog: List[Tuple[EpochKey, Any]] = []
        for entry in merged:
            if entry in seen:
                continue
            seen.add(entry)
            backlog.append(entry)
        self.peer_epochs[peer] = key
        step = Step()
        keep: List[Tuple[EpochKey, Any]] = []
        for mkey, msg in backlog:
            if self._deliverable(mkey, peer):
                step.send_to(peer, AlgoMessage(msg))
            else:
                keep.append((mkey, msg))
        if keep:
            self.buffered[peer] = keep
            self._cap_backlog(peer)
        # re-announce ourselves so the restarted peer learns our epoch and
        # can address us immediately
        cur = _algo_key(self.algo)
        step.send_to(peer, EpochStarted(cur))
        return step

    def _peer_advanced(self, peer: NodeId, key: EpochKey) -> Step:
        cur = self.peer_epochs.get(peer)
        if cur is not None and key <= cur:
            return Step()
        self.peer_epochs[peer] = key  # also registers unknown observers
        step = Step()
        held = self.buffered.pop(peer, [])
        keep: List[Tuple[EpochKey, Any]] = []
        for mkey, msg in held:
            if self._deliverable(mkey, peer):
                step.send_to(peer, AlgoMessage(msg))
            else:
                keep.append((mkey, msg))
        if keep:
            self.buffered[peer] = keep
        return step

    def _post(self, inner: Step) -> Step:
        """Wrap outgoing messages, buffering ones their target can't use yet,
        and announce our own epoch transitions.

        Deliverable recipients of one message share ONE ``AlgoMessage`` /
        ``TargetedMessage`` pair (a multi-node target) instead of a
        per-peer triple — the runtime's ``_dispatch`` resolves targets and
        already encodes per unique inner message, so per-peer wrapping
        only allocated; it never changed what went on the wire."""
        step = Step(output=inner.output, fault_log=inner.fault_log)
        self._known_peers()  # refresh the cache pair
        peers = self._peers_not_us
        window = _algo_window(self.algo)
        peer_epochs = self.peer_epochs
        for tm in inner.messages:
            key = message_key(tm.message)
            target = tm.target
            ready: Optional[List[NodeId]] = None
            for peer in peers:
                if not target.contains(peer):
                    continue
                era, epoch = peer_epochs.get(peer, (0, 0))
                if key <= (era, epoch + window):
                    if ready is None:
                        ready = []
                    ready.append(peer)
                else:
                    # key-sorted insertion (stable within a key): keeps
                    # the backlog in epoch order so the cap's front-chop
                    # and the release paths never need a sort
                    bisect.insort(
                        self.buffered.setdefault(peer, []),
                        (key, tm.message), key=lambda kv: kv[0],
                    )
                    self._cap_backlog(peer)
            if ready is not None:
                # ALWAYS an explicit node set — never Target.all(): the
                # driver resolves all() against ITS OWN membership view
                # (transport peers / every sim node), which may exceed
                # _known_peers and would bypass the per-peer epoch-gated
                # buffering this wrapper exists to enforce
                step.send(Target.nodes(ready), AlgoMessage(tm.message))
        cur = _algo_key(self.algo)
        if self.last_announced is None or cur > self.last_announced:
            self.last_announced = cur
            step.send(Target.all(), EpochStarted(cur))
        return step

    def _known_peers(self) -> List[NodeId]:
        netinfo = (
            self.algo.dhb.netinfo
            if isinstance(self.algo, QueueingHoneyBadger)
            else self.algo.netinfo
        )
        # the cached netinfo is held by strong reference, so an `is`
        # check can never be fooled by id reuse after an era rotation
        cached_ni, cached_n = self._peers_cache_key
        if (self._peers_cache is None or cached_ni is not netinfo
                or cached_n != len(self.peer_epochs)):
            known = set(netinfo.all_ids()) | set(self.peer_epochs.keys())
            self._peers_cache = sorted(known, key=repr)
            us = self.our_id()
            self._peers_not_us = [n for n in self._peers_cache if n != us]
            self._peers_cache_key = (netinfo, len(self.peer_epochs))
        return self._peers_cache

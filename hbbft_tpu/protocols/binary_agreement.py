"""Binary agreement (ABA) — Mostéfaoui–Moumen–Raynal, signature-free rounds
with a threshold-signature common coin.

Reference: ``src/binary_agreement/`` — ``binary_agreement.rs`` (the epoch
loop), ``sbv_broadcast.rs`` (the BVal/Aux "synchronized binary value"
sub-protocol), ``bool_set.rs`` (we use ``frozenset`` of bools).

Per epoch: nodes BVal-broadcast their estimate; f+1 matching BVals trigger a
relay, 2f+1 admit the value into ``bin_values`` and trigger one Aux; N−f Aux
messages whose values are all in ``bin_values`` close SBV with the supported
value set ``vals``.  A Conf round (N−f Confs with sets ⊆ bin_values) guards
the coin flip.  Then the common coin (fixed true/false for the first two of
every three epochs — the Moumen schedule that defeats the MITM delay attack —
and a ``ThresholdSign`` coin every third): if ``vals == {coin}`` decide;
if ``vals`` is a singleton, carry it as the next estimate; else adopt the
coin.  Decided nodes broadcast ``Term(b)``, which substitutes for their
BVal/Aux/Conf in all later epochs; f+1 ``Term(b)`` is itself a decision.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Dict, FrozenSet, Hashable, List, Optional, Set, Tuple

from hbbft_tpu.fault_log import FaultKind
from hbbft_tpu.netinfo import NetworkInfo
from hbbft_tpu.protocols.threshold_sign import ThresholdSign, ThresholdSignMessage
from hbbft_tpu.traits import ConsensusProtocol, Step

NodeId = Hashable

BoolSet = FrozenSet[bool]
NONE: BoolSet = frozenset()
BOTH: BoolSet = frozenset((True, False))

#: legitimate distinct messages per future epoch per sender (2×BVal,
#: 2×Aux, Conf, Coin, slack): the per-sender future-buffer cap is
#: ``FUTURE_CAP_PER_EPOCH * (max_future_epochs + 1)`` — shared with the
#: chaos campaign's guard witness so the asserted bound can never
#: silently diverge from the enforced one
FUTURE_CAP_PER_EPOCH = 8
DEFAULT_MAX_FUTURE_EPOCHS = 16


# -- messages (reference: binary_agreement message.rs) ----------------------


@dataclass(frozen=True)
class BValMsg:
    epoch: int
    value: bool


@dataclass(frozen=True)
class AuxMsg:
    epoch: int
    value: bool


@dataclass(frozen=True)
class ConfMsg:
    epoch: int
    values: BoolSet


@dataclass(frozen=True)
class TermMsg:
    value: bool


@dataclass(frozen=True)
class CoinMsg:
    epoch: int
    msg: ThresholdSignMessage


class SbvBroadcast:
    """Synchronized binary value broadcast (one instance per ABA epoch).

    Reference: ``src/binary_agreement/sbv_broadcast.rs``.
    Message emission is returned as (to_send, step) where ``to_send`` lists
    ('bval'|'aux', bool) broadcasts for the owner to wrap with epoch tags.
    """

    def __init__(self, n: int, f: int):
        self.n = n
        self.f = f
        self.bval_received: Dict[bool, Set[NodeId]] = {True: set(), False: set()}
        self.aux_received: Dict[bool, Set[NodeId]] = {True: set(), False: set()}
        self.bval_sent: Set[bool] = set()
        self.aux_sent = False
        self.bin_values: Set[bool] = set()
        self.output: Optional[BoolSet] = None

    def send_bval(self, value: bool) -> List[Tuple[str, bool]]:
        if value in self.bval_sent:
            return []
        self.bval_sent.add(value)
        return [("bval", value)]

    def handle_bval(
        self, sender: NodeId, value: bool
    ) -> Tuple[List[Tuple[str, bool]], Optional[FaultKind]]:
        if sender in self.bval_received[value]:
            # Same-value repeat: benign, NOT evidence.  A Term legitimately
            # substitutes for its sender's BVal/Aux (see _handle_term), so
            # under reordering an honest node's genuine BVal can arrive
            # after its Term already seeded these sets — faulting repeats
            # would accuse honest nodes.  (The reference's DuplicateBVal
            # fault kind is therefore intentionally not reproduced.)
            return [], None
        self.bval_received[value].add(sender)
        out: List[Tuple[str, bool]] = []
        count = len(self.bval_received[value])
        if count >= self.f + 1 and value not in self.bval_sent:
            # hblint: disable=bounded-ingress (a set of BOOLS: the value
            # domain caps it at two members)
            self.bval_sent.add(value)
            out.append(("bval", value))
        if count >= 2 * self.f + 1 and value not in self.bin_values:
            # hblint: disable=bounded-ingress (same two-member bool set)
            self.bin_values.add(value)
            if not self.aux_sent:
                self.aux_sent = True
                out.append(("aux", value))
        return out, None

    def handle_aux(
        self, sender: NodeId, value: bool
    ) -> Optional[FaultKind]:
        if sender in self.aux_received[value]:
            return None  # benign repeat — see handle_bval
        self.aux_received[value].add(sender)
        return None

    def try_output(self) -> Optional[BoolSet]:
        """vals = the set of aux-supported bin_values once support ≥ N−f."""
        if not self.bin_values:
            return None
        senders: Set[NodeId] = set()
        vals: Set[bool] = set()
        for v in self.bin_values:
            if self.aux_received[v]:
                vals.add(v)
                senders |= self.aux_received[v]
        # only count aux from senders whose value ∈ bin_values
        if len(senders) >= self.n - self.f and vals:
            self.output = frozenset(vals)
            return self.output
        return None


class BinaryAgreement(ConsensusProtocol):
    """Reference: ``src/binary_agreement/binary_agreement.rs``."""

    def __init__(
        self,
        netinfo: NetworkInfo,
        session_id: bytes,
        proposer_id: NodeId,
        max_future_epochs: int = DEFAULT_MAX_FUTURE_EPOCHS,
    ):
        self.netinfo = netinfo
        self.session_id = bytes(session_id)
        self.proposer_id = proposer_id
        self.n = netinfo.num_nodes()
        self.f = netinfo.num_faulty()
        self.epoch = 0
        self.estimate: Optional[bool] = None
        self.decision: Optional[bool] = None
        self.sbv = SbvBroadcast(self.n, self.f)
        self.conf_round = False
        self.conf_vals: Optional[BoolSet] = None
        self.conf_received: Dict[NodeId, BoolSet] = {}
        self.coin: Optional[ThresholdSign] = None
        self.coin_value: Optional[bool] = None
        self.terms: Dict[NodeId, bool] = {}
        self.term_sent = False
        # future-epoch buffer: deduplicated, bounded per sender (≤ ~8
        # distinct messages per epoch are legitimate: 2×BVal, 2×Aux, Conf,
        # Coin, slack) so one Byzantine peer cannot grow memory unboundedly.
        # Overflow is a counted EPOCH-PRIORITY eviction of the offending
        # sender's own entries (never another peer's): the sender's
        # farthest-future message goes first, because the lowest-epoch
        # entries are the ones the protocol will need soonest.
        self.future: Set[Tuple[NodeId, object]] = set()
        self.max_future_epochs = max_future_epochs
        self.future_cap_per_sender = (
            FUTURE_CAP_PER_EPOCH * (max_future_epochs + 1))
        self.future_evictions: Dict[NodeId, int] = {}
        # run-long high-water mark of any single sender's buffered
        # entries, recorded BEFORE eviction — a working cap keeps this
        # ≤ cap + 1 (the just-inserted entry), and a broken eviction
        # shows up as a growing peak.  (A post-eviction reading would
        # hold ≤ cap by construction and could never fail.)
        self.future_peak = 0

    # -- ConsensusProtocol ---------------------------------------------------

    def our_id(self) -> NodeId:
        return self.netinfo.our_id()

    def terminated(self) -> bool:
        return self.decision is not None

    def handle_input(self, input: bool) -> Step:
        if self.estimate is not None or self.decision is not None:
            return Step()
        self.estimate = bool(input)
        return self._broadcast_sbv(self.sbv.send_bval(self.estimate))

    def handle_message(self, sender_id: NodeId, message) -> Step:
        if not self.netinfo.is_node_validator(sender_id):
            return Step.from_fault(sender_id, FaultKind.UnknownSender)
        if self.decision is not None:
            return Step()
        if isinstance(message, TermMsg):
            return self._handle_term(sender_id, message.value)
        ep = message.epoch
        if ep < self.epoch:
            return Step()  # obsolete
        if ep > self.epoch:
            if ep > self.epoch + self.max_future_epochs:
                return Step.from_fault(
                    sender_id, FaultKind.AgreementEpochMismatch
                )
            entry = (sender_id, message)
            if entry not in self.future:
                self.future.add(entry)
                mine = [e for e in self.future if e[0] == sender_id]
                if len(mine) > self.future_peak:
                    self.future_peak = len(mine)  # pre-evict, on purpose
                if len(mine) > self.future_cap_per_sender:
                    # counted epoch-priority eviction of the SPAMMER's
                    # own farthest-future entry (which may be the one
                    # just admitted) — deterministic victim choice so
                    # the simulator's byte-identity replays hold
                    victim = max(
                        mine,
                        key=lambda e: (getattr(e[1], "epoch", 0),
                                       repr(e[1])),
                    )
                    self.future.discard(victim)
                    self.future_evictions[sender_id] = (
                        self.future_evictions.get(sender_id, 0) + 1
                    )
                    return Step.from_fault(
                        sender_id, FaultKind.AgreementEpochMismatch
                    )
            return Step()
        return self._handle_current(sender_id, message)

    # -- epoch machinery -----------------------------------------------------

    def _handle_current(self, sender_id: NodeId, message) -> Step:
        step = Step()
        if isinstance(message, BValMsg):
            out, fault = self.sbv.handle_bval(sender_id, message.value)
            if fault:
                return Step.from_fault(sender_id, fault)
            step.extend(self._broadcast_sbv(out))
        elif isinstance(message, AuxMsg):
            fault = self.sbv.handle_aux(sender_id, message.value)
            if fault:
                return Step.from_fault(sender_id, fault)
        elif isinstance(message, ConfMsg):
            if sender_id in self.conf_received:
                if self.conf_received[sender_id] == message.values:
                    return Step()  # network replay — idempotent
                return Step.from_fault(sender_id, FaultKind.MultipleConf)
            self.conf_received[sender_id] = message.values
        elif isinstance(message, CoinMsg):
            if self.coin is None:
                self._make_coin()
            ts_step = self.coin.handle_message(sender_id, message.msg).map(
                lambda m: CoinMsg(self.epoch, m)
            )
            ts_step.output.clear()  # the Signature is consumed via coin state
            step.extend(ts_step)
        else:
            raise TypeError(f"unknown BA message {message!r}")
        step.extend(self._progress())
        return step

    def _progress(self) -> Step:
        """Drive the epoch pipeline: SBV → Conf → coin → update/decide."""
        step = Step()
        if self.decision is not None:
            return step
        if self.conf_vals is None:
            vals = self.sbv.try_output()
            if vals is None:
                return step
            self.conf_vals = vals
            step.send_all(ConfMsg(self.epoch, vals))
            step.extend(self._handle_conf_self(vals))
            return step.extend(self._progress())
        # conf round: count confs with values ⊆ bin_values (+Term senders)
        count = sum(
            1
            for v in self.conf_received.values()
            if v <= frozenset(self.sbv.bin_values)
        )
        if count < self.n - self.f:
            return step
        # flip/invoke the coin
        if self.coin_value is None:
            sched = self._coin_schedule()
            if sched == "true":
                self.coin_value = True
            elif sched == "false":
                self.coin_value = False
            else:
                if self.coin is None:
                    self._make_coin()
                if not self.coin.had_input:
                    ts_step = self.coin.sign().map(
                        lambda m: CoinMsg(self.epoch, m)
                    )
                    ts_step.output.clear()
                    step.extend(ts_step)
                if self.coin.signature is None:
                    return step
                self.coin_value = self.coin.signature.parity()
        return step.extend(self._apply_coin())

    def _handle_conf_self(self, vals: BoolSet) -> Step:
        self.conf_received[self.our_id()] = vals
        return Step()

    def _apply_coin(self) -> Step:
        """MMR decision rule on our SBV value set ``vals`` and the coin:
        vals == {coin} → decide; singleton {v} → carry v; BOTH → adopt coin."""
        coin = self.coin_value
        vals = self.conf_vals
        step = Step()
        if vals == frozenset((coin,)):
            return step.extend(self._decide(coin))
        if len(vals) == 1:
            (est,) = vals
        else:
            est = coin
        return step.extend(self._next_epoch(est))

    def _next_epoch(self, est: bool) -> Step:
        self.epoch += 1
        self.sbv = SbvBroadcast(self.n, self.f)
        self.conf_vals = None
        self.conf_received = {}
        self.coin = None
        self.coin_value = None
        self.estimate = est
        step = Step()
        # decided peers participate via their recorded Terms
        for nid, b in self.terms.items():
            self.sbv.bval_received[b].add(nid)
            self.sbv.aux_received[b].add(nid)
            self.conf_received[nid] = frozenset((b,))
        step.extend(self._broadcast_sbv(self.sbv.send_bval(est)))
        # replay queued future-epoch messages for this epoch
        future, self.future = self.future, set()
        for sender, msg in sorted(future, key=repr):
            if getattr(msg, "epoch", None) == self.epoch:
                step.extend(self._handle_current(sender, msg))
            else:
                self.future.add((sender, msg))
        return step

    def _decide(self, b: bool) -> Step:
        if self.decision is not None:
            return Step()
        self.decision = b
        step = Step.from_output(b)
        if not self.term_sent:
            self.term_sent = True
            step.send_all(TermMsg(b))
        return step

    def _handle_term(self, sender_id: NodeId, value: bool) -> Step:
        if sender_id in self.terms:
            if self.terms[sender_id] == value:
                return Step()
            return Step.from_fault(sender_id, FaultKind.MultipleTerm)
        self.terms[sender_id] = value
        step = Step()
        # f+1 Terms for b: safe to decide b
        if sum(1 for v in self.terms.values() if v == value) >= self.f + 1:
            return step.extend(self._decide(value))
        # a Term also acts as BVal+Aux+Conf for the current epoch
        out, _ = self.sbv.handle_bval(sender_id, value)
        step.extend(self._broadcast_sbv(out))
        self.sbv.handle_aux(sender_id, value)
        self.conf_received.setdefault(sender_id, frozenset((value,)))
        step.extend(self._progress())
        return step

    def _broadcast_sbv(self, out: List[Tuple[str, bool]]) -> Step:
        """Send queued SBV broadcasts and loop them back to ourselves."""
        step = Step()
        for kind, value in out:
            if kind == "bval":
                step.send_all(BValMsg(self.epoch, value))
                o2, _ = self.sbv.handle_bval(self.our_id(), value)
                step.extend(self._broadcast_sbv(o2))
            else:
                step.send_all(AuxMsg(self.epoch, value))
                self.sbv.handle_aux(self.our_id(), value)
        if out:
            step.extend(self._progress())
        return step

    # -- coin ----------------------------------------------------------------

    def _coin_schedule(self) -> str:
        """Moumen schedule: epochs 0,1 mod 3 are fixed, every third is random.

        Reference: the coin-schedule optimization in
        ``binary_agreement.rs`` (defeats the MITM delay attack of
        ``tests/binary_agreement_mitm.rs`` while saving two coin flips in
        three).
        """
        m = self.epoch % 3
        if m == 0:
            return "true"
        if m == 1:
            return "false"
        return "random"

    def _make_coin(self) -> None:
        nonce = (
            b"HBBFT-ABA-COIN"
            + struct.pack(">I", len(self.session_id))
            + self.session_id
            + repr(self.proposer_id).encode()
            + struct.pack(">Q", self.epoch)
        )
        self.coin = ThresholdSign(self.netinfo)
        self.coin.set_document(nonce)

"""Verifiable information dispersal: availability decoupled from ordering.

The DispersedLedger (NSDI '22) construction on top of this stack: instead
of reliable-broadcasting every full contribution through the epoch's
subset (classic HoneyBadger, where one bandwidth-starved node drags every
commit), a proposer **disperses** its contribution — RS-encodes it with
the same coder/framing as RBC, ships each node exactly ONE shard plus its
Merkle proof, and collects ``n − f`` signed availability votes into a
*retrievability certificate*.  Consensus then orders only the constant-size
``(root, cert)`` commitment; the payload is **retrieved** lazily (fetch any
``k = n − 2f`` shards, reconstruct through the LRU'd Gauss–Jordan pattern
caches, re-verify against the committed root) off the ordering critical
path — see :mod:`hbbft_tpu.net.retrieve` for the fetch/reconstruct service.

Protocol pieces, all sans-I/O:

- :class:`VidDisperse` / :class:`VidVote` ride the normal SenderQueue
  message path (era-keyed, see ``sender_queue.message_key``);
  :class:`VidRetrieve` / :class:`VidShard` are driver-level messages the
  node runtime routes directly (retrieval is a network service, not a
  consensus round).
- :class:`Disperser` holds the per-node dispersal state: proposer-side
  vote collection and receiver-side shard storage + voting.
- :class:`VidQueueingHoneyBadger` is QHB in VID mode: ``_maybe_propose``
  disperses first and proposes the ``VID1``-prefixed commitment once the
  cert completes; committed epochs surface as :class:`VidQhbBatch`
  (raw ordered payloads, **no** ``all_txs`` — transactions exist only
  after retrieval).  Plain (non-``VID1``) contributions — the empty
  keep-alive and the DKG provider's — decode inline as before, so mixed
  batches are first-class.

Trust model: a cert proves ``n − f`` nodes hold proof-valid shards under
``root``, of which ``≥ n − 2f = k`` are honest — enough to reconstruct.
A Byzantine proposer can still commit a root whose leaves are NOT an RS
codeword; retrieval catches this deterministically (any ``k`` proof-valid
shards reconstruct, re-encode, and re-root — a non-codeword mismatches for
EVERY subset) and the contribution resolves to nothing, attributed to the
proposer.  Certs are verified at batch decode against the batch era's key
map; a cert that rode an era rotation (decoded after the local key map
rotated) is accepted as ordered — ordering is already final there and the
retrieval re-verification still binds the payload to the root.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, Hashable, List, Optional, Tuple

from hbbft_tpu.crypto import bls12_381 as _bls
from hbbft_tpu.crypto import tc
from hbbft_tpu.fault_log import FaultKind
from hbbft_tpu.ops import rs
from hbbft_tpu.ops.merkle import MerkleTree, Proof
from hbbft_tpu.protocols import wire
from hbbft_tpu.protocols.broadcast import _encode_value
from hbbft_tpu.protocols.dynamic_honey_badger import ChangeState, DhbBatch
from hbbft_tpu.protocols.queueing_honey_badger import (
    QueueingHoneyBadger,
    _de_txs,
    _ser_txs,
)
from hbbft_tpu.traits import Step

NodeId = Hashable

#: domain separator for availability-vote transcripts (the same plain
#: per-era BLS keys the authenticated transport signs hellos with)
VOTE_DOMAIN = b"hbbft-vid-avail/"

#: magic prefix marking a DHB contribution as a VID commitment; anything
#: else decodes through the classic ``_de_txs`` path
COMMIT_MAGIC = b"VID1"

#: proposer-side payload retention for local post-commit resolution (own
#: contributions never round-trip the network)
_PAYLOAD_KEEP = 64

#: receiver-side cache of cast votes (re-disperses re-send, never re-sign)
_VOTED_KEEP = 256


def vote_transcript(era: int, root: bytes, total_len: int) -> bytes:
    return VOTE_DOMAIN + wire.u64(era) + root + wire.u64(total_len)


def payload_digest(payload: bytes) -> str:
    """Short hex digest the audit corroborates cert vs retrieval with."""
    return hashlib.sha3_256(payload).hexdigest()[:16]


# ===========================================================================
# Wire messages
# ===========================================================================


@dataclass(frozen=True)
class VidDisperse:
    """Proposer → node ``proof.index``: your shard of ``root``."""

    era: int
    root: bytes
    total_len: int
    proof: Proof


@dataclass(frozen=True)
class VidVote:
    """Node → proposer: signed "I hold my shard of ``root``"."""

    era: int
    root: bytes
    sig: tc.Signature


@dataclass(frozen=True)
class VidCert:
    """``n − f`` availability votes: the retrievability certificate the
    epoch orders (inside a ``VID1`` contribution payload)."""

    era: int
    root: bytes
    total_len: int
    votes: Tuple[Tuple[NodeId, tc.Signature], ...]


@dataclass(frozen=True)
class VidRetrieve:
    """Requester → peer: send me your stored shard of ``root``."""

    root: bytes


@dataclass(frozen=True)
class VidShard:
    """Peer → requester: my shard of ``root`` with its inclusion proof."""

    root: bytes
    total_len: int
    proof: Proof


# ===========================================================================
# Commitment payload codec
# ===========================================================================


def encode_commitment(cert: VidCert) -> bytes:
    return COMMIT_MAGIC + wire.encode_message(cert)


def decode_commitment(payload: bytes) -> Optional[VidCert]:
    """``VID1`` payload → :class:`VidCert`; ``None`` for plain payloads.

    Raises ``ValueError`` on a ``VID1`` prefix over garbage — the caller
    faults the proposer exactly like a ``_de_txs`` failure.
    """
    if not payload.startswith(COMMIT_MAGIC):
        return None
    msg = wire.decode_message(payload[len(COMMIT_MAGIC):])
    if not isinstance(msg, VidCert):
        raise ValueError("VID1 payload does not contain a VidCert")
    return msg


def verify_cert(cert: VidCert, netinfo) -> bool:
    """``n − f`` distinct validator votes, each a valid signature over the
    cert's transcript, checked against ``netinfo``'s key map.

    Every vote signs the SAME transcript, so the whole cert verifies with
    one aggregated pairing check (sum the G1 keys, sum the G2 signatures)
    instead of one pairing per vote — the per-epoch cost that dominated
    VID commit latency.  Rogue-key aggregation is not a concern here: the
    per-node keys come from the trusted keygen/DKG key map, never from
    the cert itself.  If the aggregate fails (some signature is garbage)
    fall back to counting individually valid votes, so a cert carrying
    ``n − f`` good votes plus junk still verifies exactly as before."""
    need = netinfo.num_nodes() - netinfo.num_faulty()
    transcript = vote_transcript(cert.era, cert.root, cert.total_len)
    pairs = []
    seen = set()
    for nid, sig in cert.votes:
        if nid in seen:
            continue
        seen.add(nid)
        pk = netinfo.public_key(nid)
        if pk is not None:
            pairs.append((pk, sig))
    if len(pairs) < need:
        return False
    agg_pk = pairs[0][0].point
    agg_sig = pairs[0][1].point
    for pk, sig in pairs[1:]:
        agg_pk = _bls.g1_add(agg_pk, pk.point)
        agg_sig = _bls.g2_add(agg_sig, sig.point)
    if _bls.pairing_check([
        (_bls.g1_neg(_bls.G1_GEN), agg_sig),
        (agg_pk, _bls.hash_g2(transcript)),
    ]):
        return True
    valid = sum(1 for pk, sig in pairs if pk.verify(sig, transcript))
    return valid >= need


# ===========================================================================
# Committed-batch type (ordering output, pre-retrieval)
# ===========================================================================


@dataclass(frozen=True)
class VidQhbBatch:
    """An ordered epoch in VID mode: raw contribution payloads, each
    either a ``VID1`` commitment (transactions pending retrieval) or a
    plain ``_ser_txs`` payload (resolved inline).  Deliberately has NO
    ``all_txs`` — the driver owns resolution and journals ``commit`` /
    ``commit_retrieved`` itself."""

    era: int
    epoch: int
    contributions: Tuple[Tuple[NodeId, bytes], ...]
    change: ChangeState

    def commitments(self) -> List[Tuple[NodeId, VidCert]]:
        """The (proposer, cert) pairs still needing retrieval."""
        out = []
        for proposer, payload in self.contributions:
            if payload.startswith(COMMIT_MAGIC):
                cert = decode_commitment(payload)
                if cert is not None:
                    out.append((proposer, cert))
        return out

    def plain_txs(self) -> List[Tuple[NodeId, Tuple[bytes, ...]]]:
        """The non-VID contributions, decoded (pre-validated in
        ``_process`` — a payload that fails here was never included)."""
        out = []
        for proposer, payload in self.contributions:
            if not payload.startswith(COMMIT_MAGIC):
                out.append((proposer, _de_txs(payload)))
        return out


@dataclass(frozen=True)
class VidCertReady:
    """Step output marking a completed dispersal: the driver journals the
    ``vid_cert`` audit note from it (root / length / payload digest), the
    corroboration anchor for every later ``vid_retrieved`` note."""

    era: int
    root: bytes
    total_len: int
    payload_sha3: str


# ===========================================================================
# Dispersal engine
# ===========================================================================


@dataclass
class _Pending:
    era: int
    total_len: int
    need: int
    votes: Dict[NodeId, tc.Signature] = field(default_factory=dict)


class Disperser:
    """Per-node sans-I/O dispersal state: proposer-side encode + vote
    collection, receiver-side proof-checked shard storage + voting.

    ``store`` is the bounded shard store shared with the retrieval
    service (:class:`hbbft_tpu.net.retrieve.ShardStore` or anything with
    its ``put``/``proof_for`` surface)."""

    def __init__(self, store):
        self.store = store
        self._pending: Dict[bytes, _Pending] = {}
        self._payloads: "OrderedDict[bytes, bytes]" = OrderedDict()
        self._voted: "OrderedDict[Tuple[int, bytes, int], object]" = \
            OrderedDict()
        # deterministic plain-int counters (metrics snapshot these)
        self.disperses = 0
        self.votes_cast = 0
        self.certs = 0

    # -- proposer side -------------------------------------------------------

    def disperse(self, era: int, netinfo, payload: bytes
                 ) -> Tuple[bytes, Step]:
        """Encode ``payload``, ship each node its shard + proof, store our
        own, and open vote collection (our own vote pre-counted)."""
        n = netinfo.num_nodes()
        coder = rs.for_n_f(n, netinfo.num_faulty())
        shards, leaves = _encode_value(coder, payload)
        tree = MerkleTree.from_shards(shards, leaves)
        root = tree.root_hash()
        total_len = len(payload)
        our = netinfo.our_id()
        step = Step()
        for nid in netinfo.all_ids():
            proof = tree.proof(netinfo.node_index(nid))
            if nid == our:
                self.store.put(root, total_len, proof)
            else:
                step.send_to(nid, VidDisperse(era, root, total_len, proof))
        self._payloads[root] = payload
        while len(self._payloads) > _PAYLOAD_KEEP:
            self._payloads.popitem(last=False)
        sig = netinfo.secret_key().sign(
            vote_transcript(era, root, total_len))
        self._pending[root] = _Pending(
            era=era, total_len=total_len,
            need=n - netinfo.num_faulty(), votes={our: sig})
        self.disperses += 1
        return root, step

    def cert_if_ready(self, root: bytes) -> Optional[VidCert]:
        """The completed cert for ``root`` (consumes the pending entry) —
        immediately ready on single-node networks where our own vote is
        already ``n − f``."""
        pend = self._pending.get(root)
        if pend is None or len(pend.votes) < pend.need:
            return None
        del self._pending[root]
        self.certs += 1
        return VidCert(
            era=pend.era, root=root, total_len=pend.total_len,
            votes=tuple(sorted(pend.votes.items(),
                               key=lambda kv: repr(kv[0]))))

    def local_payload(self, root: bytes) -> Optional[bytes]:
        """Our own dispersed payload, for commit-time local resolution."""
        return self._payloads.get(root)

    def handle_vote(self, netinfo, sender: NodeId, msg: VidVote
                    ) -> Tuple[Step, Optional[VidCert]]:
        pend = self._pending.get(msg.root)
        if pend is None or msg.era != pend.era:
            # late vote for a completed/abandoned dispersal — benign
            return Step(), None
        if sender in pend.votes:
            return Step(), None
        pk = netinfo.public_key(sender)
        if pk is None or not pk.verify(
                msg.sig, vote_transcript(pend.era, msg.root,
                                         pend.total_len)):
            return Step.from_fault(sender, FaultKind.VidInvalidVote), None
        pend.votes[sender] = msg.sig
        return Step(), self.cert_if_ready(msg.root)

    # -- receiver side -------------------------------------------------------

    def handle_disperse(self, netinfo, sender: NodeId, msg: VidDisperse
                        ) -> Step:
        our_index = netinfo.node_index(netinfo.our_id())
        p = msg.proof
        if (p.index != our_index or p.root_hash != msg.root
                or not p.validate(netinfo.num_nodes())):
            return Step.from_fault(sender, FaultKind.VidInvalidDisperse)
        self.store.put(msg.root, msg.total_len, p)
        # A proposer whose contribution was excluded from an epoch's
        # subset re-samples the same queue and re-disperses the same
        # root; staying silent here would starve it of votes forever.
        # Re-send the cached vote instead — never re-sign.
        key = (msg.era, msg.root, msg.total_len)
        sig = self._voted.get(key)
        if sig is None:
            sig = netinfo.secret_key().sign(
                vote_transcript(msg.era, msg.root, msg.total_len))
            self._voted[key] = sig
            while len(self._voted) > _VOTED_KEEP:
                self._voted.popitem(last=False)
            self.votes_cast += 1
        return Step().send_to(
            sender, VidVote(msg.era, msg.root, sig))


# ===========================================================================
# VID-mode QueueingHoneyBadger
# ===========================================================================


class VidQueueingHoneyBadger(QueueingHoneyBadger):
    """QHB where proposals are dispersed first and epochs order only the
    ``(root, cert)`` commitment.

    One dispersal is in flight at a time (``propose_ahead`` pipelining is
    classic-mode only and no-ops here); a dispersal orphaned by epoch/era
    progress is abandoned and re-sampled, so vote loss can delay but
    never wedge proposals.  Committed epochs come out as
    :class:`VidQhbBatch`; the driver resolves payloads (locally for our
    own roots, via :mod:`hbbft_tpu.net.retrieve` for the rest) and calls
    :meth:`on_retrieved` so committed transactions leave the queue."""

    def __init__(self, dhb, batch_size: int = 100, rng=None, queue=None,
                 shard_store=None):
        super().__init__(dhb, batch_size=batch_size, rng=rng, queue=queue)
        if shard_store is None:
            from hbbft_tpu.net.retrieve import ShardStore

            shard_store = ShardStore()
        self.store = shard_store
        self.disperser = Disperser(shard_store)
        self._disperse_root: Optional[bytes] = None
        self._disperse_key: Tuple[int, int] = (0, 0)

    # -- ConsensusProtocol ---------------------------------------------------

    def handle_message(self, sender_id: NodeId, message) -> Step:
        if isinstance(message, VidDisperse):
            return self.disperser.handle_disperse(
                self.dhb.netinfo, sender_id, message)
        if isinstance(message, VidVote):
            step, cert = self.disperser.handle_vote(
                self.dhb.netinfo, sender_id, message)
            if cert is not None and cert.root == self._disperse_root:
                step.extend(self._propose_cert(cert))
            return step
        return super().handle_message(sender_id, message)

    def propose_ahead(self, depth: int) -> Step:
        # VID pipelining would need per-epoch concurrent dispersals;
        # depth collapses to the sequential disperse→cert→propose flow
        return Step()

    # -- internals -----------------------------------------------------------

    def _maybe_propose(self, force: bool = False) -> Step:
        if not self.dhb.is_validator():
            return Step()
        hb = self.dhb.hb
        if hb.has_input.get(hb.epoch):
            return Step()
        if self._disperse_root is not None:
            if (self.dhb.era, hb.epoch) <= self._disperse_key:
                return Step()  # cert collection for this epoch in flight
            # epoch moved on without our cert (lost votes / era rotation):
            # abandon and re-sample below
            self.disperser._pending.pop(self._disperse_root, None)
            self._disperse_root = None
        sample = self.queue.choose(self.rng, self.batch_size)
        if not sample:
            if not force:
                return Step()
            # liveness keep-alive stays a plain empty contribution —
            # nothing to disperse
            return self._process(self.dhb.propose(_ser_txs([])))
        self._proposed[(self.dhb.era, hb.epoch)] = tuple(sample)
        era = self.dhb.era
        root, step = self.disperser.disperse(
            era, self.dhb.netinfo, _ser_txs(sample))
        self._disperse_root = root
        self._disperse_key = (era, hb.epoch)
        cert = self.disperser.cert_if_ready(root)  # n − f == 1 networks
        if cert is not None:
            step.extend(self._propose_cert(cert))
        return step

    def _propose_cert(self, cert: VidCert) -> Step:
        self._disperse_root = None
        if cert.era != self.dhb.era:
            # the cert straddled an era rotation: its votes verify only
            # under the old key map — drop it and re-propose fresh
            return self._maybe_propose()
        step = Step()
        payload = self.disperser.local_payload(cert.root)
        if payload is not None:
            step.output.append(VidCertReady(
                era=cert.era, root=cert.root, total_len=cert.total_len,
                payload_sha3=payload_digest(payload)))
        return step.extend(self._process(self.dhb.propose(
            encode_commitment(cert))))

    def on_retrieved(self, txs) -> None:
        """Driver callback once a foreign commitment's payload resolved:
        committed transactions leave the queue so they are not
        re-proposed."""
        self.queue.remove_multiple({bytes(t) for t in txs})

    def _process(self, inner: Step) -> Step:
        if not inner.output:
            return inner
        step = Step(fault_log=inner.fault_log, messages=inner.messages)
        for out in inner.output:
            if isinstance(out, VidCertReady):
                step.output.append(out)
                continue
            if not isinstance(out, DhbBatch):
                continue
            contribs: List[Tuple[NodeId, bytes]] = []
            committed: List[bytes] = []
            for proposer, payload in out.contributions:
                if payload.startswith(COMMIT_MAGIC):
                    try:
                        cert = decode_commitment(payload)
                    # hblint: disable=fault-swallowed-drop (accounted
                    # below: a None cert is the proposer's counted
                    # VidInvalidCert fault, never a silent skip)
                    except ValueError:
                        cert = None
                    # our own slot needs no cert verification: the subset
                    # binds it to OUR broadcast, and we assembled the cert
                    # from individually verified votes in handle_vote
                    ok = (cert is not None and cert.era == out.era
                          and (proposer == self.our_id()
                               or out.era != self.dhb.era
                               or verify_cert(cert, self.dhb.netinfo)))
                    if not ok:
                        step.fault(proposer, FaultKind.VidInvalidCert)
                        continue
                    contribs.append((proposer, payload))
                    if proposer == self.our_id():
                        local = self.disperser.local_payload(cert.root)
                        if local is not None:
                            committed.extend(_de_txs(local))
                else:
                    try:
                        txs = _de_txs(payload)
                    except ValueError:
                        step.fault(
                            proposer, FaultKind.BatchDeserializationFailed)
                        continue
                    contribs.append((proposer, payload))
                    committed.extend(txs)
            self.queue.remove_multiple(set(committed))
            for k in [k for k in self._proposed
                      if k <= (out.era, out.epoch)]:
                del self._proposed[k]
            step.output.append(VidQhbBatch(
                era=out.era, epoch=out.epoch,
                contributions=tuple(contribs), change=out.change))
        if step.output:
            step.extend(self._maybe_propose())
        return step

"""The sans-I/O consensus state machines (object-mode execution path).

Bottom-up: ``broadcast`` (Bracha RBC) and ``binary_agreement`` (ABA with a
threshold-signature common coin) feed ``subset`` (ACS), which powers
``honey_badger`` epochs; ``dynamic_honey_badger`` adds membership changes via
``sync_key_gen`` (DKG), and ``queueing_honey_badger`` adds the transaction
queue.  ``sender_queue`` wraps the top-level algorithms to buffer messages
for lagging peers.

Every protocol implements :class:`hbbft_tpu.traits.ConsensusProtocol` — the
same contract the batched array-mode simulator in ``hbbft_tpu.parallel``
re-expresses as dense tensors.  Reference layout: ``src/`` of poanetwork/hbbft
(see SURVEY.md §1-§3).
"""

from hbbft_tpu.protocols.binary_agreement import BinaryAgreement
from hbbft_tpu.protocols.broadcast import Broadcast
from hbbft_tpu.protocols.dynamic_honey_badger import (
    Change,
    ChangeState,
    DhbBatch,
    DynamicHoneyBadger,
    JoinPlan,
)
from hbbft_tpu.protocols.honey_badger import (
    Batch,
    EncryptionSchedule,
    HoneyBadger,
    HoneyBadgerBuilder,
)
from hbbft_tpu.protocols.queueing_honey_badger import (
    QhbBatch,
    QueueingHoneyBadger,
    TransactionQueue,
)
from hbbft_tpu.protocols.sender_queue import SenderQueue
from hbbft_tpu.protocols.subset import Subset
from hbbft_tpu.protocols.sync_key_gen import SyncKeyGen
from hbbft_tpu.protocols.threshold_decrypt import ThresholdDecrypt
from hbbft_tpu.protocols.threshold_sign import ThresholdSign

__all__ = [
    "BinaryAgreement",
    "Broadcast",
    "Change",
    "ChangeState",
    "DhbBatch",
    "DynamicHoneyBadger",
    "JoinPlan",
    "Batch",
    "EncryptionSchedule",
    "HoneyBadger",
    "HoneyBadgerBuilder",
    "QhbBatch",
    "QueueingHoneyBadger",
    "TransactionQueue",
    "SenderQueue",
    "Subset",
    "SyncKeyGen",
    "ThresholdDecrypt",
    "ThresholdSign",
]

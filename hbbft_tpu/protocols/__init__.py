"""The sans-I/O consensus state machines (object-mode execution path).

Bottom-up: ``broadcast`` (Bracha RBC) and ``binary_agreement`` (ABA with a
threshold-signature common coin) feed ``subset`` (ACS), which powers
``honey_badger`` epochs; ``dynamic_honey_badger`` adds membership changes via
``sync_key_gen`` (DKG), and ``queueing_honey_badger`` adds the transaction
queue.  ``sender_queue`` wraps the top-level algorithms to buffer messages
for lagging peers.

Every protocol implements :class:`hbbft_tpu.traits.ConsensusProtocol` — the
same contract the batched array-mode simulator in ``hbbft_tpu.parallel``
re-expresses as dense tensors.  Reference layout: ``src/`` of poanetwork/hbbft
(see SURVEY.md §1-§3).
"""

from hbbft_tpu.protocols.broadcast import Broadcast

"""Bracha reliable broadcast with erasure-coded payload.

Reference: ``src/broadcast/broadcast.rs :: Broadcast`` — the proposer
RS-encodes the value into N shards (data = N−2f, parity = 2f), commits to
them with a Merkle tree, and sends each node its shard + proof as ``Value``;
nodes re-distribute their shard to everyone as ``Echo``; ``Ready(root)`` is
sent after N−f Echos (or f+1 Readys — Bracha amplification); the value is
decoded once a node holds 2f+1 Readys and ≥ N−2f Echos, re-encoded, and the
recomputed Merkle root checked against the agreed one.

Guarantees (with ≤ f Byzantine nodes): if any correct node outputs a value,
all correct nodes output that same value; if the proposer is correct, that
value is the proposer's input.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Hashable, Optional, Set, Tuple

import numpy as np

from hbbft_tpu.fault_log import FaultKind
from hbbft_tpu.netinfo import NetworkInfo
from hbbft_tpu.ops import rs
from hbbft_tpu.ops.merkle import MerkleTree, Proof
from hbbft_tpu.traits import ConsensusProtocol, Step, Target

NodeId = Hashable


# -- messages (reference: src/broadcast/message.rs :: Message) --------------


@dataclass(frozen=True)
class ValueMsg:
    proof: Proof


@dataclass(frozen=True)
class EchoMsg:
    proof: Proof


@dataclass(frozen=True)
class ReadyMsg:
    root: bytes


@dataclass(frozen=True)
class EchoHashMsg:
    """Echo *evidence* without the shard (reference:
    ``src/broadcast/message.rs :: Message::EchoHash`` [LOW] — the upstream
    message-reduction optimization).  Sent instead of a full ``Echo`` to
    peers that already announced ``CanDecode``: they no longer need the
    shard content, only proof that this sender echoed the root, which is
    all the N−f Ready threshold requires."""

    root: bytes


@dataclass(frozen=True)
class CanDecodeMsg:
    """Announcement that this node holds ≥ N−2f shards for ``root`` and
    needs no further shard payloads (reference: ``Message::CanDecode``
    [LOW]).  Peers that have not echoed to us yet send ``EchoHash``
    instead of the full shard+proof, saving O(N · shard) bytes per node."""

    root: bytes


BroadcastMessage = object  # ValueMsg | EchoMsg | ReadyMsg | EchoHash | CanDecode

#: ``CanDecode`` pays for itself only when the echo shards it suppresses
#: outweigh the announcement messages themselves (~40 framed bytes to
#: N−k peers, plus a full decode/handle pass at every receiver).  Below
#: this shard size the optimization is strictly negative — at the bench
#: shape (64 B txs, shards < 300 B) it added ~8 messages per epoch per
#: node for nothing — so tiny-payload broadcasts skip the announcement.
#: Module knob: MB-scale RBC deployments can tune it.
CAN_DECODE_MIN_SHARD_BYTES = 256


class Broadcast(ConsensusProtocol):
    """Reference: ``src/broadcast/broadcast.rs :: Broadcast<N>``."""

    def __init__(self, netinfo: NetworkInfo, proposer_id: NodeId):
        if not netinfo.is_node_validator(proposer_id):
            raise ValueError("proposer is not a validator")
        self.netinfo = netinfo
        self.proposer_id = proposer_id
        n = netinfo.num_nodes()
        f = netinfo.num_faulty()
        self.coder = rs.for_n_f(n, f)
        self.data_shard_num = self.coder.data_shards
        # state (reference field names)
        self.echo_sent = False
        self.ready_sent = False
        self.decided = False
        self.value_received = False
        self.value_proof: Optional[Proof] = None
        self.echos: Dict[NodeId, Proof] = {}
        self.echo_hashes: Dict[NodeId, bytes] = {}  # shard-less echo evidence
        # peers that need no shard, keyed per root hash on BOTH sides (as in
        # the reference, which maps hash → senders): under an equivocating
        # proposer an honest node may legitimately announce CanDecode for a
        # losing root and later for the winning one — neither direction may
        # suppress or fault that
        self.can_decodes: Dict[NodeId, set] = {}
        self.can_decode_sent: set = set()  # roots we announced
        self.readys: Dict[NodeId, bytes] = {}
        self.output: Optional[bytes] = None
        self.fault: bool = False  # proposer proven faulty (root mismatch)

    # -- ConsensusProtocol --------------------------------------------------

    def our_id(self) -> NodeId:
        return self.netinfo.our_id()

    def terminated(self) -> bool:
        return self.decided or self.fault

    def handle_input(self, input: bytes) -> Step:
        """Proposer entry point (reference ``Broadcast::broadcast``)."""
        if self.our_id() != self.proposer_id:
            raise ValueError("only the proposer can input a value")
        if self.value_received:
            return Step()
        return self._send_shards(bytes(input))

    def handle_message(self, sender_id: NodeId, message) -> Step:
        if not self.netinfo.is_node_validator(sender_id):
            return Step.from_fault(sender_id, FaultKind.UnknownSender)
        if isinstance(message, ValueMsg):
            return self._handle_value(sender_id, message.proof)
        if isinstance(message, EchoMsg):
            return self._handle_echo(sender_id, message.proof)
        if isinstance(message, ReadyMsg):
            return self._handle_ready(sender_id, message.root)
        if isinstance(message, EchoHashMsg):
            return self._handle_echo_hash(sender_id, message.root)
        if isinstance(message, CanDecodeMsg):
            return self._handle_can_decode(sender_id, message.root)
        raise TypeError(f"unknown broadcast message {message!r}")

    # -- internals ----------------------------------------------------------

    def _send_shards(self, value: bytes) -> Step:
        """RS-encode + Merkle-commit + send per-node ``Value`` proofs.

        Reference: ``Broadcast::send_shards`` (HOT: GF(2^8) matmul + keccak;
        the batched simulator replaces this whole path with
        ``parallel.rbc.BatchedRbc.propose``).
        """
        self.value_received = True
        shards, leaves = _encode_value(self.coder, value)
        tree = MerkleTree.from_shards(shards, leaves)
        step = Step()
        my_proof = None
        ids = self.netinfo.all_ids()
        for i, nid in enumerate(ids):
            proof = tree.proof(i)
            if nid == self.our_id():
                my_proof = proof
            else:
                step.send_to(nid, ValueMsg(proof))
        if my_proof is not None:
            step.extend(self._handle_value(self.our_id(), my_proof))
        return step

    def _validate_proof(self, proof: Proof, sender_id: NodeId) -> bool:
        """Proof must verify and carry the index of ``sender_id``.

        Reference: ``Broadcast::validate_proof``.
        """
        idx = self.netinfo.node_index(sender_id)
        return (
            proof.index == idx
            and proof.validate(self.netinfo.num_nodes())
        )

    def _handle_value(self, sender_id: NodeId, proof: Proof) -> Step:
        if sender_id != self.proposer_id:
            return Step.from_fault(sender_id, FaultKind.NotAProposer)
        if self.value_received and sender_id != self.our_id():
            if proof == self.value_proof:
                return Step()  # network replay — idempotent
            return Step.from_fault(sender_id, FaultKind.MultipleValues)
        self.value_received = True
        self.value_proof = proof
        # a Value for us carries OUR shard index
        if proof.index != self.netinfo.node_index(self.our_id()) or not proof.validate(
            self.netinfo.num_nodes()
        ):
            return Step.from_fault(sender_id, FaultKind.InvalidProof)
        step = Step()
        if not self.echo_sent:
            self.echo_sent = True
            # full shard+proof to everyone still needing shards (Target::All
            # so observers are reached too); hash-only evidence to peers
            # that already announced CanDecode(root)
            root = proof.root_hash
            cd_peers = {
                nid for nid, roots in self.can_decodes.items()
                if root in roots and nid != self.our_id()
            }
            if cd_peers:
                for nid in cd_peers:
                    step.send_to(nid, EchoHashMsg(root))
                step.send(Target.all_except(cd_peers), EchoMsg(proof))
            else:
                step.send_all(EchoMsg(proof))
            step.extend(self._handle_echo(self.our_id(), proof))
        return step

    def _handle_echo(self, sender_id: NodeId, proof: Proof) -> Step:
        if sender_id in self.echos:
            if self.echos[sender_id] == proof:
                return Step()
            return Step.from_fault(sender_id, FaultKind.MultipleEchos)
        if self.echo_hashes.get(sender_id, proof.root_hash) != proof.root_hash:
            return Step.from_fault(sender_id, FaultKind.EchoHashConflict)
        if not self._validate_proof(proof, sender_id):
            return Step.from_fault(sender_id, FaultKind.InvalidProof)
        self.echos[sender_id] = proof
        step = Step()
        root = proof.root_hash
        step.extend(self._maybe_send_can_decode(root))
        step.extend(self._maybe_send_ready(root))
        step.extend(self._try_decode())
        return step

    def _handle_echo_hash(self, sender_id: NodeId, root: bytes) -> Step:
        if sender_id in self.echo_hashes:
            if self.echo_hashes[sender_id] == root:
                return Step()
            return Step.from_fault(sender_id, FaultKind.MultipleEchoHashes)
        prev = self.echos.get(sender_id)
        if prev is not None and prev.root_hash != root:
            return Step.from_fault(sender_id, FaultKind.EchoHashConflict)
        self.echo_hashes[sender_id] = root
        # no _try_decode here: an EchoHash adds neither a shard nor a
        # Ready, so it can only matter through the Ready threshold (and
        # _handle_ready runs _try_decode itself)
        return self._maybe_send_ready(root)

    def _handle_can_decode(self, sender_id: NodeId, root: bytes) -> Step:
        roots = self.can_decodes.setdefault(sender_id, set())
        # Honest bound: CanDecode(root) requires ≥ k = N−2f full echoes for
        # that root, each sender's echo binds to ONE root (MultipleEchos is
        # a fault), and k ≥ (N+2)/3, so at most ⌊N/k⌋ ≤ 2 distinct roots
        # can ever cross the threshold at one node.  A repeat for the same
        # root, or a third root, is therefore provably faulty — and the
        # bound keeps per-sender state O(1) against root-spamming peers.
        if root in roots or len(roots) >= 2:
            return Step.from_fault(sender_id, FaultKind.MultipleCanDecodes)
        roots.add(root)
        return Step()

    def _maybe_send_ready(self, root: bytes) -> Step:
        """N−f echo *evidence* (full shards or hashes) → send Ready."""
        step = Step()
        n, f = self.netinfo.num_nodes(), self.netinfo.num_faulty()
        if self._count_echo_evidence(root) >= n - f and not self.ready_sent:
            self.ready_sent = True
            step.send_all(ReadyMsg(root))
            step.extend(self._handle_ready(self.our_id(), root))
        return step

    def _maybe_send_can_decode(self, root: bytes) -> Step:
        """≥ N−2f full shards in hand → tell peers to stop sending shards.

        Sent only to peers whose full Echo has NOT already arrived — the
        others have nothing left to withhold (reference sends AllExcept)."""
        step = Step()
        if (
            root not in self.can_decode_sent
            and not self.decided
            and self._count_echos(root) >= self.data_shard_num
        ):
            self.can_decode_sent.add(root)
            shard_len = max(
                len(p.value)
                for p in self.echos.values() if p.root_hash == root
            )
            if shard_len >= CAN_DECODE_MIN_SHARD_BYTES:
                step.send(
                    Target.all_except(set(self.echos)), CanDecodeMsg(root)
                )
        return step

    def _handle_ready(self, sender_id: NodeId, root: bytes) -> Step:
        if sender_id in self.readys:
            if self.readys[sender_id] == root:
                return Step()
            return Step.from_fault(sender_id, FaultKind.MultipleReadys)
        self.readys[sender_id] = root
        step = Step()
        f = self.netinfo.num_faulty()
        if self._count_readys(root) > f and not self.ready_sent:
            # Bracha amplification
            self.ready_sent = True
            step.send_all(ReadyMsg(root))
            step.extend(self._handle_ready(self.our_id(), root))
        step.extend(self._try_decode())
        return step

    def _count_echos(self, root: bytes) -> int:
        return sum(1 for p in self.echos.values() if p.root_hash == root)

    def _count_echo_evidence(self, root: bytes) -> int:
        """Distinct senders known to have echoed ``root`` — full shards plus
        hash-only EchoHash evidence (enough for the Ready threshold; decode
        still requires ``data_shard_num`` full shards)."""
        senders = {
            nid for nid, p in self.echos.items() if p.root_hash == root
        }
        senders |= {
            nid for nid, r in self.echo_hashes.items() if r == root
        }
        return len(senders)

    def _count_readys(self, root: bytes) -> int:
        return sum(1 for r in self.readys.values() if r == root)

    def _try_decode(self) -> Step:
        """Reference: ``Broadcast::compute_output`` — decode when 2f+1
        Readys agree on a root and ≥ N−2f matching Echos are in hand."""
        if self.decided or self.fault:
            return Step()
        n, f = self.netinfo.num_nodes(), self.netinfo.num_faulty()
        # sorted: with Byzantine equivocation two roots can in principle
        # clear both thresholds in the same crank at small n — set
        # iteration order must not pick which one decodes (hblint
        # det-set-iteration)
        roots = {r for r in self.readys.values()}
        for root in sorted(roots):
            if self._count_readys(root) < 2 * f + 1:
                continue
            if self._count_echos(root) < self.data_shard_num:
                continue
            # reconstruct from the echo shards
            shards: list = [None] * n
            for nid, proof in self.echos.items():
                if proof.root_hash == root:
                    shards[proof.index] = proof.value
            # Byzantine senders may echo duplicate SLOTS, so the sender
            # count above can exceed the distinct-slot count — too few
            # distinct slots stays RETRIABLE (honest echoes still coming)
            if sum(s is not None for s in shards) < self.data_shard_num:
                continue
            try:
                full = self.coder.reconstruct_np(shards)
            except ValueError:
                # ≥ k distinct committed slots in hand, yet reconstruction
                # is impossible: a PERMANENT commitment defect (the
                # proposer Merkle-committed odd/inconsistent-length
                # shards).  Treating it as retriable would livelock every
                # honest node against such a proposer (round-5 review
                # finding); fault it like the root-mismatch case.
                self.fault = True
                return Step.from_fault(
                    self.proposer_id, FaultKind.InvalidProof
                )
            # re-encode & verify the root (defends against a faulty proposer
            # whose shards don't form a consistent codeword)
            tree = MerkleTree.from_vec(full)
            if tree.root_hash() != root:
                self.fault = True
                return Step.from_fault(
                    self.proposer_id, FaultKind.InvalidProof
                )
            value = _unframe_value(
                b"".join(full[: self.data_shard_num])
            )
            if value is None:
                self.fault = True
                return Step.from_fault(
                    self.proposer_id, FaultKind.InvalidProof
                )
            self.decided = True
            self.output = value
            return Step.from_output(value)
        return Step()


# -- framing ----------------------------------------------------------------


def _frame_value(value: bytes, data_shards: int) -> np.ndarray:
    """value → (data_shards, B) uint8: 4-byte length prefix + value + zeros.

    The shard length rounds up to EVEN, matching the array-mode
    ``parallel.rbc.frame_values``: the GF(2^16) coder (networks beyond the
    reference's 256-shard limit) works in u16 symbols, and an odd length
    would fail its encode — a bug the round-5 large-N masked property
    sweep found in object mode."""
    framed = len(value).to_bytes(4, "big") + value
    shard_len = max(2, -(-len(framed) // data_shards))
    shard_len += shard_len % 2
    framed = framed.ljust(data_shards * shard_len, b"\0")
    return np.frombuffer(framed, dtype=np.uint8).reshape(data_shards, shard_len)


def _encode_value(coder, value: bytes):
    """Frame + RS-encode ``value`` into ONE contiguous shard buffer.

    Returns ``(shards, leaves)``: ``shards`` is the (total, B) uint8 array
    (data rows framed in place, parity written into the tail by
    ``encode_into``), ``leaves`` are memoryview slices of a SINGLE immutable
    bytes snapshot of it.  The Merkle tree hashes the array rows directly
    and the per-peer proofs carry the shared slices, so the proposer path
    copies each payload byte O(1) times total — the old path round-tripped
    every shard through ``tobytes()`` and re-materialized it per peer."""
    k = coder.data_shards
    framed_len = 4 + len(value)
    shard_len = max(2, -(-framed_len // k))
    shard_len += shard_len % 2
    # empty + explicit tail-zero, not zeros: calloc hands back fresh
    # lazily-mapped pages every call, and the page faults land on the
    # encode/hash steps that first touch them — malloc reuse keeps the
    # hot loop on warm pages.  Parity rows are fully overwritten below.
    shards = np.empty((coder.total_shards, shard_len), dtype=np.uint8)
    flat = shards[:k].reshape(-1)
    flat[:4] = np.frombuffer(len(value).to_bytes(4, "big"), dtype=np.uint8)
    if value:
        flat[4:framed_len] = np.frombuffer(value, dtype=np.uint8)
    flat[framed_len:] = 0
    coder.encode_into(shards)
    buf = shards.tobytes()  # the one immutable snapshot all slices share
    mv = memoryview(buf)
    leaves = [
        mv[i * shard_len:(i + 1) * shard_len]
        for i in range(coder.total_shards)
    ]
    return shards, leaves


def _unframe_value(framed: bytes) -> Optional[bytes]:
    if len(framed) < 4:
        return None
    length = int.from_bytes(framed[:4], "big")
    if 4 + length > len(framed):
        return None
    return framed[4 : 4 + length]

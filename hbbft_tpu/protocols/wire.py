"""Canonical byte codec for consensus-committed payloads.

The reference uses ``bincode``+serde at this boundary (SURVEY §2.2); we use
an explicit deterministic tag-length-value codec.  Everything that goes
*inside* a HoneyBadger contribution (votes, key-gen messages, user payloads)
must be bytes, because contributions are TPKE-encrypted and RBC-sharded.

Node ids are restricted to ints and strings on the wire (tests and the
simulator use ints; deployments use strings).
"""

from __future__ import annotations

import struct
from typing import Hashable, List, Optional, Tuple

from hbbft_tpu.crypto import tc

NodeId = Hashable

# Hard decode-side size caps.  A length prefix is attacker-controlled bytes;
# without a cap a single forged u32 makes the reader attempt a 4 GiB
# allocation (or, with nesting, many of them).  8 MiB covers every honest
# payload of the shipped configurations (contributions, shards, votes; a
# full batch-size contribution set is bounded at mempool admission —
# net/client.Mempool.max_tx_bytes).  Known exception: a DKG key-gen Part
# carries a 97·(f+1)²-byte bivariate commitment, which crosses 8 MiB
# around N ≈ 880 — a networked cluster rotating keys at that scale must
# raise these two module constants (they are resolved at call time, so
# assigning wire.MAX_BLOB_BYTES/MAX_MESSAGE_BYTES takes effect) and pass
# a matching max_frame to its Transport/NodeRuntime.  The network layer
# enforces its frame cap on top (net/framing.py).
MAX_BLOB_BYTES = 8 * 2**20
MAX_MESSAGE_BYTES = MAX_BLOB_BYTES + 4096


class Reader:
    __slots__ = ("data", "pos", "max_blob", "_depth")

    def __init__(self, data: bytes, max_blob: Optional[int] = None):
        self.data = data
        self.pos = 0
        # resolved at call time so deployments can raise the module knob
        self.max_blob = MAX_BLOB_BYTES if max_blob is None else max_blob
        self._depth = 0

    def take(self, n: int) -> bytes:
        if n < 0:
            raise ValueError(f"negative read of {n} bytes")
        if self.pos + n > len(self.data):
            raise ValueError(
                f"truncated: need {n} bytes at offset {self.pos}, "
                f"have {len(self.data) - self.pos}"
            )
        out = self.data[self.pos : self.pos + n]
        self.pos += n
        return out

    def u32(self) -> int:
        # unpack_from avoids the take() slice copy — u32/u64 run several
        # times per decoded message on the runtime's hot path
        pos = self.pos
        if pos + 4 > len(self.data):
            raise ValueError(
                f"truncated: need 4 bytes at offset {pos}, "
                f"have {len(self.data) - pos}"
            )
        self.pos = pos + 4
        return struct.unpack_from(">I", self.data, pos)[0]

    def u64(self) -> int:
        pos = self.pos
        if pos + 8 > len(self.data):
            raise ValueError(
                f"truncated: need 8 bytes at offset {pos}, "
                f"have {len(self.data) - pos}"
            )
        self.pos = pos + 8
        return struct.unpack_from(">Q", self.data, pos)[0]

    def blob(self) -> bytes:
        n = self.u32()
        if n > self.max_blob:
            raise ValueError(
                f"blob length {n} exceeds cap {self.max_blob}"
            )
        return self.take(n)

    def f64(self) -> float:
        return struct.unpack(">d", self.take(8))[0]

    def done(self) -> bool:
        return self.pos == len(self.data)


def blob(b) -> bytes:
    # join (not +) so memoryview values — the zero-copy RBC proof slices —
    # encode without a bytes() conversion at every call site
    return b"".join((struct.pack(">I", len(b)), b))


def u32(v: int) -> bytes:
    return struct.pack(">I", v)


def u64(v: int) -> bytes:
    return struct.pack(">Q", v)


def f64(v: float) -> bytes:
    """IEEE-754 big-endian double — byte-deterministic for a given float
    value (journal record timestamps/durations)."""
    return struct.pack(">d", v)


# -- node ids ---------------------------------------------------------------


def node_id(nid: NodeId) -> bytes:
    if isinstance(nid, bool) or not isinstance(nid, (int, str)):
        raise TypeError(f"wire node ids must be int or str, got {nid!r}")
    if isinstance(nid, int):
        return b"\x01" + struct.pack(">q", nid)
    enc = nid.encode()
    return b"\x02" + blob(enc)


def read_node_id(r: Reader) -> NodeId:
    tag = r.take(1)
    if tag == b"\x01":
        return struct.unpack(">q", r.take(8))[0]
    if tag == b"\x02":
        return r.blob().decode()
    raise ValueError("bad node id tag")


# -- crypto objects ---------------------------------------------------------


def ciphertext(ct: tc.Ciphertext) -> bytes:
    return blob(ct.to_bytes())


def read_ciphertext(r: Reader) -> tc.Ciphertext:
    return tc.Ciphertext.from_bytes(r.blob())


def signature(sig: tc.Signature) -> bytes:
    return blob(sig.to_bytes())


def read_signature(r: Reader) -> tc.Signature:
    return tc.Signature.from_bytes(r.blob())


def commitment_bivar(com: tc.BivarCommitment) -> bytes:
    from hbbft_tpu.crypto import bls12_381 as bls

    out = u32(com.degree())
    for row in com.points:
        for p in row:
            out += bls.g1_to_bytes(p)
    return out


def read_commitment_bivar(r: Reader) -> tc.BivarCommitment:
    from hbbft_tpu.crypto import bls12_381 as bls

    degree = r.u32()
    if degree > 1024:
        raise ValueError("absurd commitment degree")
    pts = [
        [bls.g1_from_bytes(r.take(97)) for _ in range(degree + 1)]
        for _ in range(degree + 1)
    ]
    return tc.BivarCommitment(degree, pts)


# -- committed batches -------------------------------------------------------
#
# The canonical bytes every ledger-digest chain folds over.  Shared by
# ``net.runtime.NodeRuntime`` and the flight recorder
# (``obs.flight.FlightObserver``) so both drivers produce the SAME chain for
# the same batch sequence — the cross-node/cross-driver identity the
# forensic auditor compares.


def _change_state_bytes(cs) -> bytes:
    """The batch's validator-set change decision is consensus output too —
    a fork in DKG/membership state must show in the ledger digest."""
    out = blob(cs.state.encode())
    out += cs.change.to_bytes() if cs.change is not None else b"\x00"
    return out


def batch_bytes(b) -> bytes:
    """Canonical bytes of a committed batch for the ledger digest chain."""
    from hbbft_tpu.protocols.dynamic_honey_badger import DhbBatch
    from hbbft_tpu.protocols.honey_badger import Batch as HbBatch
    from hbbft_tpu.protocols.queueing_honey_badger import QhbBatch
    from hbbft_tpu.protocols.vid import VidQhbBatch

    if isinstance(b, QhbBatch):
        out = b"qhb" + u64(b.era) + u64(b.epoch)
        for proposer, txs in b.contributions:
            out += node_id(proposer) + u32(len(txs))
            for tx in txs:
                out += blob(tx)
        return out + _change_state_bytes(b.change)
    if isinstance(b, VidQhbBatch):
        # VID mode folds the ORDERED commitments, not the retrieved
        # transactions: the digest chain stays a pure ordering artifact,
        # so nodes at different retrieval depths still share a prefix
        out = b"vqhb" + u64(b.era) + u64(b.epoch)
        for proposer, payload in b.contributions:
            out += node_id(proposer) + blob(payload)
        return out + _change_state_bytes(b.change)
    if isinstance(b, DhbBatch):
        out = b"dhb" + u64(b.era) + u64(b.epoch)
        for proposer, payload in b.contributions:
            out += node_id(proposer) + blob(payload)
        return out + _change_state_bytes(b.change)
    if isinstance(b, HbBatch):
        out = b"hb" + u64(b.epoch)
        for proposer, payload in b.contributions:
            out += node_id(proposer) + blob(payload)
        return out
    raise TypeError(f"unknown batch type {type(b).__name__}")


# ===========================================================================
# Full protocol-message wire format
# ===========================================================================
#
# The reference serializes EVERY message with serde/bincode; this is the
# equivalent explicit codec: ``encode_message``/``decode_message`` cover the
# complete message surface of the stack (RBC, ABA, threshold sign/decrypt,
# subset and honey-badger wrappers, DHB era messages, sender-queue framing).
# Deterministic, self-delimiting, fuzz-round-trip-tested; the dense-array
# simulator uses these bytes as its message payload layout.

_MSG_TAGS = {}
_MSG_DECODERS = {}


def _register(tag: int, cls, enc, dec):
    _MSG_TAGS[cls] = (tag, enc)
    _MSG_DECODERS[tag] = dec


def encode_message(msg) -> bytes:
    """Any protocol message object → canonical bytes."""
    _lazy_register()
    try:
        tag, enc = _MSG_TAGS[type(msg)]
    except KeyError:
        raise TypeError(f"no wire encoding for {type(msg).__name__}")
    return bytes([tag]) + enc(msg)


def decode_message(data: bytes, max_bytes: Optional[int] = None,
                   max_blob: Optional[int] = None):
    """``max_blob`` overrides the Reader's per-blob cap — the journal
    reader passes ``len(data)`` because its payloads are already
    length-bounded and CRC-validated, and a legally-journaled message
    near ``MAX_MESSAGE_BYTES`` embeds blobs above ``MAX_BLOB_BYTES``."""
    _lazy_register()
    if max_bytes is None:
        max_bytes = MAX_MESSAGE_BYTES
    if len(data) > max_bytes:
        raise ValueError(
            f"message of {len(data)} bytes exceeds cap {max_bytes}"
        )
    r = Reader(data, max_blob=max_blob)
    msg = _read_message(r)
    if not r.done():
        raise ValueError("trailing bytes after message")
    return msg


_MAX_NESTING = 8


def _read_message(r: Reader):
    # hand-inlined tag read + explicit depth bookkeeping (no try/finally,
    # no getattr): this function runs once per nesting level of every
    # message on the runtime's hot path.  On a decode error the Reader is
    # abandoned whole, so the depth only needs restoring on success.
    depth = r._depth
    if depth >= _MAX_NESTING:
        raise ValueError("message nesting too deep")
    pos = r.pos
    data = r.data
    if pos >= len(data):
        raise ValueError(f"truncated: need 1 byte at offset {pos}, have 0")
    tag = data[pos]
    r.pos = pos + 1
    dec = _MSG_DECODERS.get(tag)
    if dec is None:
        raise ValueError(f"unknown message tag 0x{tag:02x}")
    r._depth = depth + 1
    msg = dec(r)
    r._depth = depth
    return msg


def _lazy_register():
    """Message classes live across protocol modules that import this one —
    register on first use to avoid import cycles."""
    if _MSG_TAGS:
        return
    from hbbft_tpu.ops.merkle import Proof
    from hbbft_tpu.protocols.binary_agreement import (
        AuxMsg, BValMsg, ConfMsg, CoinMsg, TermMsg,
    )
    from hbbft_tpu.protocols.broadcast import (
        CanDecodeMsg, EchoHashMsg, EchoMsg, ReadyMsg, ValueMsg,
    )
    from hbbft_tpu.protocols.dynamic_honey_badger import (
        HbWrap, KeyGenWrap, SignedKeyGenMsg,
    )
    from hbbft_tpu.protocols.honey_badger import (
        DecryptionShareWrap, SubsetWrap,
    )
    from hbbft_tpu.protocols.sender_queue import AlgoMessage, EpochStarted
    from hbbft_tpu.protocols.subset import AgreementWrap, BroadcastWrap
    from hbbft_tpu.protocols.threshold_decrypt import DecryptionMessage
    from hbbft_tpu.protocols.threshold_sign import ThresholdSignMessage

    def boolb(v: bool) -> bytes:
        return b"\x01" if v else b"\x00"

    def read_bool(r: Reader) -> bool:
        b = r.take(1)
        if b not in (b"\x00", b"\x01"):
            raise ValueError("bad bool")
        return b == b"\x01"

    def proof_bytes(p: Proof) -> bytes:
        out = blob(p.value) + u32(p.index) + p.root_hash + u32(len(p.path))
        for digest, on_left in p.path:
            out += digest + (b"\x01" if on_left else b"\x00")
        return out

    def read_proof(r: Reader) -> Proof:
        value = r.blob()
        index = r.u32()
        root = r.take(32)
        n = r.u32()
        if n > 64:
            raise ValueError("absurd proof depth")
        path = tuple((r.take(32), read_bool(r)) for _ in range(n))
        return Proof(value=value, index=index, root_hash=root, path=path)

    def boolset_byte(s) -> bytes:
        return bytes([(False in s) | ((True in s) << 1)])

    def read_boolset(r: Reader):
        b = r.take(1)[0]
        if b > 3:
            raise ValueError("bad boolset")
        out = set()
        if b & 1:
            out.add(False)
        if b & 2:
            out.add(True)
        return frozenset(out)

    # RBC ------------------------------------------------------------------
    _register(0x10, ValueMsg,
              lambda m: proof_bytes(m.proof),
              lambda r: ValueMsg(read_proof(r)))
    _register(0x11, EchoMsg,
              lambda m: proof_bytes(m.proof),
              lambda r: EchoMsg(read_proof(r)))
    _register(0x12, ReadyMsg,
              lambda m: m.root,
              lambda r: ReadyMsg(r.take(32)))
    _register(0x13, EchoHashMsg,
              lambda m: m.root,
              lambda r: EchoHashMsg(r.take(32)))
    _register(0x14, CanDecodeMsg,
              lambda m: m.root,
              lambda r: CanDecodeMsg(r.take(32)))
    # ABA ------------------------------------------------------------------
    _register(0x20, BValMsg,
              lambda m: u64(m.epoch) + boolb(m.value),
              lambda r: BValMsg(r.u64(), read_bool(r)))
    _register(0x21, AuxMsg,
              lambda m: u64(m.epoch) + boolb(m.value),
              lambda r: AuxMsg(r.u64(), read_bool(r)))
    _register(0x22, ConfMsg,
              lambda m: u64(m.epoch) + boolset_byte(m.values),
              lambda r: ConfMsg(r.u64(), read_boolset(r)))
    _register(0x23, TermMsg,
              lambda m: boolb(m.value),
              lambda r: TermMsg(read_bool(r)))
    _register(0x24, CoinMsg,
              lambda m: u64(m.epoch) + encode_message(m.msg),
              lambda r: CoinMsg(r.u64(), _read_message(r)))
    # threshold primitives --------------------------------------------------
    _register(0x30, ThresholdSignMessage,
              lambda m: blob(m.share.to_bytes()),
              lambda r: ThresholdSignMessage(
                  tc.SignatureShare.from_bytes(r.blob())))
    _register(0x31, DecryptionMessage,
              lambda m: blob(m.share.to_bytes()),
              lambda r: DecryptionMessage(
                  tc.DecryptionShare.from_bytes(r.blob())))
    # subset ----------------------------------------------------------------
    _register(0x40, BroadcastWrap,
              lambda m: node_id(m.proposer_id) + encode_message(m.msg),
              lambda r: BroadcastWrap(read_node_id(r), _read_message(r)))
    _register(0x41, AgreementWrap,
              lambda m: node_id(m.proposer_id) + encode_message(m.msg),
              lambda r: AgreementWrap(read_node_id(r), _read_message(r)))
    # honey badger ----------------------------------------------------------
    _register(0x50, SubsetWrap,
              lambda m: u64(m.epoch) + encode_message(m.msg),
              lambda r: SubsetWrap(r.u64(), _read_message(r)))
    _register(0x51, DecryptionShareWrap,
              lambda m: (u64(m.epoch) + node_id(m.proposer_id)
                         + encode_message(m.msg)),
              lambda r: DecryptionShareWrap(
                  r.u64(), read_node_id(r), _read_message(r)))
    # dynamic honey badger --------------------------------------------------
    def enc_skg(m: SignedKeyGenMsg) -> bytes:
        kind = b"\x01" if m.kind == "part" else b"\x02"
        return (u64(m.era) + node_id(m.sender) + kind + blob(m.payload)
                + signature(m.sig))

    def dec_skg(r: Reader) -> SignedKeyGenMsg:
        era = r.u64()
        sender = read_node_id(r)
        kb = r.take(1)
        if kb == b"\x01":
            kind = "part"
        elif kb == b"\x02":
            kind = "ack"
        else:
            raise ValueError("bad keygen kind")
        payload = r.blob()
        sig = read_signature(r)
        return SignedKeyGenMsg(era, sender, kind, payload, sig)

    _register(0x60, HbWrap,
              lambda m: u64(m.era) + encode_message(m.msg),
              lambda r: HbWrap(r.u64(), _read_message(r)))
    _register(0x61, KeyGenWrap,
              lambda m: u64(m.era) + enc_skg(m.msg),
              lambda r: KeyGenWrap(r.u64(), dec_skg(r)))
    # sender queue ----------------------------------------------------------
    _register(0x70, EpochStarted,
              lambda m: u64(m.key[0]) + u64(m.key[1]),
              lambda r: EpochStarted((r.u64(), r.u64())))
    _register(0x71, AlgoMessage,
              lambda m: encode_message(m.msg),
              lambda r: AlgoMessage(_read_message(r)))
    # flight-recorder journal records ---------------------------------------
    # Registered like any other message so the wire-completeness checker
    # (frozen+hashable, tag uniqueness, codec pairs) and the per-type
    # hash/round-trip regression in tests/test_wire.py cover the journal
    # format for free.
    from hbbft_tpu.obs.flight import (
        FlightCommit, FlightFault, FlightHello, FlightMsg, FlightNote,
        FlightSpan,
    )

    def s(text: str) -> bytes:
        return blob(text.encode())

    def rs(r: Reader) -> str:
        return r.blob().decode()

    _register(0x80, FlightHello,
              lambda m: (s(m.node) + s(m.flavor) + u32(m.incarnation)
                         + u64(m.seq) + f64(m.t)),
              lambda r: FlightHello(rs(r), rs(r), r.u32(), r.u64(),
                                    r.f64()))
    _register(0x81, FlightMsg,
              lambda m: (u64(m.seq) + f64(m.t) + s(m.direction)
                         + s(m.peer) + u64(m.era) + u64(m.epoch)
                         + s(m.mtype) + blob(m.payload)),
              lambda r: FlightMsg(r.u64(), r.f64(), rs(r), rs(r),
                                  r.u64(), r.u64(), rs(r), r.blob()))
    _register(0x82, FlightCommit,
              lambda m: (u64(m.seq) + f64(m.t) + u64(m.era)
                         + u64(m.epoch) + u64(m.index) + blob(m.digest)),
              lambda r: FlightCommit(r.u64(), r.f64(), r.u64(), r.u64(),
                                     r.u64(), r.blob()))
    _register(0x83, FlightFault,
              lambda m: (u64(m.seq) + f64(m.t) + s(m.node) + s(m.kind)
                         + u64(m.era) + u64(m.epoch)),
              lambda r: FlightFault(r.u64(), r.f64(), rs(r), rs(r),
                                    r.u64(), r.u64()))
    _register(0x84, FlightSpan,
              lambda m: (u64(m.seq) + f64(m.t) + s(m.name) + u64(m.era)
                         + u64(m.epoch)
                         + u64(0 if m.round is None else m.round + 1)
                         + f64(m.t_start) + f64(m.t_end) + u64(m.count)),
              lambda r: FlightSpan(r.u64(), r.f64(), rs(r), r.u64(),
                                   r.u64(), (lambda v: v - 1 if v else
                                             None)(r.u64()),
                                   r.f64(), r.f64(), r.u64()))
    _register(0x85, FlightNote,
              lambda m: u64(m.seq) + f64(m.t) + s(m.kind) + s(m.detail),
              lambda r: FlightNote(r.u64(), r.f64(), rs(r), rs(r)))
    # snapshot state-sync records (net/statesync.py) --------------------------
    # Carried in framing.SYNC frames on client-role connections; registered
    # here so the wire-completeness checker and test_wire's per-type
    # hash/round-trip regression cover the transfer format.
    from hbbft_tpu.net.statesync import (
        SyncChunk, SyncChunkReq, SyncManifest, SyncManifestReq, SyncNack,
    )

    def rd32(r: Reader) -> bytes:
        return r.take(32)

    _register(0x90, SyncManifestReq,
              lambda m: b"",
              lambda r: SyncManifestReq())
    _register(0x91, SyncManifest,
              lambda m: (u64(m.era) + u64(m.chain_len) + m.chain_head
                         + m.image_sha3 + u64(m.image_len)
                         + u32(m.chunk_bytes) + u32(m.n_chunks)),
              lambda r: SyncManifest(r.u64(), r.u64(), rd32(r), rd32(r),
                                     r.u64(), r.u32(), r.u32()))
    _register(0x92, SyncChunkReq,
              lambda m: m.image_sha3 + u32(m.index),
              lambda r: SyncChunkReq(rd32(r), r.u32()))
    _register(0x93, SyncChunk,
              lambda m: (m.image_sha3 + u32(m.index) + u32(m.crc)
                         + blob(m.data)),
              lambda r: SyncChunk(rd32(r), r.u32(), r.u32(), r.blob()))
    _register(0x94, SyncNack,
              lambda m: s(m.reason),
              lambda r: SyncNack(rs(r)))
    # verifiable information dispersal (protocols/vid.py) --------------------
    from hbbft_tpu.protocols.vid import (
        VidCert, VidDisperse, VidRetrieve, VidShard, VidVote,
    )

    def rt32(r: Reader) -> bytes:
        return r.take(32)

    def enc_cert(m: VidCert) -> bytes:
        out = (u64(m.era) + m.root + u64(m.total_len)
               + u32(len(m.votes)))
        for nid, sig in m.votes:
            out += node_id(nid) + signature(sig)
        return out

    def dec_cert(r: Reader) -> VidCert:
        era = r.u64()
        root = rt32(r)
        total_len = r.u64()
        n = r.u32()
        if n > 4096:
            raise ValueError("absurd vote count")
        votes = tuple(
            (read_node_id(r), read_signature(r)) for _ in range(n)
        )
        return VidCert(era, root, total_len, votes)

    _register(0xA0, VidDisperse,
              lambda m: (u64(m.era) + m.root + u64(m.total_len)
                         + proof_bytes(m.proof)),
              lambda r: VidDisperse(r.u64(), rt32(r), r.u64(),
                                    read_proof(r)))
    _register(0xA1, VidVote,
              lambda m: u64(m.era) + m.root + signature(m.sig),
              lambda r: VidVote(r.u64(), rt32(r), read_signature(r)))
    _register(0xA2, VidCert, enc_cert, dec_cert)
    _register(0xA3, VidRetrieve,
              lambda m: m.root,
              lambda r: VidRetrieve(rt32(r)))
    _register(0xA4, VidShard,
              lambda m: (m.root + u64(m.total_len)
                         + proof_bytes(m.proof)),
              lambda r: VidShard(rt32(r), r.u64(), read_proof(r)))
    # per-tx causal trace record (obs/trace.py) ------------------------------
    from hbbft_tpu.obs.trace import FlightTrace

    _register(0x95, FlightTrace,
              lambda m: (u64(m.seq) + f64(m.t) + s(m.stage) + u64(m.era)
                         + u64(m.epoch) + u32(m.hop) + s(m.detail)
                         + blob(m.tids)),
              lambda r: FlightTrace(r.u64(), r.f64(), rs(r), r.u64(),
                                    r.u64(), r.u32(), rs(r), r.blob()))
    # live health plane incident record (obs/flight.py, emitted by
    # obs/watch.py and the runtime's local health hooks) ---------------------
    from hbbft_tpu.obs.flight import HealthIncident

    _register(0x96, HealthIncident,
              lambda m: (u64(m.seq) + f64(m.t) + s(m.source) + s(m.kind)
                         + s(m.severity) + s(m.subject) + s(m.key)
                         + s(m.detail)),
              lambda r: HealthIncident(r.u64(), r.f64(), rs(r), rs(r),
                                       rs(r), rs(r), rs(r), rs(r)))
    # performance-plane sampling window (obs/flight.py, emitted by
    # obs/perf.py through the flight recorder) -------------------------------
    from hbbft_tpu.obs.flight import PerfSnapshot

    _register(0x97, PerfSnapshot,
              lambda m: (u64(m.seq) + f64(m.t) + s(m.source)
                         + f64(m.window_s) + f64(m.cpu_frac)
                         + f64(m.headroom) + s(m.doc)),
              lambda r: PerfSnapshot(r.u64(), r.f64(), rs(r), r.f64(),
                                     r.f64(), r.f64(), rs(r)))


def ensure_registered():
    _lazy_register()

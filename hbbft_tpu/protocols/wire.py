"""Canonical byte codec for consensus-committed payloads.

The reference uses ``bincode``+serde at this boundary (SURVEY §2.2); we use
an explicit deterministic tag-length-value codec.  Everything that goes
*inside* a HoneyBadger contribution (votes, key-gen messages, user payloads)
must be bytes, because contributions are TPKE-encrypted and RBC-sharded.

Node ids are restricted to ints and strings on the wire (tests and the
simulator use ints; deployments use strings).
"""

from __future__ import annotations

import struct
from typing import Hashable, List, Optional, Tuple

from hbbft_tpu.crypto import tc

NodeId = Hashable


class Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def take(self, n: int) -> bytes:
        if self.pos + n > len(self.data):
            raise ValueError("truncated")
        out = self.data[self.pos : self.pos + n]
        self.pos += n
        return out

    def u32(self) -> int:
        return struct.unpack(">I", self.take(4))[0]

    def u64(self) -> int:
        return struct.unpack(">Q", self.take(8))[0]

    def blob(self) -> bytes:
        return self.take(self.u32())

    def done(self) -> bool:
        return self.pos == len(self.data)


def blob(b: bytes) -> bytes:
    return struct.pack(">I", len(b)) + b


def u32(v: int) -> bytes:
    return struct.pack(">I", v)


def u64(v: int) -> bytes:
    return struct.pack(">Q", v)


# -- node ids ---------------------------------------------------------------


def node_id(nid: NodeId) -> bytes:
    if isinstance(nid, bool) or not isinstance(nid, (int, str)):
        raise TypeError(f"wire node ids must be int or str, got {nid!r}")
    if isinstance(nid, int):
        return b"\x01" + struct.pack(">q", nid)
    enc = nid.encode()
    return b"\x02" + blob(enc)


def read_node_id(r: Reader) -> NodeId:
    tag = r.take(1)
    if tag == b"\x01":
        return struct.unpack(">q", r.take(8))[0]
    if tag == b"\x02":
        return r.blob().decode()
    raise ValueError("bad node id tag")


# -- crypto objects ---------------------------------------------------------


def ciphertext(ct: tc.Ciphertext) -> bytes:
    return blob(ct.to_bytes())


def read_ciphertext(r: Reader) -> tc.Ciphertext:
    return tc.Ciphertext.from_bytes(r.blob())


def signature(sig: tc.Signature) -> bytes:
    return blob(sig.to_bytes())


def read_signature(r: Reader) -> tc.Signature:
    return tc.Signature.from_bytes(r.blob())


def commitment_bivar(com: tc.BivarCommitment) -> bytes:
    from hbbft_tpu.crypto import bls12_381 as bls

    out = u32(com.degree())
    for row in com.points:
        for p in row:
            out += bls.g1_to_bytes(p)
    return out


def read_commitment_bivar(r: Reader) -> tc.BivarCommitment:
    from hbbft_tpu.crypto import bls12_381 as bls

    degree = r.u32()
    if degree > 1024:
        raise ValueError("absurd commitment degree")
    pts = [
        [bls.g1_from_bytes(r.take(97)) for _ in range(degree + 1)]
        for _ in range(degree + 1)
    ]
    return tc.BivarCommitment(degree, pts)

"""Threshold decryption of one TPKE ciphertext.

Reference: ``src/threshold_decrypt.rs :: ThresholdDecrypt<N>`` — collect
t+1 = f+1 valid decryption shares for a ciphertext and interpolate the
plaintext mask.

Optimisation over the reference (which pairing-verifies every share): a
Fiat–Shamir batch verification — check
``e(Σ ρ_i·share_i, H) == e(Σ ρ_i·pk_i, W)`` with coefficients ρ_i derived by
hashing the share set — one pairing-check for the whole set; per-share
verification only runs as a fallback to attribute blame.  The batched TPU
verifier uses the identical random-linear-combination trick.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Dict, Hashable, Optional

from hbbft_tpu.crypto import bls12_381 as bls
from hbbft_tpu.crypto import tc
from hbbft_tpu.fault_log import FaultKind
from hbbft_tpu.netinfo import NetworkInfo
from hbbft_tpu.traits import ConsensusProtocol, Step

NodeId = Hashable


@dataclass(frozen=True)
class DecryptionMessage:
    share: tc.DecryptionShare


class ThresholdDecrypt(ConsensusProtocol):
    """Reference: ``src/threshold_decrypt.rs``."""

    def __init__(self, netinfo: NetworkInfo):
        self.netinfo = netinfo
        self.ciphertext: Optional[tc.Ciphertext] = None
        self.shares: Dict[NodeId, tc.DecryptionShare] = {}
        self.verified: Dict[NodeId, bool] = {}
        self.pending: Dict[NodeId, tc.DecryptionShare] = {}
        self.plaintext: Optional[bytes] = None
        self.had_input = False
        # Deferred-verification hook (the epoch-pipelined runtime's seam):
        # when set, reaching t+1 shares does NOT verify inline — the chosen
        # share set is parked and ``defer_verify(self)`` registers this
        # instance with the caller, who verifies MANY instances (across the
        # epochs in flight) in one merged pairing-product call and resumes
        # each via :meth:`finish_deferred`.  None (the default) keeps the
        # reference-exact inline behavior — the simulator path.
        self.defer_verify = None
        self._deferred_items = None

    def our_id(self) -> NodeId:
        return self.netinfo.our_id()

    def terminated(self) -> bool:
        return self.plaintext is not None

    # -- API ----------------------------------------------------------------

    def set_ciphertext(self, ct: tc.Ciphertext,
                       share: Optional[tc.DecryptionShare] = None) -> Step:
        """Set the ciphertext, emit our share, process buffered shares.

        The caller must have validated ``ct`` (``Ciphertext.verify``) —
        HoneyBadger does this when accepting a subset contribution.
        ``share`` may carry our own pre-computed decryption share (the
        batched generation path, ``crypto.batch.batch_decrypt_share_gen``);
        it must equal what ``decrypt_share(ct, check=False)`` returns.
        """
        if self.ciphertext is not None:
            return Step()
        self.ciphertext = ct
        step = Step()
        if self.netinfo.is_validator():
            self.had_input = True
            if share is None:
                # check=False: HoneyBadger validates the ciphertext on
                # acceptance
                share = self.netinfo.secret_key_share().decrypt_share(
                    ct, check=False
                )
            step.send_all(DecryptionMessage(share))
            step.extend(self._handle_share(self.our_id(), share))
        pending, self.pending = self.pending, {}
        for sender, share in pending.items():
            step.extend(self._handle_share(sender, share))
        return step

    def handle_input(self, input: tc.Ciphertext) -> Step:
        return self.set_ciphertext(input)

    def handle_message(self, sender_id: NodeId, message) -> Step:
        if not self.netinfo.is_node_validator(sender_id):
            return Step.from_fault(sender_id, FaultKind.UnknownSender)
        if not isinstance(message, DecryptionMessage):
            raise TypeError(f"unknown threshold_decrypt message {message!r}")
        if self.ciphertext is None:
            if sender_id in self.pending:
                if self.pending[sender_id] == message.share:
                    return Step()  # network replay — idempotent
                return Step.from_fault(
                    sender_id, FaultKind.MultipleDecryptionShares
                )
            self.pending[sender_id] = message.share
            return Step()
        return self._handle_share(sender_id, message.share)

    # -- internals ----------------------------------------------------------

    def _handle_share(self, sender_id: NodeId, share: tc.DecryptionShare) -> Step:
        if self.plaintext is not None:
            return Step()
        if sender_id in self.shares:
            if self.shares[sender_id] == share:
                return Step()  # network replay — idempotent
            return Step.from_fault(sender_id, FaultKind.MultipleDecryptionShares)
        self.shares[sender_id] = share
        return self._try_output()

    def _batch_verify(self, items) -> bool:
        """One pairing-check for many shares via a hash-derived random
        linear combination (soundness error ~2^-255).  The two MSM folds
        route through :func:`hbbft_tpu.crypto.batch.rlc_fold_g1` — host
        asm at coin-sized batches, device ladders past the crossover."""
        from hbbft_tpu.crypto.batch import rlc_fold_g1

        ct = self.ciphertext
        h = tc._hash_ciphertext_point(ct.u, ct.v)
        seed = hashlib.sha3_256(
            b"HBBFT-TD-BATCH"
            + ct.to_bytes()
            + b"".join(s.to_bytes() for _, s in items)
        ).digest()
        rhos = [
            int.from_bytes(
                hashlib.sha3_256(seed + k.to_bytes(4, "big")).digest(),
                "big",
            )
            % bls.R
            for k in range(len(items))
        ]
        pks = self.netinfo.public_key_set()
        acc_share = rlc_fold_g1([s.point for _, s in items], rhos)
        acc_pk = rlc_fold_g1(
            [pks.public_key_share(idx).point for idx, _ in items], rhos
        )
        return bls.pairing_check(
            [(bls.g1_neg(acc_share), h), (acc_pk, ct.w)]
        )

    def deferred_job(self):
        """``(items, ciphertext)`` of the parked verification, or None."""
        if self._deferred_items is None:
            return None
        return self._deferred_items, self.ciphertext

    def finish_deferred(self, ok: bool) -> Step:
        """Resume a deferred verification with the batch verdict.

        ``ok=True`` decrypts from the parked share set (exactly what the
        inline path would have done); ``ok=False`` re-runs the full inline
        path — per-share blame fallback included — so fault attribution is
        identical to the undeferred protocol."""
        items, self._deferred_items = self._deferred_items, None
        if (items is None or self.plaintext is not None
                or self.ciphertext is None):
            return Step()
        if ok:
            pks = self.netinfo.public_key_set()
            self.plaintext = pks.decrypt(dict(items), self.ciphertext)
            return Step.from_output(self.plaintext)
        defer, self.defer_verify = self.defer_verify, None
        try:
            return self._try_output()
        finally:
            self.defer_verify = defer

    def _try_output(self) -> Step:
        pks = self.netinfo.public_key_set()
        t = pks.threshold()
        if len(self.shares) < t + 1:
            return Step()
        chosen = sorted(self.shares.items(), key=lambda kv: repr(kv[0]))[: t + 1]
        items = [(self.netinfo.node_index(nid), s) for nid, s in chosen]
        if self.defer_verify is not None:
            if self._deferred_items is None:
                self._deferred_items = items
                self.defer_verify(self)
            return Step()
        if self._batch_verify(items):
            plaintext = pks.decrypt(dict(items), self.ciphertext)
            self.plaintext = plaintext
            return Step.from_output(plaintext)
        # someone lied: verify individually, evict, wait for more
        step = Step()
        for nid in [nid for nid, _ in chosen]:
            if self.verified.get(nid):
                continue
            idx = self.netinfo.node_index(nid)
            ok = pks.public_key_share(idx).verify_decryption_share(
                self.shares[nid], self.ciphertext
            )
            if ok:
                self.verified[nid] = True
            else:
                del self.shares[nid]
                step.fault(nid, FaultKind.InvalidDecryptionShare)
        if len(self.shares) >= t + 1:
            step.extend(self._try_output())
        return step

"""Synchronous distributed key generation (DKG).

Reference: ``src/sync_key_gen.rs :: SyncKeyGen<N>`` — a Pedersen-style DKG
over symmetric bivariate polynomials (``threshold_crypto::BivarPoly``):

- Every dealer d samples a symmetric bivariate poly f_d of degree t and
  broadcasts a ``Part``: the G1 commitment matrix plus, for each node j, the
  row f_d(j+1, ·) encrypted to j's plain public key.
- Node i validates its row against the commitment and answers with an
  ``Ack`` carrying f_d(i+1, j+1) encrypted to each node j — giving every j
  evidence that i's row is consistent (symmetry: f_d(i+1, j+1) is also a
  point on j's row).
- A Part is *complete* with 2t+1 valid Acks; the DKG ``is_ready`` with t+1
  complete Parts (≥ 1 honest dealer).  ``generate()`` sums the complete
  dealers: node i's secret share is Σ_d f_d(i+1, 0) (decrypted row at 0) and
  the public commitment is Σ_d commit_d.row(0).

SyncKeyGen needs *external agreement* on which Parts/Acks count, in what
order — ``DynamicHoneyBadger`` provides it by committing the messages
through consensus; tests provide it by identical delivery order.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Dict, Hashable, List, Optional, Sequence, Set, Tuple

from hbbft_tpu.crypto import tc
from hbbft_tpu.fault_log import FaultKind

NodeId = Hashable


def _ser_poly(poly: tc.Poly) -> bytes:
    out = struct.pack(">I", len(poly.coeffs))
    for coef in poly.coeffs:
        out += coef.to_bytes(32, "big")
    return out


def _de_poly(data: bytes) -> Optional[tc.Poly]:
    if len(data) < 4:
        return None
    (n,) = struct.unpack(">I", data[:4])
    if len(data) < 4 + 32 * n or n == 0 or n > 1024:
        return None
    return tc.Poly(
        [int.from_bytes(data[4 + 32 * i : 36 + 32 * i], "big") for i in range(n)]
    )


@dataclass(frozen=True)
class Part:
    """Dealer's proposal.  Reference: ``sync_key_gen.rs :: Part``."""

    commitment: tc.BivarCommitment
    rows: Tuple[tc.Ciphertext, ...]  # rows[j] encrypted to node j


@dataclass(frozen=True)
class Ack:
    """Row acknowledgement.  Reference: ``sync_key_gen.rs :: Ack``."""

    proposer_index: int
    values: Tuple[tc.Ciphertext, ...]  # values[j] encrypted to node j


class PartOutcome:
    def __init__(self, ack: Optional[Ack] = None, fault: Optional[FaultKind] = None):
        self.ack = ack
        self.fault = fault


class AckOutcome:
    def __init__(self, fault: Optional[FaultKind] = None):
        self.fault = fault


class _ProposalState:
    def __init__(self, commitment: tc.Commitment):
        self.commitment = commitment  # row(our_index+1) commitment? no: full
        self.acks: Set[int] = set()
        self.secret_row_at_zero: Optional[int] = None


class SyncKeyGen:
    """Reference: ``src/sync_key_gen.rs :: SyncKeyGen<N>``."""

    def __init__(
        self,
        our_id: NodeId,
        secret_key: tc.SecretKey,
        pub_keys: Dict[NodeId, tc.PublicKey],
        threshold: int,
        rng,
    ):
        self.our_id = our_id
        self.secret_key = secret_key
        self.pub_keys = dict(pub_keys)
        self.ids: List[NodeId] = sorted(pub_keys.keys())
        self.our_index: Optional[int] = (
            self.ids.index(our_id) if our_id in self.ids else None
        )
        self.threshold = threshold
        self.rng = rng
        self.parts: Dict[int, tc.BivarCommitment] = {}
        self.acks: Dict[int, Set[int]] = {}
        self.our_rows: Dict[int, int] = {}  # dealer idx → f_d(our+1, 0)
        # value cross-checks received via acks: dealer → {acker}
        self._row_polys: Dict[int, tc.Poly] = {}

    # -- dealing -------------------------------------------------------------

    def generate_part(self) -> Part:
        """Sample our bivariate poly and deal rows (done once, by dealers)."""
        from hbbft_tpu.crypto import batch as _batch

        n = len(self.ids)
        bp = tc.BivarPoly.random(self.threshold, self.rng)
        commitment = _batch.bivar_commitment(bp)
        rows = []
        # all n rows in one finite-difference sweep (consecutive share
        # points — the efficient-Shamir evaluation from PAPERS.md)
        for j, row in enumerate(_batch.bivar_rows_range(bp, n)):
            ct = self.pub_keys[self.ids[j]].encrypt(_ser_poly(row), self.rng)
            rows.append(ct)
        return Part(commitment, tuple(rows))

    # -- handling ------------------------------------------------------------

    def handle_part(self, sender_id: NodeId, part: Part) -> PartOutcome:
        """Validate the dealer's Part; if we are a node, decrypt + check our
        row and produce an Ack.  Reference: ``handle_part → PartOutcome``."""
        if sender_id not in self.ids:
            return PartOutcome(fault=FaultKind.UnknownSender)
        dealer = self.ids.index(sender_id)
        if dealer in self.parts:
            return PartOutcome()  # duplicate Part: first one wins
        if (
            part.commitment.degree() != self.threshold
            or len(part.rows) != len(self.ids)
        ):
            return PartOutcome(fault=FaultKind.InvalidPart)
        self.parts[dealer] = part.commitment
        self.acks.setdefault(dealer, set())
        if self.our_index is None:
            return PartOutcome()
        row_bytes = self.secret_key.decrypt(part.rows[self.our_index])
        row = _de_poly(row_bytes) if row_bytes is not None else None
        if row is None or row.degree() > self.threshold:
            return PartOutcome(fault=FaultKind.InvalidPart)
        # check the row against the dealer's commitment (device-batched at
        # large (t+1)² — SURVEY §7 hard part #3)
        from hbbft_tpu.crypto import batch as _batch

        if _batch.commitment_row(
            part.commitment, self.our_index + 1
        ) != row.commitment():
            return PartOutcome(fault=FaultKind.InvalidPart)
        self._row_polys[dealer] = row
        self.our_rows[dealer] = row.evaluate(0)
        values = []
        # one finite-difference sweep over all node indices (PAPERS.md's
        # efficient Shamir share evaluation) instead of n Horner passes
        for j, v in enumerate(_batch.poly_eval_range(row.coeffs,
                                                     len(self.ids))):
            ct = self.pub_keys[self.ids[j]].encrypt(
                v.to_bytes(32, "big"), self.rng
            )
            values.append(ct)
        return PartOutcome(ack=Ack(dealer, tuple(values)))

    def handle_ack(self, sender_id: NodeId, ack: Ack) -> AckOutcome:
        """Validate an Ack against the dealer's commitment and count it."""
        if sender_id not in self.ids:
            return AckOutcome(fault=FaultKind.UnknownSender)
        acker = self.ids.index(sender_id)
        dealer = ack.proposer_index
        if dealer not in self.parts:
            return AckOutcome(fault=FaultKind.InvalidAck)
        if len(ack.values) != len(self.ids):
            return AckOutcome(fault=FaultKind.InvalidAck)
        if acker in self.acks.get(dealer, set()):
            return AckOutcome()  # duplicate — idempotent
        if self.our_index is not None:
            val_bytes = self.secret_key.decrypt(ack.values[self.our_index])
            if val_bytes is None or len(val_bytes) != 32:
                return AckOutcome(fault=FaultKind.InvalidAck)
            v = int.from_bytes(val_bytes, "big")
            # g1^v must equal commitment_d(acker+1, our+1)
            from hbbft_tpu.crypto import batch as _batch
            from hbbft_tpu.crypto import bls12_381 as bls

            expect = _batch.commitment_eval(
                self.parts[dealer], acker + 1, self.our_index + 1
            )
            if not bls.g1_eq(bls.g1_mul(bls.G1_GEN, v), expect):
                return AckOutcome(fault=FaultKind.InvalidAck)
        # hblint: disable=bounded-ingress (dealer and acker are validator
        # indices: both dimensions are capped by the node count)
        self.acks.setdefault(dealer, set()).add(acker)
        return AckOutcome()

    # -- completion ----------------------------------------------------------

    def _complete_dealers(self) -> List[int]:
        need = 2 * self.threshold + 1
        return sorted(
            d for d, ackers in self.acks.items() if len(ackers) >= need
        )

    def count_complete(self) -> int:
        return len(self._complete_dealers())

    def is_ready(self) -> bool:
        """t+1 complete Parts → at least one honest dealer contributed."""
        return self.count_complete() >= self.threshold + 1

    def generate(self) -> Tuple[tc.PublicKeySet, Optional[tc.SecretKeyShare]]:
        """Sum the complete dealers into the final key material.

        Reference: ``generate() → (PublicKeySet, Option<SecretKeyShare>)``.
        """
        dealers = self._complete_dealers()
        if len(dealers) < self.threshold + 1:
            raise ValueError("DKG not ready")
        com: Optional[tc.Commitment] = None
        for d in dealers:
            row0 = self.parts[d].row(0)
            com = row0 if com is None else com + row0
        sk_share = None
        if self.our_index is not None:
            missing = [d for d in dealers if d not in self.our_rows]
            if not missing:
                total = sum(self.our_rows[d] for d in dealers) % tc.R
                sk_share = tc.SecretKeyShare(total)
        return tc.PublicKeySet(com), sk_share

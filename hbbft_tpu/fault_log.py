"""Byzantine-fault evidence accumulation.

Mirrors the reference's ``src/fault_log.rs`` (``Fault``, ``FaultLog``,
``FaultKind``): protocols never panic on misbehaving peers — they record the
evidence in the ``Step`` they return and carry on.  The caller decides what to
do with the log (tests assert on it; a real deployment might slash).

The reference splits fault kinds into per-module enums in newer versions; we
keep one flat string-flavored enum for simplicity but preserve every variant
name a protocol needs.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Hashable, Iterable, List


class FaultKind(enum.Enum):
    """Why a node was logged as faulty.

    Variant set follows the reference's per-protocol fault enums
    (``src/fault_log.rs :: FaultKind`` and the per-module enums that replaced
    it upstream).
    """

    # broadcast
    InvalidProof = "broadcast: Value/Echo carried an invalid Merkle proof"
    MultipleValues = "broadcast: received multiple Values from the proposer"
    MultipleEchos = "broadcast: received multiple Echos from a node"
    MultipleReadys = "broadcast: received multiple Readys from a node"
    MultipleEchoHashes = "broadcast: received multiple EchoHashes from a node"
    MultipleCanDecodes = "broadcast: received multiple CanDecodes from a node"
    NotAProposer = "broadcast: Value message from a node that is not the proposer"
    UnknownSender = "message from a node that is not on the network"
    # binary agreement
    # (the reference's DuplicateBVal/DuplicateAux are intentionally absent:
    # Term substitutes for its sender's BVal/Aux here, so same-value repeats
    # are indistinguishable from honest reordering and are treated as benign)
    MultipleConf = "binary_agreement: multiple Conf from a node"
    MultipleTerm = "binary_agreement: multiple Term from a node"
    AgreementEpochMismatch = "binary_agreement: message for an impossible epoch"
    # threshold sign / decrypt
    UnexpectedSignatureShare = "threshold_sign: share before the document was set"
    InvalidSignatureShare = "threshold_sign: invalid signature share"
    MultipleSignatureShares = "threshold_sign: multiple shares from a node"
    UnexpectedDecryptionShare = "threshold_decrypt: share before ciphertext set"
    InvalidDecryptionShare = "threshold_decrypt: invalid decryption share"
    MultipleDecryptionShares = "threshold_decrypt: multiple shares from a node"
    # honey badger
    InvalidCiphertext = "honey_badger: proposed an invalid ciphertext"
    BatchDeserializationFailed = "honey_badger: contribution failed to deserialize"
    UnexpectedHbMessage = "honey_badger: message for an epoch outside the window"
    DecryptionFailed = "honey_badger: threshold decryption failed"
    FutureEpochFlood = (
        "honey_badger: per-sender future-epoch message budget exhausted "
        "(window-edge spam; the message was dropped, counted)"
    )
    # subset
    InvalidSubsetMessage = "subset: message for an unknown proposer"
    SubsetMessageFlood = (
        "subset: per-sender message budget for one ACS instance "
        "exhausted (flood; the message was dropped, counted)"
    )
    # dynamic honey badger / key gen
    InvalidVoteSignature = "dynamic_honey_badger: invalid vote signature"
    InvalidKeyGenMessage = "dynamic_honey_badger: invalid Part/Ack"
    UnexpectedKeyGenPart = "dynamic_honey_badger: Part from a non-candidate"
    InvalidPart = "sync_key_gen: invalid Part (bad commitment/row)"
    InvalidAck = "sync_key_gen: invalid Ack (bad value)"
    EchoHashConflict = "broadcast: EchoHash conflicts with a full Echo"
    # (EchoHashConflict is raised by broadcast when a node's hash-only echo
    # evidence names a different root than its full Echo)
    # verifiable information dispersal
    VidInvalidDisperse = (
        "vid: Disperse carried an invalid or misdirected Merkle proof"
    )
    VidInvalidVote = "vid: availability vote with an invalid signature"
    VidInvalidCert = (
        "vid: committed contribution carried an invalid retrievability "
        "certificate"
    )
    VidShardProofInvalid = (
        "vid: retrieved shard failed its Merkle proof (counted; "
        "reconstruction proceeds from other donors)"
    )
    VidReconstructMismatch = (
        "vid: reconstructed shards do not re-root to the committed "
        "commitment (non-codeword dispersal — proposer fault)"
    )


def equivocation_kinds() -> frozenset:
    """The :class:`FaultKind` variants that denote *equivocation* — one
    sender emitting conflicting values for the same protocol slot — as
    opposed to merely invalid or mistimed input.  This is the evidence
    class the forensic auditor (:mod:`hbbft_tpu.obs.audit`) can
    reconstruct from merged per-node journals: two receivers holding
    different values from the same sender for one slot is proof of
    misbehavior regardless of which value is "right"."""
    return frozenset({
        FaultKind.MultipleValues,
        FaultKind.MultipleEchos,
        FaultKind.MultipleEchoHashes,
        FaultKind.MultipleCanDecodes,
        FaultKind.MultipleReadys,
        FaultKind.MultipleConf,
        FaultKind.MultipleTerm,
        FaultKind.MultipleSignatureShares,
        FaultKind.MultipleDecryptionShares,
    })


@dataclass(frozen=True)
class Fault:
    """One piece of evidence: ``node_id`` did ``kind``.

    Reference: ``src/fault_log.rs :: Fault``.
    """

    node_id: Hashable
    kind: FaultKind

    def __repr__(self) -> str:  # keep logs short
        return f"Fault({self.node_id!r}, {self.kind.name})"


@dataclass
class FaultLog:
    """An append-only list of :class:`Fault` entries.

    Reference: ``src/fault_log.rs :: FaultLog``.
    """

    faults: List[Fault] = field(default_factory=list)

    @classmethod
    def init(cls, node_id: Hashable, kind: FaultKind) -> "FaultLog":
        return cls([Fault(node_id, kind)])

    def append(self, node_id: Hashable, kind: FaultKind) -> None:
        self.faults.append(Fault(node_id, kind))

    def extend(self, other: "FaultLog") -> None:
        self.faults.extend(other.faults)

    def is_empty(self) -> bool:
        return not self.faults

    def __len__(self) -> int:
        return len(self.faults)

    def __iter__(self):
        return iter(self.faults)

    def __bool__(self) -> bool:
        return bool(self.faults)

"""hbbft_tpu — a TPU-native (JAX/XLA) HoneyBadgerBFT framework.

A brand-new implementation of the capabilities of the Rust consensus library
``yangl1996/hbbft`` (fork of ``poanetwork/hbbft``): a sans-I/O, deterministic
stack of asynchronous BFT consensus state machines —

- ``protocols.broadcast.Broadcast`` — Bracha reliable broadcast with GF(2^8)
  Reed–Solomon erasure coding and SHA3/Merkle commitments
  (reference: ``src/broadcast/broadcast.rs :: Broadcast``),
- ``protocols.binary_agreement.BinaryAgreement`` — Mostéfaoui et al. ABA with a
  BLS threshold-signature common coin
  (reference: ``src/binary_agreement/binary_agreement.rs``),
- ``protocols.subset.Subset`` — asynchronous common subset (ACS)
  (reference: ``src/subset/subset.rs``),
- ``protocols.honey_badger.HoneyBadger`` — epochs with TPKE-encrypted
  contributions (reference: ``src/honey_badger/honey_badger.rs``),
- ``protocols.dynamic_honey_badger`` / ``protocols.sync_key_gen`` — dynamic
  membership via on-line DKG,
- ``protocols.queueing_honey_badger`` — transaction queueing.

The hot per-epoch math lives in ``ops/`` as batched jnp kernels over
arbitrary leading axes (node × instance × epoch): GF(2^8) and GF(2^16)
Reed–Solomon, keccak/Merkle, and limbed BLS12-381 field/curve arithmetic.
``parallel/`` holds the dense-array bulk-synchronous simulator — batched
RBC rounds, ABA epochs, their ACS composition, and the full HoneyBadger
epoch — cross-checked against object mode, single-device or
``shard_map``-sharded over a mesh, scaling to N=4096 nodes on one chip.
``sim/`` holds the object-mode deterministic ``VirtualNet`` harness with
adversaries, tracing, and a cost model (reference: ``tests/net/``).
``crypto/`` is the host BLS/TPKE (``threshold_crypto``-shaped API) with a
byte-parity-proven C++ fast path (``native/``) and device batch
verification (``crypto/batch.py``).

The reference is sans-I/O: every algorithm consumes inputs/messages and
returns a ``Step``; the caller owns the event loop.  We keep that contract
exactly (``traits.ConsensusProtocol``) so the two execution modes — object
mode and batched array mode — are interchangeable and cross-checkable.
"""

from hbbft_tpu.traits import (
    ConsensusProtocol,
    Step,
    Target,
    TargetedMessage,
)
from hbbft_tpu.netinfo import NetworkInfo
from hbbft_tpu.fault_log import Fault, FaultKind, FaultLog

__version__ = "0.1.0"

__all__ = [
    "ConsensusProtocol",
    "Step",
    "Target",
    "TargetedMessage",
    "NetworkInfo",
    "Fault",
    "FaultKind",
    "FaultLog",
]

"""The universal state-machine contract.

Mirrors the reference's ``src/traits.rs`` (older ``src/messaging.rs``):
everything in the stack — broadcast, agreement, subset, honey badger — is an
object that consumes an input or a message and returns a :class:`Step`
containing outputs, a fault log, and outgoing :class:`TargetedMessage`\\ s.
No I/O, no threads, no clocks: the caller owns the event loop
(``sim.virtual_net.VirtualNet`` in tests, the batched array simulator in
``parallel/`` on TPU).

Reference items mirrored here:
``ConsensusProtocol`` (assoc. types NodeId/Input/Output/Message/Error; methods
``handle_input``/``handle_message``/``terminated``/``our_id``),
``Step { output, fault_log, messages }`` with ``extend``/``map``/``join``,
``TargetedMessage { target, message }`` and ``Target::{All, AllExcept, Nodes, Node}``.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import (
    Any,
    Callable,
    FrozenSet,
    Generic,
    Hashable,
    Iterable,
    List,
    Optional,
    TypeVar,
)

from hbbft_tpu.fault_log import FaultKind, FaultLog

NodeId = Hashable
M = TypeVar("M")  # message type
O = TypeVar("O")  # output type


class Target:
    """Message routing directive.  Reference: ``src/traits.rs :: Target``.

    Construct via the factory classmethods: ``Target.all()``,
    ``Target.node(id)``, ``Target.nodes(ids)``, ``Target.all_except(ids)``.
    The caller (simulator / network layer) resolves the target set against the
    current membership; the protocols never enumerate peers themselves.
    """

    __slots__ = ("kind", "ids")

    ALL = "all"
    NODES = "nodes"
    ALL_EXCEPT = "all_except"

    def __init__(self, kind: str, ids: Optional[FrozenSet[NodeId]] = None):
        self.kind = kind
        self.ids = ids

    @classmethod
    def all(cls) -> "Target":
        return cls(cls.ALL)

    @classmethod
    def node(cls, node_id: NodeId) -> "Target":
        return cls(cls.NODES, frozenset((node_id,)))

    @classmethod
    def nodes(cls, ids: Iterable[NodeId]) -> "Target":
        return cls(cls.NODES, frozenset(ids))

    @classmethod
    def all_except(cls, ids: Iterable[NodeId]) -> "Target":
        return cls(cls.ALL_EXCEPT, frozenset(ids))

    def resolve(self, all_ids: Iterable[NodeId], our_id: NodeId) -> List[NodeId]:
        """Expand to the concrete destination list (never includes ``our_id``)."""
        if self.kind == self.ALL:
            return [n for n in all_ids if n != our_id]
        if self.kind == self.ALL_EXCEPT:
            return [n for n in all_ids if n != our_id and n not in self.ids]
        return [n for n in all_ids if n in self.ids and n != our_id]

    def contains(self, node_id: NodeId) -> bool:
        if self.kind == self.ALL:
            return True
        if self.kind == self.ALL_EXCEPT:
            return node_id not in self.ids
        return node_id in self.ids

    def __eq__(self, other: object) -> bool:
        return (
            isinstance(other, Target)
            and self.kind == other.kind
            and self.ids == other.ids
        )

    def __hash__(self) -> int:
        return hash((self.kind, self.ids))

    def __repr__(self) -> str:
        if self.kind == self.ALL:
            return "Target.all()"
        if self.kind == self.ALL_EXCEPT:
            return f"Target.all_except({sorted(self.ids, key=repr)!r})"
        return f"Target.nodes({sorted(self.ids, key=repr)!r})"


@dataclass(slots=True)
class TargetedMessage(Generic[M]):
    """A message plus its routing directive.

    Reference: ``src/traits.rs :: TargetedMessage``.
    """

    target: Target
    message: M

    def map(self, f: Callable[[M], Any]) -> "TargetedMessage":
        return TargetedMessage(self.target, f(self.message))


@dataclass(slots=True)
class Step(Generic[M, O]):
    """The result of handling one input or message.

    Reference: ``src/traits.rs :: Step`` — ``output: Vec<O>``, ``fault_log``,
    ``messages: Vec<TargetedMessage>``, combinators ``extend``/``join``/``map``.
    """

    output: List[O] = field(default_factory=list)
    fault_log: FaultLog = field(default_factory=FaultLog)
    messages: List[TargetedMessage] = field(default_factory=list)

    # -- constructors ------------------------------------------------------
    @classmethod
    def from_output(cls, out: O) -> "Step":
        return cls(output=[out])

    @classmethod
    def from_fault(cls, node_id: NodeId, kind: FaultKind) -> "Step":
        return cls(fault_log=FaultLog.init(node_id, kind))

    @classmethod
    def from_msg(cls, msg: TargetedMessage) -> "Step":
        return cls(messages=[msg])

    # -- combinators -------------------------------------------------------
    def extend(self, other: "Step") -> "Step":
        """Absorb ``other`` into ``self`` (in place), returning ``self``."""
        self.output.extend(other.output)
        self.fault_log.extend(other.fault_log)
        self.messages.extend(other.messages)
        return self

    def join(self, other: "Step") -> "Step":
        return self.extend(other)

    def map(
        self,
        msg_f: Callable[[M], Any],
        out_f: Optional[Callable[[O], Any]] = None,
    ) -> "Step":
        """Rewrap messages (and optionally outputs) IN PLACE, returning
        ``self``.

        This is how an outer protocol lifts an inner protocol's step into
        its own message/output types (reference ``Step::map``).  The
        receiver is CONSUMED: every call site discards it in favor of the
        result, and the QHB wrapper chain maps each step three times per
        message — copying output/fault/message lists at every layer was a
        measurable slice of the per-message hot path.
        """
        if out_f:
            self.output = [out_f(o) for o in self.output]
        self.messages = [tm.map(msg_f) for tm in self.messages]
        return self

    def send(self, target: Target, message: M) -> "Step":
        self.messages.append(TargetedMessage(target, message))
        return self

    def send_all(self, message: M) -> "Step":
        return self.send(Target.all(), message)

    def send_to(self, node_id: NodeId, message: M) -> "Step":
        return self.send(Target.node(node_id), message)

    def fault(self, node_id: NodeId, kind: FaultKind) -> "Step":
        self.fault_log.append(node_id, kind)
        return self

    def __repr__(self) -> str:
        return (
            f"Step(output={self.output!r}, faults={len(self.fault_log)}, "
            f"messages={len(self.messages)})"
        )


class StepObserver:
    """Observability hook threaded through :class:`Step` processing.

    The protocols stay sans-I/O: they never call this themselves.  Every
    driver that pumps Steps — ``sim.virtual_net.VirtualNet`` per delivery,
    ``net.runtime.NodeRuntime`` per socket message — reports each inbound
    message and the resulting Step through one of these, which is how the
    epoch-phase tracer (``obs.spans.SpanTracer``) attributes wall-clock time
    to RBC/ABA/coin/decrypt/DKG phases without touching protocol code.

    Both methods are optional no-ops; ``t`` is a monotonic timestamp the
    driver may supply (the observer stamps its own clock when omitted).
    """

    def on_message(self, sender_id: NodeId, message: Any,
                   t: Optional[float] = None) -> None:
        """One inbound protocol message, before it is handled."""

    def on_input(self, sender_id: NodeId, input: Any,
                 t: Optional[float] = None) -> None:
        """A locally-admitted input (contribution), before it is handled
        — the ingress end of the per-tx causal trace
        (``obs.trace`` / ``obs.critpath``)."""

    def on_step(self, step: "Step", t: Optional[float] = None) -> None:
        """The Step the protocol returned (outputs close epochs)."""

    def on_note(self, kind: str, detail: str,
                t: Optional[float] = None) -> None:
        """An out-of-band driver lifecycle event (``start`` / ``restart``
        / ``replay_gap`` / ``crash`` / ``stop``) — protocol-free context
        the flight recorder journals alongside the message stream."""


class ConsensusProtocol(abc.ABC, Generic[M, O]):
    """Abstract sans-I/O consensus state machine.

    Reference: ``src/traits.rs :: ConsensusProtocol`` (older name
    ``DistAlgorithm``).  Implementations are single-threaded and
    deterministic; randomness, time, and delivery order all live with the
    caller.
    """

    @abc.abstractmethod
    def handle_input(self, input: Any) -> Step:
        """Propose/insert our own input into the protocol."""

    @abc.abstractmethod
    def handle_message(self, sender_id: NodeId, message: M) -> Step:
        """Process one message received from ``sender_id``.

        ``message`` must be one of the protocol's message types: the wire
        codec / simulator owns that guarantee (the reference gets it from
        serde — untypeable bytes never reach the protocol).  A wrong *type*
        raises ``TypeError``; Byzantine *content* in a well-typed message
        never raises — it is recorded in the step's fault log.
        """

    @abc.abstractmethod
    def terminated(self) -> bool:
        """True once the protocol can make no further progress."""

    @abc.abstractmethod
    def our_id(self) -> NodeId:
        """This node's identifier."""

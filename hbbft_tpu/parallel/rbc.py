"""Batched Bracha reliable broadcast as a dense array program.

Reference semantics: ``src/broadcast/broadcast.rs`` (see the object-mode
mirror in :mod:`hbbft_tpu.protocols.broadcast`).  Here one *communication
round* of the whole network — N proposers × N receivers — executes as a
single jitted computation over dense arrays (the bulk-synchronous
over-approximation of SURVEY.md §5: every message of a round is "in flight"
at once, and adversarial schedules are recovered via delivery-mask and
tamper arrays instead of a message queue).

Axes: ``P`` proposers (RBC instances), ``N`` nodes, ``k = N−2f`` data
shards, ``B`` bytes per shard, ``D`` Merkle proof depth.

Protocol dataflow (all phases batched, nothing data-dependently shaped):

1. *Value* — proposers RS-encode (constant bit-plane matmul → MXU), Merkle
   commit (batched keccak), and "send" shard i + proof to node i: delivery is
   the mask ``value_mask[p, i]``.
2. *Echo* — each node that validated its Value proof re-sends it to all;
   arrival is ``echo_mask[i, j, p]``.  Receivers verify all N×P proofs in one
   ``merkle_verify_jax`` sweep and count.
3. *Ready* — sent on ≥ N−f echoes; one amplification sub-round (f+1 rule);
   arrival masks ``ready_mask``.
4. *Decode* — receivers holding ≥ 2f+1 Readys and ≥ k valid echoes pick their
   first k surviving shard indices, invert the matching encode-matrix rows
   *on device* (``gf_inv_matrix_jnp`` — the survivor pattern is
   data-dependent under adversarial drops), reconstruct, re-encode, rebuild
   the Merkle root and compare — the faulty-proposer (inconsistent codeword)
   check, exactly as the object-mode ``Broadcast._try_decode``.

Byzantine proposer models:
- ``codeword_tamper``: XORed into shards *before* the Merkle commit — an
  inconsistent codeword with valid proofs; caught by the re-encode check.
- ``value_tamper``: XORed *after* the commit — invalid proofs; caught by
  per-receiver proof verification.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from hbbft_tpu.ops import gf16, gf256
from hbbft_tpu.ops import rs as rs_mod
from hbbft_tpu.ops.merkle import merkle_build_jax, merkle_verify_jax


class BatchedRbc:
    """Batched RBC rounds for an (n, f) network.

    All methods are pure array functions, safe under ``jax.jit`` /
    ``shard_map`` (static shapes, no Python branching on data).

    Multi-chip: the sharded counterparts live in
    :mod:`hbbft_tpu.parallel.mesh` — ``make_sharded_rbc_run`` (N ≤ 256:
    node-axis sharding, proposal fan-out as hierarchical all_gathers)
    and ``make_sharded_rbc_large_run`` (N > 256: the proposer axis of
    :meth:`large_stage_a`/``b`` sharded; the straggler decode between
    the stages stays on the host).  Both are bit-equal to the
    single-device paths here (tier-1 asserts it).
    """

    def __init__(self, n: int, f: int):
        self.n = n
        self.f = f
        self.coder = rs_mod.for_n_f(n, f)
        self.k = self.coder.data_shards
        # N > 256 exceeds GF(2^8): the coder (and the masked path's device
        # decode) switches to GF(2^16); full delivery takes the chunked
        # scale path (_run_large)
        self.large = n > 256
        self._jit_cache = {}

    # ---------------------------------------------------------------- phases

    def _decode_batch(self, surv, use):
        """Survivor-dependent decode on device, in the coder's field.

        surv: uint8 (..., k, B) survivor shards; use: int (..., k) their
        row indices in the encode matrix.  Returns ``(data, inv_ok)`` with
        data (..., k, B) — the batched equivalent of the host
        ``reconstruct`` (invert the survivor rows, apply as a bit-matrix).
        """
        import jax.numpy as jnp

        enc = jnp.asarray(self.coder.matrix)  # (n, k) constant
        sub = enc[use]  # (..., k, k)
        if self.large:
            dec, inv_ok = gf16.gf_inv_matrix_jnp(sub)
            dec_bits = gf16.gf_matrix_to_bits_jnp(dec)
            return gf16.gf_apply_bitmatrix(surv, dec_bits), inv_ok
        dec, inv_ok = gf256.gf_inv_matrix_jnp(sub)
        dec_bits = gf256.gf_matrix_to_bits_jnp(dec)  # (..., k*8, k*8)
        out = gf256.gf_apply_bitmatrix(jnp.swapaxes(surv, -1, -2), dec_bits)
        return jnp.swapaxes(out, -1, -2), inv_ok

    def propose(self, data, codeword_tamper=None):
        """Proposer phase: encode + Merkle commit, batched over proposers.

        data: uint8 (P, k, B) → (shards (P, N, B), root (P, 32),
        proofs (P, N, D, 32), proof_mask (N, D)).
        """
        import jax.numpy as jnp

        shards = self.coder.encode_jax(data)  # (P, n, B)
        if codeword_tamper is not None:
            shards = shards ^ codeword_tamper
        root, proofs, pmask = merkle_build_jax(shards)
        return shards, root, proofs, pmask

    def run(
        self,
        data,
        value_mask=None,
        echo_mask=None,
        ready_mask=None,
        codeword_tamper=None,
        value_tamper=None,
        receivers=None,
    ):
        """One full batched RBC execution (Value→Echo→Ready→decode).

        data: uint8 (P, k, B).
        value_mask: bool (P, N) — Value p→i delivered (default all).
        echo_mask: bool (N, N, P) — Echo i→j for p delivered (default all).
        ready_mask: bool (N, N, P) — Ready i→j for p delivered (default all).
        codeword_tamper / value_tamper: uint8 (P, N, B) XOR patterns.
        receivers: optional int array — restrict the per-receiver decode of
        the masked path (its cost bound at large N; see run_from_proposal).

        Returns a dict of arrays:
        ``delivered`` bool (N, P), ``fault`` bool (N, P) (proposer proven
        faulty at that receiver), ``data`` uint8 (N, P, k, B) (valid only
        where delivered), ``root`` (P, 32), ``echo_count`` (N, P),
        ``ready_count`` (N, P).
        """
        if self.large and receivers is None and not any(
            m is not None for m in (value_mask, echo_mask, ready_mask)
        ):
            # full-delivery scale path (chunked, root-only Merkle) — the
            # masked path below also works for N > 256 (GF(2^16) decode on
            # device) but materializes (receiver, sender, instance) tensors;
            # callers bound its cost via small P / the `receivers` arg
            return self._run_large(data, codeword_tamper, value_tamper)
        shards, root, proofs, pmask = self.propose(data, codeword_tamper)
        sent = shards if value_tamper is None else shards ^ value_tamper
        return self.run_from_proposal(
            sent, root, proofs, pmask, value_mask, echo_mask, ready_mask,
            receivers=receivers,
        )

    def run_from_proposal(
        self,
        sent,
        root,
        proofs,
        pmask,
        value_mask=None,
        echo_mask=None,
        ready_mask=None,
        receivers=None,
    ):
        """Echo→Ready→decode given (possibly tampered) proposal arrays.

        ``receivers``: optional int array of receiver indices — the decode
        phase (the per-receiver heavy part) runs only for these; counting
        phases are global and cheap.  Used by the ``shard_map`` wrapper to
        place a slice of receivers on each device.  Default: all n.
        """
        import jax.numpy as jnp

        n, f, k = self.n, self.f, self.k
        P = sent.shape[0]

        if (value_mask is None and echo_mask is None and ready_mask is None
                and receivers is None):
            # full-delivery fast path: every receiver sees the identical
            # message set, so counting is O(N·P) and the heavy decode runs
            # ONCE and is shared — this is what makes N ≥ 1024 feasible
            # (the masked path materializes (receiver, sender, instance)
            # tensors and per-receiver decodes: O(N³) / O(N²·k·B)).
            return self._run_full_delivery(sent, root, proofs, pmask)

        if value_mask is None:
            value_mask = jnp.ones((P, n), dtype=bool)
        if echo_mask is None:
            echo_mask = jnp.ones((n, n, P), dtype=bool)
        if ready_mask is None:
            ready_mask = jnp.ones((n, n, P), dtype=bool)
        # Self-edges cannot be dropped: object mode handles a node's own
        # Value/Echo/Ready internally (no network hop), so the diagonal is
        # forced on to keep mask semantics aligned with the oracle.
        eye_n = jnp.eye(n, dtype=bool)
        value_mask = value_mask | (jnp.arange(n)[None, :] == jnp.arange(P)[:, None])
        echo_mask = echo_mask | eye_n[:, :, None]
        ready_mask = ready_mask | eye_n[:, :, None]

        # -- Value: node i verifies its own proof (index binding is by
        # construction: slot i of proposer p's tree) ----------------------
        idx = jnp.broadcast_to(jnp.arange(n)[None, :], (P, n))
        vv = merkle_verify_jax(
            sent,                                  # (P, n, B) leaf values
            idx,                                   # (P, n)
            root[:, None, :],                      # broadcast (P, 1, 32)
            proofs,                                # (P, n, D, 32)
            pmask[None, :, :],                     # (1, n, D)
        )  # (P, n) bool
        vv = vv & value_mask

        # -- Echo: i → all j; per-source validity is vv (tamper is
        # per-source, so every receiver's verification agrees) -------------
        # valid_echo[j, i, p] = vv[p, i] & echo_mask[i, j, p]
        valid_echo = vv.T[None, :, :] & jnp.transpose(echo_mask, (1, 0, 2))
        echo_count = valid_echo.sum(axis=1)  # (j, P) over sources i

        # -- Ready: send on ≥ n−f echoes; Bracha f+1 amplification to
        # fixpoint (monotone — matches object-mode run-to-quiescence even
        # when amplification chains through several hops of the mask) ------
        import jax

        rmask_t = jnp.transpose(ready_mask, (1, 0, 2))  # (l, j, P)
        ready_send0 = echo_count >= (n - f)  # (j, P)

        def amplify(_, rs_now):
            counts = (rs_now[None, :, :] & rmask_t).sum(axis=1)  # (l, P)
            return rs_now | (counts >= (f + 1))

        ready_send = jax.lax.fori_loop(0, n, amplify, ready_send0)
        ready_count = (ready_send[None, :, :] & rmask_t).sum(axis=1)  # (l, P)

        can_decode = (ready_count >= (2 * f + 1)) & (echo_count >= k)

        # -- restrict the heavy per-receiver decode to `receivers` ---------
        if receivers is None:
            receivers = jnp.arange(n)
        valid_echo = jnp.take(valid_echo, receivers, axis=0)
        echo_count = jnp.take(echo_count, receivers, axis=0)
        ready_count = jnp.take(ready_count, receivers, axis=0)
        can_decode = jnp.take(can_decode, receivers, axis=0)
        nl = receivers.shape[0]

        # -- Decode: first-k surviving shard selection (data-dependent) ----
        sel = jnp.transpose(valid_echo, (0, 2, 1))  # (l, P, i)
        order = jnp.argsort(~sel, axis=-1, stable=True)  # present-first, asc i
        use = order[..., :k]  # (l, P, k) survivor shard indices
        surv_ok = jnp.take_along_axis(sel, use, axis=-1).all(axis=-1)

        # survivor shards: sent[p, use[l,p,t], :] → (l, P, k, B)
        surv = jnp.take_along_axis(
            jnp.broadcast_to(sent[None], (nl, *sent.shape)),  # (l, P, n, B)
            use[..., None],
            axis=-2,
        )
        data_rec, inv_ok = self._decode_batch(surv, use)  # (l, P, k, B)

        # -- re-encode + Merkle root check (faulty-proposer detection) -----
        # Reference semantics (``reed-solomon-erasure``'s reconstruct +
        # ``Broadcast::compute_output``): present shards are used AS
        # RECEIVED; only missing ones come from the re-encode.  The root is
        # rebuilt over that mixed shard set and compared to the agreed one.
        full = self.coder.encode_jax(data_rec)  # (l, P, n, B)
        present = sel[..., None]  # (l, P, i, 1)
        full_obj = jnp.where(present, jnp.broadcast_to(sent[None], full.shape), full)
        root_chk, _, _ = merkle_build_jax(full_obj)
        root_ok = jnp.all(root_chk == root[None], axis=-1)  # (l, P)
        data_rec = full_obj[..., :k, :]  # data rows, received-where-present

        # framing check — object mode's ``_unframe_value`` returns None (→
        # proposer fault) when the length prefix is inconsistent; mirror it:
        # the first 4 bytes of the row-major (k·B) stream must encode a
        # length fitting in the payload.
        B = sent.shape[-1]
        flat = data_rec.reshape(*data_rec.shape[:-2], k * B)
        if k * B >= 4:
            ln = (
                flat[..., 0].astype(jnp.uint32) << 24
                | flat[..., 1].astype(jnp.uint32) << 16
                | flat[..., 2].astype(jnp.uint32) << 8
                | flat[..., 3].astype(jnp.uint32)
            )
            frame_ok = ln <= jnp.uint32(k * B - 4)  # no +4: uint32 overflow
        else:
            frame_ok = jnp.zeros(flat.shape[:-1], dtype=bool)

        ok = can_decode & surv_ok & inv_ok
        delivered = ok & root_ok & frame_ok
        fault = ok & ~(root_ok & frame_ok)
        return {
            "delivered": delivered,
            "fault": fault,
            "data": data_rec,
            "data_receivers": receivers,
            "root": root,
            "echo_count": echo_count,
            "ready_count": ready_count,
        }

    def _run_full_delivery(self, sent, root, proofs, pmask):
        """All messages delivered: every receiver's state is identical, so
        verdicts are computed once and broadcast.  ``data`` has a single
        shared row (``data_receivers == [0]``)."""
        import jax.numpy as jnp

        n, f, k = self.n, self.f, self.k
        P = sent.shape[0]

        idx = jnp.broadcast_to(jnp.arange(n)[None, :], (P, n))
        vv = merkle_verify_jax(
            sent, idx, root[:, None, :], proofs, pmask[None, :, :]
        )  # (P, n): source i's Value/Echo is valid
        ec = vv.sum(axis=1)  # (P,) — every receiver counts the same echoes
        ready = ec >= (n - f)
        rc = jnp.where(ready, n, 0)  # all n send Ready together
        can_decode = (rc >= (2 * f + 1)) & (ec >= k)

        # shared decode: first-k surviving shards (same pattern everywhere)
        order = jnp.argsort(~vv, axis=-1, stable=True)
        use = order[..., :k]  # (P, k)
        surv_ok = jnp.take_along_axis(vv, use, axis=-1).all(axis=-1)
        surv = jnp.take_along_axis(sent, use[..., None], axis=-2)  # (P,k,B)
        data_rec, inv_ok = self._decode_batch(surv, use)  # (P, k, B)

        full = self.coder.encode_jax(data_rec)  # (P, n, B)
        full_obj = jnp.where(vv[..., None], sent, full)
        root_chk, _, _ = merkle_build_jax(full_obj)
        root_ok = jnp.all(root_chk == root, axis=-1)
        data_rec = full_obj[..., :k, :]

        B = sent.shape[-1]
        flat = data_rec.reshape(P, k * B)
        if k * B >= 4:
            ln = (
                flat[..., 0].astype(jnp.uint32) << 24
                | flat[..., 1].astype(jnp.uint32) << 16
                | flat[..., 2].astype(jnp.uint32) << 8
                | flat[..., 3].astype(jnp.uint32)
            )
            frame_ok = ln <= jnp.uint32(k * B - 4)
        else:
            frame_ok = jnp.zeros((P,), dtype=bool)

        ok = can_decode & surv_ok & inv_ok
        delivered = ok & root_ok & frame_ok  # (P,)
        fault = ok & ~(root_ok & frame_ok)
        bc = lambda a: jnp.broadcast_to(a[None, :], (n, P))
        return {
            "delivered": bc(delivered),
            "fault": bc(fault),
            "data": data_rec[None],  # (1, P, k, B) — shared row
            "data_receivers": jnp.zeros((1,), dtype=jnp.int32),
            "root": root,
            "echo_count": bc(ec),
            "ready_count": bc(rc),
        }


    # -- pickling (snapshot/restore support) --------------------------------

    def __getstate__(self):
        """Drop jit handles and device-resident constants — they rebuild
        lazily after :func:`hbbft_tpu.snapshot.restore`."""
        d = self.__dict__.copy()
        d["_jit_cache"] = {}
        d.pop("_pbits_dev", None)
        return d

    # ------------------------------------------------------------- large N
    def _jit(self, name, fn):
        if name not in self._jit_cache:
            import jax

            self._jit_cache[name] = jax.jit(fn)
        return self._jit_cache[name]

    def _large_chunk_size(self, P: int) -> int:
        # chunk the proposer axis: bounds the keccak working set (P·n Merkle
        # leaves at once is gigabytes at N=4096).  cs is shape-derived, so
        # it must be part of the jit-cache key (a cached closure retraced
        # with a stale cs would mis-reshape a different P).
        return next(c for c in (64, 32, 16, 8, 4, 2, 1) if P % c == 0)

    @staticmethod
    def _chunked_map(fn, args, P: int, cs: int):
        """lax.map ``fn`` over proposer-axis chunks of ``args`` (None
        members pass through unchunked as empty pytrees)."""
        import jax

        nch = P // cs
        chunk = lambda a: (
            None if a is None else a.reshape(nch, cs, *a.shape[1:])
        )
        outs = jax.lax.map(fn, tuple(chunk(a) for a in args))
        unc = lambda a: a.reshape(a.shape[0] * a.shape[1], *a.shape[2:])
        return tuple(unc(o) for o in outs)

    def large_stage_a(self, d, cw, vt, pbits, cs: int):
        """Large-N stage 1 (pure; proposer-parallel): encode + root-only
        Merkle commit + god-view echo validity.  Shardable over the
        proposer axis — no cross-proposer dataflow (mesh.py wraps it in
        ``shard_map``)."""
        import jax.numpy as jnp

        from hbbft_tpu.ops.merkle import merkle_root_jax

        k = self.k

        def one(args):
            dc, cwc, vtc = args
            shards = self.coder.encode_jax(dc, pbits)
            if cwc is not None:
                shards = shards ^ cwc
            root = merkle_root_jax(shards)
            sent = shards if vtc is None else shards ^ vtc
            vv = jnp.all(sent == shards, axis=-1)
            # per-proposer reductions IN-GRAPH so the host decision reads
            # (P,) scalars instead of the (P, N) vv matrix — 16 MB/epoch
            # across the bandwidth-limited link at N=4096
            ec = vv.sum(axis=-1).astype(jnp.int32)
            ident = vv[..., :k].all(axis=-1)
            return sent, root, vv, ec, ident

        return self._chunked_map(one, (d, cw, vt), d.shape[0], cs)

    def large_stage_b(self, dr, sent_, vv_, root_, pbits, cs: int):
        """Large-N stage 2 (pure; proposer-parallel): re-encode, root
        re-check, framing check.  Shardable like :meth:`large_stage_a`."""
        import jax.numpy as jnp

        from hbbft_tpu.ops.merkle import merkle_root_jax

        k = self.k

        def one(args):
            drc, sc, vc, rc = args
            full = self.coder.encode_jax(drc, pbits)
            full_obj = jnp.where(vc[..., None], sc, full)
            root_chk = merkle_root_jax(full_obj)
            root_ok = jnp.all(root_chk == rc, axis=-1)
            out_data = full_obj[..., :k, :]
            B = out_data.shape[-1]
            flat = out_data.reshape(out_data.shape[0], k * B)
            ln = (
                flat[..., 0].astype(jnp.uint32) << 24
                | flat[..., 1].astype(jnp.uint32) << 16
                | flat[..., 2].astype(jnp.uint32) << 8
                | flat[..., 3].astype(jnp.uint32)
            )
            frame_ok = ln <= jnp.uint32(k * B - 4)
            return out_data, root_ok, frame_ok

        return self._chunked_map(one, (dr, sent_, vv_, root_), dr.shape[0], cs)

    def _pbits(self):
        import jax.numpy as jnp

        if not hasattr(self, "_pbits_dev"):
            self._pbits_dev = jnp.asarray(self.coder._parity_bits)
        return self._pbits_dev

    def upload_framed(self, values):
        """Frame ``values`` like :func:`frame_values` but cross the
        host→device link compact: the (P, k, B) frame is k·B bytes per
        proposer (the GF(2^16) coder's minimum k·2 ≈ 2.7 KB at N=4096)
        while the actual payload is 4+len(v) bytes — at the flagship
        shape ~87 % of the naive upload is zero padding.  Uploads a
        (P, L) buffer trimmed to the longest payload and zero-pads +
        reshapes ON DEVICE; bit-identical to uploading
        ``frame_values(values, k)``.
        """
        import jax.numpy as jnp

        k = self.k
        shard_len = _frame_shard_len(values, k)
        # round the buffer width up (extra zeros are exactly what the
        # device-side pad writes) so the expand jit-key set stays small
        # across epochs with drifting payload sizes, like _fetch_data_compact
        L = min(
            -(-max(4 + len(v) for v in values) // 256) * 256,
            k * shard_len,
        )
        P = len(values)
        buf = np.zeros((P, L), dtype=np.uint8)
        for i, v in enumerate(values):
            stream = _frame_stream(v)
            buf[i, : len(stream)] = np.frombuffer(stream, dtype=np.uint8)

        def expand(b):
            return jnp.pad(
                b, ((0, 0), (0, k * shard_len - L))
            ).reshape(P, k, shard_len)

        return self._jit(("expand", P, L, shard_len), expand)(
            jnp.asarray(buf)
        )

    def _fetch_data_compact(self, out_data, frame_ok=None):
        """Device→host fetch of the shared (P, k, B) data row, bounded by
        the per-proposer framed lengths: only ``max(ln)+4`` leading bytes
        of each row cross the link (the rest of a frame is zero padding —
        the inverse of :meth:`upload_framed`'s compaction).  Rows whose
        framing check failed contribute nothing to the bound and are
        masked to ALL-ZEROS in the returned array — a fault row is only
        partially inside the fetch window, and partial bytes must never
        read as real shard data.  ``frame_ok=None`` derives the framing
        verdict from the fetched lengths (the all-match fast path, where
        data rows are the committed shards verbatim).  Returns
        ``(host (P, k, B) uint8 array, ln, frame_ok)``."""
        import jax.numpy as jnp

        P, k, B = out_data.shape
        kb = k * B

        def ln_of(d):
            flat = d.reshape(P, kb)
            return (
                flat[:, 0].astype(jnp.uint32) << 24
                | flat[:, 1].astype(jnp.uint32) << 16
                | flat[:, 2].astype(jnp.uint32) << 8
                | flat[:, 3].astype(jnp.uint32)
            )

        ln = np.asarray(self._jit(("ln", P, kb), ln_of)(out_data))
        if frame_ok is None:
            frame_ok = ln <= np.uint32(kb - 4)
        ok_ln = ln[frame_ok]
        maxb = int(min(kb, (int(ok_ln.max()) + 4) if ok_ln.size else 4))
        # round the fetch window up so the slice jit-key set stays small
        # across epochs with drifting payload sizes
        maxb = int(min(kb, -(-maxb // 256) * 256))

        def head(d):
            return d.reshape(P, kb)[:, :maxb]

        host = np.zeros((P, kb), dtype=np.uint8)
        host[:, :maxb] = np.asarray(
            self._jit(("head", P, kb, maxb), head)(out_data)
        )
        # fault rows come back ALL-ZERO: a row whose framing failed is
        # only partially inside the fetch window, and partial row bytes
        # must never be mistakable for real shard data by a future
        # (diagnostic/observability) consumer — delivered rows are the
        # only ones carrying payload
        host[~np.asarray(frame_ok)] = 0
        return host.reshape(P, k, B), ln, frame_ok

    def finish_large(self, stage_a_out, stage_b_fn):
        """Shared host orchestration of the large-N round: threshold
        decisions + straggler decode between stage A and stage B, then the
        ``run`` result-dict assembly.  Used by both the single-device
        ``_run_large`` and the mesh-sharded variant so the delivery-verdict
        logic exists exactly once.

        ``stage_a_out``: (sent, root, vv, ec_d, ident_d) device arrays;
        ``stage_b_fn(data_rec, sent, vv, root)`` runs the (possibly
        sharded) stage B.
        """
        import jax.numpy as jnp

        n, f, k = self.n, self.f, self.k
        sent, root, vv, ec_d, ident_d = stage_a_out
        # only the (P,)-shaped reductions cross the link; vv stays on
        # device for stage B (and is fetched only on the rare straggler
        # path below)
        ec = np.asarray(ec_d)
        ident = np.asarray(ident_d)
        ready = ec >= (n - f)
        can_decode = ready & (ec >= k)
        all_match = bool((ec == n).all())  # every shard equals commitment
        if bool(ident.all()):
            data_rec = sent[:, :k, :]
        else:
            data_rec = jnp.asarray(self.reconstruct_stragglers(
                np.asarray(sent), np.asarray(vv), can_decode, ident
            ))

        if all_match:
            # Stage B is a TAUTOLOGY here: vv all-true means sent == the
            # committed shards everywhere, so full_obj == shards and the
            # re-built root equals the stage-A root by construction.  Only
            # the framing check has content — ~half the large-N device
            # work (a full re-encode + a 16.8M-leaf Merkle build at
            # N=4096) skipped on the clean path.
            out_data, _, frame_ok = self._fetch_data_compact(data_rec)
            root_ok = np.ones(ec.shape, dtype=bool)
        else:
            out_data, root_ok, frame_ok = stage_b_fn(
                data_rec, sent, vv, root
            )
            root_ok = np.asarray(root_ok)
            frame_ok = np.asarray(frame_ok)
            out_data, _, _ = self._fetch_data_compact(out_data, frame_ok)
        delivered = can_decode & root_ok & frame_ok
        fault = can_decode & ~(root_ok & frame_ok)
        P = ec.shape[0]
        bc = lambda a: np.broadcast_to(a[None, :], (n, P))
        return {
            "delivered": bc(delivered),
            "fault": bc(fault),
            "data": out_data[None],  # (1, P, k, B) shared row (host)
            "data_receivers": np.zeros((1,), dtype=np.int32),
            "root": np.asarray(root),
            "echo_count": bc(ec),
            "ready_count": bc(np.where(ready, n, 0)),
        }

    def reconstruct_stragglers(self, sent_h, vv_h, can_decode, ident):
        """Host GF(2^16) reconstruct for proposers whose first k shards did
        not survive (rare); identity rows elsewhere.  Shared by the
        single-device and mesh large-N paths."""
        rows = []
        k = self.k
        for p in range(sent_h.shape[0]):
            if ident[p] or not can_decode[p]:
                rows.append(sent_h[p, :k])
                continue
            use = tuple(np.flatnonzero(vv_h[p])[:k].tolist())
            rows.append(
                self.coder.reconstruct_data_np(sent_h[p, list(use)], use)
            )
        return np.stack(rows)

    def _run_large(self, data, codeword_tamper=None, value_tamper=None):
        """Full-delivery RBC round for N > 256 (GF(2^16) coder).

        Two jitted stages with a host decision between them:

        1. encode + root-only Merkle commit; echo validity as a direct
           comparison of the received shard against the commitment (the
           simulator's god-view equivalent of per-proof verification —
           a proof verifies iff the shard matches what was committed; the
           per-receiver verify work a deployment performs is charged by
           ``CostModel.batched_epoch_estimate``'s proof-verification term,
           so the shortcut is cost-accounted, not dropped);
        2. reconstruct (identity decode where the data rows survived —
           the overwhelmingly common case; host GF(2^16) decode for the
           stragglers), re-encode, root re-check, framing check.
        """
        P = data.shape[0]
        cs = self._large_chunk_size(P)

        def stage_a(d, cw, vt, pbits):
            return self.large_stage_a(d, cw, vt, pbits, cs)

        key = ("A", P, cs, codeword_tamper is not None,
               value_tamper is not None)
        a_out = self._jit(key, stage_a)(
            data, codeword_tamper, value_tamper, self._pbits()
        )

        def stage_b(dr, sent_, vv_, root_, pbits):
            return self.large_stage_b(dr, sent_, vv_, root_, pbits, cs)

        jit_b = self._jit(("B", P, cs), stage_b)
        return self.finish_large(
            a_out,
            lambda dr, sent_, vv_, root_: jit_b(
                dr, sent_, vv_, root_, self._pbits()
            ),
        )


# -- host-side helpers for tests / object-mode cross-checks -----------------


def _frame_shard_len(values, k: int) -> int:
    """The common shard length for a batch of values: rounded up to even
    so the same framing feeds both the GF(2^8) and GF(2^16) (u16-symbol)
    coders.  Single source of truth for :func:`frame_values` and the
    compact ``upload_framed`` path — they must stay bit-identical."""
    shard_len = max(2, max(-(-(4 + len(v)) // k) for v in values))
    return shard_len + shard_len % 2


def _frame_stream(v: bytes) -> bytes:
    """One value's framed byte stream (4-byte length prefix + payload)."""
    return len(v).to_bytes(4, "big") + v


def frame_values(values, k: int) -> np.ndarray:
    """Frame a list of P byte-strings like the object-mode proposer does
    (4-byte length prefix, zero-padded) at one common shard length, so the
    row-major byte stream stays contiguous: (P, k, B)."""
    shard_len = _frame_shard_len(values, k)
    out = np.zeros((len(values), k, shard_len), dtype=np.uint8)
    for i, v in enumerate(values):
        stream = _frame_stream(v).ljust(k * shard_len, b"\0")
        out[i] = np.frombuffer(stream, dtype=np.uint8).reshape(k, shard_len)
    return out


def unframe_value(data_row: np.ndarray) -> Optional[bytes]:
    """Inverse of :func:`frame_values` for one (k, B) reconstruction."""
    from hbbft_tpu.protocols.broadcast import _unframe_value

    return _unframe_value(np.asarray(data_row, dtype=np.uint8).tobytes())

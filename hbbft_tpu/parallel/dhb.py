"""Array-mode DynamicHoneyBadger: validator-set changes over batched epochs.

The array-mode counterpart of
:mod:`hbbft_tpu.protocols.dynamic_honey_badger` (reference:
``src/dynamic_honey_badger/`` + ``src/sync_key_gen.rs``): every epoch runs
as one :class:`~hbbft_tpu.parallel.acs.BatchedHoneyBadgerEpoch` (TPKE
encrypt → batched ACS → master-scalar decrypt) whose contributions are the
object-mode ``InternalContrib`` wire format — user payload + signed votes +
signed DKG Part/Ack messages.  Vote counting, the per-node ``SyncKeyGen``
instances, and era rotation then run on the god-view exactly once, the same
way the batched simulator combines threshold shares once per proposer: every
correct node processes the identical committed batch deterministically, so
one execution of the deterministic state transition IS every node's
execution (the per-node signature/commitment re-verification a deployment
performs N× is the cost model's business, mirroring
``CostModel.batched_epoch_estimate``'s accounting stance).

God-view divergences from the object-mode state machines, documented:

- Key-gen gossip (``KeyGenWrap`` broadcasts) is instant: a Part/Ack a node
  emits lands in the shared pending pool immediately and is proposed by
  validators in the next epoch's contributions.  Object mode's direct
  broadcast + per-node ``pending_kg`` converges to the same committed
  sequence; the committed sequence is the only thing that drives state.
- Votes/parts/acks are signature-checked once (god view); each node's
  ``SyncKeyGen`` still processes every committed Part/Ack itself, so the
  per-node key material (rows, acks, resulting ``SecretKeyShare``) is the
  real thing, node for node — era rotation produces a genuine new
  ``NetworkInfo`` map with working threshold keys (asserted by running the
  next era's epochs under them).

Eras mirror object mode: ``session_id + era`` namespaces each era's inner
epochs, the batch reports ``ChangeState`` exactly as
``dynamic_honey_badger.rs`` does (``InProgress`` while the DKG runs, and
the era-completing batch itself reports ``Complete``).
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional, Set, Tuple

from hbbft_tpu.crypto import tc
from hbbft_tpu.netinfo import NetworkInfo
from hbbft_tpu.parallel.acs import BatchedHoneyBadgerEpoch
from hbbft_tpu.protocols import wire
from hbbft_tpu.protocols.dynamic_honey_badger import (
    Change,
    ChangeState,
    DhbBatch,
    InternalContrib,
    JoinPlan,
    SignedKeyGenMsg,
    SignedVote,
    VoteCounter,
    _keygen_payload,
    _vote_payload,
    de_ack,
    de_part,
    ser_ack,
    ser_part,
)
from hbbft_tpu.protocols.sync_key_gen import SyncKeyGen


class BatchedDynamicHoneyBadger:
    """God-view epoch driver with on-line validator-set changes.

    ``secret_keys`` must hold the long-term secret key of every current
    validator AND any candidate a vote may add (the god-view simulator owns
    all key material, like ``NetworkInfo.generate_map`` does).
    """

    def __init__(
        self,
        netinfo_map: Dict,
        secret_keys: Optional[Dict] = None,
        session_id: bytes = b"batched-dhb",
        rng: Optional[random.Random] = None,
        mesh=None,
    ):
        self.mesh = mesh
        self.netinfo_map = dict(netinfo_map)
        ids = sorted(self.netinfo_map.keys(), key=repr)
        self.secret_keys = dict(secret_keys) if secret_keys else {
            nid: self.netinfo_map[nid].secret_key() for nid in ids
        }
        self.session_id = session_id
        self.rng = rng or random.Random(0)
        from hbbft_tpu.protocols.honey_badger import EncryptionSchedule

        self.encryption_schedule = EncryptionSchedule.always()
        self.era = 0
        self.epoch = 0  # epoch within the current era
        self.era_has_batches = False
        self.change_state: ChangeState = ChangeState.none()
        self.vote_counter = VoteCounter(0)
        self.vote_num: Dict = {}
        self.pending_votes: Dict[object, List[SignedVote]] = {}
        # shared pools (god-view instant gossip; see module docstring)
        self.pending_kg: List[SignedKeyGenMsg] = []
        self.kg_seen: Set[bytes] = set()
        self.key_gens: Optional[Dict[object, SyncKeyGen]] = None
        self.key_gen_change: Optional[Change] = None
        self.batches: List[DhbBatch] = []
        self.hb = self._make_hb()

    # -- pickling (snapshot/restore support) --------------------------------

    def __getstate__(self):
        """A live ``Mesh`` binds devices and cannot round-trip a pickle;
        refuse here like ``BatchedAcs`` does (the inner epoch's own guard
        no longer fires when the current era fell back to single-device)."""
        if self.mesh is not None:
            raise TypeError(
                "cannot snapshot a mesh-attached BatchedDynamicHoneyBadger; "
                "snapshot the mesh=None driver and re-attach the mesh after "
                "restore"
            )
        return self.__dict__.copy()

    # -- construction of the per-era inner epoch runner ---------------------

    def _make_hb(self) -> BatchedHoneyBadgerEpoch:
        # era rotation can change N to something the mesh no longer divides
        # (the sharded epoch needs n % devices == 0); fall back to the
        # single-device path for such eras rather than refusing the change
        mesh = self.mesh
        if mesh is not None and len(self.netinfo_map) % mesh.devices.size:
            mesh = None
        return BatchedHoneyBadgerEpoch(
            self.netinfo_map,
            session_id=self.session_id + b"/era" + wire.u64(self.era),
            compact=True,
            mesh=mesh,
        )

    @property
    def validators(self) -> List:
        return sorted(self.netinfo_map.keys(), key=repr)

    def is_validator(self, node_id) -> bool:
        return node_id in self.netinfo_map

    # -- votes (mirrors DynamicHoneyBadger.vote_for / vote_to_add/remove) ---

    def vote_for(self, voter, change: Change) -> None:
        if not self.is_validator(voter):
            return
        self.vote_num[voter] = self.vote_num.get(voter, 0) + 1
        payload = _vote_payload(voter, self.era, self.vote_num[voter], change)
        vote = SignedVote(
            voter, self.era, self.vote_num[voter], change,
            self.secret_keys[voter].sign(payload),
        )
        self.pending_votes.setdefault(voter, []).append(vote)

    def vote_to_add(self, voter, node_id, pub_key: tc.PublicKey,
                    secret_key: Optional[tc.SecretKey] = None) -> None:
        """``secret_key`` gives the god-view the candidate's long-term key
        so its DKG instance can decrypt its Part rows after the change wins
        (a real deployment's candidate owns it; the simulator must too)."""
        if secret_key is not None:
            self.secret_keys[node_id] = secret_key
        keys = dict(self.netinfo_map[self.validators[0]].public_key_map())
        keys[node_id] = pub_key
        self.vote_for(voter, Change.node_change(keys))

    def vote_to_remove(self, voter, node_id) -> None:
        keys = dict(self.netinfo_map[self.validators[0]].public_key_map())
        keys.pop(node_id, None)
        self.vote_for(voter, Change.node_change(keys))

    def vote_for_encryption_schedule(self, voter, schedule) -> None:
        self.vote_for(voter, Change.encryption_schedule(schedule))

    # -- the epoch loop ------------------------------------------------------

    def run_epoch(self, contributions: Dict, rng: Optional[random.Random] = None
                  ) -> DhbBatch:
        """One full DHB epoch: wrap per-validator user payloads with their
        pending votes and the shared key-gen pool, run the batched HB epoch,
        then apply votes/DKG/era-rotation to the god view.  Returns the
        :class:`DhbBatch` (identical at every correct node)."""
        rng = rng or random.Random(self.rng.getrandbits(48))
        kg_msgs = list(self.pending_kg)
        internal = {}
        for nid in self.validators:
            contrib = InternalContrib(
                contribution=bytes(contributions.get(nid, b"")),
                votes=list(self.pending_votes.get(nid, [])),
                key_gen_msgs=kg_msgs,
            )
            internal[nid] = contrib.to_bytes()
        batch_map, detail = self.hb.run(
            internal, rng, session_suffix=b"/e" + wire.u64(self.epoch),
            encrypt=self.encryption_schedule.encrypt_on_epoch(self.epoch),
        )
        # what wrappers need for cost accounting (the QDHB virtual clock):
        # n/f of the era that RAN this epoch — _process_batch may rotate
        # the era before control returns to the caller
        self.last_detail = {
            "payload_bytes": int(detail["payload_bytes"]),
            "epochs": int(detail["epochs"]),
            "n": self.hb.n,
            "f": self.hb.f,
        }
        return self._process_batch(batch_map)

    def run_until_change_completes(self, contribution_fn=None,
                                   max_epochs: int = 8) -> DhbBatch:
        """Drive epochs (empty or ``contribution_fn(nid)`` payloads) until
        a batch reports the change Complete — the DKG-pipeline loop the
        object mode keeps alive via ``contribution_provider``."""
        for _ in range(max_epochs):
            contribs = {
                nid: (contribution_fn(nid) if contribution_fn else b"")
                for nid in self.validators
            }
            batch = self.run_epoch(contribs)
            if batch.change.state == "complete":
                return batch
        raise RuntimeError("change did not complete")

    # -- committed-batch processing (the object-mode _process_batch, once) --

    def _process_batch(self, batch_map: Dict) -> DhbBatch:
        contributions: List[Tuple] = []
        all_kg: List[Tuple[object, SignedKeyGenMsg]] = []
        info0 = self.netinfo_map[self.validators[0]]
        for nid in self.validators:
            if nid not in batch_map:
                continue
            contrib = InternalContrib.from_bytes(batch_map[nid])
            contributions.append((nid, contrib.contribution))
            for vote in contrib.votes:
                self._commit_vote(vote, info0)
            for skg in contrib.key_gen_msgs:
                all_kg.append((nid, skg))
        # proposed votes are committed now; drop them from the proposers
        for nid in batch_map:
            self.pending_votes.pop(nid, None)
        # winner check before applying this batch's key-gen messages
        # (a fresh InProgress change means the DKG starts with this batch)
        if self.change_state.state == "none":
            winner = self.vote_counter.compute_winner(self.validators)
            if winner is not None:
                self._start_change(winner)
        # every proposer includes the shared pool, so the batch carries up
        # to N copies of each Part/Ack; the handlers are idempotent (object
        # mode applies the duplicates), so applying each committed message
        # once per batch is the same state, N× cheaper
        seen_in_batch: Set[bytes] = set()
        for _proposer, skg in all_kg:
            key = skg.to_bytes()
            if key in seen_in_batch:
                continue
            seen_in_batch.add(key)
            self._apply_committed_kg(skg)
        era_of_batch, epoch_of_batch = self.era, self.epoch
        self.era_has_batches = True
        self.epoch += 1
        completed = self._try_rotate_era()
        batch = DhbBatch(
            era=era_of_batch,
            epoch=epoch_of_batch,
            contributions=tuple(contributions),
            change=(
                ChangeState.complete(completed)
                if completed is not None
                else self.change_state
            ),
        )
        self.batches.append(batch)
        return batch

    def _commit_vote(self, vote: SignedVote, info0: NetworkInfo) -> None:
        if vote.era != self.era or vote.voter not in self.netinfo_map:
            return
        pk = info0.public_key(vote.voter)
        if pk is None or not pk.verify(vote.sig, vote.signed_payload()):
            return
        self.vote_counter.add_committed(vote)

    # -- DKG (one SyncKeyGen per member of the new set; real key material) --

    def _kg_key_map(self) -> Dict:
        keys = dict(self.netinfo_map[self.validators[0]].public_key_map())
        if self.key_gen_change is not None:
            keys.update(self.key_gen_change.key_map())
        return keys

    def _start_change(self, change: Change) -> None:
        if change.kind == "encryption_schedule":
            # no DKG: rotates at the next batch boundary
            self.change_state = ChangeState.in_progress(change)
            return
        new_keys = change.key_map()
        threshold = (len(new_keys) - 1) // 3
        # validate BEFORE mutating any state: raising with change_state
        # already InProgress (and key_gens still None) would wedge every
        # subsequent epoch on the rotation check
        missing = [n for n in new_keys if n not in self.secret_keys]
        if missing:
            raise ValueError(
                f"god-view needs the long-term secret keys of {missing} "
                "(pass them via vote_to_add(..., secret_key=...))"
            )
        self.change_state = ChangeState.in_progress(change)
        self.key_gen_change = change
        self.key_gens = {
            nid: SyncKeyGen(
                nid, self.secret_keys[nid], dict(new_keys), threshold,
                random.Random(self.rng.getrandbits(64)),
            )
            for nid in sorted(new_keys, key=repr)
        }
        for nid, kg in self.key_gens.items():
            part = kg.generate_part()
            self._queue_kg(nid, "part", ser_part(part))

    def _queue_kg(self, sender, kind: str, payload: bytes) -> None:
        skg = SignedKeyGenMsg(
            era=self.era, sender=sender, kind=kind, payload=payload,
            sig=self.secret_keys[sender].sign(
                _keygen_payload(self.era, sender, kind, payload)
            ),
        )
        key = skg.to_bytes()
        if key not in self.kg_seen:
            self.kg_seen.add(key)
            self.pending_kg.append(skg)

    def _apply_committed_kg(self, skg: SignedKeyGenMsg) -> None:
        if self.key_gens is None or skg.era != self.era:
            return
        key = skg.to_bytes()
        self.kg_seen.add(key)
        self.pending_kg = [m for m in self.pending_kg if m.to_bytes() != key]
        pk = self._kg_key_map().get(skg.sender)
        if pk is None or not pk.verify(skg.sig, skg.signed_payload()):
            return
        if skg.kind == "part":
            part = de_part(skg.payload)
            for nid, kg in self.key_gens.items():
                outcome = kg.handle_part(skg.sender, part)
                if outcome.ack is not None:
                    self._queue_kg(nid, "ack", ser_ack(outcome.ack))
        elif skg.kind == "ack":
            ack = de_ack(skg.payload)
            for kg in self.key_gens.values():
                kg.handle_ack(skg.sender, ack)

    # -- era rotation --------------------------------------------------------

    def _try_rotate_era(self) -> Optional[Change]:
        if self.change_state.state != "in_progress":
            return None
        change = self.change_state.change
        if change.kind == "encryption_schedule":
            from hbbft_tpu.protocols.honey_badger import EncryptionSchedule

            k, a, b = change.schedule
            self.encryption_schedule = EncryptionSchedule(k, a, b)
            self._rotate(change, self.netinfo_map)
            return change
        assert self.key_gens is not None
        if not all(kg.is_ready() for kg in self.key_gens.values()):
            return None
        new_keys = change.key_map()
        new_map: Dict = {}
        pub_key_set = None
        for nid, kg in self.key_gens.items():
            pks, sk_share = kg.generate()
            if pub_key_set is None:
                pub_key_set = pks
            else:
                # deterministic from the committed Part sequence — every
                # node derives the identical public key set
                assert pks.public_key().to_bytes() == \
                    pub_key_set.public_key().to_bytes()
            new_map[nid] = NetworkInfo(
                our_id=nid,
                public_keys=dict(new_keys),
                public_key_set=pks,
                secret_key_share=sk_share,
                secret_key=self.secret_keys[nid],
            )
        self._rotate(change, new_map)
        return change

    def _rotate(self, change: Change, new_map: Dict) -> None:
        self.netinfo_map = dict(new_map)
        self.era += 1
        self.epoch = 0
        self.era_has_batches = False
        self.change_state = ChangeState.none()
        self.vote_counter = VoteCounter(self.era)
        self.key_gens = None
        self.key_gen_change = None
        self.pending_kg = []
        self.kg_seen = set()
        self.vote_num = {}
        self.pending_votes = {}
        self.hb = self._make_hb()

    # -- join plan (era boundary; mirrors DynamicHoneyBadger.join_plan) -----

    def join_plan(self) -> JoinPlan:
        if self.era_has_batches:
            raise ValueError(
                "join_plan() is only valid at an era boundary (epochs of "
                "this era already completed; rotate the era first)"
            )
        from hbbft_tpu.crypto import bls12_381 as bls

        info0 = self.netinfo_map[self.validators[0]]
        pks = info0.public_key_set()
        sched = self.encryption_schedule
        return JoinPlan(
            era=self.era,
            pub_key_set_bytes=b"".join(
                bls.g1_to_bytes(p) for p in pks.commitment.points
            ),
            pub_keys=tuple(
                sorted(
                    (
                        (nid, pk.to_bytes())
                        for nid, pk in info0.public_key_map().items()
                    ),
                    key=lambda kv: repr(kv[0]),
                )
            ),
            encryption_schedule=(sched.kind, sched.a, sched.b),
        )

"""Multi-epoch transaction queueing over the batched HoneyBadger epoch.

The array-mode counterpart of :mod:`hbbft_tpu.protocols.queueing_honey_badger`
(reference: ``src/queueing_honey_badger/`` + ``src/transaction_queue.rs``):
per-node transaction queues, a random ``batch_size`` sample proposed each
epoch (sampling keeps different nodes' proposals mostly disjoint), committed
transactions removed everywhere, leftovers re-proposed — with every epoch
executed as one :class:`~hbbft_tpu.parallel.acs.BatchedHoneyBadgerEpoch`
(TPKE encrypt → batched ACS → master-scalar decrypt) instead of an
object-mode message pump.  This is the scenario the reference's
``examples/simulation.rs`` benchmarks; ``examples/simulation.py --batched``
drives it.
"""

from __future__ import annotations

import struct
from typing import Callable, Dict, List, Optional

from hbbft_tpu.parallel.acs import BatchedHoneyBadgerEpoch
from hbbft_tpu.protocols.queueing_honey_badger import (
    TransactionQueue,
    _de_txs,
    _ser_txs,
)


class BatchedQueueingHoneyBadger:
    """Epoch driver: queues + batched epochs until the ledger drains."""

    def __init__(self, netinfo_map: Dict, batch_size: int = 100,
                 session_id: bytes = b"batched-qhb", encrypt: bool = True,
                 cost_model=None):
        self.hb = BatchedHoneyBadgerEpoch(netinfo_map, session_id=session_id)
        self.ids = self.hb.ids
        self.batch_size = batch_size
        self.encrypt = encrypt
        self.cost_model = cost_model  # optional sim.CostModel → virtual clock
        self.virtual_time = 0.0
        self.queues = {nid: TransactionQueue() for nid in self.ids}
        self.committed: List[bytes] = []  # network commit order, once each
        self._seen = set()
        self.epoch = 0

    def push(self, node_id, tx: bytes) -> None:
        """Inject a transaction at one node (``Input::User`` analog)."""
        self.queues[node_id].extend([tx])

    def pending(self) -> int:
        return sum(len(q) for q in self.queues.values())

    def run_epoch(self, rng) -> List[bytes]:
        """One full epoch: sample proposals, run the batched HB epoch,
        commit new transactions exactly once, drop them from every queue.
        Returns the transactions newly committed this epoch."""
        contribs = {
            nid: _ser_txs(self.queues[nid].choose(rng, self.batch_size))
            for nid in self.ids
        }
        # per-epoch coin namespace (the object-mode analog: each epoch is a
        # fresh Subset under session_id + "/hb-epoch/" + epoch)
        batch, detail = self.hb.run(
            contribs, rng, encrypt=self.encrypt,
            session_suffix=struct.pack(">Q", self.epoch),
        )
        if self.cost_model is not None:
            self.virtual_time += self.cost_model.batched_epoch_estimate(
                self.hb.n, self.hb.f,
                int(detail["payload_bytes"]),  # ciphertext bytes on the wire
                int(detail["epochs"]),
            )
        new: List[bytes] = []
        epoch_txs: List[bytes] = []
        for nid in sorted(batch.keys(), key=repr):
            for tx in _de_txs(batch[nid]):
                epoch_txs.append(tx)
                if tx not in self._seen:
                    self._seen.add(tx)
                    new.append(tx)
        for q in self.queues.values():
            q.remove_multiple(epoch_txs)
        self.committed.extend(new)
        self.epoch += 1
        return new

    def run_to_empty(self, rng, max_epochs: int = 64,
                     on_epoch: Optional[Callable] = None) -> int:
        """Run epochs until every injected transaction committed; returns
        the epoch count.  ``on_epoch(epoch, new_txs)`` fires after each."""
        start = self.epoch
        while self.pending() > 0:
            if self.epoch - start >= max_epochs:
                raise RuntimeError("transactions not drained")
            new = self.run_epoch(rng)
            if on_epoch is not None:
                on_epoch(self.epoch, new)
        return self.epoch - start

"""Multi-epoch transaction queueing over the batched HoneyBadger epoch.

The array-mode counterpart of :mod:`hbbft_tpu.protocols.queueing_honey_badger`
(reference: ``src/queueing_honey_badger/`` + ``src/transaction_queue.rs``):
per-node transaction queues, a random ``batch_size`` sample proposed each
epoch (sampling keeps different nodes' proposals mostly disjoint), committed
transactions removed everywhere, leftovers re-proposed — with every epoch
executed as one :class:`~hbbft_tpu.parallel.acs.BatchedHoneyBadgerEpoch`
(TPKE encrypt → batched ACS → master-scalar decrypt) instead of an
object-mode message pump.  This is the scenario the reference's
``examples/simulation.rs`` benchmarks; ``examples/simulation.py --batched``
drives it.
"""

from __future__ import annotations

import struct
import threading
from typing import Callable, Dict, List, Optional

from hbbft_tpu.parallel.acs import BatchedHoneyBadgerEpoch
from hbbft_tpu.protocols.queueing_honey_badger import (
    TransactionQueue,
    _de_txs,
    _ser_txs,
)


def _commit_txs(pairs, seen, committed, queues, lock=None):
    """Shared ledger-commit step of the queueing drivers: dedup one epoch's
    (proposer, serialized-txs) pairs in deterministic proposer order, prune
    every queue with ONE drop set (the O(N²)-hash fix), record the network
    commit order.  Returns the newly committed transactions."""
    new: List[bytes] = []
    epoch_txs: List[bytes] = []
    for _nid, payload in sorted(pairs, key=lambda kv: repr(kv[0])):
        for tx in _de_txs(payload):
            epoch_txs.append(tx)
            if tx not in seen:
                seen.add(tx)
                new.append(tx)
    drop = frozenset(epoch_txs)
    import contextlib

    with lock if lock is not None else contextlib.nullcontext():
        for q in queues.values():
            q.remove_multiple(drop)
    committed.extend(new)
    return new


class BatchedQueueingHoneyBadger:
    """Epoch driver: queues + batched epochs until the ledger drains."""

    def __init__(self, netinfo_map: Dict, batch_size: int = 100,
                 session_id: bytes = b"batched-qhb", encrypt: bool = True,
                 cost_model=None, mesh=None):
        # mesh= threads straight through to the epoch driver: every epoch
        # this queue runs — RBC/ABA collectives and crypto ladders alike —
        # rides the one device mesh (see BatchedHoneyBadgerEpoch)
        self.hb = BatchedHoneyBadgerEpoch(
            netinfo_map, session_id=session_id, compact=True, mesh=mesh
        )
        self.ids = self.hb.ids
        self.batch_size = batch_size
        self.encrypt = encrypt
        self.cost_model = cost_model  # optional sim.CostModel → virtual clock
        self.virtual_time = 0.0
        self.queues = {nid: TransactionQueue() for nid in self.ids}
        # guards queue state: the pipelined driver samples on a worker
        # thread while _commit prunes on the main thread
        self._queue_lock = threading.Lock()
        self.committed: List[bytes] = []  # network commit order, once each
        self._seen = set()
        self.epoch = 0

    # -- pickling (snapshot/restore support) --------------------------------

    def __getstate__(self):
        d = self.__dict__.copy()
        d["_queue_lock"] = None  # locks don't pickle; recreated on restore
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        self._queue_lock = threading.Lock()

    def push(self, node_id, tx: bytes) -> None:
        """Inject a transaction at one node (``Input::User`` analog)."""
        with self._queue_lock:
            self.queues[node_id].extend([tx])

    def pending(self) -> int:
        return sum(len(q) for q in self.queues.values())

    def run_epoch(self, rng) -> List[bytes]:
        """One full epoch: sample proposals, run the batched HB epoch,
        commit new transactions exactly once, drop them from every queue.
        Returns the transactions newly committed this epoch."""
        contribs = {
            nid: _ser_txs(self.queues[nid].choose(rng, self.batch_size))
            for nid in self.ids
        }
        # per-epoch coin namespace (the object-mode analog: each epoch is a
        # fresh Subset under session_id + "/hb-epoch/" + epoch)
        batch, detail = self.hb.run(
            contribs, rng, encrypt=self.encrypt,
            session_suffix=struct.pack(">Q", self.epoch),
        )
        if self.cost_model is not None:
            self.virtual_time += self.cost_model.batched_epoch_estimate(
                self.hb.n, self.hb.f,
                int(detail["payload_bytes"]),  # ciphertext bytes on the wire
                int(detail["epochs"]),
            )
        return self._commit(batch)

    def run_to_empty(self, rng, max_epochs: int = 64,
                     on_epoch: Optional[Callable] = None) -> int:
        """Run epochs until every injected transaction committed; returns
        the epoch count.  ``on_epoch(epoch, new_txs)`` fires after each."""
        start = self.epoch
        while self.pending() > 0:
            if self.epoch - start >= max_epochs:
                raise RuntimeError("transactions not drained")
            new = self.run_epoch(rng)
            if on_epoch is not None:
                on_epoch(self.epoch, new)
        return self.epoch - start

    def _commit(self, batch) -> List[bytes]:
        """Dedup + queue-prune one epoch's agreed batch (host)."""
        new = _commit_txs(
            batch.items(), self._seen, self.committed, self.queues,
            lock=self._queue_lock,
        )
        self.epoch += 1
        return new

    def run_epochs_pipelined(self, rng, n_epochs: int,
                             on_epoch: Optional[Callable] = None) -> int:
        """Run ``n_epochs`` with epoch-axis overlap (SURVEY §2.3 PP row):
        epoch e+1's TPKE encryption runs on a worker thread (native
        oracle, GIL released — or the split device-MSM path, whose
        hash-to-G2 half is itself a GIL-released native batch call and
        whose ladder dispatches interleave with epoch e's on the device
        queue) while epoch e's ACS drives the device.

        Pipelining divergence, documented: epoch e+1's proposals are
        sampled BEFORE epoch e's commits prune the queues — the in-flight
        behavior the reference allows via ``max_future_epochs``; a
        transaction committed in e and re-proposed in e+1 commits once
        (dedup at the ledger), and random sampling makes such overlaps
        rare.  Returns the number of transactions newly committed."""
        import random as _random
        from concurrent.futures import ThreadPoolExecutor

        def sample_and_encrypt(seed):
            with self._queue_lock:
                contribs = {
                    nid: _ser_txs(self.queues[nid].choose(
                        _random.Random(seed ^ i), self.batch_size
                    ))
                    for i, nid in enumerate(self.ids)
                }
            return self.hb.encrypt_phase(
                contribs, _random.Random(seed), encrypt=self.encrypt
            )

        if n_epochs <= 0:
            return 0
        total_new = 0
        with ThreadPoolExecutor(max_workers=1) as pool:
            fut = pool.submit(sample_and_encrypt, rng.getrandbits(48))
            for e in range(n_epochs):
                payloads = fut.result()
                if e + 1 < n_epochs:
                    fut = pool.submit(sample_and_encrypt, rng.getrandbits(48))
                batch, detail = self.hb.run_from_payloads(
                    payloads, encrypt=self.encrypt,
                    session_suffix=struct.pack(">Q", self.epoch),
                )
                if self.cost_model is not None:
                    self.virtual_time += self.cost_model.batched_epoch_estimate(
                        self.hb.n, self.hb.f,
                        int(detail["payload_bytes"]),
                        int(detail["epochs"]),
                    )
                new = self._commit(batch)
                total_new += len(new)
                if on_epoch is not None:
                    on_epoch(self.epoch, new)
        return total_new


class BatchedQueueingDynamicHoneyBadger:
    """The reference's top-of-stack composition in array mode:
    ``QueueingHoneyBadger`` wraps ``DynamicHoneyBadger`` (reference:
    ``src/queueing_honey_badger/`` over ``src/dynamic_honey_badger/``), so
    transaction queueing and on-line membership changes run TOGETHER.  Here
    the per-node queues feed a :class:`~hbbft_tpu.parallel.dhb.
    BatchedDynamicHoneyBadger`: each epoch samples ``batch_size``
    transactions per validator, runs them (plus pending votes and DKG
    messages) through one batched HoneyBadger epoch, commits new
    transactions exactly once, and prunes every queue.  Era rotations are
    transparent to the ledger: queues persist across eras, a removed
    validator simply stops proposing, an added one starts.
    """

    def __init__(self, netinfo_map: Dict, batch_size: int = 100,
                 session_id: bytes = b"batched-qdhb", rng=None,
                 cost_model=None):
        from hbbft_tpu.parallel.dhb import BatchedDynamicHoneyBadger

        self.dhb = BatchedDynamicHoneyBadger(
            netinfo_map, session_id=session_id, rng=rng
        )
        self.batch_size = batch_size
        self.queues = {nid: TransactionQueue() for nid in self.dhb.validators}
        self.committed: List[bytes] = []
        self._seen = set()
        self.cost_model = cost_model  # optional sim.CostModel → virtual clock
        self.virtual_time = 0.0

    # -- transaction + vote inputs ------------------------------------------

    def push(self, node_id, tx: bytes) -> None:
        self.queues.setdefault(node_id, TransactionQueue()).extend([tx])

    def pending(self) -> int:
        return sum(
            len(self.queues.get(nid, ())) for nid in self.dhb.validators
        )

    def vote_to_add(self, voter, node_id, pub_key, secret_key=None) -> None:
        self.dhb.vote_to_add(voter, node_id, pub_key, secret_key=secret_key)

    def vote_to_remove(self, voter, node_id) -> None:
        self.dhb.vote_to_remove(voter, node_id)

    def vote_for_encryption_schedule(self, voter, schedule) -> None:
        self.dhb.vote_for_encryption_schedule(voter, schedule)

    # -- the epoch loop ------------------------------------------------------

    def run_epoch(self, rng) -> List[bytes]:
        """Sample proposals from the CURRENT validator set's queues, run one
        dynamic epoch (votes/DKG ride along), commit + prune.  Returns the
        newly committed transactions."""
        contribs = {}
        for nid in self.dhb.validators:
            q = self.queues.setdefault(nid, TransactionQueue())
            contribs[nid] = _ser_txs(q.choose(rng, self.batch_size))
        batch = self.dhb.run_epoch(contribs, rng)
        if self.cost_model is not None:
            d = self.dhb.last_detail  # n/f of the era that ran the epoch
            self.virtual_time += self.cost_model.batched_epoch_estimate(
                d["n"], d["f"], d["payload_bytes"], d["epochs"],
            )
        return _commit_txs(
            batch.contributions, self._seen, self.committed, self.queues,
        )

    def run_to_empty(self, rng, max_epochs: int = 64) -> int:
        """Epochs until every transaction in a CURRENT validator's queue
        committed (queues of non-validators don't count — a removed node
        cannot propose)."""
        epochs = 0
        while self.pending() > 0:
            if epochs >= max_epochs:
                raise RuntimeError("transactions not drained")
            self.run_epoch(rng)
            epochs += 1
        return epochs

"""Batched binary agreement as dense array epochs.

Reference semantics: ``src/binary_agreement/`` (object-mode mirror:
:mod:`hbbft_tpu.protocols.binary_agreement`).  One *epoch* of ALL N nodes ×
P instances executes as a single jitted array program under the
bulk-synchronous model (every message of a sub-round delivered in one step,
adversarial drops as masks):

- SBV: BVal one-hots over (node, instance, value) with the f+1 relay and
  2f+1 bin_values rules iterated to fixpoint (monotone; n rounds cover the
  longest relay chains partial delivery masks can build);
- Aux support counted over senders whose value landed in the receiver's
  bin_values; Conf as a 2-bit set with the ⊆-bin_values filter;
- the Moumen coin schedule (epochs 0, 1 mod 3 fixed true/false; every third
  a threshold coin).  The random coin value is an INPUT to the jitted epoch
  (`coin_bits`): in simulation it is produced once per (instance, epoch) by
  combining t+1 real signature shares on the host/native oracle — the
  per-node share-verify redundancy of a real deployment is accounted by the
  cost model, not re-executed N times (SURVEY §5's cost-model hook);
- the MMR decision rule and Term seeding: deciders participate in later
  epochs through their recorded Terms, exactly like object-mode
  ``_next_epoch``.

Aux-choice semantics (round 5): object mode sends Aux for the value whose
2f+1-th BVal arrives FIRST.  The bulk-sync step models the round
structure exactly: with ``o_i(v)`` = the round node i first sends BVal(v)
(−1 for the initial estimate; relays loop back to the sender INSTANTLY,
as ``_broadcast_sbv`` does), node j's observed count in round t is
``c_j(t) = |{i≠j : o_i < t}| + [o_j ≤ t]`` — everyone else's sends arrive
one round later, its own immediately.  Relay fires at f+1 within the
round, crossing (bin_values entry) at 2f+1; the Aux choice is the value
with the earlier PER-NODE crossing round, same-round tie → True.  Under
round-aligned delivery with True-before-False tie order — the schedule
class ``tests/test_aba_cross_mode.py`` pins down — the two modes agree
verdict-for-verdict; under arbitrary masks any first-crossing choice is
protocol-valid (agreement/validity/termination hold; invariant suite).
"""

from __future__ import annotations

import hashlib
import struct
from typing import Dict, Optional

import numpy as np


# Round-model horizons, shared by the single-device and mesh step variants
# (their bit-equality is test-pinned — the constants must match pairwise).
# Full delivery reaches the fixpoint in ≤ 2 spread rounds (4 = margin);
# masked relay chains can be ~n hops.
SBV_ROUNDS_FULL = 4
SBV_INF_FULL = 9


def sbv_rounds_masked(n: int) -> int:
    return n + 2


def sbv_inf_masked(n: int) -> int:
    return n + 4


def sbv_round_model(sent, f: int, n_rounds: int, count_fn, inf):
    """The per-node BVal round model (module doc), shared by every step
    variant (masked/full × single-device/mesh — bit-equality across them is
    test-pinned, so the model lives exactly once).

    ``sent``: bool (..., 2) initial senders; ``count_fn(early) -> E`` is the
    caller's neighbor reduction (masked einsum / global sum / psum /
    gather+einsum), returning each node's view of |{i : o_i < t}| INCLUDING
    its own row — roundstep subtracts the own-row indicator and adds the
    instant-self term ``[o_j ≤ t]``.  Returns ``(o, x)``: first-send and
    per-node crossing rounds (``inf`` = never).
    """
    import jax
    import jax.numpy as jnp

    o0 = jnp.where(sent, jnp.int32(-1), inf)
    x0 = jnp.full_like(o0, inf)

    def roundstep(t, carry):
        o, x = carry
        t = t.astype(jnp.int32)
        early = (o < t).astype(jnp.int32)
        E = count_fn(early)
        c0 = E - early + (o <= t)
        o = jnp.where((c0 >= (f + 1)) & (o == inf), t, o)
        # a round-t relay changes only [o_j ≤ t] (o=t is not < t), so E
        # and early are unchanged
        c1 = E - early + (o <= t)
        x = jnp.where((c1 >= (2 * f + 1)) & (x == inf), t, x)
        return o, x

    return jax.lax.fori_loop(0, n_rounds, roundstep, (o0, x0))


def aux_pref_from_crossings(x, inf):
    """(bin_vals_per_node, pref_true) from crossing rounds: the earlier-
    crossing value wins the Aux choice, same-round tie → True."""
    binv = x < inf
    pref_true = binv[..., 1] & (
        ~binv[..., 0] | (x[..., 1] <= x[..., 0])
    )
    return binv, pref_true


class BatchedAba:
    """Batched ABA epochs for an (n, f) network, P instances.

    Multi-chip: :func:`hbbft_tpu.parallel.mesh.make_sharded_aba_step`
    wraps :meth:`epoch_step` with the node-state rows sharded over a
    device mesh (bit-equal — tier-1 asserts it); the coin helpers below
    stay replicated — one ``bls_coin_batch`` native call per random
    epoch covers the whole instance axis and is noise next to the
    sharded exchanges, so there is nothing to shard in them.
    """

    def __init__(self, n: int, f: int):
        self.n = n
        self.f = f

    def init_state(self, est):
        """est: bool (N, P) initial estimates (input of every node/instance).

        Returns the dense state dict: ``est``, ``decided``, ``decision``
        (bool (N, P); deciders participate in later epochs through their
        decision, the Term analogue) and ``epoch`` (scalar int32).
        """
        import jax.numpy as jnp

        est = jnp.asarray(est, dtype=bool)
        z = jnp.zeros(est.shape, dtype=bool)
        return {
            "est": est,
            "decided": z,
            "decision": z,
            "epoch": jnp.zeros((), dtype=jnp.int32),
        }

    def epoch_step(self, state, coin_bits, bval_mask=None, aux_mask=None,
                   conf_mask=None):
        """One bulk-synchronous ABA epoch for all (node, instance).

        coin_bits: bool (P,) — the threshold-coin value per instance for
        this epoch (ignored on fixed-schedule epochs).
        Masks: bool (N_src, N_dst, P) deliveries (default all-delivered).
        Returns the next state.
        """
        import jax.numpy as jnp

        n, f = self.n, self.f
        est = state["est"]
        decided = state["decided"]
        decision = state["decision"]
        P = est.shape[1]

        if bval_mask is None and aux_mask is None and conf_mask is None:
            # full-delivery fast path: counts are receiver-independent, so
            # nothing of shape (N, N, P) is materialized — O(N·P) per epoch,
            # which is what makes N ≥ 1024 instances × nodes feasible
            return self._epoch_step_full_delivery(state, coin_bits)

        if bval_mask is None:
            bval_mask = jnp.ones((n, n, P), dtype=bool)
        if aux_mask is None:
            aux_mask = jnp.ones((n, n, P), dtype=bool)
        if conf_mask is None:
            conf_mask = jnp.ones((n, n, P), dtype=bool)
        eye = jnp.eye(n, dtype=bool)[:, :, None]
        bval_mask = bval_mask | eye
        aux_mask = aux_mask | eye
        conf_mask = conf_mask | eye

        # -- SBV: BVal one-hots (N, P, 2); deciders vote their Term --------
        active = ~decided
        val_axis = jnp.stack([~est, est], axis=-1)  # [..., v] = est == v
        term_axis = jnp.stack([~decision, decision], axis=-1)
        sent = jnp.where(decided[..., None], term_axis, val_axis)

        # masked round model: counts c_j(t) = Σ_{i≠j} mask[i,j]·[o_i<t] +
        # [o_j ≤ t] (own sends loop back instantly); relay chains can be up
        # to ~n hops long under partial delivery masks (same reason rbc.py
        # iterates its Ready amplification n times)
        INF = jnp.int32(sbv_inf_masked(n))
        maski = bval_mask.astype(jnp.int32)
        o, x = sbv_round_model(
            sent, f, sbv_rounds_masked(n),
            lambda early: jnp.einsum("ipv,ijp->jpv", early, maski),
            INF,
        )
        bin_vals, pref_true = aux_pref_from_crossings(x, INF)  # (N, P, 2)

        # -- Aux: earlier-crossing bin_value (tie → True); deciders send
        # their Term value
        has_any = bin_vals.any(axis=-1)
        aux_val = jnp.where(decided, decision, pref_true)
        aux_sent = has_any | decided
        # support at receiver j: senders i whose aux value ∈ bin_vals[j]
        aux_v = jnp.stack([~aux_val, aux_val], axis=-1) & aux_sent[..., None]
        deliv = aux_mask  # (i, j, p)
        # sender i's aux value v counts at j iff bin_vals[j, p, v]
        support = jnp.einsum(
            "ipv,ijp,jpv->jp", aux_v.astype(jnp.int32),
            deliv.astype(jnp.int32), bin_vals.astype(jnp.int32),
        )
        # senders (not sender×value) — aux is a single value per sender, so
        # the einsum over v counts each supporting sender once
        vals = bin_vals & (
            jnp.einsum(
                "ipv,ijp->jpv", aux_v.astype(jnp.int32),
                deliv.astype(jnp.int32),
            )
            > 0
        )
        sbv_done = support >= (n - f)

        # -- Conf: 2-bit sets; count confs ⊆ receiver's bin_vals ----------
        conf = jnp.where(
            decided[..., None],
            term_axis,
            vals,
        )  # (N, P, 2) sender's conf set
        # subset test: conf_i ⊆ bin_j  ⟺  conf_i & ~bin_j empty
        viol = jnp.einsum(
            "ipv,jpv->ijp", conf.astype(jnp.int32),
            (~bin_vals).astype(jnp.int32),
        )
        sent_conf = sbv_done | decided
        conf_count = (
            (viol == 0) & conf_mask & sent_conf[:, None, :]
        ).sum(axis=0)
        conf_done = conf_count >= (n - f)

        # -- coin ----------------------------------------------------------
        m = state["epoch"] % 3
        coin = jnp.where(
            m == 0,
            jnp.ones((P,), dtype=bool),
            jnp.where(m == 1, jnp.zeros((P,), dtype=bool), coin_bits),
        )  # (P,)
        coin_b = jnp.broadcast_to(coin[None, :], est.shape)

        # -- MMR decision rule (only where conf_done & active) -------------
        only_true = vals[..., 1] & ~vals[..., 0]
        only_false = vals[..., 0] & ~vals[..., 1]
        both = vals[..., 0] & vals[..., 1]
        vals_single = only_true | only_false
        vals_val = only_true  # the singleton's value (valid when single)
        ready = conf_done & sbv_done & active
        # Decision guard for the LOSSY lockstep model: MMR's safety rests
        # on every correct node completing every epoch (true in the async
        # model with reliable channels — a node waits inside the epoch
        # until its thresholds are met).  The lockstep step instead lets a
        # mask-starved node SKIP the epoch with est unchanged, so a lone
        # decider could strand against nodes that never saw its quorum.
        # Gating decisions on all-active-nodes-completed restores safety
        # (a documented god-view over-approximation; full-delivery and
        # round-aligned schedules are unaffected — there the predicate is
        # implied).  Termination still follows once delivery recovers.
        all_complete = ((conf_done & sbv_done) | ~active).all(axis=0)  # (P,)
        decide_now = (
            ready & vals_single & (vals_val == coin_b) & all_complete[None]
        )
        new_est = jnp.where(
            vals_single, vals_val, coin_b
        )  # singleton carries; BOTH adopts coin
        est = jnp.where(ready, new_est, est)
        decision = jnp.where(decide_now, coin_b, decision)
        decided = decided | decide_now

        # f+1 Terms rule: laggards adopt a value with f+1 deciders
        for v in (False, True):
            term_cnt = (decided & (decision == v)).sum(axis=0)  # (P,)
            adopt = active & (term_cnt >= (f + 1))[None, :] & ~decided
            decision = jnp.where(adopt, v, decision)
            decided = decided | adopt

        return {
            "est": est,
            "decided": decided,
            "decision": decision,
            "epoch": state["epoch"] + 1,
        }

    def _epoch_step_full_delivery(self, state, coin_bits):
        """Masks-free epoch: every count is the same at every receiver."""
        import jax
        import jax.numpy as jnp

        n, f = self.n, self.f
        est = state["est"]
        decided = state["decided"]
        decision = state["decision"]
        P = est.shape[1]

        active = ~decided
        val_axis = jnp.stack([~est, est], axis=-1)
        term_axis = jnp.stack([~decision, decision], axis=-1)
        sent = jnp.where(decided[..., None], term_axis, val_axis)  # (N,P,2)

        # full-delivery round model: the neighbor count is one global sum
        INF = jnp.int32(SBV_INF_FULL)
        o, x = sbv_round_model(
            sent, f, SBV_ROUNDS_FULL,
            lambda early: early.sum(axis=0)[None], INF,
        )
        binv_j, pref_true = aux_pref_from_crossings(x, INF)  # (N, P, 2)
        bin_vals = binv_j.any(axis=0)  # (P, 2) — same set at fixpoint
        aux_val = jnp.where(decided, decision, pref_true)
        aux_sent = bin_vals.any(axis=-1)[None] | decided
        aux_v = jnp.stack([~aux_val, aux_val], axis=-1) & aux_sent[..., None]
        support = (aux_v & bin_vals[None]).any(axis=-1).sum(axis=0)  # (P,)
        vals = bin_vals & (aux_v.sum(axis=0) > 0)  # (P, 2), shared
        sbv_done = support >= (n - f)  # (P,)

        conf = jnp.where(decided[..., None], term_axis, vals[None])
        viol = (conf & ~bin_vals[None]).any(axis=-1)  # (N, P)
        sent_conf = sbv_done[None] | decided
        conf_count = (sent_conf & ~viol).sum(axis=0)  # (P,)
        conf_done = conf_count >= (n - f)

        m = state["epoch"] % 3
        coin = jnp.where(
            m == 0,
            jnp.ones((P,), dtype=bool),
            jnp.where(m == 1, jnp.zeros((P,), dtype=bool), coin_bits),
        )

        only_true = vals[:, 1] & ~vals[:, 0]
        only_false = vals[:, 0] & ~vals[:, 1]
        vals_single = only_true | only_false
        vals_val = only_true
        ready = (conf_done & sbv_done)[None] & active
        decide_now = ready & (vals_single & (vals_val == coin))[None]
        new_est = jnp.where(vals_single, vals_val, coin)[None]
        est = jnp.where(ready, jnp.broadcast_to(new_est, est.shape), est)
        coin_b = jnp.broadcast_to(coin[None], est.shape)
        decision = jnp.where(decide_now, coin_b, decision)
        decided = decided | decide_now

        for v in (False, True):
            term_cnt = (decided & (decision == v)).sum(axis=0)
            adopt = active & (term_cnt >= (f + 1))[None] & ~decided
            decision = jnp.where(adopt, v, decision)
            decided = decided | adopt

        return {
            "est": est,
            "decided": decided,
            "decision": decision,
            "epoch": state["epoch"] + 1,
        }


def _coin_nonce(session_id: bytes, proposer_id, epoch: int) -> bytes:
    return (
        b"HBBFT-ABA-COIN"
        + struct.pack(">I", len(session_id))
        + session_id
        + repr(proposer_id).encode()
        + struct.pack(">Q", epoch)
    )


def coins_for_epoch(netinfo_map, session_id: bytes, proposer_ids,
                    epoch: int) -> list:
    """``coin_for`` over a whole instance axis in ONE native call.

    Bit-identical to per-instance :func:`coin_for` (same nonces, same
    master-scalar fold); the native ``bls_coin_batch`` runs every
    hash-to-G2 + GLS scalar-mul + parity in C with the GIL released —
    the per-epoch host hop the round-4 verdict flagged in the ACS loop.
    """
    from hbbft_tpu.crypto import bls12_381 as c

    nonces = [_coin_nonce(session_id, p, epoch) for p in proposer_ids]
    master = _master_scalar(netinfo_map)
    nat = c._native()
    if nat is not None:
        return nat.bls_coin_batch(master, nonces)
    from hbbft_tpu.crypto import tc

    return [
        tc.Signature(c.g2_mul(c.hash_g2(n), master)).parity() for n in nonces
    ]


def coin_for(netinfo_map, session_id: bytes, proposer_id, epoch: int) -> bool:
    """The threshold-coin value for (instance, epoch).

    God-view shortcut (same class as the simulator's once-per-proposer
    decryption): the combined signature equals H(nonce)^{f(0)} — Lagrange
    in the exponent — so the master scalar f(0) = Σ λ_i·x_i is
    interpolated once from t+1 secret shares (cheap mod-r arithmetic) and
    ONE G2 scalar-mul replaces the t+1 share signs + combine.  The result
    is bit-identical to ``PublicKeySet.combine_signatures`` over any t+1
    valid shares (interpolation uniqueness); the N-redundant share
    exchange/verification of a real deployment is the cost model's
    business."""
    from hbbft_tpu.crypto import bls12_381 as c
    from hbbft_tpu.crypto import tc

    nonce = _coin_nonce(session_id, proposer_id, epoch)
    master = _master_scalar(netinfo_map)
    return tc.Signature(c.g2_mul(c.hash_g2(nonce), master)).parity()


# id(pks) → (pks, master).  The strong reference to the PublicKeySet keeps
# its id from being recycled while the entry lives (an id()-keyed cache
# without it could serve another network's secret after GC address reuse);
# bounded so long-running multi-network processes don't grow it forever.
_MASTER_CACHE: Dict[int, tuple] = {}
_MASTER_CACHE_MAX = 64


def _master_scalar(netinfo_map) -> int:
    """f(0) interpolated from t+1 secret shares; cached per PublicKeySet
    (the O(t²) Lagrange-coefficient computation would otherwise repeat for
    every one of the N coin instances)."""
    from hbbft_tpu.crypto import tc

    pks = next(iter(netinfo_map.values())).public_key_set()
    hit = _MASTER_CACHE.get(id(pks))
    if hit is not None and hit[0] is pks:
        return hit[1]
    t = pks.threshold()
    ids = sorted(netinfo_map.keys(), key=repr)
    master = tc.master_secret_from_shares(
        (
            netinfo_map[nid].node_index(nid),
            netinfo_map[nid].secret_key_share().scalar,
        )
        for nid in ids[: t + 1]
    )
    if len(_MASTER_CACHE) >= _MASTER_CACHE_MAX:
        _MASTER_CACHE.clear()
    _MASTER_CACHE[id(pks)] = (pks, master)
    return master

"""Batched Asynchronous Common Subset and a full HoneyBadger epoch.

Composition of the dense-array protocol rounds (SURVEY §7 step 5):

    RBC round (parallel.rbc)  →  N×N delivered mask + values
    ABA epochs (parallel.aba) →  accepted instance set, identical at every
                                 correct node
    threshold decrypt         →  contributions → the epoch Batch

Reference semantics: ``src/subset/`` + ``src/honey_badger/`` (object-mode
mirrors: protocols/subset.py, protocols/honey_badger.py).  Bulk-synchronous
divergences, documented: ABA inputs are fixed at the RBC outcome (there is
no "slow RBC" in a synchronous round, so Subset's input-false-after-N−f
rule degenerates to inputting the delivered mask), and threshold decryption
is combined once per accepted proposer on the host oracle — the N-per-node
share redundancy of a real deployment is the cost model's business, not
re-executed N times.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from hbbft_tpu.parallel.aba import BatchedAba, coin_for, coins_for_epoch
from hbbft_tpu.parallel.rbc import BatchedRbc, frame_values, unframe_value


class BatchedAcs:
    """One ACS instance over an (n, f) network: N proposers, N receivers."""

    def __init__(self, n: int, f: int, mesh=None):
        self.n = n
        self.f = f
        self.mesh = mesh
        self.rbc = BatchedRbc(n, f)
        self.aba = BatchedAba(n, f)
        self._build_runners()

    def __getstate__(self):
        """Snapshot support: jit handles rebuild on restore.

        Mesh-sharded instances refuse to pickle — a ``Mesh`` is bound to
        live devices of THIS process, so a pickled one could never restore
        elsewhere.  The supported path is reconstruct-from-unsharded:
        snapshot a ``mesh=None`` driver (state-sync snapshots already do —
        ``net/statesync.py`` ships protocol state, never device placement),
        then build a fresh ``BatchedAcs(n, f, mesh=mesh)`` /
        ``BatchedHoneyBadgerEpoch(..., mesh=mesh)`` on the restoring host
        and replay into it; results are bit-identical to the sharded
        original (tests/test_parallel_mesh.py asserts mesh/single
        equality), so nothing is lost by snapshotting unsharded."""
        if self.mesh is not None:
            raise TypeError(
                "cannot snapshot a mesh-sharded BatchedAcs; snapshot the "
                "mesh=None driver and reconstruct the sharded one from it"
            )
        d = self.__dict__.copy()
        d.pop("_rbc_run", None)
        d.pop("_aba_step", None)
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        self._build_runners()

    def _build_runners(self):
        # jit once per instance — a fresh jax.jit per run() call would
        # recompile the whole pipeline every epoch
        import jax

        mesh, n = self.mesh, self.n

        if mesh is not None:
            # the whole epoch rides the device mesh: RBC fan-out and ABA
            # exchanges become ICI/DCN collectives (SURVEY §2.3 comm backend)
            from hbbft_tpu.parallel.mesh import (
                make_sharded_aba_step,
                make_sharded_rbc_large_run,
                make_sharded_rbc_run,
            )

            assert n % mesh.devices.size == 0, (n, mesh.devices.size)
            if self.rbc.large:
                # N > 256: shard the full-delivery scale path's proposer
                # axis (round-5; nothing in the flagship config is
                # single-chip by construction anymore).  Masked adversarial
                # runs at this scale fall back to the unsharded masked
                # path, whose O(N³) mask tensors callers already bound.
                large_run = make_sharded_rbc_large_run(self.rbc, mesh)

                # explicit signature (mirrors BatchedRbc.run) so unknown
                # kwargs raise like every other path instead of being
                # silently dropped
                def rbc_run(data, value_mask=None, echo_mask=None,
                            ready_mask=None, codeword_tamper=None,
                            value_tamper=None, receivers=None):
                    if any(m is not None for m in
                           (value_mask, echo_mask, ready_mask, receivers)):
                        return self.rbc.run(
                            data, value_mask=value_mask,
                            echo_mask=echo_mask, ready_mask=ready_mask,
                            codeword_tamper=codeword_tamper,
                            value_tamper=value_tamper, receivers=receivers,
                        )
                    return large_run(
                        data, codeword_tamper=codeword_tamper,
                        value_tamper=value_tamper,
                    )

                self._rbc_run = rbc_run
            else:
                self._rbc_run = make_sharded_rbc_run(self.rbc, mesh)
            self._aba_step = make_sharded_aba_step(self.aba, mesh)
        else:
            # the large-N RBC path orchestrates host steps internally and
            # must not be wrapped in jit
            self._rbc_run = (
                self.rbc.run if self.rbc.large else jax.jit(self.rbc.run)
            )
            self._aba_step = jax.jit(self.aba.epoch_step)


    def run(
        self,
        values: Sequence[bytes],
        coin_fn=None,
        max_epochs: int = 24,
        compact: bool = False,
        coin_batch_fn=None,
        **rbc_kwargs,
    ):
        """values[p] = proposer p's contribution.  Returns a dict with
        ``accepted`` bool (N, P) (identical rows for correct nodes),
        ``data`` (N, P, k, B), ``delivered`` (N, P), ``epochs`` int.

        coin_fn(p, epoch) -> bool supplies the threshold-coin values for
        the random epochs (default: a deterministic hash — fine for tests;
        the simulator passes `aba.coin_for` over real key shares).
        coin_batch_fn(epoch) -> length-N bool sequence, preferred when set:
        one call covers the whole instance axis (the native
        ``bls_coin_batch`` path) instead of N per-instance host hops.

        ``compact=True`` returns only what an epoch driver needs —
        ``accepted_row`` (P,), ``accepted_agree``/``delivered_ok`` flags,
        and per-instance ``data_sel`` (P, k, B) from a delivering receiver.
        The (N, P) decision array reduces on device (its ~16 MB at N=4096
        would otherwise cross the bandwidth-limited link); delivered/data
        are host arrays already on the large-N RBC path, so the rest
        reduces in numpy.  Compact mode requires a data row per receiver
        and refuses ``receivers=``-bounded RBC calls.
        """
        import jax
        import jax.numpy as jnp

        n = self.n
        if self.rbc.large and not any(
            rbc_kwargs.get(m) is not None
            for m in ("value_mask", "echo_mask", "ready_mask", "receivers")
        ):
            # large-N scale path: cross the link compact (payload bytes,
            # not the ~87 %-zero (P, k, B) frame) and expand on device
            data = self.rbc.upload_framed(list(values))
        else:
            data = jnp.asarray(frame_values(list(values), self.rbc.k))
        out = self._rbc_run(data, **rbc_kwargs)
        delivered = out["delivered"]  # (N, P)

        if coin_fn is None:
            import hashlib

            def coin_fn(p, e):
                h = hashlib.sha3_256(b"acs-coin%d-%d" % (p, e)).digest()
                return bool(h[0] & 1)

        # the large-N path returns ``delivered`` as a host broadcast view
        # (identical rows); upload ONE row and re-broadcast on device
        # instead of shipping the materialized (N, P) matrix
        est_in = delivered
        if isinstance(delivered, np.ndarray) and delivered.strides[0] == 0:
            est_in = jnp.broadcast_to(
                jnp.asarray(np.ascontiguousarray(delivered[0])),
                delivered.shape,
            )
        st = self.aba.init_state(est_in)
        step = self._aba_step
        epochs = 0
        # reduce on device, fetch ONE scalar — np.asarray(st["decided"])
        # would ship the whole (N, P) matrix (16 MB at N=4096) every epoch
        while not bool(np.asarray(jnp.all(st["decided"]))):
            if epochs >= max_epochs:
                raise RuntimeError("ABA did not terminate")
            if epochs % 3 == 2:  # only the random epochs consult the coin
                if coin_batch_fn is not None:
                    bits = coin_batch_fn(epochs)
                else:
                    bits = [coin_fn(p, epochs) for p in range(n)]
                coins = jnp.asarray(np.array(bits, dtype=bool))
            else:
                coins = jnp.zeros((n,), dtype=bool)
            st = step(st, coins)
            epochs += 1

        if compact:
            if "receivers" in rbc_kwargs:
                raise ValueError(
                    "compact mode needs a data row per receiver; it cannot "
                    "be combined with a receivers=-bounded RBC call"
                )
            decision = st["decision"]
            # the (N, P) decision array stays on device: only its first row
            # and the agreement scalar cross the link (the large-N RBC path
            # already returns delivered/data as host arrays, so everything
            # else reduces in numpy for free)
            row = np.asarray(decision[0])
            agree = bool(np.asarray(
                (decision == decision[0][None, :]).all()
            ))
            delivered_np = np.asarray(delivered)
            any_deliv = delivered_np.any(axis=0)
            delivered_ok = bool((~row | any_deliv).all())
            src = delivered_np.argmax(axis=0)      # first delivering node
            recv = np.asarray(out["data_receivers"])
            inv = np.zeros(n, dtype=np.int32)
            inv[recv] = np.arange(len(recv), dtype=np.int32)
            data_np = np.asarray(out["data"])
            data_sel = data_np[inv[src], np.arange(len(src))]
            return {
                "accepted_row": row,
                "accepted_agree": agree,
                "delivered_ok": delivered_ok,
                "data_sel": data_sel,
                "epochs": epochs,
            }

        return {
            "accepted": np.asarray(st["decision"]),
            "delivered": np.asarray(delivered),
            "data": np.asarray(out["data"]),
            "data_receivers": np.asarray(out["data_receivers"]),
            "rbc_fault": np.asarray(out["fault"]),
            "epochs": epochs,
        }


class BatchedHoneyBadgerEpoch:
    """One full HoneyBadger epoch in array mode.

    Encrypt (host TPKE, per proposer) → batched ACS over the ciphertext
    bytes → decrypt accepted contributions (host oracle combine, once per
    proposer) → per-node Batch.  Cross-checked against the object-mode
    ``HoneyBadger`` in tests.
    """

    def __init__(self, netinfo_map: Dict, session_id: bytes = b"batched-hb",
                 mesh=None, compact: bool = False):
        ids = sorted(netinfo_map.keys(), key=repr)
        self.ids = ids
        self.netinfo_map = netinfo_map
        info0 = netinfo_map[ids[0]]
        self.n = info0.num_nodes()
        self.f = info0.num_faulty()
        self.session_id = session_id
        # compact: device-side ACS result reduction (see BatchedAcs.run) —
        # the epoch drivers at scale enable it; the default keeps the full
        # detail arrays that cross-mode equality tests compare
        self.compact = compact
        # ONE mesh threads the whole epoch: the protocol rounds (BatchedAcs
        # → sharded RBC/ABA below) and the crypto ladders (the sharded
        # verify/decrypt makers pin crypto.batch.cache_for(mesh), and
        # encrypt_phase scopes crypto.batch.routed_mesh(mesh) around its
        # backend routing) all see the same object — use_mesh and the
        # epoch driver's mesh= used to be set independently and could
        # disagree.
        self.mesh = mesh
        self.acs = BatchedAcs(self.n, self.f, mesh=mesh)
        if mesh is not None:
            from hbbft_tpu.parallel.mesh import (
                make_sharded_coin_verify,
                make_sharded_decrypt,
            )

            # mesh-routed share verification for callers that check coin
            # shares around this epoch (bench/verification flows) — the
            # god-view epoch itself derives coins from the master scalar
            self.coin_verify = make_sharded_coin_verify(mesh)
            self._check_decrypt = make_sharded_decrypt(mesh)
        else:
            from hbbft_tpu.crypto.batch import (
                batch_tpke_check_decrypt,
                batch_verify_sig_shares,
            )

            self.coin_verify = batch_verify_sig_shares
            self._check_decrypt = batch_tpke_check_decrypt

    def encrypt_phase(self, contributions: Dict, rng,
                      encrypt: bool = True) -> List[bytes]:
        """The host-side TPKE encrypt of every proposer's contribution.

        Split out so epoch pipelines can run it for epoch e+1 (host/native
        work, GIL released inside the C oracle) while epoch e's ACS drives
        the device — the §2.3 epoch-axis (PP) overlap.  Returns the
        per-proposer payload list for :meth:`run_from_payloads` (ciphertext
        bytes when encrypting; accepted payloads are re-parsed at decrypt
        time, so nothing else needs the Ciphertext objects).

        All N proposers encrypt in ONE ``tc.tpke_encrypt_batch`` call —
        the round-4 24 s serial loop at N=4096 collapses to the per-item
        ψ/GLS cost.  The backend routes by measured roofline (see
        crypto/batch.py): one native C call (endomorphism fast paths +
        amortized fixed-base tables + a single GIL release), or the SPLIT
        device path — all proposers' G1/G2 ladders as device MSM
        dispatches chunk-pipelined against the native hash-to-G2 batch —
        when a mesh is attached; HBBFT_ENCRYPT_BACKEND overrides.  The
        roofline consults THIS epoch's mesh: the routing runs inside
        ``crypto.batch.routed_mesh(self.mesh)``, so the device path's
        row-sharding and the ACS sharding ride one mesh."""
        from hbbft_tpu.crypto import batch as _cb
        from hbbft_tpu.crypto import tc

        contribs = [contributions.get(nid, b"") for nid in self.ids]
        if not encrypt:
            return contribs
        pk = self.netinfo_map[self.ids[0]].public_key_set().public_key()
        with _cb.routed_mesh(self.mesh):
            return [
                ct.to_bytes()
                for ct in tc.tpke_encrypt_batch(pk, contribs, rng)
            ]

    def run(self, contributions: Dict, rng, encrypt: bool = True,
            session_suffix: bytes = b"", **rbc_kwargs):
        """contributions: {node_id: bytes}.  Returns (batch, detail): the
        agreed {node_id: contribution} map plus the ACS detail arrays.

        ``session_suffix`` namespaces the coin nonces of this run — callers
        executing several epochs with one instance (e.g. the batched QHB
        driver) pass a per-epoch suffix, mirroring the object-mode
        HoneyBadger's ``session_id + "/hb-epoch/" + epoch`` subset naming,
        so coin values never repeat across epochs.  Host-side only: no
        recompilation."""
        payloads = self.encrypt_phase(contributions, rng, encrypt)
        return self.run_from_payloads(
            payloads, encrypt=encrypt,
            session_suffix=session_suffix, **rbc_kwargs,
        )

    def run_from_payloads(self, payloads, encrypt: bool = True,
                          session_suffix: bytes = b"", timer=None,
                          **rbc_kwargs):
        """ACS + threshold-decrypt over pre-encrypted payloads (see
        :meth:`encrypt_phase`).

        ``timer``: optional zero-arg clock (e.g. ``time.perf_counter``)
        injected by benches for per-phase attribution — when set, the
        detail dict gains ``phase_s = {"acs": ..., "decrypt": ...}``.
        Injected rather than read here so this module stays clock-free
        (hblint determinism scope)."""
        info0 = self.netinfo_map[self.ids[0]]
        pks = info0.public_key_set()
        session = self.session_id + session_suffix

        def coin_fn(p, e):
            return coin_for(self.netinfo_map, session, self.ids[p], e)

        def coin_batch_fn(e):
            return coins_for_epoch(self.netinfo_map, session, self.ids, e)

        t0 = timer() if timer is not None else None
        out = self.acs.run(
            payloads, coin_fn=coin_fn, coin_batch_fn=coin_batch_fn,
            compact=self.compact, **rbc_kwargs
        )
        if timer is not None:
            out["phase_s"] = {"acs": timer() - t0}
            t0 = timer()
        # what the RBC actually broadcast (ciphertext bytes when encrypting)
        # — cost models need this, not the plaintext length
        out["payload_bytes"] = max((len(p) for p in payloads), default=0)
        batch: Dict = {}
        t = pks.threshold()
        pending: List[Tuple] = []  # (nid, payload)
        # each mode provides framed(p): the framed value of accepted
        # instance p, taken from a receiver that actually DELIVERED it
        # (rbc data is valid only where delivered — under partial masks
        # node 0 may have voted 1 from others' echoes)
        if self.compact:
            row = out["accepted_row"]
            # Compact mode is deliberately STRICTER than full mode here:
            # full mode takes node 0's row and leaves cross-node agreement
            # to callers/tests, while compact mode (used by the scale epoch
            # drivers, where nobody re-checks the detail arrays) refuses to
            # emit a batch any correct node would disagree with.  The check
            # spans all N rows — under adversarial masks a Byzantine-faulty
            # row could trip it, which is the safe direction for a driver
            # (fail loudly, never commit a divergent batch).
            if not out["accepted_agree"]:
                raise RuntimeError("nodes disagree on the accepted set")
            if not out["delivered_ok"]:
                raise RuntimeError(
                    "an accepted instance has no delivering node"
                )

            def framed(p):
                return out["data_sel"][p]

        else:
            # agreement across correct nodes is asserted by callers/tests
            row = out["accepted"][0]
            delivered = out["delivered"]
            # map delivering receivers to rows in the data array once
            # (the full-delivery fast path returns one shared row)
            row_of = {int(r): i for i, r in enumerate(out["data_receivers"])}

            def framed(p):
                deliverers = np.flatnonzero(delivered[:, p])
                if deliverers.size == 0:
                    raise RuntimeError(
                        f"instance {p} accepted but no node delivered its value"
                    )
                rows = [
                    row_of[int(d)] for d in deliverers if int(d) in row_of
                ]
                if not rows:
                    raise RuntimeError(
                        f"instance {p}: no delivering receiver has a data row"
                    )
                return out["data"][rows[0], p]

        for p, nid in enumerate(self.ids):
            if not row[p]:
                continue
            payload = unframe_value(framed(p))
            if payload is None:
                continue
            if encrypt:
                pending.append((nid, payload))
            else:
                batch[nid] = payload
        if encrypt and pending:
            # parse + decrypt of all accepted ciphertexts fused into one
            # native call: the per-proposer ``Ciphertext.from_bytes`` wire
            # checks (canonical/on-curve/subgroup for U and W) and the
            # master-scalar decrypt run back-to-back in C with the GIL
            # released — at N=4096 this was a ~1 s host loop of Python
            # bigint parsing on top of the 0.6 s decrypt call.  Routed
            # through self._check_decrypt: the mesh-pinned sharded entry
            # point when this epoch carries a mesh, the plain batch call
            # otherwise (byte-identical results either way).
            shares = [
                (
                    self.netinfo_map[onid].node_index(onid),
                    self.netinfo_map[onid].secret_key_share(),
                )
                for onid in self.ids[: t + 1]
            ]
            plaintexts = self._check_decrypt(
                pks, [pl for _, pl in pending], shares
            )
            for (nid, _), pt in zip(pending, plaintexts):
                batch[nid] = pt
        if timer is not None:
            out["phase_s"]["decrypt"] = timer() - t0
        return batch, out

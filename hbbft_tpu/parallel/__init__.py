"""Batched array-mode protocol execution — the TPU payoff.

Object mode (``hbbft_tpu.protocols`` + ``hbbft_tpu.sim``) runs one message at
a time through Python state machines: that is the reference semantics and the
correctness oracle.  This package re-expresses protocol *rounds* as dense
array programs over (receiver × sender × instance) axes — one jitted step per
communication round, with adversarial drop/tamper schedules as mask arrays —
so the whole network's round executes as a handful of MXU matmuls and batched
keccak sweeps, and shards across TPU devices via ``shard_map`` with
``all_gather``/``all_to_all`` playing the role of the network
(SURVEY.md §2.3, §5 "distributed communication backend").

Modules:
- :mod:`hbbft_tpu.parallel.rbc` — batched Bracha reliable broadcast rounds.
- :mod:`hbbft_tpu.parallel.aba` — batched binary-agreement epochs.
- :mod:`hbbft_tpu.parallel.acs` — ACS composition and the full batched
  HoneyBadger epoch (encrypt → RBC → ABA → decrypt).
- :mod:`hbbft_tpu.parallel.mesh` — ``shard_map`` wrappers placing the node
  axis across a device mesh.
"""

from hbbft_tpu.parallel.aba import BatchedAba  # noqa: F401
from hbbft_tpu.parallel.acs import BatchedAcs, BatchedHoneyBadgerEpoch  # noqa: F401
from hbbft_tpu.parallel.rbc import BatchedRbc  # noqa: F401

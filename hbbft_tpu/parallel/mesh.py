"""``shard_map`` placement of batched protocol rounds on a device mesh.

The scaling story (SURVEY.md §2.3): the node axis is the data-parallel axis.
Each device owns a contiguous slice of nodes — it runs their proposer phase
locally and their receiver phase locally; the *network* between the phases is
an ``all_gather`` over the mesh axis (every node's proposal must reach every
node — exactly RBC's Value/Echo fan-out), riding ICI between chips instead
of a message queue.  Counting phases are replicated (they are O(N²·P) bool
ops — noise); the heavy per-receiver decode work is sharded.

The same function runs on a real multi-chip mesh or on the virtual
`--xla_force_host_platform_device_count` CPU mesh used by tests and the
driver's ``dryrun_multichip`` contract.
"""

from __future__ import annotations

import numpy as np

from hbbft_tpu.parallel.rbc import BatchedRbc


def sharded_rbc_run(rbc: BatchedRbc, mesh, data, codeword_tamper=None,
                    value_tamper=None, value_mask=None, echo_mask=None,
                    ready_mask=None):
    """Full batched RBC round with node axis sharded over ``mesh``.

    ``data``: uint8 (P, k, B) with P == rbc.n divisible by the mesh size.
    Masks/tampers as in :meth:`BatchedRbc.run` (replicated).

    Returns the same dict as ``BatchedRbc.run`` with per-receiver arrays
    gathered back to full size, so results are directly comparable with the
    single-device path (tests assert bit-equality).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from jax import shard_map

    n = rbc.n
    (axis,) = mesh.axis_names
    n_dev = mesh.devices.size
    assert n % n_dev == 0, (n, n_dev)
    per = n // n_dev

    P_, k, B = data.shape
    if codeword_tamper is None:
        codeword_tamper = jnp.zeros((P_, n, B), dtype=jnp.uint8)
    if value_tamper is None:
        value_tamper = jnp.zeros((P_, n, B), dtype=jnp.uint8)
    if value_mask is None:
        value_mask = jnp.ones((P_, n), dtype=bool)
    if echo_mask is None:
        echo_mask = jnp.ones((n, n, P_), dtype=bool)
    if ready_mask is None:
        ready_mask = jnp.ones((n, n, P_), dtype=bool)

    def step(d, cw, vt, vm, em, rm):
        # d: local (per, k, B) — this device's proposers
        shards, root, proofs, pmask = rbc.propose(d, cw)
        shards = shards ^ vt
        # the "network": every proposal reaches every node over ICI
        shards = jax.lax.all_gather(shards, axis, tiled=True)   # (P, n, B)
        root = jax.lax.all_gather(root, axis, tiled=True)       # (P, 32)
        proofs = jax.lax.all_gather(proofs, axis, tiled=True)   # (P, n, D, 32)
        # receiver phase for this device's slice of nodes
        me = jax.lax.axis_index(axis)
        receivers = me * per + jnp.arange(per)
        out = rbc.run_from_proposal(
            shards, root, proofs, pmask,
            value_mask=vm, echo_mask=em, ready_mask=rm,
            receivers=receivers,
        )
        return out

    spec_p = P(axis)        # sharded over proposers/receivers (leading axis)
    spec_r = P()            # replicated

    in_specs = (spec_p, spec_p, spec_p, spec_r, spec_r, spec_r)
    out_specs = {
        "delivered": spec_p,
        "fault": spec_p,
        "data": spec_p,
        "data_receivers": spec_p,
        "root": spec_r,
        "echo_count": spec_p,
        "ready_count": spec_p,
    }

    # check_vma off: the "root" output is replicated by construction (it is
    # an all_gather result) but the checker can't see that through the
    # data-dependent receiver phase.
    fn = shard_map(
        step, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_vma=False,
    )
    return jax.jit(fn)(
        data, codeword_tamper, value_tamper, value_mask, echo_mask, ready_mask
    )

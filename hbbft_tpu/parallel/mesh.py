"""``shard_map`` placement of batched protocol rounds on a device mesh.

The scaling story (SURVEY.md §2.3): the node axis is the data-parallel axis.
Each device owns a contiguous slice of nodes — it runs their proposer phase
locally and their receiver phase locally; the *network* between the phases is
an ``all_gather`` over the mesh axes (every node's proposal must reach every
node — exactly RBC's Value/Echo fan-out), riding ICI between chips instead
of a message queue.  Counting phases are replicated (they are O(N²·P) bool
ops — noise); the heavy per-receiver decode work is sharded.

Multi-host: pass a TWO-axis mesh (conventionally ``("dcn", "ici")`` — hosts
over the data-center network × chips over ICI).  The node axis shards over
both; the proposal fan-out is hierarchical — gather over the innermost
(ICI) axis first, so the expensive cross-host hop moves each shard once,
already host-aggregated, instead of once per chip.  On real hardware build
the mesh from ``jax.distributed``-initialized global devices (one process
per host); the virtual CPU mesh used by tests and the driver's
``dryrun_multichip`` exercises the same code path with the same collectives.

The same function runs on a real multi-chip mesh or on the virtual
`--xla_force_host_platform_device_count` CPU mesh.
"""

from __future__ import annotations

import numpy as np

from hbbft_tpu.parallel.rbc import BatchedRbc


def _gather_nodes(x, axes):
    """all_gather the leading (node-sharded) axis back to full size —
    innermost mesh axis (ICI) first, then outward (DCN), so each cross-host
    transfer carries the host's already-gathered block once."""
    import jax

    for ax in reversed(axes):
        x = jax.lax.all_gather(x, ax, tiled=True)
    return x


def _flat_device_index(axes):
    """This device's rank in the node-axis sharding (row-major over mesh
    axes, matching ``PartitionSpec((*axes,))``)."""
    import jax

    idx = jax.lax.axis_index(axes[0])
    for ax in axes[1:]:
        idx = idx * jax.lax.axis_size(ax) + jax.lax.axis_index(ax)
    return idx


def sharded_rbc_run(rbc: BatchedRbc, mesh, data, codeword_tamper=None,
                    value_tamper=None, value_mask=None, echo_mask=None,
                    ready_mask=None):
    """Full batched RBC round with the node axis sharded over ``mesh``.

    ``mesh`` may have one axis (single-host chips over ICI) or two
    (hosts × chips — DCN × ICI); ``data``: uint8 (P, k, B) with
    P == rbc.n divisible by the total device count.  Masks/tampers as in
    :meth:`BatchedRbc.run` (replicated).

    Returns the same dict as ``BatchedRbc.run`` with per-receiver arrays
    gathered back to full size, so results are directly comparable with the
    single-device path (tests assert bit-equality).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from jax import shard_map

    n = rbc.n
    axes = tuple(mesh.axis_names)
    n_dev = mesh.devices.size
    assert n % n_dev == 0, (n, n_dev)
    per = n // n_dev

    P_, k, B = data.shape
    if codeword_tamper is None:
        codeword_tamper = jnp.zeros((P_, n, B), dtype=jnp.uint8)
    if value_tamper is None:
        value_tamper = jnp.zeros((P_, n, B), dtype=jnp.uint8)
    if value_mask is None:
        value_mask = jnp.ones((P_, n), dtype=bool)
    if echo_mask is None:
        echo_mask = jnp.ones((n, n, P_), dtype=bool)
    if ready_mask is None:
        ready_mask = jnp.ones((n, n, P_), dtype=bool)

    def step(d, cw, vt, vm, em, rm):
        # d: local (per, k, B) — this device's proposers
        shards, root, proofs, pmask = rbc.propose(d, cw)
        shards = shards ^ vt
        # the "network": every proposal reaches every node — ICI inside a
        # host, one host-aggregated hop over DCN on a two-axis mesh
        shards = _gather_nodes(shards, axes)   # (P, n, B)
        root = _gather_nodes(root, axes)       # (P, 32)
        proofs = _gather_nodes(proofs, axes)   # (P, n, D, 32)
        # receiver phase for this device's slice of nodes
        me = _flat_device_index(axes)
        receivers = me * per + jnp.arange(per)
        out = rbc.run_from_proposal(
            shards, root, proofs, pmask,
            value_mask=vm, echo_mask=em, ready_mask=rm,
            receivers=receivers,
        )
        return out

    spec_p = P(axes)        # sharded over proposers/receivers (leading axis)
    spec_r = P()            # replicated

    in_specs = (spec_p, spec_p, spec_p, spec_r, spec_r, spec_r)
    out_specs = {
        "delivered": spec_p,
        "fault": spec_p,
        "data": spec_p,
        "data_receivers": spec_p,
        "root": spec_r,
        "echo_count": spec_p,
        "ready_count": spec_p,
    }

    # check_vma off: the "root" output is replicated by construction (it is
    # an all_gather result) but the checker can't see that through the
    # data-dependent receiver phase.
    fn = shard_map(
        step, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_vma=False,
    )
    return jax.jit(fn)(
        data, codeword_tamper, value_tamper, value_mask, echo_mask, ready_mask
    )

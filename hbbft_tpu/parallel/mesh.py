"""``shard_map`` placement of batched protocol rounds on a device mesh.

The scaling story (SURVEY.md §2.3): the node axis is the data-parallel axis.
Each device owns a contiguous slice of nodes — it runs their proposer phase
locally and their receiver phase locally; the *network* between the phases is
an ``all_gather`` over the mesh axes (every node's proposal must reach every
node — exactly RBC's Value/Echo fan-out), riding ICI between chips instead
of a message queue.  Counting phases are replicated (they are O(N²·P) bool
ops — noise); the heavy per-receiver decode work is sharded.

Multi-host: pass a TWO-axis mesh (conventionally ``("dcn", "ici")`` — hosts
over the data-center network × chips over ICI).  The node axis shards over
both; the proposal fan-out is hierarchical — gather over the innermost
(ICI) axis first, so the expensive cross-host hop moves each shard once,
already host-aggregated, instead of once per chip.  On real hardware build
the mesh from ``jax.distributed``-initialized global devices (one process
per host); the virtual CPU mesh used by tests and the driver's
``dryrun_multichip`` exercises the same code path with the same collectives.

The same function runs on a real multi-chip mesh or on the virtual
`--xla_force_host_platform_device_count` CPU mesh.
"""

from __future__ import annotations

import numpy as np

from hbbft_tpu.parallel.rbc import BatchedRbc

# Deterministic host-side accounting of the sharded wrappers' collective
# traffic.  Plain ints — this module sits in hblint's determinism scope,
# so no clocks here; net/runtime.py folds deltas into the hbbft_mesh_*
# registry counters at scrape time (same pattern as ops/rs.py::STATS →
# hbbft_rbc_erasure_*).  ``collectives`` counts mesh-spanning collective
# launches (one all_gather/psum group per mesh axis); ``gather_bytes``
# counts the bytes those collectives return, computed statically from the
# array shapes (shard + root payloads for RBC — Merkle proof tensors are
# excluded, their depth varies per shape; gathered state rows for ABA;
# affine point bytes for the crypto phases).
STATS = {
    ph: {"collectives": 0, "gather_bytes": 0}
    for ph in ("rbc", "aba", "coin", "decrypt")
}


def stats_snapshot():
    """Copy of the per-phase mesh-collective counters."""
    return {ph: dict(v) for ph, v in STATS.items()}


def _account(phase: str, collectives: int, gather_bytes: int) -> None:
    s = STATS[phase]
    s["collectives"] += int(collectives)
    s["gather_bytes"] += int(gather_bytes)


def _gather_nodes(x, axes):
    """all_gather the leading (node-sharded) axis back to full size —
    innermost mesh axis (ICI) first, then outward (DCN), so each cross-host
    transfer carries the host's already-gathered block once."""
    import jax

    for ax in reversed(axes):
        x = jax.lax.all_gather(x, ax, tiled=True)
    return x


def _flat_device_index(axes):
    """This device's rank in the node-axis sharding (row-major over mesh
    axes, matching ``PartitionSpec((*axes,))``)."""
    import jax

    idx = jax.lax.axis_index(axes[0])
    for ax in axes[1:]:
        # axis_size(ax) post-dates the 0.4.x line; psum(1, ax) is the
        # version-stable spelling (constant-folded by the partitioner)
        if hasattr(jax.lax, "axis_size"):
            size = jax.lax.axis_size(ax)
        else:
            size = jax.lax.psum(1, ax)
        idx = idx * size + jax.lax.axis_index(ax)
    return idx


def make_sharded_rbc_run(rbc: BatchedRbc, mesh):
    """Build ONE jitted sharded-RBC round for ``(rbc, mesh)``.

    ``mesh`` may have one axis (single-host chips over ICI) or two
    (hosts × chips — DCN × ICI).  The returned callable has the signature
    of :func:`sharded_rbc_run` minus the leading ``rbc, mesh`` and reuses
    its compiled executable across calls — epoch drivers must build it once
    (a fresh ``jax.jit`` per epoch would re-trace the whole pipeline).

    Returns the same dict as ``BatchedRbc.run`` with per-receiver arrays
    gathered back to full size, so results are directly comparable with the
    single-device path (tests assert bit-equality).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from hbbft_tpu.util import shard_map_compat
    shard_map = shard_map_compat()

    n = rbc.n
    axes = tuple(mesh.axis_names)
    n_dev = mesh.devices.size
    assert n % n_dev == 0, (n, n_dev)
    per = n // n_dev

    def step(d, cw, vt, vm, em, rm):
        # d: local (per, k, B) — this device's proposers
        shards, root, proofs, pmask = rbc.propose(d, cw)
        shards = shards ^ vt
        # the "network": every proposal reaches every node — ICI inside a
        # host, one host-aggregated hop over DCN on a two-axis mesh
        shards = _gather_nodes(shards, axes)   # (P, n, B)
        root = _gather_nodes(root, axes)       # (P, 32)
        proofs = _gather_nodes(proofs, axes)   # (P, n, D, 32)
        # receiver phase for this device's slice of nodes
        me = _flat_device_index(axes)
        receivers = me * per + jnp.arange(per)
        out = rbc.run_from_proposal(
            shards, root, proofs, pmask,
            value_mask=vm, echo_mask=em, ready_mask=rm,
            receivers=receivers,
        )
        return out

    spec_p = P(axes)        # sharded over proposers/receivers (leading axis)
    spec_r = P()            # replicated

    in_specs = (spec_p, spec_p, spec_p, spec_r, spec_r, spec_r)
    out_specs = {
        "delivered": spec_p,
        "fault": spec_p,
        "data": spec_p,
        "data_receivers": spec_p,
        "root": spec_r,
        "echo_count": spec_p,
        "ready_count": spec_p,
    }

    # check_vma off: the "root" output is replicated by construction (it is
    # an all_gather result) but the checker can't see that through the
    # data-dependent receiver phase.
    fn = jax.jit(shard_map(
        step, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_vma=False,
    ))

    def run(data, codeword_tamper=None, value_tamper=None, value_mask=None,
            echo_mask=None, ready_mask=None):
        P_, k, B = data.shape
        # three gathers (shards, roots, proofs) per mesh axis; bytes are
        # the shard + root payloads every device receives
        _account("rbc", 3 * len(axes), P_ * n * B + P_ * 32)
        if codeword_tamper is None:
            codeword_tamper = jnp.zeros((P_, n, B), dtype=jnp.uint8)
        if value_tamper is None:
            value_tamper = jnp.zeros((P_, n, B), dtype=jnp.uint8)
        if value_mask is None:
            value_mask = jnp.ones((P_, n), dtype=bool)
        if echo_mask is None:
            echo_mask = jnp.ones((n, n, P_), dtype=bool)
        if ready_mask is None:
            ready_mask = jnp.ones((n, n, P_), dtype=bool)
        return fn(data, codeword_tamper, value_tamper, value_mask,
                  echo_mask, ready_mask)

    return run


def make_sharded_rbc_large_run(rbc: BatchedRbc, mesh):
    """The large-N (N > 256, GF(2^16)) full-delivery RBC round with the
    PROPOSER axis sharded over ``mesh`` — the round-4 gap that capped the
    mesh at N ≤ 256.

    The large-N round is a god-view full-delivery verdict: every stage is
    proposer-parallel with no cross-proposer dataflow, so each device runs
    :meth:`BatchedRbc.large_stage_a`/``b`` on its slice of proposers and the
    per-proposer verdict arrays gather back to full size (the all_gather is
    the Value/Echo fan-out of SURVEY §2.3's comm-backend row — each
    proposer's shards/root leave its device once).  The straggler decode
    between the stages stays on the host exactly as in the single-device
    path; results are bit-equal to :meth:`BatchedRbc._run_large` (tests).

    Returns ``run(data, codeword_tamper=None, value_tamper=None)`` with the
    ``BatchedRbc.run`` result contract.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from hbbft_tpu.util import shard_map_compat
    shard_map = shard_map_compat()

    n, f, k = rbc.n, rbc.f, rbc.k
    axes = tuple(mesh.axis_names)
    n_dev = mesh.devices.size
    spec_p = P(axes)
    spec_r = P()

    fns = {}

    def _stage_fns(P_, has_cw, has_vt):
        key = (P_, has_cw, has_vt)
        if key in fns:
            return fns[key]
        assert P_ % n_dev == 0, (P_, n_dev)
        cs = rbc._large_chunk_size(P_ // n_dev)  # chunk per-device slices

        # variable arity: tamper tensors exist as inputs only when given —
        # no dead (P, N, B) zero buffers on the common honest path
        def stage_a(d, pbits, *tampers):
            it = iter(tampers)
            cw = next(it) if has_cw else None
            vt = next(it) if has_vt else None
            return rbc.large_stage_a(d, cw, vt, pbits, cs)

        def stage_b(dr, sent_, vv_, root_, pbits):
            return rbc.large_stage_b(dr, sent_, vv_, root_, pbits, cs)

        n_tampers = int(has_cw) + int(has_vt)
        a = jax.jit(shard_map(
            stage_a, mesh=mesh,
            in_specs=(spec_p, spec_r) + (spec_p,) * n_tampers,
            out_specs=(spec_p, spec_p, spec_p, spec_p, spec_p),
            check_vma=False,
        ))
        b = jax.jit(shard_map(
            stage_b, mesh=mesh,
            in_specs=(spec_p, spec_p, spec_p, spec_p, spec_r),
            out_specs=(spec_p, spec_p, spec_p),
            check_vma=False,
        ))
        fns[key] = (a, b)
        return fns[key]

    def run(data, codeword_tamper=None, value_tamper=None):
        P_ = data.shape[0]
        # proposer-parallel stages: no cross-proposer collective inside;
        # the two sharded stage launches re-assemble their per-proposer
        # verdicts across the mesh once each (counted per axis), and the
        # bytes that leave each device are its slice of the framed data
        _account(
            "rbc", 2 * len(axes), int(np.prod(np.asarray(data.shape)))
        )
        has_cw = codeword_tamper is not None
        has_vt = value_tamper is not None
        a, b = _stage_fns(P_, has_cw, has_vt)
        tampers = tuple(
            jnp.asarray(t)
            for t in (codeword_tamper, value_tamper)
            if t is not None
        )
        a_out = a(jnp.asarray(data), rbc._pbits(), *tampers)
        return rbc.finish_large(
            a_out,
            lambda dr, sent_, vv_, root_: b(
                dr, sent_, vv_, root_, rbc._pbits()
            ),
        )

    return run


def sharded_rbc_run(rbc: BatchedRbc, mesh, data, **kwargs):
    """One-shot convenience wrapper over :func:`make_sharded_rbc_run`.

    Single calls (tests, the driver dryrun) only; epoch drivers hold on to
    the maker's callable instead so the compiled executable is reused.
    """
    return make_sharded_rbc_run(rbc, mesh)(data, **kwargs)


def make_sharded_aba_step(aba, mesh):
    """A jitted ABA epoch step with node-state rows sharded over ``mesh``.

    Same semantics as :meth:`BatchedAba.epoch_step` (bit-equal — tests
    assert it): state arrays (N, P) shard their node axis; the BVal/Aux/Conf
    exchanges become ``all_gather``/``psum`` collectives over the mesh axes
    (ICI-first on a hierarchical mesh) instead of in-device reductions.
    Masks, ``coin_bits`` and the epoch counter are replicated.

    Returns ``step(state, coin_bits, bval_mask=None, aux_mask=None,
    conf_mask=None) -> state``; jit once, call per epoch.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from hbbft_tpu.util import shard_map_compat
    shard_map = shard_map_compat()

    n, f = aba.n, aba.f
    axes = tuple(mesh.axis_names)
    n_dev = mesh.devices.size
    assert n % n_dev == 0, (n, n_dev)
    per = n // n_dev

    spec_p = P(axes)
    spec_r = P()
    state_specs = {
        "est": spec_p, "decided": spec_p, "decision": spec_p,
        "epoch": spec_r,
    }

    def _psum(x):
        return jax.lax.psum(x, axes)

    def step_full(state, coin_bits):
        # local slices: est/decided/decision (per, P)
        est = state["est"]
        decided = state["decided"]
        decision = state["decision"]
        P_ = est.shape[1]

        active = ~decided
        val_axis = jnp.stack([~est, est], axis=-1)
        term_axis = jnp.stack([~decision, decision], axis=-1)
        sent = jnp.where(decided[..., None], term_axis, val_axis)

        # full-delivery round model (parallel/aba.py::sbv_round_model) with
        # the node rows sharded: the neighbor count is a psum, everything
        # else stays local — bit-equal to the single-device step (tests)
        from hbbft_tpu.parallel.aba import (
            SBV_INF_FULL,
            SBV_ROUNDS_FULL,
            aux_pref_from_crossings,
            sbv_round_model,
        )

        INF = jnp.int32(SBV_INF_FULL)
        o, x = sbv_round_model(
            sent, f, SBV_ROUNDS_FULL,
            lambda early: _psum(early.sum(axis=0))[None], INF,
        )
        binv_j, pref_true = aux_pref_from_crossings(x, INF)  # (per, P, 2)
        bin_vals = _psum(binv_j.any(axis=0).astype(jnp.int32)) > 0  # (P, 2)
        aux_val = jnp.where(decided, decision, pref_true)
        aux_sent = bin_vals.any(axis=-1)[None] | decided
        aux_v = jnp.stack([~aux_val, aux_val], axis=-1) & aux_sent[..., None]
        support = _psum((aux_v & bin_vals[None]).any(axis=-1).sum(axis=0))
        vals = bin_vals & (_psum(aux_v.sum(axis=0)) > 0)
        sbv_done = support >= (n - f)  # (P,)

        conf = jnp.where(decided[..., None], term_axis, vals[None])
        viol = (conf & ~bin_vals[None]).any(axis=-1)  # (per, P)
        sent_conf = sbv_done[None] | decided
        conf_count = _psum((sent_conf & ~viol).sum(axis=0))
        conf_done = conf_count >= (n - f)

        m = state["epoch"] % 3
        coin = jnp.where(
            m == 0,
            jnp.ones((P_,), dtype=bool),
            jnp.where(m == 1, jnp.zeros((P_,), dtype=bool), coin_bits),
        )

        only_true = vals[:, 1] & ~vals[:, 0]
        vals_single = only_true | (vals[:, 0] & ~vals[:, 1])
        vals_val = only_true
        ready = (conf_done & sbv_done)[None] & active
        decide_now = ready & (vals_single & (vals_val == coin))[None]
        new_est = jnp.where(vals_single, vals_val, coin)[None]
        est = jnp.where(ready, jnp.broadcast_to(new_est, est.shape), est)
        coin_b = jnp.broadcast_to(coin[None], est.shape)
        decision = jnp.where(decide_now, coin_b, decision)
        decided = decided | decide_now

        for v in (False, True):
            term_cnt = _psum((decided & (decision == v)).sum(axis=0))
            adopt = active & (term_cnt >= (f + 1))[None] & ~decided
            decision = jnp.where(adopt, v, decision)
            decided = decided | adopt

        return {
            "est": est,
            "decided": decided,
            "decision": decision,
            "epoch": state["epoch"] + 1,
        }

    def step_masked(state, coin_bits, bval_mask, aux_mask, conf_mask):
        est = state["est"]
        decided = state["decided"]
        decision = state["decision"]

        me = _flat_device_index(axes)
        base = me * per
        # receiver slices of the replicated (N_src, N_dst, P) masks
        bm = jax.lax.dynamic_slice_in_dim(bval_mask, base, per, axis=1)
        am = jax.lax.dynamic_slice_in_dim(aux_mask, base, per, axis=1)
        cm = jax.lax.dynamic_slice_in_dim(conf_mask, base, per, axis=1)

        active = ~decided
        val_axis = jnp.stack([~est, est], axis=-1)
        term_axis = jnp.stack([~decision, decision], axis=-1)
        sent = jnp.where(decided[..., None], term_axis, val_axis)  # local

        # masked round model (parallel/aba.py::sbv_round_model): o/x rows
        # local, the neighbor sum gathers the o<t indicators — bit-equal to
        # BatchedAba.epoch_step (tests)
        from hbbft_tpu.parallel.aba import (
            aux_pref_from_crossings,
            sbv_inf_masked,
            sbv_round_model,
            sbv_rounds_masked,
        )

        INF = jnp.int32(sbv_inf_masked(n))
        bmi = bm.astype(jnp.int32)
        o, x = sbv_round_model(
            sent, f, sbv_rounds_masked(n),
            lambda early: jnp.einsum(
                "ipv,ijp->jpv", _gather_nodes(early, axes), bmi
            ),
            INF,
        )
        bin_vals, pref_true = aux_pref_from_crossings(x, INF)  # (per, P, 2)
        aux_val = jnp.where(decided, decision, pref_true)
        aux_sent = bin_vals.any(axis=-1) | decided
        aux_v = jnp.stack([~aux_val, aux_val], axis=-1) & aux_sent[..., None]
        aux_v_full = _gather_nodes(aux_v, axes)  # (N, P, 2)
        support = jnp.einsum(
            "ipv,ijp,jpv->jp", aux_v_full.astype(jnp.int32),
            am.astype(jnp.int32), bin_vals.astype(jnp.int32),
        )
        vals = bin_vals & (
            jnp.einsum(
                "ipv,ijp->jpv", aux_v_full.astype(jnp.int32),
                am.astype(jnp.int32),
            )
            > 0
        )
        sbv_done = support >= (n - f)  # (per, P)

        conf = jnp.where(decided[..., None], term_axis, vals)
        conf_full = _gather_nodes(conf, axes)  # (N, P, 2)
        viol = jnp.einsum(
            "ipv,jpv->ijp", conf_full.astype(jnp.int32),
            (~bin_vals).astype(jnp.int32),
        )  # (N senders, per receivers, P)
        sent_conf_full = _gather_nodes(sbv_done | decided, axes)  # (N, P)
        conf_count = (
            (viol == 0) & cm & sent_conf_full[:, None, :]
        ).sum(axis=0)  # (per, P)
        conf_done = conf_count >= (n - f)

        m = state["epoch"] % 3
        P_ = est.shape[1]
        coin = jnp.where(
            m == 0,
            jnp.ones((P_,), dtype=bool),
            jnp.where(m == 1, jnp.zeros((P_,), dtype=bool), coin_bits),
        )
        coin_b = jnp.broadcast_to(coin[None, :], est.shape)

        only_true = vals[..., 1] & ~vals[..., 0]
        vals_single = only_true | (vals[..., 0] & ~vals[..., 1])
        vals_val = only_true
        ready = conf_done & sbv_done & active
        # all-active-completed decision guard (see parallel/aba.py — the
        # lossy-lockstep safety condition), psum'd across the node shards
        incomplete = _psum(
            (~((conf_done & sbv_done) | ~active)).sum(axis=0)
        )  # (P,)
        decide_now = (
            ready & vals_single & (vals_val == coin_b)
            & (incomplete == 0)[None]
        )
        new_est = jnp.where(vals_single, vals_val, coin_b)
        est = jnp.where(ready, new_est, est)
        decision = jnp.where(decide_now, coin_b, decision)
        decided = decided | decide_now

        for v in (False, True):
            term_cnt = _psum((decided & (decision == v)).sum(axis=0))
            adopt = active & (term_cnt >= (f + 1))[None, :] & ~decided
            decision = jnp.where(adopt, v, decision)
            decided = decided | adopt

        return {
            "est": est,
            "decided": decided,
            "decision": decision,
            "epoch": state["epoch"] + 1,
        }

    fn_full = jax.jit(shard_map(
        step_full, mesh=mesh,
        in_specs=(state_specs, spec_r),
        out_specs=state_specs,
        check_vma=False,
    ))
    fn_masked = jax.jit(shard_map(
        step_masked, mesh=mesh,
        in_specs=(state_specs, spec_r, spec_r, spec_r, spec_r),
        out_specs=state_specs,
        check_vma=False,
    ))

    # static collective counts per traced step, for the hbbft_mesh_*
    # accounting: the SBV round-model reductions plus the aux/conf/term
    # exchanges (6 on the full-delivery path, 6 gathers+psums masked)
    from hbbft_tpu.parallel.aba import SBV_ROUNDS_FULL, sbv_rounds_masked

    _coll_full = (SBV_ROUNDS_FULL + 6) * len(axes)
    _coll_masked = (sbv_rounds_masked(n) + 6) * len(axes)

    def step(state, coin_bits, bval_mask=None, aux_mask=None, conf_mask=None):
        P_ = state["est"].shape[1]
        if bval_mask is None and aux_mask is None and conf_mask is None:
            # psum results are (P,)-shaped int32 reductions
            _account("aba", _coll_full, (SBV_ROUNDS_FULL + 6) * P_ * 4)
            return fn_full(state, coin_bits)
        import jax.numpy as jnp

        # gathered (N, P, 2)-ish bool tensors per round + aux/conf/sent
        _account(
            "aba", _coll_masked,
            (2 * sbv_rounds_masked(n) + 5) * n * P_,
        )
        eye = jnp.eye(n, dtype=bool)[:, :, None]
        ones = jnp.ones((n, n, P_), dtype=bool)
        bm = ones if bval_mask is None else jnp.asarray(bval_mask) | eye
        am = ones if aux_mask is None else jnp.asarray(aux_mask) | eye
        cm = ones if conf_mask is None else jnp.asarray(conf_mask) | eye
        return fn_masked(state, coin_bits, bm, am, cm)

    return step


# ---------------------------------------------------------------------------
# Sharded crypto phases (coin share verification, threshold decryption)
# ---------------------------------------------------------------------------
#
# The protocol rounds above shard the NODE axis; the crypto phases shard the
# MSM ROW axis instead (crypto/batch._MsmCache row-shards its ladders when a
# mesh is attached — row-sharding is collective-free until the final fold).
# These makers pin the per-mesh ladder cache (crypto.batch.cache_for) at
# build time, so the mesh an epoch driver threads through BatchedAcs and the
# mesh the crypto ladders run on are the SAME object — the two used to be
# set independently (use_mesh vs. BatchedHoneyBadgerEpoch(mesh=...)) and
# could disagree.


def make_sharded_coin_verify(mesh):
    """Coin/signature share batch verification with the MSM ladders
    row-sharded over ``mesh``.

    Returns ``verify(pairs, msg, rng) -> bool`` with the exact semantics
    of :func:`hbbft_tpu.crypto.batch.batch_verify_sig_shares` (True ⟹
    every (PublicKeyShare, SignatureShare) pair is valid), routed through
    the per-mesh ladder cache.  Single-device fallbacks (small batches,
    CPU backend) keep the verdict bit-identical — the mesh only moves the
    MSM rows.
    """
    from hbbft_tpu.crypto import batch as _cb

    cache = _cb.cache_for(mesh)
    n_axes = len(tuple(mesh.axis_names)) if mesh is not None else 0

    def verify(pairs, msg, rng):
        # two ladder folds (G2 sigs, G1 pks); affine point bytes gathered
        _account("coin", 2 * max(n_axes, 1), len(pairs) * (192 + 96))
        return _cb.batch_verify_sig_shares(pairs, msg, rng, cache=cache)

    return verify


def make_sharded_decrypt(mesh):
    """Master-scalar-folded threshold decryption with the mask ladder
    row-sharded over ``mesh``.

    Returns ``decrypt(pks, payloads, secret_shares) -> plaintexts`` with
    the exact semantics of :func:`hbbft_tpu.crypto.batch.
    batch_tpke_check_decrypt` (wire-validate + decrypt, ValueError on a
    malformed payload), routed through the per-mesh ladder cache.  Below
    the device-decrypt crossover the native/host paths run unchanged —
    plaintexts are byte-identical either way (tier-1 asserts it).
    """
    from hbbft_tpu.crypto import batch as _cb

    cache = _cb.cache_for(mesh)
    n_axes = len(tuple(mesh.axis_names)) if mesh is not None else 0

    def decrypt(pks, payloads, secret_shares):
        # one G1 mask ladder fold; affine G1 bytes per ciphertext
        _account("decrypt", max(n_axes, 1), len(payloads) * 96)
        return _cb.batch_tpke_check_decrypt(
            pks, payloads, secret_shares, cache=cache
        )

    return decrypt

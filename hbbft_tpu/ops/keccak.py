"""Batched keccak-f[1600] and SHA3-256 in jnp.

The reference's Merkle commitments hash RBC shards with SHA3-256 (reference:
``src/broadcast/merkle.rs`` digests via ``tiny-keccak``), and the common coin
is the hash of the combined threshold signature.  On TPU we need *many*
digests per protocol round (N nodes × N instances × shards), so the permutation
is written to batch over arbitrary leading axes.

TPUs have no native 64-bit integer path, so every 64-bit lane is a pair of
uint32 arrays ``(hi, lo)``; rotations/xors are expressed on the halves.  The
state is ``(..., 25)`` with flat index ``5*y + x`` (the byte-serialization
order), i.e. ``state[5y+x] = A[x,y]`` in the Keccak reference's coordinates.

Round constants and rotation offsets are derived programmatically from the
spec (LFSR / triangular numbers) rather than transcribed tables.

Host oracle: ``hashlib.sha3_256`` (tests assert bit-exactness against it).
"""

from __future__ import annotations

import functools

import numpy as np

# ---------------------------------------------------------------------------
# Spec-derived constants
# ---------------------------------------------------------------------------


def _rc_bit(t: int) -> int:
    if t % 255 == 0:
        return 1
    R = 1
    for _ in range(1, t % 255 + 1):
        R <<= 1
        if R & 0x100:
            R ^= 0x171
    return R & 1


def _round_constants():
    rcs = []
    for i in range(24):
        rc = 0
        for j in range(7):
            rc |= _rc_bit(7 * i + j) << ((1 << j) - 1)
        rcs.append(rc)
    return rcs


ROUND_CONSTANTS = _round_constants()


def _rho_pi_tables():
    """Per-target-lane source index and rotation for the fused ρ∘π step.

    ρ offsets from the triangular-number walk: start (x,y)=(1,0);
    r[x,y] = (t+1)(t+2)/2 mod 64; step (x,y) ← (y, 2x+3y).
    π: A'[x', y'] = A[x, y] with x' = y, y' = (2x+3y) mod 5, fused so
    ``out[tgt] = rotl(state[src[tgt]], rot[tgt])``.
    """
    r = np.zeros((5, 5), dtype=np.int64)  # r[x, y]
    x, y = 1, 0
    for t in range(24):
        r[x, y] = ((t + 1) * (t + 2) // 2) % 64
        x, y = y, (2 * x + 3 * y) % 5
    src = np.zeros(25, dtype=np.int32)
    rot = np.zeros(25, dtype=np.int32)
    for yt in range(5):
        for xt in range(5):
            tgt = 5 * yt + xt
            sx = (xt + 3 * yt) % 5  # source x
            sy = xt  # source y
            src[tgt] = 5 * sy + sx
            rot[tgt] = r[sx, sy]
    return src, rot


_PI_SRC, _PI_ROT = _rho_pi_tables()

RATE_BYTES = 136  # SHA3-256: rate 1088 bits, capacity 512
DIGEST_BYTES = 32


# ---------------------------------------------------------------------------
# 64-bit-as-two-uint32 helpers
# ---------------------------------------------------------------------------


def _rotl64(hi, lo, s):
    """Rotate-left (hi, lo) by per-element shifts ``s`` (0..63, array ok,
    broadcasting against the state)."""
    import jax.numpy as jnp

    s = jnp.asarray(s, dtype=jnp.uint32)
    swap = (s >= 32) & (s < 64)
    s32 = jnp.where(swap, s - 32, s)
    a, b = jnp.where(swap, lo, hi), jnp.where(swap, hi, lo)
    # now rotate (a, b) left by s32 in [0, 32)
    nz = s32 > 0
    inv = jnp.where(nz, 32 - s32, 1)  # avoid >>32 UB when s32 == 0
    hi_out = jnp.where(nz, (a << s32) | (b >> inv), a)
    lo_out = jnp.where(nz, (b << s32) | (a >> inv), b)
    return hi_out.astype(jnp.uint32), lo_out.astype(jnp.uint32)


def _rotl_const(hi, lo, s: int):
    """Rotate-left (hi, lo) by a COMPILE-TIME shift: the hi/lo swap and the
    shift amounts resolve at trace time, so each lane's rotation is two
    shifts and an or — no per-element selects."""
    s %= 64
    if s == 0:
        return hi, lo
    if s >= 32:
        hi, lo = lo, hi
        s -= 32
    if s == 0:
        return hi, lo
    return (
        ((hi << s) | (lo >> (32 - s))).astype(hi.dtype),
        ((lo << s) | (hi >> (32 - s))).astype(lo.dtype),
    )


def _keccak_form() -> str:
    """Which round-body form to trace.

    ``wide``: fully-unrolled 25-lane form — static lane indices, constant
    rotation amounts, zero gathers/rolls.  ~5× faster on TPU (full vector
    width on the batch axis, no cross-lane shuffles) but traces ~10× more
    ops, so compiles ~4× slower — the right trade exactly once per shape
    on the accelerator.
    ``compact``: rolled form (gather + broadcast rotate) — ~equal runtime
    on CPU, far cheaper to compile; the right trade for the CPU test
    suite, which instantiates sha3 at dozens of shapes.
    Override with HBBFT_KECCAK_FORM; ``auto`` picks by backend.
    """
    import os

    form = os.environ.get("HBBFT_KECCAK_FORM", "auto")
    if form in ("wide", "compact"):
        return form
    import jax

    return "compact" if jax.default_backend() == "cpu" else "wide"


def keccak_f1600(hi, lo):
    """One keccak-f[1600] permutation, batched.

    hi, lo: uint32 arrays of shape (..., 25).

    TPU-layout note: the public shape keeps the 25 lanes on the minor axis
    (callers slice digests out of it), but computing in that layout wastes
    ~4/5 of every vector register (25-wide rows in 128-wide lanes) and
    turns θ/ρ/π into cross-lane shuffles.  Internally the state is
    lane-major — (25, batch) with the batch on the minor axis at full
    vector width.  Two round-body forms exist (see :func:`_keccak_form`);
    both are bit-exact against hashlib (tests sweep both).
    """
    import jax
    import jax.numpy as jnp

    batch_shape = hi.shape[:-1]
    rcs_hi = jnp.asarray([(c >> 32) & 0xFFFFFFFF for c in ROUND_CONSTANTS],
                         dtype=jnp.uint32)
    rcs_lo = jnp.asarray([c & 0xFFFFFFFF for c in ROUND_CONSTANTS],
                         dtype=jnp.uint32)

    if _keccak_form() == "wide":
        H = [jnp.moveaxis(hi, -1, 0)[i] for i in range(25)]
        L = [jnp.moveaxis(lo, -1, 0)[i] for i in range(25)]
        src_i = [int(s) for s in _PI_SRC]
        rot_i = [int(r) for r in _PI_ROT]

        def round_wide(carry, rc):
            H, L = list(carry[0]), list(carry[1])
            rc_hi, rc_lo = rc
            # θ — column parities (static lane indices; state[5y+x])
            Ch = [H[x] ^ H[5 + x] ^ H[10 + x] ^ H[15 + x] ^ H[20 + x]
                  for x in range(5)]
            Cl = [L[x] ^ L[5 + x] ^ L[10 + x] ^ L[15 + x] ^ L[20 + x]
                  for x in range(5)]
            for x in range(5):
                rh, rl = _rotl_const(Ch[(x + 1) % 5], Cl[(x + 1) % 5], 1)
                dh = Ch[(x - 1) % 5] ^ rh
                dl = Cl[(x - 1) % 5] ^ rl
                for y in range(5):
                    H[5 * y + x] = H[5 * y + x] ^ dh
                    L[5 * y + x] = L[5 * y + x] ^ dl
            # ρ ∘ π — constant-shift rotations of statically-chosen lanes
            PH, PL = H[:], L[:]
            for i in range(25):
                H[i], L[i] = _rotl_const(PH[src_i[i]], PL[src_i[i]], rot_i[i])
            # χ — row nonlinearity
            XH, XL = H[:], L[:]
            for y in range(5):
                for x in range(5):
                    a, b = 5 * y + (x + 1) % 5, 5 * y + (x + 2) % 5
                    H[5 * y + x] = XH[5 * y + x] ^ (~XH[a] & XH[b])
                    L[5 * y + x] = XL[5 * y + x] ^ (~XL[a] & XL[b])
            # ι
            H[0] = H[0] ^ rc_hi
            L[0] = L[0] ^ rc_lo
            return (tuple(H), tuple(L)), None

        (H, L), _ = jax.lax.scan(round_wide, (tuple(H), tuple(L)),
                                 (rcs_hi, rcs_lo))
        hi_out = jnp.moveaxis(jnp.stack(H, axis=0), 0, -1)
        lo_out = jnp.moveaxis(jnp.stack(L, axis=0), 0, -1)
        assert hi_out.shape == (*batch_shape, 25)
        return hi_out, lo_out

    hi = jnp.moveaxis(hi, -1, 0)  # (25, ...)
    lo = jnp.moveaxis(lo, -1, 0)
    ext = hi.ndim - 1
    src = jnp.asarray(_PI_SRC)
    rot = jnp.asarray(_PI_ROT).reshape(25, *([1] * ext))

    def grid(h):
        return h.reshape(5, 5, *h.shape[1:])  # [y, x, ...]

    def flat(h):
        return h.reshape(25, *h.shape[2:])

    def round_fn(carry, rc):
        hi, lo = carry
        rc_hi, rc_lo = rc
        # θ — column parities
        Th, Tl = grid(hi), grid(lo)
        Ch = Th[0] ^ Th[1] ^ Th[2] ^ Th[3] ^ Th[4]  # (5x, ...)
        Cl = Tl[0] ^ Tl[1] ^ Tl[2] ^ Tl[3] ^ Tl[4]
        C1h, C1l = _rotl64(jnp.roll(Ch, -1, axis=0), jnp.roll(Cl, -1, axis=0), 1)
        Dh = jnp.roll(Ch, 1, axis=0) ^ C1h
        Dl = jnp.roll(Cl, 1, axis=0) ^ C1l
        Th = Th ^ Dh[None]
        Tl = Tl ^ Dl[None]
        hi, lo = flat(Th), flat(Tl)
        # ρ ∘ π — row gather + per-row rotate (amounts constant per row)
        hi, lo = _rotl64(hi[src], lo[src], rot)
        # χ — row nonlinearity
        Th, Tl = grid(hi), grid(lo)
        Th = Th ^ (~jnp.roll(Th, -1, axis=1) & jnp.roll(Th, -2, axis=1))
        Tl = Tl ^ (~jnp.roll(Tl, -1, axis=1) & jnp.roll(Tl, -2, axis=1))
        hi, lo = flat(Th), flat(Tl)
        # ι
        hi = hi.at[0].set(hi[0] ^ rc_hi)
        lo = lo.at[0].set(lo[0] ^ rc_lo)
        return (hi, lo), None

    # lax.scan over the 24 rounds: the round body appears ONCE in the traced
    # graph instead of 24× — keccak dominates every Merkle-heavy program's
    # compile time, and merkle_build/verify instantiate sha3 per tree level.
    (hi, lo), _ = jax.lax.scan(round_fn, (hi, lo), (rcs_hi, rcs_lo))
    hi_out = jnp.moveaxis(hi, 0, -1)
    lo_out = jnp.moveaxis(lo, 0, -1)
    assert hi_out.shape == (*batch_shape, 25)
    return hi_out, lo_out


def _bytes_to_lanes(block):
    """uint8 (..., 8*L) little-endian → (hi, lo) uint32 (..., L)."""
    import jax.numpy as jnp

    b = block.reshape(*block.shape[:-1], block.shape[-1] // 8, 8).astype(jnp.uint32)
    w = jnp.left_shift(jnp.uint32(1), jnp.arange(4, dtype=jnp.uint32) * 8)
    lo = (b[..., :4] * w).sum(axis=-1).astype(jnp.uint32)
    hi = (b[..., 4:] * w).sum(axis=-1).astype(jnp.uint32)
    return hi, lo


def _lanes_to_bytes(hi, lo):
    """(hi, lo) uint32 (..., L) → uint8 (..., 8*L) little-endian."""
    import jax.numpy as jnp

    sh = jnp.arange(4, dtype=jnp.uint32) * 8
    lo_b = (lo[..., None] >> sh) & 0xFF
    hi_b = (hi[..., None] >> sh) & 0xFF
    out = jnp.concatenate([lo_b, hi_b], axis=-1).astype(jnp.uint8)
    return out.reshape(*hi.shape[:-1], hi.shape[-1] * 8)


def sha3_256(data):
    """Batched SHA3-256.  data: uint8 (..., L) with static L → (..., 32).

    Pads per FIPS-202 (domain 0x06, final bit 0x80), absorbs at rate 136,
    squeezes 32 bytes.  Bit-exact with ``hashlib.sha3_256``.
    """
    import jax.numpy as jnp

    data = jnp.asarray(data, dtype=jnp.uint8)
    L = data.shape[-1]
    nblocks = L // RATE_BYTES + 1
    padded_len = nblocks * RATE_BYTES
    pad = jnp.zeros((*data.shape[:-1], padded_len - L), dtype=jnp.uint8)
    m = jnp.concatenate([data, pad], axis=-1)
    m = m.at[..., L].set(m[..., L] ^ 0x06)
    m = m.at[..., -1].set(m[..., -1] ^ 0x80)

    batch_shape = data.shape[:-1]
    hi = jnp.zeros((*batch_shape, 25), dtype=jnp.uint32)
    lo = jnp.zeros((*batch_shape, 25), dtype=jnp.uint32)
    for i in range(nblocks):
        block = m[..., i * RATE_BYTES : (i + 1) * RATE_BYTES]
        bhi, blo = _bytes_to_lanes(block)
        hi = hi.at[..., : RATE_BYTES // 8].set(hi[..., : RATE_BYTES // 8] ^ bhi)
        lo = lo.at[..., : RATE_BYTES // 8].set(lo[..., : RATE_BYTES // 8] ^ blo)
        hi, lo = keccak_f1600(hi, lo)
    return _lanes_to_bytes(hi[..., :4], lo[..., :4])


def sha3_256_host(data: bytes) -> bytes:
    """Host oracle — Python's built-in SHA3 (FIPS-202)."""
    import hashlib

    return hashlib.sha3_256(data).digest()

"""GF(2^16) arithmetic — the erasure field for N > 256 networks.

The reference's ``reed-solomon-erasure`` crate (and our GF(2^8) coder in
:mod:`hbbft_tpu.ops.gf256`) caps total shards at 256, i.e. N ≤ 256 nodes.
BASELINE configs 4–5 ask for N = 1024 / 4096, so large networks switch to
GF(2^16) (poly x¹⁶+x¹²+x³+x+1 = 0x1100B, generator 2): up to 65536 shards.

Same design as gf256: host log/exp tables for construction/inversion, and
the bit-plane lowering for device encode — a constant GF(2^16) matrix is
GF(2)-linear, so applying it is one int8 matmul on (16·k → 16·r) bit
vectors (symbols are u16, stored as little-endian byte pairs in shards).
"""

from __future__ import annotations

import numpy as np

GF16_POLY = 0x1100B
GF16_GEN = 2
ORDER = 1 << 16


def _build_tables():
    exp = np.zeros(2 * ORDER, dtype=np.uint32)
    log = np.zeros(ORDER, dtype=np.int64)
    x = 1
    for i in range(ORDER - 1):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & ORDER:
            x ^= GF16_POLY
    for i in range(ORDER - 1, 2 * ORDER):
        exp[i] = exp[i - (ORDER - 1)]
    return exp, log


GF_EXP, GF_LOG = _build_tables()


def gf_mul(a, b):
    """Elementwise GF(2^16) multiply (numpy uint16-compatible arrays)."""
    a = np.asarray(a, dtype=np.uint32)
    b = np.asarray(b, dtype=np.uint32)
    r = GF_EXP[(GF_LOG[a] + GF_LOG[b]) % (ORDER - 1)]
    return np.where((a != 0) & (b != 0), r, 0).astype(np.uint16)


def gf_inv(a):
    a = np.asarray(a)
    if np.any(a == 0):
        raise ZeroDivisionError("GF(2^16) inverse of 0")
    return GF_EXP[(ORDER - 1) - GF_LOG[a]].astype(np.uint16)


def gf_pow(a: int, n: int) -> int:
    if n == 0:
        return 1
    if a == 0:
        return 0
    return int(GF_EXP[(int(GF_LOG[a]) * n) % (ORDER - 1)])


def gf_matmul_np(A: np.ndarray, B: np.ndarray) -> np.ndarray:
    """GF(2^16) matrix product. A: (r, k), B: (k, c) → (r, c)."""
    A = np.asarray(A, dtype=np.uint16)
    B = np.asarray(B, dtype=np.uint16)
    r, k = A.shape
    k2, c = B.shape
    assert k == k2
    out = np.zeros((r, c), dtype=np.uint16)
    for i in range(k):
        out ^= gf_mul(A[:, i][:, None], B[i][None, :])
    return out


def gf_inv_matrix_np(M: np.ndarray) -> np.ndarray:
    """Gauss–Jordan inversion over GF(2^16) (host)."""
    M = np.asarray(M, dtype=np.uint16)
    n = M.shape[0]
    aug = np.concatenate([M.copy(), np.eye(n, dtype=np.uint16)], axis=1)
    for col in range(n):
        piv = col + int(np.argmax(aug[col:, col] != 0))
        if aug[piv, col] == 0:
            raise np.linalg.LinAlgError("singular GF(2^16) matrix")
        if piv != col:
            aug[[col, piv]] = aug[[piv, col]]
        aug[col] = gf_mul(aug[col], gf_inv(aug[col, col]))
        mask = aug[:, col].copy()
        mask[col] = 0
        aug ^= gf_mul(mask[:, None], aug[col][None, :])
    return aug[:, n:].copy()


def vandermonde(rows: int, cols: int) -> np.ndarray:
    """V[r, c] = r^c — vectorized (the python-loop version took minutes at
    the N=4096 network shape)."""
    r = np.arange(rows, dtype=np.int64)
    c = np.arange(cols, dtype=np.int64)
    expnt = (GF_LOG[r][:, None] * c[None, :]) % (ORDER - 1)
    V = GF_EXP[expnt].astype(np.uint16)
    V[0, :] = 0  # 0^c = 0 …
    V[:, 0] = 1  # … except c = 0: r^0 = 1 (including 0^0 per the coder)
    return V


def gf_matrix_to_bits(M: np.ndarray) -> np.ndarray:
    """(r, k) GF(2^16) matrix → (k·16, r·16) GF(2) bit matrix (int8).

    Layout mirrors gf256: ``A[k·16+i, j·16+b]`` = bit b of
    ``gf_mul(M[j, k], 1 << i)``, bits LSB-first, so ``(bits(D) @ A) & 1``
    applies M to symbol vectors D.
    """
    M = np.asarray(M, dtype=np.uint16)
    r, k = M.shape
    powers = (1 << np.arange(16)).astype(np.uint32)
    prod = gf_mul(M[:, :, None], powers[None, None, :])  # (r, k, 16)
    bits = (prod[..., None].astype(np.uint32) >> np.arange(16)) & 1
    A = bits.transpose(1, 2, 0, 3).reshape(k * 16, r * 16)
    return A.astype(np.int8)


# device helpers -------------------------------------------------------------


def gf_mul_jnp(a, b):
    """Elementwise GF(2^16) multiply on device via log/exp gathers.

    For data×data products (e.g. Gauss–Jordan on survivor-dependent decode
    matrices).  Constant-matrix products use :func:`gf_apply_bitmatrix`.
    """
    import jax.numpy as jnp

    exp = jnp.asarray(GF_EXP[: ORDER - 1].astype(np.int32))
    log = jnp.asarray(GF_LOG.astype(np.int32))
    ai = a.astype(jnp.int32)
    bi = b.astype(jnp.int32)
    r = exp[(log[ai] + log[bi]) % (ORDER - 1)]
    nz = (ai != 0) & (bi != 0)
    return jnp.where(nz, r, 0).astype(jnp.uint16)


def gf_inv_jnp(a):
    """Elementwise GF(2^16) inverse on device; maps 0 → 0 (caller masks)."""
    import jax.numpy as jnp

    exp = jnp.asarray(GF_EXP[: ORDER].astype(np.int32))
    log = jnp.asarray(GF_LOG.astype(np.int32))
    ai = a.astype(jnp.int32)
    r = exp[(ORDER - 1) - log[ai]]
    return jnp.where(ai != 0, r, 0).astype(jnp.uint16)


def gf_inv_matrix_jnp(M):
    """Batched GF(2^16) matrix inversion on device — the same generic
    Gauss–Jordan as :func:`hbbft_tpu.ops.gf256.gf_inv_matrix_jnp` (partial
    pivoting, first nonzero at-or-below the diagonal; bit-identical to the
    host :func:`gf_inv_matrix_np`).  Returns ``(inv, ok)``.
    """
    import jax.numpy as jnp

    from hbbft_tpu.ops.gf256 import gf_inv_matrix_jnp_impl

    return gf_inv_matrix_jnp_impl(M, gf_mul_jnp, gf_inv_jnp, jnp.uint16)


def gf_matrix_to_bits_jnp(M):
    """Device version of :func:`gf_matrix_to_bits`, batched.

    M: uint16 (..., r, k) → int8 (..., k*16, r*16), same layout as the host
    function, for data-dependent (per receiver × proposer) decode matrices.
    """
    import jax.numpy as jnp

    r, k = M.shape[-2:]
    powers = jnp.left_shift(
        jnp.uint16(1), jnp.arange(16, dtype=jnp.uint16)
    )
    prod = gf_mul_jnp(M[..., None], powers)  # (..., r, k, 16)
    bits = (
        prod[..., None].astype(jnp.uint32) >> jnp.arange(16, dtype=jnp.uint32)
    ) & 1
    # (..., r, k, i, b) → (..., k, i, r, b) → (..., k*16, r*16)
    A = jnp.moveaxis(bits, -4, -2)
    return A.reshape(*M.shape[:-2], k * 16, r * 16).astype(jnp.int8)


def bytes_to_symbol_bits(x):
    """uint8 (..., k, B) shards → int8 bits (..., B//2, k*16).

    Symbols are u16 from little-endian byte pairs along the shard; B must be
    even.  Output layout matches :func:`gf_matrix_to_bits`.
    """
    import jax.numpy as jnp

    *lead, k, B = x.shape
    sym = x.reshape(*lead, k, B // 2, 2)
    lo = sym[..., 0]
    hi = sym[..., 1]
    bits_lo = (lo[..., None] >> jnp.arange(8, dtype=jnp.uint8)) & 1
    bits_hi = (hi[..., None] >> jnp.arange(8, dtype=jnp.uint8)) & 1
    bits = jnp.concatenate([bits_lo, bits_hi], axis=-1)  # (..., k, B/2, 16)
    bits = jnp.swapaxes(bits, -3, -2)  # (..., B/2, k, 16)
    return bits.reshape(*lead, B // 2, k * 16).astype(jnp.int8)


def symbol_bits_to_bytes(bits, r: int):
    """int (..., B//2, r*16) bits → uint8 (..., r, B)."""
    import jax.numpy as jnp

    *lead, half, _ = bits.shape
    b = bits.reshape(*lead, half, r, 16).astype(jnp.uint8)
    w8 = jnp.left_shift(jnp.uint8(1), jnp.arange(8, dtype=jnp.uint8))
    lo = (b[..., :8] * w8).sum(axis=-1).astype(jnp.uint8)
    hi = (b[..., 8:] * w8).sum(axis=-1).astype(jnp.uint8)
    sym = jnp.stack([lo, hi], axis=-1)  # (..., B/2, r, 2)
    sym = jnp.swapaxes(sym, -3, -2)  # (..., r, B/2, 2)
    return sym.reshape(*lead, r, half * 2)


def gf_apply_bitmatrix(data, bitmat):
    """Apply a constant GF(2^16) matrix to shard bytes on device.

    data: uint8 (..., k, B) with even B; bitmat from
    :func:`gf_matrix_to_bits` of shape (k*16, r*16).
    Returns uint8 (..., r, B).
    """
    import jax.numpy as jnp

    dbits = bytes_to_symbol_bits(data)
    obits = jnp.matmul(dbits, bitmat, preferred_element_type=jnp.int32) & 1
    r = bitmat.shape[-1] // 16  # last axis: bitmat may carry batch dims
    return symbol_bits_to_bytes(obits, r)

"""Batched TPU kernels (jnp/XLA) for the protocol hot path, with host
(numpy) oracles.

- ``gf256`` — GF(2^8) arithmetic (poly 0x11D, generator 2, matching the
  ``reed-solomon-erasure`` crate's field) and the bit-plane lowering that
  turns GF(2^8) matmuls into single MXU int8 matmuls.
- ``rs`` — systematic Vandermonde Reed–Solomon erasure coding
  (encode/reconstruct/verify) used by reliable broadcast.
- ``keccak`` — batched keccak-f[1600] / SHA3-256 on uint32 lane halves.
- ``merkle`` — Merkle trees over SHA3-256 digests with batched build/verify.
"""

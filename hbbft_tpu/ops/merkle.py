"""Merkle trees over SHA3-256 digests.

Mirrors the reference's ``src/broadcast/merkle.rs`` (``MerkleTree::from_vec``,
``Proof { value, index, root_hash, lemma }``): the RBC proposer commits to the
N erasure-coded shards with a Merkle root; each ``Value``/``Echo`` message
carries one shard plus its inclusion proof.

Tree shape: leaves are ``sha3_256(value)``; at every level pairs hash to
``sha3_256(left || right)`` and an odd trailing node is carried up unchanged.
This exactly determines the root for any leaf count (no power-of-two padding),
and gives ⌈log2⌉-length proofs.

Host path: bytes + hashlib.  Device path: batched build over
(... × n_leaves × leaf_bytes) arrays and batched proof verification, for the
array-mode simulator.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

from hbbft_tpu.ops.keccak import sha3_256_host

Digest = bytes  # 32 bytes

# Below this many total bytes the per-call overhead of the native batch
# hasher beats its 4-way SIMD win; small trees stay on hashlib.
_BATCH_MIN_BYTES = 2048

_batch_fn = None
_batch_checked = False


def _sha3_batch():
    """Native equal-length batch hasher ((n, L) uint8 → (n, 32)), or None."""
    global _batch_fn, _batch_checked
    if not _batch_checked:
        _batch_checked = True
        try:
            from hbbft_tpu.native.oracle import get_oracle

            _batch_fn = get_oracle().sha3_256_batch
        except Exception:
            _batch_fn = None
    return _batch_fn


def _hash_rows(arr: np.ndarray) -> List[Digest]:
    """Digest every row of a contiguous (n, L) uint8 array, batched."""
    batch = _sha3_batch()
    if batch is not None and arr.size >= _BATCH_MIN_BYTES:
        dig = batch(arr)
        return [dig[i].tobytes() for i in range(arr.shape[0])]
    return [sha3_256_host(arr[i].tobytes()) for i in range(arr.shape[0])]


def _leaf_digests(values: Sequence[bytes]) -> List[Digest]:
    """Leaf hashing: equal-length leaf sets go through the batch hasher
    (commitment cost scales with bytes, not leaves); ragged sets fall back
    to per-leaf hashlib."""
    n = len(values)
    if n >= 2:
        L = len(values[0])
        if L > 0 and n * L >= _BATCH_MIN_BYTES and all(
            len(v) == L for v in values
        ) and _sha3_batch() is not None:
            arr = np.empty((n, L), dtype=np.uint8)
            for i, v in enumerate(values):
                arr[i] = np.frombuffer(v, dtype=np.uint8)
            dig = _sha3_batch()(arr)
            return [dig[i].tobytes() for i in range(n)]
    return [sha3_256_host(v) for v in values]


@dataclass(frozen=True)
class Proof:
    """Inclusion proof for ``value`` at ``index`` under ``root_hash``.

    ``path`` lists (sibling_digest, sibling_on_left) from leaf level up;
    levels where the node had no sibling (odd carry) are skipped.
    Reference: ``src/broadcast/merkle.rs :: Proof``.
    """

    value: bytes
    index: int
    root_hash: Digest
    path: Tuple[Tuple[Digest, bool], ...]

    def __getstate__(self):
        # zero-copy proofs hold memoryview leaves, which neither pickle
        # nor deepcopy; snapshots materialize the slice here — the one
        # cold path where the copy is the point
        state = dict(self.__dict__)
        if isinstance(state["value"], memoryview):
            state["value"] = bytes(state["value"])
        return state

    def __setstate__(self, state) -> None:
        self.__dict__.update(state)  # bypasses the frozen __setattr__

    def validate(self, n_leaves: int) -> bool:
        """Check the proof against its own root (and index bounds).

        Reference: ``Proof::validate``.
        """
        if not 0 <= self.index < n_leaves:
            return False
        h = sha3_256_host(self.value)
        idx, width = self.index, n_leaves
        path = list(self.path)
        while width > 1:
            if (idx ^ 1) < width:  # this level has a sibling
                if not path:
                    return False
                sibling, sib_left = path.pop(0)
                if sib_left != (idx % 2 == 1):
                    return False
                h = (
                    sha3_256_host(sibling + h)
                    if sib_left
                    else sha3_256_host(h + sibling)
                )
            idx //= 2
            width = (width + 1) // 2
        return not path and h == self.root_hash


class MerkleTree:
    """Reference: ``src/broadcast/merkle.rs :: MerkleTree``."""

    def __init__(self, values: Sequence[bytes]):
        if not values:
            raise ValueError("MerkleTree needs at least one leaf")
        # bytes and memoryview leaves are stored as-is (memoryview slices of
        # one shared buffer make the proposer path zero-copy); anything else
        # is converted once, and the conversion count is exposed so the
        # hot-path test can assert the pipeline stays copy-free
        self.values: List[bytes] = []
        self.leaf_copies = 0
        for v in values:
            if not isinstance(v, (bytes, memoryview)):
                v = bytes(v)
                self.leaf_copies += 1
            self.values.append(v)
        self.levels: List[List[Digest]] = self._build_levels(
            _leaf_digests(self.values)
        )

    @staticmethod
    def _build_levels(level0: List[Digest]) -> List[List[Digest]]:
        levels = [level0]
        while len(levels[-1]) > 1:
            prev = levels[-1]
            pairs = len(prev) // 2
            if pairs * 64 >= _BATCH_MIN_BYTES and _sha3_batch() is not None:
                buf = np.frombuffer(
                    b"".join(prev[: 2 * pairs]), dtype=np.uint8
                ).reshape(pairs, 64)
                nxt = _hash_rows(buf)
            else:
                nxt = [
                    sha3_256_host(prev[i] + prev[i + 1])
                    for i in range(0, len(prev) - 1, 2)
                ]
            if len(prev) % 2 == 1:
                nxt.append(prev[-1])  # odd carry
            levels.append(nxt)
        return levels

    @classmethod
    def from_vec(cls, values: Sequence[bytes]) -> "MerkleTree":
        return cls(values)

    @classmethod
    def from_shards(
        cls, arr: np.ndarray, leaves: Sequence[bytes]
    ) -> "MerkleTree":
        """Build from a contiguous (n, B) uint8 shard array without copying.

        ``arr`` feeds the batch hasher directly; ``leaves`` supplies the
        per-shard buffers stored as proof values (typically memoryview
        slices of ONE bytes object over the same shard data) — the encode →
        commit path of :mod:`hbbft_tpu.protocols.broadcast` touches each
        shard byte exactly once here.
        """
        n, B = arr.shape
        if n != len(leaves) or any(len(v) != B for v in leaves):
            raise ValueError("leaves must mirror the shard array")
        tree = cls.__new__(cls)
        tree.values = list(leaves)
        tree.leaf_copies = 0
        tree.levels = cls._build_levels(_hash_rows(arr))
        return tree

    def root_hash(self) -> Digest:
        return self.levels[-1][0]

    def proof(self, index: int) -> Optional[Proof]:
        if not 0 <= index < len(self.values):
            return None
        path = []
        idx = index
        for level in self.levels[:-1]:
            sib = idx ^ 1
            if sib < len(level):
                path.append((level[sib], sib < idx))
            idx //= 2
        return Proof(
            value=self.values[index],
            index=index,
            root_hash=self.root_hash(),
            path=tuple(path),
        )


# ---------------------------------------------------------------------------
# Device (batched) path
# ---------------------------------------------------------------------------


def merkle_build_jax(leaves):
    """Batched tree build.

    leaves: uint8 (..., n, leaf_bytes) → (root (..., 32),
    proof_digests (..., n, depth, 32), proof_mask (depth,) per-level
    has-sibling bools per leaf as (..., n, depth) int8).

    The per-level structure (odd carries) is static given n, so everything
    jits to fixed shapes.  Proof layout matches :class:`Proof`: level order
    leaf→root, missing-sibling levels masked out.
    """
    import jax.numpy as jnp

    from hbbft_tpu.ops.keccak import sha3_256

    n = leaves.shape[-2]
    level = sha3_256(leaves)  # (..., n, 32)
    depth = 0
    w = n
    while w > 1:
        depth += 1
        w = (w + 1) // 2

    batch = leaves.shape[:-2]
    proof = jnp.zeros((*batch, n, max(depth, 1), 32), dtype=jnp.uint8)
    mask = jnp.zeros((n, max(depth, 1)), dtype=jnp.int8)

    idx = list(range(n))  # leaf → current node position at this level
    width = n
    d = 0
    while width > 1:
        import numpy as _np

        pos = _np.asarray(idx)
        sib = pos ^ 1
        has = sib < width
        # record sibling digest for each original leaf
        sib_digest = jnp.take(level, jnp.asarray(_np.where(has, sib, pos)), axis=-2)
        proof = proof.at[..., :, d, :].set(
            jnp.where(jnp.asarray(has)[..., None], sib_digest, 0)
        )
        mask = mask.at[:, d].set(jnp.asarray(has, dtype=jnp.int8))
        # next level
        pairs = width // 2
        left = level[..., 0 : 2 * pairs : 2, :]
        right = level[..., 1 : 2 * pairs : 2, :]
        parents = sha3_256(jnp.concatenate([left, right], axis=-1))
        if width % 2 == 1:
            parents = jnp.concatenate([parents, level[..., -1:, :]], axis=-2)
        level = parents
        idx = [i // 2 for i in idx]
        width = (width + 1) // 2
        d += 1
    root = level[..., 0, :]
    return root, proof, mask


def merkle_root_jax(leaves):
    """Root only — no proof/mask materialization.

    leaves: uint8 (..., n, leaf_bytes) → (..., 32).  At N = 4096 the full
    proof tensor of :func:`merkle_build_jax` is (P, n, 12, 32) ≈ gigabytes;
    root checks (the batched simulator's re-encode verification) only need
    this."""
    import jax.numpy as jnp

    from hbbft_tpu.ops.keccak import sha3_256

    level = sha3_256(leaves)  # (..., n, 32)
    width = leaves.shape[-2]
    while width > 1:
        pairs = width // 2
        left = level[..., 0 : 2 * pairs : 2, :]
        right = level[..., 1 : 2 * pairs : 2, :]
        parents = sha3_256(jnp.concatenate([left, right], axis=-1))
        if width % 2 == 1:
            parents = jnp.concatenate([parents, level[..., -1:, :]], axis=-2)
        level = parents
        width = (width + 1) // 2
    return level[..., 0, :]


def merkle_verify_jax(values, indices, roots, proofs, mask):
    """Batched proof verification.

    values: uint8 (..., leaf_bytes); indices: int32 (...,);
    roots: (..., 32); proofs: (..., depth, 32); mask: (..., depth) int8.
    Returns bool (...,).
    """
    import jax.numpy as jnp

    from hbbft_tpu.ops.keccak import sha3_256

    h = sha3_256(values)
    idx = indices
    depth = proofs.shape[-2]
    for d in range(depth):
        sib = proofs[..., d, :]
        has = mask[..., d].astype(bool)
        is_right = (idx % 2).astype(bool)  # we are the right child → sib on left
        cat_l = jnp.concatenate([sib, h], axis=-1)
        cat_r = jnp.concatenate([h, sib], axis=-1)
        hashed = sha3_256(jnp.where(is_right[..., None], cat_l, cat_r))
        h = jnp.where(has[..., None], hashed, h)
        idx = idx // 2  # odd-carry nodes also halve their position per level
    return jnp.all(h == roots, axis=-1)

"""Systematic Vandermonde Reed–Solomon erasure coding over GF(2^8).

Mirrors the semantics of the ``reed-solomon-erasure`` crate used by the
reference's reliable broadcast (``src/broadcast/broadcast.rs :: send_shards``
encodes a value into N shards: data = N−2f, parity = 2f; receivers
``reconstruct`` from any ``data`` surviving shards and re-encode to verify the
Merkle root).  Same construction as that crate (a Backblaze-style port):
encode matrix = Vandermonde(total, data) normalised by the inverse of its top
data×data block, so the first ``data`` rows are the identity (systematic).

Host path: numpy tables.  Device path: constant-matrix application via the
bit-plane MXU matmul in :mod:`hbbft_tpu.ops.gf256`, batched over arbitrary
leading axes (node × instance × epoch).
"""

from __future__ import annotations

import functools
from typing import List, Optional, Sequence, Tuple

import numpy as np

from hbbft_tpu.ops import gf256


class ReedSolomon:
    """``ReedSolomon::new(data_shards, parity_shards)`` equivalent.

    ``parity_shards == 0`` degrades to the reference's ``Coding::Trivial``
    (identity coding) used when f = 0.
    """

    def __init__(self, data_shards: int, parity_shards: int):
        if data_shards < 1:
            raise ValueError("data_shards must be >= 1")
        if data_shards + parity_shards > 256:
            raise ValueError("total shards must be <= 256 over GF(2^8)")
        self.data_shards = data_shards
        self.parity_shards = parity_shards
        self.total_shards = data_shards + parity_shards
        # Systematic encode matrix: top block identity, bottom parity rows.
        V = gf256.vandermonde(self.total_shards, data_shards)
        top_inv = gf256.gf_inv_matrix_np(V[:data_shards])
        self.matrix = gf256.gf_matmul_np(V, top_inv)  # (total, data)
        assert np.array_equal(
            self.matrix[:data_shards], np.eye(data_shards, dtype=np.uint8)
        )
        self.parity_matrix = self.matrix[data_shards:]  # (parity, data)
        self._parity_bits = gf256.gf_matrix_to_bits(self.parity_matrix)
        self._decode_cache = {}

    # ------------------------------------------------------------------ host
    def encode_np(self, data: np.ndarray) -> np.ndarray:
        """data (data_shards, B) uint8 → all shards (total_shards, B)."""
        data = np.asarray(data, dtype=np.uint8)
        assert data.shape[0] == self.data_shards
        if self.parity_shards == 0:
            return data.copy()
        parity = gf256.gf_matmul_np(self.parity_matrix, data)
        return np.concatenate([data, parity], axis=0)

    def verify_np(self, shards: np.ndarray) -> bool:
        """True iff parity shards are consistent with data shards."""
        shards = np.asarray(shards, dtype=np.uint8)
        return bool(np.array_equal(self.encode_np(shards[: self.data_shards]), shards))

    def reconstruct_np(
        self, shards: Sequence[Optional[bytes]]
    ) -> List[bytes]:
        """Fill in missing (None) shards; needs ≥ data_shards present.

        Mirrors ``ReedSolomon::reconstruct(&mut Vec<Option<_>>)``.
        """
        def decode(sub, use):
            dec = self._decode_matrix(tuple(use))
            data = gf256.gf_matmul_np(dec, sub)
            return (
                gf256.gf_matmul_np(self.matrix, data)
                if self.parity_shards else data
            )

        return _reconstruct_optional(self, shards, decode)

    def _decode_matrix(self, use: Tuple[int, ...]) -> np.ndarray:
        """Inverse of the encode-matrix rows for the surviving shard set."""
        if use not in self._decode_cache:
            sub = self.matrix[list(use)]  # (data, data)
            self._decode_cache[use] = gf256.gf_inv_matrix_np(sub)
        return self._decode_cache[use]

    # ---------------------------------------------------------------- device
    def encode_jax(self, data):
        """Batched device encode.

        data: uint8 (..., data_shards, B) → (..., total_shards, B).
        Lowered to one int8 MXU matmul via the bit-plane trick.
        """
        import jax.numpy as jnp

        if self.parity_shards == 0:
            return data
        # (..., k, B) → (..., B, k) for the symbol-contraction layout.
        d = jnp.swapaxes(data, -1, -2)
        parity = gf256.gf_apply_bitmatrix(d, jnp.asarray(self._parity_bits))
        parity = jnp.swapaxes(parity, -1, -2)  # (..., parity, B)
        return jnp.concatenate([data, parity], axis=-2)

    def decode_bits(self, use: Tuple[int, ...]) -> np.ndarray:
        """Constant bit-matrix reconstructing data shards from rows ``use``."""
        return gf256.gf_matrix_to_bits(self._decode_matrix(tuple(use)))

    def reconstruct_jax(self, survivors, use: Tuple[int, ...]):
        """Batched device reconstruct for one survivor pattern.

        survivors: uint8 (..., data_shards, B) — the shards at indices
        ``use`` (in that order).  Returns (..., data_shards, B) data shards.
        """
        import jax.numpy as jnp

        s = jnp.swapaxes(survivors, -1, -2)
        data = gf256.gf_apply_bitmatrix(s, jnp.asarray(self.decode_bits(use)))
        return jnp.swapaxes(data, -1, -2)


class ReedSolomon16:
    """Systematic Vandermonde RS over GF(2^16) — for N > 256 networks.

    Same construction as :class:`ReedSolomon` in the 65536-element field
    (shard symbols are u16 little-endian byte pairs; shard length must be
    even).  Exposes the subset of the API the batched large-N simulator
    uses: host encode, device encode, host reconstruct.
    """

    def __init__(self, data_shards: int, parity_shards: int):
        import os

        from hbbft_tpu.ops import gf16

        if data_shards < 1:
            raise ValueError("data_shards must be >= 1")
        if data_shards + parity_shards > (1 << 16):
            raise ValueError("total shards must be <= 65536 over GF(2^16)")
        self.gf = gf16
        self.data_shards = data_shards
        self.parity_shards = parity_shards
        self.total_shards = data_shards + parity_shards
        # The systematic-matrix construction is O(total·data²) host table
        # lookups — ~10 minutes at the N=4096 network shape — so it is
        # cached on disk (the 4096-shard matrix is ~11 MB).
        cache_dir = os.path.join(
            os.path.expanduser("~"), ".cache", "hbbft_tpu"
        )
        cache = os.path.join(
            cache_dir, f"rs16_{data_shards}_{parity_shards}.npz"
        )
        if os.path.exists(cache):
            self.matrix = np.load(cache)["matrix"]
        else:
            V = gf16.vandermonde(self.total_shards, data_shards)
            top_inv = gf16.gf_inv_matrix_np(V[:data_shards])
            self.matrix = gf16.gf_matmul_np(V, top_inv)
            try:
                os.makedirs(cache_dir, exist_ok=True)
                np.savez_compressed(cache, matrix=self.matrix)
            except OSError:
                pass
        assert np.array_equal(
            self.matrix[:data_shards],
            np.eye(data_shards, dtype=np.uint16),
        )
        self.parity_matrix = self.matrix[data_shards:]
        self._parity_bits = gf16.gf_matrix_to_bits(self.parity_matrix)
        self._decode_cache = {}

    def _to_symbols(self, shards: np.ndarray) -> np.ndarray:
        k, B = shards.shape[-2:]
        assert B % 2 == 0, "GF(2^16) shards need even byte length"
        s = shards.reshape(*shards.shape[:-1], B // 2, 2).astype(np.uint16)
        return s[..., 0] | (s[..., 1] << 8)

    def _from_symbols(self, sym: np.ndarray) -> np.ndarray:
        lo = (sym & 0xFF).astype(np.uint8)
        hi = (sym >> 8).astype(np.uint8)
        return np.stack([lo, hi], axis=-1).reshape(
            *sym.shape[:-1], sym.shape[-1] * 2
        )

    def encode_np(self, data: np.ndarray) -> np.ndarray:
        data = np.asarray(data, dtype=np.uint8)
        assert data.shape[0] == self.data_shards
        if self.parity_shards == 0:
            return data.copy()
        D = self._to_symbols(data)
        parity = self.gf.gf_matmul_np(self.parity_matrix, D)
        return np.concatenate([data, self._from_symbols(parity)], axis=0)

    def encode_jax(self, data, parity_bits=None):
        """uint8 (..., data_shards, B) → (..., total_shards, B), B even.

        ``parity_bits`` lets callers pass the (large — ~1 GB at the N=4096
        shape) bit matrix as a traced ARGUMENT; capturing it as a jit
        constant embeds it in the serialized HLO, which breaks the remote
        compile transport in this environment."""
        import jax.numpy as jnp

        if self.parity_shards == 0:
            return data
        if parity_bits is None:
            parity_bits = jnp.asarray(self._parity_bits)
        parity = self.gf.gf_apply_bitmatrix(data, parity_bits)
        return jnp.concatenate([data, parity], axis=-2)

    def decode_matrix(self, use: Tuple[int, ...]) -> np.ndarray:
        if use not in self._decode_cache:
            sub = self.matrix[list(use)]
            self._decode_cache[use] = self.gf.gf_inv_matrix_np(sub)
        return self._decode_cache[use]

    def reconstruct_data_np(
        self, survivors: np.ndarray, use: Tuple[int, ...]
    ) -> np.ndarray:
        """(data, B) data shards from the survivor rows ``use``."""
        dec = self.decode_matrix(tuple(use))
        S = self._to_symbols(np.asarray(survivors, dtype=np.uint8))
        return self._from_symbols(self.gf.gf_matmul_np(dec, S))

    def reconstruct_np(
        self, shards: Sequence[Optional[bytes]]
    ) -> List[bytes]:
        """Fill in missing (None) shards; needs ≥ data_shards present.

        Same contract as :meth:`ReedSolomon.reconstruct_np` — the
        object-mode ``Broadcast`` decode path calls this, so the GF(2^16)
        coder must offer it too (found by the round-5 large-N masked
        property sweep: object mode at N > 256 previously had no erasure
        reconstruction at all)."""
        def decode(sub, use):
            return self.encode_np(self.reconstruct_data_np(sub, use))

        return _reconstruct_optional(self, shards, decode, even_len=True)


def _reconstruct_optional(coder, shards, decode, even_len: bool = False):
    """Shared fill-in-missing-shards driver for both coders.

    ``decode(sub, use) -> full`` rebuilds all shards from the first
    data_shards survivors; validation (counts, lengths, the GF(2^16)
    even-length requirement) lives here exactly once.
    """
    present = [i for i, s in enumerate(shards) if s is not None]
    if len(present) < coder.data_shards:
        raise ValueError(
            f"too few shards: {len(present)} < {coder.data_shards}"
        )
    if len(shards) != coder.total_shards:
        raise ValueError("wrong shard count")
    shard_len = len(shards[present[0]])
    if (even_len and shard_len % 2) or any(
        len(shards[i]) != shard_len for i in present
    ):
        raise ValueError("inconsistent/odd shard lengths")
    use = tuple(present[: coder.data_shards])
    sub = np.stack(
        [np.frombuffer(shards[i], dtype=np.uint8) for i in use]
    )
    full = decode(sub, use)
    out: List[bytes] = []
    for i in range(coder.total_shards):
        if shards[i] is not None:
            out.append(bytes(shards[i]))
        else:
            out.append(full[i].tobytes())
    return out


@functools.lru_cache(maxsize=256)
def for_n_f(n: int, f: int):
    """The RBC coder for an (n, f) network: data = n−2f, parity = 2f.

    GF(2^8) (bit-exact with the reference's crate) up to 256 shards; the
    GF(2^16) coder beyond — the reference cannot represent such networks
    at all (its erasure field caps shards at 256)."""
    if n <= 256:
        return ReedSolomon(n - 2 * f, 2 * f)
    return ReedSolomon16(n - 2 * f, 2 * f)

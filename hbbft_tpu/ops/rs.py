"""Systematic Vandermonde Reed–Solomon erasure coding over GF(2^8).

Mirrors the semantics of the ``reed-solomon-erasure`` crate used by the
reference's reliable broadcast (``src/broadcast/broadcast.rs :: send_shards``
encodes a value into N shards: data = N−2f, parity = 2f; receivers
``reconstruct`` from any ``data`` surviving shards and re-encode to verify the
Merkle root).  Same construction as that crate (a Backblaze-style port):
encode matrix = Vandermonde(total, data) normalised by the inverse of its top
data×data block, so the first ``data`` rows are the identity (systematic).

Host path: numpy tables.  Device path: constant-matrix application via the
bit-plane MXU matmul in :mod:`hbbft_tpu.ops.gf256`, batched over arbitrary
leading axes (node × instance × epoch).
"""

from __future__ import annotations

import functools
import os
from typing import List, Optional, Sequence, Tuple

import numpy as np

from hbbft_tpu.ops import gf256

# ---------------------------------------------------------------------------
# Erasure backend switch (mirrors the HBBFT_ENCRYPT_BACKEND roofline pattern)
# ---------------------------------------------------------------------------
#
# HBBFT_ERASURE_BACKEND selects the host encode/decode engine:
#   native — AVX2 pshufb nibble tables over the CACHED matrix (gf256.cpp)
#   numpy  — cached bitmatrix-XOR schedule (packed bit-planes, CSE, tiling)
#   jax    — the bit-plane MXU matmul (device roofline path)
#   auto   — native when the oracle library loads, else numpy (default)
#
# All backends are byte-identical (pinned by tests/test_rs_backends.py);
# the switch trades setup cost against per-byte throughput.

_BACKENDS = ("auto", "native", "numpy", "jax")

# Per-backend work counters (bytes = shard bytes produced).  Plain ints —
# this module sits in the determinism-lint scope, so no clocks here; the
# runtime snapshots these into hbbft_rbc_* metrics.
STATS = {b: {"calls": 0, "bytes": 0} for b in ("native", "numpy", "jax")}


def stats_snapshot():
    """Copy of the per-backend encode/decode work counters."""
    return {b: dict(v) for b, v in STATS.items()}


# Decode-side artifacts are keyed by (matrix, erasure-pattern): the matrix
# identity is the per-coder cache instance, the pattern is the key tuple.
# The pattern space is C(n, f) — unbounded dicts would grow without limit
# under adversarial erasure churn, so every per-coder cache is a small LRU.
_DECODE_CACHE_MAX = 512

# Above this many matrix columns the numpy path skips the XOR-schedule
# compile (its greedy CSE scans all operand pairs per output row —
# quadratic in the bit-matrix density) and keeps the cached table matmul;
# the inversion cache is the dominant win at those shapes anyway.
_SCHED_MAX_COLS = 64


class _Lru:
    """Tiny insertion-ordered LRU for per-coder compiled artifacts
    (decode matrices, XOR schedules, bit matrices).  ``get`` refreshes
    recency; ``put`` returns the value and evicts the oldest entries
    beyond ``maxsize``."""

    __slots__ = ("_d", "maxsize")

    def __init__(self, maxsize: int = _DECODE_CACHE_MAX):
        from collections import OrderedDict

        self._d = OrderedDict()
        self.maxsize = maxsize

    def get(self, key):
        v = self._d.get(key)
        if v is not None:
            self._d.move_to_end(key)
        return v

    def put(self, key, value):
        self._d[key] = value
        self._d.move_to_end(key)
        while len(self._d) > self.maxsize:
            self._d.popitem(last=False)
        return value

    def __len__(self):
        return len(self._d)

    def __contains__(self, key):
        return key in self._d


_native_oracle = None
_native_checked = False


def _native():
    """The ctypes oracle, or None when the library can't build/load."""
    global _native_oracle, _native_checked
    if not _native_checked:
        _native_checked = True
        try:
            from hbbft_tpu.native.oracle import get_oracle

            _native_oracle = get_oracle()
        except Exception:
            _native_oracle = None
    return _native_oracle


def resolve_backend() -> str:
    """The effective erasure backend for this process."""
    mode = os.environ.get("HBBFT_ERASURE_BACKEND", "auto")
    if mode not in _BACKENDS:
        raise ValueError(
            f"HBBFT_ERASURE_BACKEND={mode!r}: want one of {_BACKENDS}"
        )
    if mode == "auto":
        return "native" if _native() is not None else "numpy"
    if mode == "native" and _native() is None:
        raise RuntimeError(
            "HBBFT_ERASURE_BACKEND=native but the oracle library "
            "failed to build/load"
        )
    return mode


class ReedSolomon:
    """``ReedSolomon::new(data_shards, parity_shards)`` equivalent.

    ``parity_shards == 0`` degrades to the reference's ``Coding::Trivial``
    (identity coding) used when f = 0.
    """

    def __init__(self, data_shards: int, parity_shards: int):
        if data_shards < 1:
            raise ValueError("data_shards must be >= 1")
        if data_shards + parity_shards > 256:
            raise ValueError("total shards must be <= 256 over GF(2^8)")
        self.data_shards = data_shards
        self.parity_shards = parity_shards
        self.total_shards = data_shards + parity_shards
        # Systematic encode matrix: top block identity, bottom parity rows.
        V = gf256.vandermonde(self.total_shards, data_shards)
        top_inv = gf256.gf_inv_matrix_np(V[:data_shards])
        self.matrix = gf256.gf_matmul_np(V, top_inv)  # (total, data)
        assert np.array_equal(
            self.matrix[:data_shards], np.eye(data_shards, dtype=np.uint8)
        )
        self.parity_matrix = self.matrix[data_shards:]  # (parity, data)
        self._parity_bits = gf256.gf_matrix_to_bits(self.parity_matrix)
        self._decode_cache = _Lru()
        # per-matrix compiled artifacts, built lazily ONCE and reused for
        # every call (the old path rebuilt its gather indices per call):
        # key → XorSchedule (numpy backend) / bit matrix (jax backend);
        # decode-side keys carry the erasure pattern, so all three caches
        # are LRU-bounded (see _DECODE_CACHE_MAX)
        self._sched_cache = _Lru()
        self._bits_cache = _Lru()

    # ------------------------------------------------------------------ host
    def encode_np(self, data: np.ndarray) -> np.ndarray:
        """data (data_shards, B) uint8 → all shards (total_shards, B)."""
        data = np.asarray(data, dtype=np.uint8)
        assert data.shape[0] == self.data_shards
        if self.parity_shards == 0:
            return data.copy()
        out = np.empty(
            (self.total_shards, data.shape[1]), dtype=np.uint8
        )
        out[: self.data_shards] = data
        self.encode_into(out)
        return out

    def encode_into(self, shards: np.ndarray) -> np.ndarray:
        """Fill parity rows of a contiguous (total, B) buffer in place.

        The zero-copy encode primitive: data rows are already where they
        belong, parity is written into the tail of the same allocation, so
        the full shard set exists in ONE buffer with no concatenate.
        """
        assert shards.shape[0] == self.total_shards
        if self.parity_shards:
            self._apply_matrix(
                ("parity",),
                self.parity_matrix,
                shards[: self.data_shards],
                out=shards[self.data_shards:],
            )
        return shards

    def verify_np(self, shards: np.ndarray) -> bool:
        """True iff parity shards are consistent with data shards."""
        shards = np.asarray(shards, dtype=np.uint8)
        return bool(np.array_equal(self.encode_np(shards[: self.data_shards]), shards))

    def reconstruct_np(
        self, shards: Sequence[Optional[bytes]]
    ) -> List[bytes]:
        """Fill in missing (None) shards; needs ≥ data_shards present.

        Mirrors ``ReedSolomon::reconstruct(&mut Vec<Option<_>>)``.
        """
        def decode(sub, use):
            dec = self._decode_matrix(tuple(use))
            data = self._apply_matrix(("dec", tuple(use)), dec, sub)
            return (
                self._apply_matrix(("full",), self.matrix, data)
                if self.parity_shards else data
            )

        return _reconstruct_optional(self, shards, decode)

    def _decode_matrix(self, use: Tuple[int, ...]) -> np.ndarray:
        """Inverse of the encode-matrix rows for the surviving shard set —
        the survivor-pattern Gauss–Jordan, LRU-cached per pattern."""
        dec = self._decode_cache.get(use)
        if dec is None:
            sub = self.matrix[list(use)]  # (data, data)
            dec = self._decode_cache.put(use, gf256.gf_inv_matrix_np(sub))
        return dec

    def reconstruct_data_np(
        self, survivors: np.ndarray, use: Tuple[int, ...]
    ) -> np.ndarray:
        """(data, B) data shards from the survivor rows ``use`` — same
        contract as :meth:`ReedSolomon16.reconstruct_data_np`; both the
        inversion and the compiled apply are pattern-cached."""
        dec = self._decode_matrix(tuple(use))
        return self._apply_matrix(("dec", tuple(use)), dec, survivors)

    def _apply_matrix(self, key, matrix, data, out=None):
        """Backend-dispatched constant-matrix apply with cached artifacts.

        ``key`` identifies the matrix in the per-coder caches (the matrix
        itself is never rebuilt, and neither is its compiled form).
        """
        data = np.ascontiguousarray(data, dtype=np.uint8)
        backend = resolve_backend()
        if backend == "native":
            out = _native().gf_matmul_simd(matrix, data, out=out)
        elif backend == "jax":
            import jax.numpy as jnp

            bits = self._bits_cache.get(key)
            if bits is None:
                bits = self._bits_cache.put(
                    key, gf256.gf_matrix_to_bits(matrix)
                )
            res = np.asarray(
                gf256.gf_apply_bitmatrix(data.T, jnp.asarray(bits))
            ).T
            if out is None:
                out = np.ascontiguousarray(res)
            else:
                out[:] = res
        else:
            sched = self._sched_cache.get(key)
            if sched is None:
                sched = self._sched_cache.put(
                    key,
                    gf256.build_xor_schedule(gf256.gf_matrix_to_bits(matrix)),
                )
            out = gf256.apply_xor_schedule(sched, data, out=out)
        s = STATS[backend]
        s["calls"] += 1
        s["bytes"] += int(out.shape[0]) * int(out.shape[1])
        return out

    # ---------------------------------------------------------------- device
    def encode_jax(self, data):
        """Batched device encode.

        data: uint8 (..., data_shards, B) → (..., total_shards, B).
        Lowered to one int8 MXU matmul via the bit-plane trick.
        """
        import jax.numpy as jnp

        if self.parity_shards == 0:
            return data
        # (..., k, B) → (..., B, k) for the symbol-contraction layout.
        d = jnp.swapaxes(data, -1, -2)
        parity = gf256.gf_apply_bitmatrix(d, jnp.asarray(self._parity_bits))
        parity = jnp.swapaxes(parity, -1, -2)  # (..., parity, B)
        return jnp.concatenate([data, parity], axis=-2)

    def decode_bits(self, use: Tuple[int, ...]) -> np.ndarray:
        """Constant bit-matrix reconstructing data shards from rows ``use``."""
        return gf256.gf_matrix_to_bits(self._decode_matrix(tuple(use)))

    def reconstruct_jax(self, survivors, use: Tuple[int, ...]):
        """Batched device reconstruct for one survivor pattern.

        survivors: uint8 (..., data_shards, B) — the shards at indices
        ``use`` (in that order).  Returns (..., data_shards, B) data shards.
        """
        import jax.numpy as jnp

        s = jnp.swapaxes(survivors, -1, -2)
        data = gf256.gf_apply_bitmatrix(s, jnp.asarray(self.decode_bits(use)))
        return jnp.swapaxes(data, -1, -2)


class ReedSolomon16:
    """Systematic Vandermonde RS over GF(2^16) — for N > 256 networks.

    Same construction as :class:`ReedSolomon` in the 65536-element field
    (shard symbols are u16 little-endian byte pairs; shard length must be
    even).  Exposes the subset of the API the batched large-N simulator
    uses: host encode, device encode, host reconstruct.
    """

    def __init__(self, data_shards: int, parity_shards: int):
        import os

        from hbbft_tpu.ops import gf16

        if data_shards < 1:
            raise ValueError("data_shards must be >= 1")
        if data_shards + parity_shards > (1 << 16):
            raise ValueError("total shards must be <= 65536 over GF(2^16)")
        self.gf = gf16
        self.data_shards = data_shards
        self.parity_shards = parity_shards
        self.total_shards = data_shards + parity_shards
        # The systematic-matrix construction is O(total·data²) host table
        # lookups — ~10 minutes at the N=4096 network shape — so it is
        # cached on disk (the 4096-shard matrix is ~11 MB).
        cache_dir = os.path.join(
            os.path.expanduser("~"), ".cache", "hbbft_tpu"
        )
        cache = os.path.join(
            cache_dir, f"rs16_{data_shards}_{parity_shards}.npz"
        )
        if os.path.exists(cache):
            self.matrix = np.load(cache)["matrix"]
        else:
            V = gf16.vandermonde(self.total_shards, data_shards)
            top_inv = gf16.gf_inv_matrix_np(V[:data_shards])
            self.matrix = gf16.gf_matmul_np(V, top_inv)
            try:
                os.makedirs(cache_dir, exist_ok=True)
                np.savez_compressed(cache, matrix=self.matrix)
            except OSError:
                pass
        assert np.array_equal(
            self.matrix[:data_shards],
            np.eye(data_shards, dtype=np.uint16),
        )
        self.parity_matrix = self.matrix[data_shards:]
        self._parity_bits = gf16.gf_matrix_to_bits(self.parity_matrix)
        # decode-side artifacts keyed by (matrix, erasure-pattern) — same
        # bounded-LRU policy as the GF(2^8) coder
        self._decode_cache = _Lru()
        self._sched_cache = _Lru()
        self._bits_cache = _Lru()

    def _to_symbols(self, shards: np.ndarray) -> np.ndarray:
        k, B = shards.shape[-2:]
        assert B % 2 == 0, "GF(2^16) shards need even byte length"
        s = shards.reshape(*shards.shape[:-1], B // 2, 2).astype(np.uint16)
        return s[..., 0] | (s[..., 1] << 8)

    def _from_symbols(self, sym: np.ndarray) -> np.ndarray:
        lo = (sym & 0xFF).astype(np.uint8)
        hi = (sym >> 8).astype(np.uint8)
        return np.stack([lo, hi], axis=-1).reshape(
            *sym.shape[:-1], sym.shape[-1] * 2
        )

    def encode_np(self, data: np.ndarray) -> np.ndarray:
        data = np.asarray(data, dtype=np.uint8)
        assert data.shape[0] == self.data_shards
        if self.parity_shards == 0:
            return data.copy()
        D = self._to_symbols(data)
        parity = self.gf.gf_matmul_np(self.parity_matrix, D)
        return np.concatenate([data, self._from_symbols(parity)], axis=0)

    def encode_into(self, shards: np.ndarray) -> np.ndarray:
        """Same in-place contract as :meth:`ReedSolomon.encode_into`."""
        assert shards.shape[0] == self.total_shards
        if self.parity_shards:
            D = self._to_symbols(shards[: self.data_shards])
            parity = self.gf.gf_matmul_np(self.parity_matrix, D)
            shards[self.data_shards:] = self._from_symbols(parity)
        return shards

    def encode_jax(self, data, parity_bits=None):
        """uint8 (..., data_shards, B) → (..., total_shards, B), B even.

        ``parity_bits`` lets callers pass the (large — ~1 GB at the N=4096
        shape) bit matrix as a traced ARGUMENT; capturing it as a jit
        constant embeds it in the serialized HLO, which breaks the remote
        compile transport in this environment."""
        import jax.numpy as jnp

        if self.parity_shards == 0:
            return data
        if parity_bits is None:
            parity_bits = jnp.asarray(self._parity_bits)
        parity = self.gf.gf_apply_bitmatrix(data, parity_bits)
        return jnp.concatenate([data, parity], axis=-2)

    def decode_matrix(self, use: Tuple[int, ...]) -> np.ndarray:
        """Inverse of the encode-matrix rows for the surviving shard set —
        the survivor-pattern Gauss–Jordan, LRU-cached per pattern."""
        dec = self._decode_cache.get(use)
        if dec is None:
            sub = self.matrix[list(use)]
            dec = self._decode_cache.put(use, self.gf.gf_inv_matrix_np(sub))
        return dec

    def _apply_matrix(self, key, matrix, data):
        """Backend-dispatched constant-matrix apply with cached artifacts.

        GF(2^16) twist: the native SIMD kernel is GF(2^8)-only, so
        ``native`` routes to the numpy path here (still byte-identical —
        pinned by tests).  The numpy path compiles the same bitmatrix-XOR
        schedule the GF(2^8) coder uses: a u16 symbol is its two
        little-endian bytes, so a (k, B) shard block becomes (2k, B/2)
        interleaved byte rows (row 2k = low bytes, row 2k+1 = high bytes
        of symbol row k — exactly the ``k*16 + bit`` input numbering of
        :func:`gf16.gf_matrix_to_bits`) and ``apply_xor_schedule`` runs
        verbatim.  Above ``_SCHED_MAX_COLS`` matrix columns the schedule
        compile is skipped (greedy CSE is quadratic in bit-matrix
        density) and the cached log/exp table matmul is used instead.
        """
        data = np.ascontiguousarray(data, dtype=np.uint8)
        backend = resolve_backend()
        if backend == "jax":
            import jax.numpy as jnp

            bits = self._bits_cache.get(key)
            if bits is None:
                bits = self._bits_cache.put(
                    key, self.gf.gf_matrix_to_bits(matrix)
                )
            out = np.ascontiguousarray(
                np.asarray(self.gf.gf_apply_bitmatrix(data, jnp.asarray(bits)))
            )
        else:
            backend = "numpy"  # native kernel is GF(2^8)-only
            k, B = data.shape
            r = matrix.shape[0]
            if matrix.shape[1] <= _SCHED_MAX_COLS:
                sched = self._sched_cache.get(key)
                if sched is None:
                    sched = self._sched_cache.put(
                        key,
                        gf256.build_xor_schedule(
                            self.gf.gf_matrix_to_bits(matrix)
                        ),
                    )
                half = B // 2
                d2 = (
                    data.reshape(k, half, 2)
                    .transpose(0, 2, 1)
                    .reshape(2 * k, half)
                )
                r2 = gf256.apply_xor_schedule(sched, d2)
                out = np.ascontiguousarray(
                    r2.reshape(r, 2, half).transpose(0, 2, 1).reshape(r, B)
                )
            else:
                out = self._from_symbols(
                    self.gf.gf_matmul_np(matrix, self._to_symbols(data))
                )
        s = STATS[backend]
        s["calls"] += 1
        s["bytes"] += int(out.shape[0]) * int(out.shape[1])
        return out

    def reconstruct_data_np(
        self, survivors: np.ndarray, use: Tuple[int, ...]
    ) -> np.ndarray:
        """(data, B) data shards from the survivor rows ``use``.

        This is the large-N straggler decode the batched RBC calls on the
        host; both halves of the work are now cached per erasure pattern —
        the Gauss–Jordan inversion (the decode-side gap ROADMAP item 2
        named) AND the compiled apply — so repeated decodes under a stable
        straggler set pay only the XOR/table application."""
        dec = self.decode_matrix(tuple(use))
        return self._apply_matrix(("dec", tuple(use)), dec, survivors)

    def reconstruct_np(
        self, shards: Sequence[Optional[bytes]]
    ) -> List[bytes]:
        """Fill in missing (None) shards; needs ≥ data_shards present.

        Same contract as :meth:`ReedSolomon.reconstruct_np` — the
        object-mode ``Broadcast`` decode path calls this, so the GF(2^16)
        coder must offer it too (found by the round-5 large-N masked
        property sweep: object mode at N > 256 previously had no erasure
        reconstruction at all)."""
        def decode(sub, use):
            return self.encode_np(self.reconstruct_data_np(sub, use))

        return _reconstruct_optional(self, shards, decode, even_len=True)


def _reconstruct_optional(coder, shards, decode, even_len: bool = False):
    """Shared fill-in-missing-shards driver for both coders.

    ``decode(sub, use) -> full`` rebuilds all shards from the first
    data_shards survivors; validation (counts, lengths, the GF(2^16)
    even-length requirement) lives here exactly once.
    """
    present = [i for i, s in enumerate(shards) if s is not None]
    if len(present) < coder.data_shards:
        raise ValueError(
            f"too few shards: {len(present)} < {coder.data_shards}"
        )
    if len(shards) != coder.total_shards:
        raise ValueError("wrong shard count")
    shard_len = len(shards[present[0]])
    if (even_len and shard_len % 2) or any(
        len(shards[i]) != shard_len for i in present
    ):
        raise ValueError("inconsistent/odd shard lengths")
    use = tuple(present[: coder.data_shards])
    sub = np.stack(
        [np.frombuffer(shards[i], dtype=np.uint8) for i in use]
    )
    full = decode(sub, use)
    out: List[bytes] = []
    for i in range(coder.total_shards):
        if shards[i] is not None:
            out.append(bytes(shards[i]))
        else:
            out.append(full[i].tobytes())
    return out


@functools.lru_cache(maxsize=256)
def for_n_f(n: int, f: int):
    """The RBC coder for an (n, f) network: data = n−2f, parity = 2f.

    GF(2^8) (bit-exact with the reference's crate) up to 256 shards; the
    GF(2^16) coder beyond — the reference cannot represent such networks
    at all (its erasure field caps shards at 256)."""
    if n <= 256:
        return ReedSolomon(n - 2 * f, 2 * f)
    return ReedSolomon16(n - 2 * f, 2 * f)

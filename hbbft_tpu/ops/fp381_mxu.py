"""MXU-formulated BLS12-381 base-field arithmetic: 8-bit digits, matmul
limb products.

The VPU lowering in :mod:`hbbft_tpu.ops.fp381` computes the 30×30 limb
convolution as 30 sequential shifted FMAs — measured at the int32 VPU
throughput floor (STATUS.md round-3 investigation).  This module reformulates
the product so the arithmetic-heavy part runs on the MXU (the systolic
array), which is the order-of-magnitude lever that investigation named:

- **Representation**: 49 digits × 8 bits (radix 2⁸, little-endian) in int32
  lanes; *lazy* invariant only — digits in [0, 256], value an arbitrary
  residue (mod p).  8-bit digits are chosen so every matmul below is EXACT
  in f32: digit products ≤ 2¹⁶ and row sums ≤ 97·2¹⁶ < 2²³ < 2²⁴ (the f32
  integer-exactness bound).  49 digits (392 ≥ 381 bits) leave the same
  ~11-bit fold headroom per squeeze round as the 13-bit field's 390-bit
  layout — 48 would leave only 3 bits and the top-digit fold would not
  converge.
- **Convolution as matmul**: t_k = Σ_{i+j=k} a_i·b_j is the batched outer
  product a⊗b (B, 48, 48) contracted against a constant one-hot tensor
  S[(i,j), k] = [i+j = k] — i.e. ONE (B, 2304) @ (2304, 95) matmul that the
  MXU executes at matrix throughput, replacing 48 sequential VPU FMAs.
  ``jax.lax.Precision.HIGHEST`` keeps f32 multiplies exact on TPU (the
  default TPU matmul truncates inputs to bf16).
- **Modular fold as matmul**: digit positions ≥ 49 (values ≥ 2³⁹²) fold
  against precomputed residue rows 2^(8m) mod p — a second constant-matrix
  (B, hi) @ (hi, 48) matmul.
- Carries stay rough (3 int32 VPU passes), exactly like the 13-bit lazy
  field; zero/equality tests are digit-based with the same soundness
  conditions (see fp381's lazy section: ladder scalars < 2¹²⁸, infinity as
  an explicit flag).

Reference: ``threshold_crypto``'s 64-bit limb field (``pairing``/``ff``) is
the functional spec; the formulation here is TPU-native.  Host ground truth:
:mod:`hbbft_tpu.crypto.bls12_381`; tests assert exact equality.
"""

from __future__ import annotations

import numpy as np

from hbbft_tpu.crypto.bls12_381 import P

DIGIT_BITS = 8
NL = 49  # 49 × 8 = 392 ≥ 381 (11 bits of fold headroom)
MASK = (1 << DIGIT_BITS) - 1  # 255
_CONV_OUT = 2 * NL - 1  # 95 positions before carrying
_CARRY_PAD = 3  # carry room past the conv output


def int_to_limbs(x: int, n: int = NL) -> np.ndarray:
    """Host: python int → little-endian 8-bit digits (int32)."""
    out = np.frombuffer(
        int(x).to_bytes(n, "little"), dtype=np.uint8
    ).astype(np.int32)
    return out


def limbs_to_int(limbs) -> int:
    """Host: digit array (little-endian, any magnitudes) → python int."""
    x = 0
    for i, v in enumerate(np.asarray(limbs).tolist()):
        x += int(v) << (DIGIT_BITS * i)
    return x


def ints_to_limbs_batch(xs, n: int = NL) -> np.ndarray:
    """Host: ints in [0, 2^(8n)) → (B, n) int32 digits (LE bytes)."""
    buf = b"".join(int(x).to_bytes(n, "little") for x in xs)
    return (
        np.frombuffer(buf, dtype=np.uint8)
        .reshape(len(xs), n)
        .astype(np.int32)
    )


_DIGIT_WEIGHTS = np.array(
    [1 << (DIGIT_BITS * i) for i in range(NL + _CARRY_PAD)], dtype=object
)


def limbs_to_ints_batch(limbs) -> list:
    """Host: (B, NL) digits (lazy magnitudes allowed) → python ints."""
    arr = np.asarray(limbs)
    return list(arr.astype(object) @ _DIGIT_WEIGHTS[: arr.shape[-1]])


P_LIMBS = int_to_limbs(P)

# one-hot convolution tensor: S[(i*NL + j), k] = 1 iff i + j == k
_S_CONV = np.zeros((NL * NL, _CONV_OUT), dtype=np.float32)
for _i in range(NL):
    for _j in range(NL):
        _S_CONV[_i * NL + _j, _i + _j] = 1.0

# fold rows: 2^(8m) mod p for digit positions m ≥ NL (conv output + carry
# room), as 8-bit digit rows — the constant matrix of the fold matmul
_N_HI = _CONV_OUT + _CARRY_PAD - NL  # hi positions after carrying
_FOLD_ROWS = np.stack(
    [int_to_limbs((1 << (DIGIT_BITS * (NL + m))) % P) for m in range(_N_HI)]
).astype(np.float32)  # (_N_HI, NL)

# squeeze fold rows: 2^(8(NL+m)) mod p for the _CARRY_PAD overflow digits
_SQUEEZE_ROWS = np.stack(
    [
        int_to_limbs((1 << (DIGIT_BITS * (NL + m))) % P)
        for m in range(_CARRY_PAD)
    ]
)

# ≡ −2·(2^392 − 1) (mod p), canonical — completes the digitwise complement
# in fp_sub (same construction as fp381._SUBC_LIMBS in the 13-bit field)
_SUBC_LIMBS = int_to_limbs((-2 * ((1 << (DIGIT_BITS * NL)) - 1)) % P)


def _shift1(c):
    """Shift digits up one position (pad/slice, not dynamic-update-slice —
    DUS breaks XLA elementwise fusion and each unfused op is a separate
    kernel launch, which is what the launch-bound ladders pay for)."""
    import jax.numpy as jnp

    pad = [(0, 0)] * (c.ndim - 1) + [(1, 0)]
    return jnp.pad(c[..., :-1], pad)


def _carry_rough(t):
    """3 rough passes over limbs < 2^31: digits land ≤ 384 (not yet the
    ≤ 256 lazy invariant — from near-2^31 inputs three masked passes bound
    each digit by 255 + carry-in ≤ 255 + 129).  The ≤ 256 invariant is
    restored by the fold-round carries in :func:`_squeeze`, which always
    follow; callers must not use these digits directly."""
    for _ in range(3):
        t = (t & MASK) + _shift1(t >> DIGIT_BITS)
    return t


def _squeeze(acc):
    """(…, NL) int32 limbs with values < 2^31 → lazy-invariant digits.

    Appends ``_CARRY_PAD`` carry positions (one is NOT enough: a single
    appended digit's own carry would fall off the end for limbs ≥ 2^16),
    rough-carries, then folds ALL overflow digits back through their
    2^(8(NL+m)) mod p residue rows; each fold with a nonzero overhang
    shrinks it by ≥ 2^11 (2^392 vs p < 2^381), so 3 rounds reach overhang
    0 from any in-contract input (mirrors fp381._squeeze_lazy)."""
    import jax.numpy as jnp

    rows = jnp.asarray(_SQUEEZE_ROWS)
    zero_pad = jnp.zeros((*acc.shape[:-1], _CARRY_PAD), acc.dtype)
    acc = jnp.concatenate([acc, zero_pad], -1)
    acc = _carry_rough(acc)
    for _ in range(3):
        top = acc[..., NL:]
        fold = jnp.einsum("...m,md->...d", top, rows)
        acc = jnp.concatenate([acc[..., :NL] + fold, zero_pad], -1)
        acc = _carry_rough(acc)
    return acc[..., :NL]


def _conv_mxu(a, b):
    """Digit convolution on the MXU: outer product + one-hot matmul.

    a, b: int32 (..., NL), digits ≤ 256.  Returns int32 (..., _CONV_OUT)
    with values ≤ 49·(256·256) < 2²³ — exact through f32."""
    import jax
    import jax.numpy as jnp

    af = a.astype(jnp.float32)
    bf = b.astype(jnp.float32)
    outer = af[..., :, None] * bf[..., None, :]  # (..., NL, NL) ≤ 2^16
    flat = outer.reshape(*outer.shape[:-2], NL * NL)
    conv = jnp.matmul(
        flat, jnp.asarray(_S_CONV),
        precision=jax.lax.Precision.HIGHEST,
    )
    return conv.astype(jnp.int32)


def fp_mul(a, b):
    """Lazy modular product, MXU path: conv matmul → carry → fold matmul
    → squeeze.  Inputs/outputs int32 (..., NL) with digits ≤ 256."""
    import jax
    import jax.numpy as jnp

    t = _conv_mxu(a, b)
    t = jnp.concatenate(
        [t, jnp.zeros((*t.shape[:-1], _CARRY_PAD), t.dtype)], -1
    )
    t = _carry_rough(t)  # digits ≤ 256 over NL + _N_HI positions
    lo = t[..., :NL]
    hi = t[..., NL:].astype(jnp.float32)  # (..., _N_HI) ≤ 256
    fold = jnp.matmul(
        hi, jnp.asarray(_FOLD_ROWS),
        precision=jax.lax.Precision.HIGHEST,
    )  # ≤ _N_HI·256·255 < 2^22 — exact
    return _squeeze(lo + fold.astype(jnp.int32))


def fp_sqr(a):
    return fp_mul(a, a)


def fp_add(a, b):
    return _squeeze(a + b)


def fp_sub(a, b):
    """a − b (mod p), lazy: a + (2·MASK − b_digits) + const (the digitwise
    complement represents 2·(2^392−1) − b; the constant is ≡ −2·(2^392−1))."""
    import jax.numpy as jnp

    t = a + (2 * MASK - b) + jnp.asarray(_SUBC_LIMBS)
    return _squeeze(t)


def fp_neg(a):
    import jax.numpy as jnp

    return fp_sub(jnp.zeros_like(a), a)


def fp_is_zero_digits(a):
    import jax.numpy as jnp

    return jnp.all(a == 0, axis=-1)


def fp_select(mask, a, b):
    import jax.numpy as jnp

    return jnp.where(mask[..., None], a, b)


# -- Fp2 (Karatsuba, mirrors the 13-bit lazy field) --------------------------


def fp2_add(a, b):
    return (fp_add(a[0], b[0]), fp_add(a[1], b[1]))


def fp2_sub(a, b):
    return (fp_sub(a[0], b[0]), fp_sub(a[1], b[1]))


def fp2_neg(a):
    return (fp_neg(a[0]), fp_neg(a[1]))


def fp2_mul(a, b):
    """Karatsuba with the three independent products STACKED into one
    fp_mul launch — one conv matmul of 3× the rows instead of three small
    dispatches (the ladder's cost is op-launch-bound, not flop-bound)."""
    import jax.numpy as jnp

    lhs = jnp.stack([a[0], a[1], fp_add(a[0], a[1])])
    rhs = jnp.stack([b[0], b[1], fp_add(b[0], b[1])])
    t = fp_mul(lhs, rhs)
    t0, t1, t2 = t[0], t[1], t[2]
    return (fp_sub(t0, t1), fp_sub(t2, fp_add(t0, t1)))


def fp2_sqr(a):
    import jax.numpy as jnp

    lhs = jnp.stack([fp_add(a[0], a[1]), a[0]])
    rhs = jnp.stack([fp_sub(a[0], a[1]), a[1]])
    t = fp_mul(lhs, rhs)
    t0, t1 = t[0], t[1]
    return (t0, fp_add(t1, t1))


def fp2_is_zero_digits(a):
    return fp_is_zero_digits(a[0]) & fp_is_zero_digits(a[1])


def fp2_select(mask, a, b):
    return (fp_select(mask, a[0], b[0]), fp_select(mask, a[1], b[1]))

"""Pallas (Mosaic) kernel for the lazy 13-bit×30-limb field — an EXPERIMENT.

Round-3/4 verdicts asked whether a Pallas kernel that keeps ladder limbs
resident in VMEM could beat the XLA lowering of :mod:`hbbft_tpu.ops.fp381`
in the compute-bound MSM regime (the dkg 16 384-row ladder, SURVEY §7.2a).
This module is the measured answer.  It implements the SAME lazy-field
multiplication (schoolbook limb convolution → rough carries → fold-by-rows
→ squeeze) as a Pallas TPU kernel in the lanes-last ``(NL, R)`` layout and
is bit-exact against ``fp381`` (tests, interpret mode on CPU; verified on
the real chip too).

Measured on TPU v5 lite (2026-07-31, tunneled chip, in-kernel 50-mul chain
so launch/transfer amortize):

  ===========  ==================  =========================
  rows R       Pallas (this file)  XLA lowering of fp381
  ===========  ==================  =========================
  8192         522 ns/row-mul      ~135 ns/row-mul
  2048         1382 ns/row-mul     (launch-bound regime)
  ===========  ==================  =========================

i.e. Mosaic currently lowers the pad-shifted-FMA convolution ~4× SLOWER
than XLA's fusion of the identical math — each ``jnp.pad`` materializes a
(61, R) buffer, and the 30 pads per product dominate VMEM traffic.  The
roofline conclusion (recorded in STATUS.md): this op is MEMORY-bound
elementwise int32 with arithmetic intensity ≈ 0.5 op/byte — both lowerings
run at ~1 % of VPU peak, so the ceiling is bandwidth/fusion, not the
int32 ALU, and a winning kernel would need a fundamentally different data
layout (limbs in registers across ladder steps), which Mosaic does not
express today.  The compute-bound MSM crown therefore stays with the
ADX/BMI2 host oracle (~40 ns/mul after round 5); the device ladder wins in
the launch-bound small-batch regime (MXU field) and by row-sharding over a
mesh (``crypto/batch.use_mesh``).

Kept as a working, tested kernel so the next attempt starts from running
code rather than a blank file.
"""

from __future__ import annotations

import numpy as np

from hbbft_tpu.ops import fp381 as F

NL = F.NL
MASK = F.MASK
LIMB_BITS = F.LIMB_BITS

_FOLD_HI = np.asarray(F._FOLD_HI, np.int32)  # (31, 30) residue rows


def _shift1(c):
    """Digits up one position along the LIMB axis (axis 0)."""
    import jax.numpy as jnp

    return jnp.pad(c[:-1], ((1, 0), (0, 0)))


def _carry_rough(t):
    for _ in range(3):
        t = (t & MASK) + _shift1(t >> LIMB_BITS)
    return t


def _conv(a, b):
    """Schoolbook convolution over (2·NL+1, R) via pad-shifted FMAs."""
    import jax.numpy as jnp

    t = jnp.pad(a[0] * b, ((0, NL + 1), (0, 0)))
    for i in range(1, NL):
        t = t + jnp.pad(a[i] * b, ((i, NL + 1 - i), (0, 0)))
    return t


def _fold_hi(t, fold):
    acc = t[:NL]
    for j in range(NL + 1):
        acc = acc + t[NL + j] * fold[j][:, None]
    return acc


def _squeeze(acc, row0):
    import jax.numpy as jnp

    acc = _carry_rough(jnp.pad(acc, ((0, 1), (0, 0))))
    for _ in range(4):
        top = acc[NL]
        acc = _carry_rough(
            jnp.pad(acc[:NL] + top * row0[:, None], ((0, 1), (0, 0)))
        )
    return acc[:NL]


def mul_lazy_cols(a, b, fold):
    """Lazy modular product, ``(NL, R)`` columns layout (limb axis first).

    Same semantics as ``fp381.fp_mul_lazy`` on the transposed layout."""
    return _squeeze(_fold_hi(_carry_rough(_conv(a, b)), fold), fold[0])


def _mul_kernel(a_ref, b_ref, fold_ref, o_ref):
    o_ref[:] = mul_lazy_cols(a_ref[:], b_ref[:], fold_ref[:])


def fp_mul_lazy_pallas(a, b, interpret: bool = False):
    """One lazy field multiplication as a Pallas kernel.

    ``a``, ``b``: int32 ``(NL, R)`` lazy-digit columns; returns the same.
    ``interpret=True`` runs the Pallas interpreter (CPU tests).
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    return pl.pallas_call(
        _mul_kernel,
        out_shape=jax.ShapeDtypeStruct(a.shape, jnp.int32),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 3,
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=interpret,
    )(a, b, jnp.asarray(_FOLD_HI))

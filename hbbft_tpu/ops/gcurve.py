"""Batched G1/G2 Jacobian point arithmetic on TPU.

Mirrors the host oracle's generic Jacobian formulas
(``crypto/bls12_381.py :: _jac_double / _jac_add`` — dbl-2009-l and
add-2007-bl) over the limbed device field (:mod:`hbbft_tpu.ops.fp381`),
with a *complete* branchless addition: the P==Q case routes through the
doubling result and P==−Q falls out naturally (the add formula's Z3 = 2·Z1
Z2·H is zero when H = 0), all chosen by masks — no data-dependent Python
control flow, so everything jits, vmaps, and ladders under ``lax.fori_loop``.

Points are (X, Y, Z) limb pytrees with **Z = 0 encoding infinity** (the host
uses ``None``).  A batch of points is just leading axes on every limb array.

The scalar ladder is fixed-length (255 = |r| bits, MSB-first, select-by-bit)
— constant shape, constant time.  ``msm`` tree-reduces a batch of ladders:
the multi-scalar multiplication at the heart of randomized-linear-combination
share verification (SURVEY §7.2c: the common-coin hot loop).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from hbbft_tpu.crypto.bls12_381 import R
from hbbft_tpu.ops import fp381 as F

R_BITS = 255


# ---------------------------------------------------------------------------
# field-op bundles (G1 over Fp, G2 over Fp2) so the formulas are written once
# ---------------------------------------------------------------------------


class _FpOps:
    add = staticmethod(F.fp_add)
    sub = staticmethod(F.fp_sub)
    mul = staticmethod(F.fp_mul)
    sqr = staticmethod(F.fp_sqr)
    neg = staticmethod(F.fp_neg)
    is_zero = staticmethod(F.fp_is_zero)
    select = staticmethod(F.fp_select)


class _Fp2Ops:
    add = staticmethod(F.fp2_add)
    sub = staticmethod(F.fp2_sub)
    mul = staticmethod(F.fp2_mul)
    sqr = staticmethod(F.fp2_sqr)
    neg = staticmethod(F.fp2_neg)
    is_zero = staticmethod(F.fp2_is_zero)
    select = staticmethod(F.fp2_select)


class _LazyFpOps:
    """Non-canonical fast field (see fp381 lazy section for the soundness
    conditions — ladders must use scalars < 2^128)."""

    add = staticmethod(F.fp_add_lazy)
    sub = staticmethod(F.fp_sub_lazy)
    mul = staticmethod(F.fp_mul_lazy)
    sqr = staticmethod(lambda a: F.fp_mul_lazy(a, a))
    neg = staticmethod(F.fp_neg_lazy)
    is_zero = staticmethod(F.fp_is_zero_digits)
    select = staticmethod(F.fp_select)


class _LazyFp2Ops:
    add = staticmethod(F.fp2_add_lazy)
    sub = staticmethod(F.fp2_sub_lazy)
    mul = staticmethod(F.fp2_mul_lazy)
    sqr = staticmethod(F.fp2_sqr_lazy)
    neg = staticmethod(F.fp2_neg_lazy)
    is_zero = staticmethod(F.fp2_is_zero_digits)
    select = staticmethod(F.fp2_select)


def _mxu():
    from hbbft_tpu.ops import fp381_mxu as M

    return M


class _MxuFpOps:
    """8-bit-digit MXU field (see ops/fp381_mxu.py) — lazy semantics, same
    soundness conditions as the 13-bit lazy ops; pair with ``rep=fp381_mxu``
    in the host converters."""

    def __init__(self):
        M = _mxu()
        self.add = M.fp_add
        self.sub = M.fp_sub
        self.mul = M.fp_mul
        self.sqr = M.fp_sqr
        self.neg = M.fp_neg
        self.is_zero = M.fp_is_zero_digits
        self.select = M.fp_select


class _MxuFp2Ops:
    def __init__(self):
        M = _mxu()
        self.add = M.fp2_add
        self.sub = M.fp2_sub
        self.mul = M.fp2_mul
        self.sqr = M.fp2_sqr
        self.neg = M.fp2_neg
        self.is_zero = M.fp2_is_zero_digits
        self.select = M.fp2_select


def _dbl_small(o, a, times: int):
    """a·2^times via repeated additions (host oracle's ``scal`` uses small
    integer factors 2 and 8 only)."""
    for _ in range(times):
        a = o.add(a, a)
    return a


# ---------------------------------------------------------------------------
# point formulas (generic over the ops bundle)
# ---------------------------------------------------------------------------


def point_double(o, pt):
    x, y, z = pt
    a = o.sqr(x)
    b = o.sqr(y)
    c = o.sqr(b)
    d = o.sub(o.sqr(o.add(x, b)), o.add(a, c))
    d = o.add(d, d)
    e = o.add(o.add(a, a), a)
    f = o.sqr(e)
    x3 = o.sub(f, o.add(d, d))
    y3 = o.sub(o.mul(e, o.sub(d, x3)), _dbl_small(o, c, 3))
    z3 = o.mul(o.add(y, y), z)
    return (x3, y3, z3)


def point_add_raw(o, p1, p2):
    """add-2007-bl only — valid for FINITE operands with distinct x.

    The lazy ladder uses this with explicit infinity flags (its scalar
    regime rules out the P==±Q cases; see :func:`scalar_mul_lazy`)."""
    x1, y1, z1 = p1
    x2, y2, z2 = p2
    z1z1 = o.sqr(z1)
    z2z2 = o.sqr(z2)
    u1 = o.mul(x1, z2z2)
    u2 = o.mul(x2, z1z1)
    s1 = o.mul(o.mul(y1, z2), z2z2)
    s2 = o.mul(o.mul(y2, z1), z1z1)
    h = o.sub(u2, u1)
    r = o.sub(s2, s1)
    i = o.sqr(o.add(h, h))
    j = o.mul(h, i)
    r2 = o.add(r, r)
    v = o.mul(u1, i)
    x3 = o.sub(o.sub(o.sqr(r2), j), o.add(v, v))
    y3 = o.sub(o.mul(r2, o.sub(v, x3)), _dbl_small(o, o.mul(s1, j), 1))
    z3 = o.mul(_dbl_small(o, o.mul(z1, z2), 1), h)
    return (x3, y3, z3)


def point_add(o, p1, p2):
    """Complete addition: handles inf operands, P==Q, and P==−Q by masks."""
    x1, y1, z1 = p1
    x2, y2, z2 = p2
    inf1 = o.is_zero(z1)
    inf2 = o.is_zero(z2)

    z1z1 = o.sqr(z1)
    z2z2 = o.sqr(z2)
    u1 = o.mul(x1, z2z2)
    u2 = o.mul(x2, z1z1)
    s1 = o.mul(o.mul(y1, z2), z2z2)
    s2 = o.mul(o.mul(y2, z1), z1z1)
    h = o.sub(u2, u1)
    r = o.sub(s2, s1)
    same_x = o.is_zero(h)
    same_y = o.is_zero(r)
    is_dbl = same_x & same_y & ~inf1 & ~inf2

    i = o.sqr(o.add(h, h))
    j = o.mul(h, i)
    r2 = o.add(r, r)
    v = o.mul(u1, i)
    x3 = o.sub(o.sub(o.sqr(r2), j), o.add(v, v))
    y3 = o.sub(o.mul(r2, o.sub(v, x3)), _dbl_small(o, o.mul(s1, j), 1))
    z3 = o.mul(_dbl_small(o, o.mul(z1, z2), 1), h)
    # same_x & ~same_y (P = −Q): z3 = …·h = 0 already encodes infinity.

    dx, dy, dz = point_double(o, p1)
    x3 = o.select(is_dbl, dx, x3)
    y3 = o.select(is_dbl, dy, y3)
    z3 = o.select(is_dbl, dz, z3)
    # inf operands
    x3 = o.select(inf2, x1, o.select(inf1, x2, x3))
    y3 = o.select(inf2, y1, o.select(inf1, y2, y3))
    z3 = o.select(inf2, z1, o.select(inf1, z2, z3))
    return (x3, y3, z3)


def point_select(o, mask, p, q):
    return (
        o.select(mask, p[0], q[0]),
        o.select(mask, p[1], q[1]),
        o.select(mask, p[2], q[2]),
    )


def scalar_mul(o, pt, bits):
    """Fixed-length MSB-first double-and-add ladder, batched.

    pt: (X, Y, Z) with batch leading axes; bits: int32 (..., nbits)
    little-endian bit order (bit i = 2^i coefficient).  The ladder length is
    bits.shape[-1]: pass 255 for full-range scalars (canonical ops) or 128
    for the lazy-ops randomizer path.
    """
    import jax
    import jax.numpy as jnp

    nbits = bits.shape[-1]

    def zeros_like_coord(c):
        if isinstance(c, tuple):
            return tuple(jnp.zeros_like(x) for x in c)
        return jnp.zeros_like(c)

    acc = tuple(zeros_like_coord(c) for c in pt)  # infinity (Z = 0)

    def body(i, acc):
        acc = point_double(o, acc)
        with_add = point_add(o, acc, pt)
        bit = jax.lax.dynamic_index_in_dim(
            bits, nbits - 1 - i, axis=-1, keepdims=False
        ).astype(bool)
        return point_select(o, bit, with_add, acc)

    return jax.lax.fori_loop(0, nbits, body, acc)


def scalar_mul_lazy(o, pt, bits, base_inf):
    """Ladder for the LAZY field ops, with infinity as an explicit flag.

    The lazy field does not preserve digit-zero through subtractions (Fp2
    Karatsuba routes products of zero through them), so Z-digit-zero cannot
    encode infinity; instead an ``inf`` bool mask rides along and the raw
    add formula is used.  Soundness requires scalars < 2^128 (rules out the
    P == ±Q ladder collisions — a collision needs a bit-prefix m with
    2m ≡ ±1 (mod r), i.e. m ≥ (r−1)/2 ≥ 2^253).

    pt: (X, Y, Z); bits (..., nbits) little-endian; base_inf bool (...,).
    Returns ((X, Y, Z), inf_mask).
    """
    import jax
    import jax.numpy as jnp

    nbits = bits.shape[-1]

    def zeros_like_coord(c):
        if isinstance(c, tuple):
            return tuple(jnp.zeros_like(x) for x in c)
        return jnp.zeros_like(c)

    acc0 = tuple(zeros_like_coord(c) for c in pt)
    inf0 = jnp.ones(base_inf.shape, dtype=bool)

    def body(i, carry):
        acc, inf = carry
        acc = point_double(o, acc)  # double keeps finiteness (odd order)
        added = point_add_raw(o, acc, pt)
        # if acc is ∞: acc + base = base; if base is ∞: stays acc
        res = point_select(o, inf, pt, point_select(o, base_inf, acc, added))
        res_inf = inf & base_inf
        bit = jax.lax.dynamic_index_in_dim(
            bits, nbits - 1 - i, axis=-1, keepdims=False
        ).astype(bool)
        acc = point_select(o, bit, res, acc)
        inf = jnp.where(bit, res_inf, inf)
        return acc, inf

    return jax.lax.fori_loop(0, nbits, body, (acc0, inf0))


def scalar_mul_lazy_window(o, pt, bits, base_inf, w: int = 4):
    """Windowed variant of :func:`scalar_mul_lazy`: same lazy-field and
    scalar-regime soundness conditions, ~1.5× fewer point operations.

    Precomputes the table [P, 2P, …, (2^w−1)P] (even entries by doubling,
    odd by raw add — always distinct-x inside the scalar regime), then
    processes ``w`` bits per iteration: w doubles + ONE table add selected
    by a one-hot mask over the window value (gathers lower to slow loops on
    TPU; 2^w−1 masked adds fuse into elementwise selects).

    ``bits`` length must be a multiple of ``w`` (pad scalars_to_bits nbits
    accordingly).  Returns ((X, Y, Z), inf_mask) like scalar_mul_lazy.
    """
    import jax
    import jax.numpy as jnp

    nbits = bits.shape[-1]
    assert nbits % w == 0, (nbits, w)
    n_win = nbits // w

    # table[k] = (k+1)·P for k in 0..2^w−2, built batched
    table = [pt]
    for k in range(2, 1 << w):
        if k % 2 == 0:
            table.append(point_double(o, table[k // 2 - 1]))
        else:
            table.append(point_add_raw(o, table[k - 2], pt))

    def stack_coord(ci):
        if isinstance(pt[ci], tuple):
            return tuple(
                jnp.stack([t[ci][j] for t in table])
                for j in range(len(pt[ci]))
            )
        return jnp.stack([t[ci] for t in table])

    tstack = tuple(stack_coord(ci) for ci in range(3))  # (2^w−1, B, NL)

    def select_entry(idx):
        """One-hot Σ_k [idx == k+1]·table[k] per coordinate — a single
        k-contraction einsum per coordinate, not 2^w−1 masked adds."""
        onehot = (
            idx[None, :] == jnp.arange(1, 1 << w)[:, None]
        ).astype(jnp.int32)  # (2^w−1, B)

        def sel(c):
            if isinstance(c, tuple):
                return tuple(sel(x) for x in c)
            return jnp.einsum("kb,kbd->bd", onehot, c)

        return tuple(sel(c) for c in tstack)

    def zeros_like_coord(c):
        if isinstance(c, tuple):
            return tuple(jnp.zeros_like(x) for x in c)
        return jnp.zeros_like(c)

    acc0 = tuple(zeros_like_coord(c) for c in pt)
    inf0 = jnp.ones(base_inf.shape, dtype=bool)

    def body(j, carry):
        acc, inf = carry
        for _ in range(w):
            acc = point_double(o, acc)
        # window value (MSB-first): bits are little-endian
        start = nbits - (j + 1) * w
        win = jax.lax.dynamic_slice_in_dim(bits, start, w, axis=-1)
        weights = (1 << jnp.arange(w)).astype(win.dtype)
        idx = jnp.sum(win * weights, axis=-1)  # (B,)
        selT = select_entry(idx)
        added = point_add_raw(o, acc, selT)
        res = point_select(o, inf, selT, point_select(o, base_inf, acc, added))
        res_inf = inf & base_inf
        considered = idx != 0
        acc = point_select(o, considered, res, acc)
        inf = jnp.where(considered, res_inf, inf)
        return acc, inf

    return jax.lax.fori_loop(0, n_win, body, (acc0, inf0))


def msm(o, pt, bits):
    """Σ_b bits[b]·pt[b] — batched ladders, then a tree of point_adds where
    each level HALVES the batch by adding the two halves.

    The tree is folded on fixed pairings so the whole reduction is
    log₂(B) batched adds; callers that are compile-time-sensitive (CPU
    tests) can instead fetch the ladder results and accumulate on the host
    (see ``crypto/batch.py``), since the ladders dominate the math.
    """
    import jax.numpy as jnp

    def take(c, sl):
        if isinstance(c, tuple):
            return tuple(x[sl] for x in c)
        return c[sl]

    def pad_inf(c, n):
        if isinstance(c, tuple):
            return tuple(
                jnp.concatenate([x, jnp.zeros((n, *x.shape[1:]), x.dtype)])
                for x in c
            )
        return jnp.concatenate([c, jnp.zeros((n, *c.shape[1:]), c.dtype)])

    pts = scalar_mul(o, pt, bits)  # (B, …) points
    B = pts[0][0].shape[0] if isinstance(pts[0], tuple) else pts[0].shape[0]
    size = 1
    while size < B:
        size *= 2
    if size != B:
        pts = tuple(pad_inf(c, size - B) for c in pts)
    while size > 1:
        half = size // 2
        lo = tuple(take(c, slice(0, half)) for c in pts)
        hi = tuple(take(c, slice(half, size)) for c in pts)
        pts = point_add(o, lo, hi)
        size = half
    return tuple(take(c, 0) for c in pts)


# ---------------------------------------------------------------------------
# host conversions
# ---------------------------------------------------------------------------


def scalars_to_bits(scalars: Sequence[int], nbits: int = R_BITS) -> np.ndarray:
    """ints (mod r) → (B, nbits) int32 little-endian bits."""
    sc = [s % R for s in scalars]
    assert all(s < (1 << nbits) for s in sc), "scalar exceeds ladder width"
    return F.bits_batch(sc, nbits)


def g1_to_device(points: Sequence[Optional[tuple]], rep=F) -> Tuple:
    """Host Jacobian G1 points (or None) → stacked device limb arrays.

    ``rep`` selects the device representation module: :mod:`fp381` (13-bit
    limbs, default) or :mod:`fp381_mxu` (8-bit digits for the MXU ops)."""
    coords = ([], [], [])
    for p in points:
        for ci in range(3):
            coords[ci].append(0 if p is None else p[ci] % F.P)
    return tuple(rep.ints_to_limbs_batch(cs) for cs in coords)


def g1_from_device(pt) -> Optional[tuple]:
    """Device → host point; canonicalizes on host (lazy-path values are
    arbitrary residues)."""
    x, y, z = (np.asarray(c) for c in pt)
    zi = F.limbs_to_int(z) % F.P
    if zi == 0:
        return None
    return (F.limbs_to_int(x) % F.P, F.limbs_to_int(y) % F.P, zi)


def g2_to_device(points: Sequence[Optional[tuple]], rep=F) -> Tuple:
    """Host Jacobian G2 points (Fp2 coords) → device ((re,im) limb pairs)."""
    coords = ([], []), ([], []), ([], [])
    for p in points:
        if p is None:
            p = ((0, 0), (0, 0), (0, 0))
        for ci, c in enumerate(p):
            coords[ci][0].append(c[0] % F.P)
            coords[ci][1].append(c[1] % F.P)
    return tuple(
        (rep.ints_to_limbs_batch(re), rep.ints_to_limbs_batch(im))
        for (re, im) in coords
    )


def g1_from_device_batch(pt, rep=F) -> list:
    """Device (X, Y, Z) limb arrays with a leading batch axis → list of host
    Jacobian points (None = infinity).  Canonicalizes on host; one
    object-dtype matvec per coordinate instead of a per-point limb loop."""
    xs, ys, zs = (
        rep.limbs_to_ints_batch(np.asarray(c).reshape(-1, rep.NL)) for c in pt
    )
    return [
        None if (z % F.P) == 0 else (x % F.P, y % F.P, z % F.P)
        for x, y, z in zip(xs, ys, zs)
    ]


def g2_from_device_batch(pt, rep=F) -> list:
    """Device G2 ((re, im) limb-pair coords, leading batch axis) → list of
    host Jacobian points (None = infinity)."""
    (xr, xi), (yr, yi), (zr, zi) = (
        tuple(
            rep.limbs_to_ints_batch(np.asarray(c).reshape(-1, rep.NL))
            for c in coord
        )
        for coord in pt
    )
    out = []
    for i in range(len(zr)):
        z = (zr[i] % F.P, zi[i] % F.P)
        if z == (0, 0):
            out.append(None)
            continue
        out.append(
            (
                (xr[i] % F.P, xi[i] % F.P),
                (yr[i] % F.P, yi[i] % F.P),
                z,
            )
        )
    return out


def g2_from_device(pt) -> Optional[tuple]:
    (xr, xi), (yr, yi), (zr, zi) = (
        (np.asarray(c[0]), np.asarray(c[1])) for c in pt
    )
    z = (F.limbs_to_int(zr) % F.P, F.limbs_to_int(zi) % F.P)
    if z == (0, 0):
        return None
    return (
        (F.limbs_to_int(xr) % F.P, F.limbs_to_int(xi) % F.P),
        (F.limbs_to_int(yr) % F.P, F.limbs_to_int(yi) % F.P),
        z,
    )


FP_OPS = _FpOps()
FP2_OPS = _Fp2Ops()
LAZY_FP_OPS = _LazyFpOps()
LAZY_FP2_OPS = _LazyFp2Ops()
MXU_FP_OPS = _MxuFpOps()
MXU_FP2_OPS = _MxuFp2Ops()

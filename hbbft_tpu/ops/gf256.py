"""GF(2^8) arithmetic — host tables and the TPU bit-plane lowering.

Field: GF(2^8) with reducing polynomial x^8+x^4+x^3+x^2+1 (0x11D) and
generator 2 — the same field as the ``reed-solomon-erasure`` crate the
reference links for RBC shard coding (reference:
``src/broadcast/broadcast.rs`` uses ``ReedSolomon::new(data, parity)``).

Two execution paths:

1. **Host (numpy) oracle** — log/exp tables, used for matrix construction,
   inversion (data-dependent, tiny) and bit-exact tests.
2. **Device (jnp) bit-plane path** — multiplication by a *constant* GF(2^8)
   element is linear over GF(2), so a GF(2^8) matrix–vector product
   ``out_j = XOR_k mul(M[j,k], d_k)`` lowers to ONE binary matmul:
   expand bytes to bits, multiply by an 8×-expanded 0/1 matrix with an int8
   MXU matmul, take parity (``& 1``), repack bits to bytes.  No gathers, no
   scalar loops — exactly the shape XLA tiles onto the MXU.  This is the
   whole RS encode/decode story on TPU.
"""

from __future__ import annotations

import numpy as np

GF_POLY = 0x11D  # x^8 + x^4 + x^3 + x^2 + 1
GF_GEN = 2

# ---------------------------------------------------------------------------
# Host tables
# ---------------------------------------------------------------------------


def _build_tables():
    exp = np.zeros(512, dtype=np.uint8)
    log = np.zeros(256, dtype=np.int32)
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & 0x100:
            x ^= GF_POLY
    for i in range(255, 512):
        exp[i] = exp[i - 255]
    return exp, log


GF_EXP, GF_LOG = _build_tables()

# Full 256×256 multiplication table (64 KiB) — handy for vectorized host code.
_A = np.arange(256)
_MUL_TABLE = np.zeros((256, 256), dtype=np.uint8)
_nz = _A[1:]
_MUL_TABLE[1:, 1:] = GF_EXP[(GF_LOG[_nz][:, None] + GF_LOG[_nz][None, :]) % 255]


def gf_mul(a, b):
    """Elementwise GF(2^8) multiply (numpy, any broadcastable uint8 shapes)."""
    a = np.asarray(a, dtype=np.uint8)
    b = np.asarray(b, dtype=np.uint8)
    return _MUL_TABLE[a, b]


def gf_inv(a):
    a = np.asarray(a)
    if np.any(a == 0):
        raise ZeroDivisionError("GF(2^8) inverse of 0")
    return GF_EXP[255 - GF_LOG[a]]


def gf_div(a, b):
    return gf_mul(a, gf_inv(b))


def gf_pow(a: int, n: int) -> int:
    if n == 0:
        return 1
    if a == 0:
        return 0
    return int(GF_EXP[(int(GF_LOG[a]) * n) % 255])


def gf_matmul_np(A: np.ndarray, B: np.ndarray) -> np.ndarray:
    """GF(2^8) matrix product (host oracle). A: (r,k), B: (k,c) → (r,c)."""
    A = np.asarray(A, dtype=np.uint8)
    B = np.asarray(B, dtype=np.uint8)
    r, k = A.shape
    k2, c = B.shape
    assert k == k2
    out = np.zeros((r, c), dtype=np.uint8)
    for i in range(k):  # k is small (≤ N); columns vectorized
        out ^= _MUL_TABLE[A[:, i][:, None], B[i][None, :]]
    return out


def gf_inv_matrix_np(M: np.ndarray) -> np.ndarray:
    """Invert a square GF(2^8) matrix by Gauss–Jordan elimination (host)."""
    M = np.asarray(M, dtype=np.uint8)
    n = M.shape[0]
    assert M.shape == (n, n)
    aug = np.concatenate([M.copy(), np.eye(n, dtype=np.uint8)], axis=1)
    for col in range(n):
        piv = col + int(np.argmax(aug[col:, col] != 0))
        if aug[piv, col] == 0:
            raise np.linalg.LinAlgError("singular GF(2^8) matrix")
        if piv != col:
            aug[[col, piv]] = aug[[piv, col]]
        aug[col] = gf_mul(aug[col], gf_inv(aug[col, col]))
        mask = aug[:, col].copy()
        mask[col] = 0
        aug ^= _MUL_TABLE[mask[:, None], aug[col][None, :]]
    return aug[:, n:].copy()


def vandermonde(rows: int, cols: int) -> np.ndarray:
    """V[r, c] = r^c in GF(2^8) — the ``reed-solomon-erasure`` construction."""
    V = np.zeros((rows, cols), dtype=np.uint8)
    for r in range(rows):
        for c in range(cols):
            V[r, c] = gf_pow(r, c)
    return V


# ---------------------------------------------------------------------------
# Cached bitmatrix-XOR schedule (host hot path)
# ---------------------------------------------------------------------------
#
# "Accelerating XOR-based Erasure Coding using Program Optimization
# Techniques" playbook: a constant GF(2^8) matrix is a GF(2) bitmatrix, so
# applying it is pure XORs of bit-planes.  The schedule is computed ONCE per
# matrix (the per-call cost of the old table-matmul path was the whole
# problem), common XOR subexpressions are eliminated greedily, and execution
# walks the shards in column tiles so every plane of a tile stays cache-hot.


class XorSchedule:
    """A straight-line XOR program for one constant bit matrix.

    Nodes ``0..n_in-1`` are the input bit-planes (input symbol ``k``, bit
    ``i`` → node ``k*8 + i``).  Each op ``(dest, a, b)`` defines node
    ``dest = a ^ b`` (the CSE intermediates, in dependency order).
    ``outputs[j]`` lists the node ids whose XOR is output bit-row ``j``
    (output symbol ``j // 8``, bit ``j % 8``).
    """

    __slots__ = ("n_in", "ops", "outputs", "xor_count")

    def __init__(self, n_in, ops, outputs):
        self.n_in = n_in
        self.ops = ops
        self.outputs = outputs
        self.xor_count = len(ops) + sum(
            max(0, len(o) - 1) for o in outputs
        )


def build_xor_schedule(bitmat: np.ndarray) -> XorSchedule:
    """Compile a (k*8, r*8) bit matrix into an :class:`XorSchedule`.

    Greedy pairwise common-subexpression elimination: repeatedly extract
    the operand pair shared by the most output rows into an intermediate
    node.  Fully deterministic (ties break on the smallest pair), so the
    schedule — and therefore the XOR order — is a pure function of the
    matrix.
    """
    bitmat = np.asarray(bitmat)
    n_in, n_out = bitmat.shape
    sets = [
        set(int(i) for i in np.nonzero(bitmat[:, j])[0])
        for j in range(n_out)
    ]
    ops = []
    next_id = n_in
    while True:
        counts: dict = {}
        for s in sets:
            if len(s) < 2:
                continue
            ss = sorted(s)
            for x in range(len(ss)):
                for y in range(x + 1, len(ss)):
                    p = (ss[x], ss[y])
                    counts[p] = counts.get(p, 0) + 1
        if not counts:
            break
        best_count = max(counts.values())
        if best_count < 2:
            break
        a, b = min(p for p, c in counts.items() if c == best_count)
        ops.append((next_id, a, b))
        for s in sets:
            if a in s and b in s:
                s.discard(a)
                s.discard(b)
                s.add(next_id)
        next_id += 1
    return XorSchedule(n_in, ops, [sorted(s) for s in sets])


_BIT_WEIGHTS = np.left_shift(1, np.arange(8)).astype(np.uint8)


def apply_xor_schedule(
    sched: XorSchedule,
    data: np.ndarray,
    out: np.ndarray = None,
    tile_bytes: int = 1 << 15,
) -> np.ndarray:
    """Run a schedule over shard rows: (k, B) uint8 → (r, B) uint8.

    ``out`` may be a view into the caller's allocation (the parity tail of
    one contiguous buffer).  Columns are processed ``tile_bytes`` at a time
    so the k+r+intermediate bit-planes of a tile fit in cache.
    """
    data = np.ascontiguousarray(data, dtype=np.uint8)
    k, B = data.shape
    assert k * 8 == sched.n_in
    r8 = len(sched.outputs)
    r = r8 // 8
    if out is None:
        out = np.empty((r, B), dtype=np.uint8)
    n_nodes = sched.n_in + len(sched.ops)
    for t0 in range(0, B, tile_bytes):
        tile = data[:, t0:t0 + tile_bytes]
        T = tile.shape[1]
        # decompose: (k, 8, T) bit arrays → packed planes (k*8, ceil(T/8))
        bits = (
            tile[:, None, :] >> np.arange(8, dtype=np.uint8)[None, :, None]
        ) & 1
        planes = np.packbits(bits != 0, axis=-1, bitorder="little")
        planes = planes.reshape(k * 8, -1)
        nodes: list = [None] * n_nodes
        for i in range(k * 8):
            nodes[i] = planes[i]
        for dest, a, b in sched.ops:
            nodes[dest] = nodes[a] ^ nodes[b]
        W = planes.shape[1]
        obits = np.zeros((r8, W), dtype=np.uint8)
        for j, ids in enumerate(sched.outputs):
            if not ids:
                continue
            acc = nodes[ids[0]]
            for nid in ids[1:]:
                acc = acc ^ nodes[nid]
            obits[j] = acc
        # repack: unpack each output plane and recombine the 8 bit rows
        ob = np.unpackbits(
            obits.reshape(r, 8, W), axis=-1, bitorder="little"
        )[..., :T]
        out[:, t0:t0 + T] = (
            ob * _BIT_WEIGHTS[None, :, None]
        ).sum(axis=1, dtype=np.uint8)
    return out


# ---------------------------------------------------------------------------
# Bit-plane lowering (device path)
# ---------------------------------------------------------------------------


def gf_matrix_to_bits(M: np.ndarray) -> np.ndarray:
    """Expand a GF(2^8) matrix (r, k) to its GF(2) bit matrix (k*8, r*8).

    Layout: ``A[k*8 + i, j*8 + b]`` = bit ``b`` of ``gf_mul(M[j, k], 1 << i)``
    (bits LSB-first), so that for data bits ``D`` of shape (..., k*8):
    ``out_bits = (D @ A) & 1`` gives (..., r*8) with
    ``out_bits[..., j*8 + b]`` = bit b of ``XOR_k gf_mul(M[j,k], d_k)``.
    """
    M = np.asarray(M, dtype=np.uint8)
    r, k = M.shape
    powers = np.left_shift(1, np.arange(8)).astype(np.uint8)  # 1<<i
    # prod[j, kk, i] = gf_mul(M[j, kk], 1<<i)
    prod = _MUL_TABLE[M[:, :, None], powers[None, None, :]]
    # bits[j, kk, i, b]
    bits = (prod[..., None] >> np.arange(8)) & 1
    # → (kk, i, j, b) → (k*8, r*8)
    A = bits.transpose(1, 2, 0, 3).reshape(k * 8, r * 8)
    return A.astype(np.int8)


# jnp helpers — imported lazily so the host oracle works without jax.


def bytes_to_bits(x):
    """uint8 (..., K) → int8 bits (..., K*8), LSB-first."""
    import jax.numpy as jnp

    bits = (x[..., None] >> jnp.arange(8, dtype=jnp.uint8)) & 1
    return bits.reshape(*x.shape[:-1], x.shape[-1] * 8).astype(jnp.int8)


def bits_to_bytes(bits):
    """int (..., K*8) bits → uint8 (..., K), LSB-first."""
    import jax.numpy as jnp

    b = bits.reshape(*bits.shape[:-1], bits.shape[-1] // 8, 8).astype(jnp.uint8)
    weights = jnp.left_shift(jnp.uint8(1), jnp.arange(8, dtype=jnp.uint8))
    return (b * weights).sum(axis=-1).astype(jnp.uint8)


def gf_apply_bitmatrix(data, bitmat):
    """Apply a GF(2^8) matrix to byte data on device.

    data: uint8 (..., B, k) — B byte-positions × k input symbols.
    bitmat: int8 (k*8, r*8) from :func:`gf_matrix_to_bits` (constant), or a
    batched (..., k*8, r*8) from :func:`gf_matrix_to_bits_jnp` with leading
    dims broadcast-compatible with ``data`` (``jnp.matmul`` batches it).
    Returns uint8 (..., B, r).

    The contraction is an int8×int8→int32 matmul — on TPU this is a single
    MXU pass; the bit (un)packing fuses into it as elementwise ops.
    """
    import jax.numpy as jnp

    dbits = bytes_to_bits(data)  # (..., B, k*8)
    obits = jnp.matmul(dbits, bitmat, preferred_element_type=jnp.int32) & 1
    return bits_to_bytes(obits)


def gf_mul_jnp(a, b):
    """Elementwise GF(2^8) multiply on device via log/exp gathers.

    For data×data products (both operands runtime values).  Constant-matrix
    products should use :func:`gf_apply_bitmatrix` instead.
    """
    import jax.numpy as jnp

    exp = jnp.asarray(GF_EXP)
    log = jnp.asarray(GF_LOG)
    r = exp[(log[a] + log[b]) % 255]
    nz = (a != 0) & (b != 0)
    return jnp.where(nz, r, 0).astype(jnp.uint8)


def gf_inv_jnp(a):
    """Elementwise GF(2^8) inverse on device; maps 0 → 0 (caller masks)."""
    import jax.numpy as jnp

    exp = jnp.asarray(GF_EXP)
    log = jnp.asarray(GF_LOG)
    r = exp[255 - log[a]]
    return jnp.where(a != 0, r, 0).astype(jnp.uint8)


def gf_inv_matrix_jnp_impl(M, mul, inv, dtype):
    """Field-generic batched Gauss–Jordan on device (char-2 fields: row
    elimination is XOR).  See :func:`gf_inv_matrix_jnp` for semantics;
    :mod:`hbbft_tpu.ops.gf16` reuses this with its own ``mul``/``inv``.
    """
    import jax
    import jax.numpy as jnp

    M = jnp.asarray(M, dtype=dtype)
    n = M.shape[-1]
    batch = M.shape[:-2]
    eye = jnp.broadcast_to(jnp.eye(n, dtype=dtype), (*batch, n, n))
    aug0 = jnp.concatenate([M, eye], axis=-1)  # (..., n, 2n)
    rows = jnp.arange(n)

    def body(col, carry):
        aug, ok = carry
        colvec = aug[..., :, col]  # (..., n)
        cand = (colvec != 0) & (rows >= col)
        ok = ok & jnp.any(cand, axis=-1)
        piv = jnp.argmax(cand, axis=-1)  # first True (or 0 if none — masked)
        # swap rows col ↔ piv via a per-batch permutation gather
        idx = jnp.broadcast_to(rows, (*batch, n))
        piv_b = piv[..., None]
        perm = jnp.where(idx == col, piv_b, jnp.where(idx == piv_b, col, idx))
        aug = jnp.take_along_axis(aug, perm[..., None], axis=-2)
        # normalize the pivot row
        pivot_row = aug[..., col, :]  # (..., 2n)
        pinv = inv(
            jnp.take_along_axis(
                aug[..., col], jnp.broadcast_to(col, (*batch, 1)), axis=-1
            )
        )  # (..., 1) — aug[..., col(row), col(column)]
        pivot_row = mul(pivot_row, pinv)
        aug = jnp.moveaxis(
            jnp.moveaxis(aug, -2, 0).at[col].set(pivot_row), 0, -2
        )
        # eliminate the column everywhere else
        factors = aug[..., :, col]
        factors = factors * (rows != col).astype(dtype)
        aug = aug ^ mul(factors[..., None], aug[..., col, :][..., None, :])
        return aug, ok

    ok0 = jnp.ones(batch, dtype=bool)
    aug, ok = jax.lax.fori_loop(0, n, body, (aug0, ok0))
    return aug[..., n:], ok


def gf_inv_matrix_jnp(M):
    """Batched GF(2^8) matrix inversion on device (Gauss–Jordan).

    M: uint8 (..., n, n) — data-dependent matrices (e.g. the encode-matrix
    rows of each receiver's surviving shard set, which differ per (node,
    proposer) under an adversarial drop pattern, so they must be inverted on
    device).  Returns ``(inv, ok)`` with ``ok`` bool (...,) false for
    singular inputs (their ``inv`` content is garbage; caller masks).

    The column loop is a ``lax.fori_loop`` (n is static, tiny); every step is
    vectorized over the batch.  Partial pivoting picks the first nonzero
    entry at-or-below the diagonal, exactly like the host
    :func:`gf_inv_matrix_np`, so decode matrices are bit-identical.
    """
    import jax.numpy as jnp

    return gf_inv_matrix_jnp_impl(M, gf_mul_jnp, gf_inv_jnp, jnp.uint8)


def gf_matrix_to_bits_jnp(M):
    """Device version of :func:`gf_matrix_to_bits`, batched.

    M: uint8 (..., r, k) → int8 (..., k*8, r*8), same layout as the host
    function (verified bit-identical in tests), for data-dependent matrices
    such as per-(node, proposer) decode matrices.
    """
    import jax.numpy as jnp

    r, k = M.shape[-2:]
    powers = jnp.left_shift(jnp.uint8(1), jnp.arange(8, dtype=jnp.uint8))
    prod = gf_mul_jnp(M[..., None], powers)  # (..., r, k, 8)
    bits = (prod[..., None] >> jnp.arange(8, dtype=jnp.uint8)) & 1
    # (..., r, k, i, b) → (..., k, i, r, b) → (..., k*8, r*8)
    A = jnp.moveaxis(bits, -4, -2)  # (..., k, i, r, b)
    return A.reshape(*M.shape[:-2], k * 8, r * 8).astype(jnp.int8)



"""Batched BLS12-381 base-field (Fp, Fp2) arithmetic for TPU.

The reference's crypto is the ``threshold_crypto`` crate over ``pairing``/
``ff`` — native 64-bit limb arithmetic.  TPUs have no 64-bit integer path
and no carry flags, so this module uses a **13-bit × 30-limb** radix-2¹³
representation in int32 lanes, chosen so that

- a schoolbook product limb (Σ of ≤31 products of 13-bit digits) peaks below
  2³¹ — no overflow before carry propagation,
- modular reduction is *fold-by-rows*: digits ≥ 2³⁹⁰ are replaced by their
  precomputed residues (``2^(13·j) mod p`` rows applied as vector FMAs),
  and the final 381-bit overhang folds bitwise — NO gathers and NO integer
  matmuls anywhere, both of which measured ~ms per op at batch size on this
  TPU (int32 dot_general avoids the MXU; row gathers lower to slow loops).

Two variants share those kernels:
- **canonical** (``fp_add``/``fp_sub``/``fp_mul``): exact ``[0, p)`` digits
  — Kogge–Stone carry resolution + conditional-subtract chains; the general
  and test path.
- **lazy** (``*_lazy``): digits ≤ 2¹³, value an arbitrary residue, rough
  carries only — ~an order of magnitude fewer vector ops; the MSM ladder
  path (see the lazy section below for its soundness conditions).

Everything is elementwise over a leading batch shape — no data-dependent
control flow — so the point ladders in :mod:`hbbft_tpu.ops.gcurve` jit and
vmap cleanly.  Host ground truth: :mod:`hbbft_tpu.crypto.bls12_381`
(pure-Python ints); tests assert exact equality on random residues.
"""

from __future__ import annotations

import numpy as np

from hbbft_tpu.crypto.bls12_381 import P

LIMB_BITS = 13
NL = 30  # 30 × 13 = 390 ≥ 381
MASK = (1 << LIMB_BITS) - 1
FOLD_AT = 29  # limbs below this (29·13 = 377 bits) stay; above get folded


def int_to_limbs(x: int, n: int = NL) -> np.ndarray:
    """Host: python int → little-endian 13-bit limbs (int32)."""
    out = np.zeros(n, dtype=np.int32)
    for i in range(n):
        out[i] = x & MASK
        x >>= LIMB_BITS
    assert x == 0, "value too large for limb count"
    return out


def limbs_to_int(limbs) -> int:
    """Host: limb array (little-endian) → python int."""
    x = 0
    for i, v in enumerate(np.asarray(limbs).tolist()):
        x += int(v) << (LIMB_BITS * i)
    return x


_LIMB_WEIGHTS = np.array(
    [1 << (LIMB_BITS * i) for i in range(NL)], dtype=object
)


def ints_to_limbs_batch(xs, n: int = NL) -> np.ndarray:
    """Host: list of ints in [0, 2^(13n)) → (B, n) int32 limbs, vectorized
    (bytes → unpackbits → 13-bit regroup; ~100× the per-int Python loop)."""
    nbytes = (LIMB_BITS * n + 7) // 8
    buf = b"".join(x.to_bytes(nbytes, "little") for x in xs)
    raw = np.frombuffer(buf, dtype=np.uint8).reshape(len(xs), nbytes)
    bits = np.unpackbits(raw, axis=1, bitorder="little")[:, : LIMB_BITS * n]
    w = (1 << np.arange(LIMB_BITS, dtype=np.int32)).astype(np.int32)
    return (bits.reshape(len(xs), n, LIMB_BITS) * w).sum(-1, dtype=np.int32)


def limbs_to_ints_batch(limbs) -> list:
    """Host: (B, NL) limb array (any digit magnitudes — lazy values allowed)
    → list of python ints, via one object-dtype matvec instead of a per-limb
    Python loop."""
    arr = np.asarray(limbs)
    return list(arr.astype(object) @ _LIMB_WEIGHTS[: arr.shape[-1]])


def bits_batch(xs, nbits: int) -> np.ndarray:
    """Host: ints → (B, nbits) int32 little-endian bits, vectorized."""
    nbytes = (nbits + 7) // 8
    buf = b"".join(x.to_bytes(nbytes, "little") for x in xs)
    raw = np.frombuffer(buf, dtype=np.uint8).reshape(len(xs), nbytes)
    return (
        np.unpackbits(raw, axis=1, bitorder="little")[:, :nbits]
        .astype(np.int32)
    )


P_LIMBS = int_to_limbs(P)

# fold rows for full-product reduction: position j in [NL, 2*NL) contributes
# 2^(13 j) mod p  (NL rows of NL limbs)
_FOLD_HI = np.stack(
    [int_to_limbs((1 << (LIMB_BITS * j)) % P) for j in range(NL, 2 * NL + 1)]
)  # 31 rows: conv output carries one digit past 2·NL

# final 377-bit fold: v = (v mod 2^377) + (h · 2^377 mod p) for h = v >> 377
# < 2^14.  h is decomposed into bits and folded with 14 constant residue
# rows (2^(377+t) mod p) — NO lookup table: a row gather on TPU costs ~ms at
# batch size while 14 masked row-adds are pure VPU elementwise.
# (v mod 2^377) < p/13.6 and the fold < 14·p/…, one conditional subtract
# away from canonical [0, p).
_FOLD377_BITS = np.stack(
    [int_to_limbs((1 << (LIMB_BITS * FOLD_AT + t)) % P) for t in range(14)]
)


# complement constant: 2^390 − p (30 limbs) — lets "v − p" be computed as
# the all-positive "v + C" with the 2^390 bit as the ≥-p indicator.
C_LIMBS = int_to_limbs((1 << (LIMB_BITS * NL)) - P)

# ---------------------------------------------------------------------------
# device helpers (all take/return int32 (..., n) little-endian limb arrays)
# ---------------------------------------------------------------------------
#
# Carry discipline: all intermediate limb values are kept NON-NEGATIVE
# (subtraction goes through the complement constant above), so carries are
# always ≥ 0.  `_carry` is exact for any limbs < 2³¹: three rough passes
# shrink every limb to ≤ 2¹³, then a Kogge–Stone generate/propagate scan
# resolves the remaining ±1 ripple chains in log₂ depth — a plain k-pass
# loop would need one pass per limb in the worst case (e.g. 0x1FFF…FFF + 1),
# which adversarial field elements can and do produce.


def _carry(t):
    """Exact carry propagation; limbs must be in [0, 2³¹)."""
    import jax.numpy as jnp

    for _ in range(3):
        c = t >> LIMB_BITS
        t = t & MASK
        t = t.at[..., 1:].add(c[..., :-1])
    # now limbs ∈ [0, 2^13]; resolve the ±1 chains exactly
    g = (t >> LIMB_BITS).astype(jnp.int32)       # generates a carry
    p = (t == MASK).astype(jnp.int32)            # propagates one
    # Kogge–Stone scan of (g, p) under (g2|p2&g1, p2&p1), shifted so that
    # carry_in[i] = combined (g, p) of limbs < i applied to carry 0.
    n = t.shape[-1]
    G, Pp = g, p
    shift = 1
    while shift < n:
        Gs = jnp.pad(G[..., :-shift], [(0, 0)] * (G.ndim - 1) + [(shift, 0)])
        Ps = jnp.pad(Pp[..., :-shift], [(0, 0)] * (G.ndim - 1) + [(shift, 0)])
        G = Gs * Pp | G
        Pp = Pp * Ps
        shift *= 2
    cin = jnp.pad(G[..., :-1], [(0, 0)] * (G.ndim - 1) + [(1, 0)])
    return (t + cin) & MASK


# complements 2^390 − k·p for the binary conditional-subtract chain
_CK_LIMBS = {
    k: int_to_limbs((1 << (LIMB_BITS * NL)) - k * P) for k in (1, 2, 4, 8)
}


def _cond_sub_kp(v, k: int):
    """v in [0, 2kp) over NL limbs → [0, kp): subtract k·p where v ≥ k·p."""
    import jax.numpy as jnp

    c = jnp.asarray(_CK_LIMBS[k])
    s = jnp.concatenate(
        [v + c, jnp.zeros((*v.shape[:-1], 1), v.dtype)], -1
    )
    s = _carry(s)  # value v + 2^390 − kp; bit 390 set ⟺ v ≥ kp
    ge = s[..., NL] > 0
    return jnp.where(ge[..., None], s[..., :NL], v)


def _cond_sub_p(v):
    return _cond_sub_kp(v, 1)


def _reduce377(v):
    """(..., NL+1) limbs (13-bit digits), value < 2^391 → canonical [0, p).

    The 377-bit overhang h = v ≫ 377 < 2¹⁴ folds in bitwise against the
    ``2^(377+t) mod p`` residue rows (no lookup-table gather — row gathers
    cost milliseconds at batch on TPU), leaving a value < 2^377 + 14p < 16p
    that a binary 8p/4p/2p/p conditional-subtract chain reduces exactly."""
    import jax.numpy as jnp

    rows = jnp.asarray(_FOLD377_BITS)
    h = v[..., FOLD_AT] + (v[..., FOLD_AT + 1] << LIMB_BITS)  # v >> 377 < 2^14
    t = v.at[..., FOLD_AT:].set(0)[..., :NL]
    for tb in range(14):
        bit = (h >> tb) & 1
        t = t + bit[..., None] * rows[tb]
    t = _carry(t)  # value < 2^377 + 14p < 16p
    for k in (8, 4, 2, 1):
        t = _cond_sub_kp(t, k)
    return t


def fp_add(a, b):
    import jax.numpy as jnp

    t = jnp.concatenate([a + b, jnp.zeros((*a.shape[:-1], 1), a.dtype)], -1)
    return _reduce377(_carry(t))


def fp_sub(a, b):
    """a − b mod p via complement: a + ~b + 1 + p − 2^390 (all positive)."""
    import jax.numpy as jnp

    p = jnp.asarray(P_LIMBS)
    bc = MASK - b  # valuewise: (2^390 − 1) − b
    t = a + bc + p
    t = t.at[..., 0].add(1)  # … + 1  ⇒ value = a − b + p + 2^390
    t = jnp.concatenate([t, jnp.zeros((*t.shape[:-1], 1), t.dtype)], -1)
    t = _carry(t)
    t = t.at[..., NL].set(0)  # drop the 2^390 bit (always set: a−b+p > 0)
    return _cond_sub_p(t[..., :NL])


def fp_neg(a):
    import jax.numpy as jnp

    return fp_sub(jnp.zeros_like(a), a)


def _conv_sched(a, b):
    """Schoolbook convolution t_k = Σ_{i+j=k} a_i b_j as 30 shifted FMAs.

    Both the matmul formulation ((B, 900) @ one-hot) and any gather-based
    scheme are pathologically slow on this TPU (int32 dot_general avoids the
    MXU; row gathers cost ~ms at batch).  Shifted multiply-accumulates are
    pure VPU elementwise and fuse."""
    import jax.numpy as jnp

    # 2·NL + 1 limbs: with 13-bit digits the top product a_29·b_29 can be
    # 2^26, whose carry would otherwise fall off the end of a 60-limb array
    t = jnp.zeros((*a.shape[:-1], 2 * NL + 1), dtype=jnp.int32)
    for i in range(NL):
        t = t.at[..., i : i + NL].add(a[..., i : i + 1] * b)
    return t


def _fold_hi(t):
    """Fold digit positions ≥ NL of a carried 61-digit value against the
    precomputed 2^(13j) mod p rows.  Returns 30 limbs, values < 2^31
    (Σ of 31 ≤ 2^26 products + 2^13 = 2.09e9 < 2^31)."""
    import jax.numpy as jnp

    lo = t[..., :NL]
    hi = t[..., NL:]
    fold = jnp.asarray(_FOLD_HI)
    acc = lo
    for j in range(NL + 1):
        acc = acc + hi[..., j : j + 1] * fold[j]
    return acc


def fp_mul(a, b):
    """Canonical modular product; inputs canonical (..., NL)."""
    import jax.numpy as jnp

    batch = a.shape[:-1]
    fold = jnp.asarray(_FOLD_HI)
    t = _carry(_conv_sched(a, b))  # 13-bit digits over 60 positions
    # fold positions ≥ NL; Σ of 30 digit×p terms leaves a value < 2^399, so
    # one more single-limb fold is needed before the 377-bit reduction
    # (which requires < 2^391).
    acc = _fold_hi(t)
    acc = jnp.concatenate(
        [acc, jnp.zeros((*batch, 1), acc.dtype)], -1
    )
    acc = _carry(acc)  # 31 digits; limb 30 ≤ 2^9  (value < 2^399)
    acc = acc.at[..., NL].set(0)[..., :NL] + acc[..., NL : NL + 1] * fold[0]
    acc = jnp.concatenate(
        [acc, jnp.zeros((*batch, 1), acc.dtype)], -1
    )
    acc = _carry(acc)  # value < 2^390 + 2^9·p < 2^391
    return _reduce377(acc)


def fp_sqr(a):
    return fp_mul(a, a)


def fp_is_zero(a):
    import jax.numpy as jnp

    return jnp.all(a == 0, axis=-1)


def fp_eq(a, b):
    import jax.numpy as jnp

    return jnp.all(a == b, axis=-1)


def fp_select(mask, a, b):
    """mask (...,) bool → a where mask else b (limb arrays)."""
    import jax.numpy as jnp

    return jnp.where(mask[..., None], a, b)


# ---------------------------------------------------------------------------
# Fp2 = Fp[u]/(u²+1): pairs (re, im) of limb arrays
# ---------------------------------------------------------------------------


def fp2_add(a, b):
    return (fp_add(a[0], b[0]), fp_add(a[1], b[1]))


def fp2_sub(a, b):
    return (fp_sub(a[0], b[0]), fp_sub(a[1], b[1]))


def fp2_neg(a):
    return (fp_neg(a[0]), fp_neg(a[1]))


def fp2_mul(a, b):
    # Karatsuba, same formula as the host oracle
    t0 = fp_mul(a[0], b[0])
    t1 = fp_mul(a[1], b[1])
    t2 = fp_mul(fp_add(a[0], a[1]), fp_add(b[0], b[1]))
    return (fp_sub(t0, t1), fp_sub(t2, fp_add(t0, t1)))


def fp2_sqr(a):
    t0 = fp_mul(fp_add(a[0], a[1]), fp_sub(a[0], a[1]))
    t1 = fp_mul(a[0], a[1])
    return (t0, fp_add(t1, t1))


def fp2_is_zero(a):
    import jax.numpy as jnp

    return fp_is_zero(a[0]) & fp_is_zero(a[1])


def fp2_eq(a, b):
    return fp_eq(a[0], b[0]) & fp_eq(a[1], b[1])


def fp2_select(mask, a, b):
    return (fp_select(mask, a[0], b[0]), fp_select(mask, a[1], b[1]))


# ---------------------------------------------------------------------------
# LAZY (non-canonical) field variant — the performance path
# ---------------------------------------------------------------------------
#
# Invariant: 30 limbs, every digit in [0, 2^13] (note: 2^13 itself allowed),
# value an arbitrary residue < ~2^390.01.  No Kogge–Stone scans, no
# conditional subtracts, no canonical form: rough carry passes and residue-
# row folds only — every op is a short chain of elementwise int32 vector
# instructions, an order of magnitude cheaper than the canonical path.
#
# Zero/equality are DIGIT-based here and therefore sound only when values
# that are ≡ 0 (mod p) are exactly digit-zero.  That holds throughout the
# complete-addition ladders of `gcurve.scalar_mul` PROVIDED scalars are
# < 2^128 (see crypto/batch.py): the P==±Q collision in a double-and-add
# ladder requires a bit-prefix m with 2m ≡ ±1 (mod r), i.e. m = (r±1)/2 ≥
# 2^253 — unreachable from scalars below 2^128 — and the infinity flag
# (Z = 0) propagates as exact digit-zero through these ops.  Canonicalize on
# the HOST (limbs_to_int % P) at boundaries.


def _carry_rough(t):
    """3 rough passes: limbs < 2^31 → digits ≤ 2^13 (±1 chains unresolved —
    fine for the lazy invariant, which allows digit == 2^13)."""
    for _ in range(3):
        c = t >> LIMB_BITS
        t = t & MASK
        t = t.at[..., 1:].add(c[..., :-1])
    return t


def _squeeze_lazy(acc):
    """(…, NL) limbs with values < 2^31 → lazy-invariant 30 digits.

    Appends a carry limb, does rough carries, then folds the top digit back
    through 2^390 mod p repeatedly.  Each fold with a nonzero top digit
    strictly decreases the value by ≥ 2^390 − 2^10·p, so 4 rounds reach
    top-digit 0 from any value < 2^399."""
    import jax.numpy as jnp

    row0 = jnp.asarray(_FOLD_HI[0])
    acc = jnp.concatenate(
        [acc, jnp.zeros((*acc.shape[:-1], 1), acc.dtype)], -1
    )
    acc = _carry_rough(acc)
    for _ in range(4):
        top = acc[..., NL : NL + 1]
        acc = acc.at[..., NL].set(0)
        acc = acc.at[..., :NL].add(top * row0)
        acc = _carry_rough(acc)
    return acc[..., :NL]


def fp_mul_lazy(a, b):
    t = _carry_rough(_conv_sched(a, b))
    return _squeeze_lazy(_fold_hi(t))


def fp_add_lazy(a, b):
    return _squeeze_lazy(a + b)


# constant ≡ −2·(2^390 − 1) (mod p), canonical — completes the digitwise
# complement in fp_sub_lazy
_SUBC_LIMBS = int_to_limbs((-2 * ((1 << (LIMB_BITS * NL)) - 1)) % P)


def fp_sub_lazy(a, b):
    """a − b (mod p), lazy: a + (2·MASK − b_digits) + const.

    (2·MASK − b_i) ≥ 0 for digits ≤ 2^13 and represents 2·(2^390−1) − b;
    adding the precomputed ≡ −2·(2^390−1) constant makes the total ≡ a − b."""
    import jax.numpy as jnp

    t = a + (2 * MASK - b) + jnp.asarray(_SUBC_LIMBS)
    return _squeeze_lazy(t)


def fp_neg_lazy(a):
    import jax.numpy as jnp

    return fp_sub_lazy(jnp.zeros_like(a), a)


def fp_is_zero_digits(a):
    """Digit-zero test (see module invariant for when this is sound)."""
    import jax.numpy as jnp

    return jnp.all(a == 0, axis=-1)


def fp2_add_lazy(a, b):
    return (fp_add_lazy(a[0], b[0]), fp_add_lazy(a[1], b[1]))


def fp2_sub_lazy(a, b):
    return (fp_sub_lazy(a[0], b[0]), fp_sub_lazy(a[1], b[1]))


def fp2_neg_lazy(a):
    return (fp_neg_lazy(a[0]), fp_neg_lazy(a[1]))


def fp2_mul_lazy(a, b):
    t0 = fp_mul_lazy(a[0], b[0])
    t1 = fp_mul_lazy(a[1], b[1])
    t2 = fp_mul_lazy(fp_add_lazy(a[0], a[1]), fp_add_lazy(b[0], b[1]))
    return (fp_sub_lazy(t0, t1), fp_sub_lazy(t2, fp_add_lazy(t0, t1)))


def fp2_sqr_lazy(a):
    t0 = fp_mul_lazy(fp_add_lazy(a[0], a[1]), fp_sub_lazy(a[0], a[1]))
    t1 = fp_mul_lazy(a[0], a[1])
    return (t0, fp_add_lazy(t1, t1))


def fp2_is_zero_digits(a):
    return fp_is_zero_digits(a[0]) & fp_is_zero_digits(a[1])


# host conversion helpers for Fp2 / points ----------------------------------


def fp2_to_limbs(x) -> np.ndarray:
    """(re, im) python ints → (2, NL) int32."""
    return np.stack([int_to_limbs(x[0] % P), int_to_limbs(x[1] % P)])


def limbs_to_fp2(a) -> tuple:
    return (limbs_to_int(a[0]) % P, limbs_to_int(a[1]) % P)

// BLS12-381 full-scheme CPU oracle.
//
// Native ground truth for hbbft_tpu/crypto/{bls12_381,tc}.py and the device
// kernels in ops/{fp381,gcurve}.py — the role the `threshold_crypto` crate
// plays for the reference (SURVEY §2.2 row 2).  Same algorithms as the host
// Python (affine Miller loop, cube-of-ate final exponentiation, w-basis
// Fp12, try-and-increment hashing), so parity tests can compare exact
// bytes, not just accept/reject outcomes.  Constants come from
// bls381_constants.h, generated from the Python derivation at build time.
//
// Field arithmetic: 64-bit-limb Montgomery (CIOS) via unsigned __int128.
// Exposed through a C ABI on the host serialization formats (G1 = 97
// bytes, G2 = 193, scalars = 32 big-endian) and loaded with ctypes.

#include <array>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <vector>

#include "bls381_constants.h"

extern "C" void hbbft_sha3_256(const uint8_t* data, int64_t len,
                               uint8_t* out32);

#if defined(__x86_64__) && defined(__ADX__) && defined(__BMI2__)
// ADX/BMI2 dual-carry-chain Montgomery mul (bls381_mont.S) — ~4× the
// __int128 C fallback below, which stays as its differential-test oracle.
// The guard ties dispatch to the BUILD host's features; the library is
// always built on the machine that runs it (first-use make in oracle.py).
#define HBBFT_MONT_ASM 1
extern "C" void hbbft_mont_mul_384(uint64_t* out, const uint64_t* a,
                                   const uint64_t* b, const uint64_t* p,
                                   uint64_t n0);
#endif

namespace bls {

typedef unsigned __int128 u128;
typedef uint64_t u64;

// ---------------------------------------------------------------------------
// generic N-limb Montgomery modular arithmetic
// ---------------------------------------------------------------------------

template <int N>
struct Mod {
  u64 p[N];
  u64 n0;      // -p^{-1} mod 2^64
  u64 r2[N];   // 2^{128N} mod p
  u64 one[N];  // 2^{64N} mod p  (Montgomery form of 1)

  static int cmp(const u64* a, const u64* b) {
    for (int i = N - 1; i >= 0; --i) {
      if (a[i] != b[i]) return a[i] < b[i] ? -1 : 1;
    }
    return 0;
  }

  static bool is_zero(const u64* a) {
    for (int i = 0; i < N; ++i)
      if (a[i]) return false;
    return true;
  }

  // out = a + b, returns carry
  static u64 raw_add(const u64* a, const u64* b, u64* out) {
    u128 c = 0;
    for (int i = 0; i < N; ++i) {
      c += (u128)a[i] + b[i];
      out[i] = (u64)c;
      c >>= 64;
    }
    return (u64)c;
  }

  // out = a - b, returns borrow
  static u64 raw_sub(const u64* a, const u64* b, u64* out) {
    u128 br = 0;
    for (int i = 0; i < N; ++i) {
      u128 d = (u128)a[i] - b[i] - br;
      out[i] = (u64)d;
      br = (d >> 64) & 1;
    }
    return (u64)br;
  }

  void init(const u64* prime) {
    memcpy(p, prime, sizeof(p));
    // n0 via Newton on 64 bits
    u64 x = 1;
    for (int i = 0; i < 6; ++i) x *= 2 - p[0] * x;
    n0 = (u64)(0 - x);
    // one = 2^{64N} mod p by repeated doubling of 1
    u64 t[N] = {1};
    for (int i = 0; i < 64 * N; ++i) dbl_mod(t);
    memcpy(one, t, sizeof(one));
    // r2 = 2^{128N} mod p: keep doubling
    for (int i = 0; i < 64 * N; ++i) dbl_mod(t);
    memcpy(r2, t, sizeof(r2));
  }

  void dbl_mod(u64* a) const {
    u64 t[N];
    u64 carry = raw_add(a, a, t);
    if (carry || cmp(t, p) >= 0) raw_sub(t, p, t);
    memcpy(a, t, sizeof(u64) * N);
  }

  void add(const u64* a, const u64* b, u64* out) const {
    u64 t[N];
    u64 carry = raw_add(a, b, t);
    if (carry || cmp(t, p) >= 0) raw_sub(t, p, t);
    memcpy(out, t, sizeof(t));
  }

  void sub(const u64* a, const u64* b, u64* out) const {
    u64 t[N];
    if (raw_sub(a, b, t)) raw_add(t, p, t);
    memcpy(out, t, sizeof(t));
  }

  void neg(const u64* a, u64* out) const {
    if (is_zero(a)) {
      memset(out, 0, sizeof(u64) * N);
      return;
    }
    u64 t[N];
    raw_sub(p, a, t);
    memcpy(out, t, sizeof(t));
  }

  // CIOS Montgomery multiplication
  void mul(const u64* a, const u64* b, u64* out) const {
#ifdef HBBFT_MONT_ASM
    if constexpr (N == 6) {
      hbbft_mont_mul_384(out, a, b, p, n0);
      return;
    }
#endif
    u64 t[N + 2];
    memset(t, 0, sizeof(t));
    for (int i = 0; i < N; ++i) {
      u128 c = 0;
      for (int j = 0; j < N; ++j) {
        c += (u128)t[j] + (u128)a[i] * b[j];
        t[j] = (u64)c;
        c >>= 64;
      }
      c += t[N];
      t[N] = (u64)c;
      t[N + 1] = (u64)(c >> 64);
      u64 m = t[0] * n0;
      c = (u128)t[0] + (u128)m * p[0];
      c >>= 64;
      for (int j = 1; j < N; ++j) {
        c += (u128)t[j] + (u128)m * p[j];
        t[j - 1] = (u64)c;
        c >>= 64;
      }
      c += t[N];
      t[N - 1] = (u64)c;
      t[N] = t[N + 1] + (u64)(c >> 64);
    }
    if (t[N] || cmp(t, p) >= 0) raw_sub(t, p, t);
    memcpy(out, t, sizeof(u64) * N);
  }

  void sqr(const u64* a, u64* out) const { mul(a, a, out); }

  void from_raw(const u64* raw, u64* out) const { mul(raw, r2, out); }

  void to_raw(const u64* m, u64* out) const {
    u64 u[N] = {1};
    mul(m, u, out);
  }

  // out = base^e (e raw little-endian, nlimbs), Montgomery in/out
  void pow(const u64* base, const u64* e, int nlimbs, u64* out) const {
    u64 acc[N];
    memcpy(acc, one, sizeof(acc));
    int bits = nlimbs * 64;
    for (int i = bits - 1; i >= 0; --i) {
      sqr(acc, acc);
      if ((e[i / 64] >> (i % 64)) & 1) mul(acc, base, acc);
    }
    memcpy(out, acc, sizeof(acc));
  }

  void inv(const u64* a, u64* out) const {
    // p - 2 exponent supplied by caller wrappers; generic: compute here
    u64 e[N];
    u64 two[N] = {2};
    raw_sub(p, two, e);
    pow(a, e, N, out);
  }
};

static Mod<6> FP;
static Mod<4> FR;
static bool g_init = false;

struct Fp2 {
  u64 a[6];
  u64 b[6];
};

static Fp2 FP2_ZERO_, FP2_ONE_;
static Fp2 GAMMA_M[6];
static Fp2 B2_M;       // 4(u+1) in Montgomery
static u64 B1_M[6];    // 4
static u64 HALF_M[6];  // 1/2
static Fp2 PSI_CX_M, PSI_CY_M;  // ψ endomorphism constants (Montgomery)
static u64 GLV_BETA_M[6];       // G1 endomorphism β (Montgomery)

static void init_all() {
  if (g_init) return;
  FP.init(BLS_P);
  FR.init(BLS_R);
  memset(&FP2_ZERO_, 0, sizeof(FP2_ZERO_));
  memset(&FP2_ONE_, 0, sizeof(FP2_ONE_));
  memcpy(FP2_ONE_.a, FP.one, sizeof(FP.one));
  for (int i = 0; i < 6; ++i) {
    FP.from_raw(BLS_GAMMA[i][0], GAMMA_M[i].a);
    FP.from_raw(BLS_GAMMA[i][1], GAMMA_M[i].b);
  }
  u64 four[6] = {4};
  FP.from_raw(four, B1_M);
  memcpy(B2_M.a, B1_M, sizeof(B1_M));
  memcpy(B2_M.b, B1_M, sizeof(B1_M));
  FP.from_raw(BLS_HALF, HALF_M);
  FP.from_raw(BLS_PSI_CX[0], PSI_CX_M.a);
  FP.from_raw(BLS_PSI_CX[1], PSI_CX_M.b);
  FP.from_raw(BLS_PSI_CY[0], PSI_CY_M.a);
  FP.from_raw(BLS_PSI_CY[1], PSI_CY_M.b);
  FP.from_raw(BLS_GLV_BETA, GLV_BETA_M);
  g_init = true;
}

// ---------------------------------------------------------------------------
// Fp2 (mirrors host: Karatsuba, ξ = 1 + u)
// ---------------------------------------------------------------------------

static void f2_add(const Fp2& x, const Fp2& y, Fp2& o) {
  FP.add(x.a, y.a, o.a);
  FP.add(x.b, y.b, o.b);
}
static void f2_sub(const Fp2& x, const Fp2& y, Fp2& o) {
  FP.sub(x.a, y.a, o.a);
  FP.sub(x.b, y.b, o.b);
}
static void f2_neg(const Fp2& x, Fp2& o) {
  FP.neg(x.a, o.a);
  FP.neg(x.b, o.b);
}
static void f2_mul(const Fp2& x, const Fp2& y, Fp2& o) {
  u64 t0[6], t1[6], sa[6], sb[6], t2[6];
  FP.mul(x.a, y.a, t0);
  FP.mul(x.b, y.b, t1);
  FP.add(x.a, x.b, sa);
  FP.add(y.a, y.b, sb);
  FP.mul(sa, sb, t2);
  FP.sub(t0, t1, o.a);
  u64 s[6];
  FP.add(t0, t1, s);
  FP.sub(t2, s, o.b);
}
static void f2_sqr(const Fp2& x, Fp2& o) {
  u64 s[6], d[6], t[6];
  FP.add(x.a, x.b, s);
  FP.sub(x.a, x.b, d);
  FP.mul(x.a, x.b, t);
  FP.mul(s, d, o.a);
  FP.add(t, t, o.b);
}
static void f2_mul_xi(const Fp2& x, Fp2& o) {  // (a+bu)(1+u) = (a−b) + (a+b)u
  u64 na[6], nb[6];
  FP.sub(x.a, x.b, na);
  FP.add(x.a, x.b, nb);
  memcpy(o.a, na, sizeof(na));
  memcpy(o.b, nb, sizeof(nb));
}
static void f2_conj(const Fp2& x, Fp2& o) {
  memcpy(o.a, x.a, sizeof(x.a));
  FP.neg(x.b, o.b);
}
static bool f2_is_zero(const Fp2& x) {
  return Mod<6>::is_zero(x.a) && Mod<6>::is_zero(x.b);
}
static void f2_inv(const Fp2& x, Fp2& o) {
  u64 n[6], t[6], ninv[6];
  FP.sqr(x.a, n);
  FP.sqr(x.b, t);
  FP.add(n, t, n);  // norm = a² + b²
  FP.pow(n, BLS_P_M2, 6, ninv);
  FP.mul(x.a, ninv, o.a);
  u64 nb[6];
  FP.neg(x.b, nb);
  FP.mul(nb, ninv, o.b);
}
static void f2_scal_small(const Fp2& x, int k, Fp2& o) {
  Fp2 acc = FP2_ZERO_;
  for (int i = 0; i < k; ++i) f2_add(acc, x, acc);
  o = acc;
}

// Jacobi symbol of a (Montgomery in) over p — binary algorithm on raw
// limbs, ~1000× cheaper than the Euler-criterion pow.  Used as the QR
// pre-test in hash-to-curve: χ_Fp2(g) = jacobi(norm(g), p), so losing
// try-and-increment candidates cost no field exponentiations.
static int jacobi_m(const u64* a_m) {
  u64 a[6], n[6];
  FP.to_raw(a_m, a);      // a < p already
  memcpy(n, FP.p, sizeof(n));
  int t = 1;
  auto is_one = [](const u64* x) {
    if (x[0] != 1) return false;
    for (int i = 1; i < 6; ++i)
      if (x[i]) return false;
    return true;
  };
  auto shr1 = [](u64* x) {
    for (int i = 0; i < 5; ++i) x[i] = (x[i] >> 1) | (x[i + 1] << 63);
    x[5] >>= 1;
  };
  while (!Mod<6>::is_zero(a)) {
    while (!(a[0] & 1)) {
      shr1(a);
      u64 r8 = n[0] & 7;
      if (r8 == 3 || r8 == 5) t = -t;
    }
    if ((a[0] & 3) == 3 && (n[0] & 3) == 3) t = -t;
    u64 tmp[6];
    memcpy(tmp, a, sizeof(tmp));
    memcpy(a, n, sizeof(a));
    memcpy(n, tmp, sizeof(n));  // swap; now reduce a mod n (n odd, a < 2^384)
    while (Mod<6>::cmp(a, n) >= 0) {
      // subtract the largest n·2^s ≤ a (binary reduction, O(384) total)
      u64 t2[6];
      memcpy(t2, n, sizeof(t2));
      while (true) {
        u64 t3[6];
        bool of = t2[5] >> 63;
        for (int i = 5; i > 0; --i) t3[i] = (t2[i] << 1) | (t2[i - 1] >> 63);
        t3[0] = t2[0] << 1;
        if (of || Mod<6>::cmp(t3, a) > 0) break;
        memcpy(t2, t3, sizeof(t2));
      }
      Mod<6>::raw_sub(a, t2, a);
    }
  }
  return is_one(n) ? t : 0;
}

static bool fp_sqrt(const u64* a, u64* out) {  // Montgomery in/out
  u64 r[6], chk[6];
  FP.pow(a, BLS_SQRT_EXP, 6, r);
  FP.sqr(r, chk);
  if (Mod<6>::cmp(chk, a) != 0) return false;
  memcpy(out, r, sizeof(r));
  return true;
}

static bool f2_sqrt(const Fp2& x, Fp2& o) {  // mirrors host fp2_sqrt
  if (f2_is_zero(x)) {
    o = FP2_ZERO_;
    return true;
  }
  if (Mod<6>::is_zero(x.b)) {
    u64 s[6];
    if (fp_sqrt(x.a, s)) {
      memcpy(o.a, s, sizeof(s));
      memset(o.b, 0, sizeof(o.b));
      return true;
    }
    u64 na[6];
    FP.neg(x.a, na);
    if (!fp_sqrt(na, s)) return false;
    memset(o.a, 0, sizeof(o.a));
    memcpy(o.b, s, sizeof(s));
    return true;
  }
  u64 n[6], t[6], s[6];
  FP.sqr(x.a, n);
  FP.sqr(x.b, t);
  FP.add(n, t, n);
  if (!fp_sqrt(n, s)) return false;
  for (int sign = 0; sign < 2; ++sign) {
    u64 sg[6], half[6], alpha[6];
    if (sign == 0)
      memcpy(sg, s, sizeof(sg));
    else
      FP.neg(s, sg);
    FP.add(x.a, sg, half);
    FP.mul(half, HALF_M, half);
    // Jacobi pre-test picks the working sign branch without paying a
    // full exponentiation on the losing one (χ((a±s)/2) decides)
    if (jacobi_m(half) != 1) continue;
    if (!fp_sqrt(half, alpha) || Mod<6>::is_zero(alpha)) continue;
    u64 denom[6], dinv[6], beta[6];
    FP.add(alpha, alpha, denom);
    FP.pow(denom, BLS_P_M2, 6, dinv);
    FP.mul(x.b, dinv, beta);
    Fp2 cand, chk;
    memcpy(cand.a, alpha, sizeof(alpha));
    memcpy(cand.b, beta, sizeof(beta));
    f2_sqr(cand, chk);
    if (Mod<6>::cmp(chk.a, x.a) == 0 && Mod<6>::cmp(chk.b, x.b) == 0) {
      o = cand;
      return true;
    }
  }
  return false;
}

// ---------------------------------------------------------------------------
// Fp12 in the w-basis (mirrors host)
// ---------------------------------------------------------------------------

struct Fp12 {
  Fp2 c[6];
};

static Fp12 f12_one() {
  Fp12 o;
  for (int i = 0; i < 6; ++i) o.c[i] = FP2_ZERO_;
  o.c[0] = FP2_ONE_;
  return o;
}

static void f12_mul(const Fp12& x, const Fp12& y, Fp12& o) {
  Fp2 acc[11];
  for (int i = 0; i < 11; ++i) acc[i] = FP2_ZERO_;
  Fp2 t;
  for (int i = 0; i < 6; ++i)
    for (int j = 0; j < 6; ++j) {
      f2_mul(x.c[i], y.c[j], t);
      f2_add(acc[i + j], t, acc[i + j]);
    }
  Fp12 r;
  for (int k = 0; k < 6; ++k) r.c[k] = acc[k];
  for (int k = 6; k < 11; ++k) {
    f2_mul_xi(acc[k], t);
    f2_add(r.c[k - 6], t, r.c[k - 6]);
  }
  o = r;
}

static void f12_sqr(const Fp12& x, Fp12& o) { f12_mul(x, x, o); }

static void f12_conj(const Fp12& x, Fp12& o) {
  Fp12 r = x;
  f2_neg(x.c[1], r.c[1]);
  f2_neg(x.c[3], r.c[3]);
  f2_neg(x.c[5], r.c[5]);
  o = r;
}

// Fp6 helpers over v = w² (for inversion), mirroring the host
typedef Fp2 Fp6[3];
static void f6_mul(const Fp6& a, const Fp6& b, Fp6& o) {
  Fp2 t[5], tmp;
  for (int i = 0; i < 5; ++i) t[i] = FP2_ZERO_;
  for (int i = 0; i < 3; ++i)
    for (int j = 0; j < 3; ++j) {
      f2_mul(a[i], b[j], tmp);
      f2_add(t[i + j], tmp, t[i + j]);
    }
  Fp2 r0, r1;
  f2_mul_xi(t[3], r0);
  f2_add(t[0], r0, o[0]);
  f2_mul_xi(t[4], r1);
  f2_add(t[1], r1, o[1]);
  o[2] = t[2];
}
static void f6_sub(const Fp6& a, const Fp6& b, Fp6& o) {
  for (int i = 0; i < 3; ++i) f2_sub(a[i], b[i], o[i]);
}
static void f6_neg(const Fp6& a, Fp6& o) {
  for (int i = 0; i < 3; ++i) f2_neg(a[i], o[i]);
}
static void f6_inv(const Fp6& x, Fp6& o) {
  Fp2 c0, c1, c2, t, t2, norm, ninv;
  f2_sqr(x[0], c0);
  f2_mul(x[1], x[2], t);
  f2_mul_xi(t, t);
  f2_sub(c0, t, c0);
  f2_sqr(x[2], t);
  f2_mul_xi(t, t);
  f2_mul(x[0], x[1], t2);
  f2_sub(t, t2, c1);
  f2_sqr(x[1], t);
  f2_mul(x[0], x[2], t2);
  f2_sub(t, t2, c2);
  // norm = x0·c0 + ξ(x2·c1 + x1·c2)
  f2_mul(x[2], c1, t);
  f2_mul(x[1], c2, t2);
  f2_add(t, t2, t);
  f2_mul_xi(t, t);
  f2_mul(x[0], c0, t2);
  f2_add(t2, t, norm);
  f2_inv(norm, ninv);
  f2_mul(c0, ninv, o[0]);
  f2_mul(c1, ninv, o[1]);
  f2_mul(c2, ninv, o[2]);
}

static void f12_inv(const Fp12& x, Fp12& o) {
  Fp6 A = {x.c[0], x.c[2], x.c[4]};
  Fp6 B = {x.c[1], x.c[3], x.c[5]};
  Fp6 A2, B2, vB2, denom, dinv, ne, no_;
  f6_mul(A, A, A2);
  f6_mul(B, B, B2);
  f2_mul_xi(B2[2], vB2[0]);
  vB2[1] = B2[0];
  vB2[2] = B2[1];
  f6_sub(A2, vB2, denom);
  f6_inv(denom, dinv);
  f6_mul(A, dinv, ne);
  f6_mul(B, dinv, no_);
  f6_neg(no_, no_);
  o.c[0] = ne[0];
  o.c[1] = no_[0];
  o.c[2] = ne[1];
  o.c[3] = no_[1];
  o.c[4] = ne[2];
  o.c[5] = no_[2];
}

static void f12_frob(const Fp12& x, int power, Fp12& o) {
  Fp12 r = x;
  for (int t = 0; t < power; ++t) {
    Fp12 nx;
    for (int i = 0; i < 6; ++i) {
      Fp2 cj;
      f2_conj(r.c[i], cj);
      f2_mul(cj, GAMMA_M[i], nx.c[i]);
    }
    r = nx;
  }
  o = r;
}

static void f12_pow_u(const Fp12& base, const u64* e, int nlimbs, Fp12& o) {
  Fp12 acc = f12_one();
  for (int i = nlimbs * 64 - 1; i >= 0; --i) {
    f12_sqr(acc, acc);
    if ((e[i / 64] >> (i % 64)) & 1) f12_mul(acc, base, acc);
  }
  o = acc;
}

static bool f12_is_one(const Fp12& x) {
  if (Mod<6>::cmp(x.c[0].a, FP.one) != 0) return false;
  if (!Mod<6>::is_zero(x.c[0].b)) return false;
  for (int i = 1; i < 6; ++i)
    if (!f2_is_zero(x.c[i])) return false;
  return true;
}

// ---------------------------------------------------------------------------
// curves: Jacobian points; inf flag explicit
// ---------------------------------------------------------------------------

struct G1 {
  u64 x[6], y[6], z[6];
  bool inf;
};
struct G2 {
  Fp2 x, y, z;
  bool inf;
};

static void g1_double(const G1& pt, G1& o) {
  if (pt.inf) {
    o = pt;
    return;
  }
  u64 a[6], b[6], c[6], d[6], e[6], f[6], t[6], t2[6];
  FP.sqr(pt.x, a);
  FP.sqr(pt.y, b);
  FP.sqr(b, c);
  FP.add(pt.x, b, t);
  FP.sqr(t, t);
  FP.add(a, c, t2);
  FP.sub(t, t2, d);
  FP.add(d, d, d);
  FP.add(a, a, e);
  FP.add(e, a, e);
  FP.sqr(e, f);
  G1 r;
  r.inf = false;
  FP.add(d, d, t);
  FP.sub(f, t, r.x);
  FP.sub(d, r.x, t);
  FP.mul(e, t, t);
  u64 c8[6];
  FP.add(c, c, c8);
  FP.add(c8, c8, c8);
  FP.add(c8, c8, c8);
  FP.sub(t, c8, r.y);
  FP.add(pt.y, pt.y, t);
  FP.mul(t, pt.z, r.z);
  o = r;
}

static void g1_add(const G1& p1, const G1& p2, G1& o) {
  if (p1.inf) {
    o = p2;
    return;
  }
  if (p2.inf) {
    o = p1;
    return;
  }
  u64 z1z1[6], z2z2[6], u1[6], u2[6], s1[6], s2[6], t[6];
  FP.sqr(p1.z, z1z1);
  FP.sqr(p2.z, z2z2);
  FP.mul(p1.x, z2z2, u1);
  FP.mul(p2.x, z1z1, u2);
  FP.mul(p1.y, p2.z, t);
  FP.mul(t, z2z2, s1);
  FP.mul(p2.y, p1.z, t);
  FP.mul(t, z1z1, s2);
  u64 h[6], r2[6];
  FP.sub(u2, u1, h);
  FP.sub(s2, s1, r2);
  if (Mod<6>::is_zero(h)) {
    if (Mod<6>::is_zero(r2)) {
      g1_double(p1, o);
      return;
    }
    o.inf = true;
    return;
  }
  u64 i[6], j[6], v[6];
  FP.add(h, h, t);
  FP.sqr(t, i);
  FP.mul(h, i, j);
  FP.add(r2, r2, r2);
  FP.mul(u1, i, v);
  G1 r;
  r.inf = false;
  FP.sqr(r2, t);
  FP.sub(t, j, t);
  u64 v2[6];
  FP.add(v, v, v2);
  FP.sub(t, v2, r.x);
  FP.sub(v, r.x, t);
  FP.mul(r2, t, t);
  u64 sj[6];
  FP.mul(s1, j, sj);
  FP.add(sj, sj, sj);
  FP.sub(t, sj, r.y);
  FP.mul(p1.z, p2.z, t);
  FP.add(t, t, t);
  FP.mul(t, h, r.z);
  o = r;
}

static void g1_mul_limbs(const G1& pt, const u64* k, int nlimbs, G1& o) {
  G1 acc;
  acc.inf = true;
  G1 add = pt;
  for (int i = 0; i < nlimbs * 64; ++i) {
    if ((k[i / 64] >> (i % 64)) & 1) g1_add(acc, add, acc);
    g1_double(add, add);
  }
  o = acc;
}

static void g2_double(const G2& pt, G2& o) {
  if (pt.inf) {
    o = pt;
    return;
  }
  Fp2 a, b, c, d, e, f, t, t2;
  f2_sqr(pt.x, a);
  f2_sqr(pt.y, b);
  f2_sqr(b, c);
  f2_add(pt.x, b, t);
  f2_sqr(t, t);
  f2_add(a, c, t2);
  f2_sub(t, t2, d);
  f2_add(d, d, d);
  f2_add(a, a, e);
  f2_add(e, a, e);
  f2_sqr(e, f);
  G2 r;
  r.inf = false;
  f2_add(d, d, t);
  f2_sub(f, t, r.x);
  f2_sub(d, r.x, t);
  f2_mul(e, t, t);
  Fp2 c8;
  f2_scal_small(c, 8, c8);
  f2_sub(t, c8, r.y);
  f2_add(pt.y, pt.y, t);
  f2_mul(t, pt.z, r.z);
  o = r;
}

static void g2_add(const G2& p1, const G2& p2, G2& o) {
  if (p1.inf) {
    o = p2;
    return;
  }
  if (p2.inf) {
    o = p1;
    return;
  }
  Fp2 z1z1, z2z2, u1, u2, s1, s2, t, h, r2;
  f2_sqr(p1.z, z1z1);
  f2_sqr(p2.z, z2z2);
  f2_mul(p1.x, z2z2, u1);
  f2_mul(p2.x, z1z1, u2);
  f2_mul(p1.y, p2.z, t);
  f2_mul(t, z2z2, s1);
  f2_mul(p2.y, p1.z, t);
  f2_mul(t, z1z1, s2);
  f2_sub(u2, u1, h);
  f2_sub(s2, s1, r2);
  if (f2_is_zero(h)) {
    if (f2_is_zero(r2)) {
      g2_double(p1, o);
      return;
    }
    o.inf = true;
    return;
  }
  Fp2 i, j, v;
  f2_add(h, h, t);
  f2_sqr(t, i);
  f2_mul(h, i, j);
  f2_add(r2, r2, r2);
  f2_mul(u1, i, v);
  G2 r;
  r.inf = false;
  f2_sqr(r2, t);
  f2_sub(t, j, t);
  Fp2 v2;
  f2_add(v, v, v2);
  f2_sub(t, v2, r.x);
  f2_sub(v, r.x, t);
  f2_mul(r2, t, t);
  Fp2 sj;
  f2_mul(s1, j, sj);
  f2_add(sj, sj, sj);
  f2_sub(t, sj, r.y);
  f2_mul(p1.z, p2.z, t);
  f2_add(t, t, t);
  f2_mul(t, h, r.z);
  o = r;
}

static void g2_mul_limbs(const G2& pt, const u64* k, int nlimbs, G2& o) {
  G2 acc;
  acc.inf = true;
  G2 add = pt;
  for (int i = 0; i < nlimbs * 64; ++i) {
    if ((k[i / 64] >> (i % 64)) & 1) g2_add(acc, add, acc);
    g2_double(add, add);
  }
  o = acc;
}

static void g1_affine(const G1& pt, G1& o) {
  if (pt.inf) {
    o = pt;
    return;
  }
  u64 zi[6], zi2[6];
  FP.pow(pt.z, BLS_P_M2, 6, zi);
  FP.sqr(zi, zi2);
  G1 r;
  r.inf = false;
  FP.mul(pt.x, zi2, r.x);
  FP.mul(pt.y, zi2, r.y);
  FP.mul(r.y, zi, r.y);
  memcpy(r.z, FP.one, sizeof(FP.one));
  o = r;
}

static void g2_affine(const G2& pt, G2& o) {
  if (pt.inf) {
    o = pt;
    return;
  }
  Fp2 zi, zi2;
  f2_inv(pt.z, zi);
  f2_sqr(zi, zi2);
  G2 r;
  r.inf = false;
  f2_mul(pt.x, zi2, r.x);
  f2_mul(pt.y, zi2, r.y);
  f2_mul(r.y, zi, r.y);
  r.z = FP2_ONE_;
  o = r;
}

// ---------------------------------------------------------------------------
// endomorphism fast paths (mirrors crypto/bls12_381.py: g2_psi,
// g2_clear_cofactor; crypto/batch.py: the GLV split).  ψ acts as [p] ≡ [X]
// (mod r) on G2 and φ as [λ] on G1, so full-range scalars split into 64/128-
// bit digit ladders.  PRECONDITION for the *_glv/*_gls muls: the input point
// lies in the r-order subgroup (guaranteed by the Python layer — wire
// deserialization subgroup-checks, and hash outputs are cofactor-cleared);
// the exported generic bls_g1_mul/bls_g2_mul stay plain ladders because the
// Python subgroup checks themselves route through them.
// ---------------------------------------------------------------------------

static void g2_psi(const G2& pt, G2& o) {
  if (pt.inf) {
    o = pt;
    return;
  }
  Fp2 xc, yc, zc;
  f2_conj(pt.x, xc);
  f2_conj(pt.y, yc);
  f2_conj(pt.z, zc);
  o.inf = false;
  f2_mul(PSI_CX_M, xc, o.x);
  f2_mul(PSI_CY_M, yc, o.y);
  o.z = zc;
}

static void g2_neg_pt(const G2& pt, G2& o) {
  o = pt;
  if (!pt.inf) f2_neg(pt.y, o.y);
}

static void g1_endo(const G1& pt, G1& o) {  // φ(X,Y,Z) = (β·X, Y, Z)
  o = pt;
  if (!pt.inf) FP.mul(GLV_BETA_M, pt.x, o.x);
}

// [|x|]P — 64-bit ladder (x = BLS parameter, negative; callers negate)
static void g2_mul_xabs(const G2& pt, G2& o) {
  u64 k = BLS_X_ABS;
  g2_mul_limbs(pt, &k, 1, o);
}

// Budroni–Pintore cofactor clearing: [x²−x−1]P + [x−1]ψ(P) + ψ²([2]P).
// Valid for ANY point of E'(Fp2); image lies in G2.  Two 64-bit ladders
// replace the naive 512-bit [h₂] multiplication (~8× fewer point ops).
static void g2_clear_cofactor(const G2& pt, G2& o) {
  if (pt.inf) {
    o = pt;
    return;
  }
  G2 a, b, t1, t2, t3, neg;
  g2_mul_xabs(pt, a);
  g2_neg_pt(a, a);  // [x]P
  g2_mul_xabs(a, b);
  g2_neg_pt(b, b);  // [x²]P
  g2_neg_pt(a, neg);
  g2_add(b, neg, t1);
  g2_neg_pt(pt, neg);
  g2_add(t1, neg, t1);  // [x²−x−1]P
  g2_add(a, neg, t2);
  g2_psi(t2, t2);  // [x−1]ψ(P)
  g2_double(pt, t3);
  g2_psi(t3, t3);
  g2_psi(t3, t3);  // ψ²([2]P)
  g2_add(t1, t2, o);
  g2_add(o, t3, o);
}

// -- small bignum helpers for the scalar decompositions ---------------------

// mag (4 limbs) divmod u64: returns remainder, quotient in-place
static u64 divmod_u64(u64* mag, u64 d) {
  u128 rem = 0;
  for (int i = 3; i >= 0; --i) {
    u128 cur = (rem << 64) | mag[i];
    mag[i] = (u64)(cur / d);
    rem = cur % d;
  }
  return (u64)rem;
}

static bool mag_is_zero(const u64* m) {
  return !(m[0] | m[1] | m[2] | m[3]);
}

// GLS digits: k (raw, < r) = d0 + x·(d1 + x·(d2 + x·(d3 + x·d4))), all
// d_i ∈ [0, |x|) (d4 ∈ {0, 1} in practice — |x|⁴ > r−... the alternating-
// sign division makes every digit non-negative; verified exhaustively in
// the Python design check).  Returns false only if k fails to terminate in
// 5 digits (never for k < r; defensive).
static bool gls_digits(const u64* kraw4, u64 d[5]) {
  u64 mag[4];
  memcpy(mag, kraw4, sizeof(mag));
  bool neg = false;
  for (int i = 0; i < 5; ++i) {
    u64 rem = divmod_u64(mag, BLS_X_ABS);
    if (!neg) {
      // v ≥ 0: d = rem; v' = −(v − d)/|x| (quotient already in mag)
      d[i] = rem;
      neg = !mag_is_zero(mag);
    } else {
      // v < 0 (mag holds |v|): d = (|x| − rem) mod |x|; v' = (|v| + d)/|x|
      if (rem == 0) {
        d[i] = 0;
      } else {
        d[i] = BLS_X_ABS - rem;
        // (|v| + d) = (quot·|x| + rem + |x| − rem) = (quot + 1)·|x|
        u64 carry = 1;
        for (int j = 0; j < 4 && carry; ++j) {
          mag[j] += carry;
          carry = (mag[j] == 0);
        }
      }
      neg = false;
    }
  }
  return mag_is_zero(mag);
}

// wNAF-3 recoding of a 64-bit value: signed digits in {0, ±1, ±3}, average
// nonzero density 1/4.  out must hold 66 entries; returns digit count.
static int wnaf3(u64 k, int8_t* out) {
  int n = 0;
  while (k) {
    if (k & 1) {
      int d = (int)(k & 7);
      if (d > 4) d -= 8;  // d ∈ {−3, −1, 1, 3}
      out[n++] = (int8_t)d;
      k -= (u64)((int64_t)d);
    } else {
      out[n++] = 0;
    }
    k >>= 1;
  }
  return n;
}

// [k]P for P ∈ G2, k raw 4-limb < r: ψ-Horner as one joint wNAF-3 ladder
// over Q_i = ψ^i(P) — ~64 doubles + ~80 signed adds vs the generic
// ladder's 512 doubles + ~256 adds (≈ 5× fewer point operations).
static void g2_mul_gls(const G2& pt, const u64* kraw4, G2& o) {
  if (pt.inf) {
    o = pt;
    return;
  }
  u64 d[5];
  if (!gls_digits(kraw4, d)) {  // defensive fallback; unreachable for k < r
    g2_mul_limbs(pt, kraw4, 4, o);
    return;
  }
  G2 q1[5], q3[5];  // ψ^i(P) and 3·ψ^i(P)
  q1[0] = pt;
  for (int i = 1; i < 5; ++i) g2_psi(q1[i - 1], q1[i]);
  for (int i = 0; i < 5; ++i) {
    G2 t2;
    g2_double(q1[i], t2);
    g2_add(t2, q1[i], q3[i]);
  }
  int8_t naf[5][66];
  int len = 0;
  for (int i = 0; i < 5; ++i) {
    int n = wnaf3(d[i], naf[i]);
    for (int j = n; j < 66; ++j) naf[i][j] = 0;
    if (n > len) len = n;
  }
  G2 acc;
  acc.inf = true;
  for (int b = len - 1; b >= 0; --b) {
    g2_double(acc, acc);
    for (int i = 0; i < 5; ++i) {
      int8_t dg = naf[i][b];
      if (!dg) continue;
      G2 t = (dg == 1 || dg == -1) ? q1[i] : q3[i];
      if (dg < 0) g2_neg_pt(t, t);
      g2_add(acc, t, acc);
    }
  }
  o = acc;
}

// [k]P for P ∈ G1, k raw 4-limb < r: GLV split k = a + b·λ (both < 2^128)
// as one joint 128-bit ladder over P, φ(P).
static void g1_mul_glv(const G1& pt, const u64* kraw4, G1& o) {
  if (pt.inf) {
    o = pt;
    return;
  }
  // divide k by λ (2-limb) via binary shift-subtract: ~130 cheap word ops
  u64 rem[4];
  memcpy(rem, kraw4, sizeof(rem));
  u64 a[2] = {0, 0}, bq[2] = {0, 0};
  int lam_bits = 127;
  while (!((BLS_GLV_LAMBDA[lam_bits / 64] >> (lam_bits % 64)) & 1)) --lam_bits;
  for (int sh = 255 - lam_bits; sh >= 0; --sh) {
    // t = λ << sh (5 limbs to be safe)
    u64 t[5] = {0};
    int w = sh / 64, s = sh % 64;
    for (int i = 0; i < 2; ++i) {
      t[i + w] |= s ? (BLS_GLV_LAMBDA[i] << s) : BLS_GLV_LAMBDA[i];
      if (s) t[i + w + 1] |= BLS_GLV_LAMBDA[i] >> (64 - s);
    }
    // rem >= t ?
    bool ge = true;
    if (t[4]) ge = false;
    if (ge) {
      for (int i = 3; i >= 0; --i) {
        if (rem[i] != t[i]) {
          ge = rem[i] > t[i];
          break;
        }
      }
    }
    if (ge) {
      u128 br = 0;
      for (int i = 0; i < 4; ++i) {
        u128 dd = (u128)rem[i] - t[i] - br;
        rem[i] = (u64)dd;
        br = (dd >> 64) & 1;
      }
      bq[sh / 64] |= 1ULL << (sh % 64);
    }
  }
  memcpy(a, rem, sizeof(a));  // a = k mod λ < 2^127, b = k / λ < 2^128

  // joint wNAF-3 ladder over P, φ(P) (and their 3-multiples): ~128 doubles
  // + ~64 signed adds vs the naive 256 doubles + ~128 adds
  G1 base[2], base3[2];
  base[0] = pt;
  g1_endo(pt, base[1]);
  for (int i = 0; i < 2; ++i) {
    G1 t2;
    g1_double(base[i], t2);
    g1_add(t2, base[i], base3[i]);
  }
  // 128-bit wNAF-3: recode (lo, hi) limb pairs
  auto wnaf128 = [](u64 lo, u64 hi, int8_t* out) {
    int n = 0;
    while (lo | hi) {
      if (lo & 1) {
        int d = (int)(lo & 7);
        if (d > 4) d -= 8;
        out[n++] = (int8_t)d;
        u64 old = lo;
        lo -= (u64)((int64_t)d);
        if ((int64_t)d < 0 && lo < old) ++hi;       // carry on += wrap
        if ((int64_t)d > 0 && lo > old) --hi;       // borrow on −= wrap
      } else {
        out[n++] = 0;
      }
      lo = (lo >> 1) | (hi << 63);
      hi >>= 1;
    }
    return n;
  };
  int8_t naf[2][131];
  int len = 0;
  u64 sc[2][2] = {{a[0], a[1]}, {bq[0], bq[1]}};
  for (int i = 0; i < 2; ++i) {
    int n = wnaf128(sc[i][0], sc[i][1], naf[i]);
    for (int j = n; j < 131; ++j) naf[i][j] = 0;
    if (n > len) len = n;
  }
  G1 acc;
  acc.inf = true;
  for (int b = len - 1; b >= 0; --b) {
    g1_double(acc, acc);
    for (int i = 0; i < 2; ++i) {
      int8_t dg = naf[i][b];
      if (!dg) continue;
      G1 t = (dg == 1 || dg == -1) ? base[i] : base3[i];
      if (dg < 0) FP.neg(t.y, t.y);
      g1_add(acc, t, acc);
    }
  }
  o = acc;
}

// -- fixed-base tables -------------------------------------------------------

static void load_gen(G1& gen) {
  gen.inf = false;
  FP.from_raw(BLS_G1_X, gen.x);
  FP.from_raw(BLS_G1_Y, gen.y);
  memcpy(gen.z, FP.one, sizeof(FP.one));
}

// generator table: T[w·255 + d−1] = [d·2^{8w}]·G, w ∈ 0..31, d ∈ 1..255 —
// a fixed-base mul is ≤ 31 additions (thread-safe lazy build: magic static)
static const std::vector<G1>& gen_table() {
  static const std::vector<G1> table = [] {
    std::vector<G1> t(32 * 255);
    G1 base;
    load_gen(base);
    for (int w = 0; w < 32; ++w) {
      t[w * 255] = base;
      for (int d = 2; d <= 255; ++d)
        g1_add(t[w * 255 + d - 2], base, t[w * 255 + d - 1]);
      for (int i = 0; i < 8; ++i) g1_double(base, base);
    }
    return t;
  }();
  return table;
}

static void g1_mul_gen(const u64* kraw4, G1& o) {
  const std::vector<G1>& t = gen_table();
  o.inf = true;
  for (int w = 0; w < 32; ++w) {
    int d = (int)((kraw4[w / 8] >> ((w % 8) * 8)) & 0xFF);
    if (d) g1_add(o, t[w * 255 + d - 1], o);
  }
}

// per-call window-4 table for an arbitrary base (used by the batched TPKE
// encrypt for pk^r: 960 build adds amortize over the batch, 63 adds/mul)
struct G1Win4 {
  std::vector<G1> t;  // [w·15 + d−1] = [d·2^{4w}]·P, w ∈ 0..63
  void build(const G1& p) {
    t.resize(64 * 15);
    G1 base = p;
    for (int w = 0; w < 64; ++w) {
      t[w * 15] = base;
      for (int d = 2; d <= 15; ++d)
        g1_add(t[w * 15 + d - 2], base, t[w * 15 + d - 1]);
      for (int i = 0; i < 4; ++i) g1_double(base, base);
    }
  }
  void mul(const u64* kraw4, G1& o) const {
    o.inf = true;
    for (int w = 0; w < 64; ++w) {
      int d = (int)((kraw4[w / 16] >> ((w % 16) * 4)) & 0xF);
      if (d) g1_add(o, t[w * 15 + d - 1], o);
    }
  }
};

// ---------------------------------------------------------------------------
// serialization (host format: tag byte + big-endian affine coords)
// ---------------------------------------------------------------------------

static void fp_to_be48(const u64* m, uint8_t* out) {
  u64 raw[6];
  FP.to_raw(m, raw);
  for (int i = 0; i < 6; ++i) {
    u64 limb = raw[5 - i];
    for (int b = 0; b < 8; ++b) out[i * 8 + b] = (uint8_t)(limb >> (56 - 8 * b));
  }
}

static void fp_from_be48(const uint8_t* in, u64* out) {
  u64 raw[6] = {0};
  for (int i = 0; i < 6; ++i) {
    u64 limb = 0;
    for (int b = 0; b < 8; ++b) limb = (limb << 8) | in[i * 8 + b];
    raw[5 - i] = limb;
  }
  FP.from_raw(raw, out);
}

static void g1_write(const G1& pt, uint8_t* out97) {
  G1 a;
  g1_affine(pt, a);
  if (a.inf) {
    memset(out97, 0, 97);
    out97[0] = 0x40;
    return;
  }
  out97[0] = 0;
  fp_to_be48(a.x, out97 + 1);
  fp_to_be48(a.y, out97 + 49);
}

static bool g1_read(const uint8_t* in97, G1& o) {
  if (in97[0] == 0x40) {
    o.inf = true;
    return true;
  }
  if (in97[0] != 0) return false;
  o.inf = false;
  fp_from_be48(in97 + 1, o.x);
  fp_from_be48(in97 + 49, o.y);
  memcpy(o.z, FP.one, sizeof(FP.one));
  return true;
}

static void g2_write(const G2& pt, uint8_t* out193) {
  G2 a;
  g2_affine(pt, a);
  if (a.inf) {
    memset(out193, 0, 193);
    out193[0] = 0x40;
    return;
  }
  out193[0] = 0;
  fp_to_be48(a.x.a, out193 + 1);
  fp_to_be48(a.x.b, out193 + 49);
  fp_to_be48(a.y.a, out193 + 97);
  fp_to_be48(a.y.b, out193 + 145);
}

static bool g2_read(const uint8_t* in193, G2& o) {
  if (in193[0] == 0x40) {
    o.inf = true;
    return true;
  }
  if (in193[0] != 0) return false;
  o.inf = false;
  fp_from_be48(in193 + 1, o.x.a);
  fp_from_be48(in193 + 49, o.x.b);
  fp_from_be48(in193 + 97, o.y.a);
  fp_from_be48(in193 + 145, o.y.b);
  o.z = FP2_ONE_;
  return true;
}

// -- batch affine writes ----------------------------------------------------
// Montgomery batch inversion: one field inversion (a ~381-bit pow) + 3(m−1)
// muls replaces m inversions.  The batch TPKE entry points spend ~10 % of
// their time in per-point affine pow-inversions without this.

// Inputs to both batch-inversion chains MUST be nonzero: one zero element
// would zero every prefix product and silently corrupt the WHOLE batch
// (the old per-point path corrupted only its own output).  Callers uphold
// this by filtering infinity points (the only source of z = 0) before the
// chain; the guard makes a future caller that forgets fail loudly.

static void batch_inv_zero_guard(const u64* limbs, int n, const char* who) {
  for (int i = 0; i < n; ++i)
    if (limbs[i]) return;
  std::fprintf(stderr,
               "hbbft native: %s got a zero element — inputs must be "
               "nonzero (filter infinity/z=0 points before the shared "
               "inversion chain)\n", who);
  std::abort();
}

static void fp_batch_inv(std::vector<std::array<u64, 6>>& vals) {
  int m = (int)vals.size();
  if (m == 0) return;
  for (auto& v : vals) batch_inv_zero_guard(v.data(), 6, "fp_batch_inv");
  std::vector<std::array<u64, 6>> pre(m);
  pre[0] = vals[0];
  for (int i = 1; i < m; ++i)
    FP.mul(pre[i - 1].data(), vals[i].data(), pre[i].data());
  u64 acc[6];
  FP.pow(pre[m - 1].data(), BLS_P_M2, 6, acc);
  for (int i = m - 1; i > 0; --i) {
    u64 vi[6];
    memcpy(vi, vals[i].data(), sizeof(vi));
    FP.mul(acc, pre[i - 1].data(), vals[i].data());
    FP.mul(acc, vi, acc);
  }
  memcpy(vals[0].data(), acc, sizeof(acc));
}

static void f2_batch_inv(std::vector<Fp2>& vals) {
  int m = (int)vals.size();
  if (m == 0) return;
  for (auto& v : vals) {
    u64 both[12];
    memcpy(both, v.a, sizeof(v.a));
    memcpy(both + 6, v.b, sizeof(v.b));
    batch_inv_zero_guard(both, 12, "f2_batch_inv");
  }
  std::vector<Fp2> pre(m);
  pre[0] = vals[0];
  for (int i = 1; i < m; ++i) f2_mul(pre[i - 1], vals[i], pre[i]);
  Fp2 acc;
  f2_inv(pre[m - 1], acc);
  for (int i = m - 1; i > 0; --i) {
    Fp2 vi = vals[i];
    f2_mul(acc, pre[i - 1], vals[i]);
    f2_mul(acc, vi, acc);
  }
  vals[0] = acc;
}

// Affine-write m G1 points with ONE shared inversion chain; outs[i] gets the
// same 97 bytes g1_write would produce.
static void g1_write_batch(const std::vector<G1>& pts,
                           const std::vector<uint8_t*>& outs) {
  int m = (int)pts.size();
  std::vector<std::array<u64, 6>> zs;
  std::vector<int> idx;
  zs.reserve(m);
  idx.reserve(m);
  for (int i = 0; i < m; ++i) {
    if (pts[i].inf) {
      memset(outs[i], 0, 97);
      outs[i][0] = 0x40;
    } else {
      std::array<u64, 6> z;
      memcpy(z.data(), pts[i].z, sizeof(z));
      zs.push_back(z);
      idx.push_back(i);
    }
  }
  fp_batch_inv(zs);
  for (size_t j = 0; j < idx.size(); ++j) {
    int i = idx[j];
    u64 zi2[6], x[6], y[6], t[6];
    FP.sqr(zs[j].data(), zi2);
    FP.mul(pts[i].x, zi2, x);
    FP.mul(pts[i].y, zi2, t);
    FP.mul(t, zs[j].data(), y);
    outs[i][0] = 0;
    fp_to_be48(x, outs[i] + 1);
    fp_to_be48(y, outs[i] + 49);
  }
}

// Affine-write m G1 points into one contiguous 97-byte-stride buffer with a
// shared inversion chain (the mask-serialization step of both batch decrypt
// entry points).
static std::vector<uint8_t> g1_write_contig(const std::vector<G1>& pts) {
  int m = (int)pts.size();
  std::vector<uint8_t> buf(97 * (size_t)m);
  std::vector<uint8_t*> outs(m);
  for (int i = 0; i < m; ++i) outs[i] = &buf[97 * (size_t)i];
  g1_write_batch(pts, outs);
  return buf;
}

static void g2_write_batch(const std::vector<G2>& pts,
                           const std::vector<uint8_t*>& outs) {
  int m = (int)pts.size();
  std::vector<Fp2> zs;
  std::vector<int> idx;
  zs.reserve(m);
  idx.reserve(m);
  for (int i = 0; i < m; ++i) {
    if (pts[i].inf) {
      memset(outs[i], 0, 193);
      outs[i][0] = 0x40;
    } else {
      zs.push_back(pts[i].z);
      idx.push_back(i);
    }
  }
  f2_batch_inv(zs);
  for (size_t j = 0; j < idx.size(); ++j) {
    int i = idx[j];
    Fp2 zi2, x, y, t;
    f2_sqr(zs[j], zi2);
    f2_mul(pts[i].x, zi2, x);
    f2_mul(pts[i].y, zi2, t);
    f2_mul(t, zs[j], y);
    outs[i][0] = 0;
    fp_to_be48(x.a, outs[i] + 1);
    fp_to_be48(x.b, outs[i] + 49);
    fp_to_be48(y.a, outs[i] + 97);
    fp_to_be48(y.b, outs[i] + 145);
  }
}

static void fr_from_be32(const uint8_t* in, u64* raw4) {
  for (int i = 0; i < 4; ++i) {
    u64 limb = 0;
    for (int b = 0; b < 8; ++b) limb = (limb << 8) | in[i * 8 + b];
    raw4[3 - i] = limb;
  }
}

// ---------------------------------------------------------------------------
// pairing (mirrors host: affine Miller over |x|, cube-of-ate final exp)
// ---------------------------------------------------------------------------

static void line_sparse(const Fp2& c0, const Fp2& c2, const Fp2& c3, Fp12& o) {
  for (int i = 0; i < 6; ++i) o.c[i] = FP2_ZERO_;
  o.c[0] = c0;
  o.c[2] = c2;
  o.c[3] = c3;
}

static void miller_loop(const std::vector<G1>& ps, const std::vector<G2>& qs,
                        Fp12& f) {
  f = f12_one();
  std::vector<G1> pa;
  std::vector<G2> qa;
  for (size_t i = 0; i < ps.size(); ++i) {
    if (ps[i].inf || qs[i].inf) continue;
    G1 a;
    g1_affine(ps[i], a);
    G2 b;
    g2_affine(qs[i], b);
    pa.push_back(a);
    qa.push_back(b);
  }
  if (pa.empty()) return;
  u64 xs = BLS_X_ABS;
  int top = 63;
  while (!((xs >> top) & 1)) --top;
  std::vector<G2> Rs = qa;
  Fp12 ln;
  for (int bit = top - 1; bit >= 0; --bit) {
    f12_sqr(f, f);
    for (size_t i = 0; i < pa.size(); ++i) {
      Fp2 lam, t, t2, c0, c2, c3;
      // λ = 3x² / 2y
      f2_sqr(Rs[i].x, t);
      f2_scal_small(t, 3, t);
      f2_add(Rs[i].y, Rs[i].y, t2);
      f2_inv(t2, t2);
      f2_mul(t, t2, lam);
      f2_mul(lam, Rs[i].x, c0);
      f2_sub(c0, Rs[i].y, c0);
      Fp2 lxp;
      memcpy(lxp.a, pa[i].x, sizeof(lxp.a));
      memset(lxp.b, 0, sizeof(lxp.b));
      f2_mul(lam, lxp, c2);
      f2_neg(c2, c2);
      memcpy(c3.a, pa[i].y, sizeof(c3.a));
      memset(c3.b, 0, sizeof(c3.b));
      line_sparse(c0, c2, c3, ln);
      f12_mul(f, ln, f);
      // R = 2R (affine)
      Fp2 x3, y3;
      f2_sqr(lam, x3);
      f2_add(Rs[i].x, Rs[i].x, t);
      f2_sub(x3, t, x3);
      f2_sub(Rs[i].x, x3, t);
      f2_mul(lam, t, y3);
      f2_sub(y3, Rs[i].y, y3);
      Rs[i].x = x3;
      Rs[i].y = y3;
      Rs[i].z = FP2_ONE_;
      Rs[i].inf = false;
    }
    if ((xs >> bit) & 1) {
      for (size_t i = 0; i < pa.size(); ++i) {
        Fp2 dx;
        f2_sub(Rs[i].x, qa[i].x, dx);
        if (f2_is_zero(dx)) {
          G2 s;
          g2_add(Rs[i], qa[i], s);
          g2_affine(s, Rs[i]);
          continue;
        }
        Fp2 lam, t, c0, c2, c3;
        f2_sub(Rs[i].y, qa[i].y, t);
        f2_inv(dx, lam);
        f2_mul(t, lam, lam);
        f2_mul(lam, qa[i].x, c0);
        f2_sub(c0, qa[i].y, c0);
        Fp2 lxp;
        memcpy(lxp.a, pa[i].x, sizeof(lxp.a));
        memset(lxp.b, 0, sizeof(lxp.b));
        f2_mul(lam, lxp, c2);
        f2_neg(c2, c2);
        memcpy(c3.a, pa[i].y, sizeof(c3.a));
        memset(c3.b, 0, sizeof(c3.b));
        line_sparse(c0, c2, c3, ln);
        f12_mul(f, ln, f);
        Fp2 x3, y3;
        f2_sqr(lam, x3);
        f2_sub(x3, Rs[i].x, x3);
        f2_sub(x3, qa[i].x, x3);
        f2_sub(Rs[i].x, x3, t);
        f2_mul(lam, t, y3);
        f2_sub(y3, Rs[i].y, y3);
        Rs[i].x = x3;
        Rs[i].y = y3;
      }
    }
  }
  Fp12 cj;
  f12_conj(f, cj);
  f = cj;  // x < 0
}

static void final_exp(const Fp12& in, Fp12& out) {
  Fp12 f, t0, t1;
  // easy: f^(p⁶−1) then ^(p²+1)
  f12_conj(in, t0);
  f12_inv(in, t1);
  f12_mul(t0, t1, f);
  f12_frob(f, 2, t0);
  f12_mul(t0, f, f);
  // hard
  u64 xm1 = BLS_X_ABS + 1;
  Fp12 t, s, u;
  f12_pow_u(f, &xm1, 1, t);
  f12_conj(t, t);
  f12_pow_u(t, &xm1, 1, t);
  f12_conj(t, t);  // t = f^((x−1)²)
  u64 ax = BLS_X_ABS;
  f12_pow_u(t, &ax, 1, s);
  f12_conj(s, s);
  f12_frob(t, 1, t0);
  f12_mul(s, t0, s);  // s = t^(x+p)
  // x² (127-bit)
  u128 xx = (u128)ax * ax;
  u64 x2[2] = {(u64)xx, (u64)(xx >> 64)};
  f12_pow_u(s, x2, 2, u);
  f12_frob(s, 2, t0);
  f12_conj(s, t1);
  f12_mul(t0, t1, t0);
  f12_mul(u, t0, u);  // u = s^(x²+p²−1)
  u64 three = 3;
  f12_pow_u(f, &three, 1, t0);
  f12_mul(u, t0, out);
}

static bool pairing_check_vec(const std::vector<G1>& ps,
                              const std::vector<G2>& qs) {
  Fp12 f, e;
  miller_loop(ps, qs, f);
  final_exp(f, e);
  return f12_is_one(e);
}

// ---------------------------------------------------------------------------
// hash to curve (mirrors host try-and-increment)
// ---------------------------------------------------------------------------

static void mod_p_from_be(const uint8_t* data, int len, u64* out_m) {
  // acc = Σ byte·256^i (big-endian) mod p, in Montgomery form
  u64 acc[6] = {0};
  for (int i = 0; i < len; ++i) {
    for (int d = 0; d < 8; ++d) FP.dbl_mod(acc);  // acc *= 256 (raw domain ok)
    u64 raw[6] = {data[i]};
    // raw add mod p
    u64 t[6];
    u64 carry = Mod<6>::raw_add(acc, raw, t);
    if (carry || Mod<6>::cmp(t, FP.p) >= 0) Mod<6>::raw_sub(t, FP.p, t);
    memcpy(acc, t, sizeof(t));
  }
  FP.from_raw(acc, out_m);
}

static void hash_prefixed(const char* prefix, uint32_t ctr,
                          const uint8_t* data, int64_t len, uint8_t* out32) {
  std::vector<uint8_t> buf;
  size_t pl = strlen(prefix);
  buf.resize(pl + 4 + len);
  memcpy(buf.data(), prefix, pl);
  buf[pl] = (uint8_t)(ctr >> 24);
  buf[pl + 1] = (uint8_t)(ctr >> 16);
  buf[pl + 2] = (uint8_t)(ctr >> 8);
  buf[pl + 3] = (uint8_t)ctr;
  memcpy(buf.data() + pl + 4, data, len);
  hbbft_sha3_256(buf.data(), (int64_t)buf.size(), out32);
}

static void hash_g2_point(const uint8_t* data, int64_t len, G2& out) {
  for (uint32_t ctr = 0;; ++ctr) {
    uint8_t h[4][32];
    hash_prefixed("HBBFT-H2G-c0", ctr, data, len, h[0]);
    hash_prefixed("HBBFT-H2G-c1", ctr, data, len, h[1]);
    hash_prefixed("HBBFT-H2G-c2", ctr, data, len, h[2]);
    hash_prefixed("HBBFT-H2G-c3", ctr, data, len, h[3]);
    uint8_t cat[64];
    Fp2 x;
    memcpy(cat, h[0], 32);
    memcpy(cat + 32, h[1], 32);
    mod_p_from_be(cat, 64, x.a);
    memcpy(cat, h[2], 32);
    memcpy(cat + 32, h[3], 32);
    mod_p_from_be(cat, 64, x.b);
    Fp2 rhs, t;
    f2_sqr(x, t);
    f2_mul(t, x, rhs);
    f2_add(rhs, B2_M, rhs);
    // QR pre-test: χ_Fp2(g) = jacobi(norm(g)) — losing try-and-increment
    // candidates cost ~µs instead of field exponentiations.  Same ctr is
    // selected as before (norm = 0 ⟺ rhs = 0 ⟺ y = 0, also rejected).
    u64 nrm[6], tb[6];
    FP.sqr(rhs.a, nrm);
    FP.sqr(rhs.b, tb);
    FP.add(nrm, tb, nrm);
    if (jacobi_m(nrm) != 1) continue;
    Fp2 y;
    if (!f2_sqrt(rhs, y) || f2_is_zero(y)) continue;
    uint8_t sg[32];
    hash_prefixed("HBBFT-H2G-sign", ctr, data, len, sg);
    if (sg[31] & 1) f2_neg(y, y);
    G2 pt;
    pt.inf = false;
    pt.x = x;
    pt.y = y;
    pt.z = FP2_ONE_;
    G2 cleared;
    g2_clear_cofactor(pt, cleared);  // ψ-based (mirrors host hash_g2)
    if (!cleared.inf) {
      out = cleared;
      return;
    }
  }
}

static void hash_g1_point(const uint8_t* data, int64_t len, G1& out) {
  for (uint32_t ctr = 0;; ++ctr) {
    uint8_t h0[32], h1[32];
    hash_prefixed("HBBFT-H1G-0", ctr, data, len, h0);
    hash_prefixed("HBBFT-H1G-1", ctr, data, len, h1);
    uint8_t cat[64];
    memcpy(cat, h0, 32);
    memcpy(cat + 32, h1, 32);
    u64 x[6];
    mod_p_from_be(cat, 64, x);
    u64 rhs[6], t[6];
    FP.sqr(x, t);
    FP.mul(t, x, rhs);
    FP.add(rhs, B1_M, rhs);
    if (jacobi_m(rhs) != 1) continue;  // QR pre-test (same ctr selected)
    u64 y[6];
    if (!fp_sqrt(rhs, y) || Mod<6>::is_zero(y)) continue;
    uint8_t sg[32];
    hash_prefixed("HBBFT-H1G-s", ctr, data, len, sg);
    if (sg[31] & 1) FP.neg(y, y);
    G1 pt;
    pt.inf = false;
    memcpy(pt.x, x, sizeof(x));
    memcpy(pt.y, y, sizeof(y));
    memcpy(pt.z, FP.one, sizeof(FP.one));
    G1 cleared;
    // effective cofactor 1−x (64-bit) in place of the 125-bit h₁ — the
    // standard G1 clearing (RFC 9380 §8.8.1); mirrors host hash_g1
    u64 heff = BLS_X_ABS + 1;
    g1_mul_limbs(pt, &heff, 1, cleared);
    if (!cleared.inf) {
      out = cleared;
      return;
    }
  }
}

// ---------------------------------------------------------------------------
// Fr Lagrange
// ---------------------------------------------------------------------------

static void lagrange_at_zero(const uint32_t* idx, int count, u64 out[][4]) {
  // xs_i = idx_i + 1 (Montgomery); λ_i = Π_{j≠i} x_j / (x_j − x_i)
  std::vector<std::array<u64, 4>> xs(count);
  for (int i = 0; i < count; ++i) {
    u64 raw[4] = {(u64)idx[i] + 1, 0, 0, 0};
    FR.from_raw(raw, xs[i].data());
  }
  for (int i = 0; i < count; ++i) {
    u64 num[4], den[4];
    memcpy(num, FR.one, sizeof(num));
    memcpy(den, FR.one, sizeof(den));
    for (int j = 0; j < count; ++j) {
      if (j == i) continue;
      u64 d[4];
      FR.mul(num, xs[j].data(), num);
      FR.sub(xs[j].data(), xs[i].data(), d);
      FR.mul(den, d, den);
    }
    u64 dinv[4];
    FR.pow(den, BLS_R_M2, 4, dinv);
    FR.mul(num, dinv, out[i]);
  }
}

// ---------------------------------------------------------------------------
// Wire validation (mirrors crypto/bls12_381.py g1_from_bytes/g2_from_bytes:
// canonical coordinates, on-curve, r-order subgroup)
// ---------------------------------------------------------------------------

// Parse 48 big-endian bytes into Montgomery form; false if >= p.
static bool fp_canonical_from_be48(const uint8_t* in, u64* out) {
  u64 raw[6] = {0};
  for (int i = 0; i < 6; ++i) {
    u64 limb = 0;
    for (int b = 0; b < 8; ++b) limb = (limb << 8) | in[i * 8 + b];
    raw[5 - i] = limb;
  }
  if (Mod<6>::cmp(raw, BLS_P) >= 0) return false;
  FP.from_raw(raw, out);
  return true;
}

static bool g1_on_curve(const G1& p) {  // affine input (z = 1)
  if (p.inf) return true;
  u64 y2[6], x3[6];
  FP.sqr(p.y, y2);
  FP.sqr(p.x, x3);
  FP.mul(x3, p.x, x3);
  FP.add(x3, B1_M, x3);
  return Mod<6>::cmp(y2, x3) == 0;
}

static bool g2_on_curve(const G2& p) {  // affine input (z = 1)
  if (p.inf) return true;
  Fp2 y2, x3;
  f2_sqr(p.y, y2);
  f2_sqr(p.x, x3);
  f2_mul(x3, p.x, x3);
  f2_add(x3, B2_M, x3);
  return Mod<6>::cmp(y2.a, x3.a) == 0 && Mod<6>::cmp(y2.b, x3.b) == 0;
}

// Eigenvalue subgroup membership (on-curve input assumed; soundness notes
// at the exported bls_g1_in_subgroup/bls_g2_in_subgroup below).
static bool g1_subgroup_ok(const G1& p) {
  if (p.inf) return true;
  // φ(P) == [λ]P with λ = x²−1: [x²]P costs two sparse [|x|] ladders
  // (x has Hamming weight 6 → 6 adds each) instead of a dense 127-bit
  // ladder's ~64 adds, then one mixed subtraction of P.
  G1 phi, lam, xp;
  u64 xk = BLS_X_ABS;
  g1_mul_limbs(p, &xk, 1, xp);    // [|x|]P
  g1_mul_limbs(xp, &xk, 1, lam);  // [x²]P
  G1 negp = p;
  FP.neg(p.y, negp.y);
  g1_add(lam, negp, lam);        // [x²−1]P
  g1_endo(p, phi);
  // g1_eq via cross-multiplied Jacobians
  if (phi.inf != lam.inf) return false;
  if (phi.inf) return true;
  u64 z1z1[6], z2z2[6], a[6], b[6], t[6];
  FP.sqr(phi.z, z1z1);
  FP.sqr(lam.z, z2z2);
  FP.mul(phi.x, z2z2, a);
  FP.mul(lam.x, z1z1, b);
  if (Mod<6>::cmp(a, b) != 0) return false;
  FP.mul(phi.y, lam.z, t);
  FP.mul(t, z2z2, a);
  FP.mul(lam.y, phi.z, t);
  FP.mul(t, z1z1, b);
  return Mod<6>::cmp(a, b) == 0;
}

static bool g2_subgroup_ok(const G2& p) {
  if (p.inf) return true;
  G2 ps, xp;
  g2_psi(p, ps);
  g2_mul_xabs(p, xp);
  g2_neg_pt(xp, xp);  // [x]P (x < 0)
  if (ps.inf != xp.inf) return false;
  if (ps.inf) return true;
  Fp2 z1z1, z2z2, a, b, t;
  f2_sqr(ps.z, z1z1);
  f2_sqr(xp.z, z2z2);
  f2_mul(ps.x, z2z2, a);
  f2_mul(xp.x, z1z1, b);
  if (Mod<6>::cmp(a.a, b.a) != 0 || Mod<6>::cmp(a.b, b.b) != 0) return false;
  f2_mul(ps.y, xp.z, t);
  f2_mul(t, z2z2, a);
  f2_mul(xp.y, ps.z, t);
  f2_mul(t, z1z1, b);
  return Mod<6>::cmp(a.a, b.a) == 0 && Mod<6>::cmp(a.b, b.b) == 0;
}

// g1_read with the full wire checks — byte-for-byte the same accept set as
// the Python g1_from_bytes (0x40 = infinity; flag byte must otherwise be 0;
// coordinates canonical; on-curve; subgroup).
static bool g1_read_checked(const uint8_t* in97, G1& o) {
  if (in97[0] == 0x40) {
    // strict: the flag must be followed by all-zero bytes — no malleable
    // encodings of the identity on the validated wire (Python
    // g1_from_bytes enforces the same accept set)
    for (int i = 1; i < 97; ++i)
      if (in97[i]) return false;
    o.inf = true;
    return true;
  }
  if (in97[0] != 0) return false;
  o.inf = false;
  if (!fp_canonical_from_be48(in97 + 1, o.x)) return false;
  if (!fp_canonical_from_be48(in97 + 49, o.y)) return false;
  memcpy(o.z, FP.one, sizeof(FP.one));
  return g1_on_curve(o) && g1_subgroup_ok(o);
}

static bool g2_read_checked(const uint8_t* in193, G2& o) {
  if (in193[0] == 0x40) {
    for (int i = 1; i < 193; ++i)  // strict infinity: flag + zeros only
      if (in193[i]) return false;
    o.inf = true;
    return true;
  }
  if (in193[0] != 0) return false;
  o.inf = false;
  if (!fp_canonical_from_be48(in193 + 1, o.x.a)) return false;
  if (!fp_canonical_from_be48(in193 + 49, o.x.b)) return false;
  if (!fp_canonical_from_be48(in193 + 97, o.y.a)) return false;
  if (!fp_canonical_from_be48(in193 + 145, o.y.b)) return false;
  o.z = FP2_ONE_;
  return g2_on_curve(o) && g2_subgroup_ok(o);
}

}  // namespace bls

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------

using namespace bls;

extern "C" {

int bls_g1_add(const uint8_t* a, const uint8_t* b, uint8_t* out) {
  init_all();
  G1 pa, pb, r;
  if (!g1_read(a, pa) || !g1_read(b, pb)) return -1;
  g1_add(pa, pb, r);
  g1_write(r, out);
  return 0;
}

int bls_g1_mul(const uint8_t* a, const uint8_t* scalar_be32, uint8_t* out) {
  init_all();
  G1 pa, r;
  if (!g1_read(a, pa)) return -1;
  u64 k[4];
  fr_from_be32(scalar_be32, k);
  // reduce mod r like the host (g1_mul takes k % R)
  u64 km[4], kr[4];
  FR.from_raw(k, km);
  FR.to_raw(km, kr);
  g1_mul_limbs(pa, kr, 4, r);
  g1_write(r, out);
  return 0;
}

int bls_g2_add(const uint8_t* a, const uint8_t* b, uint8_t* out) {
  init_all();
  G2 pa, pb, r;
  if (!g2_read(a, pa) || !g2_read(b, pb)) return -1;
  g2_add(pa, pb, r);
  g2_write(r, out);
  return 0;
}

int bls_g2_mul(const uint8_t* a, const uint8_t* scalar_be32, uint8_t* out) {
  init_all();
  G2 pa, r;
  if (!g2_read(a, pa)) return -1;
  u64 k[4], km[4], kr[4];
  fr_from_be32(scalar_be32, k);
  FR.from_raw(k, km);
  FR.to_raw(km, kr);
  g2_mul_limbs(pa, kr, 4, r);
  g2_write(r, out);
  return 0;
}

void bls_hash_g1(const uint8_t* msg, int64_t len, uint8_t* out) {
  init_all();
  G1 pt;
  hash_g1_point(msg, len, pt);
  g1_write(pt, out);
}

void bls_hash_g2(const uint8_t* msg, int64_t len, uint8_t* out) {
  init_all();
  G2 pt;
  hash_g2_point(msg, len, pt);
  g2_write(pt, out);
}

int bls_pairing_check(const uint8_t* g1s, const uint8_t* g2s, int n) {
  init_all();
  std::vector<G1> ps(n);
  std::vector<G2> qs(n);
  for (int i = 0; i < n; ++i) {
    if (!g1_read(g1s + 97 * i, ps[i])) return -1;
    if (!g2_read(g2s + 193 * i, qs[i])) return -1;
  }
  return pairing_check_vec(ps, qs) ? 1 : 0;
}

void bls_sign(const uint8_t* msg, int64_t len, const uint8_t* sk_be32,
              uint8_t* out_sig) {
  init_all();
  G2 h, sig;
  hash_g2_point(msg, len, h);
  u64 k[4], km[4], kr[4];
  fr_from_be32(sk_be32, k);
  FR.from_raw(k, km);
  FR.to_raw(km, kr);
  g2_mul_gls(h, kr, sig);  // h is a hash output → in G2
  g2_write(sig, out_sig);
}

int bls_verify(const uint8_t* pk97, const uint8_t* msg, int64_t len,
               const uint8_t* sig193) {
  init_all();
  G1 pk, g1neg;
  G2 sig, h;
  if (!g1_read(pk97, pk) || !g2_read(sig193, sig)) return -1;
  hash_g2_point(msg, len, h);
  G1 gen;
  gen.inf = false;
  FP.from_raw(BLS_G1_X, gen.x);
  FP.from_raw(BLS_G1_Y, gen.y);
  memcpy(gen.z, FP.one, sizeof(FP.one));
  g1neg = gen;
  FP.neg(gen.y, g1neg.y);
  std::vector<G1> ps = {g1neg, pk};
  std::vector<G2> qs = {sig, h};
  return pairing_check_vec(ps, qs) ? 1 : 0;
}

int bls_combine_g2(const uint32_t* idx, const uint8_t* shares193, int count,
                   uint8_t* out193) {
  init_all();
  std::vector<std::array<u64, 4>> lams(count);
  lagrange_at_zero(idx, count, reinterpret_cast<u64(*)[4]>(lams.data()));
  G2 acc;
  acc.inf = true;
  for (int i = 0; i < count; ++i) {
    G2 s, t;
    if (!g2_read(shares193 + 193 * i, s)) return -1;
    u64 lr[4];
    FR.to_raw(lams[i].data(), lr);
    g2_mul_gls(s, lr, t);  // shares are wire-subgroup-checked upstream
    g2_add(acc, t, acc);
  }
  g2_write(acc, out193);
  return 0;
}

int bls_combine_g1(const uint32_t* idx, const uint8_t* shares97, int count,
                   uint8_t* out97) {
  init_all();
  std::vector<std::array<u64, 4>> lams(count);
  lagrange_at_zero(idx, count, reinterpret_cast<u64(*)[4]>(lams.data()));
  G1 acc;
  acc.inf = true;
  for (int i = 0; i < count; ++i) {
    G1 s, t;
    if (!g1_read(shares97 + 97 * i, s)) return -1;
    u64 lr[4];
    FR.to_raw(lams[i].data(), lr);
    g1_mul_glv(s, lr, t);  // shares are wire-subgroup-checked upstream
    g1_add(acc, t, acc);
  }
  g1_write(acc, out97);
  return 0;
}

// -- TPKE (mirrors crypto/tc.py) --------------------------------------------

static void kdf_stream(const uint8_t* seed97, int64_t length, uint8_t* out) {
  int64_t done = 0;
  uint32_t ctr = 0;
  while (done < length) {
    uint8_t buf[101];
    memcpy(buf, seed97, 97);
    buf[97] = (uint8_t)(ctr >> 24);
    buf[98] = (uint8_t)(ctr >> 16);
    buf[99] = (uint8_t)(ctr >> 8);
    buf[100] = (uint8_t)ctr;
    uint8_t h[32];
    hbbft_sha3_256(buf, 101, h);
    int64_t take = length - done < 32 ? length - done : 32;
    memcpy(out + done, h, take);
    done += take;
    ++ctr;
  }
}

int bls_tpke_encrypt(const uint8_t* pk97, const uint8_t* msg, int64_t len,
                     const uint8_t* r_be32, uint8_t* out_u97, uint8_t* out_v,
                     uint8_t* out_w193) {
  init_all();
  G1 pk, u, mask;
  if (!g1_read(pk97, pk)) return -1;
  u64 k[4], km[4], kr[4];
  fr_from_be32(r_be32, k);
  FR.from_raw(k, km);
  FR.to_raw(km, kr);
  g1_mul_gen(kr, u);      // fixed-base table: ≤ 31 adds
  g1_mul_glv(pk, kr, mask);
  g1_write(u, out_u97);
  uint8_t mask_bytes[97];
  g1_write(mask, mask_bytes);
  std::vector<uint8_t> stream(len);
  kdf_stream(mask_bytes, len, stream.data());
  for (int64_t i = 0; i < len; ++i) out_v[i] = msg[i] ^ stream[i];
  // W = hash_g2("HBBFT-TPKE" + U + V)^r
  std::vector<uint8_t> hin(10 + 97 + len);
  memcpy(hin.data(), "HBBFT-TPKE", 10);
  memcpy(hin.data() + 10, out_u97, 97);
  memcpy(hin.data() + 107, out_v, len);
  G2 h, w;
  hash_g2_point(hin.data(), (int64_t)hin.size(), h);
  g2_mul_gls(h, kr, w);  // hash output → in G2
  g2_write(w, out_w193);
  return 0;
}

int bls_tpke_verify(const uint8_t* u97, const uint8_t* v, int64_t vlen,
                    const uint8_t* w193) {
  init_all();
  G1 u, gen;
  G2 w, h;
  if (!g1_read(u97, u) || !g2_read(w193, w)) return -1;
  std::vector<uint8_t> hin(10 + 97 + vlen);
  memcpy(hin.data(), "HBBFT-TPKE", 10);
  memcpy(hin.data() + 10, u97, 97);
  memcpy(hin.data() + 107, v, vlen);
  hash_g2_point(hin.data(), (int64_t)hin.size(), h);
  gen.inf = false;
  FP.from_raw(BLS_G1_X, gen.x);
  FP.from_raw(BLS_G1_Y, gen.y);
  memcpy(gen.z, FP.one, sizeof(FP.one));
  G1 uneg = u;
  if (!u.inf) FP.neg(u.y, uneg.y);
  std::vector<G1> ps = {uneg, gen};
  std::vector<G2> qs = {h, w};
  return pairing_check_vec(ps, qs) ? 1 : 0;
}

int bls_tpke_combine(const uint32_t* idx, const uint8_t* shares97, int count,
                     const uint8_t* v, int64_t vlen, uint8_t* out_msg) {
  init_all();
  uint8_t mask[97];
  if (bls_combine_g1(idx, shares97, count, mask) != 0) return -1;
  std::vector<uint8_t> stream(vlen);
  kdf_stream(mask, vlen, stream.data());
  for (int64_t i = 0; i < vlen; ++i) out_msg[i] = v[i] ^ stream[i];
  return 0;
}

// -- batch entry points (the HoneyBadger epoch hot loops: ONE ctypes call,
// GIL released for the whole batch, per-call tables amortized) --------------

// Encrypt `count` messages to one public key.  msgs: concatenated plaintext
// bytes; lens[i] their lengths; rs: count×32 big-endian scalars (< r, drawn
// by the caller's seeded RNG — byte-identical to per-item bls_tpke_encrypt
// with the same r).  out: per item U(97) ‖ W(193) ‖ V(len_i), concatenated.
int bls_tpke_encrypt_batch(const uint8_t* pk97, const uint8_t* msgs,
                           const int64_t* lens, int count, const uint8_t* rs,
                           uint8_t* out) {
  init_all();
  G1 pk;
  if (!g1_read(pk97, pk)) return -1;
  G1Win4 pk_tab;
  bool use_tab = count >= 64;  // build cost ~960 adds vs 63 adds/mul saved
  if (use_tab) pk_tab.build(pk);
  // pass 1: all U = g1^r and mask = pk^r ladders (Jacobian), then ONE
  // shared inversion chain writes every affine point — per-item pow
  // inversions were ~10 % of the batch
  std::vector<std::array<u64, 4>> krs(count);
  std::vector<G1> g1s(2 * count);
  std::vector<uint8_t> maskb(97 * (size_t)count);
  std::vector<uint8_t*> g1outs(2 * count);
  {
    uint8_t* op = out;
    for (int i = 0; i < count; ++i) {
      u64 k[4], km[4];
      fr_from_be32(rs + 32 * i, k);
      FR.from_raw(k, km);
      FR.to_raw(km, krs[i].data());
      g1_mul_gen(krs[i].data(), g1s[2 * i]);
      if (use_tab)
        pk_tab.mul(krs[i].data(), g1s[2 * i + 1]);
      else
        g1_mul_glv(pk, krs[i].data(), g1s[2 * i + 1]);
      g1outs[2 * i] = op;                      // U straight into out
      g1outs[2 * i + 1] = &maskb[97 * (size_t)i];
      op += 290 + lens[i];
    }
  }
  g1_write_batch(g1s, g1outs);
  // pass 2: V = msg ⊕ KDF(mask), W = hash_g2(U‖V)^r (Jacobian), then one
  // shared Fp2 inversion chain writes the W points
  std::vector<G2> ws(count);
  std::vector<uint8_t*> wouts(count);
  {
    const uint8_t* mp = msgs;
    uint8_t* op = out;
    for (int i = 0; i < count; ++i) {
      int64_t len = lens[i];
      uint8_t* u_out = op;
      uint8_t* v_out = op + 290;
      std::vector<uint8_t> stream(len);
      kdf_stream(&maskb[97 * (size_t)i], len, stream.data());
      for (int64_t j = 0; j < len; ++j) v_out[j] = mp[j] ^ stream[j];
      std::vector<uint8_t> hin(10 + 97 + len);
      memcpy(hin.data(), "HBBFT-TPKE", 10);
      memcpy(hin.data() + 10, u_out, 97);
      memcpy(hin.data() + 107, v_out, len);
      G2 h;
      hash_g2_point(hin.data(), (int64_t)hin.size(), h);
      g2_mul_gls(h, krs[i].data(), ws[i]);
      wouts[i] = op + 97;
      mp += len;
      op += 290 + len;
    }
  }
  g2_write_batch(ws, wouts);
  return 0;
}

// masks[i] = [s]·U_i — the master-scalar fold of batched TPKE decryption
// (crypto/batch.py::batch_tpke_decrypt host path).  U_i are wire-checked
// subgroup members; s raw big-endian 32 bytes.
int bls_tpke_mask_batch(const uint8_t* s_be32, const uint8_t* us97, int count,
                        uint8_t* out97s) {
  init_all();
  u64 k[4], km[4], kr[4];
  fr_from_be32(s_be32, k);
  FR.from_raw(k, km);
  FR.to_raw(km, kr);
  for (int i = 0; i < count; ++i) {
    G1 u, m;
    if (!g1_read(us97 + 97 * i, u)) return -1;
    g1_mul_glv(u, kr, m);
    g1_write(m, out97s + 97 * i);
  }
  return 0;
}

// Fast subgroup membership via endomorphism eigenvalues (assumes the point
// is already on the curve — the Python deserializers check that first).
//
// Soundness (gcd argument, quantities asserted in tests/test_endomorphism):
//  G2: ψ(P) = [x]P ⟹ [x²−t·x+p]P = [p−x]P = ∞ (char. eq. of ψ, t = x+1)
//      and p−x = h₁·r, so ord(P) | gcd(h₁·r, h₂·r) = r·gcd(h₁,h₂) = r.
//  G1: φ(P) = [λ]P ⟹ [λ²+λ+1]P = [r·k]P = ∞, ord(P) | r·gcd(h₁,k) = r.
// One small ladder (64/127-bit) replaces the full-width [r−1] check.
int bls_g1_in_subgroup(const uint8_t* p97) {
  init_all();
  G1 p;
  if (!g1_read(p97, p)) return -1;
  return g1_subgroup_ok(p) ? 1 : 0;
}

int bls_g2_in_subgroup(const uint8_t* p193) {
  init_all();
  G2 p;
  if (!g2_read(p193, p)) return -1;
  return g2_subgroup_ok(p) ? 1 : 0;
}

// Full batched TPKE decrypt with the master-scalar fold: out_i = V_i ⊕
// KDF([s]·U_i) — GLV ladders, KDF, and XOR in one call (GIL released).
// us: count×97; vs: concatenated V bytes with vlens[i] lengths; out: same
// layout as vs.
int bls_tpke_decrypt_batch(const uint8_t* s_be32, const uint8_t* us97,
                           const uint8_t* vs, const int64_t* vlens, int count,
                           uint8_t* out) {
  init_all();
  u64 k[4], km[4], kr[4];
  fr_from_be32(s_be32, k);
  FR.from_raw(k, km);
  FR.to_raw(km, kr);
  std::vector<G1> masks(count);
  for (int i = 0; i < count; ++i) {
    G1 u;
    if (!g1_read(us97 + 97 * i, u)) return -1;
    g1_mul_glv(u, kr, masks[i]);
  }
  std::vector<uint8_t> maskb = g1_write_contig(masks);
  const uint8_t* vp = vs;
  uint8_t* op = out;
  for (int i = 0; i < count; ++i) {
    int64_t len = vlens[i];
    std::vector<uint8_t> stream(len);
    kdf_stream(&maskb[97 * (size_t)i], len, stream.data());
    for (int64_t j = 0; j < len; ++j) op[j] = vp[j] ^ stream[j];
    vp += len;
    op += len;
  }
  return 0;
}

// Wire-validate + decrypt `count` TPKE ciphertext payloads in ONE call —
// the HoneyBadger epoch's parse and decrypt phases fused (GIL released for
// both).  Each payload is Ciphertext.to_bytes layout: U(97) ‖ W(193) ‖
// vlen(4, BE) ‖ V, with plens[i] the item's total length (vlen must be
// exactly plens[i] − 294; callers with trailing bytes use the per-item
// path).  Each item gets the FULL Ciphertext.from_bytes wire checks —
// canonical coordinates, on-curve, r-order subgroup for BOTH U and W —
// then out_i = V_i ⊕ KDF([s]·U_i) (the master-scalar decrypt fold).
// Returns 0, or i+1 if item i is malformed (caller re-parses that item on
// the Python path for the precise error).
int bls_tpke_check_decrypt_batch(const uint8_t* s_be32,
                                 const uint8_t* payloads,
                                 const int64_t* plens, int count,
                                 uint8_t* out) {
  init_all();
  u64 k[4], km[4], kr[4];
  fr_from_be32(s_be32, k);
  FR.from_raw(k, km);
  FR.to_raw(km, kr);
  std::vector<G1> masks(count);
  {
    const uint8_t* pp = payloads;
    for (int i = 0; i < count; ++i) {
      int64_t plen = plens[i];
      if (plen < 294) return i + 1;
      int64_t vlen = ((int64_t)pp[290] << 24) | ((int64_t)pp[291] << 16) |
                     ((int64_t)pp[292] << 8) | (int64_t)pp[293];
      if (vlen != plen - 294) return i + 1;
      G1 u;
      G2 w;
      if (!g1_read_checked(pp, u)) return i + 1;
      if (!g2_read_checked(pp + 97, w)) return i + 1;
      g1_mul_glv(u, kr, masks[i]);
      pp += plen;
    }
  }
  std::vector<uint8_t> maskb = g1_write_contig(masks);
  const uint8_t* pp = payloads;
  uint8_t* op = out;
  for (int i = 0; i < count; ++i) {
    int64_t vlen = plens[i] - 294;
    std::vector<uint8_t> stream(vlen);
    kdf_stream(&maskb[97 * (size_t)i], vlen, stream.data());
    for (int64_t j = 0; j < vlen; ++j) op[j] = pp[294 + j] ^ stream[j];
    pp += plens[i];
    op += vlen;
  }
  return 0;
}

// Hash `count` messages to G2 in one call — the host half of the SPLIT
// device encrypt (crypto/batch.py::batch_tpke_encrypt_device): the ladders
// (2×fixed-base G1, GLS G2) run as device MSMs while this hash-dominated
// phase stays on the host.  All affine writes share ONE Fp2 inversion
// chain; the GIL is released by ctypes for the whole batch, so the epoch
// pipeline's encrypt thread overlaps with device dispatches for real.
int bls_hash_g2_batch(const uint8_t* msgs, const int64_t* lens, int count,
                      uint8_t* out193s) {
  init_all();
  std::vector<G2> hs(count);
  std::vector<uint8_t*> outs(count);
  const uint8_t* mp = msgs;
  for (int i = 0; i < count; ++i) {
    hash_g2_point(mp, lens[i], hs[i]);
    outs[i] = out193s + 193 * (size_t)i;
    mp += lens[i];
  }
  g2_write_batch(hs, outs);
  return 0;
}

// Common-coin batch: out_bits[i] = parity(SHA3(g2_bytes([s]·H_G2(nonce_i))))
// — the master-scalar god-view fold of ThresholdSign (parallel/aba.py::
// coin_for), one call for a whole epoch's instance axis.
int bls_coin_batch(const uint8_t* s_be32, const uint8_t* nonces,
                   const int64_t* lens, int count, uint8_t* out_bits) {
  init_all();
  u64 k[4], km[4], kr[4];
  fr_from_be32(s_be32, k);
  FR.from_raw(k, km);
  FR.to_raw(km, kr);
  const uint8_t* np = nonces;
  for (int i = 0; i < count; ++i) {
    G2 h, sig;
    hash_g2_point(np, lens[i], h);
    g2_mul_gls(h, kr, sig);
    uint8_t sig_bytes[193], digest[32];
    g2_write(sig, sig_bytes);
    hbbft_sha3_256(sig_bytes, 193, digest);
    out_bits[i] = digest[0] & 1;
    np += lens[i];
  }
  return 0;
}

}  // extern "C"

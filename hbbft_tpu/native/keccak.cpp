// Keccak-f[1600] / SHA3-256 CPU oracle (C ABI, loaded via ctypes).
//
// Ground truth for hbbft_tpu/ops/keccak.py (the reference hashes Merkle
// leaves and the common-coin signature with SHA3 via `tiny-keccak`;
// src/broadcast/merkle.rs). Constants derived from the FIPS-202 LFSR, same
// as the jnp implementation, so a transcription error cannot hide in both.

#include <cstdint>
#include <cstring>

namespace {

uint64_t kRC[24];
int kRot[5][5];  // rot[x][y]
bool kInit = false;

int rc_bit(int t) {
  t %= 255;
  if (t == 0) return 1;
  int R = 1;
  for (int i = 1; i <= t; ++i) {
    R <<= 1;
    if (R & 0x100) R ^= 0x171;
  }
  return R & 1;
}

void init_tables() {
  if (kInit) return;
  for (int i = 0; i < 24; ++i) {
    uint64_t rc = 0;
    for (int j = 0; j < 7; ++j)
      if (rc_bit(7 * i + j)) rc |= 1ULL << ((1 << j) - 1);
    kRC[i] = rc;
  }
  int x = 1, y = 0;
  kRot[0][0] = 0;
  for (int t = 0; t < 24; ++t) {
    kRot[x][y] = ((t + 1) * (t + 2) / 2) % 64;
    int nx = y, ny = (2 * x + 3 * y) % 5;
    x = nx;
    y = ny;
  }
  kInit = true;
}

inline uint64_t rotl(uint64_t v, int s) {
  return s == 0 ? v : (v << s) | (v >> (64 - s));
}

// state[5*y + x] = A[x][y]
void keccak_f(uint64_t* s) {
  init_tables();
  uint64_t B[25], C[5], D[5];
  for (int rnd = 0; rnd < 24; ++rnd) {
    for (int x = 0; x < 5; ++x)
      C[x] = s[x] ^ s[x + 5] ^ s[x + 10] ^ s[x + 15] ^ s[x + 20];
    for (int x = 0; x < 5; ++x)
      D[x] = C[(x + 4) % 5] ^ rotl(C[(x + 1) % 5], 1);
    for (int y = 0; y < 5; ++y)
      for (int x = 0; x < 5; ++x) s[5 * y + x] ^= D[x];
    for (int y = 0; y < 5; ++y)
      for (int x = 0; x < 5; ++x) {
        int nx = y, ny = (2 * x + 3 * y) % 5;
        B[5 * ny + nx] = rotl(s[5 * y + x], kRot[x][y]);
      }
    for (int y = 0; y < 5; ++y)
      for (int x = 0; x < 5; ++x)
        s[5 * y + x] =
            B[5 * y + x] ^ (~B[5 * y + (x + 1) % 5] & B[5 * y + (x + 2) % 5]);
    s[0] ^= kRC[rnd];
  }
}

}  // namespace

extern "C" {

void hbbft_keccak_f1600(uint64_t* state) { keccak_f(state); }

void hbbft_sha3_256(const uint8_t* data, int64_t len, uint8_t* out) {
  const int rate = 136;
  uint64_t s[25];
  std::memset(s, 0, sizeof(s));
  int64_t off = 0;
  while (len - off >= rate) {
    for (int i = 0; i < rate / 8; ++i) {
      uint64_t lane;
      std::memcpy(&lane, data + off + 8 * i, 8);  // little-endian host assumed
      s[i] ^= lane;
    }
    keccak_f(s);
    off += rate;
  }
  uint8_t block[136];
  std::memset(block, 0, sizeof(block));
  std::memcpy(block, data + off, len - off);
  block[len - off] ^= 0x06;
  block[rate - 1] ^= 0x80;
  for (int i = 0; i < rate / 8; ++i) {
    uint64_t lane;
    std::memcpy(&lane, block + 8 * i, 8);
    s[i] ^= lane;
  }
  keccak_f(s);
  std::memcpy(out, s, 32);
}

// Batched: n messages, each msg_len bytes, contiguous.
void hbbft_sha3_256_batch(const uint8_t* data, int64_t n, int64_t msg_len,
                          uint8_t* out) {
  for (int64_t i = 0; i < n; ++i)
    hbbft_sha3_256(data + i * msg_len, msg_len, out + i * 32);
}

}  // extern "C"

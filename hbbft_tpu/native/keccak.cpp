// Keccak-f[1600] / SHA3-256 CPU oracle (C ABI, loaded via ctypes).
//
// Ground truth for hbbft_tpu/ops/keccak.py (the reference hashes Merkle
// leaves and the common-coin signature with SHA3 via `tiny-keccak`;
// src/broadcast/merkle.rs). Constants derived from the FIPS-202 LFSR, same
// as the jnp implementation, so a transcription error cannot hide in both.

#include <cstdint>
#include <cstring>

#ifdef __AVX2__
#include <immintrin.h>
#endif

namespace {

uint64_t kRC[24];
int kRot[5][5];  // rot[x][y]
bool kInit = false;

int rc_bit(int t) {
  t %= 255;
  if (t == 0) return 1;
  int R = 1;
  for (int i = 1; i <= t; ++i) {
    R <<= 1;
    if (R & 0x100) R ^= 0x171;
  }
  return R & 1;
}

void init_tables() {
  if (kInit) return;
  for (int i = 0; i < 24; ++i) {
    uint64_t rc = 0;
    for (int j = 0; j < 7; ++j)
      if (rc_bit(7 * i + j)) rc |= 1ULL << ((1 << j) - 1);
    kRC[i] = rc;
  }
  int x = 1, y = 0;
  kRot[0][0] = 0;
  for (int t = 0; t < 24; ++t) {
    kRot[x][y] = ((t + 1) * (t + 2) / 2) % 64;
    int nx = y, ny = (2 * x + 3 * y) % 5;
    x = nx;
    y = ny;
  }
  kInit = true;
}

inline uint64_t rotl(uint64_t v, int s) {
  return s == 0 ? v : (v << s) | (v >> (64 - s));
}

// state[5*y + x] = A[x][y]
void keccak_f(uint64_t* s) {
  init_tables();
  uint64_t B[25], C[5], D[5];
  for (int rnd = 0; rnd < 24; ++rnd) {
    for (int x = 0; x < 5; ++x)
      C[x] = s[x] ^ s[x + 5] ^ s[x + 10] ^ s[x + 15] ^ s[x + 20];
    for (int x = 0; x < 5; ++x)
      D[x] = C[(x + 4) % 5] ^ rotl(C[(x + 1) % 5], 1);
    for (int y = 0; y < 5; ++y)
      for (int x = 0; x < 5; ++x) s[5 * y + x] ^= D[x];
    for (int y = 0; y < 5; ++y)
      for (int x = 0; x < 5; ++x) {
        int nx = y, ny = (2 * x + 3 * y) % 5;
        B[5 * ny + nx] = rotl(s[5 * y + x], kRot[x][y]);
      }
    for (int y = 0; y < 5; ++y)
      for (int x = 0; x < 5; ++x)
        s[5 * y + x] =
            B[5 * y + x] ^ (~B[5 * y + (x + 1) % 5] & B[5 * y + (x + 2) % 5]);
    s[0] ^= kRC[rnd];
  }
}

#ifdef __AVX2__
// ---- 4-way parallel keccak ------------------------------------------------
//
// One 64-bit element of a __m256i per stream: four equal-length messages run
// the permutation in lockstep (the Merkle leaf batch is exactly this shape —
// every RBC shard has the same length).  Same table derivation as the scalar
// path, so the two cannot diverge without a test catching it.

// Immediate-count lane rotate: the variable-count form (vpsllq with an xmm
// count) costs an extra move per rotation and defeats constant folding, so
// the rho step below is unrolled with literal offsets (the standard rho/pi
// walk; the scalar path still derives its table from the LFSR, and the
// cross-check tests pin the two together).
#if defined(__AVX512VL__)
// vprolq: single-instruction lane rotate when AVX-512VL is present
#define ROL4(v, s) _mm256_rol_epi64((v), (s))
// vpternlogq: any 3-input boolean in one instruction.  0x96 = a^b^c
// (theta's 5-way column xor becomes two ops), 0xD2 = a^(~b&c) (the
// whole chi row update in one op instead of xor+andnot)
#define XOR3(a, b, c) _mm256_ternarylogic_epi64((a), (b), (c), 0x96)
#define CHI4(a, b, c) _mm256_ternarylogic_epi64((a), (b), (c), 0xD2)
#else
#define ROL4(v, s)                                            \
  _mm256_or_si256(_mm256_slli_epi64((v), (s)),                \
                  _mm256_srli_epi64((v), 64 - (s)))
#define XOR3(a, b, c) \
  _mm256_xor_si256(_mm256_xor_si256((a), (b)), (c))
#define CHI4(a, b, c) \
  _mm256_xor_si256((a), _mm256_andnot_si256((b), (c)))
#endif

void keccak_f4(__m256i* st) {
  init_tables();
  __m256i bc[5], t, u;
  for (int rnd = 0; rnd < 24; ++rnd) {
    // theta
    for (int i = 0; i < 5; ++i)
      bc[i] = XOR3(XOR3(st[i], st[i + 5], st[i + 10]), st[i + 15],
                   st[i + 20]);
    for (int i = 0; i < 5; ++i) {
      t = _mm256_xor_si256(bc[(i + 4) % 5], ROL4(bc[(i + 1) % 5], 1));
      for (int j = 0; j < 25; j += 5)
        st[j + i] = _mm256_xor_si256(st[j + i], t);
    }
    // rho + pi (unrolled with immediate rotation counts)
    t = st[1];
#define RP(dst, rot) u = st[dst]; st[dst] = ROL4(t, rot); t = u;
    RP(10, 1)  RP(7, 3)   RP(11, 6)  RP(17, 10) RP(18, 15) RP(3, 21)
    RP(5, 28)  RP(16, 36) RP(8, 45)  RP(21, 55) RP(24, 2)  RP(4, 14)
    RP(15, 27) RP(23, 41) RP(19, 56) RP(13, 8)  RP(12, 25) RP(2, 43)
    RP(20, 62) RP(14, 18) RP(22, 39) RP(9, 61)  RP(6, 20)  RP(1, 44)
#undef RP
    // chi
    for (int j = 0; j < 25; j += 5) {
      for (int i = 0; i < 5; ++i) bc[i] = st[j + i];
      for (int i = 0; i < 5; ++i)
        st[j + i] = CHI4(bc[i], bc[(i + 1) % 5], bc[(i + 2) % 5]);
    }
    // iota
    st[0] = _mm256_xor_si256(
        st[0], _mm256_set1_epi64x(static_cast<long long>(kRC[rnd])));
  }
}

// Four equal-length messages -> four 32-byte digests (out stride 32).
void sha3_256_x4(const uint8_t* msgs[4], int64_t len, uint8_t* out) {
  const int rate = 136;
  __m256i s[25];
  for (int i = 0; i < 25; ++i) s[i] = _mm256_setzero_si256();
  int64_t off = 0;
  uint64_t l[4];
  while (len - off >= rate) {
    for (int i = 0; i < rate / 8; ++i) {
      for (int t = 0; t < 4; ++t) std::memcpy(&l[t], msgs[t] + off + 8 * i, 8);
      s[i] = _mm256_xor_si256(
          s[i], _mm256_set_epi64x(static_cast<long long>(l[3]),
                                  static_cast<long long>(l[2]),
                                  static_cast<long long>(l[1]),
                                  static_cast<long long>(l[0])));
    }
    keccak_f4(s);
    off += rate;
  }
  uint8_t block[4][136];
  for (int t = 0; t < 4; ++t) {
    std::memset(block[t], 0, rate);
    std::memcpy(block[t], msgs[t] + off, len - off);
    block[t][len - off] ^= 0x06;
    block[t][rate - 1] ^= 0x80;
  }
  for (int i = 0; i < rate / 8; ++i) {
    for (int t = 0; t < 4; ++t) std::memcpy(&l[t], block[t] + 8 * i, 8);
    s[i] = _mm256_xor_si256(
        s[i], _mm256_set_epi64x(static_cast<long long>(l[3]),
                                static_cast<long long>(l[2]),
                                static_cast<long long>(l[1]),
                                static_cast<long long>(l[0])));
  }
  keccak_f4(s);
  alignas(32) uint64_t lane[4];
  for (int w = 0; w < 4; ++w) {
    _mm256_store_si256(reinterpret_cast<__m256i*>(lane), s[w]);
    for (int t = 0; t < 4; ++t) std::memcpy(out + t * 32 + 8 * w, &lane[t], 8);
  }
}
#endif  // __AVX2__

}  // namespace

extern "C" {

void hbbft_keccak_f1600(uint64_t* state) { keccak_f(state); }

void hbbft_sha3_256(const uint8_t* data, int64_t len, uint8_t* out) {
  const int rate = 136;
  uint64_t s[25];
  std::memset(s, 0, sizeof(s));
  int64_t off = 0;
  while (len - off >= rate) {
    for (int i = 0; i < rate / 8; ++i) {
      uint64_t lane;
      std::memcpy(&lane, data + off + 8 * i, 8);  // little-endian host assumed
      s[i] ^= lane;
    }
    keccak_f(s);
    off += rate;
  }
  uint8_t block[136];
  std::memset(block, 0, sizeof(block));
  std::memcpy(block, data + off, len - off);
  block[len - off] ^= 0x06;
  block[rate - 1] ^= 0x80;
  for (int i = 0; i < rate / 8; ++i) {
    uint64_t lane;
    std::memcpy(&lane, block + 8 * i, 8);
    s[i] ^= lane;
  }
  keccak_f(s);
  std::memcpy(out, s, 32);
}

// Batched: n messages, each msg_len bytes, contiguous.  Groups of four run
// the 4-way AVX2 permutation; the remainder falls back to the scalar path.
void hbbft_sha3_256_batch(const uint8_t* data, int64_t n, int64_t msg_len,
                          uint8_t* out) {
  int64_t i = 0;
#ifdef __AVX2__
  for (; i + 4 <= n; i += 4) {
    const uint8_t* msgs[4] = {
        data + i * msg_len, data + (i + 1) * msg_len,
        data + (i + 2) * msg_len, data + (i + 3) * msg_len};
    sha3_256_x4(msgs, msg_len, out + i * 32);
  }
#endif
  for (; i < n; ++i)
    hbbft_sha3_256(data + i * msg_len, msg_len, out + i * 32);
}

}  // extern "C"

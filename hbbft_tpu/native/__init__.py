"""C++ CPU oracles, loaded via ctypes (no pybind11 in this environment).

The reference links native Rust crates (``reed-solomon-erasure``,
``tiny-keccak``) for its hot math; our TPU kernels are the production path and
these C++ oracles are the bit-exactness ground truth (SURVEY §2.2).  The
library is compiled on first use with ``make`` (g++); if compilation is
impossible the loader raises and oracle tests are skipped.
"""

from hbbft_tpu.native.oracle import NativeOracle, get_oracle

__all__ = ["NativeOracle", "get_oracle"]

"""ctypes loader for the C++ CPU oracle library."""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import List, Optional, Sequence

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB = os.path.join(_DIR, "libhbbft_native.so")

_oracle: Optional["NativeOracle"] = None


def _build() -> None:
    subprocess.run(
        ["make", "-s"], cwd=_DIR, check=True, capture_output=True, text=True
    )


class NativeOracle:
    """Thin typed wrapper over the C ABI in gf256.cpp / keccak.cpp."""

    def __init__(self):
        if not os.path.exists(_LIB) or (
            os.path.getmtime(_LIB)
            < max(
                os.path.getmtime(os.path.join(_DIR, f))
                for f in ("gf256.cpp", "keccak.cpp")
            )
        ):
            _build()
        lib = ctypes.CDLL(_LIB)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        u64p = ctypes.POINTER(ctypes.c_uint64)
        lib.hbbft_gf_mul_bytes.argtypes = [u8p, u8p, u8p, ctypes.c_int64]
        lib.hbbft_gf_matmul.argtypes = [u8p, u8p, u8p] + [ctypes.c_int] * 3
        lib.hbbft_gf_invert.argtypes = [u8p, u8p, ctypes.c_int]
        lib.hbbft_gf_invert.restype = ctypes.c_int
        lib.hbbft_rs_matrix.argtypes = [ctypes.c_int, ctypes.c_int, u8p]
        lib.hbbft_rs_matrix.restype = ctypes.c_int
        lib.hbbft_rs_encode.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_int64, u8p,
        ]
        lib.hbbft_rs_encode.restype = ctypes.c_int
        lib.hbbft_rs_reconstruct.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_int64, u8p, u8p,
        ]
        lib.hbbft_rs_reconstruct.restype = ctypes.c_int
        lib.hbbft_keccak_f1600.argtypes = [u64p]
        lib.hbbft_sha3_256.argtypes = [u8p, ctypes.c_int64, u8p]
        lib.hbbft_sha3_256_batch.argtypes = [
            u8p, ctypes.c_int64, ctypes.c_int64, u8p,
        ]
        self._lib = lib

    @staticmethod
    def _p(a: np.ndarray):
        return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))

    def gf_mul(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        a = np.ascontiguousarray(a, dtype=np.uint8)
        b = np.ascontiguousarray(b, dtype=np.uint8)
        out = np.empty_like(a)
        self._lib.hbbft_gf_mul_bytes(self._p(a), self._p(b), self._p(out), a.size)
        return out

    def gf_matmul(self, A: np.ndarray, B: np.ndarray) -> np.ndarray:
        A = np.ascontiguousarray(A, dtype=np.uint8)
        B = np.ascontiguousarray(B, dtype=np.uint8)
        r, k = A.shape
        k2, c = B.shape
        assert k == k2
        out = np.empty((r, c), dtype=np.uint8)
        self._lib.hbbft_gf_matmul(self._p(A), self._p(B), self._p(out), r, k, c)
        return out

    def gf_invert(self, M: np.ndarray) -> np.ndarray:
        M = np.ascontiguousarray(M, dtype=np.uint8)
        n = M.shape[0]
        out = np.empty((n, n), dtype=np.uint8)
        rc = self._lib.hbbft_gf_invert(self._p(M), self._p(out), n)
        if rc != 0:
            raise np.linalg.LinAlgError("singular")
        return out

    def rs_matrix(self, data: int, total: int) -> np.ndarray:
        out = np.empty((total, data), dtype=np.uint8)
        rc = self._lib.hbbft_rs_matrix(data, total, self._p(out))
        if rc != 0:
            raise ValueError("bad rs dims")
        return out

    def rs_encode(self, data_shards: np.ndarray, total: int) -> np.ndarray:
        data_shards = np.ascontiguousarray(data_shards, dtype=np.uint8)
        k, B = data_shards.shape
        shards = np.zeros((total, B), dtype=np.uint8)
        shards[:k] = data_shards
        rc = self._lib.hbbft_rs_encode(k, total, B, self._p(shards))
        if rc != 0:
            raise ValueError("encode failed")
        return shards

    def rs_reconstruct(
        self, data: int, shards: Sequence[Optional[bytes]]
    ) -> List[bytes]:
        total = len(shards)
        present = np.array(
            [1 if s is not None else 0 for s in shards], dtype=np.uint8
        )
        if int(present.sum()) < data:
            raise ValueError("too few shards")
        shard_len = len(next(s for s in shards if s is not None))
        buf = np.zeros((total, shard_len), dtype=np.uint8)
        for i, s in enumerate(shards):
            if s is not None:
                buf[i] = np.frombuffer(s, dtype=np.uint8)
        rc = self._lib.hbbft_rs_reconstruct(
            data, total, shard_len, self._p(buf), self._p(present)
        )
        if rc == -1:
            raise ValueError("too few shards")
        if rc != 0:
            raise ValueError("reconstruct failed")
        return [buf[i].tobytes() for i in range(total)]

    def keccak_f1600(self, state: np.ndarray) -> np.ndarray:
        state = np.ascontiguousarray(state, dtype=np.uint64).copy()
        assert state.shape == (25,)
        self._lib.hbbft_keccak_f1600(
            state.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64))
        )
        return state

    def sha3_256(self, data: bytes) -> bytes:
        arr = np.frombuffer(bytes(data), dtype=np.uint8)
        if arr.size == 0:
            arr = np.zeros(1, dtype=np.uint8)  # valid pointer; len passed as 0
        out = np.empty(32, dtype=np.uint8)
        self._lib.hbbft_sha3_256(self._p(arr), len(data), self._p(out))
        return out.tobytes()

    def sha3_256_batch(self, msgs: np.ndarray) -> np.ndarray:
        msgs = np.ascontiguousarray(msgs, dtype=np.uint8)
        n, L = msgs.shape
        out = np.empty((n, 32), dtype=np.uint8)
        self._lib.hbbft_sha3_256_batch(self._p(msgs), n, L, self._p(out))
        return out


def get_oracle() -> NativeOracle:
    """Build (if needed) and return the singleton oracle."""
    global _oracle
    if _oracle is None:
        _oracle = NativeOracle()
    return _oracle
